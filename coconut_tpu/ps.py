"""Pointcheval-Sanders signature layer: verification and proof of knowledge
of a signature with selective disclosure.

Replaces the reference's external `ps_sig` crate (Cargo.toml:21-22). Because
this framework owns both layers, the reference's clone-transforms
`transform_to_PS_{params,verkey,sig}` (signature.rs:81-104, marked TODO
there) become identity — coconut types are used directly.

Surface parity (SURVEY.md §2.2): `PSSignature::verify` (reached via
signature.rs:477), `PoKOfSignature::{init,to_bytes,gen_proof}` and
`Proof::verify` (pok_sig.rs:85-105).

Verification hot path: one (msg_count+1)-term OtherGroup MSM plus a
2-pairing product with shared final exponentiation — exactly what the
`CurveBackend` seam batches onto TPU (BASELINE.json north star).
"""

from .errors import PSError, UnsupportedNoOfMessages
from .pok_vc import ProverCommitting
from .sss import rand_fr


def prepare_verify_statement(messages, vk, params):
    """The OtherGroup accumulator X_tilde * prod Y_tilde_j^{m_j}.

    Split out so batch backends can compute it per credential; reference:
    inferred MSM inside PSSignature::verify (SURVEY.md §3.4)."""
    if len(messages) != len(vk.Y_tilde):
        raise UnsupportedNoOfMessages(len(vk.Y_tilde), len(messages))
    ops = params.ctx.other
    return ops.add(vk.X_tilde, ops.msm(vk.Y_tilde, list(messages)))


def ps_verify(sig, messages, vk, params):
    """PS verification: e(sigma_1, X_tilde * prod Y_tilde_j^{m_j}) ==
    e(sigma_2, g_tilde), rejecting the forgeable sigma_1 == identity
    (signature.rs:472-478 -> ps_sig)."""
    if sig.sigma_1 is None:
        return False
    acc = prepare_verify_statement(messages, vk, params)
    ctx = params.ctx
    return ctx.pairing_check(
        [(sig.sigma_1, acc), (ctx.sig.neg(sig.sigma_2), params.g_tilde)]
    )


def batch_verify(sigs, messages_list, vk, params, backend=None,
                 mode="exact", epoch=None):
    """Per-credential verification booleans for a batch under one verkey.

    `backend=None` runs the sequential reference path; a `CurveBackend`
    instance or name ("python", "jax") executes the same math through the
    batched seam (coconut_tpu/backend.py). This is the north-star entry
    point (BASELINE.json configs 2 and 5).

    mode="batched" (PR 16) runs the probabilistic RLC-combined check —
    ONE pairing product with ONE shared final exponentiation for the
    whole batch — and, on a combined rejection, bisects with fresh
    per-sub-batch combiner exponents to attribute the forged lane(s)
    exactly (O(log B) extra combined checks). All-valid batches return a
    verdict vector bit-identical to mode="exact". `epoch` is the PR-15
    key epoch, folded into the exponent derivation's domain separation.
    Requires a backend."""
    if len(sigs) != len(messages_list):
        raise PSError(
            "batch size mismatch: %d sigs, %d message vectors"
            % (len(sigs), len(messages_list))
        )
    if mode not in ("exact", "batched"):
        raise PSError("unknown verify mode %r" % (mode,))
    if backend is not None and isinstance(backend, str):
        from .backend import get_backend

        backend = get_backend(backend)
    if mode == "batched":
        if backend is None:
            raise PSError("mode='batched' requires a backend")
        return _rlc_verify_bits(
            sigs, messages_list, vk, params, backend, epoch
        )
    if backend is not None:
        return backend.batch_verify(sigs, messages_list, vk, params)
    return [
        ps_verify(s, m, vk, params) for s, m in zip(sigs, messages_list)
    ]


def _rlc_verify_bits(sigs, messages_list, vk, params, backend, epoch):
    """Batched-mode verdict vector: one combined RLC check, then — only
    on rejection — the grouped-failure bisection ladder (PR 1 shape)
    driven through the combined predicate. Every sub-batch check derives
    FRESH exponents from its own transcript, so a cross-lane cancellation
    crafted against one draw cannot survive the ladder. A single-lane
    combined check is exactly equivalent to ps_verify (the lone exponent
    is invertible mod R), which is what makes leaf verdicts — and
    all-valid batches — bit-identical to the exact path."""
    from . import metrics

    B = len(sigs)

    def combined(lo, hi):
        return backend.batch_verify_combined(
            sigs[lo:hi], messages_list[lo:hi], vk, params, epoch=epoch
        )

    bits = [True] * B
    if B == 0 or combined(0, B):
        return bits
    metrics.count("verify_batched_fallbacks")

    def rec(lo, hi):
        # precondition: combined(lo, hi) rejected
        if hi - lo == 1:
            bits[lo] = False
            return
        metrics.count("verify_bisection_depth")
        mid = (lo + hi) // 2
        left_ok = combined(lo, mid)
        right_ok = combined(mid, hi)
        if left_ok and right_ok:
            # residual <= 2^-lambda event (the parent draw collided) —
            # settle the range exactly rather than trust either draw
            for i in range(lo, hi):
                bits[i] = ps_verify(sigs[i], messages_list[i], vk, params)
            return
        if not left_ok:
            rec(lo, mid)
        if not right_ok:
            rec(mid, hi)

    rec(0, B)
    return bits


def batch_show_verify(
    proofs, vk, params, revealed_msgs_list, challenges=None, backend=None,
    mode="exact", epoch=None
):
    """Batched `PoKOfSignatureProof.verify` (BASELINE config 3).

    challenges=None recomputes each Fiat-Shamir challenge from the proof
    transcript (the secure non-interactive path). A backend accelerates the
    uniform case (every proof reveals the same index set — the bench shape);
    ragged batches fall back to the sequential path.

    mode="batched" (PR 16) keeps the Schnorr check per-lane but folds the
    B pairing checks into ONE RLC-combined product with ONE shared final
    exponentiation, bisecting with fresh exponents on rejection to
    attribute the tampered lane(s). All-valid batches match mode="exact"
    bit-for-bit. `epoch` joins the exponent derivation's domain
    separation (PR 15). Requires a backend; ragged batches fall back to
    the exact sequential path exactly as the exact mode does."""
    from .signature import fiat_shamir_challenge

    if len(proofs) != len(revealed_msgs_list):
        raise PSError(
            "batch size mismatch: %d proofs, %d revealed maps"
            % (len(proofs), len(revealed_msgs_list))
        )
    if mode not in ("exact", "batched"):
        raise PSError("unknown verify mode %r" % (mode,))
    if challenges is None:
        challenges = [
            fiat_shamir_challenge(p.to_bytes_for_challenge(vk, params))
            for p in proofs
        ]
    elif len(challenges) != len(proofs):
        raise PSError(
            "batch size mismatch: %d proofs, %d challenges"
            % (len(proofs), len(challenges))
        )
    for p, rm in zip(proofs, revealed_msgs_list):
        if set(rm.keys()) != p.revealed_msg_indices:
            raise PSError("revealed messages do not match proof's indices")
    uniform = bool(proofs) and all(
        p.revealed_msg_indices == proofs[0].revealed_msg_indices
        for p in proofs
    )
    if mode == "batched" and backend is None:
        raise PSError("mode='batched' requires a backend")
    if backend is not None and uniform:
        if isinstance(backend, str):
            from .backend import get_backend

            backend = get_backend(backend)
        if mode == "batched":
            return _rlc_show_verify_bits(
                proofs, vk, params, revealed_msgs_list, challenges,
                backend, epoch,
            )
        if hasattr(backend, "batch_show_verify"):
            return backend.batch_show_verify(
                proofs, vk, params, revealed_msgs_list, challenges
            )
    if backend is not None and not uniform:
        # a real-workload cliff worth surfacing: the fused kernel needs one
        # shared revealed-index set, so ragged batches run sequentially
        from . import metrics

        metrics.count("show_verify_ragged_fallback")
        metrics.count("show_verify_ragged_proofs", len(proofs))
    return [
        p.verify(vk, params, rm, c)
        for p, rm, c in zip(proofs, revealed_msgs_list, challenges)
    ]


def _rlc_show_verify_bits(
    proofs, vk, params, revealed_msgs_list, challenges, backend, epoch
):
    """Batched-mode show verdicts. The backend's combined check returns
    (per-lane Schnorr bits, ONE batch pairing bool); a lane's verdict is
    schnorr[i] & pairing. On a pairing rejection the bisection ladder
    re-runs the combined check on halves — each sub-batch draws FRESH
    exponents from its own transcript — until the tampered lane(s) are
    named. Dead lanes (identity sigma') are excluded from the fold by
    the backend and fail via their Schnorr bit, so they never trigger
    (or hide inside) a bisection."""
    from . import metrics

    B = len(proofs)
    if B == 0:
        return []

    def combined(lo, hi):
        return backend.batch_show_verify_combined(
            proofs[lo:hi], vk, params, revealed_msgs_list[lo:hi],
            challenges[lo:hi], epoch=epoch,
        )

    schnorr, pair_ok = combined(0, B)
    pair_bits = [pair_ok] * B
    if not pair_ok:
        metrics.count("verify_batched_fallbacks")

        def exact_pair(i):
            # the full exact verify (schnorr & pairing); the schnorr half
            # is already known, so this settles the pairing half exactly
            return proofs[i].verify(
                vk, params, revealed_msgs_list[i], challenges[i]
            )

        def rec(lo, hi):
            # precondition: combined(lo, hi) pairing rejected
            if hi - lo == 1:
                pair_bits[lo] = False
                return
            metrics.count("verify_bisection_depth")
            mid = (lo + hi) // 2
            _, left_ok = combined(lo, mid)
            _, right_ok = combined(mid, hi)
            if left_ok:
                for i in range(lo, mid):
                    pair_bits[i] = True
            if right_ok:
                for i in range(mid, hi):
                    pair_bits[i] = True
            if left_ok and right_ok:
                # residual <= 2^-lambda collision in the parent draw:
                # settle the range exactly
                for i in range(lo, hi):
                    pair_bits[i] = exact_pair(i)
                return
            if not left_ok:
                rec(lo, mid)
            if not right_ok:
                rec(mid, hi)

        rec(0, B)
    return [bool(s) and bool(p) for s, p in zip(schnorr, pair_bits)]


class PoKOfSignature:
    """Commitment phase of the selective-disclosure proof ("Show" from the
    Coconut paper; reference surface pok_sig.rs:85-95).

    Re-randomizes the credential — sigma_1' = sigma_1^r,
    sigma_2' = (sigma_2 * sigma_1^t)^r — then proves knowledge of t and the
    hidden messages in J = g_tilde^t * prod_{hidden j} Y_tilde_j^{m_j}.
    """

    def __init__(self, sig, vk, params, messages, blindings=None,
                 revealed_msg_indices=None):
        revealed = set(revealed_msg_indices or ())
        if len(messages) != len(vk.Y_tilde):
            raise UnsupportedNoOfMessages(len(vk.Y_tilde), len(messages))
        for i in revealed:
            if not 0 <= i < len(messages):
                raise PSError("revealed index %d out of range" % i)
        hidden = [i for i in range(len(messages)) if i not in revealed]
        if blindings is not None and len(blindings) != len(hidden):
            raise PSError(
                "need %d blindings for hidden messages, got %d"
                % (len(hidden), len(blindings))
            )
        ctx = params.ctx
        r = rand_fr()
        t = rand_fr()
        self.sigma_prime_1 = ctx.sig.mul(sig.sigma_1, r)
        self.sigma_prime_2 = ctx.sig.mul(
            ctx.sig.add(sig.sigma_2, ctx.sig.mul(sig.sigma_1, t)), r
        )
        bases = [params.g_tilde] + [vk.Y_tilde[i] for i in hidden]
        secrets = [t] + [messages[i] for i in hidden]
        committing = ProverCommitting(ctx.other, ctx.other_to_bytes)
        committing.commit(params.g_tilde, None)
        for k, i in enumerate(hidden):
            committing.commit(
                vk.Y_tilde[i], None if blindings is None else blindings[k]
            )
        self.J = ctx.other.msm(bases, secrets)
        self._committed = committing.finish()
        self._secrets = secrets
        self._ctx = ctx
        self.revealed_msg_indices = revealed

    def to_bytes(self):
        """Fiat-Shamir transcript (challenge input; pok_sig.rs:94)."""
        ctx = self._ctx
        return (
            ctx.sig_to_bytes(self.sigma_prime_1)
            + ctx.sig_to_bytes(self.sigma_prime_2)
            + ctx.other_to_bytes(self.J)
            + self._committed.to_bytes()
        )

    def gen_proof(self, challenge):
        proof_vc = self._committed.gen_proof(challenge, self._secrets)
        return PoKOfSignatureProof(
            self.sigma_prime_1,
            self.sigma_prime_2,
            self.J,
            proof_vc,
            self.revealed_msg_indices,
        )


class PoKOfSignatureProof:
    """Response phase; verifier surface matches ps_sig's
    `Proof::verify(vk, params, revealed_msgs, challenge)` (pok_sig.rs:103-105).
    """

    def __init__(self, sigma_prime_1, sigma_prime_2, J, proof_vc,
                 revealed_msg_indices):
        self.sigma_prime_1 = sigma_prime_1
        self.sigma_prime_2 = sigma_prime_2
        self.J = J
        self.proof_vc = proof_vc
        self.revealed_msg_indices = set(revealed_msg_indices)

    def _bases(self, vk, params):
        hidden = [
            i
            for i in range(len(vk.Y_tilde))
            if i not in self.revealed_msg_indices
        ]
        return [params.g_tilde] + [vk.Y_tilde[i] for i in hidden]

    def to_bytes(self, ctx):
        """Canonical wire encoding (the struct sent prover -> verifier)."""
        out = [
            ctx.sig_to_bytes(self.sigma_prime_1),
            ctx.sig_to_bytes(self.sigma_prime_2),
            ctx.other_to_bytes(self.J),
            self.proof_vc.to_bytes(ctx.other_to_bytes),
            len(self.revealed_msg_indices).to_bytes(4, "big"),
        ]
        out.extend(
            i.to_bytes(4, "big") for i in sorted(self.revealed_msg_indices)
        )
        return b"".join(out)

    @classmethod
    def from_bytes(cls, b, ctx):
        from .errors import DeserializationError
        from .pok_vc import Proof

        n = ctx.sig_nbytes
        if len(b) < 2 * n + ctx.other_nbytes:
            raise DeserializationError("malformed PoKOfSignatureProof")
        s1 = ctx.sig_from_bytes(b[:n])
        s2 = ctx.sig_from_bytes(b[n : 2 * n])
        o = 2 * n
        J = ctx.other_from_bytes(b[o : o + ctx.other_nbytes])
        o += ctx.other_nbytes
        proof_vc, o = Proof.read_from(
            b, o, ctx.other_from_bytes, ctx.other_nbytes
        )
        if len(b) < o + 4:
            raise DeserializationError("malformed PoKOfSignatureProof")
        k = int.from_bytes(b[o : o + 4], "big")
        o += 4
        if len(b) != o + 4 * k:
            raise DeserializationError("malformed PoKOfSignatureProof")
        revealed = {
            int.from_bytes(b[o + 4 * i : o + 4 * (i + 1)], "big")
            for i in range(k)
        }
        if len(revealed) != k:
            raise DeserializationError("duplicate revealed indices")
        return cls(s1, s2, J, proof_vc, revealed)

    def to_bytes_for_challenge(self, vk, params):
        """Reconstruct the prover's transcript bytes so a Fiat-Shamir verifier
        recomputes (rather than trusts) the challenge — rebuild addition over
        the reference's out-of-band challenge passing."""
        ctx = params.ctx
        return (
            ctx.sig_to_bytes(self.sigma_prime_1)
            + ctx.sig_to_bytes(self.sigma_prime_2)
            + ctx.other_to_bytes(self.J)
            + self.proof_vc.to_bytes_with_bases(
                ctx.other_to_bytes, self._bases(vk, params)
            )
        )

    def verify(self, vk, params, revealed_msgs, challenge):
        """Check the Schnorr relation on J, then the pairing
        e(sigma_1', J * X_tilde * prod_{revealed} Y_tilde_i^{m_i}) ==
        e(sigma_2', g_tilde)."""
        ctx = params.ctx
        if self.sigma_prime_1 is None:
            return False
        if set(revealed_msgs.keys()) != self.revealed_msg_indices:
            raise PSError("revealed messages do not match proof's indices")
        if not self.proof_vc.verify(
            ctx.other, self._bases(vk, params), self.J, challenge
        ):
            return False
        acc = ctx.other.add(self.J, vk.X_tilde)
        if revealed_msgs:
            idxs = sorted(revealed_msgs)
            acc = ctx.other.add(
                acc,
                ctx.other.msm(
                    [vk.Y_tilde[i] for i in idxs],
                    [revealed_msgs[i] for i in idxs],
                ),
            )
        return ctx.pairing_check(
            [
                (self.sigma_prime_1, acc),
                (ctx.sig.neg(self.sigma_prime_2), params.g_tilde),
            ]
        )
