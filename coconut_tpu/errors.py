"""Typed errors mirroring the reference's `CoconutErrorKind` (errors.rs:5-24),
with the SURVEY.md §5 mandate applied: no asserts in library code — hot-path
`assert!`/`unwrap` in the reference (signature.rs:133-134,289-290,449,477)
become raised, typed exceptions here.

WIRE CONTRACT (PR 13, coconut_tpu/net): every error class carries a stable
machine-readable `code` (a class attribute, overridable per instance by the
wire decoder) that maps 1:1 onto the gateway's error envelopes, and every
`ServiceRetryableError` carries a `retry_after_s` that is ALWAYS a finite
float >= 0 — constructors normalize None/negative/non-finite hints to 0.0
so neither local callers nor the wire codec ever defend against None."""

import math
import re


def _finite_retry_after(value):
    """Clamp a retry-after hint to a finite float >= 0 (0.0 = "no
    estimate, retry at will") — the wire-format invariant every
    ServiceRetryableError upholds."""
    if value is None:
        return 0.0
    try:
        value = float(value)
    except (TypeError, ValueError):
        return 0.0
    if not math.isfinite(value) or value < 0.0:
        return 0.0
    return value


class CoconutError(Exception):
    """Base class for all framework errors (reference: errors.rs:26-56).

    `code` is the stable machine-readable identifier the fleet gateway
    (coconut_tpu/net/wire.py) puts in error envelopes; subclasses override
    it, and the wire decoder may stamp a more specific instance-level code
    when reconstructing a remote error."""

    code = "error"


class UnsupportedNoOfMessages(CoconutError):
    """Verkey valid for `expected` messages but given `given` (errors.rs:7-11).

    Raised on RPC-reachable paths (signature.py / ps.py / pok_sig.py run
    server-side under the engine's mint and show-verify handlers), so it
    carries a stable wire code — without one it would cross the wire as a
    GeneralError and clients could no longer distinguish "wrong message
    count" (a permanent caller bug) from a generic failure."""

    code = "unsupported_messages"

    # class-level defaults: error_from_wire rebuilds non-retryable errors
    # via cls.__new__ + CoconutError.__init__, which never runs this
    # subclass __init__ — attribute reads must still succeed
    expected = None
    given = None

    def __init__(self, expected, given):
        super().__init__(
            "Verkey valid for %d messages but given %d messages" % (expected, given)
        )
        self.expected = expected
        self.given = given

    def _restore_wire_fields(self, message):
        # the message format above is part of the wire contract: the
        # structured counts survive the round trip
        m = re.search(r"valid for (\d+) messages but given (\d+)", message)
        if m is not None:
            self.expected = int(m.group(1))
            self.given = int(m.group(2))


class UnequalNoOfBasesExponents(CoconutError):
    """Same number of bases and exponents required (errors.rs:13-17).

    Wire-coded for the same reason as UnsupportedNoOfMessages: it is
    raised under the engine's show-verify handler (pok_vc.py /
    signature.py) on malformed proofs."""

    code = "unequal_bases_exponents"

    bases = None
    exponents = None

    def __init__(self, bases, exponents):
        super().__init__(
            "Same no of bases and exponents required. %d bases and %d exponents"
            % (bases, exponents)
        )
        self.bases = bases
        self.exponents = exponents

    def _restore_wire_fields(self, message):
        m = re.search(r"(\d+) bases and (\d+) exponents", message)
        if m is not None:
            self.bases = int(m.group(1))
            self.exponents = int(m.group(2))


class PSError(CoconutError):
    """Error raised by the PS-signature layer (errors.rs:19-20; ps_sig::errors).

    Wire-coded: ps.py's checks run under the engine's mint/show handlers,
    and a PS-layer refusal must stay distinguishable from a GeneralError
    across the gateway."""

    code = "ps_error"


class DeserializationError(CoconutError):
    """Malformed or non-canonical byte encoding (rebuild addition: the
    reference had no wire validation — SURVEY.md §4 'gaps to improve')."""

    code = "bad_request"


class GeneralError(CoconutError):
    """Catch-all with a message (errors.rs:22-23)."""

    code = "general"


class TransientBackendError(CoconutError):
    """A backend dispatch or readback failure that is expected to succeed
    on re-attempt (device preemption, tunnel RPC hiccup, transient transfer
    failure). The stream supervision layer (stream.verify_stream +
    retry.RetryPolicy) retries these with bounded backoff and then falls
    back to a designated backend; any other exception class is treated as
    permanent and propagates immediately."""

    code = "transient"


class ServiceRetryableError(CoconutError):
    """Base for every LOUD-but-retriable refusal an online service emits
    (overload rejection, brownout shedding, quorum loss). The unified
    contract (coconut_tpu/engine): every subclass carries `program` — the
    engine program (verify / mint / prepare / show_prove / show_verify)
    that refused, or None for single-program legacy call sites — and
    `retry_after_s`, the service's hint for when capacity should be back:
    ALWAYS a finite float >= 0 (0.0 = no estimate; None / negative /
    non-finite hints are normalized at construction). Clients branch on
    this ONE type to implement backoff-and-resubmit without enumerating
    refusal kinds; `code` names the refusal kind machine-readably and is
    what the gateway's wire error envelopes carry."""

    code = "retryable"

    def __init__(self, message, program=None, retry_after_s=None):
        super().__init__(message)
        self.program = program
        self.retry_after_s = _finite_retry_after(retry_after_s)

    @classmethod
    def from_wire(cls, message, program=None, retry_after_s=0.0):
        """Reconstruct a retriable refusal from a decoded wire envelope.
        Bypasses the subclass constructor (an envelope carries only the
        shared fields — message/code/program/retry_after_s — not the
        structural detail like queue depths), so a wire-reconstructed
        error has the base contract but may lack subclass extras."""
        err = cls.__new__(cls)
        ServiceRetryableError.__init__(
            err, message, program=program, retry_after_s=retry_after_s
        )
        return err


class ServiceOverloadedError(ServiceRetryableError):
    """The serving layer's bounded request queue is at capacity: admission
    control rejects the request LOUDLY instead of growing the queue without
    bound (serve/queue.py). Callers should back off and resubmit; the
    "serve_rejected" counter tracks how often this fires. Carries `depth`
    (current) and `max_depth` (the configured admission bound), plus the
    ServiceRetryableError `program` / `retry_after_s` fields."""

    code = "overloaded"

    def __init__(self, depth, max_depth, program=None, retry_after_s=None):
        super().__init__(
            "serving queue at capacity (%d/%d): request rejected by "
            "admission control — back off and resubmit" % (depth, max_depth),
            program=program,
            retry_after_s=retry_after_s,
        )
        self.depth = depth
        self.max_depth = max_depth


class ServiceBrownoutError(ServiceRetryableError):
    """The serving layer is in BROWNOUT: quarantined executors cut the
    pool's capacity, or sustained queue pressure crossed the brownout
    threshold, and graded load-shedding (serve/health.BrownoutPolicy) is
    refusing this request's lane — bulk sheds first, interactive rides
    through to the hard admission bound. RETRIABLE by design: carries
    `retry_after_s`, the service's pressure-scaled hint for when capacity
    should be back (probation probes re-admitting devices, or the queue
    draining). Counted under "serve_shed_bulk"."""

    code = "brownout"

    def __init__(
        self,
        lane,
        retry_after_s,
        depth=None,
        capacity_fraction=None,
        program=None,
    ):
        detail = []
        if capacity_fraction is not None:
            detail.append("capacity %d%%" % round(capacity_fraction * 100))
        if depth is not None:
            detail.append("depth %d" % depth)
        super().__init__(
            "service brownout (%s): %s lane shed — retry after ~%.3gs"
            % (", ".join(detail) or "degraded", lane, retry_after_s),
            program=program,
            retry_after_s=retry_after_s,
        )
        self.lane = lane
        self.depth = depth
        self.capacity_fraction = capacity_fraction


class QuorumUnreachableError(ServiceRetryableError):
    """The threshold-issuance layer cannot assemble t distinct valid
    partial signatures for a request: too many authorities are crashed,
    hung, quarantined, or emitting corrupt partials (coconut_tpu/issue/).
    RETRIABLE by design — quorum loss is usually transient (authorities
    re-admit through the probation ladder; a hedged retry may land on a
    healthier pool). Carries `needed` (the threshold t), `have` (distinct
    valid partials collected), and `live` (authorities that could still
    contribute when the service gave up). Counted under
    "issue_quorum_unreachable"."""

    code = "quorum_unreachable"

    def __init__(self, needed, have, live=0, program=None, retry_after_s=None):
        super().__init__(
            "issuance quorum unreachable: have %d of %d required partial "
            "signatures with only %d live authorities left able to "
            "contribute — retry once the pool recovers" % (have, needed, live),
            program=program,
            retry_after_s=retry_after_s,
        )
        self.needed = needed
        self.have = have
        self.live = live


class ServiceClosedError(ServiceRetryableError):
    """A request was submitted to (or was still queued in) a credential
    service that is draining or shut down (serve/service.py). Futures of
    requests abandoned by a non-draining shutdown resolve with this
    exception so no caller ever hangs on a dropped future.

    RETRYABLE over the wire (PR 14): a closing replica is a fleet-level
    transient — some OTHER replica can serve the request right now, so
    the router's failover path must treat a closed-replica refusal like a
    transport failure and resubmit on a ring successor instead of
    surfacing a terminal error mid-restart. `retry_after_s` defaults to
    0.0 ("retry elsewhere immediately"); a single-replica caller with
    nowhere to fail over can still treat it as terminal by checking the
    `code`."""

    code = "closed"


class ShareVerificationError(GeneralError):
    """A Pedersen-committed share failed verification against its dealer's
    coefficient commitments (sss.PedersenVSS.verify_share), or a DVSS/DKG
    participant refused a structurally-invalid share (own share echoed
    back, duplicate dealer). Carries `dealer_id` — the authority whose
    sharing is at fault, the exact-attribution analogue of the issuance
    path's corrupt-partial naming — and `round`, the key-lifecycle round
    label ("dkg" / "refresh" / "reshare" / None for offline use) so
    complaints are auditable. NOT retriable: the same share can never
    start verifying; the dealer must be excluded."""

    code = "share_rejected"

    def __init__(self, message, dealer_id=None, round=None):
        super().__init__(message)
        self.dealer_id = dealer_id
        self.round = round


class DkgAbortedError(ServiceRetryableError):
    """A distributed key-generation (or proactive refresh / reshare) round
    could not complete: after excluding dealers named by share-verification
    complaints and dealers that were unreachable, fewer than `threshold`
    qualified dealers remain, so no key could be established
    (coconut_tpu/keylife/dkg.py). RETRIABLE — unreachable authorities
    usually return (probation ladder, restarts); a later round may
    succeed. Carries `needed` (the threshold t), `qualified` (dealers
    that survived complaints), and `excluded` (the sorted ids of dealers
    named by complaints or unreachable)."""

    code = "dkg_aborted"

    def __init__(
        self, needed, qualified, excluded=(), program=None, retry_after_s=None
    ):
        excluded = tuple(sorted(excluded))
        super().__init__(
            "DKG aborted: only %d of %d required qualified dealers remain "
            "(excluded: %s) — retry once the authority pool recovers"
            % (qualified, needed, list(excluded) or "none"),
            program=program,
            retry_after_s=retry_after_s,
        )
        self.needed = needed
        self.qualified = qualified
        self.excluded = excluded


class EpochUnknownError(CoconutError):
    """A request named a key epoch this service has never activated (or has
    not activated YET — a client racing ahead of a rollover). NOT blindly
    retriable: a future epoch may become valid after the rollover lands,
    but a fabricated epoch never will, and the service cannot tell which —
    callers should re-resolve the live epoch set from beacons and resubmit
    under an advertised epoch. Carries `epoch` and the `live` epoch ids
    known when refused. Counted under "keylife_epoch_unknown"."""

    code = "epoch_unknown"

    def __init__(self, epoch, live=()):
        super().__init__(
            "unknown key epoch %d: this service has epochs %s live — "
            "re-resolve the epoch set and resubmit" % (epoch, sorted(live))
        )
        self.epoch = epoch
        self.live = tuple(sorted(live))


class EpochRetiredError(CoconutError):
    """A request named a key epoch that existed but has been retired out of
    the bounded live window (keylife.EpochRegistry): its verkey is no
    longer served and credentials minted under it can no longer be
    verified here. NOT retriable — retirement is monotonic; the credential
    must be re-minted under a live epoch. Carries `epoch` and the `live`
    epoch ids. Counted under "keylife_epoch_retired"."""

    code = "epoch_retired"

    def __init__(self, epoch, live=()):
        super().__init__(
            "key epoch %d is retired: credentials minted under it must be "
            "re-minted (live epochs: %s)" % (epoch, sorted(live))
        )
        self.epoch = epoch
        self.live = tuple(sorted(live))


class TenantAuthError(CoconutError):
    """The gateway (coconut_tpu/net) rejected a request whose API key maps
    to no provisioned tenant. NOT retriable: resubmitting the same key
    can never succeed. Counted under "gateway_auth_failures"."""

    code = "tenant_auth"


class TenantQuotaError(CoconutError):
    """A tenant's absolute request quota is exhausted (net/tenant.py).
    NOT retriable within the quota epoch — unlike a token-bucket throttle
    there is no refill to wait for; the operator must raise the quota.
    Counted under "gateway_tenant_<id>_quota_rejected"."""

    code = "tenant_quota"

    def __init__(self, tenant, used, quota):
        super().__init__(
            "tenant %r quota exhausted (%d/%d requests): raise the quota "
            "or rotate the epoch" % (tenant, used, quota)
        )
        self.tenant = tenant
        self.used = used
        self.quota = quota


class TenantRateLimitError(ServiceRetryableError):
    """A tenant's token bucket is empty (net/tenant.py): the request was
    refused BEFORE engine admission. RETRIABLE — `retry_after_s` is the
    bucket's refill horizon for one token. Counted under
    "gateway_tenant_<id>_throttled"."""

    code = "tenant_rate_limited"

    def __init__(self, tenant, retry_after_s, program=None):
        super().__init__(
            "tenant %r rate-limited: token bucket empty — retry after "
            "~%.3gs" % (tenant, _finite_retry_after(retry_after_s)),
            program=program,
            retry_after_s=retry_after_s,
        )
        self.tenant = tenant


class DoubleSpendError(CoconutError):
    """A show-verify lane presented a credential whose nullifier is
    already in the replicated nullifier set (coconut_tpu/state) — the
    Coconut paper's e-cash/petition double-spend case. NOT retriable
    anywhere in the fleet: the nullifier is a deterministic digest of
    the proof transcript, so replaying the same show against any
    replica that has the fact (locally witnessed, WAL-replayed, or
    anti-entropy-replicated) yields the same rejection. Carries the
    `nullifier` hex digest, the `epoch` it is scoped to, and (PR 19)
    the application `domain` when the show was domain-scoped (petition
    campaign, e-cash — see state/nullifier.py). Counted under
    "nullifier_double_spends"."""

    code = "double_spend"

    # class-level defaults: error_from_wire reconstructs non-retryable
    # errors via cls.__new__ + CoconutError.__init__, which never runs
    # this subclass __init__ — attribute reads must still succeed
    nullifier = None
    epoch = None
    domain = None

    def __init__(self, nullifier=None, epoch=None, domain=None):
        super().__init__(
            "credential already shown: nullifier %s is spent%s%s"
            % (
                nullifier if nullifier is not None else "<unknown>",
                "" if epoch is None else " (epoch %d)" % epoch,
                "" if domain is None else " [domain %s]" % domain,
            )
        )
        self.nullifier = nullifier
        self.epoch = epoch
        self.domain = domain

    def _restore_wire_fields(self, message):
        # the envelope carries only (code, message); the message format
        # above is part of the wire contract, so the structured fields
        # survive the round trip — clients match on err.nullifier, not
        # on message text
        m = re.search(
            r"nullifier ([0-9a-f]{64}) is spent"
            r"(?: \(epoch (\d+)\))?(?: \[domain ([^\]]+)\])?",
            message,
        )
        if m is not None:
            self.nullifier = m.group(1)
            self.epoch = None if m.group(2) is None else int(m.group(2))
            self.domain = m.group(3)


#: the 1:1 code <-> class map the wire error envelope encodes/decodes
#: through (net/wire.py). Retriable codes reconstruct via `from_wire`
#: (shared fields only); the rest rebuild with their message.
WIRE_ERROR_CODES = {
    cls.code: cls
    for cls in (
        GeneralError,
        DeserializationError,
        UnsupportedNoOfMessages,
        UnequalNoOfBasesExponents,
        PSError,
        TransientBackendError,
        ServiceRetryableError,
        ServiceOverloadedError,
        ServiceBrownoutError,
        QuorumUnreachableError,
        ServiceClosedError,
        TenantAuthError,
        TenantQuotaError,
        TenantRateLimitError,
        ShareVerificationError,
        DkgAbortedError,
        EpochUnknownError,
        EpochRetiredError,
        DoubleSpendError,
    )
}


def error_from_wire(code, message, program=None, retry_after_s=0.0):
    """Rebuild the typed exception a wire error envelope describes.
    Unknown codes degrade to GeneralError (forward compatibility: a newer
    server may emit codes this client predates) with the code preserved
    as an instance attribute so nothing is lost."""
    cls = WIRE_ERROR_CODES.get(code)
    if cls is None:
        err = GeneralError(message)
        err.code = code
        return err
    if issubclass(cls, ServiceRetryableError):
        return cls.from_wire(
            message, program=program, retry_after_s=retry_after_s
        )
    err = cls.__new__(cls)
    CoconutError.__init__(err, message)
    if program is not None:
        err.program = program
    restore = getattr(err, "_restore_wire_fields", None)
    if restore is not None:
        restore(message)
    return err


class CheckpointCorruptError(CoconutError):
    """A stream checkpoint file failed integrity validation: truncated or
    unparseable bytes, an unknown schema version, or a CRC mismatch.
    stream.StreamState catches this internally, quarantines the file aside
    (`<path>.corrupt*`) and restarts cleanly — it must never surface as a
    bare json.JSONDecodeError mid-resume."""


class CheckpointMismatchError(CoconutError):
    """A structurally-valid checkpoint belongs to a DIFFERENT run: its
    stored run-config fingerprint (result mode + verkey digest —
    stream.run_fingerprint) disagrees with the resuming run's. Unlike
    corruption this fails loudly instead of quarantining: silently resuming
    the wrong run would produce tallies for a stream nobody asked about."""

    def __init__(self, stored, expected):
        super().__init__(
            "checkpoint fingerprint %s does not match this run's %s: "
            "refusing to resume a different run's state (delete or move "
            "the state file to start over)" % (stored, expected)
        )
        self.stored = stored
        self.expected = expected
