"""Typed errors mirroring the reference's `CoconutErrorKind` (errors.rs:5-24),
with the SURVEY.md §5 mandate applied: no asserts in library code — hot-path
`assert!`/`unwrap` in the reference (signature.rs:133-134,289-290,449,477)
become raised, typed exceptions here."""


class CoconutError(Exception):
    """Base class for all framework errors (reference: errors.rs:26-56)."""


class UnsupportedNoOfMessages(CoconutError):
    """Verkey valid for `expected` messages but given `given` (errors.rs:7-11)."""

    def __init__(self, expected, given):
        super().__init__(
            "Verkey valid for %d messages but given %d messages" % (expected, given)
        )
        self.expected = expected
        self.given = given


class UnequalNoOfBasesExponents(CoconutError):
    """Same number of bases and exponents required (errors.rs:13-17)."""

    def __init__(self, bases, exponents):
        super().__init__(
            "Same no of bases and exponents required. %d bases and %d exponents"
            % (bases, exponents)
        )
        self.bases = bases
        self.exponents = exponents


class PSError(CoconutError):
    """Error raised by the PS-signature layer (errors.rs:19-20; ps_sig::errors)."""


class DeserializationError(CoconutError):
    """Malformed or non-canonical byte encoding (rebuild addition: the
    reference had no wire validation — SURVEY.md §4 'gaps to improve')."""


class GeneralError(CoconutError):
    """Catch-all with a message (errors.rs:22-23)."""


class TransientBackendError(CoconutError):
    """A backend dispatch or readback failure that is expected to succeed
    on re-attempt (device preemption, tunnel RPC hiccup, transient transfer
    failure). The stream supervision layer (stream.verify_stream +
    retry.RetryPolicy) retries these with bounded backoff and then falls
    back to a designated backend; any other exception class is treated as
    permanent and propagates immediately."""


class ServiceRetryableError(CoconutError):
    """Base for every LOUD-but-retriable refusal an online service emits
    (overload rejection, brownout shedding, quorum loss). The unified
    contract (coconut_tpu/engine): every subclass carries `program` — the
    engine program (verify / mint / prepare / show_prove / show_verify)
    that refused, or None for single-program legacy call sites — and
    `retry_after_s`, the service's hint for when capacity should be back
    (None when it has no estimate). Clients branch on this ONE type to
    implement backoff-and-resubmit without enumerating refusal kinds."""

    def __init__(self, message, program=None, retry_after_s=None):
        super().__init__(message)
        self.program = program
        self.retry_after_s = retry_after_s


class ServiceOverloadedError(ServiceRetryableError):
    """The serving layer's bounded request queue is at capacity: admission
    control rejects the request LOUDLY instead of growing the queue without
    bound (serve/queue.py). Callers should back off and resubmit; the
    "serve_rejected" counter tracks how often this fires. Carries `depth`
    (current) and `max_depth` (the configured admission bound), plus the
    ServiceRetryableError `program` / `retry_after_s` fields."""

    def __init__(self, depth, max_depth, program=None, retry_after_s=None):
        super().__init__(
            "serving queue at capacity (%d/%d): request rejected by "
            "admission control — back off and resubmit" % (depth, max_depth),
            program=program,
            retry_after_s=retry_after_s,
        )
        self.depth = depth
        self.max_depth = max_depth


class ServiceBrownoutError(ServiceRetryableError):
    """The serving layer is in BROWNOUT: quarantined executors cut the
    pool's capacity, or sustained queue pressure crossed the brownout
    threshold, and graded load-shedding (serve/health.BrownoutPolicy) is
    refusing this request's lane — bulk sheds first, interactive rides
    through to the hard admission bound. RETRIABLE by design: carries
    `retry_after_s`, the service's pressure-scaled hint for when capacity
    should be back (probation probes re-admitting devices, or the queue
    draining). Counted under "serve_shed_bulk"."""

    def __init__(
        self,
        lane,
        retry_after_s,
        depth=None,
        capacity_fraction=None,
        program=None,
    ):
        detail = []
        if capacity_fraction is not None:
            detail.append("capacity %d%%" % round(capacity_fraction * 100))
        if depth is not None:
            detail.append("depth %d" % depth)
        super().__init__(
            "service brownout (%s): %s lane shed — retry after ~%.3gs"
            % (", ".join(detail) or "degraded", lane, retry_after_s),
            program=program,
            retry_after_s=retry_after_s,
        )
        self.lane = lane
        self.depth = depth
        self.capacity_fraction = capacity_fraction


class QuorumUnreachableError(ServiceRetryableError):
    """The threshold-issuance layer cannot assemble t distinct valid
    partial signatures for a request: too many authorities are crashed,
    hung, quarantined, or emitting corrupt partials (coconut_tpu/issue/).
    RETRIABLE by design — quorum loss is usually transient (authorities
    re-admit through the probation ladder; a hedged retry may land on a
    healthier pool). Carries `needed` (the threshold t), `have` (distinct
    valid partials collected), and `live` (authorities that could still
    contribute when the service gave up). Counted under
    "issue_quorum_unreachable"."""

    def __init__(self, needed, have, live=0, program=None, retry_after_s=None):
        super().__init__(
            "issuance quorum unreachable: have %d of %d required partial "
            "signatures with only %d live authorities left able to "
            "contribute — retry once the pool recovers" % (have, needed, live),
            program=program,
            retry_after_s=retry_after_s,
        )
        self.needed = needed
        self.have = have
        self.live = live


class ServiceClosedError(CoconutError):
    """A request was submitted to (or was still queued in) a credential
    service that is draining or shut down (serve/service.py). Futures of
    requests abandoned by a non-draining shutdown resolve with this
    exception so no caller ever hangs on a dropped future."""


class CheckpointCorruptError(CoconutError):
    """A stream checkpoint file failed integrity validation: truncated or
    unparseable bytes, an unknown schema version, or a CRC mismatch.
    stream.StreamState catches this internally, quarantines the file aside
    (`<path>.corrupt*`) and restarts cleanly — it must never surface as a
    bare json.JSONDecodeError mid-resume."""


class CheckpointMismatchError(CoconutError):
    """A structurally-valid checkpoint belongs to a DIFFERENT run: its
    stored run-config fingerprint (result mode + verkey digest —
    stream.run_fingerprint) disagrees with the resuming run's. Unlike
    corruption this fails loudly instead of quarantining: silently resuming
    the wrong run would produce tallies for a stream nobody asked about."""

    def __init__(self, stored, expected):
        super().__init__(
            "checkpoint fingerprint %s does not match this run's %s: "
            "refusing to resume a different run's state (delete or move "
            "the state file to start over)" % (stored, expected)
        )
        self.stored = stored
        self.expected = expected
