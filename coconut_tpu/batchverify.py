"""Random-linear-combination (RLC) batch verification support (PR 16).

Classical small-exponent batch verification (Bellare-Garay-Rabin '98;
Ferrara-Green-Hohenberger-Pedersen '09 for pairing-based signatures):
B independent PS pairing checks

    e(sigma1_i, acc_i) * e(-sigma2_i, g_tilde) == 1        for every i

collapse, under per-lane random exponents r_i, into ONE product

    prod_i e(r_i * sigma1_i, acc_i) * e(sum_i r_i * (-sigma2_i), g_tilde)
        == 1

evaluated with a single multi-Miller loop and a SINGLE shared final
exponentiation (B+1 pairs instead of 2B pairs and B final exps). A
forged lane survives only if its pairing defect delta_i satisfies
sum_i r_i * delta_i == 0 in GT's exponent group — probability <= 2^-lam
over the r_i draw for any adversarial batch fixed before the draw.

This module owns the two soundness-critical ingredients shared by every
backend:

  - `derive_combiners`: the r_i themselves, drawn DETERMINISTICALLY from
    a domain-separated hash of the batch transcript (SHA-256 in counter
    mode). Deterministic derivation keeps runs replayable (same batch ->
    same exponents -> bit-identical verdicts across processes) while
    remaining sound: the transcript commits to every signature, message,
    verkey byte and the key epoch, so an adversary must choose its
    forgery BEFORE learning the exponents — exactly the random-oracle
    analogue of drawing them fresh (Fiat-Shamir applied to the batch
    check).
  - `verify_transcript` / `show_transcript`: the canonical byte strings
    the exponents are derived from. Domain separation covers the check
    flavor (verify vs show), lambda, the verkey, and the PR-15 key
    epoch, so cross-epoch groups never share exponents even when the
    refreshed verkey bytes coincide (proactive refresh preserves the
    public key).

The exponent width lam ("soundness bits") is configurable via
COCONUT_BATCH_LAMBDA: default 128, floor 64 (the ISSUE's minimum),
ceiling 128 (the device backends' signed-digit schedule for combiner
scalars is sized for 128-bit magnitudes — `tpu/backend._R_RAND_BITS`).
"""

import hashlib
import os

from .ops.fields import R

#: default soundness parameter: forged lanes survive w.p. <= ~2^-128
DEFAULT_LAMBDA = 128
#: hard floor — below this the combined check is not a verifier
MIN_LAMBDA = 64
#: ceiling — the TPU backend's combiner digit schedule is 128-bit
MAX_LAMBDA = 128

_DOMAIN_VERIFY = b"coconut-tpu/batchverify/v1/verify"
_DOMAIN_SHOW = b"coconut-tpu/batchverify/v1/show"


def batch_lambda():
    """Resolve the soundness parameter from COCONUT_BATCH_LAMBDA.

    Raises ValueError on anything below MIN_LAMBDA (a too-narrow
    exponent silently weakens soundness — refuse loudly) or above
    MAX_LAMBDA (wider than the device digit schedule can carry)."""
    raw = os.environ.get("COCONUT_BATCH_LAMBDA")
    if raw is None:
        return DEFAULT_LAMBDA
    lam = int(raw)
    return _check_lambda(lam)


def env_batched_default():
    """True when COCONUT_BATCH_VERIFY selects the batched (RLC-combined)
    verify path by default — the serve/engine mode knob. Accepts
    "1"/"true"/"on"/"yes"/"batched" (case-insensitive); anything else,
    including unset, keeps the exact per-lane default."""
    raw = os.environ.get("COCONUT_BATCH_VERIFY", "")
    return raw.strip().lower() in ("1", "true", "on", "yes", "batched")


def _check_lambda(lam):
    if not MIN_LAMBDA <= lam <= MAX_LAMBDA:
        raise ValueError(
            "COCONUT_BATCH_LAMBDA must be in [%d, %d] (got %r)"
            % (MIN_LAMBDA, MAX_LAMBDA, lam)
        )
    return lam


def derive_combiners(transcript, n, lam=None, domain=_DOMAIN_VERIFY):
    """n deterministic nonzero combiner exponents r_i in [1, 2^lam - 1].

    SHA-256 counter-mode XOF over a seed committing to the domain tag,
    lam, and the batch transcript. r_i = (x_i mod (2^lam - 1)) + 1 where
    x_i is a fresh 256-bit block — the modular fold's bias is < 2^-128,
    irrelevant next to the 2^-lam soundness bound, and (unlike rejection
    sampling) keeps lane i's exponent a pure function of (seed, i)."""
    lam = batch_lambda() if lam is None else _check_lambda(lam)
    seed = hashlib.sha256(
        domain + b"|" + bytes([lam]) + b"|" + transcript
    ).digest()
    span = (1 << lam) - 1
    out = []
    for i in range(n):
        block = hashlib.sha256(seed + i.to_bytes(8, "big")).digest()
        out.append(int.from_bytes(block, "big") % span + 1)
    return out


def _absorb(h, tag, data):
    """Length-prefixed component absorption — no concatenation ambiguity
    between adjacent variable-length fields."""
    h.update(tag)
    h.update(len(data).to_bytes(4, "big"))
    h.update(data)


def _absorb_epoch(h, epoch):
    if epoch is None:
        h.update(b"E\x00")
    else:
        h.update(b"E\x01")
        h.update(int(epoch).to_bytes(8, "big"))


def verify_transcript(sigs, messages_list, vk, params, epoch=None):
    """Canonical transcript digest for a plain batch-verify RLC draw.

    Commits to the verkey bytes, the key epoch (PR 15 — proactive
    refresh keeps the verkey bytes stable across epochs, so the epoch id
    must be explicit), and every lane's signature + message vector. An
    identity sigma is encoded as an empty component (those lanes are
    rejected outright, never folded)."""
    ctx = params.ctx
    h = hashlib.sha256()
    _absorb(h, b"D", _DOMAIN_VERIFY)
    _absorb_epoch(h, epoch)
    _absorb(h, b"K", vk.to_bytes(ctx))
    h.update(len(sigs).to_bytes(4, "big"))
    for sig, msgs in zip(sigs, messages_list):
        _absorb(h, b"S", sig.to_bytes(ctx))
        h.update(len(msgs).to_bytes(4, "big"))
        for m in msgs:
            h.update((m % R).to_bytes(32, "big"))
    return h.digest()


def show_transcript(proofs, vk, params, revealed_msgs_list, challenges,
                    epoch=None):
    """Canonical transcript digest for a batched show-verify RLC draw.

    Commits to the verkey, epoch, every proof's wire bytes, its sorted
    revealed-message map, and its Fiat-Shamir challenge."""
    ctx = params.ctx
    h = hashlib.sha256()
    _absorb(h, b"D", _DOMAIN_SHOW)
    _absorb_epoch(h, epoch)
    _absorb(h, b"K", vk.to_bytes(ctx))
    h.update(len(proofs).to_bytes(4, "big"))
    for proof, revealed, chal in zip(proofs, revealed_msgs_list,
                                     challenges):
        _absorb(h, b"P", proof.to_bytes(ctx))
        items = sorted(revealed.items())
        h.update(len(items).to_bytes(4, "big"))
        for idx, m in items:
            h.update(int(idx).to_bytes(4, "big"))
            h.update((m % R).to_bytes(32, "big"))
        h.update((chal % R).to_bytes(32, "big"))
    return h.digest()
