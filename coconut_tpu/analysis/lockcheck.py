"""Runtime lock-order tracking (the dynamic half of the lock-order checker).

The static pass (analysis/lockorder.py) only sees syntactic ``with``
nesting inside one function; real deadlocks come from cross-function,
cross-thread interleavings. This module patches ``threading.Lock`` /
``threading.RLock`` so every lock allocated *by coconut_tpu code* while
tracking is enabled becomes a TrackedLock that records the global
acquisition-order graph as the process actually runs:

  - lock identity is the ALLOCATION SITE (file:line of the coconut_tpu
    frame that constructed it) — every instance of ``RequestQueue`` maps
    to the same node, so orders learned from one instance apply to all;
  - holding A while acquiring B adds edge A -> B; an acquisition that
    would add B -> A when A -> B was already observed is an INVERSION —
    the two code paths can deadlock under the right interleaving — and
    is recorded with both stacks' evidence;
  - RLock re-entry and ``Condition.wait``'s release/reacquire are
    handled via per-thread depth counting and the ``_release_save`` /
    ``_acquire_restore`` / ``_is_owned`` protocol (``threading.Condition``
    picks these up from the wrapped lock automatically — patching the
    two factories covers Conditions too);
  - self-edges are ignored (re-entering the same allocation site is the
    RLock contract, not an ordering bug).

Wiring: tests/conftest.py installs the tracker for the chaos/fake-clock
suites (and for everything when COCONUT_LOCK_CHECK=1) and fails any test
that recorded an inversion. Overhead is one dict touch per first-acquire,
zero for code outside coconut_tpu (untracked locks are returned raw).
"""

import os
import sys
import threading

_ORIG_LOCK = threading.Lock
_ORIG_RLOCK = threading.RLock

ENV_KNOB = "COCONUT_LOCK_CHECK"


def _caller_site(track_all):
    """file:line of the frame that asked for the lock, or None when the
    allocation must stay untracked.

    Locks allocated by threading.py internals (Thread/Event bootstrap
    machinery) are NEVER tracked: wrapping them makes interpreter thread
    bootstrap re-enter the tracker (observed as infinite recursion via
    ``Event.set`` on a tracked Condition lock). The one exception is
    ``Condition.__init__`` allocating its default RLock — that frame is
    walked through so the lock is attributed to ``Condition()``'s caller
    and user Conditions stay covered."""
    f = sys._getframe(1)
    for _ in range(16):
        if f is None:
            return None
        fn = f.f_code.co_filename.replace(os.sep, "/")
        if fn.endswith("analysis/lockcheck.py"):
            f = f.f_back
            continue
        if fn.endswith("threading.py"):
            slf = f.f_locals.get("self")
            if (
                f.f_code.co_name == "__init__"
                and type(slf).__name__ == "Condition"
            ):
                f = f.f_back
                continue
            return None
        site = "%s:%d" % (fn, f.f_lineno)
        if track_all:
            return site
        if "/coconut_tpu/" in fn and "/analysis/" not in fn:
            return site
        return None
    return None


class TrackedLock(object):
    """Proxy around a real Lock/RLock recording first-acquire order."""

    def __init__(self, inner, site, tracker):
        self._inner = inner
        self._site = site
        self._tracker = tracker
        self._depth = threading.local()

    # -- depth bookkeeping (first acquire / last release only) ----------

    def _inc(self):
        n = getattr(self._depth, "n", 0) + 1
        self._depth.n = n
        if n == 1:
            self._tracker.note_acquire(self._site)

    def _dec(self):
        n = getattr(self._depth, "n", 0) - 1
        self._depth.n = n
        if n <= 0:
            self._depth.n = 0
            self._tracker.note_release(self._site)

    # -- lock protocol --------------------------------------------------

    def acquire(self, blocking=True, timeout=-1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._inc()
        return ok

    def release(self):
        self._dec()
        self._inner.release()

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    # -- Condition.wait protocol ----------------------------------------

    def _release_save(self):
        n = getattr(self._depth, "n", 0)
        self._depth.n = 0
        if n > 0:
            self._tracker.note_release(self._site)
        save = getattr(self._inner, "_release_save", None)
        state = save() if save is not None else self._inner.release()
        return (state, n)

    def _acquire_restore(self, saved):
        state, n = saved
        restore = getattr(self._inner, "_acquire_restore", None)
        if restore is not None:
            restore(state)
        else:
            self._inner.acquire()
        self._depth.n = n
        if n > 0:
            self._tracker.note_acquire(self._site)

    def _is_owned(self):
        owned = getattr(self._inner, "_is_owned", None)
        if owned is not None:
            return owned()
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def __repr__(self):
        return "<TrackedLock %s of %r>" % (self._site, self._inner)


class LockOrderTracker(object):
    """Process-global acquisition-order graph + inversion log."""

    def __init__(self, track_all=False):
        self.track_all = track_all
        self.enabled = False
        self._mu = _ORIG_LOCK()  # raw: never track the tracker
        self._held = threading.local()
        self.edges = {}  # (a, b) -> {"thread", "count"}
        self.inversions = []  # {"held","acquiring","prior_edge","thread"}

    # -- recording ------------------------------------------------------

    def _stack(self):
        st = getattr(self._held, "stack", None)
        if st is None:
            st = self._held.stack = []
        return st

    def note_acquire(self, site):
        if not self.enabled:
            return
        st = self._stack()
        # get_ident() is a C-level call that cannot allocate a thread
        # object — current_thread() can (registering a _DummyThread takes
        # a threading-internal Condition), which must not re-enter here.
        tname = "tid:%d" % threading.get_ident()
        with self._mu:
            for h in st:
                if h == site:
                    continue
                if (site, h) in self.edges and (h, site) not in self.edges:
                    self.inversions.append(
                        {
                            "held": h,
                            "acquiring": site,
                            "prior_edge": "%s -> %s (seen in thread %s)"
                            % (site, h, self.edges[(site, h)]["thread"]),
                            "thread": tname,
                        }
                    )
                ev = self.edges.setdefault(
                    (h, site), {"thread": tname, "count": 0}
                )
                ev["count"] += 1
        st.append(site)

    def note_release(self, site):
        st = self._stack()
        # released out of order is legal (hand-over-hand); drop last match
        for i in range(len(st) - 1, -1, -1):
            if st[i] == site:
                del st[i]
                break

    # -- lifecycle ------------------------------------------------------

    def reset(self):
        with self._mu:
            self.edges.clear()
            self.inversions.clear()

    def drain_inversions(self):
        with self._mu:
            out = list(self.inversions)
            self.inversions.clear()
        return out

    # -- factory patching ----------------------------------------------

    def wrap_lock(self, *a, **kw):
        inner = _ORIG_LOCK(*a, **kw)
        if not self.enabled:
            return inner
        site = _caller_site(self.track_all)
        if site is None:
            return inner
        return TrackedLock(inner, site, self)

    def wrap_rlock(self, *a, **kw):
        inner = _ORIG_RLOCK(*a, **kw)
        if not self.enabled:
            return inner
        site = _caller_site(self.track_all)
        if site is None:
            return inner
        return TrackedLock(inner, site, self)


_installed = None


def install(track_all=False):
    """Patch threading.Lock/RLock; returns the (singleton) tracker.
    threading.Condition() picks the patched RLock up as its default lock
    and delegates the wait-protocol methods to the proxy."""
    global _installed
    if _installed is not None:
        _installed.enabled = True
        return _installed
    tracker = LockOrderTracker(track_all=track_all)
    tracker.enabled = True
    threading.Lock = tracker.wrap_lock
    threading.RLock = tracker.wrap_rlock
    _installed = tracker
    return tracker


def uninstall():
    global _installed
    threading.Lock = _ORIG_LOCK
    threading.RLock = _ORIG_RLOCK
    if _installed is not None:
        _installed.enabled = False
    _installed = None


def env_enabled():
    return os.environ.get(ENV_KNOB, "").strip() not in ("", "0", "false")
