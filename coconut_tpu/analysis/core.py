"""Shared infrastructure for the invariant lint suite (ISSUE 20).

The repo upholds several load-bearing cross-file contracts purely by
convention — the thread/lock discipline, the errors.py <-> WIRE_ERROR_CODES
wire contract, the CONSTTIME.md no-secret-branches rule, the state/atomic.py
every-durable-write-is-tmp+fsync+replace policy, and the README metrics
glossary. Each contract gets a checker (analysis/<name>.py); this module is
the machinery they share:

  - ``Finding``: one violation, with a STABLE fingerprint (checker + rule +
    file + content key — deliberately NOT the line number, so unrelated
    edits above a finding don't churn the baseline);
  - inline pragmas: ``# lint: allow(<checker>[, reason])`` on the flagged
    line or the line directly above suppresses that checker's findings
    there — the in-tree justification syntax for accepted exceptions
    (e.g. CONSTTIME.md's documented host big-int caveat);
  - the suppression baseline (``analysis_baseline.json`` at the repo
    root): fingerprints of known findings, each carrying a one-line
    justification. ``--fail-on-new`` (the CI gate) fails on any finding
    that is neither pragma-suppressed nor baselined;
  - ``Context``: parsed-AST + source-line cache over the scanned tree so
    five checkers pay one parse per file.

Checkers are pure functions of the tree: no network, no device, no
imports of the heavyweight jax stack (wire-contract imports errors.py
only). ``python -m coconut_tpu.analysis`` is the runner.
"""

import ast
import hashlib
import json
import os
import re

#: the five registered checker names (import order = report order)
CHECKER_NAMES = (
    "lock-order",
    "wire-contract",
    "const-time",
    "durability",
    "metrics-doc",
)

#: inline suppression: ``# lint: allow(<checker>[, reason])``. The
#: reason may wrap onto following comment lines, so only the opening —
#: ``allow(<checker>`` followed by ``,`` / ``)`` / end-of-line — anchors.
_PRAGMA_RE = re.compile(
    r"#\s*lint:\s*allow\(\s*([a-z][a-z-]*)\s*(?:[,)]|$)"
)

DEFAULT_BASELINE = "analysis_baseline.json"


class Finding(object):
    """One checker violation.

    ``key`` is the content the fingerprint hashes (defaults to the
    message): keep it free of line numbers and absolute paths so the
    fingerprint survives unrelated edits and checkouts at other roots.
    """

    def __init__(self, checker, rule, path, line, message, key=None):
        self.checker = checker
        self.rule = rule
        self.path = path  # repo-relative, forward slashes
        self.line = int(line)
        self.message = message
        self.key = key if key is not None else message
        self.suppressed_by = None  # "pragma" | "baseline" | None

    @property
    def fingerprint(self):
        h = hashlib.sha256(
            ("%s|%s|%s|%s" % (self.checker, self.rule, self.path, self.key))
            .encode("utf-8")
        )
        return h.hexdigest()[:16]

    def to_dict(self):
        return {
            "checker": self.checker,
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "fingerprint": self.fingerprint,
            "suppressed_by": self.suppressed_by,
        }

    def __repr__(self):
        return "%s:%d: [%s/%s] %s" % (
            self.path,
            self.line,
            self.checker,
            self.rule,
            self.message,
        )


class SourceFile(object):
    """Parsed view of one scanned file: text, lines, AST (None for
    non-Python or syntax errors), and the pragma map."""

    def __init__(self, root, relpath):
        self.relpath = relpath
        self.abspath = os.path.join(root, relpath)
        with open(self.abspath, "r", encoding="utf-8") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self.tree = None
        if relpath.endswith(".py"):
            try:
                self.tree = ast.parse(self.text, filename=relpath)
            except SyntaxError:
                self.tree = None
        # line -> {checker names allowed there}
        self.pragmas = {}
        for i, line in enumerate(self.lines, start=1):
            for m in _PRAGMA_RE.finditer(line):
                self.pragmas.setdefault(i, set()).add(m.group(1))

    def pragma_allows(self, checker, line):
        """True if a ``# lint: allow(checker)`` pragma covers ``line``:
        on the line itself, or anywhere in the contiguous block of
        comment-only lines directly above it (pragma reasons wrap)."""
        if checker in self.pragmas.get(line, ()):
            return True
        ln = line - 1
        while ln >= 1 and ln >= line - 6:
            text = self.lines[ln - 1].strip() if ln <= len(self.lines) else ""
            if not text.startswith("#"):
                break
            if checker in self.pragmas.get(ln, ()):
                return True
            ln -= 1
        return False


class Context(object):
    """The scanned tree: repo root + lazily parsed files."""

    #: directories under the package root the scanners walk
    PACKAGE = "coconut_tpu"

    def __init__(self, root):
        self.root = os.path.abspath(root)
        self._files = {}

    def file(self, relpath):
        relpath = relpath.replace(os.sep, "/")
        sf = self._files.get(relpath)
        if sf is None:
            sf = self._files[relpath] = SourceFile(self.root, relpath)
        return sf

    def python_files(self, subdir=None):
        """Sorted repo-relative paths of every ``.py`` file under the
        package (or ``subdir`` within it). The analysis package itself is
        excluded — its fixture strings and checker tables would trip the
        very rules they implement."""
        base = self.PACKAGE if subdir is None else subdir
        top = os.path.join(self.root, base)
        out = []
        for dirpath, dirnames, filenames in os.walk(top):
            dirnames[:] = [
                d for d in dirnames if d not in ("__pycache__", ".git")
            ]
            if os.path.basename(dirpath) == "analysis":
                dirnames[:] = []
                continue
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    rel = os.path.relpath(
                        os.path.join(dirpath, fn), self.root
                    )
                    out.append(rel.replace(os.sep, "/"))
        return sorted(out)

    def exists(self, relpath):
        return os.path.exists(os.path.join(self.root, relpath))


# -- baseline ---------------------------------------------------------------


def load_baseline(path):
    """{fingerprint: entry} from a baseline JSON (empty if missing)."""
    if not path or not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    out = {}
    for entry in doc.get("suppressions", []):
        out[entry["fingerprint"]] = entry
    return out


def write_baseline(path, findings):
    """Write every unsuppressed finding's fingerprint as a suppression
    entry (reason left as TODO — the satellite contract is that each
    shipped suppression carries a real one-line justification)."""
    doc = {
        "version": 1,
        "suppressions": [
            {
                "fingerprint": f.fingerprint,
                "checker": f.checker,
                "rule": f.rule,
                "path": f.path,
                "reason": "TODO: justify or fix",
            }
            for f in findings
            if f.suppressed_by is None
        ],
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    return doc


def apply_suppressions(findings, ctx, baseline):
    """Stamp ``suppressed_by`` on each finding: inline pragma first, then
    baseline fingerprint. Returns the list of NEW (unsuppressed) findings."""
    new = []
    for f in findings:
        try:
            sf = ctx.file(f.path)
        except (OSError, UnicodeDecodeError):
            sf = None
        if sf is not None and sf.pragma_allows(f.checker, f.line):
            f.suppressed_by = "pragma"
        elif f.fingerprint in baseline:
            f.suppressed_by = "baseline"
        else:
            new.append(f)
    return new
