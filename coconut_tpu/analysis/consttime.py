"""const-time: CONSTTIME.md's no-secret-branches rule, machine-checked.

Coconut's threat model (Sonnino et al. §5; reference enforces it with
multi_scalar_mul_const_time) forbids secret-dependent timing on the
issuance path. CONSTTIME.md states the repo's discipline in prose; this
checker encodes the Python-level half of it as taint rules over the
scope the doc covers: tpu/ + signature.py + sss.py.

Taint SOURCES (curated table, not inference — the secrets are known):
  - key-share / secret-key parameters (batch_blind_sign.sigkey,
    batch_unblind.elgamal_sk, poly_eval.coeffs,
    reconstruct_secret.shares, fr_digits_signed_np.scalars,
    glv.decompose.k);
  - hidden messages entering the blind-sign path
    (batch_prepare_blind_sign.messages_list);
  - fresh randomness: any call of rand_fr / poly_random /
    secrets.randbelow (blinding scalars ARE secrets until the
    commitment is opened).

PROPAGATION is intra-function and syntactic: assignment from a tainted
expression taints the targets, iterating a tainted iterable taints the
loop variable(s), arithmetic/method calls on tainted values stay
tainted. ``len(x)``, ``isinstance``, shape/dtype attribute reads, and
``is None`` tests SANITIZE — sizes and presence are public.

FLAGS (each a rule):
  secret-branch   ``if`` / ``while`` / ``assert`` / ternary whose test
                  reads a tainted value — Python control flow with
                  secret-dependent direction;
  secret-cast     ``int(x)`` / ``bool(x)`` on a tainted value — CPython
                  big-int conversion cost correlates with bit length
                  (CONSTTIME.md §1's documented host caveat: the two
                  accepted sites carry ``# lint: allow(const-time)``
                  pragmas citing it).

Intra-function only, by design: cross-function flows go through jnp
arrays on device where lane-uniform kernels make timing data-independent
— the Python boundary is exactly where the discipline can silently rot.
"""

import ast

from .core import Finding

CHECKER = "const-time"

#: the scope CONSTTIME.md covers
SCOPE_PREFIXES = (
    "coconut_tpu/tpu/",
    "coconut_tpu/signature.py",
    "coconut_tpu/sss.py",
)

#: (relpath, function name) -> parameter names that arrive secret
SECRET_PARAMS = {
    ("coconut_tpu/signature.py", "batch_blind_sign"): ("sigkey",),
    ("coconut_tpu/signature.py", "batch_unblind"): ("elgamal_sk",),
    ("coconut_tpu/signature.py", "batch_prepare_blind_sign"): (
        "messages_list",
    ),
    ("coconut_tpu/sss.py", "poly_eval"): ("coeffs",),
    ("coconut_tpu/sss.py", "reconstruct_secret"): ("shares",),
    ("coconut_tpu/tpu/limbs.py", "fr_digits_signed_np"): ("scalars",),
    ("coconut_tpu/tpu/glv.py", "decompose"): ("k",),
}

#: calls whose RESULT is secret wherever they appear in scope
SECRET_CALLS = {"rand_fr", "poly_random", "randbelow"}

#: attribute reads that are public even on secret values
PUBLIC_ATTRS = {"shape", "dtype", "ndim", "size", "nbytes", "keys"}

#: call targets that launder taint (public summaries of secret data)
SANITIZING_CALLS = {"len", "isinstance", "type", "id", "range", "sorted_ids"}

_CAST_CALLS = {"int", "bool"}


def _dotted(node):
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class _Taint(object):
    """Per-function taint state + finding emission."""

    def __init__(self, rel, fn_name, seeds):
        self.rel = rel
        self.fn = fn_name
        self.tainted = set(seeds)

    # -- expression taint ---------------------------------------------------

    def expr_tainted(self, node):
        if node is None:
            return False
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            d = _dotted(node)
            if d is not None and d in self.tainted:
                return True
            if node.attr in PUBLIC_ATTRS:
                return False
            return self.expr_tainted(node.value)
        if isinstance(node, ast.Call):
            fn = node.func
            fn_name = fn.id if isinstance(fn, ast.Name) else (
                fn.attr if isinstance(fn, ast.Attribute) else None
            )
            if fn_name in SANITIZING_CALLS:
                return False
            if fn_name in SECRET_CALLS:
                return True
            if isinstance(fn, ast.Attribute) and self.expr_tainted(fn.value):
                return True  # method on a tainted value
            return any(
                self.expr_tainted(a) for a in node.args
            ) or any(self.expr_tainted(kw.value) for kw in node.keywords)
        if isinstance(node, ast.Compare):
            # `x is None` / `x is not None`: presence is public
            if all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops
            ) and all(
                isinstance(c, ast.Constant) and c.value is None
                for c in node.comparators
            ):
                return False
            return self.expr_tainted(node.left) or any(
                self.expr_tainted(c) for c in node.comparators
            )
        # generic: any tainted child taints the expression
        return any(
            self.expr_tainted(child)
            for child in ast.iter_child_nodes(node)
            if isinstance(child, ast.expr)
        )

    # -- assignment targets -------------------------------------------------

    def taint_target(self, tgt):
        if isinstance(tgt, ast.Name):
            self.tainted.add(tgt.id)
        elif isinstance(tgt, ast.Attribute):
            d = _dotted(tgt)
            if d is not None:
                self.tainted.add(d)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for elt in tgt.elts:
                self.taint_target(elt)
        elif isinstance(tgt, ast.Starred):
            self.taint_target(tgt.value)


def _first_arg_tainted(call, taint):
    return bool(call.args) and taint.expr_tainted(call.args[0])


def _scan_function(rel, fn_node, seeds, findings):
    taint = _Taint(rel, fn_node.name, seeds)
    body = fn_node.body

    def propagate(stmts):
        for node in stmts:
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign):
                    if taint.expr_tainted(sub.value):
                        for t in sub.targets:
                            taint.taint_target(t)
                elif isinstance(sub, (ast.AugAssign, ast.AnnAssign)):
                    if sub.value is not None and taint.expr_tainted(sub.value):
                        taint.taint_target(sub.target)
                elif isinstance(sub, (ast.For, ast.AsyncFor)):
                    if taint.expr_tainted(sub.iter):
                        taint.taint_target(sub.target)
                elif isinstance(sub, (ast.ListComp, ast.SetComp,
                                      ast.DictComp, ast.GeneratorExp)):
                    for gen in sub.generators:
                        if taint.expr_tainted(gen.iter):
                            taint.taint_target(gen.target)
                elif isinstance(sub, ast.NamedExpr):
                    if taint.expr_tainted(sub.value):
                        taint.taint_target(sub.target)

    # two propagation passes: loops can carry taint backward in source
    # order (x tainted at loop bottom, read at loop top)
    propagate(body)
    propagate(body)

    def flag(rule, node, what):
        findings.append(
            Finding(
                CHECKER,
                rule,
                rel,
                node.lineno,
                "%s in %s(): %s — secret-dependent Python-level timing "
                "(CONSTTIME.md)" % (rule, fn_node.name, what),
                key="%s:%s:%s" % (rule, fn_node.name, what),
            )
        )

    for node in ast.walk(fn_node):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node is not fn_node:
                continue  # nested defs get their own scan
        if isinstance(node, (ast.If, ast.While)) and taint.expr_tainted(
            node.test
        ):
            src = _dotted(node.test) or ast.dump(node.test)[:60]
            flag("secret-branch", node, "branch on tainted %r" % src)
        elif isinstance(node, ast.IfExp) and taint.expr_tainted(node.test):
            flag("secret-branch", node, "ternary on tainted test")
        elif isinstance(node, ast.Assert) and taint.expr_tainted(node.test):
            flag("secret-branch", node, "assert on tainted value")
        elif isinstance(node, ast.Call):
            fn = node.func
            if (
                isinstance(fn, ast.Name)
                and fn.id in _CAST_CALLS
                and _first_arg_tainted(node, taint)
            ):
                arg = _dotted(node.args[0]) or "<expr>"
                flag(
                    "secret-cast",
                    node,
                    "%s() on tainted %r" % (fn.id, arg),
                )


def run(ctx, files=None):
    if files is None:
        files = ctx.python_files()
    findings = []
    for rel in files:
        if not rel.startswith(SCOPE_PREFIXES):
            continue
        sf = ctx.file(rel)
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            seeds = SECRET_PARAMS.get((rel, node.name), ())
            declared = {
                a.arg
                for a in (
                    node.args.posonlyargs
                    + node.args.args
                    + node.args.kwonlyargs
                )
            }
            _scan_function(
                rel, node, [s for s in seeds if s in declared], findings
            )
    # dedupe by fingerprint (ast.walk visits nested ifs once per parent fn
    # plus once per nested fn scan)
    seen = set()
    out = []
    for f in findings:
        if f.fingerprint not in seen:
            seen.add(f.fingerprint)
            out.append(f)
    return out
