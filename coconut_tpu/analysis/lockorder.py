"""lock-order: static lock-acquisition-order graph from ``with`` nesting.

The fleet runs a dozen cooperating thread families (engine placer,
per-device executors, watchdog, authority workers, gossip, replicators,
WAL group-commit) whose only deadlock defense is a conventional
acquisition order. This checker makes that order explicit:

  pass 1  collect lock *definitions*: every ``threading.Lock() /
          RLock() / Condition()`` allocation bound to ``self.<attr>``
          (keyed by enclosing class) or to a module-level name;
  pass 2  walk every function's ``with`` statements and record an edge
          A -> B whenever lock B is acquired syntactically inside a
          ``with A:`` body (intra-function nesting only — deliberately
          conservative: cross-function edges need the runtime tracker,
          see analysis/lockcheck.py);
  pass 3  fail on any directed cycle among distinct locks (self-edges
          are ignored: re-entering the same RLock is legal here).

Lock identity resolution for ``with <expr>:``, in order: ``self.X``
resolves against the enclosing class's definitions; a bare module-level
name resolves within the module; otherwise ``obj.X`` resolves only when
exactly one class in the tree defines lock attribute ``X`` (ambiguous
attrs are skipped rather than guessed — false cycles are worse than
missed edges, and the runtime tracker covers real interleavings).
"""

import ast

from .core import Finding

CHECKER = "lock-order"

_LOCK_FACTORIES = {"Lock", "RLock", "Condition"}


def _is_lock_ctor(node):
    """True for ``threading.Lock()`` / ``Lock()`` / ``RLock()`` /
    ``Condition()`` call expressions (with or without args — Condition
    takes an optional lock)."""
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr in _LOCK_FACTORIES
    if isinstance(fn, ast.Name):
        return fn.id in _LOCK_FACTORIES
    return False


def _collect_defs(ctx, files):
    """Two maps:
    attr_owners: attr name -> set of "module.Class" that allocate a lock
                 into self.<attr>
    module_locks: (relpath, name) for module-level ``NAME = Lock()``"""
    attr_owners = {}
    module_locks = set()
    for rel in files:
        sf = ctx.file(rel)
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                cls_id = "%s.%s" % (rel, node.name)
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Assign) and _is_lock_ctor(sub.value):
                        for tgt in sub.targets:
                            if (
                                isinstance(tgt, ast.Attribute)
                                and isinstance(tgt.value, ast.Name)
                                and tgt.value.id == "self"
                            ):
                                attr_owners.setdefault(tgt.attr, set()).add(
                                    cls_id
                                )
        for node in sf.tree.body:
            if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        module_locks.add((rel, tgt.id))
    return attr_owners, module_locks


def _resolve(expr, rel, cls_name, attr_owners, module_locks):
    """Map a ``with`` context expression to a stable lock node id, or
    None when it isn't (resolvably) one of the tree's locks."""
    if isinstance(expr, ast.Name):
        if (rel, expr.id) in module_locks:
            return "%s::%s" % (rel, expr.id)
        return None
    if isinstance(expr, ast.Attribute):
        attr = expr.attr
        owners = attr_owners.get(attr)
        if not owners:
            return None
        if (
            isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and cls_name is not None
        ):
            cls_id = "%s.%s" % (rel, cls_name)
            if cls_id in owners:
                return "%s.%s" % (cls_id, attr)
            # self.<attr> in a class that doesn't define it (mixin /
            # injected): fall through to the unique-owner rule.
        if len(owners) == 1:
            return "%s.%s" % (next(iter(owners)), attr)
        return None  # ambiguous attr name — skip, don't guess
    return None


class _WithWalker(ast.NodeVisitor):
    """Per-function walk recording held-lock nesting edges."""

    def __init__(self, rel, attr_owners, module_locks, edges):
        self.rel = rel
        self.attr_owners = attr_owners
        self.module_locks = module_locks
        self.edges = edges  # (a, b) -> first evidence dict
        self.cls_stack = []
        self.fn_stack = []
        self.held = []

    def visit_ClassDef(self, node):
        self.cls_stack.append(node.name)
        self.generic_visit(node)
        self.cls_stack.pop()

    def _visit_fn(self, node):
        self.fn_stack.append(node.name)
        saved, self.held = self.held, []  # nesting doesn't cross def
        self.generic_visit(node)
        self.held = saved
        self.fn_stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def visit_With(self, node):
        cls = self.cls_stack[-1] if self.cls_stack else None
        acquired = []
        for item in node.items:
            lock = _resolve(
                item.context_expr,
                self.rel,
                cls,
                self.attr_owners,
                self.module_locks,
            )
            if lock is not None:
                for h in self.held:
                    if h != lock:
                        self.edges.setdefault(
                            (h, lock),
                            {
                                "path": self.rel,
                                "line": node.lineno,
                                "fn": ".".join(
                                    filter(None, [cls] + self.fn_stack[-1:])
                                ),
                            },
                        )
                acquired.append(lock)
                self.held.append(lock)
        for stmt in node.body:
            self.visit(stmt)
        for _ in acquired:
            self.held.pop()

    visit_AsyncWith = visit_With


def build_graph(ctx, files=None):
    """(edges, attr_owners, module_locks) — exposed for tests and for
    the README's "what does the static pass actually see" story."""
    if files is None:
        files = ctx.python_files()
    attr_owners, module_locks = _collect_defs(ctx, files)
    edges = {}
    for rel in files:
        sf = ctx.file(rel)
        if sf.tree is None:
            continue
        _WithWalker(rel, attr_owners, module_locks, edges).visit(sf.tree)
    return edges, attr_owners, module_locks


def _find_cycles(edges):
    """Tarjan SCC over the lock graph; every SCC with >1 node is an
    ordering cycle. Returns a list of node lists."""
    graph = {}
    for (a, b) in edges:
        graph.setdefault(a, []).append(b)
        graph.setdefault(b, [])
    index = {}
    low = {}
    on_stack = set()
    stack = []
    counter = [0]
    sccs = []

    def strongconnect(v):
        # iterative Tarjan: (node, iterator) frames
        work = [(v, iter(graph[v]))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(graph[w])))
                    advanced = True
                    break
                elif w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                if len(scc) > 1:
                    sccs.append(sorted(scc))

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)
    return sccs


def run(ctx, files=None):
    edges, _owners, _mods = build_graph(ctx, files)
    findings = []
    for scc in _find_cycles(edges):
        members = set(scc)
        evidence = sorted(
            "%s -> %s at %s:%d in %s"
            % (a, b, ev["path"], ev["line"], ev["fn"])
            for (a, b), ev in edges.items()
            if a in members and b in members
        )
        anchor = min(
            (
                (ev["path"], ev["line"])
                for (a, b), ev in edges.items()
                if a in members and b in members
            ),
            default=("coconut_tpu", 1),
        )
        findings.append(
            Finding(
                CHECKER,
                "cycle",
                anchor[0],
                anchor[1],
                "lock acquisition-order cycle among {%s}: %s"
                % ("; ".join(scc), "; ".join(evidence)),
                key="cycle:" + "|".join(scc),
            )
        )
    return findings
