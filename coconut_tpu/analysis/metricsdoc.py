"""metrics-doc: counter/timer/gauge names in code vs the documented glossary.

The metrics glossary lives in two places — the README's per-subsystem
"Metric glossary" paragraphs and the coconut_tpu/metrics.py module
docstring (which the README declares to be the full list). Operators
alert on these names; a counter that exists in code but not in the
glossary is invisible to them, and a glossary row whose counter was
renamed away is a dashboard that silently flatlines. Both directions are
drift, and both are checked:

  undocumented   a name emitted via metrics.count / set_gauge / timer /
                 observe that matches no glossary entry (flagged at the
                 first emission site);
  stale          a glossary entry that matches no emission (flagged at
                 the doc line). Only entries whose leading name segment
                 matches some emitted family (serve_, gateway_, wal_,
                 ...) are considered — prose code-words like
                 ``max_wait_ms`` never become findings.

Dynamic names are first-class: ``"serve_dev%d_load" % i`` and f-strings
become wildcard patterns (``serve_dev*_load``) that match the README's
placeholder spelling (``serve_dev<d>_load``); bare-variable name
arguments are resolved one level through local and ``self.<attr> = ...``
assignments before giving up (unresolvable sites are skipped, not
guessed).
"""

import ast
import re

from .core import Finding

CHECKER = "metrics-doc"

_EMIT_FNS = {
    "count": "counter",
    "set_gauge": "gauge",
    "timer": "timer",
    "observe": "histogram",
}
_METRICS_RECEIVERS = {"metrics", "_metrics"}

#: %-format conversions collapse to a wildcard
_PCT_RE = re.compile(r"%[-#+ 0-9.]*[sdifuxXoer]")

#: a glossary token: lowercase snake_case, optional <placeholder> / *;
#: single-word names (``retries``, ``fallbacks``) are real counters too
_TOKEN_RE = re.compile(
    r"^(?:[a-z*][a-z0-9<>*]*(?:_[a-z0-9<>*]+)+\*?|[a-z]{4,})$"
)

_PARA_KEYWORD_RE = re.compile(
    r"(?i)\b(counters?|gauges?|glossary|metrics?|timers?|histograms?)\b"
)
_BACKTICK_RE = re.compile(r"`([^`]+)`")
_DQUOTE_RE = re.compile(r'"([a-z][a-z0-9_<>*]{3,})"')


class Emission(object):
    def __init__(self, pattern, kind, path, line):
        self.pattern = pattern  # name with * wildcards, or None
        self.kind = kind
        self.path = path
        self.line = line


def _pattern_regex(p):
    return re.compile(
        "^" + ".*".join(re.escape(seg) for seg in p.split("*")) + "$"
    )


def _pattern_sample(p):
    # a representative concrete string: wildcard -> an unlikely literal
    return p.replace("*", "q7")


def patterns_match(a, b):
    """Glob-ish intersection test: serve_dev*_load matches
    serve_dev<d>_load (normalized) and serve_dev3_load, both ways."""
    ra, rb = _pattern_regex(a), _pattern_regex(b)
    return bool(ra.match(_pattern_sample(b)) or rb.match(_pattern_sample(a)))


def _normalize_doc_token(tok):
    return re.sub(r"<[^>]*>", "*", tok.strip())


# -- code-side extraction ---------------------------------------------------


_MAX_CANDIDATES = 8


def _str_patterns(node, local_assigns=None, attr_assigns=None, depth=0):
    """Resolve an expression to the SET of wildcard name patterns it can
    take (empty set = unresolvable). Multi-candidate on purpose: the
    same ``self.busy_timer`` attribute is assigned ``serve_dev%s_busy_s``
    by the verify pool and ``issue_auth%s_busy_s`` by the mint pool."""
    if depth > 3:
        return set()
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return {node.value}
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mod):
        return {
            _PCT_RE.sub("*", p)
            for p in _str_patterns(
                node.left, local_assigns, attr_assigns, depth + 1
            )
        }
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        l = _str_patterns(node.left, local_assigns, attr_assigns, depth + 1)
        r = _str_patterns(node.right, local_assigns, attr_assigns, depth + 1)
        out = {
            a + b
            for a in (l or {"*"})
            for b in (r or {"*"})
        }
        return set(sorted(out)[:_MAX_CANDIDATES])
    if isinstance(node, ast.IfExp):
        return _str_patterns(
            node.body, local_assigns, attr_assigns, depth + 1
        ) | _str_patterns(node.orelse, local_assigns, attr_assigns, depth + 1)
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            else:
                parts.append("*")
        return {"".join(parts)}
    if isinstance(node, ast.Name) and local_assigns is not None:
        pats = local_assigns.get(node.id) or set()
        return set(sorted(pats)[:_MAX_CANDIDATES])
    if isinstance(node, ast.Attribute) and attr_assigns is not None:
        pats = attr_assigns.get(node.attr) or set()
        return set(sorted(pats)[:_MAX_CANDIDATES])
    return set()


def _useful(patterns):
    """Drop all-wildcard patterns: an unresolvable concat must not claim
    to match every glossary row."""
    return {p for p in patterns if p.strip("*")}


def collect_emissions(ctx, files=None):
    """(emissions, unresolved) across the package.

    Besides direct ``metrics.<fn>(name, ...)`` calls, two pass-through
    idioms count as emissions: keyword arguments named ``counter=`` /
    ``gauge=`` (the engine/serve failure paths build the outcome counter
    at the call site and a helper does the count), and the string
    DEFAULT of a parameter named ``counter`` (fail_all's
    ``counter="serve_failed_requests"``)."""
    if files is None:
        files = ctx.python_files()
    # pass 1: every ``self.X = <string-ish>`` and module/local
    # ``X = <string-ish>`` feeds the resolver
    attr_assigns = {}
    per_file_locals = {}
    for rel in files:
        sf = ctx.file(rel)
        if sf.tree is None:
            continue
        local = per_file_locals.setdefault(rel, {})
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Assign):
                pats = _useful(_str_patterns(node.value))
                if not pats:
                    continue
                for tgt in node.targets:
                    if isinstance(tgt, ast.Attribute):
                        attr_assigns.setdefault(tgt.attr, set()).update(pats)
                    elif isinstance(tgt, ast.Name):
                        local.setdefault(tgt.id, set()).update(pats)
    emissions, unresolved = [], []

    def emit(arg_node, kind, rel, line, local):
        pats = _useful(_str_patterns(arg_node, local, attr_assigns))
        if not pats:
            unresolved.append(Emission(None, kind, rel, line))
        for pat in sorted(pats):
            emissions.append(Emission(pat, kind, rel, line))

    for rel in files:
        sf = ctx.file(rel)
        if sf.tree is None:
            continue
        local = per_file_locals.get(rel, {})
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # string default of a parameter named counter/gauge
                pos = node.args.args
                pairs = list(
                    zip(pos[len(pos) - len(node.args.defaults):],
                        node.args.defaults)
                ) + list(zip(node.args.kwonlyargs, node.args.kw_defaults))
                for a, d in pairs:
                    if d is not None and a.arg in ("counter", "gauge"):
                        emit(
                            d,
                            "counter" if a.arg == "counter" else "gauge",
                            rel,
                            node.lineno,
                            local,
                        )
                continue
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if (
                isinstance(fn, ast.Attribute)
                and fn.attr in _EMIT_FNS
                and isinstance(fn.value, ast.Name)
                and fn.value.id in _METRICS_RECEIVERS
                and node.args
            ):
                emit(node.args[0], _EMIT_FNS[fn.attr], rel, node.lineno, local)
                continue
            for kw in node.keywords:
                if kw.arg in ("counter", "gauge"):
                    emit(
                        kw.value,
                        "counter" if kw.arg == "counter" else "gauge",
                        rel,
                        node.lineno,
                        local,
                    )
    return emissions, unresolved


# -- doc-side extraction ----------------------------------------------------


def collect_doc_entries(ctx):
    """[(normalized_pattern, raw_token, path, line)] from the README
    glossary paragraphs and the metrics.py module docstring."""
    entries = []
    if ctx.exists("README.md"):
        sf = ctx.file("README.md")
        para_lines = []  # (line_no, text) of current paragraph
        paras = []
        for i, line in enumerate(sf.lines, start=1):
            if line.strip():
                para_lines.append((i, line))
            elif para_lines:
                paras.append(para_lines)
                para_lines = []
        if para_lines:
            paras.append(para_lines)
        for para in paras:
            text = "\n".join(t for _, t in para)
            if not _PARA_KEYWORD_RE.search(text):
                continue
            for line_no, line in para:
                if line.lstrip().startswith("|"):
                    continue  # markdown table rows name programs/knobs,
                    # not glossary entries
                for m in _BACKTICK_RE.finditer(line):
                    tok = m.group(1).strip()
                    if "(" in tok or "." in tok or " " in tok:
                        continue
                    norm = _normalize_doc_token(tok)
                    if _TOKEN_RE.match(norm):
                        entries.append((norm, tok, "README.md", line_no))
    rel = "coconut_tpu/metrics.py"
    if ctx.exists(rel):
        sf = ctx.file(rel)
        if (
            sf.tree is not None
            and sf.tree.body
            and isinstance(sf.tree.body[0], ast.Expr)
            and isinstance(sf.tree.body[0].value, ast.Constant)
        ):
            end_line = sf.tree.body[0].end_lineno
            for i, line in enumerate(sf.lines[:end_line], start=1):
                for m in _DQUOTE_RE.finditer(line):
                    norm = _normalize_doc_token(m.group(1))
                    if _TOKEN_RE.match(norm):
                        entries.append((norm, m.group(1), rel, i))
    return entries


# -- the checker ------------------------------------------------------------


def run(ctx, files=None):
    emissions, unresolved = collect_emissions(ctx, files)
    entries = collect_doc_entries(ctx)
    findings = []

    doc_patterns = [e[0] for e in entries]
    # undocumented: first emission site per distinct pattern
    seen = set()
    for em in emissions:
        if em.pattern in seen:
            continue
        seen.add(em.pattern)
        if not any(patterns_match(em.pattern, d) for d in doc_patterns):
            findings.append(
                Finding(
                    CHECKER,
                    "undocumented",
                    em.path,
                    em.line,
                    "%s %r is emitted but appears in neither the README "
                    "metric glossary nor the metrics.py docstring"
                    % (em.kind, em.pattern),
                    key="undocumented:%s:%s" % (em.kind, em.pattern),
                )
            )

    # stale: glossary rows naming a family we emit, matching nothing
    families = {
        em.pattern.split("_", 1)[0]
        for em in emissions
        if not em.pattern.startswith("*")
    }
    flagged = set()
    for norm, raw, path, line in entries:
        fam = norm.split("_", 1)[0]
        if fam not in families or norm in flagged:
            continue
        # a doc token that is a literal PREFIX of an emitted name is a
        # counters_with_prefix() family reference, not a stale row
        if any(em.pattern.startswith(norm) for em in emissions):
            continue
        if not any(patterns_match(norm, em.pattern) for em in emissions):
            flagged.add(norm)
            findings.append(
                Finding(
                    CHECKER,
                    "stale",
                    path,
                    line,
                    "glossary entry %r matches no metric emitted anywhere "
                    "in coconut_tpu (renamed or removed?)" % raw,
                    key="stale:%s" % norm,
                )
            )
    return findings
