"""wire-contract: errors.py <-> WIRE_ERROR_CODES <-> error_from_wire.

The fleet gateway collapses every server-side exception into a wire
error envelope carrying ``getattr(exc, "code", "general")`` (net/wire.py
encode_error), and the client rebuilds the typed exception with
errors.error_from_wire. That round trip is only lossless when three
things hold, each a rule here:

  missing-code     every CoconutError subclass *raised on an
                   RPC-reachable path* is itself a value in
                   WIRE_ERROR_CODES. A class that only inherits the base
                   ``code = "error"`` (or a parent's code) crosses the
                   wire as a GeneralError / parent-class impostor — the
                   client's isinstance dispatch silently stops matching.
  round-trip       error_from_wire(code, msg) yields an instance of the
                   mapped class, with the same code and message, and
                   attribute reads on the reconstructed instance don't
                   explode (the ``__new__``-based rebuild skips subclass
                   ``__init__``, so structured fields need class-level
                   defaults — the DoubleSpendError pattern).
  retry-after      every ServiceRetryableError reconstruction carries a
                   finite ``retry_after_s`` >= 0 even when the envelope
                   held NaN/inf/negative junk, and duplicate codes never
                   silently collapse two classes into one map slot.

The raised-class scan is AST (no imports of the serving stack); the
round-trip rules import coconut_tpu.errors only, which is stdlib-light.
RPC-reachable scope: everything under coconut_tpu/ except the offline
checkpoint path (stream.py), the client-side scenario drivers
(scenarios/), and the loadgen client (serve/loadgen.py) — exceptions
raised there never enter a wire envelope.
"""

import ast
import math

from .core import Finding

CHECKER = "wire-contract"

#: modules whose raises never reach wire.encode_error (client-side or
#: offline paths); relpath prefixes
NON_RPC_PREFIXES = (
    "coconut_tpu/stream.py",
    "coconut_tpu/scenarios/",
    "coconut_tpu/serve/loadgen.py",
)

#: junk retry hints an envelope (or a buggy peer) could carry; every one
#: must normalize to a finite float >= 0
_JUNK_RETRY_HINTS = (float("nan"), float("inf"), float("-inf"), -5.0, None)


def _errors_module():
    from coconut_tpu import errors

    return errors


def _coconut_classes(errors):
    """name -> class for every CoconutError subclass defined in errors.py."""
    out = {}
    for name in dir(errors):
        obj = getattr(errors, name)
        if (
            isinstance(obj, type)
            and issubclass(obj, errors.CoconutError)
            and obj.__module__ == errors.__name__
        ):
            out[name] = obj
    return out


def _raised_class_names(tree):
    """(name, lineno) for every ``raise Name(...)`` / ``raise Mod.Name(...)``
    statement; re-raises of caught variables (``raise`` / ``raise e``)
    are skipped — they don't introduce a class."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Raise) or node.exc is None:
            continue
        exc = node.exc
        fn = exc.func if isinstance(exc, ast.Call) else exc
        if isinstance(fn, ast.Attribute):
            yield fn.attr, node.lineno
        elif isinstance(fn, ast.Name):
            # bare ``raise name`` is usually a caught-variable re-raise,
            # not a class; only Call forms count for bare Names unless
            # the name is Capitalized like a class
            if isinstance(exc, ast.Call) or fn.id[:1].isupper():
                yield fn.id, node.lineno


def check_raised_classes(ctx, files=None):
    """The missing-code rule: AST scan of RPC-reachable raises."""
    errors = _errors_module()
    classes = _coconut_classes(errors)
    wired = set(errors.WIRE_ERROR_CODES.values())
    if files is None:
        files = ctx.python_files()
    findings = []
    seen = set()
    for rel in files:
        if rel.startswith(NON_RPC_PREFIXES):
            continue
        sf = ctx.file(rel)
        if sf.tree is None:
            continue
        for name, lineno in _raised_class_names(sf.tree):
            cls = classes.get(name)
            if cls is None or cls in wired:
                continue
            if cls is errors.CoconutError:
                continue  # raising the bare base is its own smell, but
                # it at least round-trips as its declared code
            key = (rel, name)
            if key in seen:
                continue
            seen.add(key)
            findings.append(
                Finding(
                    CHECKER,
                    "missing-code",
                    rel,
                    lineno,
                    "%s raised on an RPC-reachable path but absent from "
                    "WIRE_ERROR_CODES: it crosses the wire as code %r and "
                    "decodes as %s, so client isinstance dispatch breaks"
                    % (
                        name,
                        cls.code,
                        errors.WIRE_ERROR_CODES.get(
                            cls.code, errors.GeneralError
                        ).__name__,
                    ),
                    key="missing-code:%s" % name,
                )
            )
    return findings


def check_round_trip(ctx):
    """round-trip + retry-after + duplicate-code rules (executable
    checks against the live errors module)."""
    errors = _errors_module()
    rel = "coconut_tpu/errors.py"
    findings = []

    # duplicate-code: two classes declaring the same code in __dict__
    # would silently collapse into one WIRE_ERROR_CODES slot
    by_code = {}
    for name, cls in _coconut_classes(errors).items():
        code = cls.__dict__.get("code")
        if code is not None:
            by_code.setdefault(code, []).append(name)
    for code, names in sorted(by_code.items()):
        if len(names) > 1:
            findings.append(
                Finding(
                    CHECKER,
                    "duplicate-code",
                    rel,
                    1,
                    "wire code %r is declared by multiple classes: %s"
                    % (code, ", ".join(sorted(names))),
                    key="duplicate-code:%s" % code,
                )
            )

    msg = "analysis round-trip probe"
    for code, cls in sorted(
        errors.WIRE_ERROR_CODES.items(), key=lambda kv: kv[0]
    ):
        try:
            err = errors.error_from_wire(
                code, msg, program="verify", retry_after_s=1.5
            )
        except Exception as exc:  # noqa: BLE001 - the rule IS "never raises"
            findings.append(
                Finding(
                    CHECKER,
                    "round-trip",
                    rel,
                    1,
                    "error_from_wire(%r) raised %s: %s"
                    % (code, type(exc).__name__, exc),
                    key="round-trip-raise:%s" % code,
                )
            )
            continue
        problems = []
        if not isinstance(err, cls):
            problems.append(
                "decoded as %s, expected %s"
                % (type(err).__name__, cls.__name__)
            )
        if getattr(err, "code", None) != code:
            problems.append(
                "instance code %r != envelope code %r"
                % (getattr(err, "code", None), code)
            )
        if str(err) != msg:
            problems.append("message not preserved (%r)" % str(err))
        try:
            repr(err)
        except Exception as exc:  # noqa: BLE001
            problems.append(
                "repr() raised %s (missing class-level attribute "
                "defaults for __new__-based rebuild?)" % type(exc).__name__
            )
        if problems:
            findings.append(
                Finding(
                    CHECKER,
                    "round-trip",
                    rel,
                    1,
                    "code %r: %s" % (code, "; ".join(problems)),
                    key="round-trip:%s" % code,
                )
            )

        if issubclass(cls, errors.ServiceRetryableError):
            for junk in _JUNK_RETRY_HINTS:
                e2 = errors.error_from_wire(
                    code, msg, program=None, retry_after_s=junk
                )
                ra = getattr(e2, "retry_after_s", None)
                ok = (
                    isinstance(ra, float)
                    and math.isfinite(ra)
                    and ra >= 0.0
                )
                if not ok:
                    findings.append(
                        Finding(
                            CHECKER,
                            "retry-after",
                            rel,
                            1,
                            "code %r with retry_after_s=%r reconstructs "
                            "retry_after_s=%r (must be finite float >= 0)"
                            % (code, junk, ra),
                            key="retry-after:%s:%r" % (code, junk),
                        )
                    )
                    break
    return findings


def run(ctx, files=None):
    return check_raised_classes(ctx, files) + check_round_trip(ctx)
