"""CLI for the invariant lint suite.

    python -m coconut_tpu.analysis                 # human report, exit 1 on NEW findings
    python -m coconut_tpu.analysis --json          # machine report (all findings + verdict)
    python -m coconut_tpu.analysis --fail-on-new   # explicit CI-gate spelling (default behavior)
    python -m coconut_tpu.analysis --write-baseline  # absorb current findings into the baseline
    python -m coconut_tpu.analysis --checkers lock-order,durability
    python -m coconut_tpu.analysis --root /path/to/tree --baseline my_baseline.json
"""

import argparse
import json
import os
import sys

from . import DEFAULT_BASELINE, run_all
from .core import CHECKER_NAMES, write_baseline


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m coconut_tpu.analysis",
        description="coconut_tpu invariant lint suite "
        "(%s)" % ", ".join(CHECKER_NAMES),
    )
    ap.add_argument(
        "--root",
        default=None,
        help="tree to scan (default: the repo containing this package)",
    )
    ap.add_argument(
        "--baseline",
        default=None,
        help="suppression baseline JSON (default: <root>/%s)"
        % DEFAULT_BASELINE,
    )
    ap.add_argument(
        "--checkers",
        default=None,
        help="comma-separated subset of: %s" % ", ".join(CHECKER_NAMES),
    )
    ap.add_argument("--json", action="store_true", help="JSON report")
    ap.add_argument(
        "--fail-on-new",
        action="store_true",
        help="exit 1 on findings not covered by a pragma or the baseline "
        "(this is also the default; the flag is the explicit CI spelling)",
    )
    ap.add_argument(
        "--write-baseline",
        action="store_true",
        help="write every new finding into the baseline (then exit 0)",
    )
    args = ap.parse_args(argv)

    root = args.root or os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    baseline_path = args.baseline or os.path.join(root, DEFAULT_BASELINE)
    checkers = args.checkers.split(",") if args.checkers else None

    findings, new = run_all(root, checkers, baseline_path)

    if args.write_baseline:
        doc = write_baseline(baseline_path, findings)
        print(
            "wrote %d suppressions to %s"
            % (len(doc["suppressions"]), baseline_path)
        )
        return 0

    if args.json:
        print(
            json.dumps(
                {
                    "root": root,
                    "checkers": checkers or list(CHECKER_NAMES),
                    "findings": [f.to_dict() for f in findings],
                    "new": len(new),
                    "suppressed": len(findings) - len(new),
                    "ok": not new,
                },
                indent=2,
                sort_keys=True,
            )
        )
    else:
        for f in findings:
            tag = (
                ""
                if f.suppressed_by is None
                else " [suppressed: %s]" % f.suppressed_by
            )
            print("%r%s" % (f, tag))
        print(
            "analysis: %d finding(s), %d suppressed, %d NEW"
            % (len(findings), len(findings) - len(new), len(new))
        )
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
