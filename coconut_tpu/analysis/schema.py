"""Structured dead-letter JSONL schema validation (replaces ci.sh greps).

ci.sh used to assert the dead-letter contract with a chain of
``grep -q '"schema": 4'``-style probes — which pass on a file whose keys
carry the wrong types, miss records entirely, or hold torn garbage after
the matched line. This module IS the contract, executable:

    python -m coconut_tpu.analysis.schema <dead.jsonl> \
        --expect batch=1 --expect credential=2

validates that every line parses, carries exactly the schema-v4 key set
with the right types (null-ability per faults.DeadLetterLog.read's
normalization contract), and that at least one record matches each
``--expect field=value`` probe. Exit status is the gate.

It is also importable (validate_record / validate_file) — the faults
tests and the analysis fixture suite use it directly.
"""

import json
import sys

DEAD_LETTER_SCHEMA = 4

#: field -> (types allowed, nullable)
_FIELDS = {
    "schema": ((int,), False),
    "batch": ((int,), False),
    "credential": ((int,), False),
    "reason": ((str,), False),
    "attempts": ((list,), False),
    "trace_id": ((str,), True),
    "span_id": ((str,), True),
    "program": ((str,), True),
    "nullifier": ((str,), True),
}


def validate_record(rec, lineno=None):
    """List of problem strings for one decoded record (empty = valid)."""
    where = "" if lineno is None else "line %d: " % lineno
    problems = []
    if not isinstance(rec, dict):
        return ["%srecord is %s, not an object" % (where, type(rec).__name__)]
    for field, (types, nullable) in _FIELDS.items():
        if field not in rec:
            problems.append("%smissing key %r" % (where, field))
            continue
        val = rec[field]
        if val is None:
            if not nullable:
                problems.append("%skey %r must not be null" % (where, field))
            continue
        if isinstance(val, bool) or not isinstance(val, types):
            problems.append(
                "%skey %r has type %s, expected %s"
                % (
                    where,
                    field,
                    type(val).__name__,
                    "/".join(t.__name__ for t in types),
                )
            )
    for extra in sorted(set(rec) - set(_FIELDS)):
        problems.append("%sunexpected key %r" % (where, extra))
    if not problems and rec["schema"] != DEAD_LETTER_SCHEMA:
        problems.append(
            "%sschema %r != %d" % (where, rec["schema"], DEAD_LETTER_SCHEMA)
        )
    if not problems and (rec["batch"] < 0 or rec["credential"] < 0):
        problems.append("%snegative batch/credential index" % where)
    return problems


def validate_file(path, expectations=()):
    """(records, problems): parse + validate every line, then check each
    (field, value) expectation matches at least one record."""
    problems = []
    records = []
    with open(path, "r", encoding="utf-8") as f:
        for lineno, line in enumerate(f, start=1):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                problems.append("line %d: unparseable JSON" % lineno)
                continue
            problems.extend(validate_record(rec, lineno))
            records.append(rec)
    if not records:
        problems.append("no records in %s" % path)
    for field, value in expectations:
        if not any(r.get(field) == value for r in records):
            problems.append(
                "no record with %s == %r among %d records"
                % (field, value, len(records))
            )
    return records, problems


def _parse_expect(raw):
    field, _, val = raw.partition("=")
    if not field or not _:
        raise SystemExit("--expect wants field=value, got %r" % raw)
    try:
        value = int(val)
    except ValueError:
        value = val
    return field, value


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    expectations = []
    paths = []
    i = 0
    while i < len(argv):
        if argv[i] == "--expect":
            expectations.append(_parse_expect(argv[i + 1]))
            i += 2
        elif argv[i].startswith("--expect="):
            expectations.append(_parse_expect(argv[i].split("=", 1)[1]))
            i += 1
        else:
            paths.append(argv[i])
            i += 1
    if not paths:
        raise SystemExit("usage: analysis.schema <dead.jsonl> [--expect f=v]")
    rc = 0
    for path in paths:
        records, problems = validate_file(path, expectations)
        if problems:
            rc = 1
            for p in problems:
                print("%s: %s" % (path, p))
        else:
            print(
                "%s: %d dead-letter records, schema v%d ok"
                % (path, len(records), DEAD_LETTER_SCHEMA)
            )
    return rc


if __name__ == "__main__":
    sys.exit(main())
