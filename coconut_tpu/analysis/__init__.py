"""coconut_tpu.analysis — the invariant lint suite.

Five project-specific checkers over the tree (see each module's
docstring for the contract it encodes):

  lock-order     static ``with``-nesting lock graph, fail on cycles
                 (runtime twin: analysis/lockcheck.py LockOrderTracker)
  wire-contract  errors raised on RPC paths have stable wire codes and
                 round-trip through error_from_wire with finite
                 retry_after_s
  const-time     CONSTTIME.md as taint rules: no Python-level branch /
                 int()/bool() cast on secret scalars in tpu/ +
                 signature.py + sss.py
  durability     no bare write-mode open() outside state/atomic.py and
                 the WAL
  metrics-doc    emitted counter/timer/gauge names <-> the documented
                 glossary, both directions

Run: ``python -m coconut_tpu.analysis [--fail-on-new]``. Suppress a
finding inline with ``# lint: allow(<checker>, <why>)`` on (or directly
above) the flagged line, or baseline it in analysis_baseline.json with a
justification. ci.sh's analysis lane gates on --fail-on-new.
"""

from .core import (  # noqa: F401
    CHECKER_NAMES,
    Context,
    DEFAULT_BASELINE,
    Finding,
    apply_suppressions,
    load_baseline,
    write_baseline,
)


def get_checkers(names=None):
    """name -> run(ctx, files=None) for the requested checker names."""
    from . import consttime, durability, lockorder, metricsdoc, wirecontract

    table = {
        "lock-order": lockorder.run,
        "wire-contract": wirecontract.run,
        "const-time": consttime.run,
        "durability": durability.run,
        "metrics-doc": metricsdoc.run,
    }
    if names:
        unknown = set(names) - set(table)
        if unknown:
            raise KeyError(
                "unknown checkers: %s (have: %s)"
                % (", ".join(sorted(unknown)), ", ".join(sorted(table)))
            )
        return {n: table[n] for n in names}
    return table


def run_all(root, checkers=None, baseline_path=None):
    """Run the suite over the tree at ``root``.

    Returns (findings, new) where ``new`` is the subset that is neither
    pragma-suppressed nor baselined — the CI gate fails iff it is
    non-empty."""
    ctx = Context(root)
    findings = []
    for name, run in get_checkers(checkers).items():
        found = run(ctx)
        found.sort(key=lambda f: (f.path, f.line, f.rule, f.key))
        findings.extend(found)
    baseline = load_baseline(baseline_path)
    new = apply_suppressions(findings, ctx, baseline)
    return findings, new
