"""durability: every durable write goes through atomic.replace_* or the WAL.

state/atomic.py is the single blessed crash-atomic write path
(tmp + fsync + os.replace + directory fsync) and state/wal.py owns its
own append handles with CRC framing + torn-tail recovery. Any OTHER
``open(..., "w"/"a"/"x"/"+")`` or ``Path.write_text/write_bytes`` in the
package is a potential torn file: a crash mid-write leaves a partial
manifest/checkpoint/journal that a reader later chokes on.

Rules:
  bare-write    write-mode ``open()`` / ``write_text`` / ``write_bytes``
                outside the blessed modules. Sites that are genuinely
                fine (best-effort observability artifacts, append-only
                JSONL whose reader tolerates a torn tail) carry a
                ``# lint: allow(durability, <why>)`` pragma — the
                justification lives next to the write.

Read-mode opens and opens of non-file objects (sockets, BytesIO) are
not flagged; mode strings that can't be resolved statically (variables)
are flagged conservatively — a pragma or refactor to a literal mode
settles them.
"""

import ast

from .core import Finding

CHECKER = "durability"

#: modules that ARE the blessed durable-write implementations
ALLOWED_MODULES = (
    "coconut_tpu/state/atomic.py",
    "coconut_tpu/state/wal.py",
)

_WRITE_MODE_CHARS = set("wax+")


def _mode_writes(call):
    """True / False / None(=unresolvable) for whether this open() call's
    mode writes."""
    mode = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if mode is None:
        return False  # default "r"
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return bool(_WRITE_MODE_CHARS & set(mode.value))
    return None


def run(ctx, files=None):
    if files is None:
        files = ctx.python_files()
    findings = []
    for rel in files:
        if rel in ALLOWED_MODULES:
            continue
        sf = ctx.file(rel)
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if isinstance(fn, ast.Name) and fn.id == "open":
                writes = _mode_writes(node)
                if writes is False:
                    continue
                mode_desc = (
                    "unresolvable mode" if writes is None else "write mode"
                )
                # describe the target expression for a stable key
                tgt = (
                    ast.unparse(node.args[0]) if node.args else "<unknown>"
                )
                findings.append(
                    Finding(
                        CHECKER,
                        "bare-write",
                        rel,
                        node.lineno,
                        "bare open(%s, %s) bypasses state/atomic.py "
                        "replace_* and the WAL: a crash mid-write leaves "
                        "a torn file" % (tgt, mode_desc),
                        key="bare-write:open:%s" % tgt,
                    )
                )
            elif isinstance(fn, ast.Attribute) and fn.attr in (
                "write_text",
                "write_bytes",
            ):
                tgt = ast.unparse(fn.value)
                findings.append(
                    Finding(
                        CHECKER,
                        "bare-write",
                        rel,
                        node.lineno,
                        "bare %s.%s() bypasses state/atomic.py replace_* "
                        "and the WAL: a crash mid-write leaves a torn "
                        "file" % (tgt, fn.attr),
                        key="bare-write:%s:%s" % (fn.attr, tgt),
                    )
                )
    return findings
