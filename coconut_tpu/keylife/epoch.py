"""Key epochs: versioned key material with a bounded live window and a
two-phase activate/retire handoff (PR 15).

A KeySet is ONE consistent set of threshold key shares — every partial
signature aggregated into one credential must come from the SAME KeySet,
because Lagrange interpolation only reconstructs a signature under one
sharing. Two coordinates version it:

  epoch   the public identity: credentials carry their mint epoch, and
          verify resolves the aggregated verkey BY epoch. A reshare
          (new t/n, fresh DKG, new verkey) bumps the epoch.
  gen     the private revision within an epoch: a proactive refresh
          (Herzberg zero-sharing) replaces every share while leaving the
          verkey bit-identical, so the epoch — the only coordinate
          clients can observe — stays put and gen increments.

The EpochRegistry is the rollover state machine:

  PENDING   registered (keys installed on authorities) but not yet
            serving — the prepare half of the two-phase handoff
  ACTIVE    the epoch new mints pin; exactly one at a time
  RETIRING  superseded by a newer activation, but in-flight fan-outs
            pinned to it are still completing and credentials minted
            under it still VERIFY — the drain half of the handoff
  RETIRED   pushed out of the bounded window of `window` live epochs:
            its key material is dropped and its verkey no longer
            served; verify refuses with the typed EpochRetiredError

`pin_active()`/`unpin()` implement the handoff: a mint fan-out pins the
active KeySet when it opens and unpins when it closes, so activation of
epoch e+1 never yanks key material out from under a fan-out minting
under e. Retirement is driven by WINDOW PRESSURE, not by pin drain — a
superseded epoch keeps verifying until `window` newer epochs crowd it
out (so every pre-rollover credential verifies post-rollover), and even
then a pinned epoch defers retirement until its last fan-out closes.
Unknown (never-registered, or not-yet-activated PENDING) epochs refuse
with EpochUnknownError; both errors carry the live epoch set and travel
the CTS-RPC error envelope (stable wire codes in errors.py).

Metrics: "keylife_active_epoch" / "keylife_live_epochs" gauges;
"keylife_activations" / "keylife_retirements" / "keylife_epoch_unknown"
/ "keylife_epoch_retired" counters.
"""

import threading

from .. import metrics
from ..errors import EpochRetiredError, EpochUnknownError, GeneralError

PENDING = "pending"
ACTIVE = "active"
RETIRING = "retiring"
RETIRED = "retired"

#: wire codes for the beacon's per-epoch state byte (net/wire.py)
EPOCH_STATE_CODES = {PENDING: 0, ACTIVE: 1, RETIRING: 2, RETIRED: 3}
EPOCH_STATE_OF_CODE = {c: s for s, c in EPOCH_STATE_CODES.items()}


class KeySet:
    """One consistent share set: `signers` (keygen.Signer list — each
    authority takes its own entry's sigkey), the aggregated verkey `vk`
    every credential minted from this set verifies under, and the
    (epoch, gen) coordinates above. `qual`/`excluded` record the DKG
    round's dealer audit (who contributed, who was named)."""

    __slots__ = (
        "epoch", "gen", "threshold", "signers", "vk", "qual", "excluded",
    )

    def __init__(self, epoch, gen, threshold, signers, vk,
                 qual=(), excluded=()):
        self.epoch = epoch
        self.gen = gen
        self.threshold = threshold
        self.signers = list(signers)
        self.vk = vk
        self.qual = tuple(sorted(qual))
        self.excluded = tuple(sorted(excluded))

    @property
    def key(self):
        """The identity a fan-out pins and an authority keys its share
        store by: one (epoch, gen) pair = one consistent share set."""
        return (self.epoch, self.gen)

    @property
    def total(self):
        return len(self.signers)

    def verkeys_by_id(self):
        return {s.id: s.verkey for s in self.signers}

    def signer(self, signer_id):
        for s in self.signers:
            if s.id == signer_id:
                return s
        return None

    def __repr__(self):
        return "KeySet(epoch=%d, gen=%d, t=%d, n=%d)" % (
            self.epoch, self.gen, self.threshold, len(self.signers),
        )


class _Entry:
    __slots__ = ("keyset", "state", "pins")

    def __init__(self, keyset):
        self.keyset = keyset
        self.state = PENDING
        #: (epoch, gen) -> open-fan-out count; old gens linger here until
        #: their in-flight mints drain, keeping refresh non-disruptive
        self.pins = {}

    def total_pins(self):
        return sum(self.pins.values())


class EpochRegistry:
    """The epoch state machine plus the verify path's epoch -> verkey
    resolver. Thread-safe: mint fan-outs pin/unpin from authority
    threads while the lifecycle manager activates from its own."""

    def __init__(self, window=3, store=None):
        if window < 1:
            raise ValueError("window must be >= 1 (got %r)" % (window,))
        self.window = window
        self._lock = threading.Lock()
        self._entries = {}  # epoch id -> _Entry
        self._active = None  # epoch id
        self._max_registered = 0
        self._retired = set()  # epoch ids retired out of the window
        #: state.StateStore (PR 17): the registry journals its state-
        #: machine transitions into the "epoch" keyspace. Key material
        #: is deliberately NOT journaled (shares cannot round-trip
        #: through a replicated log); what survives a restart is the
        #: METADATA — which epoch ids exist and which are retired — so
        #: a restarted replica keeps refusing retired-epoch credentials
        #: and never re-issues an already-used epoch id, even before
        #: its keysets are re-installed by the lifecycle manager.
        self._store = store
        #: callbacks fired AFTER the registry lock is released, once per
        #: retired epoch id — the nullifier store hangs its keyspace
        #: compaction here (state/nullifier.py retire_epoch). Fired
        #: outside the lock because hooks may fsync/compact a WAL.
        self._retire_hooks = []
        if store is not None:
            for key in store.keys("epoch"):
                epoch = int(key)
                self._max_registered = max(self._max_registered, epoch)
                rec = store.get("epoch", key)
                if rec and rec.get("event") == "retired":
                    self._retired.add(epoch)
        metrics.set_gauge("keylife_active_epoch", 0)
        metrics.set_gauge("keylife_live_epochs", 0)

    def add_retire_hook(self, fn):
        """Register fn(epoch_id), called after each retirement commits
        (lock released). Errors are swallowed — a hook failure must not
        wedge the epoch window."""
        with self._lock:
            self._retire_hooks.append(fn)

    def _fire_retire_hooks(self, victims):
        for epoch in victims:
            for fn in list(self._retire_hooks):
                try:
                    fn(epoch)
                except Exception:  # pragma: no cover - defensive
                    metrics.count("keylife_retire_hook_errors")

    def _journal_locked(self, epoch, event):
        if self._store is not None:
            self._store.put(
                "epoch", str(epoch), {"event": event}, epoch=epoch
            )

    # -- registration / activation (lifecycle-manager side) ------------------

    def next_epoch(self):
        with self._lock:
            return self._max_registered + 1

    def register(self, keyset):
        """Phase one of the handoff: the epoch exists (keys are installed
        on the authorities) but nothing serves under it yet."""
        with self._lock:
            if keyset.epoch <= self._max_registered:
                raise GeneralError(
                    "epoch ids are monotonic: %d already registered "
                    "(max %d)" % (keyset.epoch, self._max_registered)
                )
            self._entries[keyset.epoch] = _Entry(keyset)
            self._max_registered = keyset.epoch
            self._journal_locked(keyset.epoch, "registered")
            self._publish_locked()

    def activate(self, epoch):
        """Phase two: new mints pin `epoch`; the previously active epoch
        moves to RETIRING (still verifying), and the oldest retiring
        epochs retire once `window` live epochs crowd them out."""
        with self._lock:
            entry = self._entries.get(epoch)
            if entry is None:
                raise GeneralError("cannot activate unknown epoch %d" % epoch)
            if entry.state != PENDING:
                raise GeneralError(
                    "epoch %d is %s, not pending" % (epoch, entry.state)
                )
            if self._active is not None:
                self._entries[self._active].state = RETIRING
            entry.state = ACTIVE
            self._active = epoch
            metrics.count("keylife_activations")
            self._journal_locked(epoch, "active")
            victims = self._enforce_window_locked()
            self._publish_locked()
        self._fire_retire_hooks(victims)

    def install_gen(self, keyset):
        """Proactive refresh landed: swap epoch `keyset.epoch`'s current
        share set for the next gen. The verkey MUST be unchanged (the
        manager asserts bit-identity before calling); fan-outs pinned to
        the old gen keep minting from it until they drain."""
        with self._lock:
            entry = self._entries.get(keyset.epoch)
            if entry is None:
                raise GeneralError(
                    "cannot refresh unknown epoch %d" % keyset.epoch
                )
            if keyset.gen != entry.keyset.gen + 1:
                raise GeneralError(
                    "refresh gen %d does not follow current gen %d"
                    % (keyset.gen, entry.keyset.gen)
                )
            entry.keyset = keyset

    # -- pinning (mint side) -------------------------------------------------

    def pin_active(self):
        """The active KeySet, pinned: the caller's fan-out mints under it
        even if a refresh or reshare lands mid-flight. Pair with
        unpin()."""
        with self._lock:
            if self._active is None:
                raise GeneralError("no active key epoch")
            entry = self._entries[self._active]
            ks = entry.keyset
            entry.pins[ks.key] = entry.pins.get(ks.key, 0) + 1
            return ks

    def unpin(self, keyset):
        """A fan-out pinned to `keyset` closed; a crowded-out RETIRING
        epoch whose pins just drained retires now."""
        with self._lock:
            entry = self._entries.get(keyset.epoch)
            if entry is None:
                return
            n = entry.pins.get(keyset.key, 0) - 1
            if n > 0:
                entry.pins[keyset.key] = n
            else:
                entry.pins.pop(keyset.key, None)
            victims = self._enforce_window_locked()
            self._publish_locked()
        self._fire_retire_hooks(victims)

    # -- resolution (verify side) --------------------------------------------

    def resolve(self, epoch):
        """The KeySet a credential minted under `epoch` verifies against.
        ACTIVE and RETIRING epochs resolve (a pre-rollover credential
        stays verifiable through the handoff); RETIRED/evicted refuse
        with EpochRetiredError; unknown or not-yet-activated epochs with
        EpochUnknownError — both typed, both wire-coded."""
        with self._lock:
            entry = self._entries.get(epoch)
            if entry is not None and entry.state in (ACTIVE, RETIRING):
                return entry.keyset
            live = self._live_ids_locked()
            if epoch in self._retired:
                metrics.count("keylife_epoch_retired")
                raise EpochRetiredError(epoch, live=live)
            metrics.count("keylife_epoch_unknown")
            raise EpochUnknownError(epoch, live=live)

    def vk_for(self, epoch):
        return self.resolve(epoch).vk

    def active(self):
        with self._lock:
            if self._active is None:
                raise GeneralError("no active key epoch")
            return self._entries[self._active].keyset

    @property
    def active_epoch(self):
        with self._lock:
            return self._active

    def state(self, epoch):
        with self._lock:
            entry = self._entries.get(epoch)
            if entry is not None:
                return entry.state
            return RETIRED if epoch in self._retired else None

    def live_epochs(self):
        """[(epoch id, state)] for every serving-relevant epoch — what a
        replica's beacon advertises so routers know which epochs it can
        mint or verify under."""
        with self._lock:
            return [
                (e, entry.state)
                for e, entry in sorted(self._entries.items())
                if entry.state in (PENDING, ACTIVE, RETIRING)
            ]

    def pin_count(self, epoch):
        with self._lock:
            entry = self._entries.get(epoch)
            return entry.total_pins() if entry is not None else 0

    # -- internals (lock held) -----------------------------------------------

    def _live_ids_locked(self):
        return [
            e
            for e, entry in self._entries.items()
            if entry.state in (ACTIVE, RETIRING)
        ]

    def _enforce_window_locked(self):
        """Bound the window: at most `window` live (ACTIVE/RETIRING)
        epochs. Oldest RETIRING epochs retire first — their key material
        is DROPPED, not archived. An epoch with live pins defers until
        its last fan-out unpins; the ACTIVE epoch never retires.
        Returns the retired epoch ids so callers can fire the retire
        hooks AFTER releasing the registry lock."""
        victims = []
        while len(self._live_ids_locked()) > self.window:
            victim = None
            for e in sorted(self._entries):
                entry = self._entries[e]
                if entry.state == RETIRING and entry.total_pins() == 0:
                    victim = e
                    break
            if victim is None:
                break
            del self._entries[victim]
            self._retired.add(victim)
            self._journal_locked(victim, "retired")
            metrics.count("keylife_retirements")
            victims.append(victim)
        return victims

    def _publish_locked(self):
        metrics.set_gauge("keylife_active_epoch", self._active or 0)
        metrics.set_gauge(
            "keylife_live_epochs", len(self._live_ids_locked())
        )
