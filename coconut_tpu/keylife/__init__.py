"""Dealerless key lifecycle: online DKG, epoch registry, proactive
refresh and t/n reshare with zero-downtime rollover (PR 15; ROADMAP
item 4). See README "Key lifecycle & epochs"."""

from .dkg import DkgResult, run_dkg, run_refresh
from .epoch import (
    ACTIVE,
    EPOCH_STATE_CODES,
    EPOCH_STATE_OF_CODE,
    PENDING,
    RETIRED,
    RETIRING,
    EpochRegistry,
    KeySet,
)
from .manager import KeyLifecycleManager, aggregate_vk

__all__ = [
    "ACTIVE",
    "DkgResult",
    "EPOCH_STATE_CODES",
    "EPOCH_STATE_OF_CODE",
    "EpochRegistry",
    "KeyLifecycleManager",
    "KeySet",
    "PENDING",
    "RETIRED",
    "RETIRING",
    "aggregate_vk",
    "run_dkg",
    "run_refresh",
]
