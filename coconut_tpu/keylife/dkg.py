"""Online DKG and proactive refresh rounds (PR 15).

`dvss_keygen` (keygen.py) is the reference's in-process driver: it sums
every participant's dealt secret into a master secret, which is exactly
what a deployment must never do — only the test alias
`setup_signers_for_test` may aggregate in-process. The drivers here are
the online promotion of that protocol:

  run_dkg      Gennaro-style DKG: every authority deals a Pedersen-VSS
               sharing of a fresh random secret per key dimension
               (1 for x, one per attribute for the y's); recipients
               verify each share against the dealer's coefficient
               commitments and COMPLAIN — naming the dealer exactly, the
               corrupt-partial attribution pattern from issue/ — on
               mismatch. Disqualified (complained-against or
               unreachable) dealers are excluded and the key is the sum
               over the QUAL set only. If fewer than `threshold` honest
               dealers remain the round aborts with the typed, wired,
               retryable DkgAbortedError. No code path reconstructs the
               master secret: per-recipient share sums are the only
               aggregation performed.

  run_refresh  Herzberg-style proactive refresh: every QUAL dealer deals
               a verifiable sharing of ZERO (PedersenVSS.deal_zero) and
               publishes the degree-0 blinding so recipients can check
               the zero-opening comm[0] == h^{b0} — without that check a
               corrupt dealer could shift the shared secret and silently
               change the verkey. New share = old share + sum of zero
               shares: every share changes, the secret (and the
               aggregated verkey, bit for bit) does not.

A t/n-changing reshare is run_dkg with the new parameters — a fresh
secret under a fresh epoch, not a transformation of the old one, so a
compromise of the old epoch's shares never taints the new.

Transport is synchronous and in-process (the fleet drill drives real
authorities over CTS-RPC for everything *around* the round); the
`unreachable` and `tamper` hooks inject the faults the chaos drill
needs deterministically.
"""

from collections import namedtuple

from ..errors import DkgAbortedError, ShareVerificationError
from ..keygen import keygen_from_shares
from ..ops.fields import R
from ..sss import PedersenVSS

#: Outcome of a DKG or refresh round. Deliberately carries NO secret
#: aggregate — only per-signer key material (inside Signer objects) and
#: the dealer audit trail. test_keylife pins this.
DkgResult = namedtuple(
    "DkgResult",
    ["signers", "qual", "excluded", "complaints", "threshold", "total"],
)


def _maybe_tamper(tamper, dealer_id, recipient_id, dim, share):
    if tamper is None:
        return share
    out = tamper(dealer_id, recipient_id, dim, share)
    return share if out is None else out


def run_dkg(threshold, total, params, g, h, round="dkg",
            unreachable=(), tamper=None):
    """One full DKG round over `1 + params.msg_count()` key dimensions.

    `unreachable` — dealer ids that never deal (crashed/partitioned).
    `tamper(dealer_id, recipient_id, dim, (s, t))` — fault hook: return a
    replacement share to corrupt that one delivery (None = honest).

    Returns a DkgResult whose signers hold shares of the summed QUAL
    secret; raises DkgAbortedError when |QUAL| < threshold.
    """
    dims = 1 + params.msg_count()
    unreachable = set(unreachable)
    all_ids = list(range(1, total + 1))
    dealers = [i for i in all_ids if i not in unreachable]

    # Deal phase: every reachable dealer commits one sharing per dimension.
    deals = {}  # dealer_id -> [(comm_coeffs, s_shares, t_shares)] per dim
    for d in dealers:
        per_dim = []
        for _ in range(dims):
            _, _, comm, s_shares, t_shares = PedersenVSS.deal(
                threshold, total, g, h
            )
            per_dim.append((comm, s_shares, t_shares))
        deals[d] = per_dim

    # Verification phase: each recipient checks every delivered share
    # against the dealer's commitments; a failed check is a complaint
    # naming that dealer. One verifiable complaint disqualifies.
    complaints = {}  # dealer_id -> sorted recipient ids
    for d in dealers:
        for r in all_ids:
            for dim in range(dims):
                comm, s_shares, t_shares = deals[d][dim]
                share = _maybe_tamper(
                    tamper, d, r, dim, (s_shares[r], t_shares[r])
                )
                try:
                    PedersenVSS.check_share(
                        threshold, r, share, comm, g, h,
                        dealer_id=d, round=round,
                    )
                except ShareVerificationError:
                    complaints.setdefault(d, set()).add(r)
                else:
                    deals[d][dim] = (comm, dict(s_shares), t_shares)
                    deals[d][dim][1][r] = share[0]
    complaints = {d: tuple(sorted(rs)) for d, rs in complaints.items()}

    excluded = unreachable | set(complaints)
    qual = [i for i in all_ids if i not in excluded]
    if len(qual) < threshold:
        raise DkgAbortedError(threshold, len(qual), excluded=excluded)

    # Key derivation: per-recipient sums over QUAL dealers ONLY — the one
    # aggregation this path performs. Every authority 1..total receives
    # key shares (an excluded DEALER still serves as a share RECIPIENT).
    def summed(dim):
        return {
            r: sum(deals[d][dim][1][r] for d in qual) % R for r in all_ids
        }

    x_shares = summed(0)
    y_shares = [summed(1 + j) for j in range(dims - 1)]
    signers = keygen_from_shares(total, x_shares, y_shares, params)
    return DkgResult(
        signers=signers,
        qual=tuple(qual),
        excluded=tuple(sorted(excluded)),
        complaints=complaints,
        threshold=threshold,
        total=total,
    )


def run_refresh(signers, threshold, params, g, h, round="refresh",
                unreachable=(), tamper=None):
    """One proactive refresh round over an existing sharing.

    Every reachable authority deals a zero-sharing per dimension and
    publishes its degree-0 blinding; recipients enforce BOTH the usual
    share check and the zero-opening comm[0] == h^{b0} (a dealer passing
    the first but not the second is shifting the secret — complained
    against and excluded). New share_i = old share_i + Σ_QUAL zero
    share_i. Same hooks and abort semantics as run_dkg; returns a
    DkgResult whose signers' verkeys aggregate to the SAME verkey.
    """
    dims = 1 + params.msg_count()
    total = len(signers)
    by_id = {s.id: s for s in signers}
    unreachable = set(unreachable)
    all_ids = sorted(by_id)
    dealers = [i for i in all_ids if i not in unreachable]
    ops = PedersenVSS.ops

    deals = {}  # dealer_id -> [(blind0, comm_coeffs, s_shares, t_shares)]
    for d in dealers:
        per_dim = []
        for _ in range(dims):
            blind0, comm, s_shares, t_shares = PedersenVSS.deal_zero(
                threshold, total, g, h
            )
            per_dim.append((blind0, comm, s_shares, t_shares))
        deals[d] = per_dim

    complaints = {}
    for d in dealers:
        for r in all_ids:
            for dim in range(dims):
                blind0, comm, s_shares, t_shares = deals[d][dim]
                share = _maybe_tamper(
                    tamper, d, r, dim, (s_shares[r], t_shares[r])
                )
                ok = comm[0] == ops.mul(h, blind0)
                if ok:
                    try:
                        PedersenVSS.check_share(
                            threshold, r, share, comm, g, h,
                            dealer_id=d, round=round,
                        )
                    except ShareVerificationError:
                        ok = False
                if not ok:
                    complaints.setdefault(d, set()).add(r)
                else:
                    deals[d][dim] = (blind0, comm, dict(s_shares), t_shares)
                    deals[d][dim][2][r] = share[0]
    complaints = {d: tuple(sorted(rs)) for d, rs in complaints.items()}

    excluded = unreachable | set(complaints)
    qual = [i for i in all_ids if i not in excluded]
    if len(qual) < threshold:
        raise DkgAbortedError(threshold, len(qual), excluded=excluded)

    def delta(dim):
        return {
            r: sum(deals[d][dim][2][r] for d in qual) % R for r in all_ids
        }

    dx = delta(0)
    x_shares = {r: (by_id[r].sigkey.x + dx[r]) % R for r in all_ids}
    y_shares = []
    for j in range(dims - 1):
        dy = delta(1 + j)
        y_shares.append(
            {r: (by_id[r].sigkey.y[j] + dy[r]) % R for r in all_ids}
        )
    new_signers = keygen_from_shares(total, x_shares, y_shares, params)
    return DkgResult(
        signers=new_signers,
        qual=tuple(qual),
        excluded=tuple(sorted(excluded)),
        complaints=complaints,
        threshold=threshold,
        total=total,
    )
