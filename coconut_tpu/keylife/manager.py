"""Key lifecycle manager: DKG bootstrap, proactive refresh, and t/n
reshare with two-phase epoch rollover into live services (PR 15).

The manager owns the EpochRegistry and drives rounds (dkg.py) against
it, then pushes the resulting KeySets into every attached service —
anything exposing `install_keyset(keyset)`, i.e. IssuanceService /
ProtocolEngine (which forward to their MintProgram). The ordering is the
whole point:

  reshare   install keys on every authority FIRST (epoch PENDING), only
            then activate() — so the instant new mints start pinning the
            new epoch, every authority can already sign under it, and
            fan-outs pinned to the old epoch drain undisturbed.

  refresh   the verkey must not move: the manager asserts the aggregated
            verkey of the refreshed share set is BIT-IDENTICAL
            (Verkey.to_bytes) to the current one before installing the
            new gen. A refresh that would change the verkey is a corrupt
            round, never installed.

Neither path ever materializes a master secret — rounds return only
per-signer shares (dkg.DkgResult), and aggregation here is of PUBLIC
verkeys.
"""

from .. import metrics
from ..errors import GeneralError
from ..signature import Verkey
from ..sss import PedersenVSS
from .dkg import run_dkg, run_refresh
from .epoch import EpochRegistry, KeySet


def aggregate_vk(keyset_or_signers, threshold=None, ctx=None):
    """Aggregated (epoch) verkey from any `threshold` of the signers'
    public verkeys — the key credentials minted from this set verify
    under."""
    if isinstance(keyset_or_signers, KeySet):
        signers = keyset_or_signers.signers
        threshold = keyset_or_signers.threshold
    else:
        signers = keyset_or_signers
        if threshold is None:
            raise GeneralError("threshold required when passing raw signers")
    return Verkey.aggregate(
        threshold, [(s.id, s.verkey) for s in signers], ctx=ctx
    )


class KeyLifecycleManager:
    """Drives DKG/refresh/reshare rounds and rolls the results into the
    registry and every attached service."""

    def __init__(self, params, label=b"coconut-tpu keylife", window=3,
                 registry=None):
        self.params = params
        self.registry = registry if registry is not None else EpochRegistry(
            window=window
        )
        self.g, self.h = PedersenVSS.gens(label)
        self._services = []
        self.last_round = None  # audit trail of the most recent round

    # -- wiring ---------------------------------------------------------------

    def attach(self, service):
        """Register a service to receive keysets. Replays already-live
        epochs so late-attached services can serve them immediately."""
        self._services.append(service)
        for epoch, state in self.registry.live_epochs():
            if state in ("active", "retiring"):
                service.install_keyset(self.registry.resolve(epoch))

    def _install(self, keyset):
        for svc in self._services:
            svc.install_keyset(keyset)

    # -- rounds ---------------------------------------------------------------

    def bootstrap(self, threshold, total, unreachable=(), tamper=None):
        """First DKG: mint epoch 1 (or the next free id) and activate it.
        Raises DkgAbortedError if fewer than `threshold` honest dealers
        participate."""
        result = run_dkg(
            threshold, total, self.params, self.g, self.h,
            round="dkg", unreachable=unreachable, tamper=tamper,
        )
        keyset = self._keyset_from(result, gen=0)
        self.registry.register(keyset)
        self._install(keyset)
        self.registry.activate(keyset.epoch)
        self.last_round = result
        return keyset

    def refresh(self, unreachable=(), tamper=None):
        """Proactive share refresh of the ACTIVE epoch: same epoch, same
        verkey (asserted bit-identical), gen+1, every share changed."""
        current = self.registry.active()
        result = run_refresh(
            current.signers, current.threshold, self.params, self.g, self.h,
            round="refresh", unreachable=unreachable, tamper=tamper,
        )
        ctx = self.params.ctx
        new_vk = aggregate_vk(result.signers, current.threshold, ctx=ctx)
        if new_vk.to_bytes(ctx) != current.vk.to_bytes(ctx):
            raise GeneralError(
                "refresh round moved the verkey for epoch %d — corrupt "
                "round, not installing" % current.epoch
            )
        keyset = KeySet(
            epoch=current.epoch,
            gen=current.gen + 1,
            threshold=current.threshold,
            signers=result.signers,
            vk=current.vk,  # unchanged by construction, asserted above
            qual=result.qual,
            excluded=result.excluded,
        )
        self.registry.install_gen(keyset)
        self._install(keyset)
        self.last_round = result
        metrics.count("keylife_refreshes")
        return keyset

    def reshare(self, threshold=None, total=None, unreachable=(),
                tamper=None):
        """t/n-changing reshare: a fresh DKG under the new parameters,
        rolled out as a NEW epoch (new verkey) via the two-phase
        install-then-activate handoff. In-flight mints complete under
        the epoch they pinned; its credentials keep verifying until the
        old epoch retires out of the window."""
        current = self.registry.active()
        threshold = threshold if threshold is not None else current.threshold
        total = total if total is not None else current.total
        result = run_dkg(
            threshold, total, self.params, self.g, self.h,
            round="reshare", unreachable=unreachable, tamper=tamper,
        )
        keyset = self._keyset_from(result, gen=0)
        self.registry.register(keyset)  # PENDING: nothing serves it yet
        self._install(keyset)  # every authority can sign under it...
        self.registry.activate(keyset.epoch)  # ...before mints pin it
        self.last_round = result
        metrics.count("keylife_reshares")
        return keyset

    def _keyset_from(self, result, gen):
        return KeySet(
            epoch=self.registry.next_epoch(),
            gen=gen,
            threshold=result.threshold,
            signers=result.signers,
            vk=aggregate_vk(
                result.signers, result.threshold, ctx=self.params.ctx
            ),
            qual=result.qual,
            excluded=result.excluded,
        )
