"""Observability: scoped timers + counters (SURVEY.md §5 "metrics" mandate).

The reference has no observability at all (errors are the only signal —
SURVEY §5); this module provides the minimum the framework's own survey
demands: per-phase wall-clock timers (host encode / device compile / kernel /
readback), monotonic counters (verifies, batches, transfer bytes), and a
`snapshot()` the bench harness embeds in its JSON output so TPU claims are
auditable.

The stream supervision layer (stream.py / retry.py) reports its fault
handling through the same counters so `snapshot()` is the single audit
surface: "retries" (re-attempts after a transient backend error),
"fallbacks" (batches re-dispatched on the fallback backend after retries
exhausted), "bisections" (grouped-failure splits while isolating culprit
credentials), "dead_letters" (culprits appended to the dead-letter JSONL),
and "checkpoint_quarantined" (corrupt state files moved aside on resume).

The encode pipeline reports here too: "encode_cache_hits" /
"encode_cache_misses" (the backend's static-operand cache — comb tables,
grouped point uploads, g_tilde — see tpu/backend._static_operands),
"prefetched_batches" (batches encoded+dispatched by verify_stream's
background worker), and the "prefetch_wait" timer (main-thread seconds
blocked waiting on the prefetch queue: near zero means the encode worker
keeps the device fed — pipeline occupancy is 1 - prefetch_wait/wall).

Zero-cost when unused: plain dicts, no background threads, no deps.
Device-side profiling is separate: the hot kernels in tpu/backend.py carry
`jax.named_scope` annotations (comb_msm, grouped_tables /
grouped_gather_fold / grouped_horner, miller_two_pairs / grouped_miller,
affine_norm, final_exp) and `BENCH_PROFILE=1 python bench.py` writes a
`jax.profiler` trace broken down by those scopes; host-side phases are
what these timers capture.
"""

import time
from collections import defaultdict
from contextlib import contextmanager

_timers = defaultdict(float)
_counts = defaultdict(int)


@contextmanager
def timer(name):
    """Accumulate wall-clock seconds under `name`
    (e.g. "encode", "kernel", "readback")."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        _timers[name] += time.perf_counter() - t0


def count(name, n=1):
    """Add n to the counter `name` (e.g. "verifies", "transfer_bytes")."""
    _counts[name] += n


def get_count(name):
    """Current value of counter `name` (0 if never counted)."""
    return _counts.get(name, 0)


def snapshot():
    """{"timers_s": {...}, "counters": {...}} — current totals."""
    return {
        "timers_s": {k: round(v, 6) for k, v in sorted(_timers.items())},
        "counters": dict(sorted(_counts.items())),
    }


def reset():
    _timers.clear()
    _counts.clear()


def rate(counter, timer_name):
    """counter / timer seconds, or None if either is missing/zero."""
    t = _timers.get(timer_name)
    c = _counts.get(counter)
    if not t or not c:
        return None
    return c / t
