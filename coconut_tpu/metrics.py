"""Observability: scoped timers + counters + bounded latency histograms
(SURVEY.md §5 "metrics" mandate).

The reference has no observability at all (errors are the only signal —
SURVEY §5); this module provides the minimum the framework's own survey
demands: per-phase wall-clock timers — "encode" (host limb encode),
"kernel" (device dispatch), "readback" (device->host transfer) — monotonic
counters "verifies" / "batches" / "transfer_bytes", bounded latency
histograms with percentile readout (the serving layer's per-request
SLO surface), and a `snapshot()` the bench harness embeds in its JSON output
so TPU claims are auditable.

The stream supervision layer (stream.py / retry.py) reports its fault
handling through the same counters so `snapshot()` is the single audit
surface: "retries" (re-attempts after a transient backend error),
"fallbacks" (batches re-dispatched on the fallback backend after retries
exhausted), "bisections" (grouped-failure splits while isolating culprit
credentials), "dead_letters" (culprits appended to the dead-letter JSONL),
and "checkpoint_quarantined" (corrupt state files moved aside on resume).

The RLC batch verifier (PR 16, coconut_tpu/batchverify.py + the
backends' *_combined entry points) adds: "verify_batched_checks"
(combined RLC predicate evaluations — one per batch plus one per
bisection probe), "verify_batched_fallbacks" (combined batches that
rejected and fell back to the bisection ladder), "verify_bisection_depth"
(ladder splits while attributing a rejected combined batch — depth per
incident is the delta across the fallback), and "verify_final_exps"
(final exponentiations dispatched: B per exact batch, 1 per
combined/grouped batch — the <=2-per-combined-batch bench assertion
reads this counter's deltas).

The encode pipeline reports here too: "encode_cache_hits" /
"encode_cache_misses" (the backend's static-operand cache — comb tables,
grouped point uploads, g_tilde — see tpu/backend._static_operands),
"prefetched_batches" (batches encoded+dispatched by verify_stream's
background worker), and the "prefetch_wait" timer (main-thread seconds
blocked waiting on the prefetch queue: near zero means the encode worker
keeps the device fed — pipeline occupancy is 1 - prefetch_wait/wall).

The online serving layer (coconut_tpu/serve/) reports: "serve_admitted" /
"serve_rejected" (admission control), "serve_batches" /
"serve_batched_requests" / "serve_pad_lanes" (coalescing — mean batch
occupancy is batched_requests / (batches * max_batch)), "serve_valid" /
"serve_invalid" / "serve_failed_requests" / "serve_cancelled" (outcomes),
"future_callback_errors" (future done-callbacks that raised — contained,
never propagated into the settling thread), and the "serve_latency_s" /
"serve_batch_wait_s" histograms.

Every OTHER engine program reports the same shape under its own
namespace (`<ns>` is the program's metric namespace: "prep", "issue",
"prove", "showv" — the verify pool keeps the legacy "serve" prefix):
"<ns>_done" (requests settled OK), "<ns>_pad_lanes" (lanes padded to
the program's pad convention), "<ns>_valid" / "<ns>_invalid" (verdict
programs), "<ns>_failed_requests" / "<ns>_cancelled" (failure
outcomes), and the "<ns>_latency_s" histogram. The ragged show-verify
host fallback counts "show_verify_ragged_proofs" (proofs verified on
the ragged path) / "show_verify_ragged_fallback" (batches that took
it).

The mesh-scale dispatcher pool adds PER-DEVICE and placement surfaces:
each device executor `<d>` counts "serve_dev<d>_dispatches" /
"serve_dev<d>_requests" and accumulates the "serve_dev<d>_busy_s" timer
(occupancy over a window is its delta / wall), the adaptive placement
policy counts "serve_placed_single" / "serve_placed_sharded", and
point-in-time GAUGES ("serve_queue_depth", "serve_dev<d>_load" —
`set_gauge`, last-write-wins, reported verbatim under "gauges") expose
the routing state the least-loaded picker saw. `counters_with_prefix` /
`timers_with_prefix` read a whole label family (e.g. "serve_dev")
without enumerating device ids.

The SELF-HEALING pool (serve/health.py + serve/service.py) reports its
recovery ladder here: "serve_quarantined" (circuit-breaker opens),
"serve_probes" (half-open probe batches placed on PROBATION executors),
"serve_probe_failures", "serve_recovered" (breakers closed back to
HEALTHY), "serve_watchdog_timeouts" (hung dispatches expired),
"serve_executor_crashes" (executor-loop crashes contained),
"serve_redistributed_batches" / "serve_redistributed_requests" (unsettled
work re-placed onto survivors), "serve_redispatch_exhausted" (poisonous
batches failed after the hop cap), "serve_shed_bulk" (brownout sheds),
and "rotations" / "rotation_errors" (dead-letter/flight JSONL rotation)
plus "flight_torn_lines" (unparseable flight-recorder lines skipped on
read after a crash mid-append).
Gauges: "serve_dev<d>_health" (the state string), "serve_healthy_executors"
(admissible pool size), "serve_brownout" (0/1 shed-mode flag).

The THRESHOLD-ISSUANCE service (coconut_tpu/issue/) reports under the
"issue" namespace — the same queue/batcher/health machinery re-namespaced
("issue_admitted" / "issue_rejected" / "issue_batches" /
"issue_batched_requests" / "issue_shed_bulk", per-authority
"issue_auth<a>_dispatches" / "issue_auth<a>_busy_s", breaker counters
"issue_quarantined" / "issue_probes" / "issue_probe_failures" /
"issue_recovered", "issue_watchdog_timeouts", "issue_authority_crashes",
"issue_health_tick_errors") plus the quorum-specific surfaces:
"issue_minted" (credentials released — each verified under the
aggregated verkey before release), "issue_hedges" (straggler hedge
dispatches fired) / "issue_hedge_no_spare" (hedges that found no spare
authority), "issue_partials_discarded" (late/duplicate/stale partial
rows dropped by the first-t-wins guard), "issue_corrupt_partials"
(partial rows attributed to a corrupt authority by per-partial
verification), "issue_redispatched" (coverage re-dispatches to spare
authorities), "issue_cancelled_signs" (queued signs canceled after the
quorum resolved), "issue_sign_skips" (popped signs skipped because the
fan-out had already resolved), "issue_quorum_unreachable" (fan-outs
failed with QuorumUnreachableError), "issue_mint_failures" /
"issue_failed_requests" / "issue_cancelled" (failure outcomes).
Histograms: "issue_quorum_wait_s" (dispatch -> t-th partial, the quorum
assembly latency), "issue_latency_s" (admission -> release, the
client-facing SLO), "issue_batch_wait_s" (coalescing delay). Gauges:
"issue_auth<a>_health", "issue_healthy_authorities",
"issue_queue_depth", "issue_brownout".

The REPLICA LIFECYCLE layer (engine/lifecycle.py, PR 14) reports under
"lifecycle_*" and "elastic_*": gauges "lifecycle_state" (0 warming /
1 up / 2 draining / 3 closed), "lifecycle_warmup_s" (boot's manifest
replay wall time), "lifecycle_manifest_shapes" (shapes loaded at boot);
counters "lifecycle_warmed_shapes" / "lifecycle_warm_skipped" /
"lifecycle_warm_errors" (manifest replay outcomes),
"lifecycle_manifest_corrupt" / "lifecycle_manifest_save_errors" /
"lifecycle_manifest_unserializable" (artifact integrity — corruption
degrades to a cold boot, never a failed one), and
"lifecycle_cache_config_errors" (persistent compilation cache could not
be configured). Elastic pool sizing: gauges "elastic_active_executors" /
"elastic_depth" / "elastic_busy_fraction"; counters "elastic_parked" /
"elastic_unparked" (engine-level park/respawn), "elastic_grown" /
"elastic_shrunk" (controller decisions that acted), and
"elastic_emergency_unparked" (parked spares pressed into service when
every active executor died). The fleet adds the lifecycle routing
proof: "gateway_warmed" / "gateway_drain_observed" (directory
transitions), "gateway_drain_handoffs" (closed-replica refusals failed
over), and per-placement-state "gateway_placed_<state>" — the
rolling-restart drill asserts "gateway_placed_warming" and
"gateway_placed_draining" stay zero.

The DURABLE STATE plane (coconut_tpu/state/, PR 17) reports the
journal: "wal_appends" (records framed into a WAL) vs "wal_fsyncs"
(fdatasync calls — the gap between the two IS the group-commit
amortization, one sync per engine batch rather than per lane),
"wal_torn_tails" (torn trailing frames truncated on open — exactly
once per torn crash), "wal_replayed_records" (records re-applied from
segments on open), "wal_segments_rotated" (bounded-rotation events);
the store: "state_records_applied" (in-memory applies, local + remote),
"state_snapshots" / "state_snapshot_loads" / "state_snapshot_corrupt"
(a CRC-failed snapshot is quarantined `.corrupt` and the store rebuilds
from the WAL — degrade, never trust), "state_compactions"
(snapshot+WAL-truncate cycles); anti-entropy: "state_antientropy_pulls"
(gap pages pulled from peers), "state_antientropy_dropped" (pulls
suppressed by injected partition chaos), "state_replicator_errors"
(pull-loop failures — a dead peer is survivable, another peer or a
later sweep serves the gap), "gateway_state_pulls" (MSG_STATE_PULL
requests served — also while DRAINING: state transfer is how facts
escape a dying replica); and the nullifier set: "nullifier_commits"
(accepted shows durably journaled BEFORE their futures resolve),
"nullifier_double_spends" (replays rejected with DoubleSpendError),
"nullifier_probe_hits" (device-probe pre-verify hits),
"nullifier_probe_errors" (advisory probe failures — detection degrades
to commit time, never admits a double-spend), "nullifier_commit_errors"
(WAL-append failures that turned would-be accepts into
TransientBackendError: no resolve without durability),
"gateway_tenant_store_errors" / "dead_letter_index_errors" /
"dead_letter_errors" (lazy-durability write failures in the adopted
subsystems, counted and survived), and "dead_letter_torn_lines"
(unparseable dead-letter JSONL lines skipped on read — a crash
mid-append tears at most the final line).

The APPLICATION SCENARIO layer (coconut_tpu/scenarios/, PR 19) reports
under "scenario_*": "scenario_started" (workflows admitted by the
population driver) and one terminal counter per outcome —
"scenario_completed", "scenario_rejected" (EXPECTED typed rejections:
petition re-sign / e-cash double-spend, the protections firing),
"scenario_retry_exhausted", "scenario_deadline", "scenario_failed"
(unattributed errors — the acceptance bar is zero), and
"scenario_cancelled" (drain-cancelled runs — dangling futures, also
zero on a clean drain); every started workflow lands in EXACTLY ONE of
these, so started == the terminal sum is the no-lost-workflow check.
Plus "scenario_retries" (typed-transient step re-submissions),
"scenario_deferred" (arrivals refused by the bounded in-flight
window), "scenario_thinking" (arrivals skipped because the sampled
user was busy or in think-time), "scenario_hook_errors" (terminal-hook
exceptions contained), and "scenario_elastic_tick_errors" (elastic
controller ticks that raised — sizing degrades, the run continues).
The breaker journal (serve/health.py + ExecutionEngine
.attach_health_journal, PR 19) adds "health_journal_errors": journal
writes that raised inside a state transition — durability degrades to
in-memory, the transition itself never fails.

THREAD SAFETY: the serving layer is the first multi-threaded writer
(admission happens on client threads while the supervisor thread settles
batches), so every mutation and `snapshot()` runs under one module lock —
the bare defaultdict updates this module started with race under free
threading. Still zero-cost when unused: no background threads, no deps.

Histograms are bounded: `observe(name, seconds)` keeps a fixed-size window
of the most recent samples (plus exact count/total/max over the full run),
so a million-request serving run holds kilobytes, not a sample per request.
Percentiles in `snapshot()` are therefore over the retained window — recent
behavior, which is what an SLO monitor wants anyway.

Request-scoped observability is separate but joins here: while tracing is
enabled (coconut_tpu/obs, COCONUT_TRACE=1) `snapshot()` embeds a
"trace_stages" section — per-span-name count/total/mean, the queue-wait /
coalesce / encode / device / demux breakdown that separates "slow device"
from "slow batcher" — via `register_provider`, so this module never
imports obs (providers are injected, not imported).

Device-side profiling is separate: the hot kernels in tpu/backend.py carry
`jax.named_scope` annotations (comb_msm, grouped_tables /
grouped_gather_fold / grouped_horner, miller_two_pairs / grouped_miller,
affine_norm, final_exp) and `BENCH_PROFILE=1 python bench.py` writes a
`jax.profiler` trace broken down by those scopes; host-side phases are
what these timers capture.
"""

import threading
import time
from collections import defaultdict, deque
from contextlib import contextmanager

_lock = threading.RLock()
_timers = defaultdict(float)
_counts = defaultdict(int)
_hists = {}
_gauges = {}
_providers = {}  # snapshot section name -> zero-arg callable

# per-histogram retained-sample window (memory bound; count/total/max stay
# exact over the full run)
HIST_WINDOW = 4096


@contextmanager
def timer(name):
    """Accumulate wall-clock seconds under `name`
    (e.g. "encode", "kernel", "readback")."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        with _lock:
            _timers[name] += dt


def count(name, n=1):
    """Add n to the counter `name` (e.g. "verifies", "transfer_bytes")."""
    with _lock:
        _counts[name] += n


def get_count(name):
    """Current value of counter `name` (0 if never counted)."""
    with _lock:
        return _counts.get(name, 0)


def counters_with_prefix(prefix):
    """{name: value} for every counter whose name starts with `prefix` —
    how the serving report reads a whole per-device family
    ("serve_dev<d>_dispatches") without enumerating device ids."""
    with _lock:
        return {k: v for k, v in _counts.items() if k.startswith(prefix)}


def timers_with_prefix(prefix):
    """{name: seconds} for every timer whose name starts with `prefix`
    (the per-device busy-time family)."""
    with _lock:
        return {k: v for k, v in _timers.items() if k.startswith(prefix)}


def set_gauge(name, value):
    """Set the point-in-time gauge `name` (e.g. "serve_queue_depth", a
    device executor's current load): last-write-wins, reported verbatim
    by snapshot() under "gauges" — unlike counters these go DOWN."""
    with _lock:
        _gauges[name] = value


def get_gauge(name, default=None):
    with _lock:
        return _gauges.get(name, default)


def observe(name, seconds):
    """Record one sample in the bounded histogram `name` (e.g.
    "serve_latency_s"). Keeps the most recent HIST_WINDOW samples for
    percentile readout plus exact count/total/max over the full run."""
    with _lock:
        h = _hists.get(name)
        if h is None:
            h = _hists[name] = {
                "count": 0,
                "total": 0.0,
                "max": 0.0,
                "window": deque(maxlen=HIST_WINDOW),
            }
        h["count"] += 1
        h["total"] += seconds
        if seconds > h["max"]:
            h["max"] = seconds
        h["window"].append(seconds)


def percentile(samples, q):
    """q-th percentile (q in [0, 100]) of `samples` by the nearest-rank
    method. Tiny-window behavior is PINNED, not emergent:

      n == 0  ->  None (there is no sample to report — never a fabricated
                  zero);
      n == 1  ->  the single sample, for EVERY q including 0 and 100;
      q outside [0, 100] -> ValueError (previously q=-5 silently read the
                  min and q=200 the max — a caller bug masquerading as a
                  statistic).

    Small-n honest in general: p99 of 10 samples is the max, not an
    interpolated fiction."""
    if not 0.0 <= q <= 100.0:
        raise ValueError("percentile q must be in [0, 100] (got %r)" % (q,))
    if not samples:
        return None
    import math

    s = sorted(samples)
    rank = max(0, min(len(s) - 1, math.ceil(q / 100.0 * len(s)) - 1))
    return s[rank]


def percentile_summary(samples, qs=(50, 95, 99)):
    """{"p50": ..., ...} nearest-rank readout with the tiny-window policy
    of `percentile` made structural: n=0 returns an EMPTY dict (absent
    keys, not None-or-zero values), n=1 returns the single sample under
    every requested quantile."""
    if not samples:
        return {}
    return {"p%g" % q: percentile(samples, q) for q in qs}


def _hist_readout(h):
    window = list(h["window"])
    n = h["count"]
    ps = percentile_summary(window)
    return {
        "count": n,
        "mean_s": round(h["total"] / n, 6) if n else None,
        "p50_s": round(ps["p50"], 6) if ps else None,
        "p95_s": round(ps["p95"], 6) if ps else None,
        "p99_s": round(ps["p99"], 6) if ps else None,
        "max_s": round(h["max"], 6),
    }


def hist_totals(name):
    """(count, total_seconds) of histogram `name` over the FULL run —
    exact, not window-bounded. (0, 0.0) if nothing was observed. The
    RPC loadgen reads deltas of these to split client-observed latency
    into engine time vs wire overhead (rpc_overhead_s)."""
    with _lock:
        h = _hists.get(name)
        if h is None:
            return 0, 0.0
        return h["count"], h["total"]


def register_provider(name, fn):
    """Register a zero-arg callable whose result snapshot() embeds under
    `name` — how obs.trace contributes the per-stage span breakdown
    without this module importing it."""
    with _lock:
        _providers[name] = fn


def unregister_provider(name):
    with _lock:
        _providers.pop(name, None)


def snapshot():
    """{"timers_s": {...}, "counters": {...}[, "histograms": {...}]
    [, <provider sections>]} — current totals; histogram readouts
    (count / mean / p50 / p95 / p99 / max over the retained window)
    appear once anything has been observe()d; provider sections (e.g.
    "trace_stages" while tracing is enabled) appear while registered and
    non-empty."""
    with _lock:
        snap = {
            "timers_s": {k: round(v, 6) for k, v in sorted(_timers.items())},
            "counters": dict(sorted(_counts.items())),
        }
        if _hists:
            snap["histograms"] = {
                k: _hist_readout(h) for k, h in sorted(_hists.items())
            }
        if _gauges:
            snap["gauges"] = dict(sorted(_gauges.items()))
        providers = list(_providers.items())
    # provider callables run OUTSIDE the lock (they may take their own)
    for name, fn in providers:
        section = fn()
        if section:
            snap[name] = section
    return snap


def reset():
    with _lock:
        _timers.clear()
        _counts.clear()
        _hists.clear()
        _gauges.clear()


def rate(counter, timer_name):
    """counter / timer seconds, or None if either is missing/zero."""
    with _lock:
        t = _timers.get(timer_name)
        c = _counts.get(counter)
    if not t or not c:
        return None
    return c / t
