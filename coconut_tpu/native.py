"""CppBackend — ctypes bridge to the native C++ core (native/ccbls.cpp).

SURVEY.md §7 stage 1's Python-visible face: the same `CurveBackend` seam the
JAX backend implements, routed through the batch C ABI of `libccbls.so`.
The native library is the framework's CPU baseline (BASELINE.md) and the
const-time issuance path (reference const-time MSM call sites
signature.rs:157,424-428): `ct=True` selects the masked-lookup schedule,
which accumulates through the COMPLETE Renes-Costello-Batina projective
formulas (the same branch-free formulas as the TPU kernels) over
branchless masked field normalization — no secret-dependent branch,
formula path, or memory access anywhere in the schedule.

Wire codec (must match ccbls.cpp): Fp = 48B LE canonical; affine G1 = x||y
(96B), G2 = x.c0||x.c1||y.c0||y.c1 (192B); infinity = all-zero bytes
(0^3+4 != 0 so the encoding is unambiguous); scalars = 32B LE canonical Fr.

Build on demand: `make -C native` (g++); `CCBLS_SO` overrides the path.
"""

import ctypes
import os
import subprocess

from .backend import CurveBackend, register_backend
from .ops.fields import R

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")
_SO_PATH = os.environ.get("CCBLS_SO", os.path.join(_NATIVE_DIR, "libccbls.so"))

_lib = None


def _build():
    subprocess.run(
        ["make", "-C", _NATIVE_DIR, "libccbls.so"],
        check=True,
        capture_output=True,
    )


def load(build_if_missing=True):
    """Load (building if needed) and selftest the native library."""
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_SO_PATH) and build_if_missing:
        _build()
    lib = ctypes.CDLL(_SO_PATH)
    lib.cc_selftest.restype = ctypes.c_int
    rc = lib.cc_selftest()
    if rc != 0:
        raise RuntimeError("ccbls selftest failed: %d" % rc)
    for name, argt in [
        ("cc_msm_g1", [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_char_p, ctypes.c_int]),
        ("cc_msm_g2", [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_char_p, ctypes.c_int]),
        ("cc_pairing_product_is_one", [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_char_p]),
        ("cc_g1_mul", [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p]),
    ]:
        fn = getattr(lib, name)
        fn.argtypes = argt
        fn.restype = None
    for name, argt in [
        ("cc_msm_pippenger_g1", [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p]),
        ("cc_msm_pippenger_g2", [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p]),
    ]:
        fn = getattr(lib, name)
        fn.argtypes = argt
        fn.restype = None
    lib.cc_fr_lagrange_basis_at_0.argtypes = [
        ctypes.POINTER(ctypes.c_uint32),
        ctypes.c_int,
        ctypes.c_uint32,
        ctypes.c_char_p,
    ]
    lib.cc_fr_lagrange_basis_at_0.restype = ctypes.c_int
    lib.cc_fr_poly_eval.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int,
        ctypes.c_uint32,
        ctypes.c_char_p,
    ]
    lib.cc_fr_poly_eval.restype = None
    lib.cc_fr_reconstruct.argtypes = [
        ctypes.POINTER(ctypes.c_uint32),
        ctypes.c_char_p,
        ctypes.c_int,
        ctypes.c_char_p,
    ]
    lib.cc_fr_reconstruct.restype = ctypes.c_int
    lib.cc_fr_random.argtypes = [ctypes.c_char_p]
    lib.cc_fr_random.restype = ctypes.c_int
    lib.cc_pedersen_deal_from_coeffs.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_char_p,
    ]
    lib.cc_pedersen_deal_from_coeffs.restype = None
    lib.cc_pedersen_deal.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_char_p,
    ]
    lib.cc_pedersen_deal.restype = ctypes.c_int
    lib.cc_pedersen_verify_share.argtypes = [
        ctypes.c_int, ctypes.c_uint32, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
    ]
    lib.cc_pedersen_verify_share.restype = ctypes.c_int
    lib.cc_dvss_new.argtypes = [
        ctypes.c_uint32, ctypes.c_int, ctypes.c_int, ctypes.c_char_p,
        ctypes.c_char_p,
    ]
    lib.cc_dvss_new.restype = ctypes.c_void_p
    lib.cc_dvss_deal.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
    ]
    lib.cc_dvss_deal.restype = None
    lib.cc_dvss_receive.argtypes = [
        ctypes.c_void_p, ctypes.c_uint32, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_char_p,
    ]
    lib.cc_dvss_receive.restype = ctypes.c_int
    lib.cc_dvss_finalize.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
    ]
    lib.cc_dvss_finalize.restype = ctypes.c_int
    lib.cc_dvss_free.argtypes = [ctypes.c_void_p]
    lib.cc_dvss_free.restype = None
    for name in ("cc_hash_to_fr", "cc_hash_to_g1", "cc_hash_to_g2"):
        fn = getattr(lib, name)
        fn.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int,
            ctypes.c_char_p,
            ctypes.c_int,
            ctypes.c_char_p,
        ]
        fn.restype = ctypes.c_int
    try:
        # batched entry point; absent from a stale .so built before it
        # existed (hash_to_g1_batch then falls back to the per-msg calls)
        lib.cc_hash_to_g1_batch.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int),
            ctypes.c_int,
            ctypes.c_char_p,
            ctypes.c_int,
            ctypes.c_char_p,
        ]
        lib.cc_hash_to_g1_batch.restype = ctypes.c_int
    except AttributeError:
        pass
    _lib = lib
    return lib


# --- hashing (native CTH-v2: the amcl `from_msg_hash` replacement — C++
# side of spec ops/hashing.py; reference call sites signature.rs:23-29,
# 205, 598) -------------------------------------------------------------


def hash_to_fr(msg, dst=None):
    """Native hash-to-Fr, bit-identical to ops.hashing.hash_to_fr."""
    from .ops.hashing import DST_FR

    dst = DST_FR if dst is None else dst
    lib = load()
    out = ctypes.create_string_buffer(32)
    rc = lib.cc_hash_to_fr(msg, len(msg), dst, len(dst), out)
    if rc != 0:
        raise ValueError("cc_hash_to_fr failed: %d" % rc)
    return int.from_bytes(out.raw, "little")


def hash_to_g1(msg, dst=None):
    """Native hash-to-G1, bit-identical to ops.hashing.hash_to_g1."""
    from .ops.hashing import DST_G1

    dst = DST_G1 if dst is None else dst
    lib = load()
    out = ctypes.create_string_buffer(96)
    rc = lib.cc_hash_to_g1(msg, len(msg), dst, len(dst), out)
    if rc != 0:
        raise ValueError("cc_hash_to_g1 failed: %d" % rc)
    return _g1_parse(out.raw)


def hash_to_g1_batch(msgs, dst=None):
    """Batched native hash-to-G1: N messages in ONE FFI call (the per-call
    ctypes overhead across 1,024 serial hashes was a visible slice of the
    prepare phase's host wall). Bit-identical to [hash_to_g1(m) for m in
    msgs]; falls back to exactly that loop on a stale .so without the
    batched symbol."""
    from .ops.hashing import DST_G1

    dst = DST_G1 if dst is None else dst
    msgs = list(msgs)
    lib = load()
    if not hasattr(lib, "cc_hash_to_g1_batch"):
        return [hash_to_g1(m, dst) for m in msgs]
    n = len(msgs)
    if n == 0:
        return []
    lens = (ctypes.c_int * n)(*[len(m) for m in msgs])
    out = ctypes.create_string_buffer(96 * n)
    rc = lib.cc_hash_to_g1_batch(b"".join(msgs), lens, n, dst, len(dst), out)
    if rc != 0:
        raise ValueError("cc_hash_to_g1_batch failed at msg %d" % (rc - 1))
    raw = out.raw
    return [_g1_parse(raw[i * 96 : (i + 1) * 96]) for i in range(n)]


def hash_to_g2(msg, dst=None):
    """Native hash-to-G2, bit-identical to ops.hashing.hash_to_g2."""
    from .ops.hashing import DST_G2

    dst = DST_G2 if dst is None else dst
    lib = load()
    out = ctypes.create_string_buffer(192)
    rc = lib.cc_hash_to_g2(msg, len(msg), dst, len(dst), out)
    if rc != 0:
        raise ValueError("cc_hash_to_g2 failed: %d" % rc)
    return _g2_parse(out.raw)


# --- Pippenger single-MSM (reference multi_scalar_mul_var_time surface,
# signature.rs:513,521: large-t Verkey.aggregate and any big-MSM workload) --

# Below this size the windowed row schedule beats the bucket combine; the
# crossover was measured on this box (BASELINE.md "Pippenger crossover").
PIPPENGER_MIN = 96


def msm_g1_single(points, scalars, force_pippenger=False):
    """One var-time MSM over n distinct G1 points through the native core:
    Pippenger buckets for n >= PIPPENGER_MIN, the windowed row schedule
    below it. Returns a spec point tuple (None = identity)."""
    n = len(points)
    if n == 0:
        return None
    lib = load()
    if n < PIPPENGER_MIN and not force_pippenger:
        return CppBackend().msm_g1_distinct([list(points)], [list(scalars)])[0]
    pts = b"".join(_g1_bytes(p) for p in points)
    ss = b"".join((int(s) % R).to_bytes(32, "little") for s in scalars)
    out = ctypes.create_string_buffer(96)
    lib.cc_msm_pippenger_g1(pts, ss, n, out)
    return _g1_parse(out.raw)


def msm_g2_single(points, scalars, force_pippenger=False):
    """G2 variant of msm_g1_single."""
    n = len(points)
    if n == 0:
        return None
    lib = load()
    if n < PIPPENGER_MIN and not force_pippenger:
        return CppBackend().msm_g2_distinct([list(points)], [list(scalars)])[0]
    pts = b"".join(_g2_bytes(p) for p in points)
    ss = b"".join((int(s) % R).to_bytes(32, "little") for s in scalars)
    out = ctypes.create_string_buffer(192)
    lib.cc_msm_pippenger_g2(pts, ss, n, out)
    return _g2_parse(out.raw)


# --- native sss (secret_sharing crate surface: Polynomial/Lagrange/Shamir,
# reference keygen.rs:58,248, signature.rs:460,502) --------------------------


def _id_u32(v, what="signer id"):
    """The C ABI carries ids/eval points as uint32 — reject anything that
    would silently wrap (sss.py accepts arbitrary ints; callers with wider
    ids must use the Python module)."""
    from .errors import GeneralError

    v = int(v)
    if not 0 <= v < 1 << 32:
        raise GeneralError(
            "%s %d outside the native uint32 range; use coconut_tpu.sss"
            % (what, v)
        )
    return v


def lagrange_basis_at_0(ids, my_id):
    """Native l_{my_id}(0) over `ids`, bit-identical to
    sss.lagrange_basis_at_0 (same GeneralError contract)."""
    from .errors import GeneralError

    lib = load()
    ids = sorted({_id_u32(i) for i in ids})
    arr = (ctypes.c_uint32 * len(ids))(*ids)
    out = ctypes.create_string_buffer(32)
    rc = lib.cc_fr_lagrange_basis_at_0(arr, len(ids), _id_u32(my_id), out)
    if rc == 1:
        raise GeneralError("id %d not in interpolation set" % my_id)
    if rc:
        raise GeneralError("signer ids must be nonzero (1-based)")
    return int.from_bytes(out.raw, "little")


def poly_eval(coeffs, x):
    """Native Horner evaluation in Fr (the Shamir share map)."""
    lib = load()
    cb = b"".join((int(c) % R).to_bytes(32, "little") for c in coeffs)
    out = ctypes.create_string_buffer(32)
    lib.cc_fr_poly_eval(cb, len(coeffs), _id_u32(x, "eval point"), out)
    return int.from_bytes(out.raw, "little")


def reconstruct_secret(threshold, shares):
    """Native Lagrange interpolation at 0, same semantics (and GeneralError
    contract) as sss.reconstruct_secret (first `threshold` shares by id)."""
    from .errors import GeneralError

    if len(shares) < threshold:
        raise GeneralError(
            "need %d shares to reconstruct, got %d" % (threshold, len(shares))
        )
    lib = load()
    use = sorted(shares.items())[:threshold]
    ids = (ctypes.c_uint32 * threshold)(
        *[_id_u32(i) for i, _ in use]
    )
    sb = b"".join((int(s) % R).to_bytes(32, "little") for _, s in use)
    out = ctypes.create_string_buffer(32)
    rc = lib.cc_fr_reconstruct(ids, sb, threshold, out)
    if rc:
        raise GeneralError("invalid share ids")
    return int.from_bytes(out.raw, "little")


# --- native Pedersen VSS / DVSS (finishes the secret_sharing rebuild
# target: reference keygen.rs:74-205; differential tests vs sss.py in
# tests/test_backends.py) ----------------------------------------------------


def rand_fr():
    """Native uniform Fr from OS entropy (FieldElement::random surface)."""
    lib = load()
    out = ctypes.create_string_buffer(32)
    if lib.cc_fr_random(out):
        raise RuntimeError("native entropy source failed")
    return int.from_bytes(out.raw, "little")


def pedersen_deal_from_coeffs(threshold, total, g, h, f_coeffs, g_coeffs):
    """Native Pedersen deal from given polynomial coefficients: returns
    (comm_coeffs {j: point}, s_shares {id: int}, t_shares {id: int}).
    Bit-identical to the sss.py math on the same coefficients."""
    from .errors import GeneralError

    if not 0 < threshold <= total:
        raise GeneralError(
            "invalid threshold %d for total %d" % (threshold, total)
        )
    if len(f_coeffs) != threshold or len(g_coeffs) != threshold:
        raise GeneralError(
            "need %d coefficients per polynomial, got %d and %d"
            % (threshold, len(f_coeffs), len(g_coeffs))
        )
    lib = load()
    fc = b"".join(_scalar_bytes(c) for c in f_coeffs)
    gc = b"".join(_scalar_bytes(c) for c in g_coeffs)
    comms = ctypes.create_string_buffer(96 * threshold)
    ss = ctypes.create_string_buffer(32 * total)
    ts = ctypes.create_string_buffer(32 * total)
    lib.cc_pedersen_deal_from_coeffs(
        threshold, total, _g1_bytes(g), _g1_bytes(h), fc, gc, comms, ss, ts
    )
    comm_coeffs = {
        j: _g1_parse(comms.raw[j * 96 : (j + 1) * 96])
        for j in range(threshold)
    }
    s_shares = {
        i: int.from_bytes(ss.raw[(i - 1) * 32 : i * 32], "little")
        for i in range(1, total + 1)
    }
    t_shares = {
        i: int.from_bytes(ts.raw[(i - 1) * 32 : i * 32], "little")
        for i in range(1, total + 1)
    }
    return comm_coeffs, s_shares, t_shares


def pedersen_deal(threshold, total, g, h):
    """Native PedersenVSS::deal (keygen.rs:93-94): fresh random polynomials
    from native entropy. Returns (secret, blind_secret, comm_coeffs,
    s_shares, t_shares) — the sss.PedersenVSS.deal tuple."""
    from .errors import GeneralError

    if not 0 < threshold <= total:
        raise GeneralError(
            "invalid threshold %d for total %d" % (threshold, total)
        )
    lib = load()
    fc = ctypes.create_string_buffer(32 * threshold)
    gc = ctypes.create_string_buffer(32 * threshold)
    comms = ctypes.create_string_buffer(96 * threshold)
    ss = ctypes.create_string_buffer(32 * total)
    ts = ctypes.create_string_buffer(32 * total)
    if lib.cc_pedersen_deal(
        threshold, total, _g1_bytes(g), _g1_bytes(h), fc, gc, comms, ss, ts
    ):
        raise RuntimeError("native entropy source failed")
    comm_coeffs = {
        j: _g1_parse(comms.raw[j * 96 : (j + 1) * 96])
        for j in range(threshold)
    }
    s_shares = {
        i: int.from_bytes(ss.raw[(i - 1) * 32 : i * 32], "little")
        for i in range(1, total + 1)
    }
    t_shares = {
        i: int.from_bytes(ts.raw[(i - 1) * 32 : i * 32], "little")
        for i in range(1, total + 1)
    }
    secret = int.from_bytes(fc.raw[:32], "little")
    blind = int.from_bytes(gc.raw[:32], "little")
    return secret, blind, comm_coeffs, s_shares, t_shares


def pedersen_verify_share(threshold, share_id, share, comm_coeffs, g, h):
    """Native PedersenVSS::verify_share (keygen.rs:334-351)."""
    lib = load()
    s, t = share
    comms = b"".join(
        _g1_bytes(comm_coeffs[j]) for j in range(threshold)
    )
    return bool(
        lib.cc_pedersen_verify_share(
            threshold,
            _id_u32(share_id),
            _scalar_bytes(s),
            _scalar_bytes(t),
            comms,
            _g1_bytes(g),
            _g1_bytes(h),
        )
    )


class DvssParticipant:
    """Native DVSS participant (reference PedersenDVSSParticipant surface,
    keygen.rs:136-162): the dealing, share verification, and combining run
    in C++; the protocol driver stays host-side like the reference's.

    Mirrors sss.PedersenDVSSParticipant's attribute surface so the two are
    interchangeable in the keygen drivers and differential tests."""

    def __init__(self, participant_id, threshold, total, g, h):
        from .errors import GeneralError

        lib = load()
        self._lib = lib
        self.id = _id_u32(participant_id)
        self.threshold = threshold
        self.total = total
        self._h = lib.cc_dvss_new(
            self.id, threshold, total, _g1_bytes(g), _g1_bytes(h)
        )
        if not self._h:
            raise GeneralError(
                "invalid DVSS parameters id=%d t=%d n=%d"
                % (participant_id, threshold, total)
            )
        comms = ctypes.create_string_buffer(96 * threshold)
        ss = ctypes.create_string_buffer(32 * total)
        ts = ctypes.create_string_buffer(32 * total)
        lib.cc_dvss_deal(self._h, comms, ss, ts)
        self.comm_coeffs = {
            j: _g1_parse(comms.raw[j * 96 : (j + 1) * 96])
            for j in range(threshold)
        }
        self.s_shares = {
            i: int.from_bytes(ss.raw[(i - 1) * 32 : i * 32], "little")
            for i in range(1, total + 1)
        }
        self.t_shares = {
            i: int.from_bytes(ts.raw[(i - 1) * 32 : i * 32], "little")
            for i in range(1, total + 1)
        }
        self.secret_share = None
        self.t_secret_share = None
        self.final_comm_coeffs = None

    def received_share(self, from_id, comm_coeffs, share, threshold=None,
                       total=None, g=None, h=None):
        """Verify and store a share of `from_id`'s secret (the extra args
        of the sss.py surface are carried by the native handle)."""
        from .errors import GeneralError

        s, t = share
        comms = b"".join(
            _g1_bytes(comm_coeffs[j]) for j in range(self.threshold)
        )
        rc = self._lib.cc_dvss_receive(
            self._h,
            _id_u32(from_id),
            comms,
            _scalar_bytes(s),
            _scalar_bytes(t),
        )
        if rc == 1:
            raise GeneralError(
                "participant %d received its own share" % self.id
            )
        if rc == 2:
            raise GeneralError("participant id %d out of range" % from_id)
        if rc == 3:
            raise GeneralError(
                "participant %d already has a share from %d"
                % (self.id, from_id)
            )
        if rc:
            raise GeneralError(
                "share from participant %d failed verification at %d"
                % (from_id, self.id)
            )

    def compute_final_comm_coeffs_and_shares(self, threshold=None,
                                             total=None, g=None, h=None):
        from .errors import GeneralError

        s32 = ctypes.create_string_buffer(32)
        t32 = ctypes.create_string_buffer(32)
        comms = ctypes.create_string_buffer(96 * self.threshold)
        rc = self._lib.cc_dvss_finalize(self._h, s32, t32, comms)
        if rc:
            raise GeneralError(
                "participant %d is missing pairwise shares" % self.id
            )
        self.secret_share = int.from_bytes(s32.raw, "little")
        self.t_secret_share = int.from_bytes(t32.raw, "little")
        self.final_comm_coeffs = {
            j: _g1_parse(comms.raw[j * 96 : (j + 1) * 96])
            for j in range(self.threshold)
        }

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.cc_dvss_free(h)
            self._h = None


def share_secret_dvss(threshold, total, g, h):
    """Native-participant version of sss.share_secret_dvss: the full
    dealerless 3-round protocol simulated in-process (keygen.rs:126-165)."""
    participants = [
        DvssParticipant(i, threshold, total, g, h)
        for i in range(1, total + 1)
    ]
    for recv in participants:
        for sender in participants:
            if sender.id == recv.id:
                continue
            recv.received_share(
                sender.id,
                sender.comm_coeffs,
                (sender.s_shares[recv.id], sender.t_shares[recv.id]),
            )
    for p in participants:
        p.compute_final_comm_coeffs_and_shares()
    return participants


def derive_params(msg_count, label):
    """Params derivation entirely through the native core (the reference's
    Params::new, signature.rs:22-32, with amcl's from_msg_hash replaced by
    cc_hash_to_g1/g2): returns (g, g_tilde, h list) as spec point tuples
    for the default SIGNATURES_IN_G1 assignment."""
    g = hash_to_g1(bytes(label) + b" : g")
    g_tilde = hash_to_g2(bytes(label) + b" : g_tilde")
    hs = [
        hash_to_g1(bytes(label) + (" : y%d" % i).encode())
        for i in range(msg_count)
    ]
    return g, g_tilde, hs


# --- codec (ints <-> the C ABI byte layout) ---------------------------------


def _fp_bytes(x):
    return int(x).to_bytes(48, "little")


def _g1_bytes(p):
    if p is None:
        return b"\x00" * 96
    return _fp_bytes(p[0]) + _fp_bytes(p[1])


def _g2_bytes(p):
    if p is None:
        return b"\x00" * 192
    (x0, x1), (y0, y1) = p
    return _fp_bytes(x0) + _fp_bytes(x1) + _fp_bytes(y0) + _fp_bytes(y1)


def _g1_parse(b):
    if not any(b):
        return None
    return (
        int.from_bytes(b[:48], "little"),
        int.from_bytes(b[48:96], "little"),
    )


def _g2_parse(b):
    if not any(b):
        return None
    vals = [int.from_bytes(b[i * 48 : (i + 1) * 48], "little") for i in range(4)]
    return ((vals[0], vals[1]), (vals[2], vals[3]))


def _scalar_bytes(s):
    return (int(s) % R).to_bytes(32, "little")


class CppBackend(CurveBackend):
    """Native C++ batched backend (the CPU baseline)."""

    name = "cpp"

    def __init__(self, ct=False):
        self._lib = load()
        self._ct = 1 if ct else 0

    def msm_g1_shared(self, bases, scalars_batch):
        k = len(bases)
        B = len(scalars_batch)
        bb = b"".join(_g1_bytes(p) for p in bases)
        sb = b"".join(
            _scalar_bytes(s) for row in scalars_batch for s in row
        )
        out = ctypes.create_string_buffer(96 * B)
        self._lib.cc_msm_g1(bb, sb, k, B, out, self._ct)
        return [_g1_parse(out.raw[i * 96 : (i + 1) * 96]) for i in range(B)]

    def msm_g2_shared(self, bases, scalars_batch):
        k = len(bases)
        B = len(scalars_batch)
        bb = b"".join(_g2_bytes(p) for p in bases)
        sb = b"".join(
            _scalar_bytes(s) for row in scalars_batch for s in row
        )
        out = ctypes.create_string_buffer(192 * B)
        self._lib.cc_msm_g2(bb, sb, k, B, out, self._ct)
        return [_g2_parse(out.raw[i * 192 : (i + 1) * 192]) for i in range(B)]

    def msm_g1_distinct(self, points_batch, scalars_batch):
        # per-row bases: each row is a size-k shared-base MSM with B=1
        return [
            self.msm_g1_shared(pts, [row])[0]
            for pts, row in zip(points_batch, scalars_batch)
        ]

    def msm_g2_distinct(self, points_batch, scalars_batch):
        return [
            self.msm_g2_shared(pts, [row])[0]
            for pts, row in zip(points_batch, scalars_batch)
        ]

    def pairing_product_is_one(self, pairs_batch):
        B = len(pairs_batch)
        n = len(pairs_batch[0]) if B else 0
        if any(len(row) != n for row in pairs_batch):
            raise ValueError("ragged pairing batch")
        pb = b"".join(_g1_bytes(p) for row in pairs_batch for p, _ in row)
        qb = b"".join(_g2_bytes(q) for row in pairs_batch for _, q in row)
        out = ctypes.create_string_buffer(B)
        self._lib.cc_pairing_product_is_one(pb, qb, n, B, out)
        return [bool(out.raw[i]) for i in range(B)]


def available():
    """True if the native backend can load (build tools + source present)."""
    try:
        load()
        return True
    except Exception:
        return False


register_backend("cpp", CppBackend)
