"""Request-scoped tracing: spans, a thread-safe tracer, and contextvar
propagation — the Dapper-style complement to metrics.py's aggregates.

metrics.py answers "how many retries happened this run"; this module
answers "what happened to THAT request": every admitted serve request (and
every streamed batch) gets a trace — a tree of timed spans — so a
credential that survives a retry->fallback->bisection ladder before
dead-lettering leaves a joinable record of exactly that path.

Design constraints, in order:

  - ZERO-COST WHEN OFF (the default): every entry point first checks the
    module-level `_tracer is None` and returns the shared `NOOP` span —
    no Span is ever allocated, no lock taken, no clock read. The serve
    and bench hot paths run with tracing off unless `COCONUT_TRACE=1`.
  - BOUNDED MEMORY: finished spans land in a ring buffer
    (`COCONUT_TRACE_RING`, default 4096) — a million-request run retains
    the most recent few thousand spans, kilobytes not gigabytes. The
    flight recorder (obs/flight.py) exists precisely because the ring
    forgets: it dumps a request's tree at the moment of failure.
  - INJECTABLE CLOCK: `enable(clock=...)` takes any monotonic callable,
    so span durations are testable exactly with a fake clock and zero
    real sleeps (the same discipline serve/queue.py uses).
  - CROSS-THREAD TREES: propagation inside one thread rides a
    contextvar (`span()` activates, nested spans parent automatically);
    across threads — a request admitted on a client thread, batched on
    the supervisor — the span object itself is handed over and re-entered
    with `use()`. Spans are safe to start/annotate/end from any thread.

Span taxonomy (README "Observability" for the glossary):

  per-request trace:  request            admission -> verdict (root)
                        queue_wait       admission -> popped into a batch
  per-batch trace:    batch | stream_batch   (root; links member traces
                                              via the members attr, and
                                              each request span carries
                                              batch_trace back)
                        coalesce         pad/assemble the device batch
                        dispatch         host encode + device dispatch
                        device           blocking wait on the device
                        demux            verdict bits -> futures
                        bisect           grouped-failure culprit isolation

  events (timestamped points on a span): retry / attempt_failed /
  fallback (retry.py ladder), split (each bisection halving),
  dead_letter, pad_lanes, checkpoint.

  Against the serve dispatcher pool, "batch" and "dispatch"/"device"
  spans carry `device` (the executor label: "0".."N-1" or "mesh") and
  the batch root carries `placement` ("single" | "sharded") — so a
  dead-lettered request's span tree names the device that rejected it
  and which side of the adaptive routing policy its batch took.

`metrics.snapshot()` gains a "trace_stages" section while tracing is
enabled (per-span-name count/total/mean — the queue-wait vs coalesce vs
encode vs device vs demux breakdown), via metrics' provider hook so the
two modules stay decoupled.
"""

import contextvars
import itertools
import os
import threading
import time
from collections import deque

#: env knobs: COCONUT_TRACE=1 enables at import; COCONUT_TRACE_RING sizes
#: the finished-span ring buffer
ENV_FLAG = "COCONUT_TRACE"
ENV_RING = "COCONUT_TRACE_RING"
DEFAULT_RING = 4096

_FALSY = ("", "0", "false", "off", "no")


class _NoopSpan:
    """The shared do-nothing span every entry point returns while tracing
    is disabled. One module-level instance, no per-call allocation; every
    method is a no-op, it is falsy, and it nests as a context manager
    without touching the contextvar."""

    __slots__ = ()
    name = None
    trace_id = None
    span_id = None
    parent_id = None
    t0 = None
    t1 = None

    def __bool__(self):
        return False

    def set(self, **attrs):
        return self

    def event(self, name, **attrs):
        return self

    def end(self, **attrs):
        return self

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


NOOP = _NoopSpan()


class Span:
    """One timed operation in a trace tree.

    Starts at construction (via Tracer.start), ends exactly once via
    `end()` (idempotent — a defensive second end is ignored, so sweep
    paths can close spans unconditionally). `set()` merges attributes,
    `event()` records a timestamped point annotation. Entering a Span as
    a context manager activates it on the current context (nested
    `span()` calls parent under it) and ends it on exit, recording an
    `error` attribute if the body raised."""

    __slots__ = (
        "name",
        "trace_id",
        "span_id",
        "parent_id",
        "t0",
        "t1",
        "tid",
        "attrs",
        "events",
        "_tracer",
        "_token",
    )

    def __init__(self, tracer, name, trace_id, span_id, parent_id, t0):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.t0 = t0
        self.t1 = None
        self.tid = threading.get_ident()
        self.attrs = {}
        self.events = []
        self._tracer = tracer
        self._token = None

    @property
    def dur(self):
        """Span duration in seconds (None while still live)."""
        return None if self.t1 is None else self.t1 - self.t0

    def set(self, **attrs):
        with self._tracer._lock:
            self.attrs.update(attrs)
        return self

    def event(self, name, **attrs):
        """Record a timestamped point annotation (retry, split, ...)."""
        t = self._tracer
        with t._lock:
            self.events.append({"ts": t._clock(), "name": name, **attrs})
        return self

    def end(self, **attrs):
        """Finish the span: stamp t1, move it from the live set to the
        ring buffer, fold its duration into the per-stage totals.
        Idempotent — only the first end() sticks."""
        t = self._tracer
        with t._lock:
            if self.t1 is not None:
                return self
            if attrs:
                self.attrs.update(attrs)
            self.t1 = t._clock()
            t._live.pop(self.span_id, None)
            t._ring.append(self)
            agg = t._stages.get(self.name)
            if agg is None:
                agg = t._stages[self.name] = [0, 0.0]
            agg[0] += 1
            agg[1] += self.t1 - self.t0
        return self

    def to_dict(self):
        """JSON-ready record (the JSONL export / flight-recorder shape)."""
        with self._tracer._lock:
            return {
                "name": self.name,
                "trace_id": self.trace_id,
                "span_id": self.span_id,
                "parent_id": self.parent_id,
                "t0": self.t0,
                "dur": self.dur,
                "tid": self.tid,
                "attrs": dict(self.attrs),
                "events": list(self.events),
            }

    # -- context-manager activation ------------------------------------------

    def __enter__(self):
        self._token = _current.set(self)
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._token is not None:
            _current.reset(self._token)
            self._token = None
        if exc_type is not None:
            self.end(error=exc_type.__name__)
        else:
            self.end()
        return False


class Tracer:
    """Thread-safe span factory + bounded ring buffer of finished spans.

    One RLock guards id allocation, the live-span table, the ring, and
    the per-stage aggregates — span operations are short critical
    sections, never user code under the lock."""

    def __init__(self, clock=time.monotonic, ring=DEFAULT_RING):
        self._lock = threading.RLock()
        self._clock = clock
        self._ring = deque(maxlen=max(1, int(ring)))
        self._live = {}  # span_id -> Span
        self._span_ids = itertools.count(1)
        self._trace_ids = itertools.count(1)
        self._stages = {}  # span name -> [count, total_seconds]

    def start(self, name, parent=None, trace_id=None, attrs=None):
        """Create a live span. parent=None with no trace_id starts a new
        trace (a root span); a parent Span propagates its trace."""
        with self._lock:
            if parent is not None and parent.trace_id is not None:
                tid = parent.trace_id
                pid = parent.span_id
            else:
                tid = trace_id or "t%08x" % next(self._trace_ids)
                pid = None
            span = Span(self, name, tid, next(self._span_ids), pid, self._clock())
            self._live[span.span_id] = span
            if attrs:
                span.attrs.update(attrs)
            return span

    # -- readout -------------------------------------------------------------

    def tail(self, n=None):
        """The most recent finished spans, oldest first (whole ring when
        n is None)."""
        with self._lock:
            spans = list(self._ring)
        return spans if n is None else spans[-n:]

    def live_snapshot(self):
        """Spans started but not yet ended, in start order."""
        with self._lock:
            return sorted(self._live.values(), key=lambda s: s.span_id)

    def spans_for(self, trace_id, follow_links=True):
        """Every retained span (finished + live) of `trace_id`, in
        span_id order. With follow_links, traces referenced by a
        `batch_trace` attribute (the request->batch join the serve layer
        records) are included — the "full span tree" a flight-recorder
        dump wants."""
        if trace_id is None:
            return []
        with self._lock:
            universe = list(self._ring) + list(self._live.values())
        wanted = {trace_id}
        out = [s for s in universe if s.trace_id in wanted]
        if follow_links:
            linked = {
                s.attrs.get("batch_trace")
                for s in out
                if s.attrs.get("batch_trace")
            } - wanted
            if linked:
                wanted |= linked
                out = [s for s in universe if s.trace_id in wanted]
        return sorted(out, key=lambda s: s.span_id)

    def stage_summary(self):
        """{span name: {count, total_s, mean_s}} over every FINISHED span
        — the per-stage breakdown metrics.snapshot() embeds while tracing
        is on (queue_wait / coalesce / dispatch / device / demux)."""
        with self._lock:
            return {
                name: {
                    "count": c,
                    "total_s": round(tot, 6),
                    "mean_s": round(tot / c, 6) if c else None,
                }
                for name, (c, tot) in sorted(self._stages.items())
            }


# -- module-level switchboard (the instrumented seams call these) ------------

_tracer = None
_current = contextvars.ContextVar("coconut_trace_span", default=None)


def enabled():
    return _tracer is not None


def get_tracer():
    """The installed Tracer, or None while tracing is disabled."""
    return _tracer


def enable(clock=time.monotonic, ring=None, tracer=None):
    """Install a (new) global tracer and register the per-stage breakdown
    with metrics.snapshot(). Returns the tracer. Re-enabling replaces the
    previous tracer (fresh ring, fresh ids)."""
    global _tracer
    if ring is None:
        ring = int(os.environ.get(ENV_RING, str(DEFAULT_RING)))
    _tracer = tracer if tracer is not None else Tracer(clock=clock, ring=ring)
    from .. import metrics

    metrics.register_provider(
        "trace_stages", lambda: _tracer.stage_summary() if _tracer else {}
    )
    return _tracer


def disable():
    """Back to the zero-cost no-op path; drops the tracer and its ring."""
    global _tracer
    _tracer = None
    from .. import metrics

    metrics.unregister_provider("trace_stages")


def start_span(name, parent=None, root=False, **attrs):
    """Create a live span WITHOUT activating it on the current context —
    the cross-thread form (the serve queue starts a request's span on the
    client thread; the supervisor ends it after demux). Parent resolution:
    explicit `parent` wins; `root=True` forces a new trace; otherwise the
    context-active span (if any) is the parent. Returns NOOP when
    tracing is disabled."""
    t = _tracer
    if t is None:
        return NOOP
    if parent is None and not root:
        parent = _current.get()
    if parent is NOOP or (parent is not None and parent.trace_id is None):
        parent = None
    return t.start(name, parent=parent, attrs=attrs or None)


def span(name, parent=None, root=False, **attrs):
    """`with span("dispatch"): ...` — start + activate + end-on-exit.
    The no-op singleton when tracing is disabled."""
    return start_span(name, parent=parent, root=root, **attrs)


class _Use:
    """Activate an EXISTING span on the current context without ending it
    on exit — how the supervisor re-enters a batch span it created during
    launch when it later settles the batch."""

    __slots__ = ("_span", "_token")

    def __init__(self, s):
        self._span = s
        self._token = None

    def __enter__(self):
        if self._span is not None and self._span is not NOOP:
            self._token = _current.set(self._span)
        return self._span

    def __exit__(self, exc_type, exc, tb):
        if self._token is not None:
            _current.reset(self._token)
            self._token = None
        return False


def use(s):
    """Context manager: make `s` the current span without owning its
    lifetime (no-op for None / NOOP)."""
    return _Use(s)


def current():
    """The context-active Span, or None (never NOOP)."""
    s = _current.get()
    return None if s is NOOP else s


def event(name, **attrs):
    """Record a timestamped event on the context-active span, if any —
    the retry ladder's hook: zero-cost when tracing is off or nothing is
    active."""
    if _tracer is None:
        return
    s = _current.get()
    if s is not None and s is not NOOP:
        s.event(name, **attrs)


def end_span(s, **attrs):
    """End a span defensively (None / NOOP / already-ended all safe)."""
    if s is not None and s is not NOOP:
        s.end(**attrs)


def _env_enabled(value):
    """COCONUT_TRACE parse: unset/0/false/off/no -> disabled."""
    return value is not None and value.strip().lower() not in _FALSY


if _env_enabled(os.environ.get(ENV_FLAG)):  # pragma: no cover - env-driven
    enable()
