"""Observability: request-scoped tracing, trace export, and the fault
flight recorder.

  trace.py   Span/Tracer — contextvar propagation, injectable clock,
             bounded ring buffer, zero-cost no-op path when disabled
             (COCONUT_TRACE=0, the default)
  export.py  JSONL span records + Chrome-trace/Perfetto JSON
  flight.py  on dead-letter / checkpoint quarantine, dump the failing
             request's span tree + the recent-span tail to a JSONL next
             to the triggering artifact

metrics.py stays the aggregate surface (counters/timers/histograms);
this package is the per-request one. See README "Observability" for the
span taxonomy and knobs.
"""

from . import export, flight, trace  # noqa: F401
from .trace import (  # noqa: F401
    NOOP,
    Span,
    Tracer,
    current,
    disable,
    enable,
    enabled,
    end_span,
    event,
    get_tracer,
    span,
    start_span,
    use,
)

__all__ = [
    "trace",
    "export",
    "flight",
    "Span",
    "Tracer",
    "NOOP",
    "enable",
    "disable",
    "enabled",
    "get_tracer",
    "span",
    "start_span",
    "use",
    "current",
    "event",
    "end_span",
]
