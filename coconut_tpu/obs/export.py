"""Trace export: JSONL span records and Chrome-trace/Perfetto JSON.

Two serializations of the same ring buffer, for two consumers:

  - JSONL (`write_jsonl`): one span object per line, the same schema the
    flight recorder embeds — greppable, streamable, joins against the
    dead-letter log on trace_id.
  - Chrome trace events (`write_chrome`): the `{"traceEvents": [...]}`
    format Perfetto (https://ui.perfetto.dev) and chrome://tracing open
    directly. Spans become complete ("ph": "X") events with microsecond
    ts/dur; span events become instant ("ph": "i") events on the same
    thread track, so a retry or bisection split shows up as a tick inside
    its span. Drop the file next to the `BENCH_PROFILE=1` device trace
    and the host-side request timeline reads alongside the XLA one.

Span args carry trace_id/span_id/parent_id, so tooling (and
probes/probe_trace.py, the CI validator) can rebuild the tree: events are
sorted by ts, and within one parent the children's summed dur never
exceeds the parent's dur (children are sequential stages of their
parent's lifetime).
"""

import json

from . import trace as _trace

_US = 1e6  # chrome trace events are denominated in microseconds


def span_records(spans):
    """JSON-ready dicts for Span objects (dicts pass through), t0 order."""
    recs = [s if isinstance(s, dict) else s.to_dict() for s in spans]
    return sorted(recs, key=lambda r: (r["t0"], r["span_id"]))


def write_jsonl(spans, path):
    """One span record per line; returns the record count."""
    recs = span_records(spans)
    # lint: allow(durability, on-demand trace export artifact - rewritten
    # whole per call, nothing re-reads it across a crash)
    with open(path, "w") as f:
        for rec in recs:
            f.write(json.dumps(rec, sort_keys=True) + "\n")
    return len(recs)


def read_jsonl(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def chrome_events(spans, pid=1):
    """Chrome trace_events list for finished spans: one "X" (complete)
    event per span plus one "i" (instant) event per span event, sorted by
    ts so the stream is monotonic. Live (unfinished) spans are skipped —
    an X event needs a dur."""
    events = []
    for rec in span_records(spans):
        if rec["dur"] is None:
            continue
        ts = rec["t0"] * _US
        events.append(
            {
                "name": rec["name"],
                "ph": "X",
                "ts": ts,
                "dur": rec["dur"] * _US,
                "pid": pid,
                "tid": rec["tid"],
                "args": {
                    "trace_id": rec["trace_id"],
                    "span_id": rec["span_id"],
                    "parent_id": rec["parent_id"],
                    **rec["attrs"],
                },
            }
        )
        for ev in rec["events"]:
            ev = dict(ev)
            events.append(
                {
                    "name": "%s.%s" % (rec["name"], ev.pop("name")),
                    "ph": "i",
                    "ts": ev.pop("ts") * _US,
                    "s": "t",
                    "pid": pid,
                    "tid": rec["tid"],
                    "args": {"trace_id": rec["trace_id"], **ev},
                }
            )
    events.sort(key=lambda e: e["ts"])
    return events


def write_chrome(spans, path, pid=1):
    """Write the Perfetto-loadable JSON document; returns the event
    count."""
    events = chrome_events(spans, pid=pid)
    # lint: allow(durability, on-demand trace export artifact - rewritten
    # whole per call, nothing re-reads it across a crash)
    with open(path, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return len(events)


def export_chrome(path, tracer=None, pid=1):
    """Dump the (global) tracer's finished-span ring as Chrome trace JSON;
    returns the event count (0, writing an empty-but-valid document, when
    tracing is disabled)."""
    tracer = tracer if tracer is not None else _trace.get_tracer()
    spans = tracer.tail() if tracer is not None else []
    return write_chrome(spans, path, pid=pid)


def export_jsonl(path, tracer=None):
    """Dump the (global) tracer's finished-span ring as JSONL; returns
    the record count."""
    tracer = tracer if tracer is not None else _trace.get_tracer()
    spans = tracer.tail() if tracer is not None else []
    return write_jsonl(spans, path)
