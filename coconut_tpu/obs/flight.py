"""Fault flight recorder: dump the trace context of a failure the moment
it happens.

The tracer's ring buffer forgets (bounded memory is the point), so by the
time an operator greps a dead-letter line the spans that explain it may
be gone. This module closes that gap: on a dead-letter append or a
checkpoint quarantine, `record()` writes ONE JSONL line holding

  - the failing request's FULL span tree (finished + still-live spans of
    its trace, plus the batch trace linked via the `batch_trace`
    attribute — the request->batch join the serve layer records), and
  - the last `last_n` completed spans overall (what the system was doing
    just before the fault — the classic flight-recorder tail),

to `<base>.flight.jsonl` next to the artifact that triggered it (the
dead-letter log, the checkpoint file). Joining back is one grep: the
dead-letter line and the flight line share the trace_id.

Zero-cost when tracing is disabled: `record()` returns None without
touching the filesystem. Failures to WRITE the flight record are
swallowed (`flight_write_errors` counter) — the recorder must never turn
a handled fault into a crash.

BOUNDED ON DISK: both this file and the dead-letter JSONL it rides next
to are written by fault paths, and a sustained fault storm must not fill
the disk. `rotate_if_needed()` implements size/record-count JSONL
rotation (`<path>.1` newest rotated, up to `<path>.<keep>`); the flight
recorder applies it with `FLIGHT_MAX_BYTES`/`FLIGHT_KEEP`, and
faults.DeadLetterLog calls the same helper with its own knobs — one
rotation discipline for every append-only fault artifact.
"""

import json
import os
import time

from .. import metrics
from . import trace as _trace
from .export import span_records

FLIGHT_SCHEMA = 1

#: completed-span tail included in every flight record
DEFAULT_LAST_N = 64

#: per-file size cap before a flight/dead-letter JSONL rotates, and how
#: many rotated generations (`<path>.1` .. `<path>.<keep>`) are retained
FLIGHT_MAX_BYTES = 64 * 1024 * 1024
FLIGHT_KEEP = 3


def flight_path(base_path):
    """The flight-recorder file that rides next to `base_path`."""
    return "%s.flight.jsonl" % (base_path,)


def rotate_if_needed(
    path, max_bytes=None, max_records=None, keep=FLIGHT_KEEP, record_count=None
):
    """Rotate `path` aside (`path` -> `path.1` -> ... -> `path.keep`,
    oldest dropped) when it has reached `max_bytes` or `max_records`
    lines; call BEFORE appending. `record_count` lets a caller that
    already tracks its line count skip the O(file) recount. Returns True
    iff a rotation happened. None caps disable that check; rotation
    errors are swallowed (a full-disk fault path must not crash its
    handler) under the "rotation_errors" counter."""
    try:
        if keep < 1 or not os.path.exists(path):
            return False
        need = (
            max_bytes is not None and os.path.getsize(path) >= max_bytes
        )
        if not need and max_records is not None:
            if record_count is None:
                with open(path, "rb") as f:
                    record_count = sum(1 for line in f if line.strip())
            need = record_count >= max_records
        if not need:
            return False
        for i in range(keep - 1, 0, -1):
            older = "%s.%d" % (path, i)
            if os.path.exists(older):
                os.replace(older, "%s.%d" % (path, i + 1))
        os.replace(path, "%s.1" % (path,))
        metrics.count("rotations")
        return True
    except OSError:
        metrics.count("rotation_errors")
        return False


def record(base_path, reason, trace_id=None, extra=None, last_n=DEFAULT_LAST_N):
    """Append one flight record next to `base_path`; returns the record
    (None when tracing is disabled or base_path is falsy)."""
    tracer = _trace.get_tracer()
    if tracer is None or not base_path:
        return None
    tree = tracer.spans_for(trace_id) if trace_id is not None else []
    rec = {
        "schema": FLIGHT_SCHEMA,
        "wall_time": time.time(),
        "reason": reason,
        "trace_id": trace_id,
        "tree": span_records(tree),
        "recent": span_records(tracer.tail(last_n)),
    }
    if extra:
        rec.update(extra)
    rotate_if_needed(
        flight_path(base_path), max_bytes=FLIGHT_MAX_BYTES, keep=FLIGHT_KEEP
    )
    try:
        # lint: allow(durability, best-effort append-only observability
        # artifact; read() skips+counts a torn tail)
        with open(flight_path(base_path), "a") as f:
            f.write(json.dumps(rec, sort_keys=True) + "\n")
    except OSError:
        metrics.count("flight_write_errors")
        return None
    metrics.count("flight_records")
    return rec


def read(path):
    """All flight records in `path` (empty list if it does not exist) —
    accepts either the base path or the .flight.jsonl path itself.
    Torn-tail tolerant like DeadLetterLog.read: a crash mid-append can
    truncate the final line; skip it (counted under
    "flight_torn_lines") instead of poisoning every later read."""
    import os

    if not path.endswith(".flight.jsonl"):
        path = flight_path(path)
    if not os.path.exists(path):
        return []
    recs = []
    torn = 0
    with open(path) as f:
        for line in f:
            if not line.strip():
                continue
            try:
                recs.append(json.loads(line))
            except ValueError:
                torn += 1
    if torn:
        metrics.count("flight_torn_lines", torn)
    return recs
