"""Fault flight recorder: dump the trace context of a failure the moment
it happens.

The tracer's ring buffer forgets (bounded memory is the point), so by the
time an operator greps a dead-letter line the spans that explain it may
be gone. This module closes that gap: on a dead-letter append or a
checkpoint quarantine, `record()` writes ONE JSONL line holding

  - the failing request's FULL span tree (finished + still-live spans of
    its trace, plus the batch trace linked via the `batch_trace`
    attribute — the request->batch join the serve layer records), and
  - the last `last_n` completed spans overall (what the system was doing
    just before the fault — the classic flight-recorder tail),

to `<base>.flight.jsonl` next to the artifact that triggered it (the
dead-letter log, the checkpoint file). Joining back is one grep: the
dead-letter line and the flight line share the trace_id.

Zero-cost when tracing is disabled: `record()` returns None without
touching the filesystem. Failures to WRITE the flight record are
swallowed (`flight_write_errors` counter) — the recorder must never turn
a handled fault into a crash.
"""

import json
import time

from .. import metrics
from . import trace as _trace
from .export import span_records

FLIGHT_SCHEMA = 1

#: completed-span tail included in every flight record
DEFAULT_LAST_N = 64


def flight_path(base_path):
    """The flight-recorder file that rides next to `base_path`."""
    return "%s.flight.jsonl" % (base_path,)


def record(base_path, reason, trace_id=None, extra=None, last_n=DEFAULT_LAST_N):
    """Append one flight record next to `base_path`; returns the record
    (None when tracing is disabled or base_path is falsy)."""
    tracer = _trace.get_tracer()
    if tracer is None or not base_path:
        return None
    tree = tracer.spans_for(trace_id) if trace_id is not None else []
    rec = {
        "schema": FLIGHT_SCHEMA,
        "wall_time": time.time(),
        "reason": reason,
        "trace_id": trace_id,
        "tree": span_records(tree),
        "recent": span_records(tracer.tail(last_n)),
    }
    if extra:
        rec.update(extra)
    try:
        with open(flight_path(base_path), "a") as f:
            f.write(json.dumps(rec, sort_keys=True) + "\n")
    except OSError:
        metrics.count("flight_write_errors")
        return None
    metrics.count("flight_records")
    return rec


def read(path):
    """All flight records in `path` (empty list if it does not exist) —
    accepts either the base path or the .flight.jsonl path itself."""
    import os

    if not path.endswith(".flight.jsonl"):
        path = flight_path(path)
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]
