"""Secret sharing over Fr: polynomials, Lagrange, Shamir, Pedersen VSS and
dealerless Pedersen DVSS.

Replaces the reference's external `secret_sharing` crate (git rev 6bca50d,
Cargo.toml:14). Surface matches the call sites cataloged in SURVEY.md §2.2:
`Polynomial::lagrange_basis_at_0` (signature.rs:460,502; keygen.rs:270),
`get_shared_secret` / `reconstruct_secret` (keygen.rs:58,248),
`PedersenVSS::{gens,deal,verify_share}` (keygen.rs:93-94,317,334-351), and
`PedersenDVSSParticipant` (keygen.rs:136-162).
"""

import secrets

from .errors import GeneralError, ShareVerificationError
from .ops.curve import g1 as _g1_ops
from .ops.fields import R, fr_inv, fr_mul, fr_sub
from .ops.hashing import hash_to_g1


def rand_fr():
    """Uniform scalar in [0, r) from OS entropy (reference: FieldElement::random)."""
    return secrets.randbelow(R)


# --- Polynomials -----------------------------------------------------------


def poly_random(degree):
    """Random polynomial of the given degree (degree+1 coefficients, a0 first)."""
    return [rand_fr() for _ in range(degree + 1)]


def poly_eval(coeffs, x):
    """Horner evaluation at integer x, in Fr."""
    acc = 0
    for c in reversed(coeffs):
        acc = (acc * x + c) % R
    return acc


def lagrange_basis_at_0(ids, my_id):
    """Lagrange basis polynomial l_{my_id}(0) over the interpolation set `ids`.

    Reference: Polynomial::lagrange_basis_at_0 (used at signature.rs:460,502).
    Supports arbitrary (gap-containing) 1-based id sets — the edge case the
    reference tests hardest (signature.rs:711-822).
    """
    ids = set(ids)
    if my_id not in ids:
        raise GeneralError("id %d not in interpolation set %s" % (my_id, sorted(ids)))
    if 0 in ids:
        raise GeneralError("signer ids must be nonzero (1-based)")
    num, den = 1, 1
    for j in ids:
        if j == my_id:
            continue
        num = num * (j % R) % R
        den = den * ((j - my_id) % R) % R
    return fr_mul(num, fr_inv(den))


# --- Shamir secret sharing -------------------------------------------------


def get_shared_secret(threshold, total):
    """Deal a fresh random secret into `total` Shamir shares with the given
    reconstruction `threshold`. Returns (secret, {id: share}) with 1-based ids
    (reference: keygen.rs:58)."""
    if not 0 < threshold <= total:
        raise GeneralError(
            "invalid threshold %d for total %d" % (threshold, total)
        )
    coeffs = poly_random(threshold - 1)
    return coeffs[0], {i: poly_eval(coeffs, i) for i in range(1, total + 1)}


def reconstruct_secret(threshold, shares):
    """Lagrange-interpolate the secret at 0 from any `threshold` shares
    (reference: keygen.rs:248)."""
    if len(shares) < threshold:
        raise GeneralError(
            "need %d shares to reconstruct, got %d" % (threshold, len(shares))
        )
    use = dict(list(sorted(shares.items()))[:threshold])
    acc = 0
    for i, s in use.items():
        acc = (acc + lagrange_basis_at_0(use.keys(), i) * s) % R
    return acc


# --- Pedersen verifiable secret sharing ------------------------------------


class PedersenVSS:
    """Pedersen VSS with commitments in a (configurable) commitment group.

    The reference fixes the commitment group to G1 (keygen.rs:5,79-80); we
    keep that default but route through CurveOps so the group-assignment
    config stays single-source-of-truth (SURVEY.md §1 wiring quirk).
    """

    ops = _g1_ops

    @classmethod
    def gens(cls, label):
        """Two independent generators derived from a label (keygen.rs:93 via
        PedersenVSS::gens)."""
        return (
            hash_to_g1(bytes(label) + b" : g"),
            hash_to_g1(bytes(label) + b" : h"),
        )

    @classmethod
    def deal(cls, threshold, total, g, h):
        """Deal a secret with blinding: returns
        (secret, blind_secret, comm_coeffs {j: g^{a_j} h^{b_j}},
         s_shares {id: F(id)}, t_shares {id: G(id)})  — keygen.rs:93-94."""
        if not 0 < threshold <= total:
            raise GeneralError(
                "invalid threshold %d for total %d" % (threshold, total)
            )
        f_coeffs = poly_random(threshold - 1)
        g_coeffs = poly_random(threshold - 1)
        comm_coeffs = {
            j: cls.ops.add(
                cls.ops.mul(g, f_coeffs[j]), cls.ops.mul(h, g_coeffs[j])
            )
            for j in range(threshold)
        }
        s_shares = {i: poly_eval(f_coeffs, i) for i in range(1, total + 1)}
        t_shares = {i: poly_eval(g_coeffs, i) for i in range(1, total + 1)}
        return f_coeffs[0], g_coeffs[0], comm_coeffs, s_shares, t_shares

    @classmethod
    def deal_zero(cls, threshold, total, g, h):
        """Deal a sharing of ZERO for proactive refresh (Herzberg et al.):
        same tuple shape as `deal` but with f(0) = 0 pinned, so adding the
        resulting shares to an existing sharing rerandomizes every share
        while leaving the shared secret — and hence the verkey — unchanged.
        The blinding polynomial stays fully random; recipients additionally
        check comm_coeffs[0] == h^{b0} against the dealer-published `b0`
        (the degree-0 commitment opens to zero) before accepting."""
        if not 0 < threshold <= total:
            raise GeneralError(
                "invalid threshold %d for total %d" % (threshold, total)
            )
        f_coeffs = poly_random(threshold - 1)
        f_coeffs[0] = 0
        g_coeffs = poly_random(threshold - 1)
        comm_coeffs = {
            j: cls.ops.add(
                cls.ops.mul(g, f_coeffs[j]), cls.ops.mul(h, g_coeffs[j])
            )
            for j in range(threshold)
        }
        s_shares = {i: poly_eval(f_coeffs, i) for i in range(1, total + 1)}
        t_shares = {i: poly_eval(g_coeffs, i) for i in range(1, total + 1)}
        return g_coeffs[0], comm_coeffs, s_shares, t_shares

    @classmethod
    def check_share(
        cls, threshold, share_id, share, comm_coeffs, g, h,
        dealer_id=None, round=None,
    ):
        """Raising form of `verify_share`: a failed check raises
        ShareVerificationError carrying the offending `dealer_id` and the
        lifecycle `round` label, so DKG complaint rounds name the culprit
        exactly (the corrupt-partial attribution pattern from issue/)."""
        s, t = share
        lhs = cls.ops.add(cls.ops.mul(g, s), cls.ops.mul(h, t))
        bases, exps = [], []
        e = 1
        for j in range(threshold):
            bases.append(comm_coeffs[j])
            exps.append(e)
            e = e * share_id % R
        if lhs != cls.ops.msm(bases, exps):
            raise ShareVerificationError(
                "share for participant %d failed verification against "
                "dealer %s's commitments%s"
                % (
                    share_id,
                    dealer_id if dealer_id is not None else "?",
                    " in %s round" % round if round else "",
                ),
                dealer_id=dealer_id,
                round=round,
            )

    @classmethod
    def verify_share(cls, threshold, share_id, share, comm_coeffs, g, h):
        """Check g^s h^t == prod_j comm_coeffs[j]^(id^j) — the malicious-dealer
        detection the protocol's fault tolerance rests on (README.md:52-68,
        keygen.rs:334-351). Boolean convenience over `check_share` (which
        raises with dealer attribution and is what the online paths use)."""
        try:
            cls.check_share(threshold, share_id, share, comm_coeffs, g, h)
        except ShareVerificationError:
            return False
        return True


# --- Pedersen decentralized (dealerless) VSS --------------------------------


class PedersenDVSSParticipant:
    """One participant in the dealerless protocol: deal own secret, exchange
    shares pairwise, verify, additively combine (reference surface:
    keygen.rs:136-162; protocol driver pattern keygen.rs:126-165).

    Unlike the reference — where the driver is `#[cfg(test)]`-only — both the
    participant and the round drivers below are library code.
    """

    def __init__(self, participant_id, threshold, total, g, h):
        self.id = participant_id
        self.threshold = threshold
        self.total = total
        (
            self.secret,
            self.blind_secret,
            self.comm_coeffs,
            self.s_shares,
            self.t_shares,
        ) = PedersenVSS.deal(threshold, total, g, h)
        # shares of *other* participants' secrets addressed to us
        self._received = {}  # from_id -> (s, t)
        self._received_comms = {}  # from_id -> comm_coeffs
        self.secret_share = None
        self.t_secret_share = None
        self.final_comm_coeffs = None

    def received_share(self, from_id, comm_coeffs, share, threshold, total, g, h):
        """Verify and store a share of `from_id`'s secret, evaluated at our
        id. Every reject path raises ShareVerificationError naming the
        dealer, so DVSS/DKG complaint rounds attribute exactly."""
        if from_id == self.id:
            raise ShareVerificationError(
                "participant %d received its own share" % self.id,
                dealer_id=from_id,
                round="dvss",
            )
        if from_id in self._received:
            raise ShareVerificationError(
                "participant %d already has a share from %d"
                % (self.id, from_id),
                dealer_id=from_id,
                round="dvss",
            )
        PedersenVSS.check_share(
            threshold, self.id, share, comm_coeffs, g, h,
            dealer_id=from_id, round="dvss",
        )
        self._received[from_id] = share
        self._received_comms[from_id] = comm_coeffs

    def compute_final_comm_coeffs_and_shares(self, threshold, total, g, h):
        """Sum own + received shares into this participant's share of the
        distributed secret; combine coefficient commitments for later checks."""
        if len(self._received) != total - 1:
            raise GeneralError(
                "participant %d has %d of %d expected shares"
                % (self.id, len(self._received), total - 1)
            )
        s_acc = self.s_shares[self.id]
        t_acc = self.t_shares[self.id]
        for s, t in self._received.values():
            s_acc = (s_acc + s) % R
            t_acc = (t_acc + t) % R
        self.secret_share = s_acc
        self.t_secret_share = t_acc
        final = {}
        for j in range(threshold):
            acc = self.comm_coeffs[j]
            for comms in self._received_comms.values():
                acc = PedersenVSS.ops.add(acc, comms[j])
            final[j] = acc
        self.final_comm_coeffs = final


def share_secret_dvss(threshold, total, g, h):
    """Full dealerless 3-round protocol, simulated in-process: deal, pairwise
    exchange + verify, finalize. Mirrors the reference driver
    `share_secret_for_testing` (keygen.rs:126-165) as library code."""
    participants = [
        PedersenDVSSParticipant(i, threshold, total, g, h)
        for i in range(1, total + 1)
    ]
    for i in range(total):
        for j in range(total):
            if i == j:
                continue
            sender = participants[j]
            participants[i].received_share(
                sender.id,
                sender.comm_coeffs,
                (sender.s_shares[i + 1], sender.t_shares[i + 1]),
                threshold,
                total,
                g,
                h,
            )
    for p in participants:
        p.compute_final_comm_coeffs_and_shares(threshold, total, g, h)
    return participants
