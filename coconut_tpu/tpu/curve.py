"""Batched G1/G2 complete projective arithmetic and windowed MSMs.

The TPU equivalent of the reference's `multi_scalar_mul_const_time/_var_time`
call sites (signature.rs:157,424,427,465,513,521), re-designed for XLA:
points are pytrees of limb arrays and the MSM loops run over a static
window schedule with per-batch-element table gathers.

Point formulas are the Renes-Costello-Batina (2015) COMPLETE projective
addition/doubling for short-Weierstrass curves with a = 0. BLS12-381's
E(Fp) and its twist E'(Fp2) both have odd group order, so the formulas are
valid for EVERY pair of inputs including the identity (0 : 1 : 0) — no
branch predicates, no select masks, no embedded doubling in the hot path
(the previous Jacobian implementation spent ~60% of its HLO and runtime on
that edge-case machinery). Each formula's independent field products are
stacked into single MXU contractions (fl.mul_many): 12 products in 3
stacked multiplies per addition, 9 in 3 per doubling.

b3 = 3b: 12 for G1 (b = 4), 12*(1+u) for the twist (b' = 4(1+u)) — free
elementwise small-scalings in the lazy fp representation.

Only affine outputs are compared bit-for-bit against the spec
(`ops.curve.CurveOps`) — projective representatives are not canonical.

Field genericity: each function takes `fl`, a field namespace (the `fp`
module for G1 or the Fp2 shim below for G2), mirroring the spec's CurveOps
being generic over the coordinate field.
"""

import jax
import jax.numpy as jnp

from . import fp
from . import tower as tw


class _Fp2Field:
    """Adapter giving the tower's Fp2 the same surface as the fp module."""

    add = staticmethod(tw.fp2_add)
    sub = staticmethod(tw.fp2_sub)
    mul = staticmethod(tw.fp2_mul)
    sq = staticmethod(tw.fp2_sq)
    neg = staticmethod(tw.fp2_neg)
    inv = staticmethod(tw.fp2_inv)
    is_zero = staticmethod(tw.fp2_is_zero)
    eq = staticmethod(tw.fp2_eq)
    select = staticmethod(tw.fp2_select)
    zeros = staticmethod(tw.fp2_zeros)
    ones = staticmethod(tw.fp2_ones)

    @staticmethod
    def mul_small(a, k):
        return tw.fp2_mul_small(a, k)

    @staticmethod
    def mul_many(lhs, rhs):
        """Stack independent Fp2 products into one base-field contraction."""
        prods = tw.fp2_mul(tw._stack2(lhs), tw._stack2(rhs))
        return tw._unstack2(prods, len(lhs))

    @staticmethod
    def b3(t):
        # 3b' = 12(1+u): t*(1+u) is (c0-c1, c0+c1); then scale by 12 — all
        # elementwise lazy ops
        return tw.fp2_mul_small(tw.fp2_mul_xi(t), 12)


class _FpField:
    add = staticmethod(fp.add)
    sub = staticmethod(fp.sub)
    mul = staticmethod(fp.mul)
    sq = staticmethod(fp.sq)
    neg = staticmethod(fp.neg)
    inv = staticmethod(fp.inv)
    is_zero = staticmethod(fp.is_zero)
    eq = staticmethod(fp.eq)
    select = staticmethod(fp.select)
    mul_small = staticmethod(fp.mul_small)
    mul_many = staticmethod(fp.mul_stack)

    @staticmethod
    def b3(t):
        return fp.mul_small(t, 12)  # 3b = 12 (b = 4)

    @staticmethod
    def zeros(shape=()):
        from .limbs import NLIMBS

        return jnp.zeros(tuple(shape) + (NLIMBS,), dtype=jnp.float32)

    ones = staticmethod(fp.ones_mont)


FP = _FpField
FP2 = _Fp2Field


def jinfinity(fl, shape=()):
    """The projective identity (0 : 1 : 0)."""
    return (fl.zeros(shape), fl.ones(shape), fl.zeros(shape))


def jadd(fl, p, q):
    """Complete projective addition (RCB 2015 Alg. 7, a = 0): 12 products
    in 3 stacked multiplies, valid for all curve points incl. identity."""
    X1, Y1, Z1 = p
    X2, Y2, Z2 = q
    t0, t1, t2, m3, m4, m5 = fl.mul_many(
        [X1, Y1, Z1, fl.add(X1, Y1), fl.add(Y1, Z1), fl.add(X1, Z1)],
        [X2, Y2, Z2, fl.add(X2, Y2), fl.add(Y2, Z2), fl.add(X2, Z2)],
    )
    t3 = fl.sub(fl.sub(m3, t0), t1)  # X1Y2 + X2Y1
    t4 = fl.sub(fl.sub(m4, t1), t2)  # Y1Z2 + Y2Z1
    t5 = fl.sub(fl.sub(m5, t0), t2)  # X1Z2 + X2Z1
    b3t2 = fl.b3(t2)
    y3 = fl.b3(t5)
    t0_3 = fl.add(fl.add(t0, t0), t0)  # 3X1X2
    z3s = fl.add(t1, b3t2)
    t1m = fl.sub(t1, b3t2)
    x3a, t2c, y3b, t1d, t0e, z3f = fl.mul_many(
        [t4, t3, y3, t1m, t0_3, z3s],
        [y3, t1m, t0_3, z3s, t3, t4],
    )
    return (
        fl.sub(t2c, x3a),
        fl.add(t1d, y3b),
        fl.add(z3f, t0e),
    )


def jdouble(fl, p):
    """Complete projective doubling (RCB 2015 Alg. 9, a = 0): 9 products
    in 3 stacked multiplies."""
    X, Y, Z = p
    a_, b_, c_, xy = fl.mul_many([Y, Y, Z, X], [Y, Z, Z, Y])
    cb = fl.b3(c_)
    e8 = fl.mul_small(a_, 8)
    y3s = fl.add(a_, cb)
    t0m = fl.sub(a_, fl.mul_small(cb, 3))
    x3p, z3, y2m, x3m = fl.mul_many([cb, b_, t0m, t0m], [e8, e8, y3s, xy])
    return (fl.add(x3m, x3m), fl.add(x3p, y2m), z3)


def to_affine(fl, p):
    """Projective -> (x, y, is_infinity-mask). Uses one field inversion."""
    X, Y, Z = p
    zinv = fl.inv(Z)
    return fl.mul(X, zinv), fl.mul(Y, zinv), fl.is_zero(Z)


def affine_to_jacobian(fl, x, y, inf):
    """Affine pytree + infinity mask -> projective ((x,y,1) / (0,1,0))."""
    one = fl.ones(inf.shape)
    zero = fl.zeros(inf.shape)
    return (
        fl.select(inf, zero, x),
        fl.select(inf, one, y),
        fl.select(inf, zero, one),
    )


def build_tables_device(fl, x, y, inf, entries=16):
    """On-device per-point projective multiples 0..entries-1 for the
    windowed MSMs. x, y: affine coordinate pytrees [..., k]; inf: bool
    [..., k]. Returns a pytree with leaves [..., k, entries, limbs...].
    The chained complete adds run as a `lax.scan` so jadd is compiled
    ONCE; amortized over the whole [..., k] batch. entries=17 serves the
    signed 5-bit window schedule (digits in [-16, 16], negation is a
    Y-flip on the gathered entry)."""
    jac = affine_to_jacobian(fl, x, y, inf)

    def body(prev, _):
        return jadd(fl, prev, jac), prev  # emits entries 0..entries-1

    _, rows = jax.lax.scan(
        body, jinfinity(fl, inf.shape), None, length=entries
    )
    # rows leaves: [entries, ..., k, L] -> [..., k, entries, L]
    return jax.tree_util.tree_map(
        lambda t: jnp.moveaxis(t, 0, inf.ndim), rows
    )


def fold_points(fl, pts, n, axis_offset=0, chunk=16):
    """Sum a pytree of n (power of two) points along its (axis_offset)-th
    leading axis with ~n-1 lane-adds (the minimum): a lax.scan over
    chunk-size groups (jadd compiled ONCE at width n/chunk) followed by a
    pairwise-halving unroll over the n/chunk partial sums (log2(n/chunk)
    jadd shapes in HLO — small now that jadd is the complete-RCB form)."""
    assert n & (n - 1) == 0
    ax = axis_offset
    if n > chunk:
        g = n // chunk

        def split(t):
            s = t.shape
            return jnp.moveaxis(
                t.reshape(s[:ax] + (g, chunk) + s[ax + 1 :]), ax + 1, 0
            )

        xs = jax.tree_util.tree_map(split, pts)  # leaves [chunk, .. g ..]
        init = jax.tree_util.tree_map(lambda t: t[0], xs)
        rest = jax.tree_util.tree_map(lambda t: t[1:], xs)
        pts = jax.lax.scan(
            lambda c, x: (jadd(fl, c, x), None), init, rest
        )[0]
        n = g
    while n > 1:
        half = n // 2
        lo = jax.tree_util.tree_map(
            lambda t: jax.lax.slice_in_dim(t, 0, half, axis=ax), pts
        )
        hi = jax.tree_util.tree_map(
            lambda t: jax.lax.slice_in_dim(t, half, n, axis=ax), pts
        )
        pts = jadd(fl, lo, hi)
        n = half
    return jax.tree_util.tree_map(lambda t: jnp.take(t, 0, axis=ax), pts)


def fold_points_any(fl, pts, n, axis_offset=0):
    """Sum a pytree of n points (ANY n >= 1) along the (axis_offset)-th
    leading axis with n-1 lane-adds: static binary decomposition of n into
    power-of-two blocks, each folded by fold_points, partials chain-added."""
    ax = axis_offset
    if n == 1:
        return jax.tree_util.tree_map(lambda t: jnp.take(t, 0, axis=ax), pts)
    acc = None
    off = 0
    for bit in range(n.bit_length() - 1, -1, -1):
        blk = 1 << bit
        if not n & blk:
            continue
        part = jax.tree_util.tree_map(
            lambda t: jax.lax.slice_in_dim(t, off, off + blk, axis=ax), pts
        )
        folded = fold_points(fl, part, blk, axis_offset=ax)
        acc = folded if acc is None else jadd(fl, acc, folded)
        off += blk
    return acc


def build_comb_tables(fl, tables_e, nwin, window=5):
    """Fixed-base comb window tables for the shared-base MSM.

    tables_e: projective multiples 0..2^(window-1) as a pytree with leading
    [k, 2^(window-1)+1] (entry 0 = identity). Returns leading
    [k, nwin, entries] where entry (j, w, d) = d * (2^window)^(nwin-1-w) *
    base_j — i.e. the w-th MS-first signed window digit's contribution is a
    pure table lookup, so the MSM itself needs NO doublings. The scaling
    scan runs on the tiny [k, entries] shape (`window` doublings per
    window), so table build cost is negligible against the [B]-wide MSM;
    per-verkey tables are cached device-side by the backend."""

    def body(carry, _):
        nxt = carry
        for _ in range(window):
            nxt = jdouble(fl, nxt)
        return nxt, carry  # emit BEFORE scaling: row w = (2^window)^w * t

    _, rows = jax.lax.scan(body, tables_e, None, length=nwin)
    # rows: [nwin(lsb-first), k, E, L] -> msb-first, then [k, nwin, E, L]
    return jax.tree_util.tree_map(
        lambda t: jnp.moveaxis(jnp.flip(t, axis=0), 0, 1), rows
    )


def msm_shared_comb(fl, wtables, mag, sgn):
    """Fixed-base comb MSM over shared bases: gather one table entry per
    (credential, base, window) and fold — 0 doublings, k*nwin-1 lane-adds
    per credential, all at full [B] width (no sequential window scan).

    wtables: comb tables from build_comb_tables, leading [k, nwin, E];
    mag/sgn: signed window digits [B, k, nwin] (msb-first, digit =
    (-1)^sgn * mag, mag <= E-1 for E-entry tables; zero scalars ->
    all-zero digits). The backend uses the 6-bit/43-window schedule.
    Returns a projective accumulator pytree with leading [B].

    Layout: the fold runs over a LEADING (k*nwin) axis with the batch in
    the trailing lane axis — the same orientation as the grouped verify's
    _grouped_msms fold. (The transposed [B, k*nwin] orientation miscompiles
    on the axon TPU backend at B = 1024: the last batch row of the fold
    comes back corrupted, data-independently, on every mul path — same
    backend-bug family as the round-2 int8 einsum workaround in fp._school.)"""
    B, k, nwin = mag.shape
    jidx = jnp.arange(k)[:, None, None]
    widx = jnp.arange(nwin)[None, :, None]
    mag_t = jnp.transpose(mag, (1, 2, 0))  # [k, nwin, B]
    sgn_t = jnp.transpose(sgn, (1, 2, 0))

    def leaf(t):  # [k, nwin, E, L...] -> [k, nwin, B, L...]
        return t[jidx, widx, mag_t]

    X, Y, Z = (
        jax.tree_util.tree_map(leaf, wtables[0]),
        jax.tree_util.tree_map(leaf, wtables[1]),
        jax.tree_util.tree_map(leaf, wtables[2]),
    )
    Y = fl.select(sgn_t, fl.neg(Y), Y)
    flat = jax.tree_util.tree_map(
        lambda t: t.reshape((k * nwin, B) + t.shape[3:]), (X, Y, Z)
    )
    return fold_points_any(fl, flat, k * nwin, axis_offset=0)


def scalar_mul_static(fl, pt, k, window=4):
    """Projective point times a STATIC positive int scalar: windowed
    double-and-add, mirroring fp.pow_static's structure. The multiples
    table 0..2^window-1 is built by a lax.scan of chained complete adds
    (jadd compiled ONCE), then a scan over the static msb-first digit
    array runs `window` doublings + one gathered add per window. The
    dominant user is hash-to-G1's cofactor clear (G1_COFACTOR, 126 bits
    -> 32 windows); complete RCB formulas make this valid for FULL-curve
    points (the SvdW sum is not yet in the r-torsion subgroup)."""
    assert k > 0
    shape = jax.tree_util.tree_leaves(pt)[0].shape[:-1]
    nw = (k.bit_length() + window - 1) // window
    digits = jnp.array(
        [(k >> (window * i)) & ((1 << window) - 1) for i in range(nw - 1, -1, -1)],
        dtype=jnp.int32,
    )

    def tbody(prev, _):
        return jadd(fl, prev, pt), prev  # emits multiples 0..2^window-1

    _, rows = jax.lax.scan(
        tbody, jinfinity(fl, shape), None, length=1 << window
    )

    def body(acc, d):
        for _ in range(window):
            acc = jdouble(fl, acc)
        entry = jax.tree_util.tree_map(
            lambda t: jax.lax.dynamic_index_in_dim(
                t, d, axis=0, keepdims=False
            ),
            rows,
        )
        return jadd(fl, acc, entry), None

    acc, _ = jax.lax.scan(body, jinfinity(fl, shape), digits)
    return acc


# --- SvdW map (device half of CTH-v2 hash_to_g1) ----------------------------
#
# Montgomery-encoded constants for the Fp instantiation of the spec's
# straight-line Shallue-van de Woestijne map (ops/hashing._SVDW_FP — derived
# there at import from the curve equation alone; re-encoded here as balanced
# limb vectors). Resolved lazily: importing ops.hashing derives the Fp2
# constants too, which is pointless import-time work for non-hashing users.
_SVDW_MONT = None


def _svdw_mont():
    global _SVDW_MONT
    if _SVDW_MONT is None:
        from ..ops.hashing import _SVDW_FP
        from .limbs import MONT_R, balanced_limbs
        from .fp import P

        import numpy as _np

        def enc(v):
            # numpy, not jnp: the first resolve may happen INSIDE a jit
            # trace (the cached hash kernel), and arrays minted there
            # would be cached as leaked tracers
            return _np.asarray(
                balanced_limbs(v * MONT_R % P), dtype=_np.float32
            )

        Z, c1, c2, c3, c4 = _SVDW_FP
        _SVDW_MONT = (enc(Z), enc(c1), enc(c2), enc(c3), enc(c4), enc(4))
    return _SVDW_MONT


def svdw_map_fp(u, u_par):
    """Batched SvdW straight-line map for G1, bit-identical to the spec
    (ops/hashing._map_to_curve_svdw over _FpAdapter): u [..., L] field
    elements in Montgomery limbs, u_par [...] bool = host-side sgn0(u)
    (u is host-known — the expand_message_xmd output — so its parity
    ships as a bit instead of being recomputed on device). Returns
    affine (x, y) limb pytrees; the map NEVER outputs the identity or a
    y = 0 point (E(Fp) has odd order, so x^3 + 4 has no roots in Fp and
    the three-candidate select always lands on a curve point).

    Fixed op count, branchless selects — the property the CTH-v2 spec
    was designed around. The three candidate square roots run as ONE
    stacked pow_static over a [..., 3] axis (the map's dominant cost,
    ~480 Montgomery muls, same family as fp.inv)."""
    from . import fp as _f
    from ..ops.fields import P as _P

    Z, c1, c2, c3, c4, b4 = _svdw_mont()
    one = _f.ones_mont(u.shape[:-1])
    tv1 = _f.mul(_f.sq(u), c1)
    tv2 = _f.add(one, tv1)
    tv1m = _f.sub(one, tv1)
    tv3 = _f.inv(_f.mul(tv1m, tv2))  # inv0: fp.inv maps 0 -> 0
    tv4 = _f.mul(_f.mul(_f.mul(u, tv1m), tv3), c3)
    x1 = _f.sub(c2, tv4)
    x2 = _f.add(c2, tv4)
    t5 = _f.mul(_f.sq(tv2), tv3)
    x3 = _f.add(_f.mul(_f.sq(t5), c4), Z)
    xs = jnp.stack(jnp.broadcast_arrays(x1, x2, x3), axis=-2)  # [..., 3, L]
    gxs = _f.add(_f.mul(_f.sq(xs), xs), b4)  # g(x) = x^3 + 4
    ss = _f.pow_static(gxs, (_P + 1) // 4)  # candidate sqrt per x
    # is_square(gx) iff s^2 == gx (P = 3 mod 4); exactly the spec's
    # fp_sqrt-is-not-None test
    ok = _f.is_zero(_f.sub(_f.sq(ss), gxs))  # [..., 3]
    ok1, ok2 = ok[..., 0], ok[..., 1]
    x = _f.select(ok1, xs[..., 0, :], _f.select(ok2, xs[..., 1, :], xs[..., 2, :]))
    y = _f.select(ok1, ss[..., 0, :], _f.select(ok2, ss[..., 1, :], ss[..., 2, :]))
    # sgn0 is defined on the STANDARD-domain canonical value: leave the
    # Montgomery domain (one mul by raw 1) before the parity test
    flip = _f.canon_parity(_f.from_mont(y)) != u_par
    y = _f.select(flip, _f.neg(y), y)
    return x, y


def msm_distinct_bucketed(fl, x, y, inf, mag, sgn, window):
    """Bucketed (Pippenger) distinct-base MSM: the table-free schedule
    for FAT per-row base counts, where msm_distinct_signed's on-device
    17-entry table build (16 chained adds at [B*k] width) and per-window
    table gathers dominate.

    x, y, inf: affine points [..., k]; mag/sgn: [..., k, nwin] signed
    `window`-bit digits, msb first, magnitudes <= nb = 2^(window-1).
    Per window (Horner over windows, msb first): `window` doublings,
    then each of the k points is SCATTERED into its digit's bucket —
    gather the target bucket row (take_along_axis over the [..., nb]
    bucket axis), one complete add at batch width, one-hot writeback
    (cheap VPU selects, no extra field muls) — then the nb buckets fold
    with the running-sum trick (sum_b b*bucket_b in 2nb adds). Zero
    digits never scatter (the one-hot mask is all-false), so zero
    scalars and identity pad lanes cost nothing but the masked lanes.

    Cost per window ~ k + 2*nb batch-width adds + `window` doublings,
    with NO table build — vs the Horner schedule's 16k build adds +
    k adds/window; the backend's _bucket_window cost model picks the
    crossover (k ~ 64-128) and the window size. Returns a projective
    accumulator pytree with leading dims [...]."""
    nb = 1 << (window - 1)
    bshape = inf.shape[:-1]
    bdim = len(bshape)
    k = inf.shape[-1]
    jac = affine_to_jacobian(fl, x, y, inf)  # leaves [..., k, L]
    acc = jinfinity(fl, bshape)

    def win_body(acc, dw):
        mw, sw = dw  # each [..., k]
        acc = jax.lax.fori_loop(
            0, window, lambda _, a: jdouble(fl, a), acc
        )
        buckets = jinfinity(fl, bshape + (nb,))

        def scatter(j, bk):
            d = jnp.take(mw, j, axis=-1).astype(jnp.int32)  # [...], 0..nb
            sj = jnp.take(sw, j, axis=-1)
            px, py, pz = jax.tree_util.tree_map(
                lambda t: jnp.take(t, j, axis=bdim), jac
            )
            pj = (px, fl.select(sj, fl.neg(py), py), pz)
            idx = jnp.maximum(d - 1, 0)  # bucket index; d = 0 is masked

            def gather(t):  # [..., nb, L...] -> [..., L...] at idx
                ii = idx.reshape(idx.shape + (1,) * (t.ndim - idx.ndim))
                return jnp.squeeze(
                    jnp.take_along_axis(t, ii, axis=bdim), axis=bdim
                )

            cur = jax.tree_util.tree_map(gather, bk)
            new = jadd(fl, cur, pj)
            onehot = (jnp.arange(nb) == idx[..., None]) & (
                d[..., None] > 0
            )  # [..., nb]

            def put(bt, nt):
                oh = onehot.reshape(
                    onehot.shape + (1,) * (bt.ndim - onehot.ndim)
                )
                return jnp.where(oh, jnp.expand_dims(nt, axis=bdim), bt)

            return jax.tree_util.tree_map(put, bk, new)

        buckets = jax.lax.fori_loop(0, k, scatter, buckets)
        # running-sum fold, top bucket first: total = sum_b b * bucket_b
        rev = jax.tree_util.tree_map(
            lambda t: jnp.flip(jnp.moveaxis(t, bdim, 0), axis=0), buckets
        )

        def fold(carry, bslice):
            run, tot = carry
            run = jadd(fl, run, bslice)
            tot = jadd(fl, tot, run)
            return (run, tot), None

        (_, tot), _ = jax.lax.scan(
            fold, (jinfinity(fl, bshape), jinfinity(fl, bshape)), rev
        )
        return jadd(fl, acc, tot), None

    acc, _ = jax.lax.scan(
        win_body,
        acc,
        (jnp.moveaxis(mag, -1, 0), jnp.moveaxis(sgn, -1, 0)),
    )
    return acc


def msm_distinct_signed(fl, x, y, inf, mag, sgn):
    """Signed 5-bit windowed MSM over per-row bases (the issuance/show
    shape: per-credential points, so tables must be built on device).

    x, y, inf: affine points [..., k]; mag/sgn: [..., k, nwin] signed
    5-bit window digits, msb first (digit = (-1)^sgn * mag, mag <= 16).
    52-window Horner (5 doublings + k adds per window) vs the unsigned
    4-bit schedule's 64 windows. Returns a projective accumulator pytree
    with leading dims [...]."""
    tables = build_tables_device(fl, x, y, inf, entries=17)
    k = inf.shape[-1]
    acc = jinfinity(fl, inf.shape[:-1])

    def body(acc, dw):
        mw, sw = dw  # each [..., k]
        acc = jax.lax.fori_loop(0, 5, lambda _, a: jdouble(fl, a), acc)

        def add_base(j, a):
            idx = jnp.take(mw, j, axis=-1)  # [...]
            entry = jax.tree_util.tree_map(
                lambda t: jnp.squeeze(
                    jnp.take_along_axis(
                        jnp.take(t, j, axis=idx.ndim),
                        idx.reshape(idx.shape + (1,) * (t.ndim - idx.ndim - 1)),
                        axis=idx.ndim,
                    ),
                    axis=idx.ndim,
                ),
                tables,
            )
            sj = jnp.take(sw, j, axis=-1)
            ex, ey, ez = entry
            entry = (ex, fl.select(sj, fl.neg(ey), ey), ez)
            return jadd(fl, a, entry)

        acc = jax.lax.fori_loop(0, k, add_base, acc)
        return acc, None

    acc, _ = jax.lax.scan(
        body,
        acc,
        (jnp.moveaxis(mag, -1, 0), jnp.moveaxis(sgn, -1, 0)),
    )
    return acc


