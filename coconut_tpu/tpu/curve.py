"""Batched G1/G2 Jacobian arithmetic and shared-base windowed MSM.

The TPU equivalent of the reference's `multi_scalar_mul_const_time/_var_time`
call sites (signature.rs:157,424,427,465,513,521), re-designed for XLA:
points are pytrees of limb arrays, all control flow is branchless (select
masks carry the identity/doubling edge cases), and the MSM loops over a
static window schedule with per-batch-element table gathers.

Formulas match `ops.curve.CurveOps` (Jacobian: spec curve.py:95-143);
only affine outputs are compared bit-for-bit — Jacobian representatives are
not canonical.

Field genericity: each function takes `fl`, a field namespace (the `fp`
module for G1 or the Fp2 shim below for G2), mirroring the spec's CurveOps
being generic over the coordinate field.
"""

import jax
import jax.numpy as jnp

from . import fp
from . import tower as tw


class _Fp2Field:
    """Adapter giving the tower's Fp2 the same surface as the fp module."""

    add = staticmethod(tw.fp2_add)
    sub = staticmethod(tw.fp2_sub)
    mul = staticmethod(tw.fp2_mul)
    sq = staticmethod(tw.fp2_sq)
    neg = staticmethod(tw.fp2_neg)
    inv = staticmethod(tw.fp2_inv)
    is_zero = staticmethod(tw.fp2_is_zero)
    eq = staticmethod(tw.fp2_eq)
    select = staticmethod(tw.fp2_select)
    zeros = staticmethod(tw.fp2_zeros)
    ones = staticmethod(tw.fp2_ones)

    @staticmethod
    def mul_small(a, k):
        return tw.fp2_mul_small(a, k)


class _FpField:
    add = staticmethod(fp.add)
    sub = staticmethod(fp.sub)
    mul = staticmethod(fp.mul)
    sq = staticmethod(fp.sq)
    neg = staticmethod(fp.neg)
    inv = staticmethod(fp.inv)
    is_zero = staticmethod(fp.is_zero)
    eq = staticmethod(fp.eq)
    select = staticmethod(fp.select)
    mul_small = staticmethod(fp.mul_small)

    @staticmethod
    def zeros(shape=()):
        from .limbs import NLIMBS

        return jnp.zeros(tuple(shape) + (NLIMBS,), dtype=jnp.float32)

    ones = staticmethod(fp.ones_mont)


FP = _FpField
FP2 = _Fp2Field


def jinfinity(fl, shape=()):
    """The spec's identity encoding: (1, 1, 0) Jacobian (curve.py:98)."""
    return (fl.ones(shape), fl.ones(shape), fl.zeros(shape))


def jdouble(fl, j):
    """Branchless Jacobian doubling (same formulas as spec curve.py:95-113;
    Y == 0 or Z == 0 -> identity)."""
    X, Y, Z = j
    A = fl.sq(X)
    B = fl.sq(Y)
    C = fl.sq(B)
    D = fl.sub(fl.sub(fl.sq(fl.add(X, B)), A), C)
    D = fl.add(D, D)
    E = fl.mul_small(A, 3)
    F = fl.sq(E)
    X3 = fl.sub(F, fl.add(D, D))
    C8 = fl.mul_small(C, 8)
    Y3 = fl.sub(fl.mul(E, fl.sub(D, X3)), C8)
    Z3 = fl.mul(fl.add(Y, Y), Z)
    bad = fl.is_zero(Z) | fl.is_zero(Y)
    inf = jinfinity(fl, bad.shape)
    return (
        fl.select(bad, inf[0], X3),
        fl.select(bad, inf[1], Y3),
        fl.select(bad, inf[2], Z3),
    )


def jadd(fl, j1, j2):
    """Branchless Jacobian addition with all edge cases selected
    (spec curve.py:115-143): identities, doubling, inverse pair."""
    X1, Y1, Z1 = j1
    X2, Y2, Z2 = j2
    Z1Z1 = fl.sq(Z1)
    Z2Z2 = fl.sq(Z2)
    U1 = fl.mul(X1, Z2Z2)
    U2 = fl.mul(X2, Z1Z1)
    S1 = fl.mul(Y1, fl.mul(Z2, Z2Z2))
    S2 = fl.mul(Y2, fl.mul(Z1, Z1Z1))
    H = fl.sub(U2, U1)
    I = fl.sq(fl.add(H, H))
    J = fl.mul(H, I)
    rr = fl.sub(S2, S1)
    rr = fl.add(rr, rr)
    V = fl.mul(U1, I)
    X3 = fl.sub(fl.sub(fl.sq(rr), J), fl.add(V, V))
    S1J = fl.mul(S1, J)
    Y3 = fl.sub(fl.mul(rr, fl.sub(V, X3)), fl.add(S1J, S1J))
    Z3 = fl.mul(fl.mul(Z1, Z2), H)
    Z3 = fl.add(Z3, Z3)
    res = (X3, Y3, Z3)

    z1_zero = fl.is_zero(Z1)
    z2_zero = fl.is_zero(Z2)
    both = ~z1_zero & ~z2_zero
    same_x = fl.is_zero(H) & both
    same_y = fl.is_zero(rr)
    dbl = jdouble(fl, j1)
    inf = jinfinity(fl, z1_zero.shape)

    def sel(r, d, i_, p_, q_):
        out = fl.select(same_x & same_y, d, r)
        out = fl.select(same_x & ~same_y, i_, out)
        out = fl.select(z1_zero, q_, out)
        out = fl.select(z2_zero & ~z1_zero, p_, out)
        return out

    return tuple(
        sel(res[k], dbl[k], inf[k], j1[k], j2[k]) for k in range(3)
    )


def to_affine(fl, j):
    """Jacobian -> (x, y, is_infinity-mask). Uses one field inversion."""
    X, Y, Z = j
    zinv = fl.inv(Z)
    zinv2 = fl.sq(zinv)
    x = fl.mul(X, zinv2)
    y = fl.mul(Y, fl.mul(zinv2, zinv))
    return x, y, fl.is_zero(Z)


def gather_point(table, idx):
    """table: pytree with leading [n] axis; idx: int array [...] ->
    pytree with leading idx-shape."""
    return jax.tree_util.tree_map(lambda t: jnp.take(t, idx, axis=0), table)


def affine_to_jacobian(fl, x, y, inf):
    """Affine pytree + infinity mask -> Jacobian (identity = (1, 1, 0))."""
    one = fl.ones(inf.shape)
    zero = fl.zeros(inf.shape)
    return (
        fl.select(inf, one, x),
        fl.select(inf, one, y),
        fl.select(inf, zero, one),
    )


def build_tables_device(fl, x, y, inf):
    """On-device per-point multiples 0..15 for the distinct-base MSM.

    x, y: affine coordinate pytrees [..., k]; inf: bool [..., k].
    Returns Jacobian pytree with leaves [..., k, 16, NLIMBS-ish] (a new axis
    inserted before the limb dims). The 15 chained adds run as a `lax.scan`
    so jadd is compiled ONCE (unrolled, this function alone was ~91k HLO
    lines and dominated the combined-kernel compile); amortized over the
    whole [..., k] batch, unlike the host-side spec-op tables of msm_shared
    (those are only viable when the bases are shared by every batch row)."""
    jac = affine_to_jacobian(fl, x, y, inf)

    def body(prev, _):
        return jadd(fl, prev, jac), prev  # emits entries 0..15

    _, rows = jax.lax.scan(body, jinfinity(fl, inf.shape), None, length=16)
    # rows leaves: [16, ..., k, L] -> [..., k, 16, L]
    return jax.tree_util.tree_map(
        lambda t: jnp.moveaxis(t, 0, inf.ndim), rows
    )


def fold_points(fl, pts, n, axis_offset=0):
    """Sum a pytree of n points along its (axis_offset)-th leading axis by
    pairwise halving: jadd(first half, second half), width n/2, n/4, ..., 1.

    Total arithmetic is ~n-1 lane-adds — the minimum for a sum. (The earlier
    fixed-width roll-butterfly kept every step at width n so jadd compiled
    once, but that costs n*log2(n) lane-adds: 10x the FLOPs at n=1024. The
    halving tree instantiates log2(n) differently-shaped jadds in HLO, which
    compiles fine and is cached persistently.) n must be a power of two."""
    assert n & (n - 1) == 0
    ax = axis_offset
    while n > 1:
        half = n // 2
        lo = jax.tree_util.tree_map(
            lambda t: jax.lax.slice_in_dim(t, 0, half, axis=ax), pts
        )
        hi = jax.tree_util.tree_map(
            lambda t: jax.lax.slice_in_dim(t, half, n, axis=ax), pts
        )
        pts = jadd(fl, lo, hi)
        n = half
    return jax.tree_util.tree_map(lambda t: jnp.take(t, 0, axis=ax), pts)


def msm_distinct(fl, x, y, inf, digits):
    """Windowed MSM over per-row bases (the issuance shape: every credential
    request carries its own ciphertext points — reference signature.rs:400-428
    — so there is no shared table).

    x, y, inf: affine points [..., k]; digits: uint [..., k, nwin] 4-bit
    windows, most significant first (zero scalars -> all-zero digits).
    Returns a Jacobian accumulator pytree with leading dims [...]."""
    tables = build_tables_device(fl, x, y, inf)
    k = inf.shape[-1]
    acc = jinfinity(fl, inf.shape[:-1])

    def body(acc, dw):
        # dw: [..., k] digits of this window
        acc = jax.lax.fori_loop(0, 4, lambda _, a: jdouble(fl, a), acc)

        def add_base(j, a):
            idx = jnp.take(dw, j, axis=-1)  # [...]
            entry = jax.tree_util.tree_map(
                lambda t: jnp.squeeze(
                    jnp.take_along_axis(
                        jnp.take(t, j, axis=idx.ndim),
                        idx.reshape(idx.shape + (1,) * (t.ndim - idx.ndim - 1)),
                        axis=idx.ndim,
                    ),
                    axis=idx.ndim,
                ),
                tables,
            )
            return jadd(fl, a, entry)

        acc = jax.lax.fori_loop(0, k, add_base, acc)
        return acc, None

    acc, _ = jax.lax.scan(body, acc, jnp.moveaxis(digits, -1, 0))
    return acc


def msm_shared(fl, tables, digits):
    """Windowed shared-base MSM.

    tables: pytree (X, Y, Z) of arrays [k, 16, ...limbs...] — per-base
      Jacobian multiples 0..15 (entry 0 = identity), precomputed host-side
      from the spec ops so table contents are trusted.
    digits: uint array [B, k, nwin] — 4-bit windows, most significant first.
    Returns Jacobian accumulator pytree with leading [B].

    Compile-size discipline: the window loop is a `scan` and the doubling /
    per-base-add loops are `fori_loop`s, so jdouble and jadd are each
    compiled exactly ONCE regardless of window count or base count.
    """
    B, k, nwin = digits.shape
    acc = jinfinity(fl, (B,))

    def body(acc, dw):
        # dw: [B, k] digits for this window
        acc = jax.lax.fori_loop(0, 4, lambda _, a: jdouble(fl, a), acc)

        def add_base(j, a):
            row = jax.tree_util.tree_map(
                lambda t: jax.lax.dynamic_index_in_dim(
                    t, j, axis=0, keepdims=False
                ),
                tables,
            )
            entry = gather_point(row, jnp.take(dw, j, axis=1))
            return jadd(fl, a, entry)

        acc = jax.lax.fori_loop(0, k, add_base, acc)
        return acc, None

    acc, _ = jax.lax.scan(body, acc, jnp.moveaxis(digits, -1, 0))
    return acc
