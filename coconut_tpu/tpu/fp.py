"""Batched Fp (BLS12-381 base field) arithmetic on 16-bit limbs in uint64.

Every function operates on arrays of shape [..., NLIMBS] (leading dims =
batch) in the Montgomery domain (R = 2^384) and returns canonical
representatives (< p, 16-bit limbs).

XLA-friendly formulation (SURVEY.md §7 hard part (a), revised after
profiling: per-limb update-slice chains made compile time explode):

  - schoolbook products: one outer product + one static 0/1 matrix
    contraction (einsum) — no sequential limb loop;
  - Montgomery reduction in full width: m = (t * N') mod 2^384 via a
    truncated schoolbook, then (t + m*p) / 2^384 — no word-by-word REDC;
  - carry/borrow propagation: carry-lookahead via lax.associative_scan
    (the (generate, propagate) monoid), log-depth and exact — no ripple.

Magnitude discipline (uint64 headroom): 16x16-bit products accumulated over
<= 24 terms stay < 2^37; the one redundant-times-16-bit product in the
reduction stays < 2^58. All bounds are commented at the use sites.
"""

import numpy as np

import jax.numpy as jnp
from jax import lax

from ..ops.fields import P
from .limbs import LIMB_BITS, MASK, MONT_R, NLIMBS, ONE_M, P_LIMBS, int_to_limbs

_P_J = jnp.asarray(P_LIMBS, dtype=jnp.uint64)
_ONE_M_J = jnp.asarray(ONE_M, dtype=jnp.uint64)
# N' = -p^{-1} mod 2^384, full width (for the one-shot Montgomery m).
_NPRIME_J = jnp.asarray(
    int_to_limbs((-pow(P, -1, MONT_R)) % MONT_R), dtype=jnp.uint64
)
_MASK = jnp.uint64(MASK)
_SHIFT = jnp.uint64(LIMB_BITS)

def _school(a, b, out_len):
    """Polynomial limb product c_k = sum_i a_i * b_{k-i}, truncated to
    out_len limbs, via statically shifted copies of b and one reduction —
    no integer dot_general (unsupported for u64 by the TPU X64 rewriter).
    a, b: [..., N] with limb magnitudes small enough that 24 accumulated
    pairwise products fit uint64 (callers document bounds)."""
    rows = []
    for i in range(NLIMBS):
        left = min(i, out_len)
        right = max(out_len - NLIMBS - left, 0)
        keep = out_len - left - right
        row = b[..., :keep]
        pad = [(0, 0)] * (b.ndim - 1) + [(left, right)]
        rows.append(jnp.pad(row, pad))
    stacked = jnp.stack(rows, axis=-2)  # [..., N, out_len]
    return jnp.sum(a[..., :, None] * stacked, axis=-2)


# --- carry machinery --------------------------------------------------------


def _gp_combine(lo, hi):
    """The carry-lookahead monoid on (generate, propagate) bit pairs."""
    g1, p1 = lo
    g2, p2 = hi
    return (g2 | (p2 & g1), p1 & p2)


def _carry_fix(s):
    """Exact carry propagation for limbs in [0, 2^16] (at most 1-bit carry):
    returns 16-bit limbs; the final carry-out is dropped (callers guarantee
    the value fits the buffer)."""
    g = (s >> _SHIFT) != 0
    p = (s & _MASK) == _MASK
    G, _ = lax.associative_scan(_gp_combine, (g, p), axis=-1)
    carry_in = jnp.concatenate(
        [jnp.zeros_like(G[..., :1]), G[..., :-1]], axis=-1
    )
    return (s + carry_in) & _MASK


def _norm_exact(t, buf):
    """Redundant limbs (< 2^58) -> exact 16-bit limbs in a `buf`-limb buffer.
    The represented value must be < 2^(16*buf)."""
    pad = buf - t.shape[-1]
    if pad > 0:
        t = jnp.concatenate(
            [t, jnp.zeros(t.shape[:-1] + (pad,), dtype=jnp.uint64)], axis=-1
        )
    # three halving passes: 2^58 -> 2^42+ -> 2^26+ -> <= 2^16
    for _ in range(3):
        lo = t & _MASK
        hi = t >> _SHIFT
        t = lo + jnp.concatenate(
            [jnp.zeros_like(hi[..., :1]), hi[..., :-1]], axis=-1
        )
    return _carry_fix(t)


def _borrow_scan(a, b):
    """Borrow-lookahead for a - b per 16-bit limb vectors: returns
    (difference limbs mod 2^16, full-width borrow bool)."""
    bg = a < b
    bp = a == b
    BG, _ = lax.associative_scan(_gp_combine, (bg, bp), axis=-1)
    borrow_in = jnp.concatenate(
        [jnp.zeros_like(BG[..., :1]), BG[..., :-1]], axis=-1
    )
    d = (a - b - borrow_in.astype(jnp.uint64)) & _MASK
    return d, BG[..., -1]


def _cond_sub_p(r):
    """r (16-bit limbs, value < 2p) -> r mod p, canonical."""
    d, borrow = _borrow_scan(r, _P_J)
    return jnp.where(borrow[..., None], r, d)


# --- public ops -------------------------------------------------------------


def zeros_like(a):
    return jnp.zeros_like(a)


def ones_mont(shape=()):
    return jnp.broadcast_to(_ONE_M_J, tuple(shape) + (NLIMBS,))


def add(a, b):
    s = a + b  # <= 2^17 - 2 per limb
    lo = s & _MASK
    hi = s >> _SHIFT
    s = lo + jnp.concatenate(
        [jnp.zeros_like(hi[..., :1]), hi[..., :-1]], axis=-1
    )  # <= 2^16: 1-bit carries now
    return _cond_sub_p(_carry_fix(s))


def sub(a, b):
    d, borrow = _borrow_scan(a, b)
    # underflow lanes: add p back (value wraps mod 2^384; carry-out drops)
    s = d + _P_J
    lo = s & _MASK
    hi = s >> _SHIFT
    s = lo + jnp.concatenate(
        [jnp.zeros_like(hi[..., :1]), hi[..., :-1]], axis=-1
    )
    dp = _carry_fix(s)
    return jnp.where(borrow[..., None], dp, d)


def neg(a):
    return sub(zeros_like(a), a)


def mul(a, b):
    """Montgomery product a * b * 2^-384 mod p, canonical output.

    Inputs: canonical 16-bit limbs (< p)."""
    t = _school(a, b, 2 * NLIMBS - 1)  # 47 limbs < 24*2^32 = 2^36.6
    # m = t * N' mod 2^384: truncated product of redundant t_lo by 16-bit N'
    # -> limbs < 24 * 2^36.6 * 2^16 = 2^57.2; normalize to a true value
    # < 2^384 before multiplying by p (REDC requires m < R).
    m_red = _school(t[..., :NLIMBS], _NPRIME_J, NLIMBS)
    m = _norm_exact(m_red, buf=NLIMBS + 4)[..., :NLIMBS]  # mod 2^384, 16-bit
    u = _school(m, _P_J, 2 * NLIMBS - 1)  # 47 limbs < 2^36.6
    # t + m*p: divisible by 2^384; high half plus the low half's carry-out.
    w = t + u  # limbs < 2^37.6
    lo_norm = _norm_exact(w[..., :NLIMBS], buf=NLIMBS + 3)
    # limbs [0:24] of lo_norm are zero (REDC exactness); [24:27] are the
    # carry into the high half.
    hi = w[..., NLIMBS:]  # 23 limbs < 2^37.6
    hi = jnp.concatenate(
        [hi, jnp.zeros(hi.shape[:-1] + (1,), dtype=jnp.uint64)], axis=-1
    )
    hi = hi.at[..., :3].add(lo_norm[..., NLIMBS : NLIMBS + 3])
    r = _norm_exact(hi, buf=NLIMBS)  # value < 2p < 2^382: fits 24 limbs
    return _cond_sub_p(r)


def sq(a):
    return mul(a, a)


def mul_small(a, k):
    """a * k for tiny static k (2..12) via an addition chain."""
    if k == 0:
        return zeros_like(a)
    if k == 1:
        return a
    half = mul_small(a, k // 2)
    dbl = add(half, half)
    return add(dbl, a) if k & 1 else dbl


def pow_static(a, e):
    """a^e for a static positive int exponent, as a scan over its bits."""
    assert e > 0
    bits = jnp.array([int(c) for c in bin(e)[2:]], dtype=jnp.uint64)

    def body(acc, bit):
        acc = mul(acc, acc)
        with_mul = mul(acc, a)
        acc = jnp.where(bit == 1, with_mul, acc)
        return acc, None

    init = ones_mont(a.shape[:-1])
    acc, _ = lax.scan(body, init, bits)
    return acc


def inv(a):
    """a^{p-2}; returns 0 for input 0 (callers mask identities explicitly)."""
    return pow_static(a, P - 2)


def is_zero(a):
    return jnp.all(a == 0, axis=-1)


def eq(a, b):
    return jnp.all(a == b, axis=-1)


def select(mask, a, b):
    """mask [...] bool -> a where true else b (limb arrays)."""
    return jnp.where(mask[..., None], a, b)
