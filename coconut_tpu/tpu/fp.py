"""Batched Fp (BLS12-381 base field) arithmetic on balanced 8-bit limbs in f32.

Every function operates on arrays of shape [..., NLIMBS] (leading dims =
batch). Elements are in the Montgomery domain (R = 2^384) in a REDUNDANT
balanced representation:

  value = sum_i limb_i * 256^i,  limb_i in [-135, 135],  value in [0, B_MAX)

with B_MAX (~2p) chosen so B_MAX^2 <= R*p — Montgomery reduction stays valid
without ever producing a canonical (< p) value. Canonicalization happens on
the host (decode reduces mod p) and inside the exact predicates `eq` /
`is_zero` only.

Why this representation (SURVEY.md §7 hard part (a), third redesign):

  - schoolbook limb products run ON THE MXU: outer product (exact f32,
    |products| <= 135^2 < 2^15), split into two balanced byte planes
    (|.| <= 128, exact bf16), each contracted with a static 0/1 band matrix
    via bf16 matmuls with exact f32 accumulation (sums of <= 48 terms).
  - NO carry/borrow scans anywhere: balanced limbs converge under the
    shift/round "light pass" (|limb| drops 256x per pass to a <= 130 fixed
    band) with no 0xFF-chain plateau, unlike non-negative limbs which need
    carry-lookahead — the previous design spent 75% of its HLO (and tens of
    minutes of XLA compile time) on `lax.associative_scan` carry fixes.
  - exact zero test without canonicalization: once |limb| <= 254, a nonzero
    limb k dominates the lower tail (|sum_{i<k} limb_i 256^i| < 256^k), so
    value == 0  <=>  every limb == 0 (downward induction). `eq`/`is_zero`
    test the handful of multiples of p their bounded ranges allow.
  - signed-carry safety: a light pass drops the carry out of the top buffer
    limb, so every normalization that must preserve the full value runs in a
    buffer extended by `_EXTRA` limbs; value bounds (commented per site)
    prove the extension limbs end at zero — except where truncation mod
    2^384 is intended (the two inner REDC normalizations).

The import-time asserts pin the exact bounds the algebra relies on.
"""

import os

import numpy as np

import jax.numpy as jnp
from jax import lax

from ..ops.fields import P
from .limbs import MONT_R, NLIMBS, balanced_limbs

# --- bounds (exact integer arithmetic at import time) -----------------------

# Top estimate uses limbs 46..48: s = l48*2^16 + l47*2^8 + l46 approximates
# value/2^368 with error |tail| <= TAIL (the 46 lower balanced limbs).
_TAIL = 135 * ((256**46 - 1) // 255)
# masked subtract of 2p is safe (value certainly >= 2p) when s >= THRESH:
_THRESH = (2 * P + _TAIL) // (1 << (8 * 46)) + 1
# and a value that misses the test is certainly below B_MAX:
B_MAX = _THRESH * (1 << (8 * 46)) + _TAIL

assert _THRESH * (1 << (8 * 46)) - _TAIL >= 2 * P  # safety of the subtract
assert B_MAX * B_MAX <= MONT_R * P  # Montgomery reduction valid
# mul output bound: t/R + |m|*p/R + p  with |m| <= 0.51*2^384:
assert B_MAX * B_MAX // MONT_R + P * 51 // 100 + P + 4 < B_MAX
# add/sub enter _reduce with value < max(2*B_MAX, B_MAX + 4p); each masked
# round either certifies value < B_MAX (miss, by construction of B_MAX) or
# subtracts 2p; three rounds therefore always land below B_MAX:
assert 2 * B_MAX - 6 * P < B_MAX and B_MAX + 4 * P - 6 * P < B_MAX
# slicing the 4p constant to 48 limbs must not drop a top carry:
assert all(v == 0.0 for v in balanced_limbs(4 * P, NLIMBS + 1)[NLIMBS:])

_BASE = 256.0
_INV_BASE = 1.0 / 256.0
_EXTRA = 3  # buffer headroom: carries travel <= 1 limb per pass, 3 passes

_P2_J = jnp.asarray(balanced_limbs(2 * P, NLIMBS + _EXTRA), dtype=jnp.float32)
_P_BAL_J = jnp.asarray(balanced_limbs(P), dtype=jnp.float32)
_NPRIME_J = jnp.asarray(
    balanced_limbs((-pow(P, -1, MONT_R)) % MONT_R, wrap=True),
    dtype=jnp.float32,
)
_ONE_M_J = jnp.asarray(balanced_limbs(MONT_R % P), dtype=jnp.float32)
# candidate multiples of p for the exact predicates (49-limb buffers: 5p..6p
# exceed what 48 balanced limbs can represent)
_PK_J = [
    jnp.asarray(balanced_limbs(k * P, NLIMBS + 1), dtype=jnp.float32)
    for k in range(7)
]

# Static band matrix: BAND[i*NLIMBS + j, k] = 1 iff i + j == k.
_BAND_NP = np.zeros((NLIMBS * NLIMBS, 2 * NLIMBS - 1), dtype=np.float32)
for _i in range(NLIMBS):
    for _j in range(NLIMBS):
        _BAND_NP[_i * NLIMBS + _j, _i + _j] = 1.0
_BAND = jnp.asarray(_BAND_NP, dtype=jnp.bfloat16)
_BAND_I8 = jnp.asarray(_BAND_NP, dtype=jnp.int8)

# int8 MXU path (default): the same two byte planes contracted as
# int8 x int8 -> int32 matmuls — native int8 MXU peak is 2x bf16 on v5e and
# every intermediate is still exact (planes in [-128, 127] by the floor
# split; band sums <= 48*128 < 2^31). COCONUT_FP_INT8=0 falls back to bf16.
_USE_INT8 = os.environ.get("COCONUT_FP_INT8", "1") == "1"


def _school(a, b, out_len):
    """Polynomial limb product c_k = sum_{i+j=k} a_i * b_j, truncated to
    out_len limbs. |a_i|,|b_j| <= 135: outer products <= 135^2 < 2^15 (exact
    f32); split into two byte planes with hi = floor((t+128)/256), so
    lo = t - 256*hi in [-128, 127] and |hi| <= 72 — both exact in int8/bf16;
    band sums of <= 48 terms accumulate exactly in int32/f32 on the MXU;
    recombined coefficients <= 48*135^2 < 2^20 (exact f32)."""
    outer = a[..., :, None] * b[..., None, :]
    flat = outer.reshape(outer.shape[:-2] + (NLIMBS * NLIMBS,))
    hi = jnp.floor((flat + 128.0) * _INV_BASE)
    lo = flat - hi * _BASE
    if _USE_INT8:
        band = _BAND_I8[:, :out_len]
        acc_lo = jnp.einsum(
            "...x,xk->...k",
            lo.astype(jnp.int8),
            band,
            preferred_element_type=jnp.int32,
        )
        acc_hi = jnp.einsum(
            "...x,xk->...k",
            hi.astype(jnp.int8),
            band,
            preferred_element_type=jnp.int32,
        )
        return (acc_lo + acc_hi * 256).astype(jnp.float32)
    band = _BAND[:, :out_len]
    acc_lo = jnp.einsum(
        "...x,xk->...k",
        lo.astype(jnp.bfloat16),
        band,
        preferred_element_type=jnp.float32,
    )
    acc_hi = jnp.einsum(
        "...x,xk->...k",
        hi.astype(jnp.bfloat16),
        band,
        preferred_element_type=jnp.float32,
    )
    return acc_lo + acc_hi * _BASE


def _shift_up(hi):
    """Move per-limb carries one limb up. Drops the top limb's carry —
    callers either extend the buffer (value-preserving sites) or intend
    truncation mod 2^(8*buflen) (the inner REDC sites)."""
    return jnp.concatenate([jnp.zeros_like(hi[..., :1]), hi[..., :-1]], axis=-1)


def _pass(t):
    """One balanced shift/round pass: exact (power-of-two scalings and
    integer adds below 2^24), |limb| drops 256x toward the <= 130 band."""
    hi = jnp.round(t * _INV_BASE)
    lo = t - hi * _BASE
    return lo + _shift_up(hi)


def _norm(t, passes=3):
    """|limbs| < 2^21 -> |limbs| <= 130 (value preserved up to top-limb
    truncation; see _shift_up). Pass bounds: 2^21 -> 128+2^13 -> 128+33 ->
    128+2."""
    for _ in range(passes):
        t = _pass(t)
    return t


def _ext(t, extra):
    return jnp.concatenate(
        [t, jnp.zeros(t.shape[:-1] + (extra,), dtype=jnp.float32)], axis=-1
    )


def _top_estimate(t):
    """s ~= value/2^368 from limbs 46..48 (post-_norm: |l48| <= 1 whenever
    value < 2^384, so |s| < 2^17 — exact f32)."""
    return (
        t[..., NLIMBS] * 65536.0
        + t[..., NLIMBS - 1] * _BASE
        + t[..., NLIMBS - 2]
    )


def _reduce(t):
    """Post-add/sub reduction in an extended buffer: value < 2*B_MAX + 4p ->
    value < B_MAX, |limbs| <= 130, sliced back to 48 limbs (value < B_MAX
    < 2^383 forces the extension limbs to zero)."""
    t = _norm(_ext(t, _EXTRA))
    for _ in range(3):
        mask = _top_estimate(t) >= float(_THRESH)
        t = t - jnp.where(mask[..., None], _P2_J, 0.0)
        t = _pass(t)
    return t[..., :NLIMBS]


# --- public ops -------------------------------------------------------------


def zeros_like(a):
    return jnp.zeros_like(a)


def ones_mont(shape=()):
    return jnp.broadcast_to(_ONE_M_J, tuple(shape) + (NLIMBS,))


def add(a, b):
    return _reduce(a + b)  # |limbs| <= 270; value < 2*B_MAX


def sub(a, b):
    # +4p keeps the value positive (B_MAX < 4p); range (4p-B_MAX, B_MAX+4p)
    return _reduce(a - b + _PK_J[4][..., :NLIMBS])


def neg(a):
    return _reduce(_PK_J[4][..., :NLIMBS] - a)


def mul(a, b):
    """Montgomery product a * b * 2^-384 mod p; values < B_MAX in/out.

    REDC with balanced m: t = a*b; m = (t mod 2^384)*N' mod 2^384 (balanced,
    |m| <= 0.51*2^384 < R); result = (t + m*p + p*R)/2^384 — the p*R term
    keeps the numerator nonnegative despite m's sign (it adds p, still 0
    mod p, to the quotient). Output < B_MAX^2/R^2*... see import asserts."""
    t = _school(a, b, 2 * NLIMBS - 1)  # |limbs| < 2^20
    tlo = _norm(t[..., :NLIMBS])  # t mod 2^384 (truncation intended)
    m = _norm(_school(tlo, _NPRIME_J, NLIMBS))  # |value| <= 0.51*2^384
    u = _school(m, _P_BAL_J, 2 * NLIMBS - 1)  # m*p, |limbs| < 2^20
    w = t + u  # |limbs| < 2^21; value = t + m*p, divisible by 2^384
    # Low half in a value-preserving extended buffer: after _norm the limbs
    # [0:48] are exactly zero (value divisible by 2^384, |limbs| <= 130 —
    # upward induction mod 256), and [48:51] hold the carry into the high
    # half (|carry| = |w_lo|/2^384 <= 2^21*2^377/2^384 < 2^15).
    lo = _norm(_ext(w[..., :NLIMBS], _EXTRA))
    hi = _ext(w[..., NLIMBS:], 1)  # 47 -> 48 limbs
    hi = hi + _P_BAL_J  # the +p*R quotient term (nonnegativity)
    hi = hi.at[..., : _EXTRA].add(lo[..., NLIMBS : NLIMBS + _EXTRA])
    # value < B_MAX^2/R + 0.51p + p < 2.6p < B_MAX (import assert): the
    # extension limbs normalize to zero, slice back.
    return _norm(_ext(hi, _EXTRA))[..., :NLIMBS]


def sq(a):
    return mul(a, a)


def mul_small(a, k):
    """a * k for tiny static k (2..12) via an addition chain (each add
    re-reduces, keeping the value < B_MAX)."""
    if k == 0:
        return zeros_like(a)
    if k == 1:
        return a
    half = mul_small(a, k // 2)
    dbl = add(half, half)
    return add(dbl, a) if k & 1 else dbl


def pow_static(a, e):
    """a^e for a static positive int exponent, as a scan over its bits."""
    assert e > 0
    bits = jnp.array([int(c) for c in bin(e)[2:]], dtype=jnp.int32)

    def body(acc, bit):
        acc = mul(acc, acc)
        with_mul = mul(acc, a)
        acc = jnp.where(bit == 1, with_mul, acc)
        return acc, None

    init = ones_mont(a.shape[:-1])
    acc, _ = lax.scan(body, init, bits)
    return acc


def inv(a):
    """a^{p-2}; returns 0 for input 0 (callers mask identities explicitly)."""
    return pow_static(a, P - 2)


# --- exact predicates -------------------------------------------------------


def _is_zero_value(t):
    """t in a 49-limb buffer, |limbs| <= 131 after _norm: value == 0 <=>
    all limbs zero (a nonzero limb dominates the balanced tail below it)."""
    return jnp.all(t == 0.0, axis=-1)


def _is_multiple_of_p(t49, kmin, kmax):
    """t49: 49-limb normalized buffer, value in (kmin*p - p, (kmax+1)*p):
    test value == k*p for k in [kmin, kmax]."""
    bits = None
    for k in range(kmin, kmax + 1):
        b = _is_zero_value(_norm(t49 - _PK_J[k], passes=2))
        bits = b if bits is None else (bits | b)
    return bits


def is_zero(a):
    """a == 0 mod p (value in [0, B_MAX) => candidates {0, p, 2p})."""
    return _is_multiple_of_p(_norm(_ext(a, 1), passes=1), 0, 2)


def eq(a, b):
    """a == b mod p. d = a - b + 4p is in (4p - B_MAX, 4p + B_MAX) subset
    (p, 7p): candidates 2p..6p (1..6 kept for margin)."""
    d = _norm(_ext(a - b, 1) + _PK_J[4], passes=2)
    return _is_multiple_of_p(d, 1, 6)


def select(mask, a, b):
    """mask [...] bool -> a where true else b (limb arrays)."""
    return jnp.where(mask[..., None], a, b)


# --- stacked-multiply helper (the tower's compile-size lever) ---------------


def mul_stack(lhs_list, rhs_list):
    """Stack S independent products into ONE mul: [(a, b), ...] with shared
    leading dims -> list of S products. Collapses the extension-tower's many
    base-field multiplies into a single MXU contraction (compile-size and
    MXU-utilization win; see tower.py)."""
    L = jnp.stack(jnp.broadcast_arrays(*lhs_list), axis=-2)  # [..., S, N]
    Rv = jnp.stack(jnp.broadcast_arrays(*rhs_list), axis=-2)
    out = mul(L, Rv)
    return [out[..., i, :] for i in range(len(lhs_list))]
