"""Batched Fp (BLS12-381 base field) arithmetic on 52 lazy signed 8-bit
limbs in f32 — the third redesign of SURVEY.md §7 hard part (a).

Every function operates on arrays of shape [..., NLIMBS] (leading dims =
batch). Elements are in the Montgomery domain (R = 2^416) as SIGNED limb
vectors:

  value = sum_i limb_i * 256^i,  52 limbs, f32, |value| tracked by class.

R/p ~ 2^35 of headroom (52*8 = 416 bits vs the 381-bit p) buys LAZY
REDUCTION: between Montgomery multiplies nothing is ever normalized.

Two element classes, maintained by construction (import asserts pin every
bound the algebra relies on):

  NORMALIZED — mul outputs and encoded constants: |limbs| <= 132,
    |value| <= V_NORM = 4p. Tail domination then forces limbs 50 and 51 to
    be EXACTLY zero: |l51| <= (V_NORM + 132*(2^408-1)/255)/2^408 < 1, and
    an integer below 1 is 0 (same for l50). Two vacant top limbs make the
    carry passes inside `mul` value-exact: carries never fall off the top.

  LAZY — any +/-/small-constant combination of normalized values with
    total limb weight <= 2^17/132 (~992 terms; the heaviest real call site
    is the G2 complete-add b3 path at ~432 terms — t5 is a 9-term sum,
    the twist's b3 = 12(1+u) scales it 24x componentwise, and the next
    fp2_mul's Karatsuba a0+a1 doubles it):
    |limbs| <= L_LAZY = 2^17, |value| <= V_LAZY = 1024p, l50 = l51 = 0
    (sums of zeros stay zero).
    The VALUE bound relies on a tighter per-term bound than V_NORM: every
    value actually entering a lazy combination has |value| < p (mul
    outputs are < 0.66p, encoded constants are canonical < p), so even
    the maximal ~992-term combination stays below 992p < V_LAZY = 1024p.
    V_NORM = 4p is only the per-LIMB-shape class bound used by the carry
    vacancy argument above, never the per-term value entering sums.

Consequences:
  - add/sub/neg/mul_small are ELEMENTWISE f32 ops — one HLO instruction,
    no carry chains, no masked subtractions. This is where the previous
    (48-limb, eagerly-reduced) design spent most of its HLO size and VPU
    time: each add ran a 3-pass normalize + 3 masked-subtract rounds.
  - mul: two shift/round passes bring |limbs| <= 132 exactly (carries from
    l49 land in the vacant l50/l51), then one-shot Montgomery REDC with a
    signed m (|m| <= 0.64 R) — no nonnegativity fix-up term. Output value
    bound: V_LAZY^2/R + 0.64p < 0.66p.
  - schoolbook limb products run ON THE MXU: outer products (<= 132^2,
    exact f32) split into two byte planes hi = floor((t+128)/256) in
    [-69, 69] and lo = t - 256*hi in [-128, 127], each contracted against a
    static 0/1 band matrix as int8 x int8 -> int32 matmuls (native int8
    MXU peak is 2x bf16 on v5e; every sum of <= 52 terms is exact in both
    int32 and the bf16->f32 fallback, COCONUT_FP_INT8=0).
  - exact predicates COMPRESS first (one Montgomery mul by the encoded 1):
    the result is normalized with |value| < 0.66p < p, so value == 0 mod p
    iff value == 0 iff every limb is 0 (downward domination at |l| <= 132).

Kept bit-identical to the pure-Python spec (`coconut_tpu.ops.fields`) at
the decode boundary: limbs.fp_decode reduces the signed value mod p.
"""

import os

import numpy as np

import jax.numpy as jnp
from jax import lax

from ..ops.fields import P
from .limbs import MONT_R, NLIMBS, balanced_limbs

# --- bounds (exact integer arithmetic at import time) -----------------------

L_NORM = 132            # normalized limb bound
V_NORM = 4 * P          # normalized value bound
L_LAZY = 1 << 17        # lazy limb bound (mul-input cap)
V_LAZY = 1024 * P       # lazy value bound (mul-input cap)

_TAIL50 = L_NORM * ((256**50 - 1) // 255)
_TAIL51 = L_NORM * ((256**51 - 1) // 255)
# top-limb vacancy of normalized values: l50 = l51 = 0 exactly
assert V_NORM + _TAIL50 < 256**50
assert V_NORM + _TAIL51 < 256**51
# two passes on lazy limbs: pass1 <= 128 + ceil(L_LAZY/256) = 640;
# pass2 <= 128 + 3 = 131 <= L_NORM. Carries land in the vacant top limbs:
# pass1 puts <= 512 in l50, pass2 puts <= 2 in l51, carry out of l51 is 0.
_P1 = 128 + (L_LAZY + 128) // 256
assert 128 + (_P1 + 128) // 256 <= L_NORM
assert (_P1 + 128) // 256 < 128  # l51 stays far below a further carry
# byte planes exact in int8: |t| <= 132^2 => hi in [-69,69], lo in [-128,127]
assert L_NORM * L_NORM <= 127 * 256 + 127
# school coefficients: sums of <= 52 products, exact f32/int32
assert NLIMBS * L_NORM * L_NORM < 1 << 24
# REDC: |m| <= 0.64 R (m limbs <= 132 after 3 passes: 132*256/255/256 < 0.52,
# use 0.64 for slack); |out| <= V_LAZY^2/R + 0.64p < 0.66p < V_NORM
assert V_LAZY * V_LAZY // MONT_R + 64 * P // 100 + 1 < 2 * P // 3
# mul-internal coefficient bound (t + m*p): < 2^22, exact f32 adds
assert NLIMBS * L_NORM * L_NORM * 2 < 1 << 22

_BASE = 256.0
_INV_BASE = 1.0 / 256.0

_P_BAL_J = jnp.asarray(balanced_limbs(P), dtype=jnp.float32)
_NPRIME_J = jnp.asarray(
    balanced_limbs((-pow(P, -1, MONT_R)) % MONT_R, wrap=True),
    dtype=jnp.float32,
)
_ONE_M_J = jnp.asarray(balanced_limbs(MONT_R % P), dtype=jnp.float32)

# Static band matrix: BAND[i*NLIMBS + j, k] = 1 iff i + j == k.
_BAND_NP = np.zeros((NLIMBS * NLIMBS, 2 * NLIMBS - 1), dtype=np.float32)
for _i in range(NLIMBS):
    for _j in range(NLIMBS):
        _BAND_NP[_i * NLIMBS + _j, _i + _j] = 1.0
_BAND = jnp.asarray(_BAND_NP, dtype=jnp.bfloat16)
_BAND_I8 = jnp.asarray(_BAND_NP, dtype=jnp.int8)
_USE_INT8 = os.environ.get("COCONUT_FP_INT8", "1") == "1"


def _school(a, b, out_len):
    """Polynomial limb product c_k = sum_{i+j=k} a_i * b_j, truncated to
    out_len limbs. Inputs |a_i|,|b_j| <= 132 (see import asserts)."""
    outer = a[..., :, None] * b[..., None, :]
    lead = outer.shape[:-2]
    # Collapse ALL leading dims to one before the contraction: the axon TPU
    # backend miscompiles int8 dot_generals with multi-dim einsum batches
    # when several such contractions fuse in one program (observed as
    # wrong results in exactly one column of a [B, 2, ...] batch at
    # B >= 256; a 2-D [N, x] @ [x, k] matmul is always correct).
    flat = outer.reshape((-1, NLIMBS * NLIMBS))
    if _USE_INT8:
        # byte-plane split in integer arithmetic (f32 products are exact
        # ints < 2^24; >> is an arithmetic shift, i.e. floor division)
        flat_i = flat.astype(jnp.int32)
        hi_i = (flat_i + 128) >> 8
        lo_i = flat_i - (hi_i << 8)
        acc_lo = jnp.dot(
            lo_i.astype(jnp.int8),
            _BAND_I8[:, :out_len],
            preferred_element_type=jnp.int32,
        )
        acc_hi = jnp.dot(
            hi_i.astype(jnp.int8),
            _BAND_I8[:, :out_len],
            preferred_element_type=jnp.int32,
        )
        out = (acc_lo + acc_hi * 256).astype(jnp.float32)
        return out.reshape(lead + (out_len,))
    hi = jnp.floor((flat + 128.0) * _INV_BASE)
    lo = flat - hi * _BASE
    acc_lo = jnp.dot(
        lo.astype(jnp.bfloat16),
        _BAND[:, :out_len],
        preferred_element_type=jnp.float32,
    )
    acc_hi = jnp.dot(
        hi.astype(jnp.bfloat16),
        _BAND[:, :out_len],
        preferred_element_type=jnp.float32,
    )
    return (acc_lo + acc_hi * _BASE).reshape(lead + (out_len,))


def _shift_up(hi):
    """Move per-limb carries one limb up (drops the top limb's carry —
    exact at every call site by the vacancy/zero-coefficient arguments in
    `mul`, or truncation mod 2^416 is intended)."""
    return jnp.concatenate([jnp.zeros_like(hi[..., :1]), hi[..., :-1]], axis=-1)


def _pass(t):
    """One shift/round carry pass: exact power-of-two scalings and integer
    adds below 2^24; |limb| drops ~256x toward the <= 132 band."""
    hi = jnp.round(t * _INV_BASE)
    lo = t - hi * _BASE
    return lo + _shift_up(hi)


def _norm(t, passes):
    for _ in range(passes):
        t = _pass(t)
    return t


def _ext(t, extra):
    return jnp.concatenate(
        [t, jnp.zeros(t.shape[:-1] + (extra,), dtype=jnp.float32)], axis=-1
    )


# --- public ops -------------------------------------------------------------


def zeros_like(a):
    return jnp.zeros_like(a)


def ones_mont(shape=()):
    return jnp.broadcast_to(_ONE_M_J, tuple(shape) + (NLIMBS,))


def add(a, b):
    return a + b


def sub(a, b):
    return a - b


def neg(a):
    return -a


def mul_small(a, k):
    """a * k for small static nonnegative k — elementwise (lazy)."""
    if k == 0:
        return jnp.zeros_like(a)
    if k == 1:
        return a
    return a * float(k)


def mul(a, b):
    """Montgomery product a * b * 2^-416 mod p. Inputs LAZY (|limbs| <=
    L_LAZY = 2^17, |value| <= V_LAZY = 1024p, top two limbs zero), output
    NORMALIZED (|limbs| <= 132, |value| < 0.66p).

    Signed one-shot REDC: t = a*b; m = (t mod 2^416)*N' mod 2^416 (signed,
    |m| <= 0.64 R); u = (t + m*p) / 2^416 — exact division, no
    nonnegativity term needed (values may be negative).

    On TPU the whole pipeline runs as one fused Pallas kernel
    (pallas_fp.py) so no intermediate ever touches HBM; the XLA
    formulation below is the CPU/fallback path (bit-identical)."""
    from . import pallas_fp

    if pallas_fp.enabled():
        return pallas_fp.mul(a, b)
    a1 = _norm(a, 2)  # |limbs| <= 132; carries land in vacant l50/l51
    b1 = _norm(b, 2)
    t = _school(a1, b1, 2 * NLIMBS - 1)  # |coeff| < 2^21
    tlo = _norm(t[..., :NLIMBS], 3)  # t mod 2^416 (truncation intended)
    m = _norm(_school(tlo, _NPRIME_J, NLIMBS), 3)  # signed, trunc mod 2^416
    w = t + _school(m, _P_BAL_J, 2 * NLIMBS - 1)  # = t + m*p, |coeff| < 2^22
    # Low half: value divisible by 2^416 and |coeffs| normalized => limbs
    # [0:52] end exactly zero; the carry into the high half sits in the
    # extension limbs (|carry| <= 2^14, fits 3 limbs).
    lo = _norm(_ext(w[..., :NLIMBS], 3), 3)
    hi = _ext(w[..., NLIMBS:], 1)  # 51 -> 52 limbs
    hi = hi.at[..., :3].add(lo[..., NLIMBS : NLIMBS + 3])
    # w's nonzero coefficients stop by index 102 (inputs have l50=l51~0),
    # so the high half's top limbs stay small: 3 passes normalize exactly.
    return _norm(hi, 3)


_R2_BAL_J = jnp.asarray(
    balanced_limbs(MONT_R * MONT_R % P), dtype=jnp.float32
)

# Raw canonical base-256 digits (0..255 per limb) are valid LAZY mul
# inputs: 255 <= L_LAZY, value < p <= V_LAZY, and p < 2^381 < 256^48 so a
# 48-byte value leaves limbs 48..51 exactly zero after padding.
assert 255 <= L_LAZY and P <= V_LAZY and P < 256**48


def to_mont(t):
    """Raw canonical limbs -> Montgomery domain, on device.

    `t` is uint8/float [..., 48 or 52] raw base-256 digits of a canonical
    Fp value (limbs.fp_encode_raw_batch). One Montgomery multiply by R^2
    gives x * R^2 * R^-1 = x * R mod p — the same value fp_encode computes
    with host bigints, via the existing exact mul kernel (XLA or Pallas),
    so downstream arithmetic is bit-identical to the host-encoded path."""
    if t.dtype != jnp.float32:
        t = t.astype(jnp.float32)
    if t.shape[-1] < NLIMBS:
        t = _ext(t, NLIMBS - t.shape[-1])
    return mul(t, _R2_BAL_J)


_ONE_RAW_J = jnp.zeros((NLIMBS,), jnp.float32).at[0].set(1.0)


def from_mont(t):
    """Montgomery limbs -> limbs whose VALUE is the standard-domain
    representative mod p: one Montgomery multiply by the raw integer 1
    (x*R * 1 * R^-1 = x). Output is mul-class (|value| < 0.66p). Needed
    wherever device logic must observe the standard-domain value itself
    — e.g. canon_parity as the SvdW map's sgn0, which is defined on the
    canonical integer, not its Montgomery image."""
    return mul(t, _ONE_RAW_J)


def sq(a):
    return mul(a, a)


def pow_static(a, e, window=4):
    """a^e for a static positive int exponent: 4-bit windowed scan.

    Per window: `window` squarings + ONE multiply by a table entry selected
    from the precomputed powers a^0..a^15 (gathered with a one-hot mask —
    cheap VPU selects vs a Montgomery mul). vs the bit-scan's
    square+multiply-every-bit this cuts ~2 muls/bit to ~1.25, which matters
    because `inv` (a^{p-2}, 381 bits) sits inside every to_affine and
    final_exp on full-batch shapes."""
    assert e > 0
    nw = (e.bit_length() + window - 1) // window
    digits = jnp.array(
        [(e >> (window * i)) & ((1 << window) - 1) for i in range(nw - 1, -1, -1)],
        dtype=jnp.int32,
    )
    # table a^0..a^(2^w - 1): leading axis 16, built with 14 muls + encode
    pows = [ones_mont(a.shape[:-1]), a]
    for _ in range(2, 1 << window):
        pows.append(mul(pows[-1], a))
    table = jnp.stack(jnp.broadcast_arrays(*pows), axis=0)  # [16, ..., N]

    def body(acc, d):
        for _ in range(window):
            acc = mul(acc, acc)
        entry = lax.dynamic_index_in_dim(table, d, axis=0, keepdims=False)
        return mul(acc, entry), None

    init = ones_mont(a.shape[:-1])
    acc, _ = lax.scan(body, init, digits)
    return acc


def inv(a):
    """a^{p-2}; returns 0 for input 0 (callers mask identities explicitly)."""
    return pow_static(a, P - 2)


# --- canonical byte packing (device-side readback compression) --------------

_TWO_P_DIGITS_NP = np.array(
    [((2 * P) >> (8 * i)) & 0xFF for i in range(NLIMBS)], dtype=np.float32
)
# 2p's top limbs: 2p < 2^382, so digits 48.. are zero — the 48-byte slice
# below is exact for any packed |value| < 2p
assert 2 * P < 1 << 383
CANON_BYTES = 48


def pack_canon48(t):
    """f32 [..., 52] lazy limbs with |value| < 2p and |limbs| <= ~400 ->
    uint8 [..., 48] base-256 digits of (value + 2p), a canonical-width
    representative of value mod p. This is the device half of the
    readback compression: 48 bytes per Fp instead of 104 (int16 x 52) —
    the axon tunnel reads back at 2-8 MB/s, so result bytes are the wall
    cost of every point-returning program (PROFILE_r04.md).

    Exactness: adding 2p's digits (<= 255) to limbs |v| <= ~400 keeps
    every limb in [-400, 655]; the full sequential carry scan (floor
    semantics) produces exact base-256 digits of the nonnegative value
    v + 2p in (0, 4p) subset [0, 2^383), whose digits 48..51 are zero and
    are dropped. Every intermediate is an exact small f32 integer. The
    host inverse is limbs.fp_decode_batch's uint8 path (value mod p after
    the Montgomery divide).

    Scan width: this scan carries a flat [lanes] f32 (no limb dim) and
    stacks [52, lanes] — a DIFFERENT shape family from the comb-build
    scans the axon backend corrupts above ~1028 carry lanes
    (probes/README.md). Probed bit-exact on the chip at 2,048 / 8,192 /
    65,536 lanes, all lanes checked, including negative-value lazy
    inputs (probes/probe_pack.py, 2026-08-01); re-run that probe if the
    scan structure here changes."""
    digsT = _canon_digits(t)
    digs = jnp.moveaxis(digsT, 0, -1)
    return digs[..., :CANON_BYTES].astype(jnp.uint8)


def _canon_digits(t):
    """Exact base-256 digits of (value + 2p), limb-major [52, ...] —
    the shared carry scan behind pack_canon48 and canon_parity. Same
    contract as pack_canon48: |value| < 2p, |limbs| <= ~400."""
    v = t + jnp.asarray(_TWO_P_DIGITS_NP)

    def step(c, d):
        s = d + c
        hi = jnp.floor(s * _INV_BASE)
        return hi, s - hi * _BASE

    vT = jnp.moveaxis(v, -1, 0)  # [52, ...]
    _, digsT = lax.scan(step, jnp.zeros(v.shape[:-1], v.dtype), vT)
    return digsT


def canon_parity(t):
    """sgn0 of t: the parity bit of the canonical representative of t
    mod p, on device — the SvdW map's y-sign test (ops/hashing.py:
    fp_sgn0(a) = a & 1 on the canonical value).

    Contract: NORMALIZED-class limbs with |value| < p (every fp.mul /
    pow_static output qualifies at |value| < 0.66p). Then w = value + 2p
    lies in (p, 3p), so the canonical value is w - 2p when w >= 2p and
    w - p otherwise; p is odd, so parity(canonical) = parity(w) flipped
    exactly when w < 2p. Both ingredients come from the same exact digit
    scan as pack_canon48: parity(w) is digit 0 mod 2, and w >= 2p is a
    lexicographic digit compare against 2p's digits (MS digit first;
    value-0 inputs hit w == 2p exactly and return 0, matching
    sgn0(0) = 0)."""
    digsT = _canon_digits(t)  # [52, ...] exact digits of value + 2p
    twop = jnp.asarray(_TWO_P_DIGITS_NP)
    cmp = jnp.zeros(digsT.shape[1:], digsT.dtype)
    for i in range(NLIMBS - 1, -1, -1):  # first nonzero diff from MSB wins
        d = jnp.sign(digsT[i] - twop[i])
        cmp = jnp.where(cmp != 0.0, cmp, d)
    ge2p = cmp >= 0.0
    par_w = jnp.mod(digsT[0], 2.0) != 0.0
    return jnp.where(ge2p, par_w, ~par_w)


# --- exact predicates (compress, then all-limbs-zero) -----------------------


def is_zero(a):
    """a == 0 mod p for any LAZY a: one Montgomery mul by the encoded 1
    compresses to a normalized value with |value| < p, which is 0 mod p
    iff it is 0 iff every limb is 0 (downward domination)."""
    c = mul(a, ones_mont(a.shape[:-1]))
    return jnp.all(c == 0.0, axis=-1)


def is_zero_many(vals):
    """[v, ...] -> [v == 0 mod p, ...] with ALL the compress-muls stacked
    into one MXU contraction (the tower predicates' batching lever)."""
    ones = ones_mont(vals[0].shape[:-1])
    outs = mul_stack(vals, [ones] * len(vals))
    return [jnp.all(o == 0.0, axis=-1) for o in outs]


def eq(a, b):
    return is_zero(a - b)


def select(mask, a, b):
    """mask [...] bool -> a where true else b (limb arrays)."""
    return jnp.where(mask[..., None], a, b)


# --- stacked-multiply helper (the tower's compile-size lever) ---------------


def mul_stack(lhs_list, rhs_list):
    """Stack S independent products into ONE mul: [(a, b), ...] with shared
    leading dims -> list of S products. Collapses tower/curve formulas'
    many base-field multiplies into a single MXU contraction."""
    L = jnp.stack(jnp.broadcast_arrays(*lhs_list), axis=-2)  # [..., S, N]
    Rv = jnp.stack(jnp.broadcast_arrays(*rhs_list), axis=-2)
    out = mul(L, Rv)
    return [out[..., i, :] for i in range(len(lhs_list))]
