"""GLV endomorphism acceleration for G1 distinct-base MSMs.

BLS12-381's E(Fp) carries the efficiently-computable endomorphism
phi(x, y) = (beta * x, y) with phi(P) = lambda * P, where beta is a cube
root of unity in Fp and lambda = z^2 - 1 (z the BLS parameter) is a cube
root of unity mod r (lambda^2 + lambda + 1 == 0 mod r; proved by the
import-time asserts below, and phi's eigenvalue is differentially tested
against the spec ops in tests/test_backends.py).

Because lambda ~ 2^127.1 and r ~ 2^254.9, the scalar decomposition needs
no lattice reduction: the plain Euclidean split

    k = k2 * lambda + k1,   k1 = k mod lambda < 2^128,
                            k2 = k div lambda < 2^128

is exact over the integers with both halves NONNEGATIVE, so

    k * P = k1 * P + k2 * phi(P)

turns one 255-bit scalar on one base into two <= 128-bit scalars on two
bases. For the Horner-style distinct-base MSM (curve.msm_distinct_signed:
5 doublings per window) this halves the doubling chain (52 -> 27 windows)
while keeping the add count — the win the grouped/comb schedules cannot
get from GLV (they have no doublings; VERDICT r3 item 3 analysis in
BASELINE.md). phi itself costs one host-side Fp mul per base (beta * x).

Reference workload this accelerates: the issuance MSMs
(signature.rs:396-428) and the show prover's sigma re-randomization
(pok_sig.rs:85-95 surface), both routed through msm_g1_distinct.
"""

from ..ops.fields import P, R

# BLS parameter z and the G1 eigenvalue lambda = z^2 - 1 (see module doc).
Z = -0xD201000000010000
LAMBDA = (Z * Z - 1) % R
# The cube root of unity in Fp matching phi(P) = lambda * P on G1 (the
# OTHER root pairs with lambda^2; checked by tests/test_backends.py).
BETA = 0x1A0111EA397FE699EC02408663D4DE85AA0D857D89759AD4897D29650FB85F9B409427EB4F49FFFD8BFD00000000AAAC

# lambda is a primitive cube root of unity mod r, beta one in Fp
assert (LAMBDA * LAMBDA + LAMBDA + 1) % R == 0
assert BETA != 1 and pow(BETA, 3, P) == 1

# Window budget for the decomposed halves: both are < 2^128, so ceil(128/5)
# signed 5-bit windows plus one carry window cover them (the same bound the
# 128-bit combiner scalars use, backend._R_NWIN).
HALF_BITS = 128
NWIN_5 = -(-HALF_BITS // 5) + 1  # 27

assert LAMBDA.bit_length() == 128
assert (R - 1) // LAMBDA < 1 << HALF_BITS


def decompose(k):
    """k (mod r) -> (k1, k2) with k = k1 + k2 * lambda, both in [0, 2^128)."""
    # lint: allow(const-time, CONSTTIME.md §1 host caveat - CPython big-int
    # divmod cost tracks bit length; accepted on the host recode path)
    k = int(k) % R
    return k % LAMBDA, k // LAMBDA


def phi(pt):
    """The endomorphism on a spec G1 point tuple (None = identity)."""
    if pt is None:
        return None
    return (pt[0] * BETA % P, pt[1])
