"""Extension-field tower Fp2/Fp6/Fp12 over the limb Fp — batched, as pytrees.

Mirrors `coconut_tpu.ops.fields` exactly (same tower construction
u^2 = -1, v^3 = xi = u+1, w^2 = v; same Karatsuba/complex formulas) so decoded
results are bit-identical to the spec. Elements are tuples of Fp limb arrays,
which makes every value a JAX pytree that flows through scan/jit unchanged.

Additionally provides the sparse Fp12 x line multiplication for the Miller
loop (`mul_line`): lines have only the (w^0, w^2, w^3) components (see
`ops.pairing.line_to_fp12`), costing 15 Fp2 products instead of a full 54-mul
Fp12 multiply.
"""

import jax
import jax.numpy as jnp

from ..ops import fields as F
from . import fp
from .limbs import NLIMBS, fp_encode

# --- codecs (host-side) -----------------------------------------------------


def encode_batch(elems):
    """List of same-structure spec elements (ints / nested tuples) ->
    pytree of Montgomery limb arrays with leading batch dim."""
    first = elems[0]
    if isinstance(first, tuple):
        return tuple(
            encode_batch([e[i] for e in elems]) for i in range(len(first))
        )
    from .limbs import fp_encode_batch

    return jnp.asarray(fp_encode_batch(elems))


def decode_batch(tree):
    """Inverse of encode_batch: pytree of limb arrays -> list of spec
    elements (canonical ints / nested tuples)."""
    if isinstance(tree, tuple):
        parts = [decode_batch(t) for t in tree]
        return [tuple(p[i] for p in parts) for i in range(len(parts[0]))]
    import numpy as np

    from .limbs import fp_decode_batch

    return fp_decode_batch(np.asarray(tree))


# --- Fp2 --------------------------------------------------------------------


def fp2_encode_const(c):
    """Spec Fp2 (int pair) -> Montgomery limb constant pytree."""
    return (jnp.asarray(fp_encode(c[0])), jnp.asarray(fp_encode(c[1])))


def fp2_add(a, b):
    return (fp.add(a[0], b[0]), fp.add(a[1], b[1]))


def fp2_sub(a, b):
    return (fp.sub(a[0], b[0]), fp.sub(a[1], b[1]))


def fp2_neg(a):
    return (fp.neg(a[0]), fp.neg(a[1]))


def fp2_mul(a, b):
    t0 = fp.mul(a[0], b[0])
    t1 = fp.mul(a[1], b[1])
    t2 = fp.mul(fp.add(a[0], a[1]), fp.add(b[0], b[1]))
    return (fp.sub(t0, t1), fp.sub(fp.sub(t2, t0), t1))


def fp2_sq(a):
    # (a0+a1)(a0-a1), 2*a0*a1
    return (
        fp.mul(fp.add(a[0], a[1]), fp.sub(a[0], a[1])),
        fp.mul_small(fp.mul(a[0], a[1]), 2),
    )


def fp2_mul_fp(a, s):
    return (fp.mul(a[0], s), fp.mul(a[1], s))


def fp2_mul_small(a, k):
    return (fp.mul_small(a[0], k), fp.mul_small(a[1], k))


def fp2_conj(a):
    return (a[0], fp.neg(a[1]))


def fp2_mul_xi(a):
    """x (u+1): (c0 - c1, c0 + c1)."""
    return (fp.sub(a[0], a[1]), fp.add(a[0], a[1]))


def fp2_inv(a):
    norm = fp.add(fp.sq(a[0]), fp.sq(a[1]))
    ninv = fp.inv(norm)
    return (fp.mul(a[0], ninv), fp.neg(fp.mul(a[1], ninv)))


def fp2_is_zero(a):
    return fp.is_zero(a[0]) & fp.is_zero(a[1])


def fp2_eq(a, b):
    return fp.eq(a[0], b[0]) & fp.eq(a[1], b[1])


def fp2_select(mask, a, b):
    return (fp.select(mask, a[0], b[0]), fp.select(mask, a[1], b[1]))


def fp2_zeros(shape=()):
    z = jnp.zeros(tuple(shape) + (NLIMBS,), dtype=jnp.uint64)
    return (z, z)


def fp2_ones(shape=()):
    return (fp.ones_mont(shape), jnp.zeros(tuple(shape) + (NLIMBS,), dtype=jnp.uint64))


# --- Fp6 --------------------------------------------------------------------


def fp6_add(a, b):
    return tuple(fp2_add(x, y) for x, y in zip(a, b))


def fp6_sub(a, b):
    return tuple(fp2_sub(x, y) for x, y in zip(a, b))


def fp6_neg(a):
    return tuple(fp2_neg(x) for x in a)


def fp6_mul(a, b):
    a0, a1, a2 = a
    b0, b1, b2 = b
    t0 = fp2_mul(a0, b0)
    t1 = fp2_mul(a1, b1)
    t2 = fp2_mul(a2, b2)
    c0 = fp2_add(
        t0,
        fp2_mul_xi(
            fp2_sub(fp2_sub(fp2_mul(fp2_add(a1, a2), fp2_add(b1, b2)), t1), t2)
        ),
    )
    c1 = fp2_add(
        fp2_sub(fp2_sub(fp2_mul(fp2_add(a0, a1), fp2_add(b0, b1)), t0), t1),
        fp2_mul_xi(t2),
    )
    c2 = fp2_add(
        fp2_sub(fp2_sub(fp2_mul(fp2_add(a0, a2), fp2_add(b0, b2)), t0), t2), t1
    )
    return (c0, c1, c2)


def fp6_mul_by_01(a, s0, s1):
    """a * (s0 + s1 v) — sparse, 6 Fp2 products."""
    a0, a1, a2 = a
    return (
        fp2_add(fp2_mul(a0, s0), fp2_mul_xi(fp2_mul(a2, s1))),
        fp2_add(fp2_mul(a1, s0), fp2_mul(a0, s1)),
        fp2_add(fp2_mul(a2, s0), fp2_mul(a1, s1)),
    )


def fp6_mul_by_1(a, s1):
    """a * (s1 v) — sparse, 3 Fp2 products."""
    a0, a1, a2 = a
    return (fp2_mul_xi(fp2_mul(a2, s1)), fp2_mul(a0, s1), fp2_mul(a1, s1))


def fp6_mul_by_v(a):
    return (fp2_mul_xi(a[2]), a[0], a[1])


def fp6_inv(a):
    a0, a1, a2 = a
    c0 = fp2_sub(fp2_sq(a0), fp2_mul_xi(fp2_mul(a1, a2)))
    c1 = fp2_sub(fp2_mul_xi(fp2_sq(a2)), fp2_mul(a0, a1))
    c2 = fp2_sub(fp2_sq(a1), fp2_mul(a0, a2))
    t = fp2_add(
        fp2_mul_xi(fp2_add(fp2_mul(a2, c1), fp2_mul(a1, c2))), fp2_mul(a0, c0)
    )
    tinv = fp2_inv(t)
    return (fp2_mul(c0, tinv), fp2_mul(c1, tinv), fp2_mul(c2, tinv))


def fp6_select(mask, a, b):
    return tuple(fp2_select(mask, x, y) for x, y in zip(a, b))


def fp6_zeros(shape=()):
    z = fp2_zeros(shape)
    return (z, z, z)


def fp6_ones(shape=()):
    return (fp2_ones(shape), fp2_zeros(shape), fp2_zeros(shape))


# --- Fp12 -------------------------------------------------------------------


def fp12_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    t0 = fp6_mul(a0, b0)
    t1 = fp6_mul(a1, b1)
    c0 = fp6_add(t0, fp6_mul_by_v(t1))
    c1 = fp6_sub(fp6_sub(fp6_mul(fp6_add(a0, a1), fp6_add(b0, b1)), t0), t1)
    return (c0, c1)


def fp12_sq(a):
    a0, a1 = a
    t = fp6_mul(a0, a1)
    c0 = fp6_sub(
        fp6_sub(fp6_mul(fp6_add(a0, a1), fp6_add(a0, fp6_mul_by_v(a1))), t),
        fp6_mul_by_v(t),
    )
    c1 = fp6_add(t, t)
    return (c0, c1)


def fp12_conj(a):
    return (a[0], fp6_neg(a[1]))


def fp12_inv(a):
    a0, a1 = a
    t = fp6_sub(fp6_sq_(a0), fp6_mul_by_v(fp6_sq_(a1)))
    tinv = fp6_inv(t)
    return (fp6_mul(a0, tinv), fp6_neg(fp6_mul(a1, tinv)))


def fp6_sq_(a):
    return fp6_mul(a, a)


def mul_line(f, line):
    """f * (lA + lB w^2 + lC w^3) — the Miller-loop sparse product.

    The line element is s = (s0, s1) with s0 = (lA, lB, 0), s1 = (0, lC, 0)
    (cf. ops.pairing.line_to_fp12). 15 Fp2 products total."""
    lA, lB, lC = line
    f0, f1 = f
    t0 = fp6_mul_by_01(f0, lA, lB)
    t1 = fp6_mul_by_1(f1, lC)
    c0 = fp6_add(t0, fp6_mul_by_v(t1))
    # (f0 + f1) * (lA, lB + lC, 0)
    mixed = fp6_mul_by_01(fp6_add(f0, f1), lA, fp2_add(lB, lC))
    c1 = fp6_sub(fp6_sub(mixed, t0), t1)
    return (c0, c1)


# Frobenius coefficients from the spec, as Montgomery constants.
_G1C = [fp2_encode_const(c) for c in F._GAMMA1]
_G2C = [fp2_encode_const(c) for c in F._GAMMA2]


def fp12_frobenius(a):
    a0, a1 = a
    b0 = (
        fp2_conj(a0[0]),
        fp2_mul(fp2_conj(a0[1]), _G1C[2]),
        fp2_mul(fp2_conj(a0[2]), _G1C[4]),
    )
    b1 = (
        fp2_mul(fp2_conj(a1[0]), _G1C[1]),
        fp2_mul(fp2_conj(a1[1]), _G1C[3]),
        fp2_mul(fp2_conj(a1[2]), _G1C[5]),
    )
    return (b0, b1)


def fp12_frobenius2(a):
    a0, a1 = a
    b0 = (a0[0], fp2_mul(a0[1], _G2C[2]), fp2_mul(a0[2], _G2C[4]))
    b1 = (
        fp2_mul(a1[0], _G2C[1]),
        fp2_mul(a1[1], _G2C[3]),
        fp2_mul(a1[2], _G2C[5]),
    )
    return (b0, b1)


def fp12_select(mask, a, b):
    return tuple(fp6_select(mask, x, y) for x, y in zip(a, b))


def fp12_ones(shape=()):
    return (fp6_ones(shape), fp6_zeros(shape))


def fp12_is_one(a):
    """Componentwise equality with the Montgomery one."""
    one = fp12_ones(a[0][0][0].shape[:-1])
    bits = None
    for x, y in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(one)):
        b = jnp.all(x == y, axis=-1)
        bits = b if bits is None else (bits & b)
    return bits
