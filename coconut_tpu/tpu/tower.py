"""Extension-field tower Fp2/Fp6/Fp12 over the limb Fp — batched, as pytrees.

Mirrors `coconut_tpu.ops.fields` exactly (same tower construction
u^2 = -1, v^3 = xi = u+1, w^2 = v; same Karatsuba/complex formulas) so decoded
results are bit-identical to the spec. Elements are tuples of Fp limb arrays,
which makes every value a JAX pytree that flows through scan/jit unchanged.

Compile-size/MXU design: every tower multiply bottoms out in ONE stacked
base-field multiply (`fp.mul_stack`) — fp2_mul stacks its 3 Karatsuba
products, fp6_mul stacks its 6 fp2 products (-> 18 base lanes), fp12_mul its
3 fp6 products (-> 54 base lanes). One Fp12 multiply is therefore a single
[.., 54, 52] MXU contraction instead of 54 separate multiplies: ~50x fewer
HLO ops (XLA compile time) and far better systolic-array occupancy.

Also provides the sparse Fp12 x line multiplication for the Miller loop
(`mul_line`): lines have only the (w^0, w^2, w^3) components (see
`ops.pairing.line_to_fp12`), 15 Fp2 products stacked into one multiply.
"""

import jax
import jax.numpy as jnp

from ..ops import fields as F
from . import fp
from .limbs import NLIMBS, fp_encode

# --- codecs (host-side) -----------------------------------------------------


def encode_batch(elems, dtype=None):
    """List of same-structure spec elements (ints / nested tuples) ->
    pytree of Montgomery limb arrays with leading batch dim. dtype
    converts in NUMPY before the device transfer (int16 is the halved
    point-upload wire format — balanced limbs are exact |v| <= 132; the
    consuming kernels cast back to f32 at entry)."""
    first = elems[0]
    if isinstance(first, tuple):
        return tuple(
            encode_batch([e[i] for e in elems], dtype=dtype)
            for i in range(len(first))
        )
    from .limbs import fp_encode_batch

    arr = fp_encode_batch(elems)
    if dtype is not None:
        arr = arr.astype(dtype)
    return jnp.asarray(arr)


def encode_raw_batch(elems):
    """Raw-wire variant of encode_batch: pytree of np.uint8[n, 48] raw
    canonical base-256 digits, NOT in the Montgomery domain. The consuming
    kernels convert at entry via fp.to_mont (one on-device Montgomery
    multiply by R^2 — see backend._pts_f32), which keeps the host encode
    down to byte framing and the upload at 48 bytes per Fp."""
    first = elems[0]
    if isinstance(first, tuple):
        return tuple(
            encode_raw_batch([e[i] for e in elems]) for i in range(len(first))
        )
    from .limbs import fp_encode_raw_batch

    return jnp.asarray(fp_encode_raw_batch(elems))


def decode_batch(tree):
    """Inverse of encode_batch: pytree of limb arrays -> list of spec
    elements (canonical ints / nested tuples)."""
    if isinstance(tree, tuple):
        parts = [decode_batch(t) for t in tree]
        return [tuple(p[i] for p in parts) for i in range(len(parts[0]))]
    import numpy as np

    from .limbs import fp_decode_batch

    return fp_decode_batch(np.asarray(tree))


# --- stack/unstack helpers ---------------------------------------------------


def _bcast(elems):
    return jnp.broadcast_arrays(*elems)


def _stack2(elems):
    """[(c0, c1), ...] fp2s -> stacked fp2 with a new [S] axis before limbs."""
    return (
        jnp.stack(_bcast([e[0] for e in elems]), axis=-2),
        jnp.stack(_bcast([e[1] for e in elems]), axis=-2),
    )


def _unstack2(t, n):
    return [(t[0][..., i, :], t[1][..., i, :]) for i in range(n)]


def _stack6(elems):
    """[(c0, c1, c2), ...] fp6s -> stacked fp6 (components are stacked fp2s)."""
    return tuple(_stack2([e[i] for e in elems]) for i in range(3))


def _unstack6(t, n):
    parts = [_unstack2(t[i], n) for i in range(3)]
    return [(parts[0][i], parts[1][i], parts[2][i]) for i in range(n)]


# --- Fp2 --------------------------------------------------------------------


def fp2_encode_const(c):
    """Spec Fp2 (int pair) -> Montgomery limb constant pytree."""
    return (jnp.asarray(fp_encode(c[0])), jnp.asarray(fp_encode(c[1])))


def fp2_add(a, b):
    return (fp.add(a[0], b[0]), fp.add(a[1], b[1]))


def fp2_sub(a, b):
    return (fp.sub(a[0], b[0]), fp.sub(a[1], b[1]))


def fp2_neg(a):
    return (fp.neg(a[0]), fp.neg(a[1]))


def fp2_mul(a, b):
    # Karatsuba: one stacked mul of [a0*b0, a1*b1, (a0+a1)(b0+b1)]
    t0, t1, t2 = fp.mul_stack(
        [a[0], a[1], fp.add(a[0], a[1])],
        [b[0], b[1], fp.add(b[0], b[1])],
    )
    return (fp.sub(t0, t1), fp.sub(fp.sub(t2, t0), t1))


def fp2_sq(a):
    # (a0+a1)(a0-a1), 2*a0*a1 — one stacked mul
    t0, t1 = fp.mul_stack(
        [fp.add(a[0], a[1]), a[0]],
        [fp.sub(a[0], a[1]), a[1]],
    )
    return (t0, fp.add(t1, t1))


def fp2_mul_fp(a, s):
    t0, t1 = fp.mul_stack([a[0], a[1]], [s, s])
    return (t0, t1)


def fp2_mul_small(a, k):
    return (fp.mul_small(a[0], k), fp.mul_small(a[1], k))


def fp2_conj(a):
    return (a[0], fp.neg(a[1]))


def fp2_mul_xi(a):
    """x (u+1): (c0 - c1, c0 + c1)."""
    return (fp.sub(a[0], a[1]), fp.add(a[0], a[1]))


def fp2_inv(a):
    s0, s1 = fp.mul_stack([a[0], a[1]], [a[0], a[1]])
    ninv = fp.inv(fp.add(s0, s1))
    t0, t1 = fp.mul_stack([a[0], a[1]], [ninv, ninv])
    return (t0, fp.neg(t1))


def fp2_is_zero(a):
    z0, z1 = fp.is_zero_many([a[0], a[1]])
    return z0 & z1


def fp2_eq(a, b):
    z0, z1 = fp.is_zero_many([fp.sub(a[0], b[0]), fp.sub(a[1], b[1])])
    return z0 & z1


def fp2_select(mask, a, b):
    return (fp.select(mask, a[0], b[0]), fp.select(mask, a[1], b[1]))


def fp2_zeros(shape=()):
    z = jnp.zeros(tuple(shape) + (NLIMBS,), dtype=jnp.float32)
    return (z, z)


def fp2_ones(shape=()):
    return (
        fp.ones_mont(shape),
        jnp.zeros(tuple(shape) + (NLIMBS,), dtype=jnp.float32),
    )


# --- Fp6 --------------------------------------------------------------------


def fp6_add(a, b):
    return tuple(fp2_add(x, y) for x, y in zip(a, b))


def fp6_sub(a, b):
    return tuple(fp2_sub(x, y) for x, y in zip(a, b))


def fp6_neg(a):
    return tuple(fp2_neg(x) for x in a)


def fp6_mul(a, b):
    """Toom-style 6-product fp6 multiply, all products in ONE stacked
    fp2_mul (18 base lanes): t_i = a_i b_i, plus the three cross sums."""
    a0, a1, a2 = a
    b0, b1, b2 = b
    prods = fp2_mul(
        _stack2(
            [a0, a1, a2, fp2_add(a1, a2), fp2_add(a0, a1), fp2_add(a0, a2)]
        ),
        _stack2(
            [b0, b1, b2, fp2_add(b1, b2), fp2_add(b0, b1), fp2_add(b0, b2)]
        ),
    )
    t0, t1, t2, t12, t01, t02 = _unstack2(prods, 6)
    c0 = fp2_add(t0, fp2_mul_xi(fp2_sub(fp2_sub(t12, t1), t2)))
    c1 = fp2_add(fp2_sub(fp2_sub(t01, t0), t1), fp2_mul_xi(t2))
    c2 = fp2_add(fp2_sub(fp2_sub(t02, t0), t2), t1)
    return (c0, c1, c2)


def fp6_mul_by_v(a):
    return (fp2_mul_xi(a[2]), a[0], a[1])


def fp6_inv(a):
    a0, a1, a2 = a
    # six products in one stack: a0^2, a1*a2, a2^2, a0*a1, a1^2, a0*a2
    prods = fp2_mul(
        _stack2([a0, a1, a2, a0, a1, a0]), _stack2([a0, a2, a2, a1, a1, a2])
    )
    s00, s12, s22, s01, s11, s02 = _unstack2(prods, 6)
    c0 = fp2_sub(s00, fp2_mul_xi(s12))
    c1 = fp2_sub(fp2_mul_xi(s22), s01)
    c2 = fp2_sub(s11, s02)
    # t = xi*(a2 c1 + a1 c2) + a0 c0 — three products in one stack
    prods2 = fp2_mul(_stack2([a2, a1, a0]), _stack2([c1, c2, c0]))
    u1, u2, u0 = _unstack2(prods2, 3)
    t = fp2_add(fp2_mul_xi(fp2_add(u1, u2)), u0)
    tinv = fp2_inv(t)
    prods3 = fp2_mul(
        _stack2([c0, c1, c2]), _stack2([tinv, tinv, tinv])
    )
    r0, r1, r2 = _unstack2(prods3, 3)
    return (r0, r1, r2)


def fp6_select(mask, a, b):
    return tuple(fp2_select(mask, x, y) for x, y in zip(a, b))


def fp6_zeros(shape=()):
    z = fp2_zeros(shape)
    return (z, z, z)


def fp6_ones(shape=()):
    return (fp2_ones(shape), fp2_zeros(shape), fp2_zeros(shape))


# --- Fp12 -------------------------------------------------------------------


def fp12_mul(a, b):
    """Karatsuba over w: 3 fp6 products in ONE stacked fp6_mul (54 base
    lanes -> a single MXU contraction)."""
    a0, a1 = a
    b0, b1 = b
    prods = fp6_mul(
        _stack6([a0, a1, fp6_add(a0, a1)]),
        _stack6([b0, b1, fp6_add(b0, b1)]),
    )
    t0, t1, t2 = _unstack6(prods, 3)
    c0 = fp6_add(t0, fp6_mul_by_v(t1))
    c1 = fp6_sub(fp6_sub(t2, t0), t1)
    return (c0, c1)


def fp12_sq(a):
    a0, a1 = a
    # t = a0*a1 and s = (a0+a1)(a0 + v*a1) in one stacked fp6_mul
    prods = fp6_mul(
        _stack6([a0, fp6_add(a0, a1)]),
        _stack6([a1, fp6_add(a0, fp6_mul_by_v(a1))]),
    )
    t, s = _unstack6(prods, 2)
    c0 = fp6_sub(fp6_sub(s, t), fp6_mul_by_v(t))
    c1 = fp6_add(t, t)
    return (c0, c1)


def fp12_cyclo_sq(a):
    """Granger–Scott cyclotomic squaring — valid ONLY for elements of the
    cyclotomic subgroup G_{Phi12}(p) (everything after the easy part of the
    final exponentiation). For such elements the square decomposes into
    three Fp4 squarings over the pairs (z0,z1)=(c00,c11), (z2,z3)=
    (c10,c02), (z4,z5)=(c01,c12) with Fp4 = Fp2[s]/(s^2 - xi):

      fp4_sq(x, y) = (x^2 + xi y^2, 2xy)           [3 fp2 squarings]
      z0' = 3A0 - 2z0   z1' = 3B0 + 2z1            [A_i, B_i = fp4 parts]
      z4' = 3A1 - 2z4   z5' = 3B1 + 2z5
      z2' = 3 xi B2 + 2z2   z3' = 3A2 - 2z3

    Cost: 9 fp2 squarings (18 base products) + 12 compress muls, all in ONE
    stacked contraction = 30 base lanes, vs fp12_sq's 36 — and unlike
    fp12_sq the additive tail reuses the INPUT components, so each input
    component is compressed (one Montgomery mul by 1) to keep the lazy
    value/limb class bounded across unbounded squaring chains (the scan in
    pairing._pow_x_abs runs up to 31 consecutive squarings with no
    intervening normalizing multiply):
      output limb weight <= 3*(3*132) + 2*132 = 1452 << L_LAZY = 2^17,
      output |value| <= 3*2p + 2*0.66p < 8p << V_LAZY = 1024p,
    a fixed point of the recursion (outputs are built only from fresh mul
    outputs and compressed inputs)."""
    (c00, c01, c02), (c10, c11, c12) = a
    pairs = [(c00, c11), (c10, c02), (c01, c12)]
    lhs, rhs = [], []
    for x, y in pairs:
        for e in (x, y, fp2_add(x, y)):
            # fp2_sq(e) = ((e0+e1)(e0-e1), 2 e0 e1): two base products
            lhs += [fp.add(e[0], e[1]), e[0]]
            rhs += [fp.sub(e[0], e[1]), e[1]]
    one = fp.ones_mont()
    for comp in (c00, c11, c10, c02, c01, c12):
        lhs += [comp[0], comp[1]]
        rhs += [one, one]
    prods = fp.mul_stack(lhs, rhs)
    sq = []  # the 9 fp2 squares, pair-major
    for i in range(9):
        sq.append((prods[2 * i], fp.add(prods[2 * i + 1], prods[2 * i + 1])))
    cc = []  # compressed input components, in the order fed above
    for j in range(6):
        cc.append((prods[18 + 2 * j], prods[18 + 2 * j + 1]))
    z0c, z1c, z2c, z3c, z4c, z5c = cc  # (c00, c11, c10, c02, c01, c12)

    def fp4_parts(i):
        tx, ty, ts = sq[3 * i], sq[3 * i + 1], sq[3 * i + 2]
        A = fp2_add(tx, fp2_mul_xi(ty))
        B = fp2_sub(fp2_sub(ts, tx), ty)
        return A, B

    A0, B0 = fp4_parts(0)
    A1, B1 = fp4_parts(1)
    A2, B2 = fp4_parts(2)

    def t3m2(t, z):  # 3t - 2z
        return fp2_sub(fp2_mul_small(t, 3), fp2_mul_small(z, 2))

    def t3p2(t, z):  # 3t + 2z
        return fp2_add(fp2_mul_small(t, 3), fp2_mul_small(z, 2))

    z0p = t3m2(A0, z0c)
    z1p = t3p2(B0, z1c)
    z4p = t3m2(A1, z4c)
    z5p = t3p2(B1, z5c)
    z2p = t3p2(fp2_mul_xi(B2), z2c)
    z3p = t3m2(A2, z3c)
    return ((z0p, z4p, z3p), (z2p, z1p, z5p))


def fp12_conj(a):
    return (a[0], fp6_neg(a[1]))


def fp12_inv(a):
    a0, a1 = a
    prods = fp6_mul(_stack6([a0, a1]), _stack6([a0, a1]))
    s0, s1 = _unstack6(prods, 2)
    t = fp6_sub(s0, fp6_mul_by_v(s1))
    tinv = fp6_inv(t)
    prods2 = fp6_mul(_stack6([a0, a1]), _stack6([tinv, tinv]))
    r0, r1 = _unstack6(prods2, 2)
    return (r0, fp6_neg(r1))


def mul_line(f, line):
    """f * (lA + lB w^2 + lC w^3) — the Miller-loop sparse product.

    The line element is s = (s0, s1) with s0 = (lA, lB, 0), s1 = (0, lC, 0)
    (cf. ops.pairing.line_to_fp12). 15 Fp2 products in ONE stacked mul:
    6 for f0*(lA,lB), 3 for f1*lC, 6 for (f0+f1)*(lA, lB+lC)."""
    lA, lB, lC = line
    f0, f1 = f
    g = fp6_add(f0, f1)
    lBC = fp2_add(lB, lC)
    lhs = _stack2(
        [
            f0[0], f0[2], f0[1], f0[0], f0[2], f0[1],  # mul_by_01(f0, lA, lB)
            f1[2], f1[0], f1[1],                        # mul_by_1(f1, lC)
            g[0], g[2], g[1], g[0], g[2], g[1],         # mul_by_01(g, lA, lBC)
        ]
    )
    rhs = _stack2(
        [
            lA, lB, lA, lB, lA, lB,
            lC, lC, lC,
            lA, lBC, lA, lBC, lA, lBC,
        ]
    )
    p = _unstack2(fp2_mul(lhs, rhs), 15)
    # mul_by_01 structure: c0 = a0*s0 + xi*(a2*s1); c1 = a1*s0 + a0*s1;
    # c2 = a2*s0 + a1*s1 — regroup the products accordingly:
    t0 = (
        fp2_add(p[0], fp2_mul_xi(p[1])),
        fp2_add(p[2], p[3]),
        fp2_add(p[4], p[5]),
    )
    t1 = (fp2_mul_xi(p[6]), p[7], p[8])
    mixed = (
        fp2_add(p[9], fp2_mul_xi(p[10])),
        fp2_add(p[11], p[12]),
        fp2_add(p[13], p[14]),
    )
    c0 = fp6_add(t0, fp6_mul_by_v(t1))
    c1 = fp6_sub(fp6_sub(mixed, t0), t1)
    return (c0, c1)


# Frobenius coefficients from the spec, as Montgomery constants.
_G1C = [fp2_encode_const(c) for c in F._GAMMA1]
_G2C = [fp2_encode_const(c) for c in F._GAMMA2]


def fp12_frobenius(a):
    a0, a1 = a
    prods = fp2_mul(
        _stack2(
            [
                fp2_conj(a0[1]),
                fp2_conj(a0[2]),
                fp2_conj(a1[0]),
                fp2_conj(a1[1]),
                fp2_conj(a1[2]),
            ]
        ),
        _stack2([_G1C[2], _G1C[4], _G1C[1], _G1C[3], _G1C[5]]),
    )
    m01, m02, m10, m11, m12 = _unstack2(prods, 5)
    return ((fp2_conj(a0[0]), m01, m02), (m10, m11, m12))


def fp12_frobenius2(a):
    a0, a1 = a
    prods = fp2_mul(
        _stack2([a0[1], a0[2], a1[0], a1[1], a1[2]]),
        _stack2([_G2C[2], _G2C[4], _G2C[1], _G2C[3], _G2C[5]]),
    )
    m01, m02, m10, m11, m12 = _unstack2(prods, 5)
    return ((a0[0], m01, m02), (m10, m11, m12))


def fp12_select(mask, a, b):
    return tuple(fp6_select(mask, x, y) for x, y in zip(a, b))


def fp12_ones(shape=()):
    return (fp6_ones(shape), fp6_zeros(shape))


def fp12_is_one(a):
    """Exact componentwise test against the Montgomery one (values are
    redundant — the compress-based predicates do the exact mod-p
    comparison), all 12 compress-muls stacked into one contraction."""
    comps = jax.tree_util.tree_leaves(a)  # 12 Fp components, c0.c0.c0 first
    diffs = [fp.sub(comps[0], fp.ones_mont(comps[0].shape[:-1]))] + comps[1:]
    zs = fp.is_zero_many(diffs)
    bits = zs[0]
    for z in zs[1:]:
        bits = bits & z
    return bits
