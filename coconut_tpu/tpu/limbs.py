"""Limb codec: python ints <-> 52 x 8-bit limbs in float32 lanes.

The limb decomposition is the host<->device wire format for all field
elements (SURVEY.md §7 stage 6 "limb codec"). 8-bit BALANCED limbs (each in
[-128, 128]) in float32 were chosen so the schoolbook limb products run on
the MXU: products split into two exact int8/bf16 byte planes, contracted
against a static 0/1 band matrix with int32/f32 accumulation — every
intermediate is an integer below 2^24 and therefore EXACT (the systolic
array becomes a bignum multiplier). 52 limbs (416 bits, vs the 381-bit p)
buy ~2^35 of headroom so the device arithmetic can be LAZY: add/sub/neg
and small-constant scalings are single elementwise ops, with all carry
handling confined to the Montgomery multiply (see tpu/fp.py). This
replaced (1) a 16-bit-limbs-in-uint64 design whose emulated 64-bit VPU ops
were ~70x slower, and (2) a 48-limb eagerly-reduced design whose per-add
normalize/subtract pipelines dominated both XLA compile time and VPU time.

Least-significant limb first. Fp values travel in the Montgomery domain
(a * 2^416 mod p) between kernels; encode/decode converts at the boundary so
results are bit-identical to the pure-Python spec (`coconut_tpu.ops.fields`).
"""

import numpy as np

from ..ops.fields import P, R

LIMB_BITS = 8
NLIMBS = 52  # 52 * 8 = 416 bits: ~2^35 of headroom over the 381-bit p
MASK = (1 << LIMB_BITS) - 1
MONT_BITS = LIMB_BITS * NLIMBS  # 416
MONT_R = 1 << MONT_BITS

DTYPE = np.float32


def int_to_limbs(x, nlimbs=NLIMBS):
    """Python int -> np.float32[nlimbs], least-significant first."""
    if not 0 <= x < (1 << (LIMB_BITS * nlimbs)):
        raise ValueError("value out of range for %d limbs" % nlimbs)
    return np.array(
        [(x >> (LIMB_BITS * i)) & MASK for i in range(nlimbs)], dtype=DTYPE
    )


def limbs_to_int(limbs):
    """np/jnp float array (last axis = limbs) -> python int (single element)."""
    arr = np.asarray(limbs)
    return sum(int(round(float(v))) << (LIMB_BITS * i) for i, v in enumerate(arr))


def ints_to_limbs(xs, nlimbs=NLIMBS):
    """[...] list of ints -> np.float32[..., nlimbs]."""
    return np.array(
        [[int(x) >> (LIMB_BITS * i) & MASK for i in range(nlimbs)] for x in xs],
        dtype=DTYPE,
    )


def limbs_to_ints(arr):
    """np.float32[..., nlimbs] -> nested list of ints over the last axis."""
    a = np.asarray(arr)
    flat = a.reshape(-1, a.shape[-1])
    out = [
        sum(int(round(float(v))) << (LIMB_BITS * i) for i, v in enumerate(row))
        for row in flat
    ]
    return (
        np.array(out, dtype=object).reshape(a.shape[:-1]).tolist()
        if a.ndim > 1
        else out[0]
    )


# --- balanced representation ------------------------------------------------


def balanced_limbs(x, nlimbs=NLIMBS, wrap=False):
    """Nonnegative int -> balanced signed limbs (each in [-128, 128]) as
    np.float32[nlimbs]. The device representation: see tpu/fp.py. With
    `wrap`, a final carry is dropped (value taken mod 2^(8*nlimbs) — for
    constants only used in mod-2^416 arithmetic, e.g. N')."""
    digs = [(x >> (LIMB_BITS * i)) & MASK for i in range(nlimbs)]
    if x >> (LIMB_BITS * nlimbs):
        raise ValueError("value out of range for %d limbs" % nlimbs)
    out = []
    carry = 0
    for d in digs:
        v = d + carry
        if v > 128:
            v -= 256
            carry = 1
        else:
            carry = 0
        out.append(v)
    if carry and not wrap:
        raise ValueError("balanced form needs %d limbs + carry" % nlimbs)
    return np.array(out, dtype=DTYPE)


# --- Montgomery constants ---------------------------------------------------

P_LIMBS = int_to_limbs(P)
# N' = -p^{-1} mod 2^416, full width (for the one-shot Montgomery m)
NPRIME = int_to_limbs((-pow(P, -1, MONT_R)) % MONT_R)
# R^2 mod p: multiply by this (Montgomery-mul) to enter the domain
R2 = int_to_limbs(MONT_R * MONT_R % P)
# Montgomery representation of 1 and 0
ONE_M = int_to_limbs(MONT_R % P)
ZERO = int_to_limbs(0)


def fp_encode(x):
    """Canonical Fp int -> balanced Montgomery limb vector (host-side)."""
    return balanced_limbs(x % P * MONT_R % P)


def fp_decode(limbs):
    """Montgomery limb vector -> canonical Fp int (host-side)."""
    return limbs_to_int(limbs) * pow(MONT_R, -1, P) % P


def balanced_limbs_batch(xs, nlimbs=NLIMBS):
    """List of nonnegative ints -> np.float32[n, nlimbs] balanced limbs.
    Vectorized over the batch: the 0/1 balance carry propagates through one
    numpy loop over the limb axis instead of a Python loop per element."""
    buf = b"".join(int(x).to_bytes(nlimbs, "little") for x in xs)
    d = np.frombuffer(buf, dtype=np.uint8).reshape(-1, nlimbs).astype(np.int32)
    c = np.zeros(len(xs), dtype=np.int32)
    out = np.empty((len(xs), nlimbs), dtype=DTYPE)
    for i in range(nlimbs):
        v = d[:, i] + c
        c = (v > 128).astype(np.int32)
        out[:, i] = v - (c << 8)
    if c.any():
        raise ValueError("balanced form needs %d limbs + carry" % nlimbs)
    return out


def fp_encode_batch(xs):
    """list of ints [...] -> np.float32[..., NLIMBS], balanced Montgomery."""
    return balanced_limbs_batch([int(x) % P * MONT_R % P for x in xs])


# Raw (non-Montgomery) wire format: 48 canonical little-endian bytes per Fp.
RAW_BYTES = 48


def fp_encode_raw_batch(xs):
    """List of canonical Fp ints -> np.uint8[n, RAW_BYTES] raw base-256
    digits, NOT in the Montgomery domain and NOT balanced.

    This is the cheap half of the host encode: one to_bytes + frombuffer,
    no bigint Montgomery multiply and no balance-carry loop (those moved
    on-device — see fp.to_mont, which folds the multiply-by-R^2 domain
    entry into the existing exact Montgomery-multiply kernel). 48 bytes
    per element also halves the upload vs the 52 x int16 balanced wire.
    """
    buf = b"".join((int(x) % P).to_bytes(RAW_BYTES, "little") for x in xs)
    return np.frombuffer(buf, dtype=np.uint8).reshape(len(xs), RAW_BYTES)


# COCONUT_DEBUG_PACK support: backend._pack_pt's on-device bound check
# cannot raise from inside jax.debug.callback (the runtime may swallow or
# defer callback exceptions under jit), so the callback RECORDS violations
# here and the host decode boundary asserts — every packed result funnels
# through fp_decode_batch, so a violation surfaces on the very readback it
# corrupted, as a real host-side exception.
PACK_DEBUG_VIOLATIONS = []


def pack_debug_record(m):
    """jax.debug.callback target: record a limb-magnitude maximum that
    exceeds pack_canon48's |v| <= 396 contract."""
    v = float(np.asarray(m))
    if v > 396.0:
        PACK_DEBUG_VIOLATIONS.append(v)


def pack_debug_check():
    """Raise (and drain) if any recorded limb magnitude broke the pack
    bound; called at the fp_decode_batch entry so the assert fires at the
    host decode boundary."""
    if PACK_DEBUG_VIOLATIONS:
        worst = max(PACK_DEBUG_VIOLATIONS)
        del PACK_DEBUG_VIOLATIONS[:]
        raise AssertionError(
            "_pack_pt limb |v| = %r exceeds the pack bound 396" % worst
        )


def fp_decode_batch(arr):
    """Montgomery device output -> list of canonical ints. Two wire
    formats, dispatched on dtype:

      - uint8 [..., 48]: canonical base-256 digits of (value + 2p) from
        fp.pack_canon48 (the compressed readback path) — int.from_bytes
        per element, then the Montgomery divide mod p;
      - any float/int [..., NLIMBS]: signed limb vectors. Vectorized:
        limbs are pre-combined into 48-bit chunks in int64 numpy (exact:
        packed limbs are |v| <= ~400, so a 6-limb chunk is
        < 6 * 400 * 2^40 < 2^52), leaving ~9 Python big-int ops per
        element instead of NLIMBS — the decode side of the host codec was
        a visible slice of issuance/show batch time."""
    pack_debug_check()  # surface any COCONUT_DEBUG_PACK violation here
    rinv = pow(MONT_R, -1, P)
    a0 = np.asarray(arr)
    if a0.dtype == np.uint8:
        flat = np.ascontiguousarray(a0.reshape(-1, a0.shape[-1]))
        nb = flat.shape[1]
        buf = flat.tobytes()
        return [
            int.from_bytes(buf[i * nb : (i + 1) * nb], "little") * rinv % P
            for i in range(flat.shape[0])
        ]
    a = a0.astype(np.float64)
    flat = a.reshape(-1, a.shape[-1]).round().astype(np.int64)
    n, nl = flat.shape
    nchunk = -(-nl // 6)
    pad = nchunk * 6 - nl
    if pad:
        flat = np.concatenate([flat, np.zeros((n, pad), np.int64)], axis=1)
    w6 = np.int64(1) << (LIMB_BITS * np.arange(6, dtype=np.int64))
    chunks = (flat.reshape(n, nchunk, 6) * w6).sum(axis=2)
    shifts = [LIMB_BITS * 6 * j for j in range(nchunk)]
    out = []
    for row in chunks:
        v = 0
        for j in range(nchunk):
            v += int(row[j]) << shifts[j]
        out.append(v * rinv % P)
    return out


def fr_digits_signed_np(scalars, nwin=52, window=5):
    """[n] iterable of ints -> (mag [n, nwin], neg bool [n, nwin]) signed
    `window`-bit digits, msb first: k = sum_w d_w * (2^window)^w with
    d_w in [-(2^(window-1) - 1), 2^(window-1)], d = sign * mag.

    mag dtype: uint8 for window <= 8 (magnitude <= 256 only at window=9,
    so 8-bit windows still fit), int16 for window >= 9 — the r4 uint8 cap
    wrapped 256 -> 0 at window=9 and silently returned wrong verify bits
    (commit 2240a82); widening the dtype instead of capping the window
    unlocks the 9/10-bit comb schedules (VERDICT r4 item 1).

    window=5 / nwin=52 is the distinct-MSM Horner schedule (17-entry
    tables); window=6 / nwin=43 is the grouped verify's schedule (33-entry
    on-device tables); window=9/10 (29/26 windows, 257/513-entry host-built
    cached tables) are the shared-base comb schedules on the real chip. The
    top digit absorbs the final carry (Fr is 255 bits; 52*5 = 260,
    43*6 = 258, 29*9 = 261, 26*10 = 260). Negation is a Y-flip on the
    gathered point."""
    half = 1 << (window - 1)
    base = 1 << window
    mag_dtype = np.uint8 if half <= 255 else np.int16
    acc_dtype = np.int16 if window <= 10 else np.int32
    nbytes = (nwin * window + 7) // 8
    # lint: allow(const-time, CONSTTIME.md §1 host caveat - big-int reduce +
    # to_bytes cost tracks bit length; accepted on the host recode path)
    buf = b"".join((int(s) % R).to_bytes(nbytes, "little") for s in scalars)
    bits = np.unpackbits(
        np.frombuffer(buf, dtype=np.uint8).reshape(-1, nbytes),
        axis=1,
        bitorder="little",
    )[:, : nwin * window]
    uw = bits.reshape(-1, nwin, window).astype(acc_dtype) @ (
        1 << np.arange(window, dtype=acc_dtype)
    )  # unsigned base-2^window digits, lsb first
    mag = np.empty((uw.shape[0], nwin), dtype=mag_dtype)
    neg = np.empty((uw.shape[0], nwin), dtype=bool)
    c = np.zeros(uw.shape[0], dtype=acc_dtype)
    for w in range(nwin):  # lsb first; msb-first order fixed on store
        v = uw[:, w] + c
        over = v > half
        d = np.where(over, v - base, v)
        c = over.astype(acc_dtype)
        mag[:, nwin - 1 - w] = np.abs(d).astype(mag_dtype)
        neg[:, nwin - 1 - w] = d < 0
    # lint: allow(const-time, carry is structurally zero for every Fr input -
    # the branch direction is input-independent)
    assert not c.any()  # Fr < 2^255: the top window absorbs every carry
    return mag, neg
