"""Limb codec: python ints <-> 24 x 16-bit limbs in uint64 lanes.

The limb decomposition is the host<->device wire format for all field
elements (SURVEY.md §7 stage 6 "limb codec"). 16-bit limbs were chosen so
that schoolbook products (16x16 -> 32 bits) accumulated over 24 terms plus
Montgomery-reduction additions stay below 2^38 — comfortably inside a uint64
accumulator with no carry splitting inside the inner loops (the hard part (a)
in SURVEY.md §7: TPU-width-friendly carry discipline).

Least-significant limb first. Fp values travel in the Montgomery domain
(a * 2^384 mod p) between kernels; encode/decode converts at the boundary so
results are bit-identical to the pure-Python spec (`coconut_tpu.ops.fields`).
"""

import numpy as np

from ..ops.fields import P, R

LIMB_BITS = 16
NLIMBS = 24  # 24 * 16 = 384 bits >= 381
MASK = (1 << LIMB_BITS) - 1
MONT_BITS = LIMB_BITS * NLIMBS  # 384
MONT_R = 1 << MONT_BITS

# Fr scalars: 16 limbs of 16 bits = 256 bits >= 255
FR_NLIMBS = 16


def int_to_limbs(x, nlimbs=NLIMBS):
    """Python int -> np.uint64[nlimbs], least-significant first."""
    if not 0 <= x < (1 << (LIMB_BITS * nlimbs)):
        raise ValueError("value out of range for %d limbs" % nlimbs)
    return np.array(
        [(x >> (LIMB_BITS * i)) & MASK for i in range(nlimbs)], dtype=np.uint64
    )


def limbs_to_int(limbs):
    """np/jnp uint array (last axis = limbs) -> python int (single element)."""
    arr = np.asarray(limbs, dtype=np.uint64)
    return sum(int(v) << (LIMB_BITS * i) for i, v in enumerate(arr))


def ints_to_limbs(xs, nlimbs=NLIMBS):
    """[...] nested list of ints -> np.uint64[..., nlimbs]."""
    a = np.asarray(
        [[int(x) >> (LIMB_BITS * i) & MASK for i in range(nlimbs)] for x in xs],
        dtype=np.uint64,
    )
    return a


def limbs_to_ints(arr):
    """np.uint64[..., nlimbs] -> nested list of ints over the last axis."""
    a = np.asarray(arr, dtype=np.uint64)
    flat = a.reshape(-1, a.shape[-1])
    out = [
        sum(int(v) << (LIMB_BITS * i) for i, v in enumerate(row)) for row in flat
    ]
    return np.array(out, dtype=object).reshape(a.shape[:-1]).tolist() if a.ndim > 1 else out[0]


# --- Montgomery constants ---------------------------------------------------

P_LIMBS = int_to_limbs(P)
# -p^{-1} mod 2^16 (the REDC multiplier derivation constant)
N0 = (-pow(P, -1, 1 << LIMB_BITS)) % (1 << LIMB_BITS)
# R^2 mod p: multiply by this (Montgomery-mul) to enter the domain
R2 = int_to_limbs(MONT_R * MONT_R % P)
# Montgomery representation of 1 and 0
ONE_M = int_to_limbs(MONT_R % P)
ZERO = int_to_limbs(0)


def fp_encode(x):
    """Canonical Fp int -> Montgomery limb vector (numpy; host-side)."""
    return int_to_limbs(x % P * MONT_R % P)


def fp_decode(limbs):
    """Montgomery limb vector -> canonical Fp int (host-side)."""
    return limbs_to_int(limbs) * pow(MONT_R, -1, P) % P


def fp_encode_batch(xs):
    """list/array of ints [...] -> np.uint64[..., NLIMBS] in Montgomery form."""
    return ints_to_limbs([int(x) % P * MONT_R % P for x in xs])


def fp_decode_batch(arr):
    """np.uint64[..., NLIMBS] Montgomery -> list of canonical ints."""
    rinv = pow(MONT_R, -1, P)
    a = np.asarray(arr, dtype=np.uint64)
    flat = a.reshape(-1, a.shape[-1])
    return [
        sum(int(v) << (LIMB_BITS * i) for i, v in enumerate(row)) * rinv % P
        for row in flat
    ]


def fr_to_digits(k, window=4):
    """Fr scalar -> fixed-length window-digit vector (np.uint32), most
    significant digit first — the MSM window schedule."""
    k = int(k) % R
    ndig = (256 + window - 1) // window
    return np.array(
        [(k >> (window * i)) & ((1 << window) - 1) for i in range(ndig - 1, -1, -1)],
        dtype=np.uint32,
    )
