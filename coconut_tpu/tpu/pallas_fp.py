"""Fused Pallas TPU kernel for the Montgomery multiply (fp.mul).

Why: the XLA formulation of `fp.mul` materializes the schoolbook outer
product (a 52x data expansion, [N, 2704] f32) plus byte planes in HBM for
every multiply — measured to make every kernel HBM-bound. This kernel
keeps the whole REDC pipeline (input carry passes, three limb-product
reductions, low-half carry extraction, output normalization) in VMEM: per
lane only 104 input + 52 output limbs cross HBM.

Layout: everything TRANSPOSED to [limbs, lanes] — the lane (batch) axis
sits in the 128-wide vector lanes, so every carry shift and coefficient
shift is a static concatenate on the sublane axis.

The limb product itself is a pure-VPU "comb": the [52, 52, TN] outer
product's rows are shift-aligned and summed in a pairwise tree, split into
low/high coefficient halves to avoid padding (every coefficient is a sum
of <= 52 products <= 132^2 — exact f32, no byte planes, no matmul). This
measured 52.5 ns/lane vs 92.2 for the int8-MXU band contraction and 351.5
for the XLA path: the band matmul's 95x MAC redundancy makes even the MXU
lose to straight VPU accumulation here. The MXU band path is kept behind
COCONUT_PALLAS_VPU=0 (int8 planes by default there; COCONUT_FP_INT8=0 for
bf16).

The arithmetic is the same proof-carrying pipeline as fp.mul (see fp.py's
import asserts): inputs LAZY (|limbs| <= 2^17, top two limbs vacant),
output NORMALIZED (|limbs| <= 132, |value| < 0.66p), results bit-identical
to the XLA path (differential-tested).

Enabled automatically when the default JAX backend is a TPU (CPU tests
keep the pure-XLA path), or forced via COCONUT_FP_PALLAS=1/0.
"""

import os

import numpy as np

import jax
import jax.numpy as jnp

from . import fp as _fp
from .limbs import NLIMBS

TN = int(os.environ.get("COCONUT_PALLAS_TN", "256"))  # lanes per grid block
# int8 MXU planes by default; COCONUT_FP_INT8=0 (the documented knob) or
# COCONUT_PALLAS_I8=0 selects the bf16 fallback
_I8 = (
    os.environ.get("COCONUT_PALLAS_I8", os.environ.get("COCONUT_FP_INT8", "1"))
    == "1"
)

_OUT2 = 2 * NLIMBS - 1  # 103

# All Montgomery constants and the band structure are shared with fp.py so
# the two paths can never desynchronize (fp imports this module lazily
# inside mul, so there is no import cycle).
# numpy (host) constants only at module level — jnp.asarray here would
# create traced constants when this module is first imported inside a jit
# trace (fp.mul imports lazily), leaking tracers into the globals; the jnp
# conversion happens per call site (deduped per jit trace).
_BAND_T_NP = _fp._BAND_NP.T.copy()
_NPRIME_COL = np.asarray(_fp._NPRIME_J).reshape(NLIMBS, 1)
_P_COL = np.asarray(_fp._P_BAL_J).reshape(NLIMBS, 1)

_BASE = 256.0
_INV_BASE = 1.0 / 256.0


def _shift_up(h):
    """Carry shift on the sublane (limb) axis: drop top, prepend zero."""
    return jnp.concatenate([jnp.zeros_like(h[:1]), h[:-1]], axis=0)


def _pass(t):
    hi = jnp.round(t * _INV_BASE)
    lo = t - hi * _BASE
    return lo + _shift_up(hi)


def _norm(t, passes):
    for _ in range(passes):
        t = _pass(t)
    return t


def _ext(t, extra):
    return jnp.concatenate(
        [t, jnp.zeros((extra, t.shape[1]), dtype=t.dtype)], axis=0
    )


_VPU = os.environ.get("COCONUT_PALLAS_VPU", "1") == "1"
# Karatsuba on the FULL 52-limb products (the t = a*b and w = m*p steps).
# One level: 3x 26-limb schoolbooks (2,028 lane-mults) replace the 52x52
# outer product (2,704). Two levels (the default): each 26-schoolbook
# splits again into 3x 13-limb schoolbooks — 9x169 = 1,521 lane-mults —
# at the cost of deeper add-trees.
#
# Exactness proof (every f32 add of exact integers < 2^24 is exact, and
# the partial-sum ORDER below keeps every intermediate under 2^24):
#   level-2 operands: normalized halves |v| <= 132, L1-mid operands
#   (x0+x1) <= 264, their L2 halves' sums <= 528.
#   13-limb product coeff <= 13*528^2 = 3.63M; L2 z1 = mid - z0 - z2:
#   partial |mid - z0| <= 3.63M + 0.91M = 4.54M < 2^24.
#   Assembled 26-product coeff (z0 + z1 + z2 overlap) for M-bounded
#   operands <= 104*M^2: M=264 -> 7.25M < 2^24 (partials <= 6.35M).
#   L1 z1 = mid26 - z0_26 - z2_26: partial <= 7.25M + 3.63M = 10.9M
#   < 2^24. Final 103-coeff assembly partials <= 3.63M + 10.9M = 14.5M
#   < 2^24 = 16.8M; the finished coefficient is the TRUE product
#   coefficient <= 52*132^2 = 0.91M. The downstream 3-pass carry
#   extractions absorb the larger intermediate bound: pass-1 residual
#   <= 128 + round(14.5M/256) ~ 57k, pass 2 <= 128 + 224 = 352, pass 3
#   <= 128 + 2 <= 132 (the NORMALIZED class bound, as in fp.py).
# COCONUT_PALLAS_KARATSUBA: 0 = plain outer product, 1 = one level,
# 2 = two levels (default).


def _parse_karatsuba(raw, default=2):
    """Parse the COCONUT_PALLAS_KARATSUBA setting: unset/empty/garbage or
    a negative value falls back to the default (a typo'd env var must not
    crash import or silently pick a random depth); a level > 2 is an
    explicit error — the exactness proof above covers at most two levels,
    so deeper recursion would run UNPROVEN arithmetic."""
    if raw is None:
        return default
    raw = raw.strip()
    if not raw:
        return default
    try:
        level = int(raw)
    except ValueError:
        return default
    if level < 0:
        return default
    if level > 2:
        raise ValueError(
            "COCONUT_PALLAS_KARATSUBA=%d unsupported: the exactness proof "
            "covers at most two levels (use 0, 1, or 2)" % level
        )
    return level


_KARATSUBA = _parse_karatsuba(os.environ.get("COCONUT_PALLAS_KARATSUBA"))
_HALF = NLIMBS // 2  # 26


def _tree(terms):  # pairwise tree: log depth for VPU ILP
    while len(terms) > 1:
        nxt = [terms[k] + terms[k + 1] for k in range(0, len(terms) - 1, 2)]
        if len(terms) % 2:
            nxt.append(terms[-1])
        terms = nxt
    return terms[0]


def _school_comb(x, y, n, out_len):
    """n-limb VPU comb schoolbook: shift-align the [n, n, TN] outer
    product's rows and tree-sum them into 2n-1 coefficients. Row i
    contributes to coefficients [i, i+n): rows split into a low half
    t[0:n) and a high half t[n:2n-1) so no term pads to the full height.
    out_len < 2n-1 truncates AFTER the sum (dropped terms belong to
    limbs >= n and must not alias into the kept ones)."""
    tn = x.shape[1]
    outer = x[:, None, :] * y[None, :, :]  # [n, n, TN]
    lo_terms, hi_terms = [], []
    for i in range(n):
        row = outer[i]
        if i == 0:
            lo_terms.append(row)
            continue
        lo_terms.append(
            jnp.concatenate(
                [jnp.zeros((i, tn), x.dtype), row[: n - i]], axis=0
            )
        )
        hi_terms.append(
            jnp.concatenate(
                [row[n - i :], jnp.zeros((n - 1 - i, tn), x.dtype)]
                if i < n - 1
                else [row[n - i :]],
                axis=0,
            )
        )
    if out_len <= n:  # REDC's m-step: the high half is discarded
        return _tree(lo_terms)[:out_len]
    t = jnp.concatenate([_tree(lo_terms), _tree(hi_terms)], axis=0)
    return t[:out_len]


def _kara_full(x, y, n, levels):
    """Full [2n-1] coefficient product of n-limb operands via `levels` of
    Karatsuba recursion (0 = plain comb schoolbook). Requires n even at
    every recursion step; assembly order matches the exactness proof in
    the _KARATSUBA note (z0 + z1 first, then + z2)."""
    if levels <= 0 or n % 2:
        return _school_comb(x, y, n, 2 * n - 1)
    tn = x.shape[1]
    half = n // 2
    x0, x1 = x[:half], x[half:]
    y0, y1 = y[:half], y[half:]
    z0 = _kara_full(x0, y0, half, levels - 1)  # [2*half-1] coeffs 0..
    z2 = _kara_full(x1, y1, half, levels - 1)  # -> offset 2*half
    mid = _kara_full(x0 + x1, y0 + y1, half, levels - 1)
    z1 = mid - z0 - z2  # -> offset half
    out_len = 2 * n - 1
    zpad = lambda k: jnp.zeros((k, tn), x.dtype)
    return (
        jnp.concatenate([z0, zpad(out_len - (2 * half - 1))], axis=0)
        + jnp.concatenate(
            [zpad(half), z1, zpad(out_len - half - (2 * half - 1))], axis=0
        )
        + jnp.concatenate([zpad(2 * half), z2], axis=0)
    )


def _school_vpu(x, y, out_len, karatsuba=None):
    """The kernel's full limb product: plain comb schoolbook, or
    `karatsuba` levels of recursion on the full-width products (see the
    _KARATSUBA note). Module-level (pure jnp on [limbs, lanes] arrays) so
    CPU differential tests can execute the exact assembly the TPU kernel
    runs."""
    if karatsuba is None:
        karatsuba = _KARATSUBA
    if not (karatsuba and out_len == _OUT2):
        return _school_comb(x, y, NLIMBS, out_len)
    return _kara_full(x, y, NLIMBS, int(karatsuba))


def _mul_kernel(a_ref, b_ref, band_ref, np_ref, p_ref, out_ref):
    a = _norm(a_ref[:], 2)  # [52, TN], |limbs| <= 132
    b = _norm(b_ref[:], 2)

    def school(x, y, out_len):
        if _VPU:
            return _school_vpu(x, y, out_len)
        # outer[i, j, :] = x[i, :] * y[j, :] -> band-sum over i + j == k
        outer = x[:, None, :] * y[None, :, :]
        flat = outer.reshape(NLIMBS * NLIMBS, x.shape[1])
        band = band_ref[:out_len, :]
        if _I8:
            flat_i = flat.astype(jnp.int32)
            hi_i = (flat_i + 128) >> 8
            lo_i = flat_i - (hi_i << 8)
            acc_lo = jax.lax.dot_general(
                band.astype(jnp.int8),
                lo_i.astype(jnp.int8),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )
            acc_hi = jax.lax.dot_general(
                band.astype(jnp.int8),
                hi_i.astype(jnp.int8),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )
            return (acc_lo + acc_hi * 256).astype(jnp.float32)
        hi = jnp.floor((flat + 128.0) * _INV_BASE)
        lo = flat - hi * _BASE
        acc_lo = jax.lax.dot_general(
            band,
            lo.astype(jnp.bfloat16),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_hi = jax.lax.dot_general(
            band,
            hi.astype(jnp.bfloat16),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return acc_lo + acc_hi * _BASE

    t = school(a, b, _OUT2)  # [103, TN]
    tlo = _norm(t[:NLIMBS], 3)  # t mod 2^416 (truncation intended)
    nprime = jnp.broadcast_to(np_ref[:], a.shape)
    m = _norm(school(tlo, nprime, NLIMBS), 3)
    pcol = jnp.broadcast_to(p_ref[:], a.shape)
    w = t + school(m, pcol, _OUT2)  # = t + m*p
    lo52 = _norm(_ext(w[:NLIMBS], 3), 3)  # limbs 0..51 -> 0, carry above
    hi = _ext(w[NLIMBS:], 1)  # 51 -> 52 limbs
    hi = jnp.concatenate(
        [hi[:3] + lo52[NLIMBS : NLIMBS + 3], hi[3:]], axis=0
    )
    out_ref[:] = _norm(hi, 3)


def _mul_flat(at, bt, nblocks, interpret=False):
    """at, bt: f32 [52, nblocks*TN] transposed operands -> [52, n] product.
    interpret=True runs the kernel through the Pallas interpreter (any
    backend) — the CPU differential-test hook for this TPU-only path."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if _VPU:
        # band matrix unused by the VPU comb: ship a 1x1 dummy instead of
        # copying ~557 KB HBM->VMEM per launch
        band = jnp.zeros((1, 128), jnp.bfloat16)
        band_shape = (1, 128)
    else:
        band = jnp.asarray(_BAND_T_NP, dtype=jnp.bfloat16)
        band_shape = (_OUT2, NLIMBS * NLIMBS)
    return pl.pallas_call(
        _mul_kernel,
        out_shape=jax.ShapeDtypeStruct((NLIMBS, nblocks * TN), jnp.float32),
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec(
                (NLIMBS, TN), lambda i: (0, i), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (NLIMBS, TN), lambda i: (0, i), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                band_shape,
                lambda i: (0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (NLIMBS, 1), lambda i: (0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (NLIMBS, 1), lambda i: (0, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (NLIMBS, TN), lambda i: (0, i), memory_space=pltpu.VMEM
        ),
        interpret=interpret,
    )(
        at,
        bt,
        band,
        jnp.asarray(_NPRIME_COL),
        jnp.asarray(_P_COL),
    )


_ENABLED = None


def enabled():
    """Pallas path active? auto: only on a real TPU backend."""
    global _ENABLED
    if _ENABLED is None:
        flag = os.environ.get("COCONUT_FP_PALLAS", "auto")
        if flag == "auto":
            try:
                _ENABLED = jax.default_backend() == "tpu"
            except Exception:  # pragma: no cover
                _ENABLED = False
        else:
            _ENABLED = flag == "1"
    return _ENABLED


def mul(a, b, interpret=False):
    """Drop-in fused replacement for fp.mul on TPU: same element classes,
    bit-identical results. Flattens leading dims, pads lanes to TN, runs
    the transposed Pallas kernel, restores shape. interpret=True executes
    the kernel via the Pallas interpreter on any backend (tests only)."""
    shape = jnp.broadcast_shapes(a.shape, b.shape)
    a = jnp.broadcast_to(a, shape).reshape(-1, NLIMBS)
    b = jnp.broadcast_to(b, shape).reshape(-1, NLIMBS)
    n = a.shape[0]
    nblocks = -(-n // TN)
    pad = nblocks * TN - n
    if pad:
        zpad = jnp.zeros((pad, NLIMBS), jnp.float32)
        a = jnp.concatenate([a, zpad], axis=0)
        b = jnp.concatenate([b, zpad], axis=0)
    out = _mul_flat(a.T, b.T, nblocks, interpret=interpret).T
    if pad:
        out = out[:n]
    return out.reshape(shape)
