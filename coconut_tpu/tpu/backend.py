"""JaxBackend — the JAX/TPU CurveBackend implementation.

Routes the protocol hot paths (reference signature.rs:472-478 pairing check,
signature.rs:465/513 MSMs) through fused, jitted, batched limb kernels:

  host (python ints)
    -> limb encode (Montgomery)                      [limbs.py]
    -> one XLA program per batch shape:
         shared-base windowed MSM                    [curve.py]
         -> affine normalize (batched inversion)
         -> multi-Miller loop (scan over BLS bits)   [pairing.py]
         -> shared final exponentiation
         -> GT == 1 bits
    -> decode / bools

Results are bit-identical to the Python spec ops (enforced by
tests/test_backends.py and tests/test_tpu_backend.py): identical affine
coordinates for MSMs, identical booleans for pairing products, the spec's
`None`-identity conventions carried as validity masks.

Multi-chip: `shard_verify` shards the credential batch over a mesh axis with
`shard_map` (data parallelism — SURVEY.md §2.3) and all-gathers the bits.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..backend import CurveBackend
from ..ops.curve import g1 as _sg1, g2 as _sg2
from ..ops.fields import R
from . import curve as cv
from . import pairing as pr
from . import tower as tw
# Bit length of the small-exponents combiner scalars r_i (batch_verify_
# combined / _grouped sample secrets.randbits(_R_RAND_BITS)). The signed
# 5-bit recode of a (<2^128)-value occupies ceil(128/5) = 26 windows plus
# one carry window — everything above _R_NWIN is structurally zero, so the
# -sigma_2 MSM can run the short schedule.
_R_RAND_BITS = 128
_R_NWIN = -(-_R_RAND_BITS // 5) + 1  # 27

# The grouped verify's window schedule: signed 6-bit (43 windows, 33-entry
# on-device tables) — the fold adds dominate there and drop ~17% vs the
# 5-bit schedule; the comb/distinct paths keep 5-bit (17-entry host tables).
_G_WINDOW = 6
_G_NWIN = -(-255 // _G_WINDOW)  # 43
_G_RNWIN = -(-_R_RAND_BITS // _G_WINDOW) + 1  # 23


_SIGNED_NWIN = 52  # signed 5-bit windows covering the 255-bit Fr

# Comb (shared-base) schedule: signed 9-bit on the real chip — the comb has
# NO doublings, so fewer windows = strictly fewer fold adds (203 adds at
# k=7/29 windows, vs 224 at 8-bit, 301 at 6-bit, 364 at 5-bit); the larger
# tables (257 multiples/base, int16 digits) amortize behind the per-verkey
# cache. This is also why GLV buys the comb nothing (VERDICT r3 item 3):
# halving scalar bits doubles the base count at constant adds — the
# doubling-free schedule's lever is window size, harvested here directly.
# GLV is applied where doublings DO exist (msm_distinct_signed, see
# _msm_distinct). 10-bit would shave another ~10% of comb adds but is
# blocked by an axon Fp2-build miscompile (see _comb_window_default).
#
# On CPU (the virtual-mesh correctness vehicle: tests, driver dryrun) the
# schedule stays 6-bit: the 257-entry on-device table build multiplies the
# already-dominant mesh execution/compile time there for zero correctness
# value (the 9-bit schedule itself is differentially tested at small
# shapes, and bench.py asserts accept+reject of the full-width 9-bit
# programs on the real chip every run). COCONUT_COMB_WINDOW overrides.


def _comb_window_default():
    import os as _os

    w = _os.environ.get("COCONUT_COMB_WINDOW")
    if w:
        w = int(w)
        # signed digit magnitudes ride in uint8 up to w=8 and int16 for
        # w=9 (limbs.fr_digits_signed_np widens automatically — the r4
        # uint8 cap wrapped 256 -> 0 at w=9 and returned WRONG verify
        # bits, commit 2240a82). w=10 is blocked by the BACKEND, not the
        # algebra: probed 2026-07-31 on the axon chip, the Fp2 comb-table
        # build mis-stacks scan rows at E=513 even under the chunked
        # build (G1 at w=10 and BOTH groups at w=9 are bit-exact; CPU is
        # bit-exact at every window). Fail loudly rather than return
        # wrong G2 MSMs.
        if not 1 <= w <= 9:
            raise ValueError(
                "COCONUT_COMB_WINDOW=%d unsupported: comb windows are "
                "capped at 9 (axon miscompiles the Fp2 table build at "
                "513-entry tables; see _comb_window_default)" % w
            )
        return w
    try:
        return 9 if jax.default_backend() == "tpu" else 6
    except Exception:  # pragma: no cover - backend init failure
        return 6


_C_SCHED = None


def _comb_schedule():
    """(window, nwin, entries) for the shared-base comb — 29/257 at the
    9-bit TPU default (int16 digits), 43/33 at the 6-bit CPU default.
    Chosen LAZILY on first use: `jax.default_backend()`
    initializes the platform client, and doing that at import time would
    both break callers that configure the platform after importing this
    module (multi-process TPU init ordering) and freeze the window choice
    before their config lands."""
    global _C_SCHED
    if _C_SCHED is None:
        w = _comb_window_default()
        _C_SCHED = (w, -(-255 // w), (1 << (w - 1)) + 1)
    return _C_SCHED

# GLV on distinct-base G1 MSMs (see _msm_distinct). Kill switch for callers
# that feed curve points outside the r-order subgroup.
import os as _os

_GLV_ENABLED = _os.environ.get("COCONUT_GLV", "1") == "1"

# Raw point wire (see _pts_f32 / tw.encode_raw_batch): ship 48 raw
# canonical bytes per Fp and enter the Montgomery domain on device. Like
# the comb window, decided LAZILY and per platform: on the real chip the
# host-side bigint Montgomery encode is the wall (PROFILE_r05), on the CPU
# test mesh it would only force a recompile of every cached fused program
# (new operand dtypes) for zero correctness value — the conversion itself
# is differentially tested at the fp level. COCONUT_RAW_WIRE=0/1 overrides.
_RAW_WIRE = None


def _raw_wire_enabled():
    global _RAW_WIRE
    if _RAW_WIRE is None:
        v = _os.environ.get("COCONUT_RAW_WIRE")
        if v is not None:
            _RAW_WIRE = v == "1"
        else:
            try:
                _RAW_WIRE = jax.default_backend() == "tpu"
            except Exception:  # pragma: no cover - backend init failure
                _RAW_WIRE = False
    return _RAW_WIRE


# Device-resident hash-to-G1 (PR 18): run the CTH-v2 SvdW map +
# cofactor clear as one jitted program instead of per-message host
# hashing (the prepare phase's 1,024 serial native calls were the last
# host wall PROFILE_r05 could name). Same lazy per-platform default as
# the raw wire: on the real chip the device map wins; on the CPU test
# mesh it would only add compiles of a ~1k-mul program for zero
# correctness value (the map is differentially tested at small shapes).
# COCONUT_DEVICE_HASH=0/1 overrides.
_DEVICE_HASH = None


def _device_hash_enabled():
    global _DEVICE_HASH
    if _DEVICE_HASH is None:
        v = _os.environ.get("COCONUT_DEVICE_HASH")
        if v is not None:
            _DEVICE_HASH = v == "1"
        else:
            try:
                _DEVICE_HASH = jax.default_backend() == "tpu"
            except Exception:  # pragma: no cover - backend init failure
                _DEVICE_HASH = False
    return _DEVICE_HASH


# Bucketed (Pippenger) distinct-MSM schedule (PR 18): window the
# scalars, scatter points into per-row buckets, fold with the
# running-sum trick (curve.msm_distinct_bucketed) — the table-free
# alternative to msm_distinct_signed's Horner schedule. Selection is a
# cost model per (effective base count, scalar bits), resolved with the
# same lazy per-platform pattern as _comb_window_default:
# COCONUT_MSM_WINDOW=w forces the bucketed path at window w (2..8),
# COCONUT_MSM_WINDOW=0 forces Horner, unset -> cost-model choice on the
# real chip and Horner on the CPU test mesh (where an extra schedule
# only multiplies compile time for zero correctness value — parity is
# asserted by the hashmsm test/bench lanes with the window forced).
_BUCKET_MODE = None


def _bucket_cost(k, nbits, w):
    # batch-width add-equivalents per row: nwin windows of (w doublings
    # ~0.75 add each, k scatter adds, 2*nb running-sum fold adds, 1
    # Horner add); NO table build
    nwin = -(-nbits // w) + 1
    return nwin * (0.75 * w + k + 2 * (1 << (w - 1)) + 1)


def _horner_cost(k, nbits):
    # msm_distinct_signed: 16 chained build adds at k lanes + nwin
    # windows of (5 doublings, k gathered adds)
    nwin = -(-nbits // 5) + 1
    return 16 * k + nwin * (0.75 * 5 + k)


def _bucket_window(k, nbits):
    """Bucketed-schedule window for an effective (post-GLV) per-row base
    count `k` and scalar width `nbits`, or None for the Horner path.
    The cost model's crossover sits around k ~ 64-128: below it the
    17-entry-table Horner schedule is strictly cheaper (the sigma-pair
    show MSM at k = 4 stays Horner unless forced), above it the bucket
    scatter amortizes the missing table build and the larger windows."""
    global _BUCKET_MODE
    if _BUCKET_MODE is None:
        v = _os.environ.get("COCONUT_MSM_WINDOW")
        if v is not None:
            w = int(v)
            if w == 0:
                _BUCKET_MODE = "off"
            elif not 2 <= w <= 8:
                raise ValueError(
                    "COCONUT_MSM_WINDOW=%d unsupported: bucketed windows "
                    "span 2..8 (uint8 digit magnitudes; 0 disables)" % w
                )
            else:
                _BUCKET_MODE = w
        else:
            try:
                _BUCKET_MODE = (
                    "auto" if jax.default_backend() == "tpu" else "off"
                )
            except Exception:  # pragma: no cover - backend init failure
                _BUCKET_MODE = "off"
    if _BUCKET_MODE == "off" or k <= 0:
        return None
    if _BUCKET_MODE != "auto":
        return _BUCKET_MODE
    best = min(range(2, 9), key=lambda w: _bucket_cost(k, nbits, w))
    if _bucket_cost(k, nbits, best) < _horner_cost(k, nbits):
        return best
    return None


def _build_tables(spec_ops, bases, entries=16):
    """Host-side: per-base projective multiples 0..entries-1 as spec
    coordinate tuples (identity = (0, 1, 0), the complete-formula encoding).
    Incremental chain adds (row[d] = row[d-1] + b): one spec add per entry
    instead of a double-and-add ladder per entry. A `None` base (the
    sharded pad lanes from encode_verify_batch's pad_bases_to) encodes as
    an all-identity row explicitly — the complete formulas absorb identity
    entries, and the matching scalars are zero."""
    tables = []
    ident = (spec_ops.zero, spec_ops.one, spec_ops.zero)
    for b in bases:
        if b is None:
            tables.append([ident] * entries)
            continue
        row = [None]
        for _ in range(1, entries):
            row.append(spec_ops.add(row[-1], b) if row[-1] else b)
        enc = []
        for p in row:
            enc.append(ident if p is None else (p[0], p[1], spec_ops.one))
        tables.append(enc)
    # encode: [k][entries] of (X, Y, Z) -> pytree with leading [k, entries]
    flat = [e for row in tables for e in row]
    tree = tw.encode_batch(flat)
    k = len(bases)
    return jax.tree_util.tree_map(
        lambda t: t.reshape((k, entries) + t.shape[1:]), tree
    )


@functools.partial(jax.jit, static_argnums=(0,))
def _comb_build_kernel(field_is_fp2, tables_e):
    fl = cv.FP2 if field_is_fp2 else cv.FP
    window, nwin, _ = _comb_schedule()
    return cv.build_comb_tables(fl, tables_e, nwin, window)


# (is_fp2, base points) -> device comb tables. Bases are spec tuples of
# ints (hashable); the dominant user is the per-verkey fused verify, so a
# handful of entries live here per process — worth it: table build (host
# multiples + nwin x window device doublings) amortizes across every batch
# that reuses the verkey. LRU: a many-verkey workload (the realistic
# multi-issuer verifier rotating through its trust set) must evict ad-hoc
# base sets without throwing away the hot verkeys' tables — the previous
# wholesale clear() thrashed exactly the builds the cache exists to
# amortize (VERDICT r4 weak #5).
_COMB_CACHE = {}
_COMB_CACHE_MAX = 64


# The axon TPU backend corrupts the comb-build scan's stacked output above
# ~1.5k carry lanes (probed 2026-07-31: [nwin, k*E] scans are bit-exact at
# k*E <= 1028 — w9 k4 / w10 k2 — and corrupt at 1799/2052 — w9 k7, w10 k4;
# same backend-bug family as the round-2 int8 einsum and the round-3 fold
# orientation). Chunk the BASE axis so every scan stays at or below the
# probed-good width; chunks are separate dispatches, amortized by the
# per-verkey cache like the build itself.
_BUILD_MAX_LANES = 1028


def _comb_tables(spec_ops, is_fp2, bases):
    # the window is part of the key: a schedule change mid-process (tests
    # monkeypatching _C_SCHED) must never serve tables built for another
    # window
    key = (_comb_schedule()[0], is_fp2, tuple(bases))
    wt = _COMB_CACHE.get(key)
    if wt is None:
        entries = _comb_schedule()[2]
        t_e = _build_tables(spec_ops, bases, entries=entries)
        kmax = max(1, _BUILD_MAX_LANES // entries)
        if len(bases) <= kmax:
            wt = _comb_build_kernel(is_fp2, t_e)
        else:
            chunks = [
                _comb_build_kernel(
                    is_fp2,
                    jax.tree_util.tree_map(
                        lambda t: t[off : off + kmax], t_e
                    ),
                )
                for off in range(0, len(bases), kmax)
            ]
            wt = jax.tree_util.tree_map(
                lambda *ts: jnp.concatenate(ts, axis=0), *chunks
            )
        while len(_COMB_CACHE) >= _COMB_CACHE_MAX:
            _COMB_CACHE.pop(next(iter(_COMB_CACHE)))  # dict = insertion order
        _COMB_CACHE[key] = wt
    else:
        # refresh recency: python dicts iterate in insertion order, so
        # move-to-end makes the eviction above least-recently-USED
        _COMB_CACHE.pop(key)
        _COMB_CACHE[key] = wt
    return wt


# Static-operand cache: the per-(verkey, params) invariant half of a batch
# encode — comb tables over [X_tilde] + Y_tilde, the grouped other-group
# point uploads, the g_tilde pairing constant. encode_verify_batch used to
# rebuild these every call even though they never change across a stream;
# with the cache the steady-state host encode reduces to signature points
# and scalar digits. Keyed by a verkey/params fingerprint (reusing the
# stream layer's run_fingerprint) + the comb window (tests monkeypatch the
# schedule mid-process) + a per-path tag, LRU'd with move-to-end recency
# exactly like _COMB_CACHE. Hit/miss counters: metrics
# encode_cache_hits / encode_cache_misses.
_STATIC_CACHE = {}
_STATIC_CACHE_MAX = 32


def _static_fingerprint(vk, params):
    """Digest identifying a (verkey, params) pair: the stream-layer run
    fingerprint (canonical verkey bytes under the params ctx) extended
    with the params generators — two params contexts sharing a verkey
    must never share cached operands (g_tilde differs)."""
    import hashlib

    from ..stream import run_fingerprint

    h = hashlib.sha256()
    h.update(run_fingerprint("encode", vk, params).encode())
    h.update(repr((params.ctx.name, params.g, params.g_tilde)).encode())
    return h.hexdigest()[:16]


def _static_operands(kind, vk, params, extra, build):
    from .. import metrics

    key = (kind, _static_fingerprint(vk, params), _comb_schedule()[0], extra)
    val = _STATIC_CACHE.get(key)
    if val is not None:
        _STATIC_CACHE.pop(key)
        _STATIC_CACHE[key] = val  # move-to-end: evictions stay LRU
        metrics.count("encode_cache_hits")
        return val
    metrics.count("encode_cache_misses")
    val = build()
    while len(_STATIC_CACHE) >= _STATIC_CACHE_MAX:
        _STATIC_CACHE.pop(next(iter(_STATIC_CACHE)))
    _STATIC_CACHE[key] = val
    return val


def _signed_digits(scalars_batch, nwin=_SIGNED_NWIN, window=5):
    """[B][k] ints -> (mag, sgn bool) [B, k, nwin] signed window digits
    (msb first). mag is uint8 for window <= 8, int16 for window >= 9
    (see limbs.fr_digits_signed_np). Default 5-bit/52 is the distinct-MSM
    Horner schedule; the comb paths pass _comb_schedule()'s window."""
    from .limbs import fr_digits_signed_np

    B = len(scalars_batch)
    k = len(scalars_batch[0]) if B else 0
    flat = [s for row in scalars_batch for s in row]
    mag, sgn = fr_digits_signed_np(flat, nwin=nwin, window=window)
    return (
        jnp.asarray(mag.reshape(B, k, nwin)),
        jnp.asarray(sgn.reshape(B, k, nwin)),
    )


def _comb_digits(scalars_batch):
    window, nwin, _ = _comb_schedule()
    return _signed_digits(scalars_batch, nwin=nwin, window=window)


def _pack_pt(x, y):
    """Compress the device->host result bytes 4.3x: affine outputs are
    LAZY combinations of normalized limbs — G1 coordinates come straight
    out of fp.mul (|v| <= 132, |value| < 0.66p), G2 coordinates are
    fp2_mul outputs, i.e. 2- and 3-term sums of normalized values
    (c1 = t2 - t0 - t1), so the bounds are |v| <= 396, |value| < 1.98p —
    inside fp.pack_canon48's contract, which carry-propagates on device
    to 48 exact base-256 digits of a canonical-width representative
    (48 B/Fp vs 208 B of f32 limbs; the r4 int16 packing was 104 B). The
    axon tunnel reads back at only 2-8 MB/s with ~100 ms latency
    (BASELINE.md caveat), so result bytes — not device FLOPs — are the
    wall-clock cost of every point-returning program (PROFILE_r04.md).
    fp_decode_batch inverts on dtype. COCONUT_DEBUG_PACK=1 checks the
    limb bound: the on-device callback only RECORDS a violation (an
    exception raised inside jax.debug.callback may be swallowed or
    deferred under jit) and limbs.fp_decode_batch asserts host-side at
    the decode boundary of the same readback."""
    if _os.environ.get("COCONUT_DEBUG_PACK") == "1":
        from .limbs import pack_debug_record

        for t in jax.tree_util.tree_leaves((x, y)):
            jax.debug.callback(pack_debug_record, jnp.max(jnp.abs(t)))
    from . import fp as _fp_mod

    f = _fp_mod.pack_canon48
    return jax.tree_util.tree_map(f, x), jax.tree_util.tree_map(f, y)


def _unpack_pt(x, y):
    """Inverse of _pack_pt for device-to-device consumers (the offset
    path): uint8 canonical digits back to f32 limb vectors (digits
    0..255 are valid LAZY limbs; the +2p offset is absorbed mod p by the
    downstream Montgomery arithmetic; limbs 48..51 restore as zeros)."""
    from .limbs import NLIMBS as _NL

    def f(t):
        ft = t.astype(jnp.float32)
        pad = jnp.zeros(ft.shape[:-1] + (_NL - ft.shape[-1],), jnp.float32)
        return jnp.concatenate([ft, pad], axis=-1)

    return jax.tree_util.tree_map(f, x), jax.tree_util.tree_map(f, y)


@functools.partial(jax.jit, static_argnums=(0,))
def _msm_affine_kernel(field_is_fp2, wtables, mag, sgn):
    fl = cv.FP2 if field_is_fp2 else cv.FP
    acc = cv.msm_shared_comb(fl, wtables, mag, sgn)
    x, y, inf = cv.to_affine(fl, acc)
    return (*_pack_pt(x, y), inf)


@jax.jit
def _pairing_kernel(px, py, qx, qy, valid):
    px, py, qx, qy = _pts_f32((px, py, qx, qy))
    return pr.pairing_product_is_one(px, py, qx, qy, valid)


@functools.partial(jax.jit, static_argnums=(0,))
def _msm_distinct_affine_kernel(field_is_fp2, x, y, inf, mag, sgn):
    fl = cv.FP2 if field_is_fp2 else cv.FP
    x, y = _pts_f32((x, y))
    acc = cv.msm_distinct_signed(fl, x, y, inf, mag, sgn)
    ax, ay, ainf = cv.to_affine(fl, acc)
    return (*_pack_pt(ax, ay), ainf)


@functools.partial(jax.jit, static_argnums=(0,))
def _msm_distinct_plus_offset_kernel(
    field_is_fp2, x, y, inf, mag, sgn, ox, oy, oinf
):
    """Distinct-base MSM with a per-lane affine offset added before the
    affine conversion: affine(offset_i + sum_j s_ij * P_ij). The offset
    is another device program's (int16-packed) affine output triple,
    consumed device-to-device — the prepare phase's c2 = pk^k + h^m
    assembly rides here instead of decoding pk^k and adding ~2B points
    on the host."""
    fl = cv.FP2 if field_is_fp2 else cv.FP
    x, y = _pts_f32((x, y))
    acc = cv.msm_distinct_signed(fl, x, y, inf, mag, sgn)
    ox, oy = _unpack_pt(ox, oy)
    off = cv.affine_to_jacobian(fl, ox, oy, oinf)
    ax, ay, ainf = cv.to_affine(fl, cv.jadd(fl, acc, off))
    return (*_pack_pt(ax, ay), ainf)


@functools.partial(jax.jit, static_argnums=(0, 1))
def _msm_distinct_bucketed_kernel(field_is_fp2, window, x, y, inf, mag, sgn):
    """Bucketed-schedule twin of _msm_distinct_affine_kernel. `window`
    is a STATIC jit key (like field_is_fp2): the digit shapes [B, k,
    nwin] differ per window, and the schedule is chosen deterministically
    per (k, group) by _bucket_window, so each workload still compiles
    exactly one program — the engine's <ns>_jit_shapes counters stay
    flat after warmup."""
    fl = cv.FP2 if field_is_fp2 else cv.FP
    x, y = _pts_f32((x, y))
    acc = cv.msm_distinct_bucketed(fl, x, y, inf, mag, sgn, window)
    ax, ay, ainf = cv.to_affine(fl, acc)
    return (*_pack_pt(ax, ay), ainf)


@functools.partial(jax.jit, static_argnums=(0, 1))
def _msm_distinct_bucketed_plus_offset_kernel(
    field_is_fp2, window, x, y, inf, mag, sgn, ox, oy, oinf
):
    """Bucketed-schedule twin of _msm_distinct_plus_offset_kernel, so
    the PR 3 prefetch/offset seams compose with the new schedule."""
    fl = cv.FP2 if field_is_fp2 else cv.FP
    x, y = _pts_f32((x, y))
    acc = cv.msm_distinct_bucketed(fl, x, y, inf, mag, sgn, window)
    ox, oy = _unpack_pt(ox, oy)
    off = cv.affine_to_jacobian(fl, ox, oy, oinf)
    ax, ay, ainf = cv.to_affine(fl, cv.jadd(fl, acc, off))
    return (*_pack_pt(ax, ay), ainf)


@jax.jit
def _hash_to_g1_kernel(u_digits, u_par):
    """Device half of CTH-v2 hash_to_g1 (PR 18): u_digits uint8
    [B, 2, 48] raw canonical digits of the two reduced field candidates
    per message (expand_message_xmd stays on host — cheap SHA-256),
    u_par bool [B, 2] the host-side sgn0(u) bits. One jitted program:
    Montgomery domain entry, the SvdW straight-line map on both
    candidates (stacked), the complete add, the static cofactor ladder,
    affine + packed readback. Bit-identical to ops.hashing.hash_to_g1
    (tests/test_hashmsm.py parity sweep, with the PR 3 native
    cc_hash_to_g1_batch as a second oracle)."""
    from . import fp as _fp_mod
    from ..ops.curve import G1_COFACTOR

    u = _fp_mod.to_mont(u_digits)  # [B, 2, L]
    x, y = cv.svdw_map_fp(u, u_par)
    pts = (x, y, cv.FP.ones(u_par.shape))
    p0 = jax.tree_util.tree_map(lambda t: t[:, 0], pts)
    p1 = jax.tree_util.tree_map(lambda t: t[:, 1], pts)
    q = cv.jadd(cv.FP, p0, p1)
    h = cv.scalar_mul_static(cv.FP, q, G1_COFACTOR)
    ax, ay, ainf = cv.to_affine(cv.FP, h)
    return (*_pack_pt(ax, ay), ainf)


@functools.partial(jax.jit, static_argnums=(0,))
def _msm_shared_many_kernel(field_is_fp2, jobs):
    """Several independent shared-base comb MSMs in ONE XLA program: one
    dispatch + one readback for a whole protocol phase (the issuance
    prepare step runs its commitment + two ElGamal MSMs here — the
    round-3 path paid per-MSM dispatch, VERDICT r3 item 4)."""
    fl = cv.FP2 if field_is_fp2 else cv.FP
    outs = []
    for wt, mag, sgn in jobs:
        x, y, inf = cv.to_affine(fl, cv.msm_shared_comb(fl, wt, mag, sgn))
        outs.append((*_pack_pt(x, y), inf))
    return tuple(outs)


def _pts_f32(tree):
    """Uploaded point operands enter the field arithmetic here, dispatched
    on dtype per leaf:

      - uint8 [..., 48]: RAW canonical base-256 digits from the raw wire
        (tw.encode_raw_batch — 48 B/Fp, no host Montgomery bigints).
        fp.to_mont pads to 52 limbs and multiplies by R^2 through the
        existing exact Montgomery kernel, entering the domain on device
        with bit-identical downstream results (raw digits are valid LAZY
        mul inputs: |v| <= 255, value < p, limbs 48..51 zero).
      - int16 [..., 52]: balanced Montgomery limbs (the legacy halved
        wire; exact integers |v| <= 132) — cast to f32, where XLA fuses
        the cast into the first consumer.
      - f32: device-resident operands and the CPU test path, unchanged.

    NOTE the uint8 MONTGOMERY canon48 digits of the device-to-device
    offset path never come through here — they go through _unpack_pt
    (no domain conversion), see _msm_distinct_plus_offset_kernel."""
    from . import fp as _fp_mod

    def conv(t):
        if t.dtype == jnp.uint8:
            return _fp_mod.to_mont(t)
        return t.astype(jnp.float32) if t.dtype != jnp.float32 else t

    return jax.tree_util.tree_map(conv, tree)


def verify_tail(sig_is_g1, acc, s1, s2n, gtx, gty, inf1, inf2):
    """Post-MSM half of the fused verify: normalize the accumulator and run
    the 2-pair pairing product. Split out so the sharded path (shard.py) can
    combine cross-device MSM partials before entering it.

    G1 assignment uses the specialized two-pair loop with pair 2's shared
    g_tilde ladder and a merged [B] accumulator (pr.miller_two_pairs_
    shared_q2); the G2 assignment keeps the generic pair-set loop (there
    the shared element g_tilde sits on the evaluation side already)."""
    s1, s2n, gtx, gty = _pts_f32((s1, s2n, gtx, gty))
    acc_fl = cv.FP2 if sig_is_g1 else cv.FP
    with jax.named_scope("affine_norm"):
        ax, ay, ainf = cv.to_affine(acc_fl, acc)

    if sig_is_g1:
        with jax.named_scope("miller_two_pairs"):
            f = pr.miller_two_pairs_shared_q2(
                s1[0],
                s1[1],
                ax,
                ay,
                ~inf1 & ~ainf,
                s2n[0],
                s2n[1],
                gtx,
                gty,
                ~inf2,
            )
        with jax.named_scope("final_exp"):
            fe = pr.final_exp(f)
        one = tw.fp12_is_one(fe)
        return one & ~inf1

    def stack2(a, b):
        return jax.tree_util.tree_map(
            lambda x, y: jnp.stack(
                jnp.broadcast_arrays(x, y), axis=max(x.ndim, y.ndim) - 1
            ),
            a,
            b,
        )

    px = stack2(ax, gtx)
    py = stack2(ay, gty)
    qx = stack2(s1[0], s2n[0])
    qy = stack2(s1[1], s2n[1])
    qinf = jnp.stack([inf1, inf2], axis=-1)
    pinf = jnp.stack([ainf, jnp.zeros_like(ainf)], axis=-1)
    valid = ~(pinf | qinf)
    one = pr.pairing_product_is_one(px, py, qx, qy, valid)
    return one & ~inf1


def fused_verify(sig_is_g1, wtables, mag, sgn, s1, s2n, gtx, gty, inf1, inf2):
    """Fused batch verify: comb MSM accumulator + 2-pair pairing product.

    sig_is_g1: signatures live in G1 (ctx "G1") — accumulator is in G2;
    otherwise roles flip. wtables: per-verkey comb window tables
    (cv.build_comb_tables); mag/sgn: signed 6-bit digits [B, k, 43];
    s1/s2n: sigma_1 and -sigma_2 coordinate pytrees [B]; gtx/gty: g_tilde
    affine coordinates pre-encoded as limb pytrees; inf1/inf2: identity
    masks for sigma_1 / sigma_2."""
    acc_fl = cv.FP2 if sig_is_g1 else cv.FP
    with jax.named_scope("comb_msm"):
        acc = cv.msm_shared_comb(acc_fl, wtables, mag, sgn)
    return verify_tail(sig_is_g1, acc, s1, s2n, gtx, gty, inf1, inf2)


_fused_verify_kernel = functools.partial(jax.jit, static_argnums=(0,))(
    fused_verify
)


def _tree_fold_fp12(f, n):
    """Product of a [n]-leading Fp12 pytree (n pow2) by pairwise halving —
    same rationale as cv.fold_points (~n-1 lane-muls instead of the
    fixed-width butterfly's n*log2(n)). Returns a [1]-leading pytree."""
    assert n & (n - 1) == 0
    while n > 1:
        half = n // 2
        lo = jax.tree_util.tree_map(lambda t: t[:half], f)
        hi = jax.tree_util.tree_map(lambda t: t[half:n], f)
        f = tw.fp12_mul(lo, hi)
        n = half
    return f


def fused_verify_combined(
    sig_is_g1, wtables, mag, sgn, s1, s2n, rmag, rsgn, gtx, gty, inf1, inf2
):
    """Probabilistic combined batch verify — ONE boolean for the whole batch.

    Standard small-exponents batch verification: with random 128-bit r_i,

      prod_i [ e(sigma_1_i, acc_i) * e(-sigma_2_i, g_tilde) ]^{r_i} == 1
      ==  prod_i e(r_i sigma_1_i, acc_i)  *  e(sum_i r_i (-sigma_2_i), g_tilde)

    so the batch costs B+1 Miller pairs and ONE shared final exponentiation
    instead of 2B pairs + B final exps (the per-credential kernel
    `fused_verify`). A forged credential escapes detection with probability
    2^-128. Identity masks must be rejected host-side (the kernel treats
    masked lanes as factor 1).

    B must be a power of two (host pads with valid=False lanes)."""
    s1, s2n, gtx, gty = _pts_f32((s1, s2n, gtx, gty))
    acc_fl = cv.FP2 if sig_is_g1 else cv.FP
    sig_fl = cv.FP if sig_is_g1 else cv.FP2
    B = inf1.shape[0]

    acc = cv.msm_shared_comb(acc_fl, wtables, mag, sgn)
    ax, ay, ainf = cv.to_affine(acc_fl, acc)

    def add_k1(pt):
        return jax.tree_util.tree_map(lambda t: t[:, None], pt)

    # r_i * sigma_1_i and r_i * (-sigma_2_i): k=1 signed distinct MSMs over
    # the short 27-window (128-bit r_i) schedule
    s1r = cv.msm_distinct_signed(
        sig_fl, add_k1(s1[0]), add_k1(s1[1]), inf1[:, None], rmag, rsgn
    )
    s2rn = cv.msm_distinct_signed(
        sig_fl, add_k1(s2n[0]), add_k1(s2n[1]), inf2[:, None], rmag, rsgn
    )
    # mask invalid lanes to the identity so they drop out of the sum
    dead = inf1 | inf2 | ainf
    s2rn = tuple(
        sig_fl.select(dead, i_, c)
        for i_, c in zip(cv.jinfinity(sig_fl, (B,)), s2rn)
    )
    s2sum = cv.fold_points(sig_fl, s2rn, B)
    sx, sy, sinf = cv.to_affine(sig_fl, s1r)
    zx, zy, zinf = cv.to_affine(sig_fl, s2sum)

    # B+1 miller pairs: (r_i sigma_1_i, acc_i) for each i, then
    # (sum_i r_i (-sigma_2_i), g_tilde) appended as one extra lane
    def cat(a, b):
        return jax.tree_util.tree_map(
            lambda x, y: jnp.concatenate([x, y[None]], axis=0), a, b
        )

    if sig_is_g1:
        px, py = cat(sx, zx), cat(sy, zy)
        qx, qy = cat(ax, gtx), cat(ay, gty)
    else:
        px, py = cat(ax, gtx), cat(ay, gty)
        qx, qy = cat(sx, zx), cat(sy, zy)
    valid = jnp.concatenate([~dead & ~sinf, ~zinf[None]], axis=0)
    # miller over a [B+1, 1] pair-set shape (npairs = 1: nothing to fold)
    f = pr.multi_miller_loop(
        jax.tree_util.tree_map(lambda t: t[:, None], px),
        jax.tree_util.tree_map(lambda t: t[:, None], py),
        jax.tree_util.tree_map(lambda t: t[:, None], qx),
        jax.tree_util.tree_map(lambda t: t[:, None], qy),
        valid[:, None],
    )  # -> [B+1] fp12
    head = jax.tree_util.tree_map(lambda t: t[:B], f)
    tail = jax.tree_util.tree_map(lambda t: t[B:], f)
    prod = tw.fp12_mul(_tree_fold_fp12(head, B), tail)
    ok = tw.fp12_is_one(pr.final_exp(prod))[0]
    # any dead lane (identity sigma or accumulator) fails the whole batch
    return ok & ~jnp.any(inf1 | inf2 | ainf)


_fused_verify_combined_kernel = functools.partial(
    jax.jit, static_argnums=(0,)
)(fused_verify_combined)


def _grouped_msms(fl, x, y, inf, mag, sgn):
    """M MSMs over the SAME [B] points: signed 6-bit window digits
    mag/sgn [M, B, nwin] (msb first, digit = (-1)^sgn * mag, mag <= 32)
    -> projective accumulators [M].

    Structure (this is the whole per-credential cost of the grouped verify
    — no OtherGroup arithmetic, no per-credential pairing):
      1. one on-device 33-entry table build (32 batched adds over [B]);
      2. ONE gather of all (msm, window, point) table entries [M, nwin, B]
         — the window axis rides in the lane dimension, so the fold runs
         at full width instead of once per window — with the sign applied
         as a Y-flip (free elementwise negate + lane select);
      3. fold over the B axis: ~B-1 lane-adds per (m, w) via fold_points;
      4. a Horner scan over the nwin window sums: 6 doublings + 1 add on
         [M] lanes per window."""
    with jax.named_scope("grouped_tables"):
        tables = cv.build_tables_device(
            fl, x, y, inf, entries=(1 << (_G_WINDOW - 1)) + 1
        )
    M, B, nwin = mag.shape
    dw = jnp.moveaxis(mag, 1, 2)  # [M, nwin, B]
    sw = jnp.moveaxis(sgn, 1, 2)

    def leaf(t):  # t: [B, 33, L...] -> [M, nwin, B, L...]
        tb = jnp.broadcast_to(t[None, None], (M, nwin) + t.shape)
        ix = dw[..., None].reshape(dw.shape + (1,) * (t.ndim - 1))
        return jnp.take_along_axis(tb, ix, axis=3)[:, :, :, 0]

    with jax.named_scope("grouped_gather_fold"):
        X, Y, Z = jax.tree_util.tree_map(leaf, tables)  # [M, nwin, B]
        Y = fl.select(sw, fl.neg(Y), Y)  # signed digit -> negated point
        S = cv.fold_points(fl, (X, Y, Z), B, axis_offset=2)  # [M, nwin]
    Sw = jax.tree_util.tree_map(lambda t: jnp.moveaxis(t, 1, 0), S)

    def body(acc, s):
        acc = jax.lax.fori_loop(
            0, _G_WINDOW, lambda _, a: cv.jdouble(fl, a), acc
        )
        return cv.jadd(fl, acc, s), None

    with jax.named_scope("grouped_horner"):
        acc, _ = jax.lax.scan(body, cv.jinfinity(fl, (M,)), Sw)
    return acc


def grouped_accumulators(sig_fl, s1, s2n, inf1, inf2, cmag, csgn, rmag, rsgn):
    """The per-credential half of the grouped verify: q+2 shared-point MSMs
    over the (local) credential batch -> projective accumulators [q+2].
    Split out so the dp-sharded path (shard.py) can combine cross-device
    partials (point sums commute) before the pairing tail."""
    s1, s2n = _pts_f32((s1, s2n))
    acc1 = _grouped_msms(sig_fl, s1[0], s1[1], inf1, cmag, csgn)  # [q+1]
    acc2 = _grouped_msms(sig_fl, s2n[0], s2n[1], inf2, rmag, rsgn)  # [1]
    return jax.tree_util.tree_map(
        lambda a, b: jnp.concatenate([a, b], axis=0), acc1, acc2
    )


def grouped_tail(sig_is_g1, allacc, ox, oy, gtx, gty, any_dead):
    """Post-MSM half of the grouped verify: q+2 Miller pairs against the
    fixed other-group points, one shared final exponentiation, one bool."""
    ox, oy, gtx, gty = _pts_f32((ox, oy, gtx, gty))
    sig_fl = cv.FP if sig_is_g1 else cv.FP2
    px, py, pinf = cv.to_affine(sig_fl, allacc)  # [q+2] sig-group points

    qx = jax.tree_util.tree_map(
        lambda a, b: jnp.concatenate([a, b[None]], axis=0), ox, gtx
    )
    qy = jax.tree_util.tree_map(
        lambda a, b: jnp.concatenate([a, b[None]], axis=0), oy, gty
    )
    valid = ~pinf  # a zero accumulator contributes the factor 1
    npair = valid.shape[0]
    with jax.named_scope("grouped_miller"):
        f = _grouped_tail_miller(sig_is_g1, px, py, qx, qy, valid)
    # fold the q+2 miller values (pad to a power of two with ones)
    pow2 = 1 << (npair - 1).bit_length()
    if pow2 != npair:
        pad = tw.fp12_ones((pow2 - npair,))
        f = jax.tree_util.tree_map(
            lambda a, b: jnp.concatenate([a, b], axis=0), f, pad
        )
    prod = _tree_fold_fp12(f, pow2)
    with jax.named_scope("final_exp"):
        fe = pr.final_exp(prod)
    ok = tw.fp12_is_one(fe)[0]
    return ok & ~any_dead


def _grouped_tail_miller(sig_is_g1, px, py, qx, qy, valid):
    if sig_is_g1:
        f = pr.multi_miller_loop(
            jax.tree_util.tree_map(lambda t: t[:, None], px),
            jax.tree_util.tree_map(lambda t: t[:, None], py),
            jax.tree_util.tree_map(lambda t: t[:, None], qx),
            jax.tree_util.tree_map(lambda t: t[:, None], qy),
            valid[:, None],
        )
    else:
        f = pr.multi_miller_loop(
            jax.tree_util.tree_map(lambda t: t[:, None], qx),
            jax.tree_util.tree_map(lambda t: t[:, None], qy),
            jax.tree_util.tree_map(lambda t: t[:, None], px),
            jax.tree_util.tree_map(lambda t: t[:, None], py),
            valid[:, None],
        )
    return f


def fused_verify_grouped(
    sig_is_g1, s1, s2n, inf1, inf2, cmag, csgn, rmag, rsgn, ox, oy, gtx, gty
):
    """Attribute-grouped combined batch verify — ONE boolean, q+2 pairs
    TOTAL regardless of batch size.

    The small-exponents combination regrouped by verkey component: with
    random 128-bit r_i and messages m_ij,

      prod_i [e(s1_i, X * prod_j Y_j^{m_ij}) * e(-s2_i, g)]^{r_i}
      = e(sum_i r_i s1_i, X)
        * prod_j e(sum_i (r_i m_ij) s1_i, Y_j)
        * e(sum_i r_i (-s2_i), g)

    so ALL G2/OtherGroup arithmetic disappears (X, Y_j, g are fixed affine
    inputs) and the per-credential work is q+2 shared-point G1 MSMs over the
    batch (_grouped_msms). Soundness 2^-128 per forged credential, as in
    fused_verify_combined.

    Shapes: s1/s2n coordinate pytrees [B]; cmag/csgn [q+1, B, 43] signed
    6-bit window digits (scalars r_i then r_i*m_ij mod r); rmag/rsgn
    [1, B, 23] (r_i for the -s2 sum — r_i are 128-bit so only the low 23
    msb-first windows can be nonzero); ox/oy [q+1] other-group affine (X
    then Y_j); gtx/gty other-group affine g. B power of two."""
    sig_fl = cv.FP if sig_is_g1 else cv.FP2
    # dead lanes: zero digits (host guarantees) -> identity contributions
    allacc = grouped_accumulators(
        sig_fl, s1, s2n, inf1, inf2, cmag, csgn, rmag, rsgn
    )
    return grouped_tail(
        sig_is_g1, allacc, ox, oy, gtx, gty, jnp.any(inf1 | inf2)
    )


_fused_verify_grouped_kernel = functools.partial(
    jax.jit, static_argnums=(0,)
)(fused_verify_grouped)


def fused_show_verify(
    sig_is_g1,
    vc_wtables,
    resp_mag,
    resp_sgn,
    jpt,
    jinf,
    cmag_j,
    csgn_j,
    commx,
    commy,
    comminf,
    acc_wtables,
    acc_mag,
    acc_sgn,
    s1,
    s2n,
    gtx,
    gty,
    inf1,
    inf2,
):
    """Batched PoKOfSignatureProof.verify (the Show/ShowVerify hot path,
    BASELINE config 3; reference surface pok_sig.rs:103-105).

    Two checks per proof, both on-device (cf. ps.PoKOfSignatureProof.verify
    and pok_vc.Proof.verify):

      1. Schnorr randomized-commitment equation over the OtherGroup:
           prod_k bases_k^{resp_ik} * J_i^{c_i} == t_i
         (bases = [g_tilde, hidden Y_tilde] shared across the batch ->
         shared-table MSM; the J_i^{c_i} term is a k=1 distinct MSM;
         t_i is the proof's commitment point, passed affine as commx/y).
      2. Pairing check with the re-randomized signature:
           e(sigma'_1i, J_i * X_tilde * prod_rev Y_tilde^m) * e(-sigma'_2i,
           g_tilde) == 1
         (shared-base MSM over [X_tilde, revealed Y_tilde] with scalars
         [1, m_rev..]; J_i joins by one Jacobian add).

    All proofs must share the same revealed-index set (the bench shape;
    ps.batch_show_verify falls back per-proof otherwise)."""
    jpt, commx, commy = _pts_f32((jpt, commx, commy))
    oth_fl = cv.FP2 if sig_is_g1 else cv.FP

    # -- Schnorr check ------------------------------------------------------
    vc = cv.msm_shared_comb(oth_fl, vc_wtables, resp_mag, resp_sgn)
    jterm = cv.msm_distinct_signed(
        oth_fl,
        jax.tree_util.tree_map(lambda t: t[:, None], jpt[0]),
        jax.tree_util.tree_map(lambda t: t[:, None], jpt[1]),
        jinf[:, None],
        cmag_j,
        csgn_j,
    )
    lhs = cv.jadd(oth_fl, vc, jterm)
    lx, ly, linf = cv.to_affine(oth_fl, lhs)
    schnorr_ok = (
        oth_fl.eq(lx, commx) & oth_fl.eq(ly, commy) & ~linf & ~comminf
    ) | (linf & comminf)

    # -- pairing check ------------------------------------------------------
    acc = cv.msm_shared_comb(oth_fl, acc_wtables, acc_mag, acc_sgn)
    jjac = cv.affine_to_jacobian(oth_fl, jpt[0], jpt[1], jinf)
    acc = cv.jadd(oth_fl, acc, jjac)
    pair_ok = verify_tail(sig_is_g1, acc, s1, s2n, gtx, gty, inf1, inf2)
    return schnorr_ok & pair_ok


_fused_show_verify_kernel = functools.partial(jax.jit, static_argnums=(0,))(
    fused_show_verify
)


def fused_show_verify_combined(
    sig_is_g1,
    vc_wtables,
    resp_mag,
    resp_sgn,
    jpt,
    jinf,
    cmag_j,
    csgn_j,
    commx,
    commy,
    comminf,
    acc_wtables,
    acc_mag,
    acc_sgn,
    s1,
    s2n,
    rmag,
    rsgn,
    gtx,
    gty,
    inf1,
    inf2,
):
    """RLC-combined batched show verify: per-lane Schnorr bits plus ONE
    pairing boolean for the whole batch.

    The Schnorr half is `fused_show_verify`'s verbatim (it is MSM-only —
    no pairing, nothing to combine); the pairing half folds the B
    per-lane checks e(sigma'_1i, acc_i) * e(-sigma'_2i, g_tilde) under
    the combiner exponents r_i exactly as `fused_verify_combined`:
    B+1 Miller pairs, ONE shared final exponentiation.

    Dead lanes (identity sigma' or accumulator) are masked OUT of the
    fold — they fail their own verdict (schnorr_ok & ~dead) without
    poisoning the batch pairing bool, matching the exact path where an
    identity sigma' fails only its lane. Returns
    (per-lane schnorr-and-liveness bits [B], batch pairing bool); the
    caller's lane verdict is bits_i & pair_ok, with ps-layer bisection
    re-deriving exponents per sub-batch to attribute pairing failures."""
    jpt, commx, commy = _pts_f32((jpt, commx, commy))
    s1, s2n, gtx, gty = _pts_f32((s1, s2n, gtx, gty))
    oth_fl = cv.FP2 if sig_is_g1 else cv.FP
    sig_fl = cv.FP if sig_is_g1 else cv.FP2
    B = inf1.shape[0]

    # -- Schnorr check (per lane, identical to fused_show_verify) -----------
    vc = cv.msm_shared_comb(oth_fl, vc_wtables, resp_mag, resp_sgn)
    jterm = cv.msm_distinct_signed(
        oth_fl,
        jax.tree_util.tree_map(lambda t: t[:, None], jpt[0]),
        jax.tree_util.tree_map(lambda t: t[:, None], jpt[1]),
        jinf[:, None],
        cmag_j,
        csgn_j,
    )
    lhs = cv.jadd(oth_fl, vc, jterm)
    lx, ly, linf = cv.to_affine(oth_fl, lhs)
    schnorr_ok = (
        oth_fl.eq(lx, commx) & oth_fl.eq(ly, commy) & ~linf & ~comminf
    ) | (linf & comminf)

    # -- combined pairing check (RLC fold, cf. fused_verify_combined) -------
    acc = cv.msm_shared_comb(oth_fl, acc_wtables, acc_mag, acc_sgn)
    jjac = cv.affine_to_jacobian(oth_fl, jpt[0], jpt[1], jinf)
    acc = cv.jadd(oth_fl, acc, jjac)
    ax, ay, ainf = cv.to_affine(oth_fl, acc)

    def add_k1(pt):
        return jax.tree_util.tree_map(lambda t: t[:, None], pt)

    s1r = cv.msm_distinct_signed(
        sig_fl, add_k1(s1[0]), add_k1(s1[1]), inf1[:, None], rmag, rsgn
    )
    s2rn = cv.msm_distinct_signed(
        sig_fl, add_k1(s2n[0]), add_k1(s2n[1]), inf2[:, None], rmag, rsgn
    )
    dead = inf1 | inf2 | ainf
    s2rn = tuple(
        sig_fl.select(dead, i_, c)
        for i_, c in zip(cv.jinfinity(sig_fl, (B,)), s2rn)
    )
    s2sum = cv.fold_points(sig_fl, s2rn, B)
    sx, sy, sinf = cv.to_affine(sig_fl, s1r)
    zx, zy, zinf = cv.to_affine(sig_fl, s2sum)

    def cat(a, b):
        return jax.tree_util.tree_map(
            lambda x, y: jnp.concatenate([x, y[None]], axis=0), a, b
        )

    if sig_is_g1:
        px, py = cat(sx, zx), cat(sy, zy)
        qx, qy = cat(ax, gtx), cat(ay, gty)
    else:
        px, py = cat(ax, gtx), cat(ay, gty)
        qx, qy = cat(sx, zx), cat(sy, zy)
    valid = jnp.concatenate([~dead & ~sinf, ~zinf[None]], axis=0)
    f = pr.multi_miller_loop(
        jax.tree_util.tree_map(lambda t: t[:, None], px),
        jax.tree_util.tree_map(lambda t: t[:, None], py),
        jax.tree_util.tree_map(lambda t: t[:, None], qx),
        jax.tree_util.tree_map(lambda t: t[:, None], qy),
        valid[:, None],
    )  # -> [B+1] fp12
    head = jax.tree_util.tree_map(lambda t: t[:B], f)
    tail = jax.tree_util.tree_map(lambda t: t[B:], f)
    prod = tw.fp12_mul(_tree_fold_fp12(head, B), tail)
    pair_ok = tw.fp12_is_one(pr.final_exp(prod))[0]
    return schnorr_ok & ~dead, pair_ok


_fused_show_verify_combined_kernel = functools.partial(
    jax.jit, static_argnums=(0,)
)(fused_show_verify_combined)


def _combiner_digits(rs):
    """Combiner exponents -> the short signed-5-bit digit schedule the
    combined kernels' k=1 distinct MSMs run ([B, 1, _R_NWIN]). Refuses
    exponents wider than _R_RAND_BITS — the schedule would silently drop
    their top windows."""
    for r in rs:
        if not 0 <= r < (1 << _R_RAND_BITS):
            raise ValueError(
                "combiner exponent exceeds %d bits" % _R_RAND_BITS
            )
    rmag, rsgn = _signed_digits([[r] for r in rs])
    # only the last _R_NWIN msb-first windows can be nonzero
    return (
        rmag[:, :, _SIGNED_NWIN - _R_NWIN :],
        rsgn[:, :, _SIGNED_NWIN - _R_NWIN :],
    )


class JaxBackend(CurveBackend):
    """Batched JAX/TPU backend (SURVEY.md §7 stage 6)."""

    name = "jax"

    # -- encoding helpers ----------------------------------------------------
    #
    # Point batches upload on one of two wires, chosen per platform by
    # _raw_wire_enabled():
    #
    #   raw (TPU default): 48 raw canonical uint8 digits per Fp — no host
    #   bigint Montgomery multiply, no balance-carry loop, and the upload
    #   halves AGAIN vs int16 (48 B vs 104 B). _pts_f32 enters the
    #   Montgomery domain at kernel entry via fp.to_mont.
    #
    #   int16 (CPU default): balanced Montgomery limbs, exact integers
    #   |v| <= 132, cast back to f32 at kernel entry. The cast to int16
    #   happens in NUMPY, before jnp.asarray commits the buffer.

    @staticmethod
    def _encode_g1_points(points):
        xs = [(0 if p is None else p[0]) for p in points]
        ys = [(0 if p is None else p[1]) for p in points]
        inf = jnp.asarray(np.array([p is None for p in points]))
        if _raw_wire_enabled():
            return (tw.encode_raw_batch(xs), tw.encode_raw_batch(ys)), inf
        return (
            tw.encode_batch(xs, dtype=np.int16),
            tw.encode_batch(ys, dtype=np.int16),
        ), inf

    @staticmethod
    def _encode_g2_points(points):
        zero2 = (0, 0)
        xs = [(zero2 if p is None else p[0]) for p in points]
        ys = [(zero2 if p is None else p[1]) for p in points]
        inf = jnp.asarray(np.array([p is None for p in points]))
        if _raw_wire_enabled():
            return (tw.encode_raw_batch(xs), tw.encode_raw_batch(ys)), inf
        return (
            tw.encode_batch(xs, dtype=np.int16),
            tw.encode_batch(ys, dtype=np.int16),
        ), inf

    # -- CurveBackend primitives --------------------------------------------

    def _msm_shared(self, spec_ops, is_fp2, bases, scalars_batch):
        # cached: the hot users (batch_show / batch_prepare_blind_sign /
        # issuance) call with FIXED base sets (verkey components, params
        # generators) — the 64-entry cap in _comb_tables guards ad-hoc sets
        wtables = _comb_tables(spec_ops, is_fp2, bases)
        mag, sgn = _comb_digits(scalars_batch)
        x, y, inf = _msm_affine_kernel(is_fp2, wtables, mag, sgn)
        xs = tw.decode_batch(x)
        ys = tw.decode_batch(y)
        infs = np.asarray(inf)
        return [
            None if i else (xv, yv) for xv, yv, i in zip(xs, ys, infs)
        ]

    def msm_g1_shared(self, bases, scalars_batch):
        return self._msm_shared(_sg1, False, bases, scalars_batch)

    def msm_g2_shared(self, bases, scalars_batch):
        return self._msm_shared(_sg2, True, bases, scalars_batch)

    def _msm_shared_many_dispatch(self, spec_ops, is_fp2, jobs):
        """Encode + launch the fused multi-MSM program; returns the device
        output handle WITHOUT blocking (jax dispatch is async). Pair with
        `msm_shared_many_wait` — protocol drivers overlap host work (e.g.
        the prepare step's hash-to-group loop, signature.rs:194-206 shape)
        with device execution this way."""
        operands = []
        for bases, scalars_batch in jobs:
            wt = _comb_tables(spec_ops, is_fp2, bases)
            mag, sgn = _comb_digits(scalars_batch)
            operands.append((wt, mag, sgn))
        return _msm_shared_many_kernel(is_fp2, tuple(operands))

    @staticmethod
    def msm_shared_many_wait(outs):
        """Block on a `_dispatch` handle and decode to spec points."""
        results = []
        for x, y, inf in outs:
            xs = tw.decode_batch(x)
            ys = tw.decode_batch(y)
            infs = np.asarray(inf)
            results.append(
                [None if i else (xv, yv) for xv, yv, i in zip(xs, ys, infs)]
            )
        return results

    def _msm_shared_many(self, spec_ops, is_fp2, jobs):
        """jobs: [(bases, scalars_batch)] -> list of per-job result lists,
        all jobs fused into one device program (one dispatch/readback)."""
        return self.msm_shared_many_wait(
            self._msm_shared_many_dispatch(spec_ops, is_fp2, jobs)
        )

    def msm_g1_shared_many(self, jobs):
        return self._msm_shared_many(_sg1, False, jobs)

    def msm_g2_shared_many(self, jobs):
        return self._msm_shared_many(_sg2, True, jobs)

    def msm_g1_shared_many_async(self, jobs):
        return self._msm_shared_many_dispatch(_sg1, False, jobs)

    def msm_g2_shared_many_async(self, jobs):
        return self._msm_shared_many_dispatch(_sg2, True, jobs)

    def _encode_distinct(self, is_fp2, points_batch, scalars_batch,
                         window=5):
        """Shared encode for the distinct-MSM kernels: GLV split (G1),
        limb encoding, signed-digit recode -> (x, y, inf, mag, sgn).
        `window` picks the digit width (5 = the Horner schedule's
        default; the bucketed schedule passes _bucket_window's choice);
        nwin follows as ceil(bits/window) + 1 carry window over the
        128-bit GLV halves or the full 255-bit Fr."""
        B = len(points_batch)
        k = len(points_batch[0])
        if any(len(row) != k for row in points_batch):
            raise ValueError("ragged distinct-MSM batch")
        if not is_fp2 and _GLV_ENABLED:
            # GLV (tpu/glv.py): each 255-bit scalar splits into two
            # nonnegative <= 128-bit halves on (P, phi(P)) — the Horner
            # schedule's doubling chain halves (52 -> 27 windows) for the
            # same add count. G1 only (beta lives in Fp).
            #
            # PRECONDITION: points must lie in the r-order subgroup
            # (phi(P) = lambda*P holds only there; E(Fp) has cofactor
            # ~2^125). Every point that crosses the wire boundary is
            # subgroup-checked at deserialization (ops/serialize.py
            # g1_from_bytes/_from_compressed raise on non-r-torsion
            # input), so all protocol callers satisfy this; callers
            # feeding raw curve points from elsewhere must check
            # g1.in_subgroup first or set COCONUT_GLV=0.
            from . import glv

            points_batch = [
                [q for p in row for q in (p, glv.phi(p))]
                for row in points_batch
            ]
            scalars_batch = [
                [h for s in row for h in glv.decompose(s)]
                for row in scalars_batch
            ]
            k *= 2
            bits = glv.HALF_BITS
        else:
            bits = 255
        nwin = -(-bits // window) + 1  # 27 / 52 at the 5-bit default
        flat_pts = [p for row in points_batch for p in row]
        if is_fp2:
            (x, y), inf = self._encode_g2_points(flat_pts)
        else:
            (x, y), inf = self._encode_g1_points(flat_pts)
        reshape = lambda t: t.reshape((B, k) + t.shape[1:])
        x, y = jax.tree_util.tree_map(reshape, (x, y))
        inf = inf.reshape(B, k)
        mag, sgn = _signed_digits(scalars_batch, nwin=nwin, window=window)
        return x, y, inf, mag, sgn

    @staticmethod
    def _distinct_window(is_fp2, points_batch):
        """Bucketed-vs-Horner schedule choice for a distinct-MSM batch:
        None = Horner, else the bucketed window (_bucket_window's cost
        model over the post-GLV effective base count and scalar width)."""
        k0 = len(points_batch[0]) if points_batch else 0
        glv_on = not is_fp2 and _GLV_ENABLED
        from . import glv

        return _bucket_window(
            2 * k0 if glv_on else k0, glv.HALF_BITS if glv_on else 255
        )

    def _msm_distinct(self, is_fp2, points_batch, scalars_batch):
        from .. import metrics

        w = self._distinct_window(is_fp2, points_batch)
        if w is None:
            metrics.count("msm_horner_dispatches")
            return _msm_distinct_affine_kernel(
                is_fp2,
                *self._encode_distinct(is_fp2, points_batch, scalars_batch),
            )
        metrics.count("msm_bucketed_dispatches")
        metrics.set_gauge("msm_bucket_window", w)
        return _msm_distinct_bucketed_kernel(
            is_fp2,
            w,
            *self._encode_distinct(
                is_fp2, points_batch, scalars_batch, window=w
            ),
        )

    @staticmethod
    def msm_distinct_wait(handle):
        """Block on a `_distinct` dispatch handle and decode to spec points."""
        ax, ay, ainf = handle
        xs = tw.decode_batch(ax)
        ys = tw.decode_batch(ay)
        infs = np.asarray(ainf)
        return [None if i else (xv, yv) for xv, yv, i in zip(xs, ys, infs)]

    def msm_g1_distinct(self, points_batch, scalars_batch):
        return self.msm_distinct_wait(
            self._msm_distinct(False, points_batch, scalars_batch)
        )

    def msm_g2_distinct(self, points_batch, scalars_batch):
        return self.msm_distinct_wait(
            self._msm_distinct(True, points_batch, scalars_batch)
        )

    def msm_g1_distinct_async(self, points_batch, scalars_batch):
        return self._msm_distinct(False, points_batch, scalars_batch)

    def msm_g2_distinct_async(self, points_batch, scalars_batch):
        return self._msm_distinct(True, points_batch, scalars_batch)

    def _msm_distinct_plus_offset(
        self, is_fp2, points_batch, scalars_batch, offset_handle
    ):
        from .. import metrics

        ox, oy, oinf = offset_handle
        w = self._distinct_window(is_fp2, points_batch)
        if w is None:
            metrics.count("msm_horner_dispatches")
            return _msm_distinct_plus_offset_kernel(
                is_fp2,
                *self._encode_distinct(is_fp2, points_batch, scalars_batch),
                ox,
                oy,
                oinf,
            )
        metrics.count("msm_bucketed_dispatches")
        metrics.set_gauge("msm_bucket_window", w)
        return _msm_distinct_bucketed_plus_offset_kernel(
            is_fp2,
            w,
            *self._encode_distinct(
                is_fp2, points_batch, scalars_batch, window=w
            ),
            ox,
            oy,
            oinf,
        )

    def msm_g1_distinct_plus_offset_async(
        self, points_batch, scalars_batch, offset_handle
    ):
        """affine(offset_i + MSM_i) with `offset_handle` an affine device
        triple (x, y, inf) of shape [B] — e.g. one job's output from a
        `msm_g*_shared_many_async` dispatch, consumed without a host
        round trip. Settle with msm_distinct_wait."""
        return self._msm_distinct_plus_offset(
            False, points_batch, scalars_batch, offset_handle
        )

    def msm_g2_distinct_plus_offset_async(
        self, points_batch, scalars_batch, offset_handle
    ):
        return self._msm_distinct_plus_offset(
            True, points_batch, scalars_batch, offset_handle
        )

    # -- device hash-to-curve (PR 18) ---------------------------------------

    @staticmethod
    def device_hash_enabled():
        """Whether protocol callers should route batched hash-to-G1
        through this backend (the COCONUT_DEVICE_HASH knob; lazy
        per-platform default — see _device_hash_enabled)."""
        return _device_hash_enabled()

    def hash_to_g1_async(self, datas, dst=None):
        """Dispatch device-resident CTH-v2 hash_to_g1 over a batch of
        messages: expand_message_xmd runs on host (cheap SHA-256), the
        two reduced field candidates per message upload once as raw
        digits (48 B each, no host Montgomery bigints), and
        map(u0)+map(u1)+clear_cofactor executes as ONE jitted program.
        Returns a dispatch handle; settle with hash_to_g1_wait.
        Bit-identical to ops.hashing.hash_to_g1 and the native
        cc_hash_to_g1_batch oracle."""
        from .. import metrics
        from ..ops import hashing as _H
        from ..ops.fields import P as _P
        from .limbs import fp_encode_raw_batch

        dst = _H.DST_G1 if dst is None else dst
        us = []
        for m in datas:
            b = _H.expand_message_xmd(m, dst, 128)
            us.append(int.from_bytes(b[:64], "big") % _P)
            us.append(int.from_bytes(b[64:], "big") % _P)
        dig = fp_encode_raw_batch(us).reshape(len(datas), 2, -1)
        par = np.array([u & 1 for u in us], dtype=bool).reshape(
            len(datas), 2
        )
        metrics.count("device_hash_batches")
        metrics.count("device_hash_points", len(datas))
        return _hash_to_g1_kernel(jnp.asarray(dig), jnp.asarray(par))

    @staticmethod
    def hash_to_g1_wait(handle):
        """Block on a hash_to_g1_async handle and decode to spec affine
        points. Raises like the spec on the (~2^-255) identity output."""
        ax, ay, ainf = handle
        xs = tw.decode_batch(ax)
        ys = tw.decode_batch(ay)
        infs = np.asarray(ainf)
        if infs.any():
            raise ValueError(
                "hash_to_g1 hit the identity (probability ~2^-255)"
            )
        return list(zip(xs, ys))

    def hash_to_g1_batch(self, datas, dst=None):
        """Synchronous device hash-to-G1 (dispatch + wait)."""
        if not datas:
            return []
        return self.hash_to_g1_wait(self.hash_to_g1_async(datas, dst))

    def pairing_product_is_one(self, pairs_batch):
        B = len(pairs_batch)
        n = len(pairs_batch[0])
        if any(len(row) != n for row in pairs_batch):
            raise ValueError("ragged pairing batch")
        flat_p = [p for row in pairs_batch for p, _ in row]
        flat_q = [q for row in pairs_batch for _, q in row]
        (px, py), pinf = self._encode_g1_points(flat_p)
        (qx, qy), qinf = self._encode_g2_points(flat_q)
        reshape = lambda t: t.reshape((B, n) + t.shape[1:])
        px, py = jax.tree_util.tree_map(reshape, (px, py))
        qx, qy = jax.tree_util.tree_map(reshape, (qx, qy))
        valid = ~(pinf | qinf).reshape(B, n)
        bits = _pairing_kernel(px, py, qx, qy, valid)
        return [bool(b) for b in np.asarray(bits)]

    # -- fused hot path ------------------------------------------------------

    def encode_verify_batch(self, sigs, messages_list, vk, params, pad_bases_to=None):
        """Host-side encoding of a verify batch into the fused-kernel operand
        tuple (wtables, mag, sgn, s1, s2n, gtx, gty, inf1, inf2).

        pad_bases_to: pad the shared-base axis (with identity bases / zero
        scalars) up to this length — the sharded path needs the base count
        divisible by the MSM mesh axis."""
        ctx = params.ctx
        k = 1 + len(vk.Y_tilde)
        npad = max(0, (pad_bases_to or 0) - k)

        def build():
            bases = [vk.X_tilde] + list(vk.Y_tilde) + [None] * npad
            wtables = _comb_tables(ctx.other, ctx.name == "G1", bases)
            return (wtables,) + self._encode_gt(ctx, params)

        wtables, gtx, gty = _static_operands(
            "verify", vk, params, pad_bases_to, build
        )
        scalars = [
            [1] + [m % R for m in msgs] + [0] * npad
            for msgs in messages_list
        ]
        mag, sgn = _comb_digits(scalars)

        s1, inf1 = self._encode_sig_points(ctx, [s.sigma_1 for s in sigs])
        s2n, inf2 = self._encode_sig_points(
            ctx,
            [
                None if s.sigma_2 is None else ctx.sig.neg(s.sigma_2)
                for s in sigs
            ],
        )
        return (wtables, mag, sgn, s1, s2n, gtx, gty, inf1, inf2)

    def _encode_sig_points(self, ctx, pts):
        """Signature-group point batch for whichever group assignment
        `ctx` names — the per-batch (non-cacheable) half of the encode."""
        if ctx.name == "G1":
            return self._encode_g1_points(pts)
        return self._encode_g2_points(pts)

    def _encode_gt(self, ctx, params):
        """The g_tilde pairing constant (other-group generator) — invariant
        per params, so it rides the static-operand cache with the tables."""
        if ctx.name == "G1":
            return (
                tw.fp2_encode_const(params.g_tilde[0]),
                tw.fp2_encode_const(params.g_tilde[1]),
            )
        from .limbs import fp_encode

        return (
            jnp.asarray(fp_encode(params.g_tilde[0])),
            jnp.asarray(fp_encode(params.g_tilde[1])),
        )

    def _encode_sigs_and_gt(self, ctx, sig_pts_1, sig_pts_2n, params):
        """Signature-group point batches + the g_tilde constant, encoded for
        whichever group assignment `ctx` names. Shared by the per-credential,
        show-verify, and grouped paths."""
        s1, inf1 = self._encode_sig_points(ctx, sig_pts_1)
        s2n, inf2 = self._encode_sig_points(ctx, sig_pts_2n)
        gtx, gty = self._encode_gt(ctx, params)
        return s1, s2n, inf1, inf2, gtx, gty

    def batch_verify_async(self, sigs, messages_list, vk, params):
        """Pipelined variant of `batch_verify`: encodes and DISPATCHES the
        fused kernel (JAX dispatch is asynchronous), returning a zero-arg
        finalizer that blocks on the device result. The streaming driver
        (stream.verify_stream) overlaps the next batch's host encode with
        the current batch's device execution through this seam."""
        from .. import metrics

        operands = self.encode_verify_batch(sigs, messages_list, vk, params)
        bits = _fused_verify_kernel(params.ctx.name == "G1", *operands)
        metrics.count("verify_final_exps", len(sigs))

        def finalize():
            return [bool(b) for b in np.asarray(bits)]

        return finalize

    def batch_verify_grouped_async(self, sigs, messages_list, vk, params):
        """Pipelined variant of `batch_verify_grouped` (ONE bool per batch):
        dispatches the grouped kernel and returns a zero-arg finalizer.
        Same input validation as the sync path (mismatched batches must
        raise, not truncate)."""
        B = len(sigs)
        self._validate_grouped_inputs(sigs, messages_list, vk)
        if B == 0:
            return lambda: True
        if any(s.sigma_1 is None or s.sigma_2 is None for s in sigs):
            return lambda: False
        operands = self.encode_grouped_batch(sigs, messages_list, vk, params)
        ok = _fused_verify_grouped_kernel(params.ctx.name == "G1", *operands)
        return lambda: bool(ok)

    @staticmethod
    def _validate_grouped_inputs(sigs, messages_list, vk):
        B = len(sigs)
        q = len(vk.Y_tilde)
        if len(messages_list) != B:
            raise ValueError(
                "batch size mismatch: %d sigs, %d message vectors"
                % (B, len(messages_list))
            )
        for msgs in messages_list:
            if len(msgs) != q:
                raise ValueError(
                    "message vector length %d != msg_count %d"
                    % (len(msgs), q)
                )

    def batch_verify(self, sigs, messages_list, vk, params):
        """Fully-fused batched PS verification (the north-star path)."""
        from .. import metrics

        with metrics.timer("encode"):
            operands = self.encode_verify_batch(sigs, messages_list, vk, params)
            metrics.count(
                "transfer_bytes",
                sum(
                    t.size * t.dtype.itemsize
                    for t in jax.tree_util.tree_leaves(operands)
                    if hasattr(t, "size")
                ),
            )
        with metrics.timer("kernel"):
            bits = _fused_verify_kernel(params.ctx.name == "G1", *operands)
            bits.block_until_ready()
        with metrics.timer("readback"):
            out = [bool(b) for b in np.asarray(bits)]
        metrics.count("verifies", len(out))
        metrics.count("batches")
        # exact path: one final-exponentiation lane per credential
        metrics.count("verify_final_exps", len(out))
        return out

    def _combined_dispatch(self, sigs, messages_list, vk, params, rs, epoch):
        """Shared encode + dispatch for the combined verify (sync/async):
        derives deterministic combiner exponents when `rs` is None, pads
        the batch to a power of two, and returns the device bool handle.
        Callers must have rejected empty batches and identity sigmas."""
        from .. import metrics

        B = len(sigs)
        if rs is None:
            from ..batchverify import derive_combiners, verify_transcript

            rs = derive_combiners(
                verify_transcript(sigs, messages_list, vk, params,
                                  epoch=epoch),
                B,
            )
        elif len(rs) != B:
            raise ValueError(
                "combiner count mismatch: %d exponents, %d lanes"
                % (len(rs), B)
            )
        Bp = 1 << max(1, (B - 1).bit_length())
        pad = Bp - B
        if pad:
            sigs = list(sigs) + [sigs[0]] * pad
            messages_list = list(messages_list) + [messages_list[0]] * pad
            # pad lanes clone lane 0's (valid) relation; reusing r_0 keeps
            # lane 0's total exponent r_0 * (1 + pad) != 0 mod R — sound,
            # and a pure function of the same transcript
            rs = list(rs) + [rs[0]] * pad
        operands = self.encode_verify_batch(sigs, messages_list, vk, params)
        wtables, mag, sgn, s1, s2n, gtx, gty, inf1, inf2 = operands
        rmag, rsgn = _combiner_digits(rs)
        ok = _fused_verify_combined_kernel(
            params.ctx.name == "G1",
            wtables,
            mag,
            sgn,
            s1,
            s2n,
            rmag,
            rsgn,
            gtx,
            gty,
            inf1,
            inf2,
        )
        # ONE shared final exponentiation per combined batch (vs B lanes
        # on the exact path) — the bench's <= 2-per-batch assert reads this
        metrics.count("verify_final_exps", 1)
        return ok

    def batch_verify_combined(
        self, sigs, messages_list, vk, params, rs=None, epoch=None
    ):
        """One boolean for the whole batch via small-exponents combination
        (see fused_verify_combined): ~half the Miller work and 1/B of the
        final-exponentiation work of `batch_verify`. Probabilistic: a forged
        credential passes with probability <= 2^-lambda over the combiner
        draw. `rs=None` derives the combiners deterministically from the
        domain-separated batch transcript (batchverify.derive_combiners —
        replayable, sound in the random-oracle model since the transcript
        commits to the batch before the exponents exist); pass explicit
        `rs` to pin exponents (tests). `epoch` joins the transcript's
        domain separation (PR 15 key epochs share verkey bytes)."""
        from .. import metrics

        metrics.count("verify_batched_checks")
        B = len(sigs)
        if B == 0:
            return True  # empty product is 1
        if any(s.sigma_1 is None or s.sigma_2 is None for s in sigs):
            return False
        return bool(
            self._combined_dispatch(sigs, messages_list, vk, params, rs, epoch)
        )

    def batch_verify_combined_async(
        self, sigs, messages_list, vk, params, rs=None, epoch=None
    ):
        """Pipelined variant of `batch_verify_combined` (ONE bool per
        batch): dispatches the combined kernel and returns a zero-arg
        finalizer — the stream/serve "batched" mode overlaps the next
        batch's host encode with this batch's device execution."""
        from .. import metrics

        metrics.count("verify_batched_checks")
        if len(sigs) == 0:
            return lambda: True
        if any(s.sigma_1 is None or s.sigma_2 is None for s in sigs):
            return lambda: False
        ok = self._combined_dispatch(sigs, messages_list, vk, params, rs, epoch)
        return lambda: bool(ok)

    def batch_show_verify_combined(
        self, proofs, vk, params, revealed_msgs_list, challenges, rs=None,
        epoch=None
    ):
        """RLC-combined batched show verify -> (per-lane Schnorr bits,
        ONE batch pairing bool). The Schnorr half stays per-lane (it is
        MSM-only); the B pairing checks fold under deterministic combiner
        exponents into B+1 Miller pairs + ONE final exponentiation
        (fused_show_verify_combined). A lane's verdict is
        bits[i] & pair_ok; on pair_ok=False the ps-layer bisects with
        fresh per-sub-batch exponents to attribute the culprit lanes.
        All proofs must share one revealed-index set (as
        `batch_show_verify`)."""
        from .. import metrics

        metrics.count("verify_batched_checks")
        B = len(proofs)
        if B == 0:
            return [], True
        if rs is None:
            from ..batchverify import derive_combiners, show_transcript

            rs = derive_combiners(
                show_transcript(proofs, vk, params, revealed_msgs_list,
                                challenges, epoch=epoch),
                B,
            )
        elif len(rs) != B:
            raise ValueError(
                "combiner count mismatch: %d exponents, %d lanes"
                % (len(rs), B)
            )
        Bp = 1 << max(1, (B - 1).bit_length())
        pad = Bp - B
        if pad:
            # clone-first padding, as the engine's assemble(): a cloned
            # lane reuses its original's challenge AND combiner exponent
            proofs = list(proofs) + [proofs[0]] * pad
            revealed_msgs_list = (
                list(revealed_msgs_list) + [revealed_msgs_list[0]] * pad
            )
            challenges = list(challenges) + [challenges[0]] * pad
            rs = list(rs) + [rs[0]] * pad
        operands = self.encode_show_verify_batch(
            proofs, vk, params, revealed_msgs_list, challenges
        )
        (
            vc_wtables, resp_mag, resp_sgn, jpt, jinf, cmag_j, csgn_j,
            commx, commy, comminf, acc_wtables, acc_mag, acc_sgn,
            s1, s2n, gtx, gty, inf1, inf2,
        ) = operands
        rmag, rsgn = _combiner_digits(rs)
        bits, pair_ok = _fused_show_verify_combined_kernel(
            params.ctx.name == "G1",
            vc_wtables,
            resp_mag,
            resp_sgn,
            jpt,
            jinf,
            cmag_j,
            csgn_j,
            commx,
            commy,
            comminf,
            acc_wtables,
            acc_mag,
            acc_sgn,
            s1,
            s2n,
            rmag,
            rsgn,
            gtx,
            gty,
            inf1,
            inf2,
        )
        metrics.count("verify_final_exps", 1)
        return (
            [bool(b) for b in np.asarray(bits)[:B]],
            bool(pair_ok),
        )

    def batch_show_verify(
        self, proofs, vk, params, revealed_msgs_list, challenges
    ):
        """Batched selective-disclosure proof verification (config 3).

        All proofs must share one revealed-index set; `ps.batch_show_verify`
        is the public API (it recomputes Fiat-Shamir challenges and falls
        back to the sequential path on ragged batches)."""
        from .. import metrics

        if len(proofs) == 0:
            return []
        operands = self.encode_show_verify_batch(
            proofs, vk, params, revealed_msgs_list, challenges
        )
        bits = _fused_show_verify_kernel(params.ctx.name == "G1", *operands)
        metrics.count("verify_final_exps", len(proofs))
        return [bool(b) for b in np.asarray(bits)]

    def encode_show_verify_batch(
        self, proofs, vk, params, revealed_msgs_list, challenges
    ):
        """Host-side encoding of a show-verify batch into the
        fused_show_verify operand tuple (everything after sig_is_g1).
        Split out so the dp-sharded path (tpu/shard.py) shares it."""
        ctx = params.ctx
        B = len(proofs)
        revealed = sorted(proofs[0].revealed_msg_indices)
        hidden = [
            i for i in range(len(vk.Y_tilde)) if i not in proofs[0].revealed_msg_indices
        ]
        oth = ctx.other
        is_g1_ctx = ctx.name == "G1"

        # static operands (Schnorr + pairing comb tables, g_tilde): one
        # cache entry per (vk, params, revealed-index set)
        def build():
            vc_bases = [params.g_tilde] + [vk.Y_tilde[i] for i in hidden]
            acc_bases = [vk.X_tilde] + [vk.Y_tilde[i] for i in revealed]
            return (
                _comb_tables(oth, is_g1_ctx, vc_bases),
                _comb_tables(oth, is_g1_ctx, acc_bases),
            ) + self._encode_gt(ctx, params)

        vc_wtables, acc_wtables, gtx, gty = _static_operands(
            "show", vk, params, tuple(revealed), build
        )

        # Schnorr operands
        resp_mag, resp_sgn = _comb_digits(
            [[r % R for r in p.proof_vc.responses] for p in proofs]
        )
        enc_other = (
            self._encode_g2_points if is_g1_ctx else self._encode_g1_points
        )
        (jx, jy), jinf = enc_other([p.J for p in proofs])
        cmag_j, csgn_j = _signed_digits([[c % R] for c in challenges])
        (commx, commy), comminf = enc_other([p.proof_vc.t for p in proofs])

        # pairing operands
        acc_mag, acc_sgn = _comb_digits(
            [
                [1] + [rm[i] % R for i in revealed]
                for rm in revealed_msgs_list
            ]
        )
        s1, inf1 = self._encode_sig_points(
            ctx, [p.sigma_prime_1 for p in proofs]
        )
        s2n, inf2 = self._encode_sig_points(
            ctx,
            [
                None if p.sigma_prime_2 is None else ctx.sig.neg(p.sigma_prime_2)
                for p in proofs
            ],
        )
        return (
            vc_wtables,
            resp_mag,
            resp_sgn,
            ((jx, jy)),
            jinf,
            cmag_j,
            csgn_j,
            commx,
            commy,
            comminf,
            acc_wtables,
            acc_mag,
            acc_sgn,
            s1,
            s2n,
            gtx,
            gty,
            inf1,
            inf2,
        )

    def batch_verify_grouped(self, sigs, messages_list, vk, params):
        """One boolean for the whole batch via the attribute-grouped
        combination (fused_verify_grouped): q+2 pairings total, all
        per-credential work in shared-point G1 MSMs. The fastest verify
        path; soundness 2^-128 per forged credential."""
        from .. import metrics

        B = len(sigs)
        self._validate_grouped_inputs(sigs, messages_list, vk)
        if B == 0:
            return True
        if any(s.sigma_1 is None or s.sigma_2 is None for s in sigs):
            return False
        operands = self.encode_grouped_batch(sigs, messages_list, vk, params)
        ok = _fused_verify_grouped_kernel(params.ctx.name == "G1", *operands)
        metrics.count("verify_final_exps", 1)
        return bool(ok)

    def encode_grouped_batch(
        self, sigs, messages_list, vk, params, pad_batch_to=None
    ):
        """Host-side encoding for the grouped verify kernel: pads the batch
        to a power of two (>= pad_batch_to if given — the sharded path needs
        the batch divisible by the mesh's dp extent), samples the combiner
        scalars, and recodes all scalar rows to the signed 6-bit/43-window
        schedule (_G_WINDOW/_G_NWIN).
        Returns the fused_verify_grouped operand tuple (everything after
        sig_is_g1). Callers must have rejected empty batches and identity
        sigmas already."""
        import secrets

        B = len(sigs)
        q = len(vk.Y_tilde)
        Bp = 1 << max(1, (B - 1).bit_length())
        if pad_batch_to is not None:
            while Bp < pad_batch_to:
                Bp *= 2
        pad = Bp - B
        if pad:
            sigs = list(sigs) + [sigs[0]] * pad
            messages_list = list(messages_list) + [messages_list[0]] * pad
        ctx = params.ctx
        rs = [secrets.randbits(_R_RAND_BITS) for _ in range(Bp)]
        rows = [rs] + [
            [r * (msgs[j] % R) % R for r, msgs in zip(rs, messages_list)]
            for j in range(q)
        ]
        from .limbs import fr_digits_signed_np

        recoded = [
            fr_digits_signed_np(row, nwin=_G_NWIN, window=_G_WINDOW)
            for row in rows
        ]
        cmag = jnp.asarray(np.stack([m for m, _ in recoded]))
        csgn = jnp.asarray(np.stack([s for _, s in recoded]))  # [q+1, Bp, 43]
        # r_i are _R_RAND_BITS-bit: only the last _G_RNWIN msb-first windows
        # of the r-row can be nonzero — slice so the -sigma_2 MSM runs a
        # short schedule. A real check (not assert: must survive python -O)
        # so a widened sampler can never silently drop top windows.
        nwin = cmag.shape[-1]
        if recoded[0][0][:, : nwin - _G_RNWIN].any():
            raise ValueError(
                "combiner scalar exceeds %d bits: top windows nonzero"
                % _R_RAND_BITS
            )
        rmag = cmag[:1, :, nwin - _G_RNWIN :]
        rsgn = csgn[:1, :, nwin - _G_RNWIN :]

        s1, inf1 = self._encode_sig_points(ctx, [s.sigma_1 for s in sigs])
        s2n, inf2 = self._encode_sig_points(
            ctx, [ctx.sig.neg(s.sigma_2) for s in sigs]
        )

        def build():
            others = [vk.X_tilde] + list(vk.Y_tilde)
            if ctx.name == "G1":
                ox = tw.encode_batch([p[0] for p in others])
                oy = tw.encode_batch([p[1] for p in others])
            else:
                from .limbs import fp_encode_batch

                ox = jnp.asarray(fp_encode_batch([p[0] for p in others]))
                oy = jnp.asarray(fp_encode_batch([p[1] for p in others]))
            return (ox, oy) + self._encode_gt(ctx, params)

        ox, oy, gtx, gty = _static_operands("grouped", vk, params, None, build)
        return (s1, s2n, inf1, inf2, cmag, csgn, rmag, rsgn, ox, oy, gtx, gty)

    def batch_verify_sharded(self, sigs, messages_list, vk, params, mesh, **kw):
        """Multi-chip variant: dp-sharded credentials, tp-sharded MSM bases
        over `mesh` (see tpu/shard.py)."""
        from . import shard

        return shard.batch_verify_sharded(
            self, sigs, messages_list, vk, params, mesh, **kw
        )

    def batch_verify_grouped_sharded(
        self, sigs, messages_list, vk, params, mesh, **kw
    ):
        """Multi-chip HEADLINE variant: the attribute-grouped one-bool
        verify with the credential batch dp-sharded over `mesh` and the
        MSM accumulators combined across devices (see tpu/shard.py)."""
        from . import shard

        return shard.batch_verify_grouped_sharded(
            self, sigs, messages_list, vk, params, mesh, **kw
        )
