"""JaxBackend — the JAX/TPU CurveBackend implementation.

Routes the protocol hot paths (reference signature.rs:472-478 pairing check,
signature.rs:465/513 MSMs) through fused, jitted, batched limb kernels:

  host (python ints)
    -> limb encode (Montgomery)                      [limbs.py]
    -> one XLA program per batch shape:
         shared-base windowed MSM                    [curve.py]
         -> affine normalize (batched inversion)
         -> multi-Miller loop (scan over BLS bits)   [pairing.py]
         -> shared final exponentiation
         -> GT == 1 bits
    -> decode / bools

Results are bit-identical to the Python spec ops (enforced by
tests/test_backends.py and tests/test_tpu_backend.py): identical affine
coordinates for MSMs, identical booleans for pairing products, the spec's
`None`-identity conventions carried as validity masks.

Multi-chip: `shard_verify` shards the credential batch over a mesh axis with
`shard_map` (data parallelism — SURVEY.md §2.3) and all-gathers the bits.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..backend import CurveBackend
from ..ops.curve import g1 as _sg1, g2 as _sg2
from ..ops.fields import R
from . import curve as cv
from . import pairing as pr
from . import tower as tw
from .limbs import fr_to_digits

_WINDOW = 4
_NDIG = 64


def _build_tables(spec_ops, bases):
    """Host-side: per-base Jacobian multiples 0..15 as spec coordinate
    tuples (identity = the spec's (1, 1, 0))."""
    tables = []
    for b in bases:
        row = [None] + [spec_ops.mul(b, d) for d in range(1, 16)]
        enc = []
        for p in row:
            if p is None:
                enc.append((spec_ops.one, spec_ops.one, spec_ops.zero))
            else:
                enc.append((p[0], p[1], spec_ops.one))
        tables.append(enc)
    # encode: [k][16] of (X, Y, Z) -> pytree with leading [k, 16]
    flat = [e for row in tables for e in row]
    tree = tw.encode_batch(flat)
    k = len(bases)
    return jax.tree_util.tree_map(
        lambda t: t.reshape((k, 16) + t.shape[1:]), tree
    )


def _digits(scalars_batch):
    return jnp.asarray(
        np.stack(
            [
                np.stack([fr_to_digits(s, _WINDOW) for s in row])
                for row in scalars_batch
            ]
        )
    )


@functools.partial(jax.jit, static_argnums=(0,))
def _msm_affine_kernel(field_is_fp2, tables, digits):
    fl = cv.FP2 if field_is_fp2 else cv.FP
    acc = cv.msm_shared(fl, tables, digits)
    return cv.to_affine(fl, acc)


@jax.jit
def _pairing_kernel(px, py, qx, qy, valid):
    return pr.pairing_product_is_one(px, py, qx, qy, valid)


@functools.partial(jax.jit, static_argnums=(0,))
def _fused_verify_kernel(sig_is_g1, tables, digits, s1, s2n, gtx, gty, inf1, inf2):
    """Fused batch verify: MSM accumulator + 2-pair pairing product.

    sig_is_g1: signatures live in G1 (ctx "G1") — accumulator is in G2;
    otherwise roles flip. s1/s2n: sigma_1 and -sigma_2 coordinate pytrees
    [B]; gtx/gty: g_tilde affine coordinates pre-encoded as limb pytrees;
    inf1/inf2: identity masks for sigma_1 / sigma_2."""
    acc_fl = cv.FP2 if sig_is_g1 else cv.FP
    acc = cv.msm_shared(acc_fl, tables, digits)
    ax, ay, ainf = cv.to_affine(acc_fl, acc)

    def stack2(a, b):
        return jax.tree_util.tree_map(
            lambda x, y: jnp.stack(
                jnp.broadcast_arrays(x, y), axis=max(x.ndim, y.ndim) - 1
            ),
            a,
            b,
        )

    if sig_is_g1:
        px = stack2(s1[0], s2n[0])
        py = stack2(s1[1], s2n[1])
        qx = stack2(ax, gtx)
        qy = stack2(ay, gty)
        pinf = jnp.stack([inf1, inf2], axis=-1)
        qinf = jnp.stack([ainf, jnp.zeros_like(ainf)], axis=-1)
    else:
        px = stack2(ax, gtx)
        py = stack2(ay, gty)
        qx = stack2(s1[0], s2n[0])
        qy = stack2(s1[1], s2n[1])
        qinf = jnp.stack([inf1, inf2], axis=-1)
        pinf = jnp.stack([ainf, jnp.zeros_like(ainf)], axis=-1)
    valid = ~(pinf | qinf)
    one = pr.pairing_product_is_one(px, py, qx, qy, valid)
    return one & ~inf1


class JaxBackend(CurveBackend):
    """Batched JAX/TPU backend (SURVEY.md §7 stage 6)."""

    name = "jax"

    # -- encoding helpers ----------------------------------------------------

    @staticmethod
    def _encode_g1_points(points):
        xs = [(0 if p is None else p[0]) for p in points]
        ys = [(0 if p is None else p[1]) for p in points]
        inf = jnp.asarray(np.array([p is None for p in points]))
        return (tw.encode_batch(xs), tw.encode_batch(ys)), inf

    @staticmethod
    def _encode_g2_points(points):
        zero2 = (0, 0)
        xs = [(zero2 if p is None else p[0]) for p in points]
        ys = [(zero2 if p is None else p[1]) for p in points]
        inf = jnp.asarray(np.array([p is None for p in points]))
        return (tw.encode_batch(xs), tw.encode_batch(ys)), inf

    # -- CurveBackend primitives --------------------------------------------

    def _msm_shared(self, spec_ops, is_fp2, bases, scalars_batch):
        tables = _build_tables(spec_ops, bases)
        digits = _digits(scalars_batch)
        x, y, inf = _msm_affine_kernel(is_fp2, tables, digits)
        xs = tw.decode_batch(x)
        ys = tw.decode_batch(y)
        infs = np.asarray(inf)
        return [
            None if i else (xv, yv) for xv, yv, i in zip(xs, ys, infs)
        ]

    def msm_g1_shared(self, bases, scalars_batch):
        return self._msm_shared(_sg1, False, bases, scalars_batch)

    def msm_g2_shared(self, bases, scalars_batch):
        return self._msm_shared(_sg2, True, bases, scalars_batch)

    def pairing_product_is_one(self, pairs_batch):
        B = len(pairs_batch)
        n = len(pairs_batch[0])
        if any(len(row) != n for row in pairs_batch):
            raise ValueError("ragged pairing batch")
        flat_p = [p for row in pairs_batch for p, _ in row]
        flat_q = [q for row in pairs_batch for _, q in row]
        (px, py), pinf = self._encode_g1_points(flat_p)
        (qx, qy), qinf = self._encode_g2_points(flat_q)
        reshape = lambda t: t.reshape((B, n) + t.shape[1:])
        px, py = jax.tree_util.tree_map(reshape, (px, py))
        qx, qy = jax.tree_util.tree_map(reshape, (qx, qy))
        valid = ~(pinf | qinf).reshape(B, n)
        bits = _pairing_kernel(px, py, qx, qy, valid)
        return [bool(b) for b in np.asarray(bits)]

    # -- fused hot path ------------------------------------------------------

    def batch_verify(self, sigs, messages_list, vk, params):
        """Fully-fused batched PS verification (the north-star path)."""
        ctx = params.ctx
        bases = [vk.X_tilde] + list(vk.Y_tilde)
        scalars = [[1] + [m % R for m in msgs] for msgs in messages_list]
        tables = _build_tables(ctx.other, bases)
        digits = _digits(scalars)

        sig_pts_1 = [s.sigma_1 for s in sigs]
        sig_pts_2n = [
            None if s.sigma_2 is None else ctx.sig.neg(s.sigma_2) for s in sigs
        ]
        if ctx.name == "G1":
            s1, inf1 = self._encode_g1_points(sig_pts_1)
            s2n, inf2 = self._encode_g1_points(sig_pts_2n)
            gtx = tw.fp2_encode_const(params.g_tilde[0])
            gty = tw.fp2_encode_const(params.g_tilde[1])
        else:
            s1, inf1 = self._encode_g2_points(sig_pts_1)
            s2n, inf2 = self._encode_g2_points(sig_pts_2n)
            from .limbs import fp_encode

            gtx = jnp.asarray(fp_encode(params.g_tilde[0]))
            gty = jnp.asarray(fp_encode(params.g_tilde[1]))
        bits = _fused_verify_kernel(
            ctx.name == "G1",
            tables,
            digits,
            s1,
            s2n,
            gtx,
            gty,
            inf1,
            inf2,
        )
        return [bool(b) for b in np.asarray(bits)]
