"""Multi-chip sharded batch verification over a (dp, tp) device mesh.

The TPU-native answer to SURVEY.md §2.3's parallelism table:

  - **dp** (data parallelism): the credential batch is sharded over the mesh's
    ``dp`` axis — each device verifies its slice independently. This is the
    primary axis; the workload (one pairing check per credential, reference
    signature.rs:472-478) is embarrassingly data-parallel.
  - **tp** (tensor parallelism / sharded MSM): the shared-base MSM inside each
    verification (the X̃·∏Ỹⱼ^{mⱼ} accumulator, SURVEY.md §3.4) is sharded
    over the ``tp`` axis by *base index*: each device computes a partial MSM
    over its subset of bases, partials are combined with an
    ``all_gather`` + Jacobian-add tree inside ``shard_map`` (point addition is
    not a ring sum, so ``psum`` does not apply — the combine rides the same
    ICI links), and every device then runs the pairing tail on its dp-slice.

Collectives ride ICI via XLA (`all_gather` over the tp axis); nothing here
depends on device count — the same program runs on a v5e-8 mesh or the
8-device virtual CPU mesh the tests use (conftest.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.4.35 exposes shard_map at top level
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

from . import backend as bk
from . import curve as cv


_PROGRAM_CACHE = {}


def require_axes(mesh, *axes):
    """Check that `mesh` names every axis in `axes`, with a clear error up
    front instead of a bare KeyError from mesh.shape['tp'] deep inside the
    first batch's dispatch."""
    missing = [a for a in axes if a not in mesh.shape]
    if missing:
        raise ValueError(
            "mesh is missing axis(es) %s: it has %s; build the mesh with "
            "shard.default_mesh() or Mesh(devices, ('dp', 'tp'))"
            % (
                ", ".join(repr(a) for a in missing),
                tuple(mesh.shape) or "no axes",
            )
        )


def _shard_map(local, mesh, in_specs, out_specs):
    """shard_map with the check_vma/check_rep spelling fallback (the
    scans initialize carries from replicated constants that become
    mesh-varying inside the loop — sound, since every sharded program's
    outputs are asserted bit-identical to the spec path, but rejected by
    the static vma check; older jax spells the kwarg check_rep)."""
    try:
        return shard_map(
            local, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    except TypeError:  # pragma: no cover - jax < 0.4.35 spelling
        return shard_map(
            local, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )


def make_sharded_verify(mesh, sig_is_g1, batch_axis="dp", msm_axis="tp"):
    """Build the jitted shard_map'd fused-verify program for `mesh`.

    Operands are the same tuple `JaxBackend.encode_verify_batch` produces,
    with the base axis padded to a multiple of the tp axis size and the batch
    divisible by the dp axis size. Returns bits [B] (fully replicated gather
    of the dp shards).

    Programs are memoized per (mesh, flavor, axes): a fresh closure + jit
    per call would defeat jit's function-identity cache and re-pay the
    multi-minute fused compile on every batch of a streamed run."""
    key = (mesh, sig_is_g1, batch_axis, msm_axis)
    cached = _PROGRAM_CACHE.get(key)
    if cached is not None:
        return cached
    ntp = mesh.shape[msm_axis]
    acc_fl = cv.FP2 if sig_is_g1 else cv.FP

    def local(wtables, mag, sgn, s1, s2n, gtx, gty, inf1, inf2):
        # wtables: leading [k/ntp, nwin, 17]; mag/sgn: [B/ndp, k/ntp, nwin]
        acc = cv.msm_shared_comb(acc_fl, wtables, mag, sgn)
        if ntp > 1:
            parts = jax.lax.all_gather(acc, msm_axis)  # leaves [ntp, ...]

            def take(i):
                return jax.tree_util.tree_map(lambda t: t[i], parts)

            acc = take(0)
            for i in range(1, ntp):
                acc = cv.jadd(acc_fl, acc, take(i))
        return bk.verify_tail(sig_is_g1, acc, s1, s2n, gtx, gty, inf1, inf2)

    in_specs = (
        P(msm_axis),  # comb tables: bases sharded
        P(batch_axis, msm_axis),  # mag: batch x bases
        P(batch_axis, msm_axis),  # sgn
        P(batch_axis),  # s1
        P(batch_axis),  # s2n
        P(),  # gtx (replicated constant)
        P(),  # gty
        P(batch_axis),  # inf1
        P(batch_axis),  # inf2
    )
    # check_vma=False (via _shard_map): the Miller/MSM scans initialize
    # carries from replicated constants (identity points, GT one) that
    # become mesh-varying inside the loop — sound here (outputs are
    # asserted bit-identical to the spec path), but the static vma type
    # check rejects it.
    jitted = jax.jit(_shard_map(local, mesh, in_specs, P(batch_axis)))
    _PROGRAM_CACHE[key] = jitted
    return jitted


def make_sharded_grouped_verify(mesh, sig_is_g1, batch_axis="dp"):
    """The HEADLINE program, sharded: dp-shard the credential batch of the
    attribute-grouped one-bool verify (backend.fused_verify_grouped).

    Each device runs the q+2 shared-point grouped MSMs on its credential
    slice; the projective accumulators (point sums — order-independent,
    the complete RCB formulas are exact) are combined across the dp axis
    with an all_gather + Jacobian-add tree, and every device then runs the
    identical q+2-pair pairing tail, returning the replicated batch bool.
    The identity-sigma death flag is psum-reduced so ANY device's dead lane
    fails the whole batch, exactly like the single-chip kernel."""
    key = ("grouped", mesh, sig_is_g1, batch_axis)
    cached = _PROGRAM_CACHE.get(key)
    if cached is not None:
        return cached
    ndp = mesh.shape[batch_axis]
    sig_fl = cv.FP if sig_is_g1 else cv.FP2

    def local(s1, s2n, inf1, inf2, cmag, csgn, rmag, rsgn, ox, oy, gtx, gty):
        allacc = bk.grouped_accumulators(
            sig_fl, s1, s2n, inf1, inf2, cmag, csgn, rmag, rsgn
        )
        if ndp > 1:
            parts = jax.lax.all_gather(allacc, batch_axis)  # leaves [ndp, ..]

            def take(i):
                return jax.tree_util.tree_map(lambda t: t[i], parts)

            allacc = take(0)
            for i in range(1, ndp):
                allacc = cv.jadd(sig_fl, allacc, take(i))
        dead = jnp.any(inf1 | inf2).astype(jnp.int32)
        any_dead = jax.lax.psum(dead, batch_axis) > 0
        return bk.grouped_tail(sig_is_g1, allacc, ox, oy, gtx, gty, any_dead)

    in_specs = (
        P(batch_axis),  # s1 (coordinate pytree, leading [B])
        P(batch_axis),  # s2n
        P(batch_axis),  # inf1
        P(batch_axis),  # inf2
        P(None, batch_axis),  # cmag [q+1, B, nwin]
        P(None, batch_axis),  # csgn
        P(None, batch_axis),  # rmag [1, B, nwin_r]
        P(None, batch_axis),  # rsgn
        P(),  # ox (replicated verkey points)
        P(),  # oy
        P(),  # gtx
        P(),  # gty
    )
    jitted = jax.jit(_shard_map(local, mesh, in_specs, P()))
    _PROGRAM_CACHE[key] = jitted
    return jitted


def batch_verify_grouped_sharded(
    backend, sigs, messages_list, vk, params, mesh, batch_axis="dp",
    pad_batch_to=None,
):
    """dp-sharded attribute-grouped batch verify on a mesh: ONE bool for
    the whole batch, same semantics (and 2^-128 soundness) as
    `JaxBackend.batch_verify_grouped`. The batch is padded to a power of
    two divisible by the dp extent (pad_batch_to, default 2x the dp
    extent; the dryrun passes ndp for the one-lane-per-device minimum);
    per-device slices stay powers of two (fold_points requires it)."""
    require_axes(mesh, batch_axis)
    ndp = mesh.shape[batch_axis]
    if ndp & (ndp - 1):
        raise ValueError("dp extent %d must be a power of two" % ndp)
    if len(sigs) == 0:
        return True
    if any(s.sigma_1 is None or s.sigma_2 is None for s in sigs):
        return False
    operands = backend.encode_grouped_batch(
        sigs, messages_list, vk, params,
        pad_batch_to=2 * ndp if pad_batch_to is None else pad_batch_to,
    )
    fn = make_sharded_grouped_verify(
        mesh, params.ctx.name == "G1", batch_axis
    )
    return bool(fn(*operands))


def batch_verify_grouped_sharded_async(
    backend, sigs, messages_list, vk, params, mesh, batch_axis="dp",
    pad_batch_to=None,
):
    """Pipelined variant of `batch_verify_grouped_sharded`: dispatches the
    sharded grouped program (JAX dispatch is asynchronous) and returns a
    zero-arg finalizer, so `stream.verify_stream` can overlap batch i+1's
    host encode with batch i's mesh execution — config 5 on a mesh."""
    require_axes(mesh, batch_axis)
    ndp = mesh.shape[batch_axis]
    if ndp & (ndp - 1):
        raise ValueError("dp extent %d must be a power of two" % ndp)
    if len(sigs) == 0:
        return lambda: True
    if any(s.sigma_1 is None or s.sigma_2 is None for s in sigs):
        return lambda: False
    operands = backend.encode_grouped_batch(
        sigs, messages_list, vk, params,
        pad_batch_to=2 * ndp if pad_batch_to is None else pad_batch_to,
    )
    fn = make_sharded_grouped_verify(
        mesh, params.ctx.name == "G1", batch_axis
    )
    ok = fn(*operands)
    return lambda: bool(ok)


def make_sharded_show_verify(mesh, sig_is_g1, batch_axis="dp"):
    """dp-sharded batched show-verify (config 3 on a mesh): each device runs
    the fused Schnorr + pairing checks (backend.fused_show_verify) on its
    slice of proofs; bits are per-proof, so no cross-device combine is
    needed — the output stays dp-sharded and gathers on readback."""
    key = ("show", mesh, sig_is_g1, batch_axis)
    cached = _PROGRAM_CACHE.get(key)
    if cached is not None:
        return cached

    def local(*ops):
        return bk.fused_show_verify(sig_is_g1, *ops)

    dp = P(batch_axis)
    in_specs = (
        P(),  # vc_wtables (shared Schnorr bases, replicated)
        dp,  # resp_mag [B, k, nwin]
        dp,  # resp_sgn
        dp,  # jpt (J coordinate pytree, leading [B])
        dp,  # jinf
        dp,  # cmag_j [B, 1, nwin]
        dp,  # csgn_j
        dp,  # commx
        dp,  # commy
        dp,  # comminf
        P(),  # acc_wtables (replicated)
        dp,  # acc_mag
        dp,  # acc_sgn
        dp,  # s1
        dp,  # s2n
        P(),  # gtx
        P(),  # gty
        dp,  # inf1
        dp,  # inf2
    )
    jitted = jax.jit(_shard_map(local, mesh, in_specs, P(batch_axis)))
    _PROGRAM_CACHE[key] = jitted
    return jitted


def batch_show_verify_sharded(
    backend, proofs, vk, params, revealed_msgs_list, challenges, mesh,
    batch_axis="dp",
):
    """dp-sharded batched PoKOfSignatureProof.verify on a mesh: [B] bools,
    bit-identical to `JaxBackend.batch_show_verify` (reference surface
    pok_sig.rs:103-105). The proof batch must divide the dp extent."""
    require_axes(mesh, batch_axis)
    ndp = mesh.shape[batch_axis]
    if len(proofs) % ndp:
        raise ValueError(
            "batch size %d not divisible by %s=%d"
            % (len(proofs), batch_axis, ndp)
        )
    operands = backend.encode_show_verify_batch(
        proofs, vk, params, revealed_msgs_list, challenges
    )
    fn = make_sharded_show_verify(
        mesh, params.ctx.name == "G1", batch_axis
    )
    bits = fn(*operands)
    return [bool(b) for b in np.asarray(bits)]


def pad_to_multiple(k, n):
    return ((k + n - 1) // n) * n


class _IdentityLane:
    """Identity-signature pad lane (`sigma_1 is None`): verifies False by
    the reference rule (signature.rs:472-478) and encodes as the point at
    infinity, so a pad lane can never flip a real lane's verdict — the
    same identity-lane convention serve/batcher.PAD_CREDENTIAL and
    `encode_verify_batch(pad_bases_to=...)` use."""

    __slots__ = ()
    sigma_1 = None
    sigma_2 = None


PAD_LANE = _IdentityLane()


def batch_verify_sharded_async(
    backend, sigs, messages_list, vk, params, mesh, batch_axis="dp",
    msm_axis="tp",
):
    """Pipelined variant of `batch_verify_sharded` ([B] bools, the
    reference's per-credential verdict semantics, signature.rs:472-478):
    dispatches the sharded fused program and returns a zero-arg finalizer
    so `stream.verify_stream(mode='per_credential', mesh=...)` can keep
    the mesh busy across the readback round trip.

    The final batch of a stream rarely divides the dp extent; it is padded
    with IDENTITY lanes up to the next multiple (ADVICE r5 #1 — matching
    the grouped mesh path's identity-lane encode convention rather than
    duplicating a real credential) and the verdict bits are sliced back to
    the true length, so callers never see the padding (identity lanes
    verify False; verdicts are per-lane, so pad lanes cannot affect real
    ones)."""
    require_axes(mesh, batch_axis, msm_axis)
    ndp = mesh.shape[batch_axis]
    ntp = mesh.shape[msm_axis]  # the sharded program requires both axes
    B = len(sigs)
    if B == 0:
        return lambda: []
    pad = (-B) % ndp
    if pad:
        sigs = list(sigs) + [PAD_LANE] * pad
        messages_list = list(messages_list) + [messages_list[-1]] * pad
    k = 1 + len(vk.Y_tilde)
    operands = backend.encode_verify_batch(
        sigs, messages_list, vk, params, pad_bases_to=pad_to_multiple(k, ntp)
    )
    fn = make_sharded_verify(mesh, params.ctx.name == "G1", batch_axis, msm_axis)
    bits = fn(*operands)
    return lambda: [bool(b) for b in np.asarray(bits)[:B]]


# --- sharded issuance (config 4 on a mesh) ----------------------------------


def make_sharded_distinct(mesh, is_fp2, with_offset, batch_axis="dp"):
    """dp-sharded distinct-base MSM program (the issuance/show shape:
    per-credential bases, on-device tables — backend's
    _msm_distinct_affine_kernel / _msm_distinct_plus_offset_kernel).
    Every operand leads with the batch axis, so the spec is a plain dp
    shard per leaf; outputs stay dp-sharded and gather on readback."""
    key = ("distinct", mesh, is_fp2, with_offset, batch_axis)
    cached = _PROGRAM_CACHE.get(key)
    if cached is not None:
        return cached
    fl = cv.FP2 if is_fp2 else cv.FP

    def local(x, y, inf, mag, sgn, *offset):
        x, y = bk._pts_f32((x, y))
        acc = cv.msm_distinct_signed(fl, x, y, inf, mag, sgn)
        if offset:
            ox, oy, oinf = offset
            ox, oy = bk._unpack_pt(ox, oy)
            off = cv.affine_to_jacobian(fl, ox, oy, oinf)
            acc = cv.jadd(fl, acc, off)
        ax, ay, ainf = cv.to_affine(fl, acc)
        return (*bk._pack_pt(ax, ay), ainf)

    dp = P(batch_axis)
    nargs = 8 if with_offset else 5
    jitted = jax.jit(
        _shard_map(local, mesh, (dp,) * nargs, (dp, dp, dp))
    )
    _PROGRAM_CACHE[key] = jitted
    return jitted


def make_sharded_shared_many(mesh, is_fp2, njobs, batch_axis="dp"):
    """dp-sharded multi-job shared-base comb MSM (the prepare phase's
    fused program, backend._msm_shared_many_kernel): comb tables are
    replicated (fixed bases), digit arrays shard over the batch axis."""
    key = ("shared_many", mesh, is_fp2, njobs, batch_axis)
    cached = _PROGRAM_CACHE.get(key)
    if cached is not None:
        return cached
    fl = cv.FP2 if is_fp2 else cv.FP
    dp = P(batch_axis)

    def local(jobs):
        outs = []
        for wt, mag, sgn in jobs:
            x, y, inf = cv.to_affine(fl, cv.msm_shared_comb(fl, wt, mag, sgn))
            outs.append((*bk._pack_pt(x, y), inf))
        return tuple(outs)

    in_specs = (tuple((P(), dp, dp) for _ in range(njobs)),)
    out_specs = tuple((dp, dp, dp) for _ in range(njobs))
    jitted = jax.jit(_shard_map(local, mesh, in_specs, out_specs))
    _PROGRAM_CACHE[key] = jitted
    return jitted


class ShardedIssuanceBackend(bk.JaxBackend):
    """JaxBackend with the issuance-shape MSM programs dp-sharded over a
    mesh, so the protocol drivers — `signature.batch_prepare_blind_sign`,
    `signature.batch_blind_sign`, `signature.batch_unblind`,
    `pok_sig.batch_show` — run unchanged with each device computing its
    slice of the credential batch (config 4 multi-chip; reference surface
    signature.rs:124-207, 380-433). Verify-side entry points inherit the
    sharded variants' superclass behavior (single-device); use the
    dedicated `batch_verify_*_sharded` drivers for those.

    Batch sizes must divide the dp extent (the prepare driver's row
    counts are B and B*hidden, so B must be a multiple of ndp and the
    hidden count is unconstrained)."""

    name = "jax_sharded_issuance"

    def __init__(self, mesh, batch_axis="dp"):
        require_axes(mesh, batch_axis)
        self.mesh = mesh
        self.batch_axis = batch_axis

    def _check_rows(self, n):
        ndp = self.mesh.shape[self.batch_axis]
        if n % ndp:
            raise ValueError(
                "row count %d not divisible by %s=%d"
                % (n, self.batch_axis, ndp)
            )

    def _msm_distinct(self, is_fp2, points_batch, scalars_batch):
        ops = self._encode_distinct(is_fp2, points_batch, scalars_batch)
        self._check_rows(ops[2].shape[0])
        fn = make_sharded_distinct(self.mesh, is_fp2, False, self.batch_axis)
        return fn(*ops)

    def _msm_distinct_plus_offset(
        self, is_fp2, points_batch, scalars_batch, offset_handle
    ):
        ops = self._encode_distinct(is_fp2, points_batch, scalars_batch)
        self._check_rows(ops[2].shape[0])
        fn = make_sharded_distinct(self.mesh, is_fp2, True, self.batch_axis)
        return fn(*ops, *offset_handle)

    def _msm_shared_many_dispatch(self, spec_ops, is_fp2, jobs):
        operands = []
        for bases, scalars_batch in jobs:
            wt = bk._comb_tables(spec_ops, is_fp2, bases)
            mag, sgn = bk._comb_digits(scalars_batch)
            self._check_rows(mag.shape[0])
            operands.append((wt, mag, sgn))
        fn = make_sharded_shared_many(
            self.mesh, is_fp2, len(jobs), self.batch_axis
        )
        return fn(tuple(operands))


def batch_verify_sharded(
    backend, sigs, messages_list, vk, params, mesh, batch_axis="dp", msm_axis="tp"
):
    """Data+tensor-parallel batch verify on a mesh: [B] bools, bit-identical
    to `JaxBackend.batch_verify` / the Python spec path."""
    return batch_verify_sharded_async(
        backend, sigs, messages_list, vk, params, mesh, batch_axis, msm_axis
    )()


def default_mesh(ndp=None, ntp=1, devices=None):
    """A (dp, tp) mesh over the available devices (dp fills what tp leaves)."""
    devices = list(jax.devices()) if devices is None else list(devices)
    n = len(devices)
    if ndp is None:
        ndp = n // ntp
    if ndp * ntp != n:
        raise ValueError("mesh %dx%d != %d devices" % (ndp, ntp, n))
    arr = np.array(devices).reshape(ndp, ntp)
    return Mesh(arr, ("dp", "tp"))
