"""Multi-chip sharded batch verification over a (dp, tp) device mesh.

The TPU-native answer to SURVEY.md §2.3's parallelism table:

  - **dp** (data parallelism): the credential batch is sharded over the mesh's
    ``dp`` axis — each device verifies its slice independently. This is the
    primary axis; the workload (one pairing check per credential, reference
    signature.rs:472-478) is embarrassingly data-parallel.
  - **tp** (tensor parallelism / sharded MSM): the shared-base MSM inside each
    verification (the X̃·∏Ỹⱼ^{mⱼ} accumulator, SURVEY.md §3.4) is sharded
    over the ``tp`` axis by *base index*: each device computes a partial MSM
    over its subset of bases, partials are combined with an
    ``all_gather`` + Jacobian-add tree inside ``shard_map`` (point addition is
    not a ring sum, so ``psum`` does not apply — the combine rides the same
    ICI links), and every device then runs the pairing tail on its dp-slice.

Collectives ride ICI via XLA (`all_gather` over the tp axis); nothing here
depends on device count — the same program runs on a v5e-8 mesh or the
8-device virtual CPU mesh the tests use (conftest.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.4.35 exposes shard_map at top level
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

from . import backend as bk
from . import curve as cv


_PROGRAM_CACHE = {}


def make_sharded_verify(mesh, sig_is_g1, batch_axis="dp", msm_axis="tp"):
    """Build the jitted shard_map'd fused-verify program for `mesh`.

    Operands are the same tuple `JaxBackend.encode_verify_batch` produces,
    with the base axis padded to a multiple of the tp axis size and the batch
    divisible by the dp axis size. Returns bits [B] (fully replicated gather
    of the dp shards).

    Programs are memoized per (mesh, flavor, axes): a fresh closure + jit
    per call would defeat jit's function-identity cache and re-pay the
    multi-minute fused compile on every batch of a streamed run."""
    key = (mesh, sig_is_g1, batch_axis, msm_axis)
    cached = _PROGRAM_CACHE.get(key)
    if cached is not None:
        return cached
    ntp = mesh.shape[msm_axis]
    acc_fl = cv.FP2 if sig_is_g1 else cv.FP

    def local(tables, digits, s1, s2n, gtx, gty, inf1, inf2):
        # tables: leading [k/ntp, 16]; digits: [B/ndp, k/ntp, nwin]
        acc = cv.msm_shared(acc_fl, tables, digits)
        if ntp > 1:
            parts = jax.lax.all_gather(acc, msm_axis)  # leaves [ntp, ...]

            def take(i):
                return jax.tree_util.tree_map(lambda t: t[i], parts)

            acc = take(0)
            for i in range(1, ntp):
                acc = cv.jadd(acc_fl, acc, take(i))
        return bk.verify_tail(sig_is_g1, acc, s1, s2n, gtx, gty, inf1, inf2)

    in_specs = (
        P(msm_axis),  # tables: bases sharded
        P(batch_axis, msm_axis),  # digits: batch x bases
        P(batch_axis),  # s1
        P(batch_axis),  # s2n
        P(),  # gtx (replicated constant)
        P(),  # gty
        P(batch_axis),  # inf1
        P(batch_axis),  # inf2
    )
    # check_vma=False: the Miller/MSM scans initialize carries from
    # replicated constants (identity points, GT one) that become
    # mesh-varying inside the loop — sound here (outputs are asserted
    # bit-identical to the spec path), but the static vma type check
    # rejects it. Older jax spells the kwarg check_rep.
    try:
        fn = shard_map(
            local,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=P(batch_axis),
            check_vma=False,
        )
    except TypeError:  # pragma: no cover - jax < 0.4.35 spelling
        fn = shard_map(
            local,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=P(batch_axis),
            check_rep=False,
        )
    jitted = jax.jit(fn)
    _PROGRAM_CACHE[key] = jitted
    return jitted


def pad_to_multiple(k, n):
    return ((k + n - 1) // n) * n


def batch_verify_sharded(
    backend, sigs, messages_list, vk, params, mesh, batch_axis="dp", msm_axis="tp"
):
    """Data+tensor-parallel batch verify on a mesh: [B] bools, bit-identical
    to `JaxBackend.batch_verify` / the Python spec path."""
    ndp = mesh.shape[batch_axis]
    ntp = mesh.shape[msm_axis]
    if len(sigs) % ndp:
        raise ValueError(
            "batch size %d not divisible by %s=%d" % (len(sigs), batch_axis, ndp)
        )
    k = 1 + len(vk.Y_tilde)
    operands = backend.encode_verify_batch(
        sigs, messages_list, vk, params, pad_bases_to=pad_to_multiple(k, ntp)
    )
    fn = make_sharded_verify(mesh, params.ctx.name == "G1", batch_axis, msm_axis)
    bits = fn(*operands)
    return [bool(b) for b in np.asarray(bits)]


def default_mesh(ndp=None, ntp=1, devices=None):
    """A (dp, tp) mesh over the available devices (dp fills what tp leaves)."""
    devices = list(jax.devices()) if devices is None else list(devices)
    n = len(devices)
    if ndp is None:
        ndp = n // ntp
    if ndp * ntp != n:
        raise ValueError("mesh %dx%d != %d devices" % (ndp, ntp, n))
    arr = np.array(devices).reshape(ndp, ntp)
    return Mesh(arr, ("dp", "tp"))
