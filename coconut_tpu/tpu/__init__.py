"""JAX/TPU execution layer.

Everything under this package is the TPU-native equivalent of the reference's
`amcl_wrapper` curve layer (SURVEY.md §2.2) re-designed for XLA: 381-bit base
field elements are decomposed into 52 x 8-bit lazy signed limbs in float32,
limb products run as bf16 matmuls with exact f32 accumulation ON THE MXU
(see tpu/limbs.py for why this representation), every operation is natively
batched over leading array dimensions, control flow is `lax.scan` over the
static BLS parameter bits, and the whole credential-verification hot path
(reference signature.rs:472-478) compiles to one fused XLA program per batch
shape. No 64-bit lane support is required — everything is f32/bf16/int32.
"""
