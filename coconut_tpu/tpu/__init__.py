"""JAX/TPU execution layer.

Everything under this package is the TPU-native equivalent of the reference's
`amcl_wrapper` curve layer (SURVEY.md §2.2) re-designed for XLA: 381-bit base
field elements are decomposed into 24 x 16-bit limbs held in uint64 lanes,
every operation is natively batched over leading array dimensions, control
flow is `lax.scan` over the static BLS parameter bits, and the whole
credential-verification hot path (reference signature.rs:472-478) compiles to
one fused XLA program per batch shape.

Requires 64-bit lane support (uint64 accumulators for the 16x16-bit limb
products); enabled here before any tracing.
"""

import jax

jax.config.update("jax_enable_x64", True)
