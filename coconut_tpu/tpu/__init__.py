"""JAX/TPU execution layer.

Everything under this package is the TPU-native equivalent of the reference's
`amcl_wrapper` curve layer (SURVEY.md §2.2) re-designed for XLA: 381-bit base
field elements are decomposed into 52 x 8-bit lazy signed limbs in float32,
limb products run as bf16 matmuls with exact f32 accumulation ON THE MXU
(see tpu/limbs.py for why this representation), every operation is natively
batched over leading array dimensions, control flow is `lax.scan` over the
static BLS parameter bits, and the whole credential-verification hot path
(reference signature.rs:472-478) compiles to one fused XLA program per batch
shape. No 64-bit lane support is required — everything is f32/bf16/int32.
"""

import os as _os


def enable_compile_cache():
    """Point jax at the repo's persistent compile cache (.jax_cache).

    The fused/sharded programs take minutes to compile cold on a 1-core
    host. ONE definition, shared by tests/conftest.py, bench.py, and
    __graft_entry__ — round 3's driver MULTICHIP timeout happened because
    the three call sites were hand-copied and one copy was missing
    (VERDICT r3 item 1). JAX_CACHE_DIR overrides the location."""
    import jax

    jax.config.update(
        "jax_compilation_cache_dir",
        _os.environ.get(
            "JAX_CACHE_DIR",
            _os.path.join(
                _os.path.dirname(
                    _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
                ),
                ".jax_cache",
            ),
        ),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
