"""Batched optimal-ate pairing: multi-Miller loop + final exponentiation.

Blueprint: `ops.pairing.miller_loop_projective` / `final_exp_chain` — the
same homogeneous twist coordinates, line coefficients, and x-power chain, so
post-final-exp GT values decode bit-identical to the spec (the line scalings
lie in the Fp4 subfield and are killed by the final exponentiation; spec
pairing.py docstring).

Shapes: a "pair set" has G1 points [..., ] and twist points as Fp2 pytrees
with the same leading dims; the Miller scan runs over the static |BLS_X| bit
schedule (lax.scan, select for the 6 sparse addition steps). Identity inputs
are handled with validity masks exactly like the spec's `None` convention
(miller factor = 1).
"""

import jax.numpy as jnp
from jax import lax

from ..ops.fields import BLS_X
from . import fp
from . import tower as tw

# Static bit schedule of |BLS_X|, msb first, leading bit dropped.
_XBITS = jnp.array([int(b) for b in bin(-BLS_X)[2:]][1:], dtype=jnp.int32)

# Segment decomposition of the same schedule for the Miller loop: |BLS_X|
# has only 5 set bits after the leading one, so instead of computing the
# addition step on every iteration and select-masking it away (the r2
# design: ~58 of 63 add steps + line muls thrown away), run scans of pure
# doubling steps between the STATIC set-bit positions and unroll the 5
# double+add steps. _SEG_ZEROS[i] = number of pure-double steps before the
# i-th set bit; _TRAILING = pure-double steps after the last set bit.
_SEG_ZEROS, _TRAILING = [], 0
for _b in [int(b) for b in bin(-BLS_X)[2:]][1:]:
    if _b:
        _SEG_ZEROS.append(_TRAILING)
        _TRAILING = 0
    else:
        _TRAILING += 1


def _proj_double_step(T):
    """Mirror of ops.pairing.proj_double_step on Fp2 limb pytrees."""
    X, Y, Z = T
    A = tw.fp2_sq(X)
    B = tw.fp2_sq(Y)
    C = tw.fp2_sq(Z)
    D = tw.fp2_mul(tw.fp2_mul(X, B), Z)
    F = tw.fp2_sub(tw.fp2_mul_small(tw.fp2_sq(A), 9), tw.fp2_mul_small(D, 8))
    YZ = tw.fp2_mul(Y, Z)
    X3 = tw.fp2_mul(tw.fp2_mul_small(YZ, 2), F)
    Y3 = tw.fp2_sub(
        tw.fp2_mul(tw.fp2_mul_small(A, 3), tw.fp2_sub(tw.fp2_mul_small(D, 4), F)),
        tw.fp2_mul_small(tw.fp2_mul(tw.fp2_sq(B), C), 8),
    )
    t = tw.fp2_mul_small(YZ, 2)
    Z3 = tw.fp2_mul(tw.fp2_sq(t), t)
    lA = tw.fp2_sub(
        tw.fp2_mul(X, A), tw.fp2_mul_small(tw.fp2_mul_xi(tw.fp2_mul(Z, C)), 8)
    )
    lB = tw.fp2_neg(tw.fp2_mul_small(tw.fp2_mul(A, Z), 3))
    lC = tw.fp2_mul_small(tw.fp2_mul(Y, C), 2)
    return (X3, Y3, Z3), (lA, lB, lC)


def _proj_add_step(T, q):
    """Mirror of ops.pairing.proj_add_step; q = (x2, y2) affine twist."""
    X, Y, Z = T
    x2, y2 = q
    theta = tw.fp2_sub(Y, tw.fp2_mul(y2, Z))
    lam = tw.fp2_sub(X, tw.fp2_mul(x2, Z))
    lam2 = tw.fp2_sq(lam)
    lam3 = tw.fp2_mul(lam2, lam)
    H = tw.fp2_sub(
        tw.fp2_mul(tw.fp2_sq(theta), Z),
        tw.fp2_mul(lam2, tw.fp2_add(X, tw.fp2_mul(x2, Z))),
    )
    X3 = tw.fp2_mul(lam, H)
    Y3 = tw.fp2_sub(
        tw.fp2_mul(theta, tw.fp2_sub(tw.fp2_mul(lam2, X), H)),
        tw.fp2_mul(lam3, Y),
    )
    Z3 = tw.fp2_mul(lam3, Z)
    lA = tw.fp2_sub(tw.fp2_mul(theta, x2), tw.fp2_mul(lam, y2))
    lB = tw.fp2_neg(theta)
    lC = lam
    return (X3, Y3, Z3), (lA, lB, lC)


def _eval_line(line, px, py):
    """(lA, lB, lC) -> (lA, lB*px, lC*py): the sparse element for mul_line."""
    lA, lB, lC = line
    return (lA, tw.fp2_mul_fp(lB, px), tw.fp2_mul_fp(lC, py))


def multi_miller_loop(px, py, qx, qy, valid):
    """Product of Miller loops over the trailing "pairs" axis folded into the
    leading batch dims.

    px, py: Fp limb arrays [...]; qx, qy: Fp2 pytrees (affine twist);
    valid: bool [...] — False lanes contribute the factor 1 (the spec's
    `None` -> FP12_ONE convention).
    Returns an Fp12 pytree with the same leading dims [...].

    PAD-LANE CONTRACT (pinned by tests/test_ops.py's pad-lane
    regressions; the RLC batch verifier of PR 16 leans on it): a lane
    with valid=False contributes EXACTLY the GT identity to the product
    — every one of its line evaluations is masked to (1, 0, 0) inside
    _mask_line, so its point coordinates may be garbage (zeros,
    off-curve, aliased) without perturbing the other lanes. All-pad pair
    sets therefore fold to FP12_ONE, and ragged batches padded with
    valid=0 lanes return bit-identical products to their unpadded
    prefix, regardless of where the pad lanes sit (trailing or
    interleaved)."""
    shape = valid.shape
    T0 = (qx, qy, tw.fp2_ones(shape))
    f0 = tw.fp12_ones(shape)

    def dbl_body(carry, _):
        f, T = carry
        T, line = _proj_double_step(T)
        f = tw.mul_line(tw.fp12_sq(f), _eval_line(line, px, py))
        return (f, T), None

    carry = (f0, T0)
    for nz in _SEG_ZEROS:
        if nz:
            carry, _ = lax.scan(dbl_body, carry, None, length=nz)
        # the set-bit step, unrolled: double + add, no masks
        (carry, _) = dbl_body(carry, None)
        f, T = carry
        T, la = _proj_add_step(T, (qx, qy))
        f = tw.mul_line(f, _eval_line(la, px, py))
        carry = (f, T)
    if _TRAILING:
        carry, _ = lax.scan(dbl_body, carry, None, length=_TRAILING)
    f, _ = carry
    f = tw.fp12_conj(f)  # x < 0
    f = tw.fp12_select(valid, f, tw.fp12_ones(shape))
    # fold the pairs axis (last leading dim) by multiplication
    npairs = shape[-1]
    out = _index_fp12(f, 0)
    for i in range(1, npairs):
        out = tw.fp12_mul(out, _index_fp12(f, i))
    return out


def _index_fp12(f, i):
    import jax

    return jax.tree_util.tree_map(lambda t: t[..., i, :], f)


def _mask_line(line, valid):
    """Select the identity line (1, 0, 0) on invalid lanes so a dead pair
    contributes the factor 1 to the merged accumulator (the generic loop's
    post-hoc fp12 select, pushed down to the sparse element). This is the
    mechanism behind multi_miller_loop's pad-lane contract: masking every
    LINE (rather than the final fp12) keeps a valid=0 lane's garbage
    coordinates out of the product at every step, not just at the end."""
    lA, lB, lC = line
    one = tw.fp2_ones(valid.shape)
    zero = tw.fp2_zeros(valid.shape)
    return (
        tw.fp2_select(valid, lA, one),
        tw.fp2_select(valid, lB, zero),
        tw.fp2_select(valid, lC, zero),
    )


def miller_two_pairs_shared_q2(
    px1, py1, qx1, qy1, valid1, px2, py2, q2x, q2y, valid2
):
    """Miller product of exactly two pairs per credential with pair 2's
    TWIST point shared across the batch — the verify shape
    e(sigma_1_i, acc_i) * e(-sigma_2_i, g_tilde) in the G1 assignment.

    Two structural wins over the generic [B, 2] pair-set loop:
      - the fp12 accumulator is [B]-shaped (one per credential, both
        pairs' lines multiplied in per step) instead of [B, 2] + final
        fold — halving the dominant fp12_sq/mul_line work;
      - pair 2's T-ladder and line COEFFICIENTS run once at scalar shape
        (g_tilde is one point); only the two line evaluations at
        (px2_i, py2_i) are per-credential.
    Dead pairs contribute the factor 1 via line masking (_mask_line)."""
    shape = valid1.shape
    T1 = (qx1, qy1, tw.fp2_ones(shape))
    T2 = (q2x, q2y, tw.fp2_ones(()))
    f0 = tw.fp12_ones(shape)

    def fuse(f, l1, l2):
        f = tw.mul_line(f, _mask_line(_eval_line(l1, px1, py1), valid1))
        return tw.mul_line(f, _mask_line(_eval_line(l2, px2, py2), valid2))

    def dbl_body(carry, _):
        f, T1, T2 = carry
        T1, l1 = _proj_double_step(T1)
        T2, l2 = _proj_double_step(T2)
        f = fuse(tw.fp12_sq(f), l1, l2)
        return (f, T1, T2), None

    carry = (f0, T1, T2)
    for nz in _SEG_ZEROS:
        if nz:
            carry, _ = lax.scan(dbl_body, carry, None, length=nz)
        carry, _ = dbl_body(carry, None)
        f, T1, T2 = carry
        T1, l1 = _proj_add_step(T1, (qx1, qy1))
        T2, l2 = _proj_add_step(T2, (q2x, q2y))
        carry = (fuse(f, l1, l2), T1, T2)
    if _TRAILING:
        carry, _ = lax.scan(dbl_body, carry, None, length=_TRAILING)
    return tw.fp12_conj(carry[0])  # x < 0


def _pow_x_abs(m):
    """m^{|BLS_X|} in the cyclotomic subgroup (scan over the static bits).
    Squarings use the Granger-Scott cyclotomic form (tw.fp12_cyclo_sq,
    30 base lanes vs fp12_sq's 36) — sound because every value in the
    chain is a power of the cyclotomic input."""

    def body(acc, bit):
        acc = tw.fp12_cyclo_sq(acc)
        accm = tw.fp12_mul(acc, m)
        acc = tw.fp12_select(
            jnp.broadcast_to(bit == 1, _leading(acc)), accm, acc
        )
        return acc, None

    acc, _ = lax.scan(body, m, _XBITS)  # leading bit folds in via init = m
    return acc


def _leading(f):
    return f[0][0][0].shape[:-1]


def _pow_x_neg(m):
    """m^{BLS_X} (x negative): conj of m^{|x|}."""
    return tw.fp12_conj(_pow_x_abs(m))


def final_exp(f):
    """Mirror of ops.pairing.final_exp_chain (identical GT values)."""
    m = tw.fp12_mul(tw.fp12_conj(f), tw.fp12_inv(f))
    m = tw.fp12_mul(tw.fp12_frobenius2(m), m)
    t0 = tw.fp12_mul(_pow_x_neg(m), tw.fp12_conj(m))
    t1 = tw.fp12_mul(_pow_x_neg(t0), tw.fp12_conj(t0))
    t2 = tw.fp12_mul(_pow_x_neg(t1), tw.fp12_frobenius(t1))
    t3 = tw.fp12_mul(
        tw.fp12_mul(_pow_x_neg(_pow_x_neg(t2)), tw.fp12_frobenius2(t2)),
        tw.fp12_conj(t2),
    )
    return tw.fp12_mul(t3, tw.fp12_mul(tw.fp12_cyclo_sq(m), m))


def pairing_product_is_one(px, py, qx, qy, valid):
    """[..., npairs] pair sets -> bool [...]: prod e(P_i, Q_i) == 1.

    Inherits multi_miller_loop's pad-lane contract: valid=0 pairs are
    identity factors, so an all-pad set answers True (empty product) and
    pad lanes never change a batch's verdict — the invariant the PR-16
    combined verifier's clone-first power-of-two padding relies on."""
    f = multi_miller_loop(px, py, qx, qy, valid)
    return tw.fp12_is_one(final_exp(f))
