"""Exponential ElGamal over an arbitrary group.

Replaces the reference's three declarative macros (elgamal.rs:1-28) with
plain functions parameterized by `CurveOps`. Encrypt returns the randomness
`k` because the issuance PoK proves knowledge of it (signature.rs:175-178)."""

from .sss import rand_fr


def elgamal_keygen(ops, base):
    """(sk, base^sk) — elgamal.rs:1-9."""
    sk = rand_fr()
    return sk, ops.mul(base, sk)


def elgamal_encrypt(ops, base, pk, msg_point):
    """(base^k, pk^k * msg, k) — elgamal.rs:11-20."""
    k = rand_fr()
    c1 = ops.mul(base, k)
    c2 = ops.add(ops.mul(pk, k), msg_point)
    return c1, c2, k


def elgamal_decrypt(ops, c1, c2, sk):
    """c2 - c1^sk — elgamal.rs:22-28."""
    return ops.sub(c2, ops.mul(c1, sk))
