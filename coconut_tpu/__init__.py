"""coconut_tpu — TPU-native framework for Coconut threshold-issuance
selective-disclosure anonymous credentials over BLS12-381.

Capability surface mirrors the reference (3for/coconut-rust, see SURVEY.md):
setup, threshold keygen (Shamir / Pedersen-VSS / dealerless Pedersen-DVSS),
blind signature requests with Schnorr PoKs, blind signing / unblinding,
Lagrange aggregation of signatures and verkeys, PS verification, and
selective-disclosure proof of knowledge of a credential. The data-parallel
hot paths (batched MSM + pairing-product checks) route through a
`CurveBackend` seam onto JAX/TPU.
"""

__version__ = "0.1.0"
