"""coconut_tpu — TPU-native framework for Coconut threshold-issuance
selective-disclosure anonymous credentials over BLS12-381.

Capability surface mirrors the reference (3for/coconut-rust, see SURVEY.md):
setup, threshold keygen (Shamir / Pedersen-VSS / dealerless Pedersen-DVSS),
blind signature requests with Schnorr PoKs, blind signing / unblinding,
Lagrange aggregation of signatures and verkeys, PS verification, and
selective-disclosure proof of knowledge of a credential. The data-parallel
hot paths (batched MSM + pairing-product checks) route through a
`CurveBackend` seam onto JAX/TPU.

The canonical 8-step flow (reference README.md:8-172):

    from coconut_tpu import *

    params = Params.new(msg_count=6, label=b"my-app")           # 1. Setup
    sx, sy, signers = trusted_party_SSS_keygen(3, 5, params)    # 2. Keygen
    elg_sk, elg_pk = elgamal_keygen(params.ctx.sig, params.g)   # 3. User keys
    req, rand = SignatureRequest.new(msgs, 2, elg_pk, params)   # 4. Request
    pok = SignatureRequestPoK.init(req, elg_pk, params)         #    + PoK
    c = fiat_shamir_challenge(pok.to_bytes())
    proof = pok.gen_proof(msgs[:2], rand, elg_sk, c)
    # each signer: proof.verify(...) then                       # 5. BlindSign
    bsig = BlindSignature.new(req, signer.sigkey, params)
    sig = bsig.unblind(elg_sk, params.ctx)                      # 6. Unblind
    aggr = Signature.aggregate(3, [(id, sig), ...])             # 7. AggCred
    vk = Verkey.aggregate(3, [(id, vk_i), ...])                 #    AggKey
    aggr.verify(msgs, vk, params)                               # 8. Verify
    show(aggr, vk, params, msgs, {3, 5})                        #    Show
"""

from .elgamal import elgamal_decrypt, elgamal_encrypt, elgamal_keygen  # noqa
from .errors import (  # noqa
    CoconutError,
    DeserializationError,
    GeneralError,
    PSError,
    UnequalNoOfBasesExponents,
    UnsupportedNoOfMessages,
)
from .keygen import (  # noqa
    Signer,
    dvss_keygen,
    keygen_from_shares,
    trusted_party_PVSS_keygen,
    trusted_party_SSS_keygen,
)
from .params import (  # noqa
    DEFAULT_CTX,
    SIGNATURES_IN_G1,
    SIGNATURES_IN_G2,
    GroupContext,
    Params,
)
from .pok_sig import PoKOfSignature, PoKOfSignatureProof, show, show_verify  # noqa
from .ps import batch_show_verify, batch_verify, ps_verify  # noqa
from .signature import (  # noqa
    BlindSignature,
    Sigkey,
    Signature,
    SignatureRequest,
    SignatureRequestPoK,
    SignatureRequestProof,
    Verkey,
    batch_blind_sign,
    batch_unblind,
    fiat_shamir_challenge,
)
from .sss import (  # noqa
    PedersenDVSSParticipant,
    PedersenVSS,
    get_shared_secret,
    lagrange_basis_at_0,
    reconstruct_secret,
    share_secret_dvss,
)

__version__ = "0.1.0"
