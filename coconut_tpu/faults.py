"""Deterministic fault injection + the dead-letter sink.

Two pieces of the stream supervision layer that live OUTSIDE the happy
path:

  - `FaultyBackend` wraps any verify-capable backend and injects scheduled
    faults at exactly the seam `stream.verify_stream` dispatches through:
    raise-on-Nth-dispatch transient errors, flipped verdicts, corrupted
    (raising) finalizers, executor-loop crashes, and hung dispatches.
    Schedules are index-based and fully deterministic, so
    tests/test_faults.py proves the retry / fallback / bisection paths —
    and tests/test_serve.py the self-healing pool — without flaky
    randomness.

    Crash injection (`crash_on`): the matching dispatch raises
    `InjectedCrash`, a BaseException — it deliberately ESCAPES the
    per-batch `except Exception` containment in serve._launch/_settle,
    exactly the way a real code bug in the dispatch path would, and lands
    in the executor loop's crash handler (quarantine + redistribution).

    Hang injection (`hang_on` / `hang_every`): the matching dispatch
    BLOCKS on a threading.Event (`hang_release`) instead of returning —
    the failure mode retry ladders cannot see and only the serve
    watchdog can break. Deterministic and sleep-free: `hang_entered` is
    set the moment a dispatch starts hanging (the test's sync point), the
    test advances its fake clock, ticks the watchdog, then sets
    `hang_release`; `hang_max_s` bounds an un-released hang so a buggy
    test can never wedge the suite.

    Latency injection (the serving layer's deadline-flush and timeout
    tests need SLOW dispatches, not just failed ones): `delay_every=N` /
    `delay_on={i, ...}` schedule a `sleep(delay_s)` immediately before the
    inner backend runs on the matching 0-based dispatch indices — the same
    global counter the fault schedules use, so "the 3rd dispatch is slow"
    is exactly reproducible. `sleep` is injectable (default `time.sleep`):
    tests pass a recording fake so deadline/timeout behavior is proven
    without wall-clock flakiness — the schedule stays deterministic either
    way.

  - `DeadLetterLog` is the append-only JSONL file that receives culprit
    credentials isolated by grouped-failure bisection: one object per
    line with the batch index, the credential's index within the batch,
    a reason, and the batch's retry attempt history. JSONL so a ledger
    operator can grep/stream it without loading a document; ci.sh greps
    the schema as a smoke check. BOUNDED: the file rotates
    (`<path>.1`, `.2`, ..., keep-N — obs/flight.rotate_if_needed) at a
    size or record-count cap, so a sustained fault storm cannot fill the
    disk; the flight-recorder sidecar is capped the same way.

    Schema v2 (request-scoped tracing): entries carry `trace_id` /
    `span_id` so a dead-letter line joins back to its span tree (the
    serve path passes the CULPRIT request's trace_id; the offline stream
    defaults both to the active bisection span). Both are null with
    tracing disabled, and v1 files (no trace fields) read back with the
    fields normalized to null — old logs stay parseable. Each append
    also triggers the flight recorder (obs/flight.py): the failing
    trace's span tree plus the recent-span tail land in
    `<path>.flight.jsonl` next to this log.
"""

import json
import os
import threading
import time

from . import metrics
from .errors import TransientBackendError
from .obs import flight as _flight
from .obs import trace as otrace

#: dead-letter JSONL schema: v2 added trace_id/span_id (absent -> null);
#: v3 adds the engine program name (absent -> null) so one shared-pool
#: dead-letter file stays attributable per phase; v4 adds `nullifier`
#: (absent -> null) so a show-verify double-spend rejection carries the
#: replicated-state fact that condemned it (coconut_tpu/state)
DEAD_LETTER_SCHEMA = 4


class InjectedCrash(BaseException):
    """Deterministic executor-loop crash injection. Derives from
    BaseException ON PURPOSE: the serve layer's per-batch containment
    (`except Exception` in _launch/_settle) must NOT catch it — it
    escapes to the executor loop's crash handler, modeling a genuine code
    bug in the dispatch path rather than a batch-level backend fault."""


class SimulatedCrash(Exception):
    """A process kill simulated at a named durability seam (PR 17).

    Raised by `WalChaos.crash(point)` inside the WAL/StateStore write
    paths. Unlike `InjectedCrash` this IS a plain Exception: the
    crash-point enumeration harness (tests/test_state.py) catches it at
    the call site, abandons the store object mid-operation exactly as a
    SIGKILL would abandon the process, and re-opens the directory to
    prove replay converges."""


class WalChaos:
    """Deterministic fault schedule for the durable state plane
    (state/wal.py, state/store.py).

      crash_at       — named crash points ("wal.pre_append",
                       "wal.post_append", "store.mid_snapshot",
                       "store.mid_compact") at which `crash()` raises
                       SimulatedCrash; each fires every time it is hit,
                       so remove the point (or swap the chaos object)
                       before re-driving a recovered store;
      torn_on        — 0-based WAL append indices that write only a
                       PREFIX of the frame (fsync'd, so the torn bytes
                       really land on disk) then raise — the
                       mid-record kill, counted in `torn_writes`;
      fsync_fail_on  — 0-based WAL fsync indices that raise OSError
                       instead of syncing (a dying disk).

    All schedules are index-based and deterministic, the same
    discipline as FaultyBackend's dispatch schedules."""

    def __init__(self, crash_at=(), torn_on=(), fsync_fail_on=()):
        self.crash_at = set(crash_at)
        self.torn_on = set(torn_on)
        self.fsync_fail_on = set(fsync_fail_on)
        self.torn_writes = 0
        self.crashes = 0
        self._fsyncs = 0

    def crash(self, point):
        if point in self.crash_at:
            self.crashes += 1
            raise SimulatedCrash("injected crash at %s" % point)

    def fsync_fails(self):
        idx = self._fsyncs
        self._fsyncs += 1
        return idx in self.fsync_fail_on

    def error(self, message):
        return SimulatedCrash(message)


class ReplicationChaos:
    """Replication-gap injection for the anti-entropy path
    (state/replicate.py): `drop_pairs` is a set of (peer_id, keyspace)
    pairs — with keyspace None matching every keyspace — whose pulls
    are swallowed (counted under "state_antientropy_dropped"). Dropped
    pulls retry on a later step, so clearing the schedule demonstrates
    convergence-after-heal."""

    def __init__(self, drop_pairs=()):
        self.drop_pairs = set(drop_pairs)
        self.dropped = 0

    def drop(self, peer, keyspace):
        hit = (peer, keyspace) in self.drop_pairs or (
            peer,
            None,
        ) in self.drop_pairs
        if hit:
            self.dropped += 1
        return hit

    def heal(self):
        self.drop_pairs.clear()

# the verify entry points verify_stream._dispatchers probes for; faults are
# injected only on these, everything else delegates untouched
_SYNC_VERIFY = frozenset({
    "batch_verify",
    "batch_verify_grouped",
    "batch_verify_combined",
    "batch_show_verify_combined",
})
_ASYNC_VERIFY = frozenset({
    "batch_verify_async",
    "batch_verify_grouped_async",
    "batch_verify_combined_async",
})


class FaultyBackend:
    """Capability-transparent fault-injecting wrapper around a backend.

    Attribute access delegates to the wrapped backend, so a wrapped
    backend exposes exactly the verify capabilities of the inner one
    (`hasattr` probes in stream._dispatchers see through the wrapper).
    A single dispatch counter ticks across all wrapped verify methods;
    schedules address dispatches by that 0-based global index:

      raise_every=N  — every Nth dispatch (indices N-1, 2N-1, ...) raises
                       `error` at dispatch time, before the inner backend
                       runs (a device/tunnel failure on submit);
      raise_on       — explicit dispatch indices that raise at dispatch;
      flip_on        — dispatch indices whose verdicts are negated
                       (elementwise for per-credential lists, the single
                       bool for grouped) — a miscompute, not a crash;
      corrupt_finalizer_on — dispatch indices whose readback raises
                       `error`: for async seams the returned finalizer
                       raises when settled; for sync seams the call raises
                       after the inner compute (the result is lost in
                       flight);
      delay_every=N / delay_on — dispatch indices that `sleep(delay_s)`
                       BEFORE the inner backend runs (a slow device, not a
                       dead one): deterministic latency injection for the
                       serving layer's deadline-flush and timeout tests.
                       `sleep` is injectable (default time.sleep) so those
                       tests can record the scheduled delays instead of
                       actually waiting.
      crash_on       — dispatch indices that raise `InjectedCrash` (a
                       BaseException: escapes per-batch containment and
                       crashes the executor LOOP — the quarantine +
                       redistribution path, not the retry ladder);
      hang_every=N / hang_on — dispatch indices that BLOCK on the
                       `hang_release` event instead of returning (a wedged
                       device: only the serve watchdog frees its batch).
                       `hang_entered` is set when a hang begins (the
                       test's deterministic sync point); `hang_max_s`
                       bounds an un-released hang.

    Schedule sets are plain attributes and may be reassigned mid-run
    (e.g. ``fb.crash_on = frozenset({fb.dispatches})`` to crash the NEXT
    dispatch) — the probe/bench chaos phases schedule faults relative to
    the live dispatch counter this way.

    SIGN-PATH seams (threshold issuance, coconut_tpu/issue/): the
    authority executors dispatch `batch_blind_sign` THROUGH the backend
    object when it exposes one, and this wrapper always does — so the
    same harness drives issuance chaos. Sign dispatches tick their OWN
    0-based counter (`sign_dispatches`), independent of the verify
    counter, so a chaos schedule addresses "the 3rd sign" without
    counting verify traffic:

      fail_sign_on    — sign dispatch indices that raise `error` before
                        the inner signer runs (a transient authority
                        fault: the quorum layer hedges around it);
      crash_sign_on   — sign dispatch indices that raise `InjectedCrash`
                        (BaseException: crashes the AUTHORITY loop — the
                        quarantine + hedge-coverage path);
      hang_sign_on    — sign dispatch indices that block on the shared
                        `hang_release` event (a wedged authority: only
                        the issue watchdog frees its fan-out);
      corrupt_partial_on — sign dispatch indices whose FIRST partial
                        signature comes back with one limb flipped
                        (c_tilde_2 displaced by h): a Byzantine
                        authority emitting a plausible-but-invalid
                        share — the verify-before-release gate must
                        catch and attribute it.

    `error` is the exception class raised (default TransientBackendError;
    pass e.g. RuntimeError to model a permanent fault)."""

    def __init__(
        self,
        inner,
        raise_every=None,
        raise_on=(),
        flip_on=(),
        corrupt_finalizer_on=(),
        delay_every=None,
        delay_on=(),
        delay_s=0.0,
        crash_on=(),
        hang_every=None,
        hang_on=(),
        hang_release=None,
        hang_max_s=30.0,
        fail_sign_on=(),
        crash_sign_on=(),
        hang_sign_on=(),
        corrupt_partial_on=(),
        sleep=time.sleep,
        error=TransientBackendError,
    ):
        self.inner = inner
        self.raise_every = raise_every
        self.raise_on = frozenset(raise_on)
        self.flip_on = frozenset(flip_on)
        self.corrupt_finalizer_on = frozenset(corrupt_finalizer_on)
        self.delay_every = delay_every
        self.delay_on = frozenset(delay_on)
        self.delay_s = delay_s
        self.crash_on = frozenset(crash_on)
        self.hang_every = hang_every
        self.hang_on = frozenset(hang_on)
        self.hang_release = (
            hang_release if hang_release is not None else threading.Event()
        )
        self.hang_entered = threading.Event()
        self.hang_max_s = hang_max_s
        self.fail_sign_on = frozenset(fail_sign_on)
        self.crash_sign_on = frozenset(crash_sign_on)
        self.hang_sign_on = frozenset(hang_sign_on)
        self.corrupt_partial_on = frozenset(corrupt_partial_on)
        self.hangs = 0
        self.crashes = 0
        self.corrupted_partials = 0
        self.sleep = sleep
        self.error = error
        self.dispatches = 0
        self.sign_dispatches = 0

    def _tick(self):
        idx = self.dispatches
        self.dispatches += 1
        return idx

    def _sign_tick(self):
        idx = self.sign_dispatches
        self.sign_dispatches += 1
        return idx

    def _dispatch_faulted(self, idx):
        if self.raise_every and (idx + 1) % self.raise_every == 0:
            return True
        return idx in self.raise_on

    def _dispatch_delayed(self, idx):
        if self.delay_every and (idx + 1) % self.delay_every == 0:
            return True
        return idx in self.delay_on

    def _maybe_delay(self, idx):
        if self.delay_s and self._dispatch_delayed(idx):
            self.sleep(self.delay_s)

    def _dispatch_hangs(self, idx):
        if self.hang_every and (idx + 1) % self.hang_every == 0:
            return True
        return idx in self.hang_on

    def _maybe_crash(self, idx, name):
        if idx in self.crash_on:
            self.crashes += 1
            raise InjectedCrash(
                "injected executor crash #%d (%s)" % (idx, name)
            )

    def _maybe_hang(self, idx):
        if self._dispatch_hangs(idx):
            self.hangs += 1
            # deterministic hang: block until the harness releases it —
            # no sleeps, and hang_max_s keeps an un-released hang from
            # wedging a whole test run
            self.hang_entered.set()
            self.hang_release.wait(self.hang_max_s)

    def _mangle(self, idx, result):
        if idx in self.flip_on:
            if isinstance(result, list):
                return [not b for b in result]
            if isinstance(result, tuple) and len(result) == 2:
                # batch_show_verify_combined's (schnorr bits, pairing ok)
                bits, ok = result
                return ([not b for b in bits], not ok)
            return not result
        return result

    def batch_blind_sign(self, sig_requests, sigkey, params):
        """The authority-side sign seam (coconut_tpu/issue/authority.py
        dispatches through the backend's `batch_blind_sign` when it has
        one — this wrapper always does, so wrapping an authority's backend
        puts its sign path under the chaos schedules). Ticks the SEPARATE
        sign-dispatch counter; delegates to the inner backend's own
        `batch_blind_sign` when present, else to the library entry point
        with the inner backend's MSM primitives."""
        idx = self._sign_tick()
        if idx in self.crash_sign_on:
            self.crashes += 1
            raise InjectedCrash(
                "injected authority crash on sign dispatch #%d" % idx
            )
        if idx in self.fail_sign_on:
            raise self.error("injected sign-dispatch fault #%d" % idx)
        if idx in self.hang_sign_on:
            self.hangs += 1
            self.hang_entered.set()
            self.hang_release.wait(self.hang_max_s)
        inner_sign = getattr(self.inner, "batch_blind_sign", None)
        if inner_sign is not None:
            out = inner_sign(sig_requests, sigkey, params)
        else:
            from .signature import batch_blind_sign as _bbs

            out = _bbs(sig_requests, sigkey, params, backend=self.inner)
        if idx in self.corrupt_partial_on and out:
            # flip ONE limb of ONE partial: displace the first partial's
            # c_tilde_2 by its own h — still a valid curve point (the
            # plausible Byzantine case), but the share no longer
            # interpolates, so only verify-before-release can catch it
            from .signature import BlindSignature

            bs = out[0]
            ops = params.ctx.sig
            out = [
                BlindSignature(
                    bs.h, (bs.blinded[0], ops.add(bs.blinded[1], bs.h))
                )
            ] + list(out[1:])
            self.corrupted_partials += 1
        return out

    def __getattr__(self, name):
        attr = getattr(self.inner, name)
        if name in _SYNC_VERIFY:

            def sync_injected(*args, **kwargs):
                idx = self._tick()
                self._maybe_crash(idx, name)
                if self._dispatch_faulted(idx):
                    raise self.error(
                        "injected dispatch fault #%d (%s)" % (idx, name)
                    )
                self._maybe_hang(idx)
                self._maybe_delay(idx)
                result = attr(*args, **kwargs)
                if idx in self.corrupt_finalizer_on:
                    raise self.error(
                        "injected readback fault #%d (%s)" % (idx, name)
                    )
                return self._mangle(idx, result)

            return sync_injected
        if name in _ASYNC_VERIFY:

            def async_injected(*args, **kwargs):
                idx = self._tick()
                self._maybe_crash(idx, name)
                if self._dispatch_faulted(idx):
                    raise self.error(
                        "injected dispatch fault #%d (%s)" % (idx, name)
                    )
                self._maybe_delay(idx)
                fin = attr(*args, **kwargs)

                def finalize():
                    # async seams hang at READBACK: the launch returned,
                    # the result never arrives
                    self._maybe_hang(idx)
                    if idx in self.corrupt_finalizer_on:
                        raise self.error(
                            "injected finalizer fault #%d (%s)" % (idx, name)
                        )
                    return self._mangle(idx, fin())

                return finalize

            return async_injected
        return attr


class ChaosSchedule:
    """A declarative chaos experiment: WHICH 0-based dispatch indices
    crash, hang, fault, flip, or stall — one object a test, probe, or
    bench lane can both APPLY (`wrap()` a backend) and DESCRIBE
    (`describe()` into a report). Everything stays deterministic: the
    schedule is pure data, the wrapped FaultyBackend's single dispatch
    counter drives it, and `release_hangs()` is the only side-effectful
    control (freeing every hung dispatch across every wrapped backend —
    call it before drain so abandoned workers exit promptly)."""

    def __init__(
        self,
        crash_on=(),
        hang_on=(),
        fault_on=(),
        flip_on=(),
        delay_on=(),
        delay_s=0.0,
        fail_sign_on=(),
        crash_sign_on=(),
        hang_sign_on=(),
        corrupt_partial_on=(),
    ):
        self.crash_on = frozenset(crash_on)
        self.hang_on = frozenset(hang_on)
        self.fault_on = frozenset(fault_on)
        self.flip_on = frozenset(flip_on)
        self.delay_on = frozenset(delay_on)
        self.delay_s = delay_s
        self.fail_sign_on = frozenset(fail_sign_on)
        self.crash_sign_on = frozenset(crash_sign_on)
        self.hang_sign_on = frozenset(hang_sign_on)
        self.corrupt_partial_on = frozenset(corrupt_partial_on)
        self.backends = []

    def wrap(self, inner, **kwargs):
        """FaultyBackend over `inner` carrying this schedule; extra
        kwargs (sleep, error, hang_max_s, ...) pass through."""
        fb = FaultyBackend(
            inner,
            raise_on=self.fault_on,
            flip_on=self.flip_on,
            delay_on=self.delay_on,
            delay_s=self.delay_s,
            crash_on=self.crash_on,
            hang_on=self.hang_on,
            fail_sign_on=self.fail_sign_on,
            crash_sign_on=self.crash_sign_on,
            hang_sign_on=self.hang_sign_on,
            corrupt_partial_on=self.corrupt_partial_on,
            **kwargs,
        )
        self.backends.append(fb)
        return fb

    def release_hangs(self):
        for fb in self.backends:
            fb.hang_release.set()

    def describe(self):
        """JSON-ready description for bench/probe reports."""
        return {
            "crash_on": sorted(self.crash_on),
            "hang_on": sorted(self.hang_on),
            "fault_on": sorted(self.fault_on),
            "flip_on": sorted(self.flip_on),
            "delay_on": sorted(self.delay_on),
            "delay_s": self.delay_s,
            "fail_sign_on": sorted(self.fail_sign_on),
            "crash_sign_on": sorted(self.crash_sign_on),
            "hang_sign_on": sorted(self.hang_sign_on),
            "corrupt_partial_on": sorted(self.corrupt_partial_on),
        }


class DeadLetterLog:
    """Append-only JSONL sink for credentials the stream could not accept.

    One object per line, keys sorted for grep-ability (schema v4):
      {"attempts": [...], "batch": int, "credential": int,
       "nullifier": str|null, "reason": str, "schema": 4,
       "span_id": int|null, "trace_id": str|null}
    where `credential` is the index WITHIN the batch, `attempts` is the
    batch's retry attempt history (retry.note_attempt records),
    trace_id/span_id join the line to its request's span tree (null with
    tracing disabled), and `nullifier` is the spent-nullifier hex digest
    on show-verify double-spend rejections (null everywhere else).

    Disk-bounded: before an append that would cross `max_bytes` or
    `max_records`, the file rotates aside (`<path>.1` newest ..
    `<path>.<keep>` oldest, via obs/flight.rotate_if_needed — the same
    cap discipline the flight-recorder sidecar uses). `read()` reads ONE
    file; pass the rotated names explicitly to walk history.

    Durable-state ride-along (PR 17): given a `store` (state/store.py
    StateStore), every append is also indexed into its "deadletter"
    keyspace — key `<batch>/<credential>/<n>` -> the record — so the
    dead-letter index survives restarts via WAL replay and replicates
    with the rest of the state plane. The JSONL file remains the
    grep-able source of truth; the store index is lazy-durability
    (fsync=False: losing the last few index entries on a crash is
    acceptable, the JSONL line is what operators act on)."""

    def __init__(
        self,
        path,
        max_bytes=_flight.FLIGHT_MAX_BYTES,
        max_records=None,
        keep=_flight.FLIGHT_KEEP,
        store=None,
    ):
        self.path = path
        self.max_bytes = max_bytes
        self.max_records = max_records
        self.keep = keep
        self.store = store
        self._indexed = 0  # store-index sequence (uniquifies keys)
        self._records = None  # lazy line count of the live file

    def append(
        self,
        batch,
        credential,
        reason,
        attempts=(),
        trace_id=None,
        span_id=None,
        program=None,
        nullifier=None,
    ):
        """Append one culprit record. trace_id/span_id default to the
        ACTIVE span's (the bisection span, within the batch trace) when
        tracing is enabled; the serve path overrides trace_id with the
        culprit request's own. `program` names the engine program whose
        batch produced the culprit (schema v3); `nullifier` is the spent
        digest on double-spend rejections (schema v4). Triggers a
        flight-recorder dump for the recorded trace."""
        cur = otrace.current()
        if cur is not None:
            if trace_id is None:
                trace_id = cur.trace_id
            if span_id is None:
                span_id = cur.span_id
        rec = {
            "schema": DEAD_LETTER_SCHEMA,
            "batch": int(batch),
            "credential": int(credential),
            "reason": reason,
            "attempts": list(attempts),
            "trace_id": trace_id,
            "span_id": span_id,
            "program": program,
            "nullifier": nullifier,
        }
        if self._records is None:
            self._records = (
                len(DeadLetterLog.read(self.path))
                if self.max_records is not None
                else 0
            )
        if _flight.rotate_if_needed(
            self.path,
            max_bytes=self.max_bytes,
            max_records=self.max_records,
            keep=self.keep,
            record_count=self._records,
        ):
            self._records = 0
        # lint: allow(durability, append-only JSONL; read() skips+counts a
        # torn tail, so a crash mid-append loses at most this one record)
        with open(self.path, "a") as f:
            f.write(json.dumps(rec, sort_keys=True) + "\n")
        self._records += 1
        if self.store is not None:
            self._indexed += 1
            try:
                self.store.put(
                    "deadletter",
                    "%d/%d/%d"
                    % (rec["batch"], rec["credential"], self._indexed),
                    rec,
                    fsync=False,
                )
            except Exception:
                # the JSONL line already landed: a failing durable
                # index must not turn a dead-letter append into a
                # second failure
                metrics.count("dead_letter_index_errors")
        _flight.record(
            self.path,
            "dead_letter",
            trace_id=trace_id,
            extra={
                "batch": rec["batch"],
                "credential": rec["credential"],
                "program": program,
            },
        )
        return rec

    @staticmethod
    def read(path):
        """All records in `path` (empty list if it does not exist).
        Older records are normalized on read: absent trace fields become
        null (pre-v2), absent program becomes null (pre-v3), absent
        nullifier becomes null (pre-v4), absent schema becomes 1 —
        readers never need per-version key checks.

        Torn-tail tolerant (the WAL's recovery contract, in miniature):
        the append path is plain JSONL, so a crash mid-append can leave
        a truncated final line. Unparseable lines are skipped and
        counted under "dead_letter_torn_lines" instead of poisoning
        every future read() — and, through the lazy record count above,
        every future append()."""
        if not os.path.exists(path):
            return []
        recs = []
        torn = 0
        with open(path) as f:
            for line in f:
                if not line.strip():
                    continue
                try:
                    recs.append(json.loads(line))
                except ValueError:
                    torn += 1
        if torn:
            metrics.count("dead_letter_torn_lines", torn)
        for rec in recs:
            rec.setdefault("schema", 1)
            rec.setdefault("trace_id", None)
            rec.setdefault("span_id", None)
            rec.setdefault("program", None)
            rec.setdefault("nullifier", None)
        return recs
