"""Deterministic fault injection + the dead-letter sink.

Two pieces of the stream supervision layer that live OUTSIDE the happy
path:

  - `FaultyBackend` wraps any verify-capable backend and injects scheduled
    faults at exactly the seam `stream.verify_stream` dispatches through:
    raise-on-Nth-dispatch transient errors, flipped verdicts, and corrupted
    (raising) finalizers. Schedules are index-based and fully
    deterministic, so tests/test_faults.py proves the retry / fallback /
    bisection paths without flaky randomness.

    Latency injection (the serving layer's deadline-flush and timeout
    tests need SLOW dispatches, not just failed ones): `delay_every=N` /
    `delay_on={i, ...}` schedule a `sleep(delay_s)` immediately before the
    inner backend runs on the matching 0-based dispatch indices — the same
    global counter the fault schedules use, so "the 3rd dispatch is slow"
    is exactly reproducible. `sleep` is injectable (default `time.sleep`):
    tests pass a recording fake so deadline/timeout behavior is proven
    without wall-clock flakiness — the schedule stays deterministic either
    way.

  - `DeadLetterLog` is the append-only JSONL file that receives culprit
    credentials isolated by grouped-failure bisection: one object per
    line with the batch index, the credential's index within the batch,
    a reason, and the batch's retry attempt history. JSONL so a ledger
    operator can grep/stream it without loading a document; ci.sh greps
    the schema as a smoke check.

    Schema v2 (request-scoped tracing): entries carry `trace_id` /
    `span_id` so a dead-letter line joins back to its span tree (the
    serve path passes the CULPRIT request's trace_id; the offline stream
    defaults both to the active bisection span). Both are null with
    tracing disabled, and v1 files (no trace fields) read back with the
    fields normalized to null — old logs stay parseable. Each append
    also triggers the flight recorder (obs/flight.py): the failing
    trace's span tree plus the recent-span tail land in
    `<path>.flight.jsonl` next to this log.
"""

import json
import os
import time

from .errors import TransientBackendError
from .obs import flight as _flight
from .obs import trace as otrace

#: dead-letter JSONL schema: v2 added trace_id/span_id (absent -> null)
DEAD_LETTER_SCHEMA = 2

# the verify entry points verify_stream._dispatchers probes for; faults are
# injected only on these, everything else delegates untouched
_SYNC_VERIFY = frozenset({"batch_verify", "batch_verify_grouped"})
_ASYNC_VERIFY = frozenset({"batch_verify_async", "batch_verify_grouped_async"})


class FaultyBackend:
    """Capability-transparent fault-injecting wrapper around a backend.

    Attribute access delegates to the wrapped backend, so a wrapped
    backend exposes exactly the verify capabilities of the inner one
    (`hasattr` probes in stream._dispatchers see through the wrapper).
    A single dispatch counter ticks across all wrapped verify methods;
    schedules address dispatches by that 0-based global index:

      raise_every=N  — every Nth dispatch (indices N-1, 2N-1, ...) raises
                       `error` at dispatch time, before the inner backend
                       runs (a device/tunnel failure on submit);
      raise_on       — explicit dispatch indices that raise at dispatch;
      flip_on        — dispatch indices whose verdicts are negated
                       (elementwise for per-credential lists, the single
                       bool for grouped) — a miscompute, not a crash;
      corrupt_finalizer_on — dispatch indices whose readback raises
                       `error`: for async seams the returned finalizer
                       raises when settled; for sync seams the call raises
                       after the inner compute (the result is lost in
                       flight);
      delay_every=N / delay_on — dispatch indices that `sleep(delay_s)`
                       BEFORE the inner backend runs (a slow device, not a
                       dead one): deterministic latency injection for the
                       serving layer's deadline-flush and timeout tests.
                       `sleep` is injectable (default time.sleep) so those
                       tests can record the scheduled delays instead of
                       actually waiting.

    `error` is the exception class raised (default TransientBackendError;
    pass e.g. RuntimeError to model a permanent fault)."""

    def __init__(
        self,
        inner,
        raise_every=None,
        raise_on=(),
        flip_on=(),
        corrupt_finalizer_on=(),
        delay_every=None,
        delay_on=(),
        delay_s=0.0,
        sleep=time.sleep,
        error=TransientBackendError,
    ):
        self.inner = inner
        self.raise_every = raise_every
        self.raise_on = frozenset(raise_on)
        self.flip_on = frozenset(flip_on)
        self.corrupt_finalizer_on = frozenset(corrupt_finalizer_on)
        self.delay_every = delay_every
        self.delay_on = frozenset(delay_on)
        self.delay_s = delay_s
        self.sleep = sleep
        self.error = error
        self.dispatches = 0

    def _tick(self):
        idx = self.dispatches
        self.dispatches += 1
        return idx

    def _dispatch_faulted(self, idx):
        if self.raise_every and (idx + 1) % self.raise_every == 0:
            return True
        return idx in self.raise_on

    def _dispatch_delayed(self, idx):
        if self.delay_every and (idx + 1) % self.delay_every == 0:
            return True
        return idx in self.delay_on

    def _maybe_delay(self, idx):
        if self.delay_s and self._dispatch_delayed(idx):
            self.sleep(self.delay_s)

    def _mangle(self, idx, result):
        if idx in self.flip_on:
            if isinstance(result, list):
                return [not b for b in result]
            return not result
        return result

    def __getattr__(self, name):
        attr = getattr(self.inner, name)
        if name in _SYNC_VERIFY:

            def sync_injected(*args, **kwargs):
                idx = self._tick()
                if self._dispatch_faulted(idx):
                    raise self.error(
                        "injected dispatch fault #%d (%s)" % (idx, name)
                    )
                self._maybe_delay(idx)
                result = attr(*args, **kwargs)
                if idx in self.corrupt_finalizer_on:
                    raise self.error(
                        "injected readback fault #%d (%s)" % (idx, name)
                    )
                return self._mangle(idx, result)

            return sync_injected
        if name in _ASYNC_VERIFY:

            def async_injected(*args, **kwargs):
                idx = self._tick()
                if self._dispatch_faulted(idx):
                    raise self.error(
                        "injected dispatch fault #%d (%s)" % (idx, name)
                    )
                self._maybe_delay(idx)
                fin = attr(*args, **kwargs)

                def finalize():
                    if idx in self.corrupt_finalizer_on:
                        raise self.error(
                            "injected finalizer fault #%d (%s)" % (idx, name)
                        )
                    return self._mangle(idx, fin())

                return finalize

            return async_injected
        return attr


class DeadLetterLog:
    """Append-only JSONL sink for credentials the stream could not accept.

    One object per line, keys sorted for grep-ability (schema v2):
      {"attempts": [...], "batch": int, "credential": int, "reason": str,
       "schema": 2, "span_id": int|null, "trace_id": str|null}
    where `credential` is the index WITHIN the batch, `attempts` is the
    batch's retry attempt history (retry.note_attempt records), and
    trace_id/span_id join the line to its request's span tree (null with
    tracing disabled)."""

    def __init__(self, path):
        self.path = path

    def append(
        self, batch, credential, reason, attempts=(), trace_id=None, span_id=None
    ):
        """Append one culprit record. trace_id/span_id default to the
        ACTIVE span's (the bisection span, within the batch trace) when
        tracing is enabled; the serve path overrides trace_id with the
        culprit request's own. Triggers a flight-recorder dump for the
        recorded trace."""
        cur = otrace.current()
        if cur is not None:
            if trace_id is None:
                trace_id = cur.trace_id
            if span_id is None:
                span_id = cur.span_id
        rec = {
            "schema": DEAD_LETTER_SCHEMA,
            "batch": int(batch),
            "credential": int(credential),
            "reason": reason,
            "attempts": list(attempts),
            "trace_id": trace_id,
            "span_id": span_id,
        }
        with open(self.path, "a") as f:
            f.write(json.dumps(rec, sort_keys=True) + "\n")
        _flight.record(
            self.path,
            "dead_letter",
            trace_id=trace_id,
            extra={"batch": rec["batch"], "credential": rec["credential"]},
        )
        return rec

    @staticmethod
    def read(path):
        """All records in `path` (empty list if it does not exist).
        Pre-v2 records are normalized on read: absent trace fields become
        null, absent schema becomes 1 — readers never need per-version
        key checks."""
        if not os.path.exists(path):
            return []
        with open(path) as f:
            recs = [json.loads(line) for line in f if line.strip()]
        for rec in recs:
            rec.setdefault("schema", 1)
            rec.setdefault("trace_id", None)
            rec.setdefault("span_id", None)
        return recs
