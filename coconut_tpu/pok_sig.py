"""Proof of knowledge of a credential with selective disclosure — the
"Show"/"ShowVerify" step.

The reference's pok_sig.rs is a 6-line delegation to ps_sig plus a test
(pok_sig.rs:1-6); here the protocol lives in `coconut_tpu.ps` and this module
provides the convenience pair the README's 8-step flow ends with
(README.md:141-172)."""

from .ps import PoKOfSignature, PoKOfSignatureProof  # noqa: F401 (re-export)
from .signature import fiat_shamir_challenge


def show(sig, vk, params, messages, revealed_msg_indices, blindings=None):
    """Prover side: returns (proof, challenge, revealed_msgs). Non-interactive
    via Fiat-Shamir over the PoK transcript (pok_sig.rs:85-95)."""
    pok = PoKOfSignature(
        sig, vk, params, messages,
        blindings=blindings,
        revealed_msg_indices=revealed_msg_indices,
    )
    challenge = fiat_shamir_challenge(pok.to_bytes())
    proof = pok.gen_proof(challenge)
    revealed_msgs = {i: messages[i] for i in proof.revealed_msg_indices}
    return proof, challenge, revealed_msgs


def show_verify(proof, vk, params, revealed_msgs, challenge=None):
    """Verifier side. When `challenge` is None the Fiat-Shamir challenge is
    recomputed from the proof transcript (the secure non-interactive path);
    passing it explicitly matches the reference's interactive-style tests
    (pok_sig.rs:94-105)."""
    if challenge is None:
        challenge = fiat_shamir_challenge(
            proof.to_bytes_for_challenge(vk, params)
        )
    return proof.verify(vk, params, revealed_msgs, challenge)
