"""Proof of knowledge of a credential with selective disclosure — the
"Show"/"ShowVerify" step.

The reference's pok_sig.rs is a 6-line delegation to ps_sig plus a test
(pok_sig.rs:1-6); here the protocol lives in `coconut_tpu.ps` and this module
provides the convenience pair the README's 8-step flow ends with
(README.md:141-172) — plus `batch_show`, the batched prover (VERDICT r2
item 4: the sequential prover dwarfed the batched verifier)."""

from .ops.fields import R
from .pok_vc import Proof
from .ps import (  # noqa: F401 (re-export)
    PoKOfSignature,
    PoKOfSignatureProof,
    batch_show_verify,
)
from .signature import fiat_shamir_challenge
from .sss import rand_fr


def show(sig, vk, params, messages, revealed_msg_indices, blindings=None):
    """Prover side: returns (proof, challenge, revealed_msgs). Non-interactive
    via Fiat-Shamir over the PoK transcript (pok_sig.rs:85-95)."""
    pok = PoKOfSignature(
        sig, vk, params, messages,
        blindings=blindings,
        revealed_msg_indices=revealed_msg_indices,
    )
    challenge = fiat_shamir_challenge(pok.to_bytes())
    proof = pok.gen_proof(challenge)
    revealed_msgs = {i: messages[i] for i in proof.revealed_msg_indices}
    return proof, challenge, revealed_msgs


def batch_show(sigs, vk, params, messages_list, revealed_msg_indices,
               backend=None):
    """Batched prover side of Show: the same per-credential proofs `show`
    produces (identical math; fresh per-credential randomness), with every
    group operation routed through a `CurveBackend` so the whole batch runs
    as a handful of fused MSM kernels instead of 4B host scalar-muls
    (reference surface pok_sig.rs:85-95).

    All credentials share one revealed-index set (the batchable shape; mixed
    sets should call `show` per credential). Returns (proofs, challenges,
    revealed_msgs_list)."""
    B = len(sigs)
    if len(messages_list) != B:
        raise ValueError(
            "batch size mismatch: %d sigs, %d message vectors"
            % (B, len(messages_list))
        )
    if backend is None or B == 0:
        out = [
            show(s, vk, params, m, revealed_msg_indices)
            for s, m in zip(sigs, messages_list)
        ]
        return (
            [o[0] for o in out],
            [o[1] for o in out],
            [o[2] for o in out],
        )
    if isinstance(backend, str):
        from .backend import get_backend

        backend = get_backend(backend)
    ctx = params.ctx
    revealed = set(revealed_msg_indices)
    q = len(vk.Y_tilde)
    for msgs in messages_list:
        if len(msgs) != q:
            from .errors import UnsupportedNoOfMessages

            raise UnsupportedNoOfMessages(q, len(msgs))
    for i in revealed:
        if not 0 <= i < q:
            raise ValueError("revealed index %d out of range" % i)
    hidden = [i for i in range(q) if i not in revealed]
    if ctx.name == "G1":
        msm_sig_distinct = backend.msm_g1_distinct
        msm_other_shared = backend.msm_g2_shared
    else:
        msm_sig_distinct = backend.msm_g2_distinct
        msm_other_shared = backend.msm_g1_shared

    # per-credential randomness (same sampling as PoKOfSignature.__init__)
    rs = [rand_fr() for _ in range(B)]
    ts = [rand_fr() for _ in range(B)]
    blindings = [[rand_fr() for _ in range(1 + len(hidden))] for _ in range(B)]

    # sigma'_1 = sigma_1^r ; sigma'_2 = (sigma_2 + t sigma_1)^r
    #          = sigma_2^r + sigma_1^{t r}
    s2_rows = [[s.sigma_2, s.sigma_1] for s in sigs]
    s2_scal = [[r, t * r % R] for r, t in zip(rs, ts)]
    # J = g_tilde^t * prod_hidden Y_j^{m_j} and the Schnorr commitment
    # t-point over the SAME shared bases — two comb MSMs, fused into one
    # device program when the backend supports multi-MSM jobs. The sigma
    # MSM and the J/commitment MSMs are independent, so with an
    # async-capable backend both programs are dispatched before either is
    # decoded (the sigma decode then overlaps the comb program).
    bases = [params.g_tilde] + [vk.Y_tilde[i] for i in hidden]
    secrets_rows = [
        [t] + [msgs[i] for i in hidden]
        for t, msgs in zip(ts, messages_list)
    ]
    from .backend import async_distinct_api, async_shared_many_api

    sig_grp, other_grp = ("g1", "g2") if ctx.name == "G1" else ("g2", "g1")
    many = getattr(backend, "msm_%s_shared_many" % other_grp, None)
    many_api = async_shared_many_api(backend, other_grp)
    distinct_api = async_distinct_api(backend, sig_grp)
    jobs = [
        (bases, [[s % R for s in row] for row in secrets_rows]),
        (bases, blindings),
    ]
    if many_api is not None and distinct_api is not None:
        # ONE fused distinct MSM for the sigma pair: the sigma'_1 rows pad
        # to the sigma'_2 width (k = 2) and stack to [2B, 2] — a single
        # dispatch + readback (VERDICT r3 item 5). Only the single-dispatch
        # device backend gains from the stacking; the per-row fallbacks
        # below skip the dummy column.
        distinct_dispatch, distinct_wait = distinct_api
        many_dispatch, many_wait = many_api
        sig_handle = distinct_dispatch(
            [[s.sigma_1, None] for s in sigs] + s2_rows,
            [[r, 0] for r in rs] + s2_scal,
        )
        many_handle = many_dispatch(jobs)
        sig_out = distinct_wait(sig_handle)
        Js, comms = many_wait(many_handle)
        sigma1p, sigma2p = sig_out[:B], sig_out[B:]
    else:
        sigma1p = msm_sig_distinct(
            [[s.sigma_1] for s in sigs], [[r] for r in rs]
        )
        sigma2p = msm_sig_distinct(s2_rows, s2_scal)
        if many is not None:
            Js, comms = many(jobs)
        else:
            Js = msm_other_shared(*jobs[0])
            comms = msm_other_shared(*jobs[1])

    # Fiat-Shamir + responses, host-side (cheap field/hash work)
    bases_bytes = b"".join(ctx.other_to_bytes(b) for b in bases)
    proofs, challenges, revealed_list = [], [], []
    for i in range(B):
        transcript = (
            ctx.sig_to_bytes(sigma1p[i])
            + ctx.sig_to_bytes(sigma2p[i])
            + ctx.other_to_bytes(Js[i])
            + bases_bytes
            + ctx.other_to_bytes(comms[i])
        )
        c = fiat_shamir_challenge(transcript)
        responses = [
            (b - c * s) % R
            for b, s in zip(blindings[i], secrets_rows[i])
        ]
        proofs.append(
            PoKOfSignatureProof(
                sigma1p[i],
                sigma2p[i],
                Js[i],
                Proof(comms[i], responses),
                revealed,
            )
        )
        challenges.append(c)
        revealed_list.append({j: messages_list[i][j] for j in revealed})
    return proofs, challenges, revealed_list


def show_verify(proof, vk, params, revealed_msgs, challenge=None):
    """Verifier side. When `challenge` is None the Fiat-Shamir challenge is
    recomputed from the proof transcript (the secure non-interactive path);
    passing it explicitly matches the reference's interactive-style tests
    (pok_sig.rs:94-105).

    The batched verifier (ps.batch_show_verify, re-exported here) grows a
    mode="batched" variant in PR 16: one RLC-combined pairing product +
    shared final exponentiation for the whole batch, bisection fallback
    on rejection. A single proof always verifies exactly."""
    if challenge is None:
        challenge = fiat_shamir_challenge(
            proof.to_bytes_for_challenge(vk, params)
        )
    return proof.verify(vk, params, revealed_msgs, challenge)
