"""Epoch-scoped nullifier set: the double-spend detector (PR 17).

The Coconut paper's e-cash and petition applications both reduce to
the same primitive: a credential may be SHOWN at most once (per epoch,
for petitions: once per petition epoch). The nullifier is a
deterministic digest of the show transcript —

    sha256(b"coconut-nullifier/v1"
           || u32 epoch (0 when unscoped)
           || 32-byte big-endian Fiat-Shamir challenge
           || proof.to_bytes(ctx))           # canonical wire encoding

— so replaying the SAME show (same proof bytes, same challenge)
anywhere in the fleet derives the same nullifier, while a fresh show
of the same credential re-randomizes sigma' and derives a new one.
That is exactly the paper's unlinkability/double-spend split: verifiers
cannot link two honest shows, but an exact replay is caught.

Two-tier membership check:

  1. `probe` — a device-resident batched membership test fused ahead
     of the verify bit: spent digests become rows of a SORTED
     [n, 8]-limb uint32 table (big-endian sha256 limbs, the same limb
     framing tpu/limbs.py uses for field elements), padded to a power
     of two with all-ones sentinel rows, and each lane runs a
     BRANCHLESS lower-bound (fixed log2(n) rounds of lexicographic
     row-compare + gather — no data-dependent control flow, so the
     whole batch stays one fused device computation). A hit clears the
     lane's own verify bit. Advisory only: the table snapshot may lag
     a concurrent commit.
  2. `commit` — the authoritative host-side check-and-set under the
     store lock: accepted lanes re-check against the live set AND
     against each other (an intra-batch replay pair must not both
     land), then every genuinely-new nullifier is WAL-appended in ONE
     group commit (`StateStore.put_many`, one fsync per batch) BEFORE
     any future resolves. An acknowledged show therefore survives a
     SIGKILL — the kill-the-witness drill in probes/probe_nullifier.py
     is the acceptance test.

Scenario domains (PR 19). The paper's applications need a second axis
of scoping: a petition campaign wants "this credential signs THIS
campaign at most once" (while the same credential may sign OTHER
campaigns), and e-cash wants "this coin spends at most once" even
though every honest show re-randomizes the transcript. Both are
expressed by an optional (domain, tag) pair on show-verify:

  - `domain` — a scope string (e.g. "petition/save-the-bees",
    "ecash"); nullifiers in different domains live in DIFFERENT
    keyspaces and never collide.
  - `tag` — an optional deterministic 32-byte spend tag supplied by
    the client (see `spend_tag_of`): when present, the nullifier is
    derived from the TAG instead of the transcript, so any re-spend of
    the same credential in the same domain collides — not just an
    exact replay.

With both absent the derivation is byte-identical to the v1 transcript
nullifier above (existing WALs, probes, and golden tests unaffected);
with either present a distinct v2 derivation is used, so domain-scoped
digests can never collide with unscoped ones. In this reproduction
the tag is client-supplied and trusted — in the full Coconut protocol
it would be derived in zero knowledge from a credential attribute;
that proof is out of scope here and the seam is the scenario layer's
simulation boundary.

Counters: "nullifier_probe_hits" (device probe masked a lane),
"nullifier_double_spends" (commit-time rejections), and
"nullifier_commits" (accepted + persisted)."""

import hashlib

import numpy as np

from .. import metrics

_TAG = b"coconut-nullifier/v1"
_TAG_V2 = b"coconut-nullifier/v2"
_SPEND_TAG = b"coconut-spend-tag/v1"
_LIMBS = 8  # sha256 = 8 big-endian u32 limbs


def nullifier_of(proof, challenge, epoch, params, domain=None, tag=None):
    """Hex nullifier for one show transcript.

    Unscoped (domain and tag both None): the v1 transcript digest —
    deterministic under replay, fresh under honest re-randomized
    shows. Scoped: a v2 digest over (epoch, domain, material) where
    material is the 32-byte spend `tag` when given (re-spend of the
    same credential collides) or the transcript otherwise (replay-only
    detection, but confined to the domain's keyspace)."""
    e = 0 if epoch is None else int(epoch)
    if domain is None and tag is None:
        return hashlib.sha256(
            _TAG
            + e.to_bytes(4, "big")
            + int(challenge).to_bytes(32, "big")
            + proof.to_bytes(params.ctx)
        ).hexdigest()
    dom = (domain or "").encode("utf-8")
    if tag is not None:
        material = bytes(tag)
        if len(material) != 32:
            raise ValueError("nullifier tag must be exactly 32 bytes")
    else:
        material = (
            int(challenge).to_bytes(32, "big") + proof.to_bytes(params.ctx)
        )
    return hashlib.sha256(
        _TAG_V2
        + e.to_bytes(4, "big")
        + len(dom).to_bytes(2, "big")
        + dom
        + material
    ).hexdigest()


def spend_tag_of(sig_bytes, domain):
    """Deterministic 32-byte spend tag binding a credential to a
    domain: sha256 over the MINTED credential's canonical bytes (which
    never change — shows re-randomize a copy) and the domain string.
    Same credential + same domain -> same tag -> the derived nullifier
    collides on any second spend; a different domain yields an
    unrelated tag, so one credential signs many campaigns."""
    dom = (domain or "").encode("utf-8")
    return hashlib.sha256(
        _SPEND_TAG + len(dom).to_bytes(2, "big") + dom + bytes(sig_bytes)
    ).digest()


def keyspace_of(epoch, domain=None):
    """Nullifier keyspace name for an (epoch, domain) scope (epoch 0 =
    unscoped shows; no domain = the classic fleet-wide keyspace)."""
    e = 0 if epoch is None else int(epoch)
    if domain:
        return "nullifier/%s/%d" % (domain, e)
    return "nullifier/%d" % e


# -- device-resident membership probe ---------------------------------------


def digests_to_limbs(hex_digests):
    """[n, 8] big-endian uint32 limb rows for sha256 hex digests."""
    if not hex_digests:
        return np.zeros((0, _LIMBS), dtype=np.uint32)
    raw = b"".join(bytes.fromhex(d) for d in hex_digests)
    return (
        np.frombuffer(raw, dtype=">u4")
        .reshape(-1, _LIMBS)
        .astype(np.uint32)
    )


def build_table(hex_digests):
    """Sorted, power-of-two-padded limb table. Sentinel rows are
    all-ones (lexicographically above any real digest, probability
    2^-256 aside), so the lower-bound never lands on padding for a
    real query."""
    rows = digests_to_limbs(sorted(set(hex_digests)))
    n = len(rows)
    pad = 1
    while pad < max(1, n):
        pad *= 2
    if pad > n:
        filler = np.full(
            (pad - n, _LIMBS), 0xFFFFFFFF, dtype=np.uint32
        )
        rows = np.concatenate([rows, filler], axis=0)
    return rows, n


def _row_less(a, b, xp):
    """Branchless lexicographic a < b over [m, 8] limb rows."""
    lt = a < b
    eq = a == b
    res = lt[:, 0]
    run = eq[:, 0]
    for j in range(1, _LIMBS):
        res = res | (run & lt[:, j])
        run = run & eq[:, j]
    return res


def membership_probe(table, n_real, queries, xp=np):
    """Boolean hit mask for `queries` ([m, 8] limb rows) against a
    sorted padded `table` ([pad, 8]): fixed-depth branchless binary
    lower-bound, then one gather + row equality. `xp` is numpy or
    jax.numpy — the math is identical; under jnp the whole probe is
    one traced device computation."""
    m = queries.shape[0]
    pad = table.shape[0]
    if m == 0 or n_real == 0:
        return np.zeros((m,), dtype=bool)
    pos = xp.zeros((m,), dtype=xp.int32)
    step = pad
    while step > 1:
        step //= 2
        cand = pos + step
        # advance while table[cand - 1] < query (classic branchless
        # lower bound: pad is a power of two, so log2(pad) rounds)
        go = _row_less(table[cand - 1], queries, xp)
        pos = xp.where(go, cand, pos)
    hit = xp.all(table[pos] == queries, axis=1) & (pos < n_real)
    return np.asarray(hit, dtype=bool)


class NullifierGuard:
    """Check-and-set front for the nullifier keyspaces of a StateStore.

    `probe` is the advisory device pass (fused into the show-verify
    bit); `commit` is the authoritative host pass that WAL-persists
    accepted nullifiers with one group commit per batch."""

    def __init__(self, store, use_device=True):
        self.store = store
        self.use_device = use_device
        # table cache per keyspace, keyed by spent-count (the set only
        # grows, so a stale count means a stale table)
        self._tables = {}

    # -- advisory device probe ----------------------------------------------

    def _table_for(self, ks):
        keys = self.store.keys(ks)
        cached = self._tables.get(ks)
        if cached is not None and cached[0] == len(keys):
            return cached[1], cached[2]
        table, n_real = build_table(keys)
        self._tables[ks] = (len(keys), table, n_real)
        return table, n_real

    def probe(self, hex_digests, epochs=None, domains=None):
        """Per-lane spent flags. Lanes are grouped by (epoch, domain)
        keyspace; each group is one batched device (or numpy-fallback)
        probe."""
        n = len(hex_digests)
        if epochs is None:
            epochs = [None] * n
        if domains is None:
            domains = [None] * n
        xp = np
        if self.use_device:
            try:
                import jax.numpy as jnp

                xp = jnp
            except Exception:  # pragma: no cover - jax is baked in
                xp = np
        out = [False] * n
        by_ks = {}
        for i, (d, e, dom) in enumerate(zip(hex_digests, epochs, domains)):
            by_ks.setdefault(keyspace_of(e, dom), []).append((i, d))
        for ks, lanes in by_ks.items():
            table, n_real = self._table_for(ks)
            if n_real == 0:
                continue
            queries = digests_to_limbs([d for _, d in lanes])
            if xp is not np:
                table = xp.asarray(table)
                queries = xp.asarray(queries)
            hits = membership_probe(table, n_real, queries, xp=xp)
            for (i, _), h in zip(lanes, hits):
                if h:
                    out[i] = True
        n_hits = sum(out)
        if n_hits:
            metrics.count("nullifier_probe_hits", n_hits)
        return out

    # -- epoch retirement ----------------------------------------------------

    def retire_epoch(self, epoch):
        """Drop a retired epoch's nullifier keyspace wholesale and
        compact the WAL underneath it. Safe because the engine refuses
        retired-epoch shows at submit time (EpochRetiredError) BEFORE
        any membership probe — the set's memory is dead weight the
        moment the epoch leaves the verification window. Domain-scoped
        keyspaces of the same epoch (suffix "/<epoch>") are dropped
        alongside the classic one. Returns the number of nullifiers
        compacted away."""
        e = 0 if epoch is None else int(epoch)
        suffix = "/%d" % e
        victims = [
            ks
            for ks in self.store.keyspaces()
            if ks.startswith("nullifier/") and ks.endswith(suffix)
        ]
        victims.append(keyspace_of(epoch))
        n = 0
        for ks in dict.fromkeys(victims):
            n += self.store.drop_keyspace(ks)
            self._tables.pop(ks, None)
        if n:
            metrics.count("state_nullifiers_compacted", n)
        return n

    # -- authoritative commit -----------------------------------------------

    def seen(self, hex_digest, epoch=None, domain=None):
        return self.store.seen(keyspace_of(epoch, domain), hex_digest)

    def commit(self, hex_digests, epochs=None, accept=None, domains=None):
        """Check-and-set under the store lock: for every lane with
        accept[i] truthy, re-check the live set and the batch itself;
        genuinely-new nullifiers are WAL-appended with ONE fsync per
        keyspace group BEFORE this returns. Returns per-lane booleans:
        True = accepted and durable, False = double spend (or the lane
        was not accepted to begin with)."""
        n = len(hex_digests)
        if epochs is None:
            epochs = [None] * n
        if domains is None:
            domains = [None] * n
        if accept is None:
            accept = [True] * n
        ok = [False] * n
        with self.store._lock:
            fresh = {}  # ks -> (epoch, [(key, value), ...])
            batch_seen = set()
            for i, (d, e, dom) in enumerate(
                zip(hex_digests, epochs, domains)
            ):
                if not accept[i]:
                    continue
                ks = keyspace_of(e, dom)
                if (ks, d) in batch_seen or self.store.seen(ks, d):
                    metrics.count("nullifier_double_spends")
                    continue
                batch_seen.add((ks, d))
                fresh.setdefault(ks, (e, []))[1].append((d, 1))
                ok[i] = True
            for ks, (e, items) in fresh.items():
                self.store.put_many(ks, items, epoch=e, fsync=True)
        n_ok = sum(ok)
        if n_ok:
            metrics.count("nullifier_commits", n_ok)
        return ok
