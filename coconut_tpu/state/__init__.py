"""Durable fleet state plane (PR 17).

`atomic`    — the ONE shared tmp+fsync+os.replace crash-atomic
              replacement helper (extracted from stream.py and
              engine/lifecycle.py, reused by everything below).
`wal`       — per-replica append-only CRC-framed write-ahead log:
              group-commit fsync, torn-tail truncation, bounded
              segment rotation.
`store`     — snapshot+replay StateStore over the WAL: named
              keyspaces, per-origin monotonic apply indices,
              last-writer-wins by (epoch, apply-index, origin),
              compaction = snapshot + WAL reset.
`replicate` — gossip-piggybacked anti-entropy: beacons carry
              per-keyspace high-water marks, gaps are pulled and
              applied idempotently.
`nullifier` — the first real consumer: the epoch-scoped double-spend
              set (device-resident batched membership probe + host
              authoritative WAL-backed check-and-set).

See README "Durable state & double-spend detection" for the record
format and recovery invariants."""

from .atomic import fsync_dir, replace_file, replace_json
from .nullifier import (
    NullifierGuard,
    build_table,
    digests_to_limbs,
    keyspace_of,
    membership_probe,
    nullifier_of,
    spend_tag_of,
)
from .replicate import StateReplicator
from .store import SNAPSHOT_SCHEMA, StateStore
from .wal import (
    DEFAULT_KEEP,
    DEFAULT_SEGMENT_BYTES,
    FRAME_HEADER_BYTES,
    WriteAheadLog,
    frame_record,
    scan_frames,
)

__all__ = [
    "DEFAULT_KEEP",
    "DEFAULT_SEGMENT_BYTES",
    "FRAME_HEADER_BYTES",
    "NullifierGuard",
    "SNAPSHOT_SCHEMA",
    "StateReplicator",
    "StateStore",
    "WriteAheadLog",
    "build_table",
    "digests_to_limbs",
    "frame_record",
    "fsync_dir",
    "keyspace_of",
    "membership_probe",
    "nullifier_of",
    "replace_file",
    "replace_json",
    "scan_frames",
    "spend_tag_of",
]
