"""Gossip-piggybacked anti-entropy replication of WAL entries (PR 17).

Replication is ASYNC and pull-based, riding the machinery the fleet
already has instead of adding a consensus layer:

  - every beacon (net/wire.py, WIRE v3) piggybacks the sender's
    per-keyspace high-water marks — ((keyspace, origin, seq), ...)
    straight from `StateStore.marks()`;
  - the gossip HealthDirectory retains the latest marks per replica
    (`state_marks(rid)`, same retention pattern as epoch windows);
  - `StateReplicator.step()` compares every peer's advertised marks
    with the local store and, for each gap (remote seq > local mark),
    issues a MSG_STATE_PULL for the missing page and applies it via
    `StateStore.apply_remote` — idempotent, so overlapping pulls and
    redelivery are harmless. Counted under "state_antientropy_pulls" /
    "state_records_applied".

Because a replica serves records it merely REPLICATED (per-origin logs
in the store), facts spread transitively: replica A witnesses a show,
B pulls it from A, C can pull it from B after A is SIGKILLed. That
transitivity is what the kill-the-witness drill exercises.

Conflict resolution is the store's LWW by (epoch, apply-index,
origin); the replicator never interprets values.

Fault seam: `faults.ReplicationChaos.drop(peer, keyspace)` — a chaos
schedule can swallow pulls to model a partitioned anti-entropy path;
dropped pulls are simply retried on a later `step()`, demonstrating
convergence-after-heal."""

import threading

from .. import metrics


class StateReplicator:
    """Periodic anti-entropy puller for one replica's StateStore.

    `clients` maps replica id -> an object with
    `pull_state(keyspace, origin, after_seq, limit)` returning an
    iterable of record dicts (GatewayClient in production, anything
    duck-typed in tests). `directory` is a gossip HealthDirectory (or
    anything with `state_marks(rid)`)."""

    def __init__(
        self,
        store,
        directory,
        clients,
        interval_s=0.25,
        page=512,
        chaos=None,
        clock=None,
    ):
        self.store = store
        self.directory = directory
        self.clients = clients
        self.interval_s = interval_s
        self.page = page
        self.chaos = chaos
        self.clock = clock
        self._stop = threading.Event()
        self._thread = None

    # -- one anti-entropy round ----------------------------------------------

    def _gaps(self, peer):
        """(keyspace, origin, remote_seq, local_seq) for every mark
        where the peer advertises records we have not applied."""
        marks = self.directory.state_marks(peer)
        out = []
        for ks, origin, seq in marks:
            local = dict(
                (o, s)
                for k, o, s in self.store.marks()
                if k == ks
            ).get(origin, 0)
            if seq > local:
                out.append((ks, origin, seq, local))
        return out

    def step(self):
        """Pull every visible gap once. Returns records applied."""
        applied = 0
        for peer, client in list(self.clients.items()):
            if peer == self.store.replica_id:
                continue
            try:
                gaps = self._gaps(peer)
            except Exception:
                continue
            for ks, origin, remote_seq, local_seq in gaps:
                if self.chaos is not None and self.chaos.drop(
                    peer, ks
                ):
                    metrics.count("state_antientropy_dropped")
                    continue
                after = local_seq
                # page until the advertised mark is reached (or the
                # peer stops making progress — a concurrently
                # compacting peer still serves from its rebuilt logs)
                while after < remote_seq:
                    try:
                        recs = client.pull_state(
                            ks, origin, after, self.page
                        )
                    except Exception:
                        # peer died mid-pull: another peer (or a
                        # later step) will serve the same records
                        break
                    metrics.count("state_antientropy_pulls")
                    recs = list(recs)
                    if not recs:
                        break
                    applied += self.store.apply_remote(recs)
                    after = max(r["s"] for r in recs)
        return applied

    # -- background loop -----------------------------------------------------

    def start(self):
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run,
            name="state-replicator-%s" % self.store.replica_id,
            daemon=True,
        )
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.step()
            except Exception:  # pragma: no cover - belt and braces
                metrics.count("state_replicator_errors")

    def stop(self):
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=5.0)
        self._thread = None
