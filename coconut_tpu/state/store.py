"""Snapshot + replay state store over the per-replica WAL (PR 17).

The StateStore is the fleet's durability seam: named keyspaces of
key -> record maps where every mutation is WAL-appended BEFORE it is
applied in memory, so the in-memory image is always reconstructible as
snapshot + replay. Consumers (`state/nullifier.py`, the EpochRegistry
journal, TenantTable quota counters, the dead-letter index) never
touch the WAL directly — they `put`/`put_many`/`get` and the store
owns framing, recovery, compaction, and replication bookkeeping.

Record model (one JSON object per WAL frame, compact keys):

    {"ks": keyspace, "k": key, "v": value, "o": origin replica id,
     "s": per-(keyspace, origin) monotonic apply index,
     "e": epoch or null, "t": 0|1 tombstone}

Conflict rule: last-writer-wins by (epoch, apply-index, origin) — a
record with a higher epoch beats any lower-epoch record, ties resolve
by apply index then lexicographic origin, so every replica converges
to the same winner regardless of apply order. `apply_remote` is
idempotent: a record at or below the origin's high-water mark is a
no-op, which is what makes "replay a pre-compaction WAL over a
post-snapshot image" safe.

Replication surface: `marks()` is the per-keyspace high-water map the
beacon piggybacks; `records_after(ks, origin, after_seq)` serves
anti-entropy pulls from the per-origin ordered logs (a replica can
relay records it merely replicated, so a killed witness's facts keep
spreading — the kill-the-witness drill depends on exactly this).

Compaction: `snapshot()` writes the full image + marks + per-origin
logs crash-atomically (state/atomic.py, CRC-checked like PR 7 stream
checkpoints — a corrupt snapshot is quarantined and the store falls
back to WAL replay alone); `compact()` = snapshot, then WAL reset.
Crash points "store.mid_snapshot" (before the atomic replace: old
snapshot + full WAL survive) and "store.mid_compact" (snapshot taken,
WAL not yet reset: replay over the snapshot is idempotent) are
enumerated by tests/test_state.py."""

import json
import os
import threading
import zlib

from .. import metrics
from .atomic import replace_json
from .wal import WriteAheadLog

SNAPSHOT_SCHEMA = 1


def _rank(rec):
    """LWW total order: (epoch, apply index, origin). Epoch None ranks
    below every real epoch (epoch-scoped facts beat legacy ones)."""
    e = rec["e"]
    return (-1 if e is None else e, rec["s"], rec["o"])


class StateStore:
    """Durable keyspace/key/value store: WAL-append before apply,
    snapshot+replay recovery, per-origin logs for anti-entropy."""

    def __init__(
        self,
        root,
        replica_id="r0",
        segment_bytes=None,
        keep=None,
        chaos=None,
    ):
        self.root = str(root)
        self.replica_id = replica_id
        self.chaos = chaos
        self._lock = threading.RLock()
        self._data = {}  # ks -> {key -> rec}
        self._marks = {}  # ks -> {origin -> highest applied seq}
        self._log = {}  # (ks, origin) -> [rec, ...] ordered by seq
        os.makedirs(self.root, exist_ok=True)
        self.snap_path = os.path.join(self.root, "store.snap")
        self._load_snapshot()
        kw = {}
        if segment_bytes is not None:
            kw["segment_bytes"] = segment_bytes
        if keep is not None:
            kw["keep"] = keep
        self.wal = WriteAheadLog(
            os.path.join(self.root, "wal.log"), chaos=chaos, **kw
        )
        for payload in self.wal.replay():
            # replay is idempotent against the snapshot: records at or
            # below the snapshot's marks are skipped by _apply_locked
            self._apply_locked(json.loads(payload.decode("utf-8")))

    # -- crash points --------------------------------------------------------

    def _fault(self, point):
        if self.chaos is not None:
            self.chaos.crash(point)

    # -- recovery ------------------------------------------------------------

    def _load_snapshot(self):
        if not os.path.exists(self.snap_path):
            return
        try:
            with open(self.snap_path, "r") as f:
                doc = json.load(f)
            body = doc["body"]
            blob = json.dumps(body, sort_keys=True).encode("utf-8")
            if doc["crc"] != zlib.crc32(blob):
                raise ValueError("snapshot CRC mismatch")
            if body["schema"] != SNAPSHOT_SCHEMA:
                raise ValueError(
                    "snapshot schema %r" % (body["schema"],)
                )
        except (OSError, ValueError, KeyError, TypeError):
            # same quarantine posture as stream checkpoints: a corrupt
            # snapshot is set aside, never silently trusted, and the
            # store rebuilds from the WAL alone
            metrics.count("state_snapshot_corrupt")
            try:
                os.replace(self.snap_path, self.snap_path + ".corrupt")
            except OSError:  # pragma: no cover - platform-dependent
                pass
            return
        for rec in body["records"]:
            self._apply_locked(rec, count=False)
        metrics.count("state_snapshot_loads")

    # -- apply ---------------------------------------------------------------

    def _apply_locked(self, rec, count=True):
        """Apply one record. Idempotent: seq at or below the origin's
        mark is a no-op. Returns True if the record was new."""
        ks, origin, seq = rec["ks"], rec["o"], rec["s"]
        marks = self._marks.setdefault(ks, {})
        if seq <= marks.get(origin, 0):
            return False
        marks[origin] = seq
        self._log.setdefault((ks, origin), []).append(rec)
        space = self._data.setdefault(ks, {})
        old = space.get(rec["k"])
        if old is None or _rank(rec) > _rank(old):
            space[rec["k"]] = rec
        if count:
            metrics.count("state_records_applied")
        return True

    # -- local mutation (WAL-append before apply) ----------------------------

    def _make_rec(self, keyspace, key, value, epoch, tombstone):
        marks = self._marks.setdefault(keyspace, {})
        seq = marks.get(self.replica_id, 0) + 1
        return {
            "ks": keyspace,
            "k": key,
            "v": value,
            "o": self.replica_id,
            "s": seq,
            "e": epoch,
            "t": 1 if tombstone else 0,
        }

    def put_many(self, keyspace, items, epoch=None, fsync=True):
        """Group commit: ONE WAL fsync for the whole batch, applied in
        memory only after the append returns. `items` is an iterable of
        (key, value). Returns the applied records."""
        with self._lock:
            recs = []
            seq_base = self._marks.setdefault(keyspace, {}).get(
                self.replica_id, 0
            )
            for i, (key, value) in enumerate(items):
                recs.append(
                    {
                        "ks": keyspace,
                        "k": key,
                        "v": value,
                        "o": self.replica_id,
                        "s": seq_base + 1 + i,
                        "e": epoch,
                        "t": 0,
                    }
                )
            if not recs:
                return ()
            self.wal.append_many(
                [
                    json.dumps(r, sort_keys=True).encode("utf-8")
                    for r in recs
                ],
                fsync=fsync,
            )
            for r in recs:
                self._apply_locked(r)
            return tuple(recs)

    def put(self, keyspace, key, value, epoch=None, fsync=True):
        return self.put_many(
            keyspace, [(key, value)], epoch=epoch, fsync=fsync
        )[0]

    def delete(self, keyspace, key, epoch=None, fsync=True):
        """Tombstone a key (the record still replicates — deletion is
        a fact, not an absence)."""
        with self._lock:
            rec = self._make_rec(keyspace, key, None, epoch, True)
            self.wal.append(
                json.dumps(rec, sort_keys=True).encode("utf-8"),
                fsync=fsync,
            )
            self._apply_locked(rec)
            return rec

    # -- reads ---------------------------------------------------------------

    def get(self, keyspace, key, default=None):
        with self._lock:
            rec = self._data.get(keyspace, {}).get(key)
            if rec is None or rec["t"]:
                return default
            return rec["v"]

    def seen(self, keyspace, key):
        with self._lock:
            rec = self._data.get(keyspace, {}).get(key)
            return rec is not None and not rec["t"]

    def keys(self, keyspace):
        with self._lock:
            return tuple(
                k
                for k, rec in self._data.get(keyspace, {}).items()
                if not rec["t"]
            )

    def keyspaces(self):
        with self._lock:
            return tuple(sorted(self._marks))

    # -- replication surface -------------------------------------------------

    def marks(self):
        """Per-keyspace high-water marks as ((ks, origin, seq), ...) —
        the beacon piggyback. Sorted for a deterministic wire image."""
        with self._lock:
            out = []
            for ks in sorted(self._marks):
                for origin in sorted(self._marks[ks]):
                    out.append((ks, origin, self._marks[ks][origin]))
            return tuple(out)

    def records_after(self, keyspace, origin, after_seq, limit=512):
        """Anti-entropy page: records from `origin`'s log in `keyspace`
        with seq > after_seq, oldest first. Serves records this replica
        merely replicated too — facts outlive their witness."""
        with self._lock:
            log = self._log.get((keyspace, origin), ())
            return tuple(
                r for r in log if r["s"] > after_seq
            )[:limit]

    def apply_remote(self, recs):
        """Apply replicated records: WAL-append the new ones (so a
        restart keeps them) then apply. Idempotent. Returns the number
        of records that were new."""
        with self._lock:
            fresh = [
                r
                for r in recs
                if r["s"]
                > self._marks.setdefault(r["ks"], {}).get(r["o"], 0)
            ]
            if not fresh:
                return 0
            self.wal.append_many(
                [
                    json.dumps(r, sort_keys=True).encode("utf-8")
                    for r in fresh
                ]
            )
            n = 0
            for r in fresh:
                if self._apply_locked(r):
                    n += 1
            return n

    # -- compaction ----------------------------------------------------------

    def snapshot(self):
        """Crash-atomically persist the full image (records in per-
        origin order, so both `_data` and `records_after` rebuild)."""
        with self._lock:
            records = []
            for key in sorted(self._log):
                records.extend(self._log[key])
            body = {
                "schema": SNAPSHOT_SCHEMA,
                "replica": self.replica_id,
                "records": records,
            }
            blob = json.dumps(body, sort_keys=True).encode("utf-8")
            self._fault("store.mid_snapshot")
            replace_json(
                self.snap_path,
                {"crc": zlib.crc32(blob), "body": body},
            )
            metrics.count("state_snapshots")

    def drop_keyspace(self, keyspace):
        """Retire a whole keyspace: remove its image, marks, and
        per-origin logs, then compact so the WAL no longer carries the
        dropped records either (epoch retirement wants the nullifier
        set's memory gone wholesale, not tombstoned key-by-key).
        Returns the number of live (non-tombstone) keys dropped."""
        with self._lock:
            space = self._data.pop(keyspace, {})
            self._marks.pop(keyspace, None)
            for key in [k for k in self._log if k[0] == keyspace]:
                del self._log[key]
            n = sum(1 for rec in space.values() if not rec["t"])
            # the snapshot inside compact() is rebuilt from _log, so
            # the dropped keyspace vanishes from disk atomically too
            self.compact()
            metrics.count("state_keyspaces_dropped")
            return n

    def compact(self):
        """snapshot + WAL reset. A crash between the two leaves the
        snapshot AND the full WAL — replay is idempotent, so the next
        open converges to the same image with zero duplicates."""
        with self._lock:
            self.snapshot()
            self._fault("store.mid_compact")
            self.wal.reset()
            metrics.count("state_compactions")

    def close(self):
        self.wal.close()
