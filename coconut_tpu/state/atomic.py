"""Crash-atomic file replacement: ONE shared implementation of the
tmp + fsync + os.replace + directory-fsync dance (PR 17).

Before this module the repo carried two hand-rolled copies of the
pattern — stream.StreamState.save (the PR 7 checkpoint discipline:
fixed sibling tmp, file fsync, atomic rename, directory-entry fsync)
and engine/lifecycle.ShapeManifest.save (pid-suffixed tmp, NO fsync —
a crash between rename and the next sync could lose the manifest the
rename claimed to persist). Both now call `replace_file` /
`replace_json`, and the WAL/StateStore snapshots (state/wal.py,
state/store.py) ride the same helper, so the crash-atomicity argument
lives in exactly one place:

  - the WHOLE document is written to `<path>.tmp` (a fixed sibling:
    a crash mid-write leaves at most one stale tmp, truncated by the
    next save and invisible to readers, which only ever open `path`);
  - the tmp is flushed and fsync'd BEFORE the rename, so the rename
    can never expose a file whose bytes are still in the page cache;
  - os.replace is atomic on POSIX: a reader sees the old complete
    file or the new complete file, never torn bytes;
  - the directory entry is fsync'd afterwards (best-effort: some
    filesystems refuse O_RDONLY directory fsync — the try/except is
    deliberate and matches the original checkpoint code), so the
    rename itself survives a power cut.

`fsync=False` skips both syncs for callers on a lazy-durability
contract (e.g. tenant quota counters, where losing the last few
increments on a crash is acceptable) while keeping the torn-file
atomicity guarantee."""

import json
import os


def fsync_dir(dirname):
    """Best-effort fsync of a directory entry (persists a rename)."""
    try:
        dfd = os.open(dirname or ".", os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:  # pragma: no cover - platform-dependent
        pass


def replace_file(path, data, fsync=True):
    """Atomically replace `path` with `data` (bytes or str). Returns
    `path`. Parent directories are created on demand."""
    path = str(path)
    dirn = os.path.dirname(os.path.abspath(path))
    if dirn:
        os.makedirs(dirn, exist_ok=True)
    tmp = path + ".tmp"
    mode = "wb" if isinstance(data, bytes) else "w"
    with open(tmp, mode) as f:
        f.write(data)
        f.flush()
        if fsync:
            os.fsync(f.fileno())
    os.replace(tmp, path)  # atomic on POSIX
    if fsync:
        fsync_dir(dirn)
    return path


def replace_json(path, doc, sort_keys=False, fsync=True):
    """Atomically replace `path` with `doc` serialized as JSON."""
    return replace_file(
        path, json.dumps(doc, sort_keys=sort_keys), fsync=fsync
    )
