"""Per-replica append-only write-ahead log (PR 17).

One WAL = one active segment file plus a bounded chain of rotated
segments (`<path>.1` newest rotated .. `<path>.<keep>` oldest — the
same keep-N naming the dead-letter/flight rotation uses). Records are
length-prefixed and CRC-framed:

    offset  size  field
    0       4     length   u32 big-endian payload byte count
    4       4     crc32    zlib.crc32(payload)
    8       len   payload  opaque bytes (the StateStore writes one
                           JSON-encoded record per frame)

Durability contract:

  - `append` / `append_many` write the frame(s), flush, and fsync —
    ONE fsync per call, so a batch of records group-commits at one
    disk-flush cost (`append_many` is the show-verify demux path's
    per-batch group commit; "wal_fsyncs" vs "wal_appends" is the
    auditable proof that the policy is per-batch, not per-lane);
  - a crash mid-append leaves a TORN TAIL: a trailing frame whose
    length prefix is incomplete, whose payload is short, or whose CRC
    disagrees. `open` scans from the start, keeps the longest valid
    prefix, truncates the tail IN PLACE exactly once (counted under
    "wal_torn_tails") and the store replays only acknowledged records
    — an unacknowledged append can vanish, an acknowledged one cannot
    (the fsync returned before the caller's future resolved);
  - `rotate_if_needed` bounds the active segment: past
    `segment_bytes` it shifts the chain (`.1` -> `.2`, ..., dropping
    beyond `keep`) and starts a fresh active segment. Compaction
    (StateStore.compact: snapshot then `reset`) is the primary bound;
    rotation is the backstop for a store that never compacts.

Fault seams (faults.WalChaos): `torn_on` append indices write only a
PREFIX of the frame then raise (a kill mid-record), `fsync_fail_on`
indices raise OSError from the sync (a dying disk), and `crash(point)`
fires the crash-point callback at the named seam — the crash-point
enumeration suite (tests/test_state.py) kills a store at every one and
asserts replay converges."""

import os
import struct
import zlib

from .. import metrics

_FRAME = struct.Struct(">II")  # length, crc32
FRAME_HEADER_BYTES = _FRAME.size  # 8

#: default active-segment size bound (rotation backstop)
DEFAULT_SEGMENT_BYTES = 8 << 20
#: rotated segments kept (newest .1 .. oldest .keep)
DEFAULT_KEEP = 4


def frame_record(payload):
    """One framed WAL record: u32 length + u32 crc32 + payload."""
    return _FRAME.pack(len(payload), zlib.crc32(payload)) + payload


def scan_frames(raw):
    """(payloads, valid_bytes): the longest valid record prefix of
    `raw` and its byte length. Anything past `valid_bytes` is a torn
    tail (incomplete header, short payload, or CRC mismatch)."""
    payloads, off = [], 0
    n = len(raw)
    while off + FRAME_HEADER_BYTES <= n:
        length, crc = _FRAME.unpack_from(raw, off)
        end = off + FRAME_HEADER_BYTES + length
        if end > n:
            break  # short payload: torn tail
        payload = raw[off + FRAME_HEADER_BYTES : end]
        if zlib.crc32(payload) != crc:
            break  # corrupt frame: everything after is unreachable
        payloads.append(payload)
        off = end
    return payloads, off


class WriteAheadLog:
    """Append-only CRC-framed log with torn-tail recovery and bounded
    rotation. NOT thread-safe on its own — the StateStore serializes
    every append/replay/reset under its lock."""

    def __init__(
        self,
        path,
        segment_bytes=DEFAULT_SEGMENT_BYTES,
        keep=DEFAULT_KEEP,
        chaos=None,
    ):
        self.path = str(path)
        self.segment_bytes = segment_bytes
        self.keep = keep
        #: faults.WalChaos (or None): torn-write / fsync-failure /
        #: crash-point injection, indexed by the append counter
        self.chaos = chaos
        self.appends = 0
        dirn = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(dirn, exist_ok=True)
        self._truncate_torn_tail()
        self._f = open(self.path, "ab")

    # -- recovery ------------------------------------------------------------

    def _segments(self):
        """Every existing segment path, oldest first, active last."""
        chain = [
            "%s.%d" % (self.path, k)
            for k in range(self.keep, 0, -1)
        ]
        return [p for p in chain if os.path.exists(p)] + (
            [self.path] if os.path.exists(self.path) else []
        )

    def _truncate_torn_tail(self):
        """Drop a torn tail from the ACTIVE segment, exactly once per
        open, under the "wal_torn_tails" counter. Rotated segments were
        sealed by a successful rotation and are never truncated."""
        if not os.path.exists(self.path):
            return
        with open(self.path, "rb") as f:
            raw = f.read()
        _, valid = scan_frames(raw)
        if valid < len(raw):
            with open(self.path, "r+b") as f:
                f.truncate(valid)
                f.flush()
                os.fsync(f.fileno())
            metrics.count("wal_torn_tails")

    def replay(self):
        """Every acknowledged payload, oldest segment first. Counted
        under "wal_replayed_records"."""
        out = []
        for seg in self._segments():
            with open(seg, "rb") as f:
                payloads, _ = scan_frames(f.read())
            out.extend(payloads)
        metrics.count("wal_replayed_records", len(out))
        return out

    # -- append path ---------------------------------------------------------

    def _fault(self, point):
        if self.chaos is not None:
            self.chaos.crash(point)

    def append_many(self, payloads, fsync=True):
        """Group commit: frame and write every payload, then flush and
        fsync ONCE. The per-batch WAL policy — N accepted show-verify
        lanes cost one disk flush, not N."""
        payloads = list(payloads)
        if not payloads:
            return 0
        self._fault("wal.pre_append")
        for payload in payloads:
            idx = self.appends
            self.appends += 1
            frame = frame_record(payload)
            if self.chaos is not None and idx in self.chaos.torn_on:
                # torn-write injection: half the frame reaches the
                # disk, then the "process" dies mid-record
                self._f.write(frame[: max(1, len(frame) // 2)])
                self._f.flush()
                os.fsync(self._f.fileno())
                self.chaos.torn_writes += 1
                raise self.chaos.error(
                    "injected torn write on WAL append #%d" % idx
                )
            self._f.write(frame)
        metrics.count("wal_appends", len(payloads))
        self._fault("wal.post_append")
        self._f.flush()
        if fsync:
            if self.chaos is not None and self.chaos.fsync_fails():
                raise OSError("injected WAL fsync failure")
            os.fsync(self._f.fileno())
            metrics.count("wal_fsyncs")
        self.rotate_if_needed()
        return len(payloads)

    def append(self, payload, fsync=True):
        return self.append_many([payload], fsync=fsync)

    def sync(self):
        self._f.flush()
        os.fsync(self._f.fileno())
        metrics.count("wal_fsyncs")

    # -- bounding ------------------------------------------------------------

    def rotate_if_needed(self):
        """Shift the segment chain when the active segment crosses the
        bound: .keep is dropped, .k -> .k+1, active -> .1, and a fresh
        active segment opens. Sealed segments are never rewritten, so
        recovery only ever truncates the active one."""
        if self._f.tell() < self.segment_bytes:
            return False
        self._f.close()
        drop = "%s.%d" % (self.path, self.keep)
        if os.path.exists(drop):
            os.remove(drop)
        for k in range(self.keep - 1, 0, -1):
            src = "%s.%d" % (self.path, k)
            if os.path.exists(src):
                os.replace(src, "%s.%d" % (self.path, k + 1))
        os.replace(self.path, self.path + ".1")
        self._f = open(self.path, "ab")
        metrics.count("wal_segments_rotated")
        return True

    def reset(self):
        """Drop every record (post-snapshot compaction): truncate the
        active segment and remove the rotated chain. Crash-safe against
        the snapshot: the store snapshots BEFORE resetting, and replay
        of a pre-reset WAL over a post-snapshot store is idempotent
        (apply indices make re-applied records no-ops)."""
        self._f.close()
        for k in range(1, self.keep + 1):
            seg = "%s.%d" % (self.path, k)
            if os.path.exists(seg):
                os.remove(seg)
        self._f = open(self.path, "wb")
        self._f.flush()
        os.fsync(self._f.fileno())

    def size_bytes(self):
        return sum(os.path.getsize(p) for p in self._segments())

    def close(self):
        if not self._f.closed:
            self._f.close()
