"""Thread-safe request queue: per-request futures, priority lanes, and
bounded-depth admission control.

The online path's front door. Client threads `submit()` individual
show/verify requests; the batcher (serve/batcher.py) pops them in
device-sized groups. Three properties the offline stream never needed:

  - PER-REQUEST FUTURES: every request carries a `ServeFuture` the client
    blocks on; the supervisor resolves it with the request's own verdict
    (or an exception) after demux. A future always resolves — drain,
    shutdown, and worker-crash paths all sweep stragglers.

  - PRIORITY LANES: "interactive" requests (a user at a turnstile) pop
    before "bulk" ones (a ledger backfill) within every coalesced batch,
    so bulk traffic can saturate the device without starving the latency-
    sensitive lane. FIFO within a lane, so each lane's head is its oldest
    request and the earliest deadline is min over the two heads.

  - BOUNDED-DEPTH ADMISSION CONTROL: `submit()` raises
    `ServiceOverloadedError` (errors.py) the moment the queue holds
    `max_depth` requests. Rejecting loudly at the front door is the only
    stable overload behavior — an unbounded queue converts overload into
    unbounded latency for EVERY request and an eventual OOM, while a
    typed error lets the client back off, shed load, or route elsewhere.
    Counters: "serve_admitted" / "serve_rejected".

Time comes from an injectable `clock` (default time.monotonic) so deadline
logic is testable with a fake clock and zero real sleeps; `kick()` wakes
the batcher to re-read the clock after a test advances it.

TRACING (coconut_tpu/obs, COCONUT_TRACE=1): admission is where a
request's trace is BORN — `submit()` starts the per-request root span
("request") plus its "queue_wait" child, and stamps the trace_id onto the
returned ServeFuture so a client can join its verdict (or a dead-letter
line) back to the trace. Rejected submissions allocate nothing: no
admission, no trace. With tracing disabled every hook is the shared no-op
span — zero allocations on the admission path.
"""

import threading
import time
from collections import deque

from .. import metrics
from ..errors import ServiceClosedError, ServiceOverloadedError
from ..obs import trace as otrace

#: priority lanes, pop order: interactive requests coalesce ahead of bulk
LANES = ("interactive", "bulk")

#: default per-request coalescing deadline (ms) when the submitter gives none
DEFAULT_MAX_WAIT_MS = 20.0


class ServeFuture:
    """Single-assignment result slot a client thread blocks on.

    Resolves exactly once, with either a verdict (`set_result`) or an
    exception (`set_exception`); later resolutions are ignored so the
    supervisor's crash-sweep can never clobber a real verdict. `result()`
    returns the verdict or re-raises the stored exception.

    `add_done_callback` registers a fire-once completion hook (called
    with the future, on the resolving thread — or immediately on the
    caller's thread if already resolved): the seam the RPC replica server
    (coconut_tpu/net/rpc.py) uses to write a response frame the moment
    the engine settles, without parking a thread per in-flight request.
    Callback exceptions are contained (counted under
    "future_callback_errors") so a broken hook can never poison the
    settling executor thread."""

    def __init__(self):
        self._done = threading.Event()
        self._result = None
        self._exc = None
        self._cb_lock = threading.Lock()
        self._callbacks = []
        #: trace id of the request this future resolves (None with
        #: tracing disabled) — the join key against trace exports,
        #: flight records, and dead-letter lines
        self.trace_id = None

    def done(self):
        return self._done.is_set()

    def _settle(self, result, exc):
        with self._cb_lock:
            if self._done.is_set():
                return
            self._result = result
            self._exc = exc
            self._done.set()
            callbacks, self._callbacks = self._callbacks, []
        for cb in callbacks:
            self._run_callback(cb)

    def _run_callback(self, cb):
        try:
            cb(self)
        except Exception:
            metrics.count("future_callback_errors")

    def set_result(self, value):
        self._settle(value, None)

    def set_exception(self, exc):
        self._settle(None, exc)

    def add_done_callback(self, fn):
        """Call `fn(self)` exactly once when the future resolves —
        immediately (on this thread) if it already has."""
        with self._cb_lock:
            if not self._done.is_set():
                self._callbacks.append(fn)
                return
        self._run_callback(fn)

    def exception(self, timeout=None):
        """The stored exception (None if the future resolved with a
        verdict); raises TimeoutError if unresolved within `timeout`."""
        if not self._done.wait(timeout):
            raise TimeoutError("request future unresolved")
        return self._exc

    def result(self, timeout=None):
        exc = self.exception(timeout)
        if exc is not None:
            raise exc
        return self._result


class Request:
    """One queued credential-verify request: the credential, its message
    vector, the lane, the coalescing deadline, and the client's future."""

    __slots__ = (
        "sig",
        "messages",
        "lane",
        "max_wait_ms",
        "t_submit",
        "future",
        "span",
        "queue_span",
        "redispatches",
        "program",
    )

    def __init__(self, sig, messages, lane, max_wait_ms, t_submit):
        if lane not in LANES:
            raise ValueError("unknown lane %r (want one of %s)" % (lane, LANES))
        self.sig = sig
        self.messages = messages
        self.lane = lane
        self.max_wait_ms = max_wait_ms
        self.t_submit = t_submit
        self.future = ServeFuture()
        # which engine program this request belongs to (stamped by the
        # owning queue at admission; None for a bare Request, which the
        # engine resolves to its primary program)
        self.program = None
        # times this request was re-placed after its executor crashed or
        # hung (serve/service.py redistribution); capped by the service's
        # max_redispatch so a poisonous batch can't serially kill the pool
        self.redispatches = 0
        # root span + queue-wait child start at ADMISSION (submit sets
        # them after the request clears admission control); both are the
        # shared no-op span while tracing is disabled
        self.span = otrace.NOOP
        self.queue_span = otrace.NOOP

    @property
    def deadline(self):
        """Absolute clock time by which this request wants to be IN a
        flushed batch (submit time + its max_wait_ms budget)."""
        return self.t_submit + self.max_wait_ms / 1000.0


class RequestQueue:
    """Bounded two-lane FIFO with a condition variable shared by submitters
    and the batcher. All waiting/flush policy lives in serve/batcher.py;
    this class owns admission, ordering, and close semantics."""

    def __init__(
        self,
        max_depth=1024,
        clock=time.monotonic,
        metric_ns="serve",
        program=None,
    ):
        """metric_ns: the counter namespace admissions report under —
        "serve" (verify service, the historical names) or "issue" (the
        threshold-issuance service, coconut_tpu/issue/). The queue itself
        is payload-agnostic: `sig` is whatever the owning service coalesces
        (a credential to verify, or an issuance order to blind-sign).
        program: the engine program name stamped onto every admitted
        request (and carried by overload rejections) so heterogeneous
        lanes sharing one executor pool stay attributable."""
        if max_depth < 1:
            raise ValueError("max_depth must be >= 1 (got %r)" % (max_depth,))
        self.max_depth = max_depth
        self.clock = clock
        self.metric_ns = metric_ns
        self.program = program
        self.cond = threading.Condition()
        self.closed = False
        self._lanes = {lane: deque() for lane in LANES}

    # -- submit side --------------------------------------------------------

    def submit(self, sig, messages, lane="interactive", max_wait_ms=None):
        """Admit one request and return its ServeFuture. Raises
        ServiceClosedError after close(), ServiceOverloadedError at the
        admission bound (counted under "serve_rejected")."""
        if max_wait_ms is None:
            max_wait_ms = DEFAULT_MAX_WAIT_MS
        req = Request(sig, messages, lane, max_wait_ms, self.clock())
        req.program = self.program
        with self.cond:
            if self.closed:
                raise ServiceClosedError(
                    "service is draining/shut down: submission refused"
                )
            depth = self._depth_locked()
            if depth >= self.max_depth:
                metrics.count("%s_rejected" % self.metric_ns)
                raise ServiceOverloadedError(
                    depth,
                    self.max_depth,
                    program=self.program,
                    retry_after_s=max_wait_ms / 1000.0,
                )
            req.span = otrace.start_span(
                "request", root=True, lane=lane, max_wait_ms=max_wait_ms
            )
            req.queue_span = otrace.start_span("queue_wait", parent=req.span)
            req.future.trace_id = req.span.trace_id
            self._lanes[lane].append(req)
            metrics.count("%s_admitted" % self.metric_ns)
            self.cond.notify_all()
        return req.future

    def close(self):
        """Stop admitting; wake the batcher so it flushes the remainder."""
        with self.cond:
            self.closed = True
            self.cond.notify_all()

    def kick(self):
        """Wake any batcher wait so it re-reads the clock — used after a
        test's fake clock advances past a deadline."""
        with self.cond:
            self.cond.notify_all()

    # -- batcher side (call with self.cond held) -----------------------------

    def _depth_locked(self):
        return sum(len(d) for d in self._lanes.values())

    def _earliest_deadline_locked(self):
        """Earliest deadline over EVERYTHING queued — not just the lane
        heads: a later arrival with a tighter max_wait_ms budget can owe a
        flush before the (older) head does. O(depth), and depth is bounded
        by admission control. None when empty."""
        earliest = None
        for d in self._lanes.values():
            for req in d:
                if earliest is None or req.deadline < earliest:
                    earliest = req.deadline
        return earliest

    def _pop_locked(self, n):
        """Pop up to n requests, interactive lane first."""
        out = []
        for lane in LANES:
            d = self._lanes[lane]
            while d and len(out) < n:
                out.append(d.popleft())
        return out

    def depth(self):
        with self.cond:
            return self._depth_locked()

    def depths(self):
        """{lane: queued count} — the per-lane backlog readout behind the
        "serve_queue_depth" gauge and the placement policy's view of how
        latency-sensitive the current backlog is."""
        with self.cond:
            return {lane: len(d) for lane, d in self._lanes.items()}

    def drain_pending(self):
        """Pop EVERYTHING queued (the non-draining shutdown path: the
        caller fails these futures with ServiceClosedError)."""
        with self.cond:
            out = self._pop_locked(self._depth_locked())
            self.cond.notify_all()
            return out
