"""Self-healing policy for the device pool: per-executor health state
machine, hung-dispatch watchdog, and graded load-shedding (brownout).

Three independent, individually-injectable policy objects the service
(serve/service.py) composes; none of them touches an executor directly —
they DECIDE, the service ACTS — so every transition is unit-testable with
a fake clock and zero real sleeps.

ExecutorHealth — a circuit breaker per executor::

      HEALTHY --failure--> SUSPECT --failures--> QUARANTINED
         ^                    |                       |
         |<----success--------+              cooldown elapsed
         |                                            v
         +<---- probe_successes probes ---------- PROBATION
                                                      |
                                 probe failure / crash: re-QUARANTINED
                                 with the cooldown ESCALATED (backoff)

    Consecutive batch-level failures (past the PR-2 retry+fallback
    ladder) open the breaker: `suspect_after` failures mark the executor
    SUSPECT, `quarantine_after` QUARANTINE it. A crash or a watchdog
    timeout quarantines immediately. QUARANTINED executors receive no
    placement; once `cooldown_s` elapses the breaker goes HALF-OPEN
    (PROBATION): the placer routes it ONE live probe batch at a time, and
    `probe_successes` consecutive good probes close the breaker back to
    HEALTHY (a failed probe re-quarantines with the cooldown multiplied
    by `cooldown_backoff`, so a persistently bad device backs off toward
    `max_cooldown_s` instead of flapping). Every transition lands as a
    "health" span (obs/) and in the metrics counters/gauges documented in
    metrics.py.

Watchdog — deadline-checks in-flight dispatches. PR-2's retry ladder only
fires when a dispatch RETURNS; a wedged device (or a deadlocked tunnel
RPC) never returns, so the watchdog tracks every dispatch from launch and
`expire()`s the ones that outlive their budget: ``k × EMA`` of that
executor's observed dispatch-to-settle time, clamped to
[min_timeout_s, max_timeout_s], with `initial_timeout_s` covering the
first dispatch (which may pay a jit compile). Expired entries are POPPED
(a hang fires exactly once); the service abandons the stuck executor and
redistributes the hung batch. The clock is injectable: tests drive
expiry by advancing a fake clock, never by sleeping.

BrownoutPolicy — graded load-shedding. Admission control (queue.py) is a
hard bound that doesn't know half the pool is quarantined. The brownout
policy does: when surviving capacity drops below `capacity_threshold` or
queue depth crosses `depth_threshold × max_depth`, bulk-lane submissions
are shed with the typed, retriable `ServiceBrownoutError` (carrying a
pressure-scaled retry-after hint) while interactive traffic stays live
up to the hard admission bound — the bulk backfill retries later; the
user at the turnstile does not.
"""

import threading
import time

from .. import metrics
from ..obs import trace as otrace

#: health states, in escalation order (also the gauge values in
#: "serve_dev<label>_health")
HEALTHY = "healthy"
SUSPECT = "suspect"
QUARANTINED = "quarantined"
PROBATION = "probation"

#: states the placer may route NEW work to (probation additionally limits
#: itself to one half-open probe at a time — enforced by the service,
#: which can see the executor's unsettled-batch count)
ADMISSIBLE_STATES = frozenset({HEALTHY, SUSPECT, PROBATION})


class HealthPolicy:
    """Knobs for the per-executor circuit breaker / probation ladder.

    suspect_after / quarantine_after: consecutive batch-failure counts
    that open the breaker (SUSPECT is the warning shot, QUARANTINED stops
    placement). probe_after_s: initial cooldown before a quarantined
    executor gets a half-open probe window. probe_successes: consecutive
    good probe batches that close the breaker. cooldown_backoff /
    max_cooldown_s: a failed probe (or a crash during probation)
    multiplies the next cooldown, bounded — persistent failures back off
    instead of flapping."""

    def __init__(
        self,
        suspect_after=1,
        quarantine_after=3,
        probe_after_s=5.0,
        probe_successes=2,
        cooldown_backoff=2.0,
        max_cooldown_s=300.0,
    ):
        if suspect_after < 1 or quarantine_after < suspect_after:
            raise ValueError(
                "need 1 <= suspect_after <= quarantine_after (got %r, %r)"
                % (suspect_after, quarantine_after)
            )
        if probe_successes < 1:
            raise ValueError(
                "probe_successes must be >= 1 (got %r)" % (probe_successes,)
            )
        self.suspect_after = suspect_after
        self.quarantine_after = quarantine_after
        self.probe_after_s = probe_after_s
        self.probe_successes = probe_successes
        self.cooldown_backoff = cooldown_backoff
        self.max_cooldown_s = max_cooldown_s


class ExecutorHealth:
    """One executor's breaker state. Thread-safe: settles report from
    executor threads while the watchdog/placer read concurrently."""

    #: bounded per-breaker transition history (journaled + replayed)
    HISTORY_CAP = 16

    def __init__(
        self,
        label,
        policy=None,
        clock=time.monotonic,
        metric_ns="serve",
        gauge_prefix="serve_dev",
        journal=None,
    ):
        """metric_ns / gauge_prefix: the counter namespace and health-gauge
        prefix this breaker reports under — "serve"/"serve_dev" for the
        verify pool (the historical names), "issue"/"issue_auth" for the
        threshold-issuance authority pool (coconut_tpu/issue/). The state
        machine is surface-agnostic; only the telemetry labels differ.

        `journal` (PR 19): optional callable(label, record) invoked
        after every state transition (UNDER the breaker lock — it must
        not call back into the breaker) — the engine wires it to a
        StateStore "health" keyspace so a restarted replica remembers
        which executors were flapping (see ExecutionEngine
        .attach_health_journal)."""
        self.label = label
        self.policy = policy if policy is not None else HealthPolicy()
        self.clock = clock
        self.metric_ns = metric_ns
        self.gauge = "%s%s_health" % (gauge_prefix, label)
        self.state = HEALTHY
        self.consecutive_failures = 0
        self.probe_ok = 0
        self.quarantines = 0  # lifetime open count (for operators)
        self.quarantined_at = None
        self.cooldown_s = self.policy.probe_after_s
        self.last_reason = None
        self.journal = journal
        #: last HISTORY_CAP transitions as (from, to, reason) — the
        #: flap record an operator (or a restart) reads back
        self.history = []
        self._lock = threading.Lock()

    def _transition(self, new, reason):
        old, self.state = self.state, new
        self.last_reason = reason
        self.history.append((old, new, reason))
        del self.history[: -self.HISTORY_CAP]
        metrics.set_gauge(self.gauge, new)
        if self.journal is not None:
            # callers hold self._lock, so hand the journal a prebuilt
            # record instead of letting it call back into the breaker
            try:
                self.journal(self.label, self._record_locked())
            except Exception:
                metrics.count("health_journal_errors")
        if otrace.enabled():
            # instant span: one record per transition, greppable by
            # executor label in the export
            otrace.start_span(
                "health",
                root=True,
                executor=self.label,
                frm=old,
                to=new,
                reason=reason,
            ).end()
        return old, new

    # -- breaker inputs (called by the service) ------------------------------

    def on_success(self):
        """A batch settled cleanly. Returns (old, new) on a state change,
        else None."""
        with self._lock:
            self.consecutive_failures = 0
            if self.state == PROBATION:
                self.probe_ok += 1
                if self.probe_ok >= self.policy.probe_successes:
                    # breaker closes; de-escalate the cooldown so the NEXT
                    # incident starts from the base again
                    self.cooldown_s = self.policy.probe_after_s
                    metrics.count("%s_recovered" % self.metric_ns)
                    return self._transition(
                        HEALTHY, "probe ladder closed the breaker"
                    )
                return None
            if self.state == SUSPECT:
                return self._transition(HEALTHY, "dispatch succeeded")
            return None

    def on_failure(self, reason="batch failure"):
        """A batch failed past retry+fallback (NOT a data rejection — a
        forged credential is the credential's problem, not the device's).
        Returns (old, new) on a state change, else None."""
        with self._lock:
            if self.state == QUARANTINED:
                return None
            if self.state == PROBATION:
                metrics.count("%s_probe_failures" % self.metric_ns)
                return self._quarantine_locked(
                    "probe failed: %s" % reason, escalate=True
                )
            self.consecutive_failures += 1
            if self.consecutive_failures >= self.policy.quarantine_after:
                return self._quarantine_locked(reason, escalate=False)
            if (
                self.state == HEALTHY
                and self.consecutive_failures >= self.policy.suspect_after
            ):
                return self._transition(SUSPECT, reason)
            return None

    def on_crash(self, reason="executor crash"):
        """The executor loop crashed or a dispatch hung (watchdog): the
        breaker opens immediately, whatever the failure count was."""
        with self._lock:
            if self.state == QUARANTINED:
                return None
            if self.state == PROBATION:
                metrics.count("%s_probe_failures" % self.metric_ns)
            return self._quarantine_locked(
                reason, escalate=self.state == PROBATION
            )

    def _quarantine_locked(self, reason, escalate):
        if escalate:
            self.cooldown_s = min(
                self.cooldown_s * self.policy.cooldown_backoff,
                self.policy.max_cooldown_s,
            )
        self.quarantines += 1
        self.quarantined_at = self.clock()
        self.probe_ok = 0
        self.consecutive_failures = 0
        metrics.count("%s_quarantined" % self.metric_ns)
        return self._transition(QUARANTINED, reason)

    # -- half-open promotion (called by the watchdog tick) -------------------

    def try_probation(self, now=None):
        """QUARANTINED -> PROBATION once the cooldown has elapsed; returns
        True iff the promotion happened (the caller revives the executor
        and kicks the placer)."""
        with self._lock:
            if self.state != QUARANTINED:
                return False
            now = self.clock() if now is None else now
            if now - self.quarantined_at < self.cooldown_s:
                return False
            self.probe_ok = 0
            self._transition(
                PROBATION, "cooldown elapsed: half-open probe window"
            )
            return True

    def admissible(self):
        """May the placer route NEW work here at all? (PROBATION is
        additionally limited to one outstanding probe — the service
        enforces that, since it owns the batch count.)"""
        return self.state in ADMISSIBLE_STATES

    # -- durability (PR 19): journal record + replay -------------------------

    def _record_locked(self):
        return {
            "state": self.state,
            "quarantines": self.quarantines,
            "cooldown_s": self.cooldown_s,
            "consecutive_failures": self.consecutive_failures,
            "reason": self.last_reason,
            "history": [list(h) for h in self.history],
        }

    def snapshot_record(self):
        """The journaled, last-writer-wins record for this breaker: one
        dict per executor label, bounded by HISTORY_CAP — compaction is
        structural (overwrite-in-place), not epoch-based."""
        with self._lock:
            return self._record_locked()

    def restore(self, record, now=None):
        """Adopt a journaled record on replica restart. The flap memory
        (lifetime quarantine count, ESCALATED cooldown, history) carries
        over verbatim; live placement state is re-derived conservatively:
        a breaker that died QUARANTINED or PROBATION re-enters
        QUARANTINED with the cooldown clock restarted at `now` (the
        device gets no placement until it re-earns it through the probe
        ladder), while HEALTHY/SUSPECT restart HEALTHY — but with the
        remembered cooldown, so the NEXT incident still backs off from
        where the flapping left off."""
        with self._lock:
            self.quarantines = int(record.get("quarantines", 0))
            self.cooldown_s = min(
                float(record.get("cooldown_s", self.policy.probe_after_s)),
                self.policy.max_cooldown_s,
            )
            self.consecutive_failures = int(
                record.get("consecutive_failures", 0)
            )
            self.history = [
                tuple(h) for h in record.get("history", ())
            ][-self.HISTORY_CAP:]
            prior = record.get("state", HEALTHY)
            self.probe_ok = 0
            if prior in (QUARANTINED, PROBATION):
                self.quarantined_at = (
                    self.clock() if now is None else now
                )
                self._transition(
                    QUARANTINED,
                    "restored from journal (was %s: %s)"
                    % (prior, record.get("reason")),
                )
            else:
                # no transition — HEALTHY is the constructor state and
                # journaling a no-op restore would churn the store
                metrics.set_gauge(self.gauge, self.state)


class Watchdog:
    """Deadline tracker for in-flight device dispatches.

    `begin()` at launch, `end()` at settle (success updates the
    per-executor EMA of dispatch-to-settle time), `expire(now)` pops and
    returns everything past its deadline. Budget per dispatch:
    ``clamp(k * ema, min_timeout_s, max_timeout_s)``, or
    `initial_timeout_s` while no EMA exists yet (the first dispatch may
    pay a jit compile; don't shoot it). All state is behind one lock —
    executor threads begin/end while the watchdog thread expires."""

    def __init__(
        self,
        clock=time.monotonic,
        k=6.0,
        min_timeout_s=1.0,
        initial_timeout_s=600.0,
        max_timeout_s=600.0,
        alpha=0.25,
    ):
        if k <= 0 or alpha <= 0 or alpha > 1:
            raise ValueError("need k > 0 and 0 < alpha <= 1")
        self.clock = clock
        self.k = k
        self.min_timeout_s = min_timeout_s
        self.initial_timeout_s = initial_timeout_s
        self.max_timeout_s = max_timeout_s
        self.alpha = alpha
        self._lock = threading.Lock()
        self._inflight = {}  # (label, seq) -> (deadline, started, reqs, span)
        self._ema = {}  # label -> EMA of successful dispatch durations

    def _budget_locked(self, label):
        ema = self._ema.get(label)
        if ema is None:
            return self.initial_timeout_s
        return min(self.max_timeout_s, max(self.min_timeout_s, self.k * ema))

    def budget(self, label):
        """Current deadline budget for `label`'s next dispatch."""
        with self._lock:
            return self._budget_locked(label)

    def ema(self, label):
        with self._lock:
            return self._ema.get(label)

    def begin(self, label, seq, requests, span=None, now=None):
        now = self.clock() if now is None else now
        with self._lock:
            self._inflight[(label, seq)] = (
                now + self._budget_locked(label),
                now,
                requests,
                span,
            )

    def end(self, label, seq, ok=True, now=None):
        """Dispatch settled. Returns its duration when it both completed
        successfully AND was still tracked (an expired entry was already
        popped — a late settle after a timeout never pollutes the EMA)."""
        now = self.clock() if now is None else now
        with self._lock:
            entry = self._inflight.pop((label, seq), None)
            if entry is None or not ok:
                return None
            dur = max(0.0, now - entry[1])
            prev = self._ema.get(label)
            self._ema[label] = (
                dur if prev is None else self.alpha * dur + (1 - self.alpha) * prev
            )
            return dur

    def forget_label(self, label):
        """Drop every tracked dispatch of `label` (its executor crashed:
        the crash path already owns those batches)."""
        with self._lock:
            gone = [key for key in self._inflight if key[0] == label]
            for key in gone:
                del self._inflight[key]
            return len(gone)

    def expire(self, now=None):
        """Pop and return every overdue dispatch as
        ``(label, seq, requests, span, overdue_s)`` — popping makes each
        hang fire exactly once."""
        now = self.clock() if now is None else now
        out = []
        with self._lock:
            due = [k for k, v in self._inflight.items() if now >= v[0]]
            for key in due:
                deadline, _started, requests, span = self._inflight.pop(key)
                out.append((key[0], key[1], requests, span, now - deadline))
        return out

    def inflight(self):
        with self._lock:
            return len(self._inflight)


class BrownoutPolicy:
    """Graded load-shedding decision: shed the bulk lane first when
    capacity degrades or the queue backs up; interactive traffic rides
    through to the hard admission bound.

    capacity_threshold: brownout when the admissible fraction of the pool
    drops BELOW this. depth_threshold: brownout when queue depth reaches
    this fraction of max_depth. retry_after_s: base of the retry hint the
    typed ServiceBrownoutError carries, scaled up with pressure."""

    def __init__(
        self, capacity_threshold=0.5, depth_threshold=0.75, retry_after_s=0.5
    ):
        if not 0.0 <= capacity_threshold <= 1.0:
            raise ValueError("capacity_threshold must be in [0, 1]")
        if not 0.0 < depth_threshold <= 1.0:
            raise ValueError("depth_threshold must be in (0, 1]")
        self.capacity_threshold = capacity_threshold
        self.depth_threshold = depth_threshold
        self.retry_after_s = retry_after_s

    def check(self, lane, depth, max_depth, capacity_fraction):
        """(active, retry_after_s_or_None): `active` is whether brownout
        conditions hold at all (the "serve_brownout" gauge); the second
        element is non-None iff THIS submission should be shed."""
        overloaded = bool(max_depth) and depth >= self.depth_threshold * max_depth
        degraded = capacity_fraction < self.capacity_threshold
        active = overloaded or degraded
        if not active or lane != "bulk":
            # interactive stays live through brownout; its only shed is
            # the hard admission bound (ServiceOverloadedError)
            return active, None
        pressure = max(
            1.0 - capacity_fraction,
            (depth / max_depth) if max_depth else 0.0,
        )
        return True, round(self.retry_after_s * (1.0 + pressure), 3)
