"""Deadline-driven coalescer: turn a trickle of individual requests into
device-sized batches without blowing per-request latency.

Flush policy (the dynamic-batching rule every inference server converges
on):

  - FULL FLUSH: the moment `max_batch` requests are queued, pop a full
    batch — the device-optimal shape, zero extra waiting.
  - DEADLINE FLUSH: otherwise, flush a PARTIAL batch the moment the
    EARLIEST queued deadline (submit time + that request's `max_wait_ms`)
    expires — a request never waits longer than its own latency budget
    for company, whatever lane or arrival order it had.
  - CLOSE FLUSH: a closed queue flushes whatever remains immediately, so
    drain never strands a request behind a deadline.

Partial batches are PADDED back to `max_batch` with identity-signature
lanes (`sigma_1 = None` — the same identity-lane convention the backends'
`encode_verify_batch(pad_bases_to=...)` path uses for base padding): every
dispatched program keeps the one batch shape, so the jit cache stays hot
instead of compiling a program per occupancy level. Identity lanes verify
False by construction (every backend's `batch_verify` rejects identity
sigma_1) and the demux simply never reads them.

Demux is the inverse of coalescing: the [B] verdict bits come back and
each request's future resolves with ITS lane's bit — one forged credential
fails its own future, not its cohabitants'.

Waiting runs on the queue's condition variable with the wait bounded by
the time to the oldest deadline (and a small poll cap so an injected fake
clock can't strand the waiter); the clock is injectable end-to-end, so the
deadline tests advance time explicitly and never sleep.
"""

import time

from .. import metrics
from ..obs import trace as otrace
from .queue import LANES  # noqa: F401  (re-export for callers)

#: cap on any single condition wait: keeps the batcher responsive to fake
#: clocks and to close() even if a notify is missed
_POLL_CAP_S = 0.05


class _PadCredential:
    """Identity-signature filler for the padded lanes of a partial batch:
    `sigma_1 is None` makes every backend verify the lane False and the
    encode path treat it as the point at infinity."""

    __slots__ = ()
    sigma_1 = None
    sigma_2 = None


PAD_CREDENTIAL = _PadCredential()


class Batcher:
    """Pops deadline-coalesced batches off a serve.queue.RequestQueue.

    `next_batch(block=True)` returns a non-empty list of Requests, or None:
    with block=True, None means the queue is closed AND empty (the
    supervisor's exit signal); with block=False, None just means nothing
    is ready to flush yet (the supervisor uses this to settle in-flight
    work instead of idling)."""

    def __init__(self, queue, max_batch, clock=time.monotonic, metric_ns=None):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1 (got %r)" % (max_batch,))
        self.queue = queue
        self.max_batch = max_batch
        self.clock = clock
        # counter namespace: follow the queue's unless overridden, so the
        # issuance service's coalescing reports under "issue_*"
        self.metric_ns = (
            metric_ns
            if metric_ns is not None
            else getattr(queue, "metric_ns", "serve")
        )

    def _ready_locked(self):
        """(flush_now, wait_s): whether a batch should flush immediately,
        else how long to wait before re-checking."""
        q = self.queue
        n = q._depth_locked()
        if n >= self.max_batch:
            return True, 0.0
        if n > 0:
            if q.closed:
                return True, 0.0
            deadline = q._earliest_deadline_locked()
            left = deadline - self.clock()
            if left <= 0:
                return True, 0.0
            return False, min(left, _POLL_CAP_S)
        return False, _POLL_CAP_S

    def next_batch(self, block=True, ready=None):
        """ready: optional zero-arg predicate consulted before any flush —
        the dispatcher pool's backpressure seam. While it returns False
        the batcher HOLDS the backlog in the queue (where admission
        control can see and bound it) instead of popping work no device
        executor can accept yet; whoever frees capacity must kick() the
        queue so the wait here re-checks. A CLOSED queue bypasses the
        gate: at drain the backlog must flush (the placer's forced-spill
        placement still settles it) rather than park forever behind a
        pool that lost its capacity."""
        q = self.queue
        with q.cond:
            while True:
                if ready is not None and not q.closed and not ready():
                    wait_s = _POLL_CAP_S
                else:
                    flush, wait_s = self._ready_locked()
                    if flush:
                        batch = q._pop_locked(self.max_batch)
                        metrics.count("%s_batches" % self.metric_ns)
                        metrics.count(
                            "%s_batched_requests" % self.metric_ns, len(batch)
                        )
                        for req in batch:
                            # queue_wait ends the moment the request is IN
                            # a coalesced batch — its dur is the admission->
                            # flush latency the per-stage breakdown reports
                            req.queue_span.end(coalesced_with=len(batch))
                        return batch
                if q.closed and q._depth_locked() == 0:
                    return None
                if not block:
                    return None
                q.cond.wait(wait_s)


def pad_batch(requests, max_batch):
    """(sigs, messages_list, n_pad) for a coalesced batch, identity-padded
    up to `max_batch` so the dispatched program shape is constant.

    Pad lanes reuse the first request's message vector (right length for
    the verkey; the identity sigma alone forces the lane False), mirroring
    the identity-lane convention of encode_verify_batch(pad_bases_to=...).
    Counted under "serve_pad_lanes"."""
    sigs = [r.sig for r in requests]
    messages_list = [r.messages for r in requests]
    n_pad = max(0, max_batch - len(requests))
    if n_pad:
        sigs.extend([PAD_CREDENTIAL] * n_pad)
        messages_list.extend([list(requests[0].messages)] * n_pad)
        metrics.count("serve_pad_lanes", n_pad)
        # annotate the active (coalesce) span so a padded flush is
        # visible per-batch in the trace, not only in aggregate
        otrace.event("pad_lanes", n=n_pad)
    return sigs, messages_list, n_pad


def demux(requests, bits, clock=time.monotonic):
    """Resolve each request's future with its own lane's verdict bit
    (padding lanes beyond len(requests) are ignored), recording the
    per-request latency histogram and verdict counters. Each request's
    root span ends here, stamped with its verdict — the trace covers
    admission through verdict delivery."""
    with otrace.span("demux", n=len(requests)):
        now = clock()
        n_valid = 0
        for req, bit in zip(requests, bits):
            ok = bool(bit)
            n_valid += ok
            metrics.observe("serve_latency_s", now - req.t_submit)
            req.span.end(verdict=ok)
            req.future.set_result(ok)
        metrics.count("serve_valid", n_valid)
        metrics.count("serve_invalid", len(requests) - n_valid)


def fail_all(requests, exc, counter="serve_failed_requests"):
    """Resolve every request's future with `exc` (the batch-level failure
    and shutdown paths) — a future must never be left dangling. Request
    spans (root + a possibly still-open queue_wait) end with the error
    class, so abandoned requests are visible in the trace, not dropped."""
    for req in requests:
        req.queue_span.end()
        req.span.end(error=type(exc).__name__)
        req.future.set_exception(exc)
    if requests:
        metrics.count(counter, len(requests))
