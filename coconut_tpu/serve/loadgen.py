"""Closed- and open-loop load generation against a CredentialService, with
the latency/goodput report the serving layer is judged by.

Two arrival disciplines, because they answer different questions:

  - CLOSED loop ("closed"): `concurrency` client threads, each submitting
    its next request the moment the previous verdict lands. Measures the
    service AT SATURATION — batch occupancy and goodput ceilings — the
    way a backfill or a load test drives it.
  - OPEN loop ("open"): one submitter with Poisson (exponential
    inter-arrival) timing at `rate_per_s`, never waiting for verdicts.
    Measures LATENCY UNDER LOAD the way real user traffic does — closed
    loops hide queueing delay because slow responses throttle the
    offered load (the classic coordinated-omission trap).

Each request draws (credential, messages, expected_verdict) from `pool`
(mix valid/forged by building the pool accordingly); verdicts are checked
against expectations so a demux bug shows up as `verdict_mismatches`, not
silently as throughput. Every future is awaited: `dropped_futures` counts
futures that never resolved (must be 0 — the service guarantees it) and
`errors` counts futures that resolved exceptionally.

The report embeds client-observed p50/p95/p99/mean/max latency, goodput
(verdicts delivered per second of wall), mean batch occupancy
(coalesced requests per flushed batch / max_batch, from the metrics
counters' delta over the run), and the admission rejection rate. With
tracing enabled (COCONUT_TRACE=1) it also embeds `stage_breakdown_s` —
the per-stage span totals accumulated DURING the run (queue_wait /
coalesce / dispatch / device / demux), which finally separates "slow
device" from "slow batcher" for the same requests the latency
percentiles describe; null when tracing is off. Against a dispatcher
POOL the report adds `devices` (per-executor dispatch/request/busy-second
deltas with occupancy = busy_s / wall) and `placement` (single vs sharded
routing decisions, plus capacity spills) — the per-device surfaces the
scaling sweep (bench.py BENCH_SERVE_DEVICES) is built from.

MIXED WORKLOAD (`issue_fraction` > 0, with `issue_service`/`issue_pool`):
each arrival is a coin flip between a verify request against `service`
and an ISSUANCE request against the threshold-issuance service
(coconut_tpu/issue) — the deployment shape where one fleet both mints
and verifies credentials. Issuance outcomes are tallied separately (a
minted credential is the truthy verdict; `expect_valid` is always True —
every accepted mint must verify) and the report grows an "issue" section
with its own latency percentiles, goodput, batch occupancy, and the
quorum-health deltas (hedges, discarded partials, quorum-unreachable)
accumulated over the run. Both workloads share the arrival discipline:
under a closed loop they compete for the same client threads, which is
exactly the interference a mixed fleet sees.

RPC TRANSPORT (`transport="rpc"`): point either loadgen at a fleet
gateway client instead of the engine — a net.GatewayClient (one
replica) or net.ReplicaRouter (the fleet front door) already mirrors
the engine's submit_* surface, and the decoded error envelopes re-raise
as the SAME typed exceptions, so the driver logic is shared verbatim.
The report then adds `rpc_overhead_s`: client-observed mean latency
minus the engine-side mean (the delta of the engine's own *_latency_s
histograms over the run) — the wire + framing + routing tax per
request. The full-session driver additionally pins each session to a
stable session id via the router's `bound(session)` seam, which is what
exercises consistent-hash affinity end to end.

AVAILABILITY (PR 14): every verify report embeds an "availability"
section — a per-second goodput/error timeline, `error_free_seconds`,
and the raw settled-future events — plus an errors split into
`errors_retryable` (refusals a caller could resubmit: retryable or
transient types) and `errors_terminal` (everything else). The rolling-
restart drill asserts `errors_terminal == 0` while replicas cycle, and
`restart_to_first_slo(report["availability"], t_mark, slo_s)` turns a
restart timestamp into the restart-to-first-SLO-compliant-response
number the bench lane asserts on.

Determinism knobs: `rng` (arrival jitter + pool sampling), `clock`, and
`sleep` are injectable, so tests can drive the generator without
wall-clock flakiness; the 2-second CI smoke uses the real ones.
"""

import random
import threading
import time

from .. import metrics
from ..errors import (
    ServiceBrownoutError,
    ServiceClosedError,
    ServiceOverloadedError,
    ServiceRetryableError,
    TransientBackendError,
)
from ..obs import trace as otrace


def _stage_totals():
    """{span name: (count, total_s)} snapshot, or None when tracing is
    off — the loadgen reports the DELTA over its run."""
    tracer = otrace.get_tracer()
    if tracer is None:
        return None
    return {
        name: (s["count"], s["total_s"])
        for name, s in tracer.stage_summary().items()
    }


def _stage_delta(before, after):
    """Per-stage {count, total_s, mean_s} accumulated between two
    _stage_totals snapshots."""
    if after is None:
        return None
    before = before or {}
    out = {}
    for name, (count, total) in sorted(after.items()):
        c0, t0 = before.get(name, (0, 0.0))
        dc, dt = count - c0, total - t0
        if dc <= 0:
            continue
        out[name] = {
            "count": dc,
            "total_s": round(dt, 6),
            "mean_s": round(dt / dc, 6),
        }
    return out


def _device_report(before_counts, before_timers, elapsed):
    """Per-device {dispatches, requests, busy_s, occupancy} delta over the
    run, keyed by executor label — nonzero dispatches on EVERY device is
    the pool's "actually scaled out" invariant (bench/ci assert it)."""
    d_counts = metrics.counters_with_prefix("serve_dev")
    d_timers = metrics.timers_with_prefix("serve_dev")
    devices = {}
    for name, value in d_counts.items():
        label, _, field = name[len("serve_dev"):].rpartition("_")
        if field not in ("dispatches", "requests"):
            continue
        delta = value - before_counts.get(name, 0)
        if delta:
            devices.setdefault(label, {})[field] = delta
    for name, value in d_timers.items():
        if not name.endswith("_busy_s"):
            continue
        label = name[len("serve_dev"):-len("_busy_s")]
        busy = value - before_timers.get(name, 0.0)
        if label in devices or busy > 0:
            dev = devices.setdefault(label, {})
            dev["busy_s"] = round(busy, 6)
            dev["occupancy"] = round(min(busy / elapsed, 1.0), 4)
    return devices or None


def _placement_report(before_counts):
    """{single, sharded[, spill]} placement-decision deltas over the run."""
    out = {}
    for kind in ("single", "sharded", "spill"):
        name = "serve_placed_%s" % kind
        delta = metrics.get_count(name) - before_counts.get(name, 0)
        if delta or kind != "spill":
            out[kind] = delta
    return out if (out.get("single") or out.get("sharded")) else None


#: engine-side latency histogram per program (metric namespaces from
#: serve/batcher.py, engine/phases.py, issue/service.py) — the
#: server-side term of the rpc_overhead_s subtraction
_ENGINE_LATENCY_HISTS = (
    "serve_latency_s",   # verify
    "prep_latency_s",    # prepare
    "issue_latency_s",   # mint
    "prove_latency_s",   # show_prove
    "showv_latency_s",   # show_verify
)


def _engine_latency_totals():
    """Summed (count, total_s) over every engine-side latency hist."""
    count, total = 0, 0.0
    for name in _ENGINE_LATENCY_HISTS:
        c, t = metrics.hist_totals(name)
        count += c
        total += t
    return count, total


def _rpc_overhead(transport, client_latencies, eng0, eng1):
    """Client-observed mean latency minus the engine-side mean over the
    run — the per-request wire/framing/routing tax. None for the direct
    transport or when either side completed nothing."""
    if transport != "rpc" or not client_latencies:
        return None
    d_count = eng1[0] - eng0[0]
    d_total = eng1[1] - eng0[1]
    if d_count <= 0:
        return None
    client_mean = sum(client_latencies) / len(client_latencies)
    return round(max(client_mean - d_total / d_count, 0.0), 6)


#: availability events embedded per report — enough for any drill, small
#: enough that a report stays a readable JSON artifact
_MAX_AVAILABILITY_EVENTS = 20000


def _availability(events, t0, elapsed):
    """The drill's availability section: a per-second goodput/error
    timeline plus error-free seconds, built from the tally's settled-
    future events. `events` are (t_absolute, latency_s | None, ok);
    bucket k covers [k, k+1) seconds after t0."""
    seconds = max(1, int(elapsed) + (1 if elapsed > int(elapsed) else 0))
    goodput = [0] * seconds
    errs = [0] * seconds
    for t, _lat, ok in events:
        idx = min(max(int(t - t0), 0), seconds - 1)
        if ok:
            goodput[idx] += 1
        else:
            errs[idx] += 1
    out_events = [
        [round(t - t0, 4), None if lat is None else round(lat, 6), bool(ok)]
        for t, lat, ok in events[:_MAX_AVAILABILITY_EVENTS]
    ]
    return {
        "seconds": seconds,
        "per_second_goodput": goodput,
        "per_second_errors": errs,
        "error_free_seconds": sum(1 for e in errs if e == 0),
        "events": out_events,
        "events_truncated": len(events) > _MAX_AVAILABILITY_EVENTS,
    }


#: public name for the per-second goodput/error timeline builder — the
#: scenario layer (coconut_tpu/scenarios/report.py) builds its
#: availability section on the SAME machinery the serve drills use
#: rather than growing a parallel implementation (PR 19)
availability_timeline = _availability


def restart_to_first_slo(availability, t_mark, slo_s):
    """Seconds from `t_mark` (relative to the run's start, e.g. the
    moment a replica restart began) to the FIRST completion at/after it
    whose latency met `slo_s` — the drill's restart-to-first-SLO-
    compliant-response number. None when no compliant completion
    followed the mark."""
    best = None
    for t, lat, ok in availability["events"]:
        if ok and lat is not None and t >= t_mark and lat <= slo_s:
            if best is None or t < best:
                best = t
    return None if best is None else max(0.0, best - t_mark)


def latency_percentiles(latencies):
    return {
        "p50": metrics.percentile(latencies, 50),
        "p95": metrics.percentile(latencies, 95),
        "p99": metrics.percentile(latencies, 99),
        "mean": (sum(latencies) / len(latencies)) if latencies else None,
        "max": max(latencies) if latencies else None,
    }


class _Tally:
    """Shared, locked accounting across client threads."""

    def __init__(self):
        self.lock = threading.Lock()
        self.latencies = []
        self.submitted = 0
        self.rejected = 0
        self.shed = 0
        self.completed = 0
        self.errors = 0
        self.errors_retryable = 0
        self.errors_terminal = 0
        self.dropped = 0
        self.valid = 0
        self.invalid = 0
        self.mismatches = 0
        #: (t_absolute, latency_s | None, ok) per settled future — the
        #: availability timeline's raw material (drill satellite, PR 14)
        self.events = []

    def settle(self, future, expect_valid, t_submit, clock, timeout):
        """Await one future and fold its outcome in."""
        try:
            verdict = future.result(timeout)
        except TimeoutError:
            with self.lock:
                self.dropped += 1
            return
        except Exception as e:
            now = clock()
            retryable = isinstance(
                e, (ServiceRetryableError, TransientBackendError)
            )
            with self.lock:
                self.errors += 1
                if retryable:
                    # a refusal the caller could resubmit (drain handoff
                    # that ran out of ring, brownout, overload) — the
                    # rolling-restart drill asserts the TERMINAL count
                    # is zero, not this one
                    self.errors_retryable += 1
                else:
                    self.errors_terminal += 1
                self.events.append((now, None, False))
            return
        now = clock()
        dt = now - t_submit
        with self.lock:
            self.completed += 1
            self.latencies.append(dt)
            self.events.append((now, dt, True))
            if verdict:
                self.valid += 1
            else:
                self.invalid += 1
            if bool(verdict) != bool(expect_valid):
                self.mismatches += 1


def run_loadgen(
    service,
    pool,
    duration_s=2.0,
    arrival="closed",
    concurrency=8,
    rate_per_s=100.0,
    lane="interactive",
    rng=None,
    clock=time.monotonic,
    sleep=time.sleep,
    result_timeout=60.0,
    issue_service=None,
    issue_pool=None,
    issue_fraction=0.0,
    transport="direct",
):
    """Drive `service` for `duration_s` and return the report dict.

    pool: non-empty list of (sig, messages, expect_valid) tuples to sample
    from. arrival: "closed" (concurrency threads, submit-on-completion) or
    "open" (Poisson arrivals at rate_per_s, verdicts awaited at the end).
    The service must already be started; it is NOT drained here — callers
    own lifecycle (the bench lane drains after reading the report).

    Mixed workload: with `issue_service` (an issue.IssuanceService) and
    `issue_pool` (a list of (sig_request, messages, elgamal_sk) tuples),
    each arrival routes to issuance with probability `issue_fraction`;
    the report gains an "issue" section. issue_fraction=1.0 drives a
    pure-issuance run (the bench --issue lane).

    transport: "direct" (service IS the engine) or "rpc" (service is a
    net.GatewayClient / net.ReplicaRouter; the report adds
    `rpc_overhead_s` when the replica engines share this process)."""
    if not pool:
        raise ValueError("loadgen pool must be non-empty")
    if arrival not in ("closed", "open"):
        raise ValueError("unknown arrival discipline %r" % (arrival,))
    if transport not in ("direct", "rpc"):
        raise ValueError("unknown transport %r" % (transport,))
    if not 0.0 <= issue_fraction <= 1.0:
        raise ValueError(
            "issue_fraction must be in [0, 1] (got %r)" % (issue_fraction,)
        )
    if issue_fraction > 0.0 and (issue_service is None or not issue_pool):
        raise ValueError(
            "issue_fraction > 0 needs issue_service and a non-empty issue_pool"
        )
    rng = rng if rng is not None else random.Random(0x5E21E)
    tally = _Tally()
    issue_tally = _Tally()
    occ0_reqs = metrics.get_count("serve_batched_requests")
    occ0_batches = metrics.get_count("serve_batches")
    dev0_counts = metrics.counters_with_prefix("serve_dev")
    dev0_timers = metrics.timers_with_prefix("serve_dev")
    placed0 = metrics.counters_with_prefix("serve_placed")
    issue0 = metrics.counters_with_prefix("issue")
    stages0 = _stage_totals()
    eng_lat0 = _engine_latency_totals()
    t0 = clock()
    t_end = t0 + duration_s

    def submit_issue():
        sig_req, messages, elg_sk = issue_pool[rng.randrange(len(issue_pool))]
        t_submit = clock()
        try:
            fut = issue_service.submit(sig_req, messages, elg_sk, lane=lane)
        except ServiceOverloadedError:
            with issue_tally.lock:
                issue_tally.submitted += 1
                issue_tally.rejected += 1
            return None
        except ServiceBrownoutError:
            with issue_tally.lock:
                issue_tally.submitted += 1
                issue_tally.shed += 1
            return None
        except ServiceClosedError:
            return None
        with issue_tally.lock:
            issue_tally.submitted += 1
        # a minted credential is the truthy verdict; every accepted
        # issuance MUST mint (the service's verify-before-release gate
        # makes anything else an error, not an "invalid")
        return fut, True, t_submit, issue_tally

    def submit_one():
        if issue_fraction > 0.0 and rng.random() < issue_fraction:
            return submit_issue()
        sig, messages, expect_valid = pool[rng.randrange(len(pool))]
        t_submit = clock()
        try:
            fut = service.submit(sig, messages, lane=lane)
        except ServiceOverloadedError:
            with tally.lock:
                tally.submitted += 1
                tally.rejected += 1
            return None
        except ServiceBrownoutError:
            # graded load-shedding (retriable, typed): counted apart from
            # hard admission rejections so a report separates "queue
            # full" from "pool degraded, retry later"
            with tally.lock:
                tally.submitted += 1
                tally.shed += 1
            return None
        except ServiceClosedError:
            return None
        with tally.lock:
            tally.submitted += 1
        return fut, expect_valid, t_submit, tally

    if arrival == "closed":

        def client():
            while clock() < t_end:
                sub = submit_one()
                if sub is None:
                    continue
                fut, expect_valid, t_submit, t_acct = sub
                t_acct.settle(fut, expect_valid, t_submit, clock, result_timeout)

        threads = [
            threading.Thread(target=client, name="loadgen-%d" % i)
            for i in range(concurrency)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    else:
        outstanding = []
        while clock() < t_end:
            sub = submit_one()
            if sub is not None:
                outstanding.append(sub)
            sleep(rng.expovariate(rate_per_s))
        for fut, expect_valid, t_submit, t_acct in outstanding:
            t_acct.settle(fut, expect_valid, t_submit, clock, result_timeout)

    elapsed = max(clock() - t0, 1e-9)
    d_reqs = metrics.get_count("serve_batched_requests") - occ0_reqs
    d_batches = metrics.get_count("serve_batches") - occ0_batches
    # a gateway client has no max_batch (batching is server-side)
    max_batch = getattr(service, "max_batch", None)
    occupancy = (
        d_reqs / (d_batches * max_batch)
        if (d_batches and max_batch)
        else None
    )
    issue_report = None
    if issue_service is not None and issue_fraction > 0.0:
        issue_report = _issue_report(
            issue_tally, issue_service, issue0, elapsed
        )
    return {
        "arrival": arrival,
        "transport": transport,
        "duration_s": round(elapsed, 3),
        "concurrency": concurrency if arrival == "closed" else None,
        "offered_rate_per_s": rate_per_s if arrival == "open" else None,
        "submitted": tally.submitted,
        "rejected": tally.rejected,
        "shed": tally.shed,
        "completed": tally.completed,
        "errors": tally.errors,
        "errors_retryable": tally.errors_retryable,
        "errors_terminal": tally.errors_terminal,
        "dropped_futures": tally.dropped,
        "availability": _availability(tally.events, t0, elapsed),
        "valid": tally.valid,
        "invalid": tally.invalid,
        "verdict_mismatches": tally.mismatches,
        "latency_s": latency_percentiles(tally.latencies),
        "rpc_overhead_s": _rpc_overhead(
            transport, tally.latencies, eng_lat0, _engine_latency_totals()
        ),
        "stage_breakdown_s": _stage_delta(stages0, _stage_totals()),
        "devices": _device_report(dev0_counts, dev0_timers, elapsed),
        "placement": _placement_report(placed0),
        "goodput_per_s": round(tally.completed / elapsed, 2),
        "mean_batch_occupancy": (
            round(occupancy, 4) if occupancy is not None else None
        ),
        "batches": d_batches,
        "rejection_rate": (
            round(tally.rejected / tally.submitted, 4)
            if tally.submitted
            else None
        ),
        "issue_fraction": issue_fraction if issue_report else None,
        "issue": issue_report,
    }


#: the full-session pipeline's phase order (engine/session.ProtocolEngine)
SESSION_PHASES = ("prepare", "mint", "show_prove", "show_verify")


def run_session_loadgen(
    engine,
    pool,
    duration_s=2.0,
    concurrency=4,
    lane="interactive",
    rng=None,
    clock=time.monotonic,
    result_timeout=60.0,
    transport="direct",
):
    """Drive FULL protocol sessions against a ProtocolEngine: each client
    walks one credential through prepare -> mint -> show_prove ->
    show_verify, end to end, and the report gives end-to-end session
    latency percentiles NEXT TO per-program goodput — the number the
    paper's deployment story is judged by (a credential is only useful
    once it has been minted AND shown).

    pool: non-empty list of (messages, elgamal_pk, elgamal_sk) tuples to
    sample from (each session mints a fresh credential for its drawn
    identity). Closed loop only: `concurrency` session threads, each
    starting its next session when the previous one's show verdict
    lands — the arrival shape of a saturating enrollment pipeline. A
    session that fails at ANY hop counts one error (attributed to its
    phase in `phase_errors`); `failed_shows` counts sessions whose final
    verdict was False — a correctness alarm, since every minted
    credential must show-verify.

    The engine must already be started; callers own lifecycle.

    transport: "direct" (engine IS a ProtocolEngine) or "rpc" (engine is
    a net.GatewayClient / net.ReplicaRouter). Over RPC each session gets
    a stable session id — routed with consistent-hash affinity when the
    target is a router (its `bound(session)` seam) — and the report adds
    `rpc_overhead_s` (mean client-observed phase latency minus the
    engine-side mean, when the replica engines share this process)."""
    if not pool:
        raise ValueError("session loadgen pool must be non-empty")
    if transport not in ("direct", "rpc"):
        raise ValueError("unknown transport %r" % (transport,))
    rng = rng if rng is not None else random.Random(0x5E5510)
    lock = threading.Lock()
    session_lat = []
    phase_lat = {p: [] for p in SESSION_PHASES}
    phase_errors = {p: 0 for p in SESSION_PHASES}
    counts = {
        "started": 0,
        "completed": 0,
        "rejected": 0,
        "shed": 0,
        "failed_shows": 0,
    }
    stages0 = _stage_totals()
    eng_lat0 = _engine_latency_totals()
    t0 = clock()
    t_end = t0 + duration_s

    def run_one_session():
        messages, elg_pk, elg_sk = pool[rng.randrange(len(pool))]
        t_start = clock()
        with lock:
            counts["started"] += 1
            session_no = counts["started"]
        if transport == "rpc" and hasattr(engine, "bound"):
            # a router pins the whole prepare->mint->show flow to the
            # session's ring-primary replica (consistent-hash affinity)
            eng = engine.bound("sess-%d" % session_no)
        else:
            eng = engine
        phase = SESSION_PHASES[0]
        try:
            t_p = clock()
            sig_req, _rand = eng.submit_prepare(
                messages, elg_pk, lane=lane
            ).result(result_timeout)
            with lock:
                phase_lat["prepare"].append(clock() - t_p)
            phase = "mint"
            t_p = clock()
            cred = eng.submit_mint(
                sig_req, messages, elg_sk, lane=lane
            ).result(result_timeout)
            with lock:
                phase_lat["mint"].append(clock() - t_p)
            phase = "show_prove"
            t_p = clock()
            proof, challenge, revealed = eng.submit_show_prove(
                cred, messages, lane=lane
            ).result(result_timeout)
            with lock:
                phase_lat["show_prove"].append(clock() - t_p)
            phase = "show_verify"
            t_p = clock()
            verdict = eng.submit_show_verify(
                proof, revealed, challenge, lane=lane
            ).result(result_timeout)
            with lock:
                phase_lat["show_verify"].append(clock() - t_p)
        except ServiceOverloadedError:
            with lock:
                counts["rejected"] += 1
            return
        except ServiceBrownoutError:
            with lock:
                counts["shed"] += 1
            return
        except ServiceClosedError:
            return
        except Exception:
            with lock:
                phase_errors[phase] += 1
            return
        with lock:
            counts["completed"] += 1
            session_lat.append(clock() - t_start)
            if not verdict:
                counts["failed_shows"] += 1

    def client():
        while clock() < t_end:
            run_one_session()

    threads = [
        threading.Thread(target=client, name="session-loadgen-%d" % i)
        for i in range(concurrency)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    elapsed = max(clock() - t0, 1e-9)
    per_program = {}
    for phase, lats in phase_lat.items():
        per_program[phase] = {
            "completed": len(lats),
            "errors": phase_errors[phase],
            "goodput_per_s": round(len(lats) / elapsed, 2),
            "latency_s": latency_percentiles(lats),
        }
    all_phase_lat = [dt for lats in phase_lat.values() for dt in lats]
    return {
        "arrival": "closed",
        "transport": transport,
        "duration_s": round(elapsed, 3),
        "concurrency": concurrency,
        "sessions_started": counts["started"],
        "sessions_completed": counts["completed"],
        "rejected": counts["rejected"],
        "shed": counts["shed"],
        "errors": sum(phase_errors.values()),
        "failed_shows": counts["failed_shows"],
        "sessions_per_s": round(counts["completed"] / elapsed, 2),
        "session_latency_s": latency_percentiles(session_lat),
        "rpc_overhead_s": _rpc_overhead(
            transport, all_phase_lat, eng_lat0, _engine_latency_totals()
        ),
        "per_program": per_program,
        "stage_breakdown_s": _stage_delta(stages0, _stage_totals()),
    }


def _issue_report(t, issue_service, before_counts, elapsed):
    """The mixed-workload report's issuance section: client-observed
    outcomes plus the quorum-health counter deltas over the run. Every
    completion IS a minted-and-verified credential, so `mismatches` > 0
    (a falsy mint) or `errors` concentrated here point at the issuance
    pool, not the verify pool."""

    def delta(name):
        return metrics.get_count(name) - before_counts.get(name, 0)

    fanouts = delta("issue_batches")
    return {
        "submitted": t.submitted,
        "rejected": t.rejected,
        "shed": t.shed,
        "minted": t.completed,
        "errors": t.errors,
        "dropped_futures": t.dropped,
        "mint_mismatches": t.mismatches,
        "latency_s": latency_percentiles(t.latencies),
        "goodput_per_s": round(t.completed / elapsed, 2),
        "mean_batch_occupancy": (
            round(
                delta("issue_batched_requests")
                / (fanouts * issue_service.max_batch),
                4,
            )
            if fanouts
            else None
        ),
        "fanouts": fanouts,
        "hedges": delta("issue_hedges"),
        "partials_discarded": delta("issue_partials_discarded"),
        "corrupt_partials": delta("issue_corrupt_partials"),
        "quorum_unreachable": delta("issue_quorum_unreachable"),
    }
