"""The online credential service: a mesh-native dispatcher pool wiring the
deadline batcher into the existing offline machinery.

Topology (PR 6): a PLACER thread owns coalescing and placement; a pool of
per-device EXECUTOR threads owns dispatch. The placer pops coalesced
batches off the request queue (serve/batcher.py) and hands each to an
executor; every executor runs the same launch/settle async double-buffer
the single-supervisor service ran — so encode for batch i+1 overlaps
device compute for batch i PER DEVICE — through the SAME seams the
offline stream uses, and demuxes per-credential verdicts back onto the
originating futures.

Placement is adaptive, decided per coalesced batch:

  - LEAST-LOADED SINGLE DEVICE (default): the batch goes whole to the
    executor with the fewest unsettled request lanes — the latency path:
    no cross-chip collective, one device round trip.
  - SHARDED ACROSS THE MESH: a batch of at least `sharded_min_lanes`
    containing no interactive requests routes through the dp-sharded
    mesh program (tpu/shard.py, via stream._dispatchers(mesh=...)) — the
    throughput path for bulk traffic, where one batch's work spans every
    chip. Batch size and lane decide; interactive requests never pay a
    collective on their latency path.

  Both paths keep jit shapes cache-hot through the identity-lane padding
  convention: per-credential batches pad to max_batch (pad_partial),
  grouped mesh batches pad to one fixed power-of-two shape.

Backpressure: each executor accepts at most one unsettled batch (two
when its dispatch is async — the in-flight one plus the one being
encoded), and the batcher's `ready` gate holds any further backlog IN
the request queue, where bounded-depth admission control can see and
refuse it. Without the gate, a pool would silently convert overload into
unbounded executor inboxes.

Everything fault- and perf-related is reused, not reinvented:

  - PR-2 supervision: each batch's dispatch+readback cycle runs under
    `retry.call_with_retry` (bounded backoff, deterministic jitter), then
    degrades to `fallback_backend`; in grouped mode a rejected batch is
    bisected with `stream._make_bisector` — so ONE forged credential
    fails ITS future (and lands in the dead-letter JSONL) while every
    cohabiting request resolves valid. Containment is per batch, hence
    per device: a fault on one device's batch never stalls the others'
    pipelines.
  - PR-3 pipelining: dispatch goes through the backends' `*_async` seams
    (probed by `stream._dispatchers`, optionally pinned to one jax
    device), the encode rides the static-operand cache.

SELF-HEALING (this layer's own fault story, serve/health.py): the pool
contains executor-level failures the way PR-2 contains batch-level ones.

  - CRASH CONTAINMENT: an executor-loop crash (a BaseException escaping
    the per-batch containment in _launch/_settle) quarantines ONLY that
    executor: its unsettled batches — the in-flight one plus its inbox —
    are REDISTRIBUTED to surviving executors through the same _route/
    _place seams, where the PR-2 retry/bisection ladder still applies.
    Service-wide poison (`_crash`) happens only when the LAST executor
    dies, so no future ever dangles either way.
  - HUNG-DISPATCH WATCHDOG: every dispatch is deadline-tracked
    (health.Watchdog, k x EMA budget per executor); a dispatch that never
    returns — the failure mode PR-2's retry can't see — is expired by the
    watchdog thread: the stuck worker is ABANDONED (generation bump; its
    eventual return is discarded by the stale-settle guard), the executor
    quarantined, the hung batch redistributed.
  - QUARANTINE -> PROBATION -> HEALTHY: a per-executor circuit breaker
    (health.ExecutorHealth) also opens on consecutive batch failures;
    after a cooldown the executor re-enters via half-open PROBATION (one
    probe batch at a time, respawning an abandoned worker) and closes
    back to HEALTHY on consecutive probe successes — a flapping device
    backs off exponentially instead of oscillating.
  - BROWNOUT: with capacity degraded or the queue near its bound, bulk
    submissions are shed with the typed, retriable ServiceBrownoutError
    (retry-after hint included) while interactive traffic stays live —
    graded degradation between "fully up" and the hard admission bound.

Request path: `submit()` -> brownout check -> admission control (bounded
queue, typed rejection) -> coalesce (full batch or oldest deadline) ->
place (least-loaded ADMISSIBLE device, or mesh-sharded) -> identity-pad
to the cache-hot shape -> dispatch under retry/fallback -> demux ->
future resolves. Per-request latency lands in the "serve_latency_s"
histogram; per-device dispatch/request counters, busy-second timers,
health gauges, placement/quarantine/watchdog/shed counters, and
queue-depth/load gauges land in `metrics.snapshot()` (see metrics.py).

Tracing (coconut_tpu/obs, COCONUT_TRACE=1): each coalesced batch is a
trace of its own — root "batch" span (stamped with the DEVICE id and the
PLACEMENT decision) with "coalesce", "dispatch" (device-stamped),
"device" and "demux" children; retry attempts, fallback switches, and
bisection splits land as events on the active span. The batch span links
its member requests' trace_ids (and each request span carries
`batch_trace` back); culprits isolated by bisection get a "dead_letter"
event on THEIR request span — so a dead-lettered request's span tree
names the device that verified (and rejected) it. Health transitions are
instant "health" spans; watchdog expiries land as "watchdog_timeout"
events on the hung batch's span, redistribution as "redistributed"
events on each affected request's span.

Lifecycle: `start()` launches the executors, the placer, and the
watchdog thread; `drain()` closes intake, flushes and settles everything
in flight, and joins all threads under ONE shared deadline (`timeout` is
a total budget, not per-thread) — every accepted future is resolved.
`shutdown(drain=False)` instead fails still-QUEUED requests with
`ServiceClosedError` (batches already placed on executors still settle).
A placer crash — or the death of the last executor — sweeps all
queued+in-flight futures with the crash exception — no caller ever hangs
on a dropped future. The context-manager form
(`with CredentialService(...) as svc:`) is start()/drain().
"""

import threading
import time
from collections import deque

from .. import metrics
from ..errors import ServiceBrownoutError, ServiceClosedError
from ..obs import trace as otrace
from ..retry import RetryPolicy, call_with_retry, note_attempt
from ..stream import _dispatchers, _fallback_dispatcher, _make_bisector
from . import health as _health
from .batcher import Batcher, demux, fail_all, pad_batch
from .queue import RequestQueue


def _next_pow2(n):
    """Smallest power of two >= n (and >= 2) — the grouped kernel's batch
    shape convention (tpu/backend.py's Bp)."""
    return 1 << max(1, (n - 1).bit_length())


def _remaining(deadline):
    """Seconds left until `deadline` on the REAL clock (thread joins are
    wall-time waits even under an injected fake clock); None = no bound."""
    if deadline is None:
        return None
    return max(0.0, deadline - time.monotonic())


class _DeviceExecutor:
    """One device's serving loop: an inbox worker thread running the
    launch/settle async double-buffer for ITS device.

    Load accounting (`load()`: unsettled request lanes) drives the
    placer's least-loaded pick; `can_accept()` bounds unsettled batches
    to 1 (sync dispatch) or 2 (async: one in flight + one being encoded),
    which is the pool-shaped generalization of the old single supervisor's
    double buffer — anything beyond that stays in the request queue where
    admission control is. Settling kicks the request queue so a
    capacity-gated placer re-checks.

    GENERATIONS: the worker thread carries the generation it was spawned
    under. `abandon()` (crash containment, watchdog timeout) bumps the
    generation and drops the thread reference — the old worker, possibly
    still stuck inside a hung dispatch, becomes STALE: `_next`/`_finish`
    ignore it, and the service's stale-settle guard discards whatever it
    eventually returns. `start()` can then respawn a FRESH worker for the
    probation probe."""

    def __init__(
        self,
        service,
        index,
        label=None,
        device=None,
        dispatch=None,
        is_async=False,
        placement="single",
    ):
        self.service = service
        self.index = index
        self.label = str(index) if label is None else label
        self.device = device
        self.dispatch = dispatch
        self.is_async = is_async
        self.placement = placement  # "single" | "sharded"
        self.busy_timer = "serve_dev%s_busy_s" % self.label
        self._cond = threading.Condition()
        self._inbox = deque()
        self._load = 0  # unsettled request lanes (queued + in flight)
        self._batches_out = 0  # unsettled batches (capacity bound)
        self._closed = False
        self._gen = 0
        self._thread = None

    # -- placer side ---------------------------------------------------------

    def load(self):
        with self._cond:
            return self._load

    def batches_out(self):
        with self._cond:
            return self._batches_out

    def can_accept(self):
        with self._cond:
            return self._batches_out < (2 if self.is_async else 1)

    def submit_batch(self, requests):
        with self._cond:
            self._inbox.append(requests)
            self._load += len(requests)
            self._batches_out += 1
            load = self._load
            self._cond.notify_all()
        metrics.set_gauge("serve_dev%s_load" % self.label, load)

    # -- lifecycle -----------------------------------------------------------

    def start(self):
        """Spawn the worker thread — a no-op while one is running (or
        after close()). Also the PROBATION revival path: after abandon()
        the thread slot is empty, so start() spawns a fresh worker under
        the new generation."""
        with self._cond:
            if self._closed or self._thread is not None:
                return
            gen = self._gen
            self._thread = threading.Thread(
                target=self._run,
                args=(gen,),
                name="coconut-serve-dev%s.g%d" % (self.label, gen),
                daemon=True,
            )
            thread = self._thread
        thread.start()

    def close(self):
        """Stop accepting; the loop still settles its inbox, then exits."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def join(self, timeout=None):
        if self._thread is None:
            return True
        self._thread.join(timeout)
        return not self._thread.is_alive()

    def has_worker(self):
        """A live (non-abandoned) worker thread exists — the executor can
        still settle batches, even quarantined."""
        with self._cond:
            return self._thread is not None and self._thread.is_alive()

    def is_current(self, gen):
        with self._cond:
            return gen == self._gen

    def abandon(self):
        """Crash/hang containment: bump the generation (the old worker —
        possibly stuck inside a dispatch that will never return — becomes
        stale), sweep the inbox, zero the load so the placer never routes
        here until a probation probe revives it. Returns the swept
        batches; the CALLER owns redistributing them. Unlike poison(),
        the executor is NOT closed: start() can respawn it."""
        with self._cond:
            self._gen += 1
            self._thread = None
            swept = list(self._inbox)
            self._inbox.clear()
            self._load = 0
            self._batches_out = 0
            self._cond.notify_all()
        metrics.set_gauge("serve_dev%s_load" % self.label, 0)
        return swept

    def sweep_inbox(self):
        """Pull every QUEUED (not yet launched) batch back out — the soft
        quarantine path: the worker stays alive to settle what's in
        flight, but its backlog moves to survivors."""
        with self._cond:
            swept = list(self._inbox)
            self._inbox.clear()
            for batch in swept:
                self._load = max(0, self._load - len(batch))
                self._batches_out = max(0, self._batches_out - 1)
            load = self._load
            self._cond.notify_all()
        metrics.set_gauge("serve_dev%s_load" % self.label, load)
        return swept

    def poison(self, exc):
        """Crash sweep: refuse everything still queued on this device."""
        with self._cond:
            self._closed = True
            swept = list(self._inbox)
            self._inbox.clear()
            self._load = 0
            self._batches_out = 0
            self._cond.notify_all()
        for batch in swept:
            fail_all(batch, exc)

    # -- worker loop ---------------------------------------------------------

    def _next(self, gen, block):
        with self._cond:
            while True:
                if self._gen != gen:
                    return None  # abandoned: this worker is stale — exit
                if self._inbox:
                    return self._inbox.popleft()
                if self._closed or not block:
                    return None
                self._cond.wait()

    def _finish(self, gen, n_lanes):
        with self._cond:
            if self._gen != gen:
                return  # stale worker: accounting belongs to the new gen
            self._load = max(0, self._load - n_lanes)
            self._batches_out = max(0, self._batches_out - 1)
            load = self._load
        metrics.set_gauge("serve_dev%s_load" % self.label, load)
        # capacity freed: wake a placer gated on ready()
        self.service._queue.kick()

    def _run(self, gen):
        svc = self.service
        pending = None  # launched, unsettled (async double-buffer slot)
        current = None  # popped from the inbox, not yet fully handled
        try:
            while True:
                current = self._next(gen, block=pending is None)
                if current is not None:
                    launched = svc._launch(current, self)
                    if pending is not None:
                        svc._settle(*pending)
                        self._finish(gen, len(pending[1]))
                        pending = None
                    if self.is_async:
                        # double-buffer: leave this batch in flight and go
                        # take the next while the device runs
                        pending = launched
                    else:
                        svc._settle(*launched)
                        self._finish(gen, len(current))
                    current = None
                    continue
                if pending is not None:
                    # nothing ready to overlap with: settle the in-flight
                    # batch now instead of holding its latency hostage
                    svc._settle(*pending)
                    self._finish(gen, len(pending[1]))
                    pending = None
                    continue
                # closed/abandoned and inbox empty: exit
                return
        except BaseException as e:  # loop-level crash (a code bug escaping
            # the per-batch containment in _launch/_settle): hand THIS
            # executor's unsettled batches — in-flight and mid-launch — to
            # the service for quarantine + redistribution; the pool
            # survives unless this was the last executor
            batches = []
            spans = []
            if pending is not None:
                batches.append(pending[1])
                spans.append(pending[6])
            if current is not None and (
                pending is None or current is not pending[1]
            ):
                batches.append(current)
            svc._executor_failed(self, e, batches, spans, gen)


class CredentialService:
    """Dynamic-batching verify service over any verify-capable backend.

    backend / fallback_backend: instances or registry names ("python",
    "jax", ...). mode: "per_credential" (bits demux directly) or "grouped"
    (one device bool per batch; a rejection bisects to per-request
    verdicts, culprits dead-lettered). max_batch: the coalesced device
    shape. max_wait_ms: default per-request coalescing deadline.
    max_depth: admission bound. pad_partial: identity-pad partial batches
    to max_batch (per_credential mode) so jit shapes stay cache-hot —
    grouped mode never pads, its encode pads internally to a power of two.
    clock: injectable time source for deadline tests.

    Pool shape (PR 6): `devices` is None (one executor, the PR-4
    behavior), an int N (N executors — worker-thread parallelism for
    backends without device placement), or a list of jax devices (one
    executor pinned to each). `mesh` adds the dp-sharded mesh dispatch
    lane; batches of >= `sharded_min_lanes` (default max_batch) with no
    interactive requests route through it (see _route).

    Self-healing knobs (serve/health.py): `health_policy` configures the
    per-executor circuit breaker, `watchdog` the hung-dispatch deadline
    tracker (pass one with a fake clock for deterministic tests),
    `watchdog_interval_s` the background health-tick period (None
    disables the thread — tests then drive `health_tick()` by hand),
    `brownout` the graded load-shedding policy, `max_redispatch` the hop
    cap for redistributed batches (default: pool size - 1, so a poisonous
    batch can visit each survivor at most once before failing loudly)."""

    def __init__(
        self,
        backend,
        vk,
        params,
        mode="per_credential",
        max_batch=64,
        max_wait_ms=20.0,
        max_depth=1024,
        retry_policy=None,
        fallback_backend=None,
        dead_letter_path=None,
        pad_partial=True,
        clock=time.monotonic,
        devices=None,
        mesh=None,
        sharded_min_lanes=None,
        health_policy=None,
        watchdog=None,
        watchdog_interval_s=0.25,
        brownout=None,
        max_redispatch=None,
    ):
        from ..backend import get_backend
        from ..errors import TransientBackendError

        if backend is None or isinstance(backend, str):
            backend = get_backend(backend or "python")
        if isinstance(fallback_backend, str):
            fallback_backend = get_backend(fallback_backend)
        if mode not in ("per_credential", "grouped"):
            raise ValueError("unknown serve mode %r" % (mode,))
        self.backend = backend
        self.vk = vk
        self.params = params
        self.mode = mode
        self.max_batch = max_batch
        self.max_wait_ms = max_wait_ms
        self.pad_partial = pad_partial and mode == "per_credential"
        self.clock = clock

        if devices is None:
            device_list = [None]
        elif isinstance(devices, int):
            if devices < 1:
                raise ValueError("devices must be >= 1 (got %r)" % (devices,))
            device_list = [None] * devices
        else:
            device_list = list(devices)
            if not device_list:
                raise ValueError("devices list must be non-empty")
        self._executors = []
        for i, dev in enumerate(device_list):
            dispatch, _, is_async = _dispatchers(backend, mode, device=dev)
            self._executors.append(
                _DeviceExecutor(
                    self, i, device=dev, dispatch=dispatch, is_async=is_async
                )
            )
        self._is_async = self._executors[0].is_async

        self.mesh = mesh
        self.sharded_min_lanes = (
            max_batch if sharded_min_lanes is None else sharded_min_lanes
        )
        self._mesh_executor = None
        if mesh is not None:
            pad_to = None
            if mode == "grouped" and "dp" in mesh.shape:
                # ONE fixed grouped shape across all occupancy levels:
                # the sharded encode's own floor (2*ndp) or the service's
                # max batch rounded to the kernel's power-of-two, whichever
                # is larger — varying coalesced sizes never recompile
                pad_to = max(2 * mesh.shape["dp"], _next_pow2(max_batch))
            mesh_dispatch, _, _ = _dispatchers(
                backend, mode, mesh=mesh, mesh_pad_to=pad_to
            )
            self._mesh_executor = _DeviceExecutor(
                self,
                len(self._executors),
                label="mesh",
                dispatch=mesh_dispatch,
                is_async=True,
                placement="sharded",
            )

        self._fallback_dispatch = (
            _fallback_dispatcher(fallback_backend, mode)
            if fallback_backend is not None
            else None
        )
        if retry_policy is None:
            # mirror verify_stream: no ladder means transient errors go
            # straight to the fallback when one exists, else propagate
            retry_policy = RetryPolicy(
                max_attempts=1,
                base_delay=0.0,
                retryable=(
                    (TransientBackendError,)
                    if self._fallback_dispatch is not None
                    else ()
                ),
            )
        self._policy = retry_policy
        self._bisector = (
            _make_bisector(
                backend,
                fallback_backend,
                vk,
                params,
                retry_policy,
                dead_letter_path,
            )
            if mode == "grouped"
            else None
        )
        self._queue = RequestQueue(max_depth=max_depth, clock=clock)
        self._batcher = Batcher(self._queue, max_batch, clock=clock)
        self._thread = None
        self._seq_lock = threading.Lock()
        self._batch_seq = 0  # dead-letter batch ids + retry jitter keys
        self._crashed = None

        # self-healing surfaces (serve/health.py)
        self.health_policy = (
            health_policy if health_policy is not None else _health.HealthPolicy()
        )
        self._watchdog = (
            watchdog if watchdog is not None else _health.Watchdog(clock=clock)
        )
        self._watchdog_interval_s = watchdog_interval_s
        self._brownout = (
            brownout if brownout is not None else _health.BrownoutPolicy()
        )
        all_ex = self._all_executors()
        self._healths = {}
        for ex in all_ex:
            self._health_of(ex.label)
        self.max_redispatch = (
            max(1, len(all_ex) - 1) if max_redispatch is None else max_redispatch
        )
        self._wd_stop = threading.Event()
        self._wd_thread = None
        for ex in all_ex:
            metrics.set_gauge("serve_dev%s_health" % ex.label, _health.HEALTHY)
        self._refresh_health_gauges()

    # -- client side ---------------------------------------------------------

    def submit(self, sig, messages, lane="interactive", max_wait_ms=None):
        """Admit one verify request; returns its ServeFuture (resolves to
        the request's own verdict bool). Raises ServiceBrownoutError when
        graded load-shedding refuses this lane (retriable, carries a
        retry-after hint), ServiceOverloadedError at the admission bound,
        ServiceClosedError after drain/shutdown."""
        if self._crashed is not None:
            raise ServiceClosedError(
                "service supervisor crashed: %r" % (self._crashed,)
            )
        depth = self._queue.depth()
        capacity = self._capacity_fraction()
        active, retry_after = self._brownout.check(
            lane, depth, self._queue.max_depth, capacity
        )
        metrics.set_gauge("serve_brownout", 1 if active else 0)
        if retry_after is not None:
            metrics.count("serve_shed_bulk")
            raise ServiceBrownoutError(
                lane, retry_after, depth=depth, capacity_fraction=capacity
            )
        return self._queue.submit(
            sig,
            messages,
            lane=lane,
            max_wait_ms=(
                self.max_wait_ms if max_wait_ms is None else max_wait_ms
            ),
        )

    def depth(self):
        return self._queue.depth()

    def kick(self):
        """Wake the placer to re-read the clock (fake-clock tests)."""
        self._queue.kick()

    # -- lifecycle -----------------------------------------------------------

    def _all_executors(self):
        if self._mesh_executor is not None:
            return self._executors + [self._mesh_executor]
        return list(self._executors)

    def start(self):
        if self._thread is None:
            for ex in self._all_executors():
                ex.start()
            self._thread = threading.Thread(
                target=self._run, name="coconut-serve", daemon=True
            )
            self._thread.start()
            if self._watchdog_interval_s is not None:
                self._wd_thread = threading.Thread(
                    target=self._watchdog_loop,
                    name="coconut-serve-watchdog",
                    daemon=True,
                )
                self._wd_thread.start()
        return self

    def _close_pool(self, deadline, ok):
        """Join the placer's executors after intake+placement ended; every
        inbox batch still settles before an executor exits. `deadline` is
        the drain/shutdown call's SINGLE shared deadline — each join gets
        whatever budget remains, not a fresh per-thread timeout."""
        for ex in self._all_executors():
            ex.close()
        for ex in self._all_executors():
            ok = ex.join(_remaining(deadline)) and ok
        # the watchdog goes LAST: it can still expire a hung dispatch
        # (and redistribute its batch) while the pool drains
        ok = self._stop_watchdog(deadline) and ok
        return ok

    def _stop_watchdog(self, deadline):
        thread = self._wd_thread
        if thread is None:
            return True
        self._wd_stop.set()
        thread.join(_remaining(deadline))
        return not thread.is_alive()

    def drain(self, timeout=None):
        """Close intake, settle every accepted request, join the placer
        and the executor pool. Every accepted future is resolved on return
        (True iff all threads exited within `timeout` — ONE deadline
        shared across every join, not a per-thread allowance)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        self._queue.close()
        if self._thread is None:
            # never started: nothing will settle the queue — fail loudly
            fail_all(
                self._queue.drain_pending(),
                ServiceClosedError("service drained before start()"),
                counter="serve_cancelled",
            )
            return True
        self._thread.join(_remaining(deadline))
        return self._close_pool(deadline, not self._thread.is_alive())

    def shutdown(self, drain=True, timeout=None):
        """drain=True: alias for drain(). drain=False: refuse the queued
        backlog (futures fail with ServiceClosedError) but still settle
        work already placed on executors, then join — `timeout` again one
        shared deadline across all joins."""
        if drain:
            return self.drain(timeout)
        deadline = None if timeout is None else time.monotonic() + timeout
        self._queue.close()
        fail_all(
            self._queue.drain_pending(),
            ServiceClosedError("service shut down before this request ran"),
            counter="serve_cancelled",
        )
        if self._thread is not None:
            self._thread.join(_remaining(deadline))
            return self._close_pool(deadline, not self._thread.is_alive())
        return self._stop_watchdog(deadline)

    def __enter__(self):
        return self.start()

    def __exit__(self, exc_type, exc, tb):
        self.drain()
        return False

    # -- health (serve/health.py integration) --------------------------------

    def _health_of(self, label):
        """The breaker for `label`, created on first sight (executors can
        be injected post-init — tests stub the mesh lane that way)."""
        h = self._healths.get(label)
        if h is None:
            h = self._healths[label] = _health.ExecutorHealth(
                label, self.health_policy, clock=self.clock
            )
        return h

    def _admits(self, ex):
        """May the placer route NEW work to `ex`? HEALTHY/SUSPECT always;
        PROBATION only while its half-open probe slot is free (one
        unsettled probe batch at a time); QUARANTINED never."""
        h = self._health_of(ex.label)
        if not h.admissible():
            return False
        if h.state == _health.PROBATION and ex.batches_out() > 0:
            return False
        return True

    def _capacity_fraction(self):
        """Fraction of the pool the placer may still route to — the
        brownout policy's degradation signal."""
        exs = self._all_executors()
        ok = sum(1 for ex in exs if self._health_of(ex.label).admissible())
        return ok / len(exs)

    def _refresh_health_gauges(self):
        metrics.set_gauge(
            "serve_healthy_executors",
            sum(
                1
                for ex in self._all_executors()
                if self._health_of(ex.label).admissible()
            ),
        )

    def _note_success(self, executor):
        change = self._health_of(executor.label).on_success()
        if change:
            self._refresh_health_gauges()
            self._queue.kick()

    def _note_failure(self, executor, exc):
        """A batch failed past retry+fallback ON this executor: feed the
        circuit breaker; if that opened it (soft quarantine — the worker
        itself is alive), move the executor's queued backlog to
        survivors."""
        change = self._health_of(executor.label).on_failure(
            "batch failed past retry+fallback: %s" % type(exc).__name__
        )
        if change:
            self._refresh_health_gauges()
            self._queue.kick()
            if change[1] == _health.QUARANTINED:
                self._redistribute(executor.sweep_inbox(), exc)

    def _executor_failed(self, executor, exc, batches, spans, gen):
        """Executor-loop crash containment (runs ON the dying worker's
        thread): quarantine ONLY this executor and hand its unsettled
        batches to survivors. A stale generation (the watchdog already
        abandoned this worker and redistributed its work) does nothing."""
        if not executor.is_current(gen):
            return
        metrics.count("serve_executor_crashes")
        for span in spans:
            otrace.end_span(span, error=type(exc).__name__)
        self._health_of(executor.label).on_crash(
            "executor loop crash: %s" % type(exc).__name__
        )
        swept = executor.abandon()
        self._watchdog.forget_label(executor.label)
        self._refresh_health_gauges()
        self._redistribute(list(batches) + swept, exc)
        self._queue.kick()

    def _redistribute(self, batches, cause):
        """Re-place a failed executor's unsettled batches through the
        normal _route/_place seams. Each request's redispatch count is
        capped (`max_redispatch`): a poisonous batch that kills every
        executor it lands on fails ITS OWN futures after the cap instead
        of serially taking down the pool. With NO survivors — the last
        executor died — the service poisons and every remaining future
        resolves with the crash exception: none dangle."""
        batches = [b for b in batches if b]
        for i, batch in enumerate(batches):
            survivors = [
                ex
                for ex in self._all_executors()
                if self._health_of(ex.label).admissible() or ex.has_worker()
            ]
            if not survivors:
                self._crash(cause)
                for rest in batches[i:]:
                    fail_all(rest, cause)
                return
            for r in batch:
                r.redispatches += 1
            if max(r.redispatches for r in batch) > self.max_redispatch:
                metrics.count("serve_redispatch_exhausted")
                fail_all(batch, cause)
                continue
            metrics.count("serve_redistributed_batches")
            metrics.count("serve_redistributed_requests", len(batch))
            for r in batch:
                r.span.event("redistributed", hops=r.redispatches)
            self._place(batch).submit_batch(batch)

    def health_tick(self, now=None):
        """One self-healing sweep: expire hung dispatches (abandon the
        stuck worker, quarantine its executor, redistribute the hung
        batch) and promote quarantined executors whose cooldown elapsed
        into half-open PROBATION (respawning abandoned workers). Runs
        periodically on the watchdog thread in production; fake-clock
        tests call it directly after advancing time."""
        if self._crashed is not None:
            return
        now = self.clock() if now is None else now
        expired = self._watchdog.expire(now)
        from ..errors import TransientBackendError

        by_label = {}
        for label, seq, requests, span, overdue_s in expired:
            metrics.count("serve_watchdog_timeouts")
            if span is not None:
                span.event(
                    "watchdog_timeout",
                    seq=seq,
                    overdue_s=round(overdue_s, 6),
                )
                span.end(error="WatchdogTimeout")
            by_label.setdefault(label, []).append(requests)
        for label, hung in by_label.items():
            ex = next(
                (x for x in self._all_executors() if x.label == label), None
            )
            if ex is None:
                continue
            cause = TransientBackendError(
                "dispatch on executor %s hung past its watchdog budget"
                % (label,)
            )
            self._health_of(label).on_crash("hung dispatch: watchdog timeout")
            # the worker is STUCK inside the dispatch — abandon it (its
            # eventual return, if any, is discarded by the stale-settle
            # guard) and redistribute both the hung batches and the inbox
            swept = ex.abandon()
            self._watchdog.forget_label(label)
            self._refresh_health_gauges()
            self._redistribute(hung + swept, cause)
        # half-open promotion: cooldown elapsed -> probation probe window
        for ex in self._all_executors():
            if self._health_of(ex.label).try_probation(now):
                ex.start()  # respawn an abandoned worker; no-op otherwise
                self._refresh_health_gauges()
                self._queue.kick()
        if expired:
            self._queue.kick()

    def _watchdog_loop(self):
        while not self._wd_stop.wait(self._watchdog_interval_s):
            try:
                self.health_tick()
            except Exception:
                # the healer must never become the failure: count and
                # keep ticking
                metrics.count("serve_health_tick_errors")

    # -- placement -----------------------------------------------------------

    def _route(self, requests):
        """The adaptive placement policy: "sharded" (dp-sharded across the
        mesh) or "single" (whole batch to one device). Batch size and lane
        decide: only batches of at least `sharded_min_lanes` with NO
        interactive requests take the mesh — a turnstile request never
        pays a cross-chip collective on its latency path, while bulk
        backfill batches get every chip."""
        if self._mesh_executor is None:
            return "single"
        if len(requests) < self.sharded_min_lanes:
            return "single"
        if any(r.lane == "interactive" for r in requests):
            return "single"
        return "sharded"

    def _has_capacity(self):
        """ready() gate for the batcher: pop a batch only when some
        ADMISSIBLE executor can take it, otherwise the backlog stays in
        the bounded queue where admission control (and the brownout
        policy) can see and refuse it. Quarantined executors contribute no
        capacity."""
        return any(
            self._admits(ex) and ex.can_accept()
            for ex in self._all_executors()
        )

    def _place(self, requests):
        """Pick the executor for one coalesced batch: the policy's route
        over the ADMISSIBLE pool, with capacity spill (a full mesh lane
        falls back to the least-loaded device and vice versa — adaptive,
        never blocking a popped batch behind one hot executor). Routing a
        batch to a PROBATION executor is that executor's half-open probe
        (counted under "serve_probes")."""
        route = self._route(requests)
        metrics.count(
            "serve_placed_sharded" if route == "sharded" else
            "serve_placed_single"
        )
        mesh_ex = self._mesh_executor
        if mesh_ex is not None and not self._admits(mesh_ex):
            mesh_ex = None
        admitted = [ex for ex in self._executors if self._admits(ex)]
        singles = [ex for ex in admitted if ex.can_accept()]
        singles.sort(key=lambda ex: (ex.load(), ex.index))
        if route == "sharded" and mesh_ex is not None:
            chosen = (
                mesh_ex
                if mesh_ex.can_accept()
                else (singles[0] if singles else mesh_ex)
            )
        elif singles:
            chosen = singles[0]
        elif mesh_ex is not None and mesh_ex.can_accept():
            chosen = mesh_ex
        else:
            # no admissible executor has capacity: overflow onto the
            # least-loaded admissible one (capacity is advisory;
            # quarantine is not) — or, with the WHOLE pool quarantined,
            # onto any executor whose worker is still alive: settling
            # behind a sick device beats parking a future behind a probe
            # that may never come
            pool = (
                admitted
                or [ex for ex in self._all_executors() if ex.has_worker()]
                or self._executors
            )
            chosen = min(pool, key=lambda ex: (ex.load(), ex.index))
        if (route == "sharded") != (chosen.placement == "sharded"):
            metrics.count("serve_placed_spill")
        if self._health_of(chosen.label).state == _health.PROBATION:
            metrics.count("serve_probes")
        metrics.set_gauge("serve_queue_depth", self._queue.depth())
        return chosen

    # -- batch work (runs on executor threads) -------------------------------

    def _launch(self, requests, executor=None):
        """Assemble + dispatch one coalesced batch NOW on `executor`'s
        device; return the settle closure state. Mirrors
        stream.verify_stream's launch(): the first dispatch attempt is
        consumed eagerly (pipelining), finalize() re-runs the full
        dispatch+readback cycle under the retry ladder, then the
        fallback."""
        if executor is None:
            executor = self._executors[0]
        with self._seq_lock:
            seq = self._batch_seq
            self._batch_seq += 1
        metrics.count("serve_dev%s_dispatches" % executor.label)
        metrics.count("serve_dev%s_requests" % executor.label, len(requests))
        bspan = otrace.start_span(
            "batch",
            root=True,
            seq=seq,
            n=len(requests),
            device=executor.label,
            placement=executor.placement,
            members=[r.future.trace_id for r in requests]
            if otrace.enabled()
            else None,
        )
        for r in requests:
            # the request->batch join: a request's trace knows which
            # batch trace (hence which DEVICE) did its device work
            r.span.set(batch_trace=bspan.trace_id, batch_seq=seq)
        # deadline-track from BEFORE the first dispatch attempt: a sync
        # dispatch that hangs never returns from this very call, and the
        # watchdog is the only thing that can still free its batch
        self._watchdog.begin(
            executor.label, seq, requests, span=bspan, now=self.clock()
        )
        with otrace.use(bspan), metrics.timer(executor.busy_timer):
            with otrace.span("coalesce"):
                if self.pad_partial:
                    sigs, messages_list, n_pad = pad_batch(
                        requests, self.max_batch
                    )
                    bspan.set(n_pad=n_pad)
                else:
                    sigs = [r.sig for r in requests]
                    messages_list = [r.messages for r in requests]
            metrics.observe(
                "serve_batch_wait_s",
                self.clock() - min(r.t_submit for r in requests),
            )
            attempts = []
            box = [None]
            permanent = None
            with otrace.span(
                "dispatch",
                backend=type(self.backend).__name__,
                device=executor.label,
            ):
                try:
                    box[0] = executor.dispatch(
                        sigs, messages_list, self.vk, self.params
                    )
                except self._policy.retryable as e:
                    note_attempt(attempts, e)
                    otrace.event(
                        "attempt_failed",
                        attempt=len(attempts),
                        error=type(e).__name__,
                    )
                except Exception as e:
                    # permanent dispatch failure (bad inputs, code bug in
                    # a sync backend's compute): unlike the offline
                    # stream — where it aborts the run — the service
                    # contains it to THIS batch's futures; finalize
                    # re-raises without burning retries
                    permanent = e
                    otrace.event("permanent_failure", error=type(e).__name__)

        def cycle():
            fin, box[0] = box[0], None
            if fin is None:
                fin = executor.dispatch(
                    sigs, messages_list, self.vk, self.params
                )
            return fin()

        fallback = (
            (
                lambda: self._fallback_dispatch(
                    sigs, messages_list, self.vk, self.params
                )()
            )
            if self._fallback_dispatch is not None
            else None
        )

        def finalize():
            if permanent is not None:
                raise permanent
            return call_with_retry(
                cycle,
                self._policy,
                key=seq,
                attempts=attempts,
                fallback=fallback,
            )

        return (
            seq,
            requests,
            sigs,
            messages_list,
            finalize,
            attempts,
            bspan,
            executor,
        )

    def _settle(
        self,
        seq,
        requests,
        sigs,
        messages_list,
        finalize,
        attempts,
        bspan,
        executor=None,
    ):
        """Block on the batch result and resolve every request's future."""
        if executor is None:
            executor = self._executors[0]
        with otrace.use(bspan), metrics.timer(executor.busy_timer):
            try:
                with otrace.span("device", device=executor.label):
                    result = finalize()
            except Exception as e:
                self._watchdog.end(
                    executor.label, seq, ok=False, now=self.clock()
                )
                if requests and all(r.future.done() for r in requests):
                    # stale settle: the watchdog timed this batch out and
                    # it was redistributed (and resolved) elsewhere — the
                    # late failure is nobody's news
                    bspan.end(result="stale")
                    return
                # batch-level failure past retry+fallback: each
                # cohabiting future gets the exception — never a silent
                # hang, and never another device's problem
                fail_all(requests, e)
                bspan.end(error=type(e).__name__)
                self._note_failure(executor, e)
                return
            self._watchdog.end(executor.label, seq, now=self.clock())
            if requests and all(r.future.done() for r in requests):
                # stale settle (watchdog fired, batch redistributed): the
                # verdicts were already delivered by the re-dispatch;
                # drop these — ServeFuture is single-assignment anyway
                bspan.end(result="stale")
                return
            self._note_success(executor)
            if self.mode == "per_credential":
                demux(requests, result[: len(requests)], clock=self.clock)
                bspan.end(result="demuxed")
                return
            if result:
                demux(requests, [True] * len(requests), clock=self.clock)
                bspan.end(result="accepted")
                return
            # grouped rejection: recover per-request verdicts by
            # bisection so one forged credential fails only its own
            # future; culprit dead-letter lines carry the CULPRIT
            # request's trace_id (not the batch's), so an operator greps
            # straight from a JSONL line to the request's span tree —
            # which names the device via its batch span
            culprits = (
                set(
                    self._bisector(
                        sigs,
                        messages_list,
                        seq,
                        attempts,
                        trace_ids=[r.future.trace_id for r in requests],
                    )
                )
                if self._bisector is not None
                else set(range(len(requests)))
            )
            for i in culprits:
                if i < len(requests):
                    requests[i].span.event("dead_letter", batch_seq=seq)
            demux(
                requests,
                [i not in culprits for i in range(len(requests))],
                clock=self.clock,
            )
            bspan.end(result="bisected", n_culprits=len(culprits))

    # -- placer --------------------------------------------------------------

    def _crash(self, e):
        """Placer crash, or the LAST executor died: sweep every queued and
        inbox future with the crash exception — no caller ever hangs."""
        self._crashed = e
        self._queue.close()
        fail_all(self._queue.drain_pending(), e)
        for ex in self._all_executors():
            ex.poison(e)

    def _run(self):
        try:
            while True:
                batch = self._batcher.next_batch(
                    block=True, ready=self._has_capacity
                )
                if batch is None:
                    # closed and fully routed: executors drain their
                    # inboxes; drain()/shutdown() closes and joins them
                    return
                self._place(batch).submit_batch(batch)
        except BaseException as e:
            self._crash(e)
            raise
