"""The online credential-verify service: a thin *program* registered on
the unified execution engine (coconut_tpu/engine, PR 12).

Everything structural that used to live here — the per-device executor
pool, the placer thread, adaptive single/mesh placement, the health
registry (circuit breakers, hung-dispatch watchdog, probation revival,
redistribution with hop caps), brownout admission, and the generic
launch/settle batch path — is now the engine's (engine/core.py,
engine/executor.py). What REMAINS here is exactly the verify phase's
crypto and policy:

  VerifyProgram     the engine program: encode (identity-lane padding in
                    per_credential mode), dispatch (stream._dispatchers
                    through the backends' *_async seams, optionally
                    device-pinned), demux (per-credential bits, or the
                    grouped accept/bisect ladder with dead-lettered
                    culprits), the retry/fallback policy, and the
                    mesh-capable placement contract.
  CredentialService an ExecutionEngine subclass that registers ONE
                    VerifyProgram, builds the device pool + optional
                    mesh lane from its constructor knobs, and keeps the
                    historical public API (`submit`, `drain`,
                    `shutdown`, `health_tick`, context manager) and
                    every historical metric/span name.

The behavior catalog — placement policy, backpressure, PR-2 containment
(retry -> fallback -> bisection -> dead letter), PR-3 pipelining, PR-9
self-healing (crash containment, watchdog, quarantine/probation,
brownout), lifecycle semantics — is unchanged from PR 6-9; see the
engine package docstrings for the mechanism and serve/health.py for the
policies. Request path: `submit()` -> brownout check -> admission
control -> coalesce -> place -> identity-pad -> dispatch under
retry/fallback -> demux -> future resolves. Metrics keep their PR-6/9
names ("serve_latency_s", "serve_dev*", "serve_placed_*", health gauges,
shed counters); batch spans gain a `program="verify"` attribute.
"""

import time

from ..engine.core import ExecutionEngine, _next_pow2, _remaining  # noqa: F401
from ..engine.executor import Executor
from ..engine.program import Program
from ..retry import RetryPolicy
from ..stream import _dispatchers, _fallback_dispatcher, _make_bisector
from .batcher import demux, fail_all, pad_batch

#: historical name — tests (and PR-8 era code) construct the executor
#: under this alias; the implementation moved to engine/executor.py
_DeviceExecutor = Executor


class VerifyProgram(Program):
    """The show-verify-credential phase as an engine program: coalesced
    credential batches, identity-lane padding, grouped bisection."""

    name = "verify"
    metric_ns = "serve"
    slo_class = "standard"  # the caller's lane decides shedding
    pad_convention = "identity-credential"
    supports_mesh = True

    def __init__(
        self,
        backend,
        vk,
        params,
        mode,
        max_batch,
        max_wait_ms,
        max_depth,
        pad_partial,
        retry_policy,
        fallback_dispatch,
        bisector,
        keychain=None,
    ):
        if keychain is not None and mode in ("grouped", "batched"):
            # grouped/batched modes fold the whole batch into one device
            # bool; per-epoch verkeys need per-group dispatch, which
            # defeats it
            raise ValueError("keychain requires per_credential mode")
        self.backend = backend
        self.vk = vk
        self.params = params
        self.mode = mode
        self.max_batch = max_batch
        self.max_wait_ms = max_wait_ms
        self.max_depth = max_depth
        self.pad_partial = pad_partial
        self.retry_policy = retry_policy
        self._fallback_dispatch = fallback_dispatch
        self._bisector = bisector
        #: keylife.EpochRegistry (PR 15): when set, each credential's
        #: `epoch` attribute resolves the verkey it verifies under (the
        #: static-operand LRU in tpu/backend.py keys on verkey
        #: fingerprints, so per-epoch caches coexist); unpinned
        #: credentials fall back to the boot `vk`
        self.keychain = keychain

    # -- epoch resolution (PR 15) --------------------------------------------

    def vk_for_epoch(self, epoch):
        """The verkey a credential minted under `epoch` verifies against.
        Raises the typed EpochUnknownError/EpochRetiredError — at submit
        time via the engine's pre-validation, or from inside a dispatch
        when an epoch retires mid-flight (the batch then fails typed)."""
        if epoch is None or self.keychain is None:
            return self.vk
        return self.keychain.resolve(epoch).vk

    def _dispatch_by_epoch(self, fn, sigs, messages_list):
        """Partition one coalesced batch by mint epoch and dispatch each
        group under ITS epoch's verkey (launching every group before
        finalizing any — same launch/finalize split as the executors),
        reassembling verdicts by index in the returned finalize thunk.
        One epoch per steady-state batch in practice (rollovers are
        rare), so the common case is a single full-width dispatch."""
        groups = {}
        for i, s in enumerate(sigs):
            groups.setdefault(getattr(s, "epoch", None), []).append(i)
        launched = []
        for epoch, idxs in sorted(
            groups.items(), key=lambda kv: (kv[0] is not None, kv[0] or 0)
        ):
            vk = self.vk_for_epoch(epoch)
            launched.append(
                (
                    idxs,
                    fn(
                        [sigs[i] for i in idxs],
                        [messages_list[i] for i in idxs],
                        vk,
                    ),
                )
            )

        def finalize():
            out = [False] * len(sigs)
            for idxs, thunk in launched:
                for i, v in zip(idxs, thunk()):
                    out[i] = bool(v)
            return out

        return finalize

    # -- engine hooks --------------------------------------------------------

    def make_dispatch(self, device=None):
        dispatch, _, is_async = _dispatchers(
            self.backend, self.mode, device=device
        )
        return dispatch, is_async

    def shape_key(self, requests, payload_a, payload_b):
        if self.mode == "batched":
            # the combined kernel clone-pads lanes to a power of two
            # internally (tpu/backend.batch_verify_combined) — key on
            # THAT shape so varying coalesced sizes within one pow2
            # bucket count as a single compiled program
            return ("batched", _next_pow2(max(1, len(payload_a))))
        return super().shape_key(requests, payload_a, payload_b)

    def assemble(self, requests, bspan):
        if self.pad_partial:
            sigs, messages_list, n_pad = pad_batch(requests, self.max_batch)
            bspan.set(n_pad=n_pad)
        else:
            sigs = [r.sig for r in requests]
            messages_list = [r.messages for r in requests]
        return sigs, messages_list

    def run_dispatch(self, executor, sigs, messages_list):
        # the bare `.dispatch` attribute, not the program registry: the
        # verify program IS every pool executor's primary dispatch (and
        # tests stub `ex.dispatch` directly)
        if self.keychain is None:
            return executor.dispatch(
                sigs, messages_list, self.vk, self.params
            )
        return self._dispatch_by_epoch(
            lambda s, m, vk: executor.dispatch(s, m, vk, self.params),
            sigs,
            messages_list,
        )

    def make_fallback(self, sigs, messages_list):
        if self._fallback_dispatch is None:
            return None
        if self.keychain is None:
            return lambda: self._fallback_dispatch(
                sigs, messages_list, self.vk, self.params
            )()
        return lambda: self._dispatch_by_epoch(
            lambda s, m, vk: self._fallback_dispatch(s, m, vk, self.params)(),
            sigs,
            messages_list,
        )

    def demux(self, requests, result, sigs, messages_list, seq, attempts,
              bspan):
        clock = self.engine.clock
        if self.mode == "per_credential":
            demux(requests, result[: len(requests)], clock=clock)
            bspan.end(result="demuxed")
            return
        if result:
            demux(requests, [True] * len(requests), clock=clock)
            bspan.end(result="accepted")
            return
        # grouped rejection: recover per-request verdicts by bisection so
        # one forged credential fails only its own future; culprit
        # dead-letter lines carry the CULPRIT request's trace_id (not the
        # batch's), so an operator greps straight from a JSONL line to
        # the request's span tree — which names the device via its batch
        # span
        culprits = (
            set(
                self._bisector(
                    sigs,
                    messages_list,
                    seq,
                    attempts,
                    trace_ids=[r.future.trace_id for r in requests],
                )
            )
            if self._bisector is not None
            else set(range(len(requests)))
        )
        for i in culprits:
            if i < len(requests):
                requests[i].span.event("dead_letter", batch_seq=seq)
        demux(
            requests,
            [i not in culprits for i in range(len(requests))],
            clock=clock,
        )
        bspan.end(result="bisected", n_culprits=len(culprits))

    def fail_batch(self, requests, exc):
        fail_all(requests, exc)


class CredentialService(ExecutionEngine):
    """Dynamic-batching verify service over any verify-capable backend.

    backend / fallback_backend: instances or registry names ("python",
    "jax", ...). mode: "per_credential" (bits demux directly), "grouped"
    (one device bool per batch; a rejection bisects to per-request
    verdicts, culprits dead-lettered), or "batched" (PR 16: ONE
    RLC-combined pairing product + shared final exponentiation per batch,
    same accept/bisect ladder as grouped but the bisection probes re-draw
    combiners per sub-slice). mode=None resolves via COCONUT_BATCH_VERIFY
    ("1"/"batched" -> "batched", else "per_credential").
    max_batch: the coalesced device
    shape. max_wait_ms: default per-request coalescing deadline.
    max_depth: admission bound. pad_partial: identity-pad partial batches
    to max_batch (per_credential mode) so jit shapes stay cache-hot —
    grouped mode never pads, its encode pads internally to a power of two.
    clock: injectable time source for deadline tests.

    Pool shape (PR 6): `devices` is None (one executor, the PR-4
    behavior), an int N (N executors — worker-thread parallelism for
    backends without device placement), or a list of jax devices (one
    executor pinned to each). `mesh` adds the dp-sharded mesh dispatch
    lane; batches of >= `sharded_min_lanes` (default max_batch) with no
    interactive requests route through it (see engine._route).

    Self-healing knobs (serve/health.py): `health_policy` configures the
    per-executor circuit breaker, `watchdog` the hung-dispatch deadline
    tracker (pass one with a fake clock for deterministic tests),
    `watchdog_interval_s` the background health-tick period (None
    disables the thread — tests then drive `health_tick()` by hand),
    `brownout` the graded load-shedding policy, `max_redispatch` the hop
    cap for redistributed batches (default: pool size - 1, so a poisonous
    batch can visit each survivor at most once before failing loudly)."""

    def __init__(
        self,
        backend,
        vk,
        params,
        mode=None,
        max_batch=64,
        max_wait_ms=20.0,
        max_depth=1024,
        retry_policy=None,
        fallback_backend=None,
        dead_letter_path=None,
        pad_partial=True,
        clock=time.monotonic,
        devices=None,
        mesh=None,
        sharded_min_lanes=None,
        health_policy=None,
        watchdog=None,
        watchdog_interval_s=0.25,
        brownout=None,
        max_redispatch=None,
        state_store=None,
    ):
        from ..backend import get_backend
        from ..errors import TransientBackendError

        if backend is None or isinstance(backend, str):
            backend = get_backend(backend or "python")
        if isinstance(fallback_backend, str):
            fallback_backend = get_backend(fallback_backend)
        if mode is None:
            # COCONUT_BATCH_VERIFY=1 defaults new services onto the
            # RLC-combined path (PR 16); unset keeps per_credential
            from ..batchverify import env_batched_default

            mode = "batched" if env_batched_default() else "per_credential"
        if mode not in ("per_credential", "grouped", "batched"):
            raise ValueError("unknown serve mode %r" % (mode,))

        super().__init__(
            name="coconut-serve",
            metric_ns="serve",
            clock=clock,
            mesh=mesh,
            sharded_min_lanes=(
                max_batch if sharded_min_lanes is None else sharded_min_lanes
            ),
            health_policy=health_policy,
            watchdog=watchdog,
            watchdog_interval_s=watchdog_interval_s,
            brownout=brownout,
        )

        self.backend = backend
        self.vk = vk
        self.params = params
        self.mode = mode
        self.max_batch = max_batch
        self.max_wait_ms = max_wait_ms
        self.pad_partial = pad_partial and mode == "per_credential"
        #: state.StateStore (PR 17): a verify-only service carries no
        #: nullifier guard (double-spend lives on the show-verify lane,
        #: engine/phases.py), but exposing the store here lets its
        #: Replica advertise state marks and serve anti-entropy pulls —
        #: a verify fleet can still host replicated state.
        self.state_store = state_store

        self._fallback_dispatch = (
            _fallback_dispatcher(fallback_backend, mode)
            if fallback_backend is not None
            else None
        )
        if retry_policy is None:
            # mirror verify_stream: no ladder means transient errors go
            # straight to the fallback when one exists, else propagate
            retry_policy = RetryPolicy(
                max_attempts=1,
                base_delay=0.0,
                retryable=(
                    (TransientBackendError,)
                    if self._fallback_dispatch is not None
                    else ()
                ),
            )
        self._policy = retry_policy
        self._bisector = (
            _make_bisector(
                backend,
                fallback_backend,
                vk,
                params,
                retry_policy,
                dead_letter_path,
                program="verify",
                predicate="combined" if mode == "batched" else "grouped",
            )
            if mode in ("grouped", "batched")
            else None
        )

        self._program = VerifyProgram(
            backend,
            vk,
            params,
            mode,
            max_batch,
            max_wait_ms,
            max_depth,
            self.pad_partial,
            retry_policy,
            self._fallback_dispatch,
            self._bisector,
        )
        self.register(self._program)

        # the device pool: one executor per device, the verify program's
        # device-pinned dispatch as each executor's primary closure
        if devices is None:
            device_list = [None]
        elif isinstance(devices, int):
            if devices < 1:
                raise ValueError("devices must be >= 1 (got %r)" % (devices,))
            device_list = [None] * devices
        else:
            device_list = list(devices)
            if not device_list:
                raise ValueError("devices list must be non-empty")
        for dev in device_list:
            dispatch, is_async = self._program.make_dispatch(device=dev)
            self._add_executor(device=dev, dispatch=dispatch,
                               is_async=is_async)

        if mesh is not None:
            pad_to = None
            if mode == "grouped" and "dp" in mesh.shape:
                # ONE fixed grouped shape across all occupancy levels:
                # the sharded encode's own floor (2*ndp) or the service's
                # max batch rounded to the kernel's power-of-two, whichever
                # is larger — varying coalesced sizes never recompile
                pad_to = max(2 * mesh.shape["dp"], _next_pow2(max_batch))
            mesh_dispatch, _, _ = _dispatchers(
                backend, mode, mesh=mesh, mesh_pad_to=pad_to
            )
            self._set_mesh_executor(mesh_dispatch)

        self._finalize_pool(max_redispatch)

    # -- client side ---------------------------------------------------------

    def submit(self, sig, messages, lane="interactive", max_wait_ms=None):
        """Admit one verify request; returns its ServeFuture (resolves to
        the request's own verdict bool). Raises ServiceBrownoutError when
        graded load-shedding refuses this lane (retriable, carries a
        retry-after hint), ServiceOverloadedError at the admission bound,
        ServiceClosedError after drain/shutdown."""
        return self.submit_request(
            "verify", sig, messages, lane=lane, max_wait_ms=max_wait_ms
        )
