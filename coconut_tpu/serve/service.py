"""The online credential service: a supervisor loop wiring the deadline
batcher into the existing offline machinery.

One background thread owns the device: it pops coalesced batches off the
request queue (serve/batcher.py), dispatches them through the SAME seams
the offline stream uses, and demuxes per-credential verdicts back onto the
originating futures. Everything fault- and perf-related is reused, not
reinvented:

  - PR-2 supervision: each batch's dispatch+readback cycle runs under
    `retry.call_with_retry` (bounded backoff, deterministic jitter), then
    degrades to `fallback_backend`; in grouped mode a rejected batch is
    bisected with `stream._make_bisector` — grouped probes over halved
    slices, per-credential at the leaves — so ONE forged credential fails
    ITS future (and lands in the dead-letter JSONL) while every cohabiting
    request in the batch resolves valid.
  - PR-3 pipelining: dispatch goes through the backends' `*_async` seams
    (probed by `stream._dispatchers`), so while the device runs batch i
    the supervisor coalesces and host-encodes batch i+1 — the encode rides
    the static-operand cache, so at steady state it is signature points +
    scalar digits only. One batch stays in flight (double-buffering);
    when no new batch is ready the in-flight one settles immediately, so
    idle-tail latency never waits on future traffic.

Request path: `submit()` -> admission control (bounded queue, typed
rejection) -> coalesce (full batch or oldest deadline) -> identity-pad to
the cache-hot shape -> dispatch under retry/fallback -> demux -> future
resolves. Per-request latency lands in the "serve_latency_s" histogram
(`metrics.snapshot()["histograms"]`), the SLO readout.

Tracing (coconut_tpu/obs, COCONUT_TRACE=1): each coalesced batch is a
trace of its own — root "batch" span with "coalesce" (pad/assemble),
"dispatch" (host encode + device dispatch), "device" (blocking readback)
and "demux" children; retry attempts, fallback switches, and bisection
splits land as events on the active span (retry.py / stream.py record
them). The batch span links its member requests' trace_ids (and each
request span carries `batch_trace` back), so a request's tree joins to
the batch work done on its behalf; culprits isolated by bisection get a
"dead_letter" event on THEIR request span and their trace_id in the
dead-letter JSONL line.

Lifecycle: `start()` launches the supervisor; `drain()` closes intake,
flushes and settles everything in flight, and joins the thread — every
accepted future is resolved. `shutdown(drain=False)` instead fails still-
QUEUED requests with `ServiceClosedError` (in-flight work still settles).
A supervisor crash sweeps all queued+in-flight futures with the crash
exception — no caller ever hangs on a dropped future. The context-manager
form (`with CredentialService(...) as svc:`) is start()/drain().
"""

import threading
import time

from .. import metrics
from ..errors import ServiceClosedError
from ..obs import trace as otrace
from ..retry import RetryPolicy, call_with_retry, note_attempt
from ..stream import _dispatchers, _fallback_dispatcher, _make_bisector
from .batcher import Batcher, demux, fail_all, pad_batch
from .queue import RequestQueue


class CredentialService:
    """Dynamic-batching verify service over any verify-capable backend.

    backend / fallback_backend: instances or registry names ("python",
    "jax", ...). mode: "per_credential" (bits demux directly) or "grouped"
    (one device bool per batch; a rejection bisects to per-request
    verdicts, culprits dead-lettered). max_batch: the coalesced device
    shape. max_wait_ms: default per-request coalescing deadline.
    max_depth: admission bound. pad_partial: identity-pad partial batches
    to max_batch (per_credential mode) so jit shapes stay cache-hot —
    grouped mode never pads, its encode pads internally to a power of two.
    clock: injectable time source for deadline tests."""

    def __init__(
        self,
        backend,
        vk,
        params,
        mode="per_credential",
        max_batch=64,
        max_wait_ms=20.0,
        max_depth=1024,
        retry_policy=None,
        fallback_backend=None,
        dead_letter_path=None,
        pad_partial=True,
        clock=time.monotonic,
    ):
        from ..backend import get_backend
        from ..errors import TransientBackendError

        if backend is None or isinstance(backend, str):
            backend = get_backend(backend or "python")
        if isinstance(fallback_backend, str):
            fallback_backend = get_backend(fallback_backend)
        if mode not in ("per_credential", "grouped"):
            raise ValueError("unknown serve mode %r" % (mode,))
        self.backend = backend
        self.vk = vk
        self.params = params
        self.mode = mode
        self.max_batch = max_batch
        self.max_wait_ms = max_wait_ms
        self.pad_partial = pad_partial and mode == "per_credential"
        self.clock = clock
        self._dispatch, _, self._is_async = _dispatchers(backend, mode)
        self._fallback_dispatch = (
            _fallback_dispatcher(fallback_backend, mode)
            if fallback_backend is not None
            else None
        )
        if retry_policy is None:
            # mirror verify_stream: no ladder means transient errors go
            # straight to the fallback when one exists, else propagate
            retry_policy = RetryPolicy(
                max_attempts=1,
                base_delay=0.0,
                retryable=(
                    (TransientBackendError,)
                    if self._fallback_dispatch is not None
                    else ()
                ),
            )
        self._policy = retry_policy
        self._bisector = (
            _make_bisector(
                backend,
                fallback_backend,
                vk,
                params,
                retry_policy,
                dead_letter_path,
            )
            if mode == "grouped"
            else None
        )
        self._queue = RequestQueue(max_depth=max_depth, clock=clock)
        self._batcher = Batcher(self._queue, max_batch, clock=clock)
        self._thread = None
        self._batch_seq = 0  # dead-letter batch ids + retry jitter keys
        self._crashed = None

    # -- client side ---------------------------------------------------------

    def submit(self, sig, messages, lane="interactive", max_wait_ms=None):
        """Admit one verify request; returns its ServeFuture (resolves to
        the request's own verdict bool). Raises ServiceOverloadedError at
        the admission bound, ServiceClosedError after drain/shutdown."""
        if self._crashed is not None:
            raise ServiceClosedError(
                "service supervisor crashed: %r" % (self._crashed,)
            )
        return self._queue.submit(
            sig,
            messages,
            lane=lane,
            max_wait_ms=(
                self.max_wait_ms if max_wait_ms is None else max_wait_ms
            ),
        )

    def depth(self):
        return self._queue.depth()

    def kick(self):
        """Wake the supervisor to re-read the clock (fake-clock tests)."""
        self._queue.kick()

    # -- lifecycle -----------------------------------------------------------

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="coconut-serve", daemon=True
            )
            self._thread.start()
        return self

    def drain(self, timeout=None):
        """Close intake, settle every accepted request, join the
        supervisor. Every accepted future is resolved on return (True iff
        the supervisor exited within `timeout`)."""
        self._queue.close()
        if self._thread is None:
            # never started: nothing will settle the queue — fail loudly
            fail_all(
                self._queue.drain_pending(),
                ServiceClosedError("service drained before start()"),
                counter="serve_cancelled",
            )
            return True
        self._thread.join(timeout)
        return not self._thread.is_alive()

    def shutdown(self, drain=True, timeout=None):
        """drain=True: alias for drain(). drain=False: refuse the queued
        backlog (futures fail with ServiceClosedError) but still settle
        work already in flight, then join."""
        if drain:
            return self.drain(timeout)
        self._queue.close()
        fail_all(
            self._queue.drain_pending(),
            ServiceClosedError("service shut down before this request ran"),
            counter="serve_cancelled",
        )
        if self._thread is not None:
            self._thread.join(timeout)
            return not self._thread.is_alive()
        return True

    def __enter__(self):
        return self.start()

    def __exit__(self, exc_type, exc, tb):
        self.drain()
        return False

    # -- supervisor ----------------------------------------------------------

    def _launch(self, requests):
        """Assemble + dispatch one coalesced batch NOW; return the settle
        closure state. Mirrors stream.verify_stream's launch(): the first
        dispatch attempt is consumed eagerly (pipelining), finalize()
        re-runs the full dispatch+readback cycle under the retry ladder,
        then the fallback."""
        seq = self._batch_seq
        self._batch_seq += 1
        bspan = otrace.start_span(
            "batch",
            root=True,
            seq=seq,
            n=len(requests),
            members=[r.future.trace_id for r in requests]
            if otrace.enabled()
            else None,
        )
        for r in requests:
            # the request->batch join: a request's trace knows which
            # batch trace did its device work (flight dumps follow it)
            r.span.set(batch_trace=bspan.trace_id, batch_seq=seq)
        with otrace.use(bspan):
            with otrace.span("coalesce"):
                if self.pad_partial:
                    sigs, messages_list, n_pad = pad_batch(
                        requests, self.max_batch
                    )
                    bspan.set(n_pad=n_pad)
                else:
                    sigs = [r.sig for r in requests]
                    messages_list = [r.messages for r in requests]
            metrics.observe(
                "serve_batch_wait_s",
                self.clock() - min(r.t_submit for r in requests),
            )
            attempts = []
            box = [None]
            permanent = None
            with otrace.span("dispatch", backend=type(self.backend).__name__):
                try:
                    box[0] = self._dispatch(
                        sigs, messages_list, self.vk, self.params
                    )
                except self._policy.retryable as e:
                    note_attempt(attempts, e)
                    otrace.event(
                        "attempt_failed",
                        attempt=len(attempts),
                        error=type(e).__name__,
                    )
                except Exception as e:
                    # permanent dispatch failure (bad inputs, code bug in
                    # a sync backend's compute): unlike the offline
                    # stream — where it aborts the run — the service
                    # contains it to THIS batch's futures; finalize
                    # re-raises without burning retries
                    permanent = e
                    otrace.event("permanent_failure", error=type(e).__name__)

        def cycle():
            fin, box[0] = box[0], None
            if fin is None:
                fin = self._dispatch(
                    sigs, messages_list, self.vk, self.params
                )
            return fin()

        fallback = (
            (
                lambda: self._fallback_dispatch(
                    sigs, messages_list, self.vk, self.params
                )()
            )
            if self._fallback_dispatch is not None
            else None
        )

        def finalize():
            if permanent is not None:
                raise permanent
            return call_with_retry(
                cycle,
                self._policy,
                key=seq,
                attempts=attempts,
                fallback=fallback,
            )

        return (seq, requests, sigs, messages_list, finalize, attempts, bspan)

    def _settle(
        self, seq, requests, sigs, messages_list, finalize, attempts, bspan
    ):
        """Block on the batch result and resolve every request's future."""
        with otrace.use(bspan):
            try:
                with otrace.span("device"):
                    result = finalize()
            except Exception as e:
                # batch-level failure past retry+fallback: each
                # cohabiting future gets the exception — never a silent
                # hang
                fail_all(requests, e)
                bspan.end(error=type(e).__name__)
                return
            if self.mode == "per_credential":
                demux(requests, result[: len(requests)], clock=self.clock)
                bspan.end(result="demuxed")
                return
            if result:
                demux(requests, [True] * len(requests), clock=self.clock)
                bspan.end(result="accepted")
                return
            # grouped rejection: recover per-request verdicts by
            # bisection so one forged credential fails only its own
            # future; culprit dead-letter lines carry the CULPRIT
            # request's trace_id (not the batch's), so an operator greps
            # straight from a JSONL line to the request's span tree
            culprits = (
                set(
                    self._bisector(
                        sigs,
                        messages_list,
                        seq,
                        attempts,
                        trace_ids=[r.future.trace_id for r in requests],
                    )
                )
                if self._bisector is not None
                else set(range(len(requests)))
            )
            for i in culprits:
                if i < len(requests):
                    requests[i].span.event("dead_letter", batch_seq=seq)
            demux(
                requests,
                [i not in culprits for i in range(len(requests))],
                clock=self.clock,
            )
            bspan.end(result="bisected", n_culprits=len(culprits))

    def _run(self):
        pending = None
        try:
            while True:
                batch = self._batcher.next_batch(block=pending is None)
                if batch:
                    launched = self._launch(batch)
                    if pending is not None:
                        self._settle(*pending)
                        pending = None
                    if self._is_async:
                        # double-buffer: leave this batch in flight and go
                        # coalesce+encode the next while the device runs
                        pending = launched
                    else:
                        self._settle(*launched)
                    continue
                if pending is not None:
                    # nothing ready to overlap with: settle the in-flight
                    # batch now instead of holding its latency hostage
                    self._settle(*pending)
                    pending = None
                    continue
                # blocking pop returned empty: closed and fully drained
                return
        except BaseException as e:  # supervisor crash: sweep every future
            self._crashed = e
            if pending is not None:
                fail_all(pending[1], e)
                otrace.end_span(pending[6], error=type(e).__name__)
            self._queue.close()
            fail_all(self._queue.drain_pending(), e)
            raise
