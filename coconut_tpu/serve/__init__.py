"""Online serving layer: dynamic-batching credential verification.

Individual show/verify requests arrive asynchronously (the deployment
shape of PAPER.md's Coconut: users present credentials one at a time);
the TPU backend only earns its throughput on device-sized batches. This
package closes that gap — the continuous-batching problem inference
servers solve, applied to credential verification:

  queue.py    bounded two-lane request queue, per-request futures,
              loud typed admission control (ServiceOverloadedError)
  batcher.py  deadline-driven coalescer: flush at max_batch or at the
              oldest request's max_wait_ms deadline; identity-lane pad
              partial batches so jit shapes stay cache-hot; demux
              verdict bits back onto the originating futures
  service.py  the placer thread + per-device executor pool: adaptive
              placement (least-loaded single device, or dp-sharded
              across the mesh for large bulk batches), each executor
              running PR-3 async double-buffering, every batch under
              the PR-2 retry/fallback/bisection ladder (one forged
              credential fails ITS future and is dead-lettered,
              cohabitants pass — per batch, hence per device),
              start/drain/shutdown
  health.py   the self-healing layer: per-executor circuit-breaker state
              machine (HEALTHY -> SUSPECT -> QUARANTINED -> PROBATION),
              the hung-dispatch Watchdog (k x EMA deadline budgets), and
              the BrownoutPolicy for graded load-shedding (bulk lane
              sheds first, typed retriable ServiceBrownoutError)
  loadgen.py  closed- and open-loop (Poisson) load generation with
              p50/p95/p99 latency, goodput, occupancy, rejection/shed
              report

See README.md "Online serving" and "Self-healing & overload" for
architecture and tuning guidance.
"""

from .health import (
    HEALTHY,
    PROBATION,
    QUARANTINED,
    SUSPECT,
    BrownoutPolicy,
    ExecutorHealth,
    HealthPolicy,
    Watchdog,
)
from .loadgen import run_loadgen, run_session_loadgen
from .queue import DEFAULT_MAX_WAIT_MS, LANES, RequestQueue, ServeFuture


def __getattr__(name):
    # service.py imports the engine, which imports this package's
    # health/queue/batcher modules — resolve CredentialService lazily so
    # the package can finish initializing mid-cycle
    if name == "CredentialService":
        from .service import CredentialService

        return CredentialService
    raise AttributeError("module %r has no attribute %r" % (__name__, name))


__all__ = [
    "CredentialService",
    "RequestQueue",
    "ServeFuture",
    "run_loadgen",
    "run_session_loadgen",
    "LANES",
    "DEFAULT_MAX_WAIT_MS",
    "HealthPolicy",
    "ExecutorHealth",
    "Watchdog",
    "BrownoutPolicy",
    "HEALTHY",
    "SUSPECT",
    "QUARANTINED",
    "PROBATION",
]
