"""System parameters and group-assignment configuration.

The reference picks which pairing group holds signatures vs verkeys through
cargo features `SignatureG1`/`SignatureG2` (Cargo.toml:24-27, lib.rs:3-4) —
with the wiring quirk that the flags don't actually forward to ps_sig
(SURVEY.md §1). Here the choice is a real runtime config: a `GroupContext`
object is the single source of truth, carried inside `Params`.

`Params.new` reproduces the reference's deterministic label-derived setup
(signature.rs:22-32): all parties derive identical params from a label, which
is the implicit config-distribution mechanism — params need no storage or
network distribution (SURVEY.md §5 checkpoint notes).
"""

from .errors import DeserializationError, GeneralError
from .ops import serialize as ser
from .ops.curve import g1 as _g1_ops, g2 as _g2_ops
from .ops.hashing import hash_to_g1, hash_to_g2
from .ops.pairing import pairing_check as _raw_pairing_check


class GroupContext:
    """Binds the abstract roles SignatureGroup / OtherGroup to concrete
    groups, with hashing, serialization, and correctly-ordered pairing."""

    def __init__(self, name):
        if name == "G1":
            self.sig, self.other = _g1_ops, _g2_ops
            self.hash_to_sig, self.hash_to_other = hash_to_g1, hash_to_g2
            self.sig_to_bytes, self.other_to_bytes = (
                ser.g1_to_bytes,
                ser.g2_to_bytes,
            )
            self.sig_from_bytes, self.other_from_bytes = (
                ser.g1_from_bytes,
                ser.g2_from_bytes,
            )
            self.sig_nbytes, self.other_nbytes = 96, 192
        elif name == "G2":
            self.sig, self.other = _g2_ops, _g1_ops
            self.hash_to_sig, self.hash_to_other = hash_to_g2, hash_to_g1
            self.sig_to_bytes, self.other_to_bytes = (
                ser.g2_to_bytes,
                ser.g1_to_bytes,
            )
            self.sig_from_bytes, self.other_from_bytes = (
                ser.g2_from_bytes,
                ser.g1_from_bytes,
            )
            self.sig_nbytes, self.other_nbytes = 192, 96
        else:
            raise GeneralError("unknown signature group %r" % name)
        self.name = name

    def pairing_check(self, pairs):
        """prod e(sig_i, other_i) == 1, with arguments mapped to the concrete
        (G1, G2) order the pairing needs."""
        if self.name == "G1":
            ordered = [(s, o) for s, o in pairs]
        else:
            ordered = [(o, s) for s, o in pairs]
        return _raw_pairing_check(ordered)


SIGNATURES_IN_G1 = GroupContext("G1")
SIGNATURES_IN_G2 = GroupContext("G2")
DEFAULT_CTX = SIGNATURES_IN_G1


class Params:
    """Setup output: g in SignatureGroup, g_tilde in OtherGroup, one h per
    message (signature.rs:13-37)."""

    def __init__(self, g, g_tilde, h, ctx=DEFAULT_CTX):
        self.g = g
        self.g_tilde = g_tilde
        self.h = list(h)
        self.ctx = ctx

    @classmethod
    def new(cls, msg_count, label, ctx=DEFAULT_CTX):
        """Deterministic params from a label with the reference's exact
        domain-separating suffixes (signature.rs:23-29)."""
        label = bytes(label)
        g = ctx.hash_to_sig(label + b" : g")
        g_tilde = ctx.hash_to_other(label + b" : g_tilde")
        h = [
            ctx.hash_to_sig(label + b" : y" + str(i).encode())
            for i in range(msg_count)
        ]
        return cls(g, g_tilde, h, ctx)

    def msg_count(self):
        return len(self.h)

    def to_bytes(self):
        out = [self.ctx.sig_to_bytes(self.g), self.ctx.other_to_bytes(self.g_tilde)]
        out.extend(self.ctx.sig_to_bytes(hi) for hi in self.h)
        return b"".join(out)

    @classmethod
    def from_bytes(cls, b, ctx=DEFAULT_CTX):
        head = ctx.sig_nbytes + ctx.other_nbytes
        if len(b) < head or (len(b) - head) % ctx.sig_nbytes:
            raise DeserializationError("malformed Params encoding")
        g = ctx.sig_from_bytes(b[: ctx.sig_nbytes])
        g_tilde = ctx.other_from_bytes(b[ctx.sig_nbytes : head])
        h = [
            ctx.sig_from_bytes(b[o : o + ctx.sig_nbytes])
            for o in range(head, len(b), ctx.sig_nbytes)
        ]
        return cls(g, g_tilde, h, ctx)

    def __eq__(self, other):
        return (
            isinstance(other, Params)
            and self.g == other.g
            and self.g_tilde == other.g_tilde
            and self.h == other.h
            and self.ctx.name == other.ctx.name
        )
