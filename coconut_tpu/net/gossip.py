"""Health gossip: the router's per-replica health directory, fed by
periodic beacons (PR 13).

Each replica self-reports a wire.Beacon (engine health-ladder summary,
queue depth, brownout flag) when polled on its beacon endpoint. The
router's GossipLoop polls every replica each interval; the
HealthDirectory folds the results into a routing view with the SAME
demotion shape PR 9 gives executors:

  WARMING   registered but not yet proven ready: either no beacon has
            landed yet (a freshly registered replica starts here — PR 14
            closed the optimistic-UP hole where a new registration could
            receive traffic before its first beacon) or the replica's
            beacon self-reports "warming" (lifecycle warmup: manifest
            replay / compilation-cache priming still running)
            -> never receives NEW sessions, not even as a spill target
  UP        beacons arriving, replica reports admissible capacity
  DEGRADED  beacons arriving, but the replica reports itself
            quarantine-level (zero admissible executors) or browned out
            -> demoted for NEW sessions, eligible only as a last-resort
            spill target
  DRAINING  the replica announced a graceful shutdown (beacon state
            "draining", or the data path received a retryable
            closed-replica refusal — note_draining): in-flight work is
            settling there but NEW sessions must go elsewhere
  DOWN      `miss_threshold` consecutive poll failures (or an explicit
            transport failure reported by the router's data path)
            -> not routed to at all; an in-flight failure there is
            retried on survivors

A DOWN or WARMING replica joins/rejoins the moment a fresh admissible
beacon lands — restart-and-readmit needs no operator action, exactly
like the probation ladder re-admits executors. A DRAINING replica that
completes its restart comes back the same way: its successor process
beacons "warming" then "healthy".

Counters: "gateway_beacons", "gateway_beacon_misses",
"gateway_demoted", "gateway_readmitted", "gateway_warmed" (first
admissible beacon promoted a WARMING replica), "gateway_drain_observed"
(a beacon or data-path refusal moved a replica into DRAINING); gauge
"gateway_up_replicas". Clock and polling are injectable: fake-clock
tests call `step()` directly and never sleep.
"""

import threading
import time

from .. import metrics

WARMING = "warming"
UP = "up"
DEGRADED = "degraded"
DRAINING = "draining"
DOWN = "down"


class _ReplicaView:
    __slots__ = ("state", "beacon", "misses", "t_beacon")

    def __init__(self):
        # pessimistic until the first admissible beacon lands: a freshly
        # registered replica may still be compiling (lifecycle WARMING)
        # and must not receive traffic on registration alone
        self.state = WARMING
        self.beacon = None
        self.misses = 0
        self.t_beacon = None


class HealthDirectory:
    """The router's view of every replica's health. Thread-safe: the
    gossip loop writes while router data-path threads read and report
    transport failures."""

    def __init__(self, replica_ids=(), miss_threshold=3):
        if miss_threshold < 1:
            raise ValueError(
                "miss_threshold must be >= 1 (got %r)" % (miss_threshold,)
            )
        self.miss_threshold = miss_threshold
        self._lock = threading.Lock()
        self._views = {}
        for rid in replica_ids:
            self._views[rid] = _ReplicaView()
        self._publish_locked()

    def _view(self, rid):
        v = self._views.get(rid)
        if v is None:
            v = self._views[rid] = _ReplicaView()
        return v

    def _publish_locked(self):
        metrics.set_gauge(
            "gateway_up_replicas",
            sum(1 for v in self._views.values() if v.state == UP),
        )

    def observe(self, beacon, now=None):
        """Fold one received beacon in; a DOWN/DEGRADED/WARMING replica
        whose fresh beacon reports admissible capacity is (re)admitted.
        Lifecycle self-reports map straight through: a beacon stating
        "warming" or "draining" pins the view to that state regardless of
        the capacity fields it carries."""
        with self._lock:
            v = self._view(beacon.replica_id)
            was = v.state
            v.beacon = beacon
            v.misses = 0
            v.t_beacon = now
            if beacon.state == "warming":
                v.state = WARMING
            elif beacon.state == "draining":
                v.state = DRAINING
            else:
                degraded = (not beacon.admissible()) or beacon.brownout
                v.state = DEGRADED if degraded else UP
            if was == WARMING and v.state in (UP, DEGRADED):
                metrics.count("gateway_warmed")
            elif was not in (UP, WARMING) and v.state == UP:
                metrics.count("gateway_readmitted")
            if was == UP and v.state != UP:
                metrics.count("gateway_demoted")
            if was != DRAINING and v.state == DRAINING:
                metrics.count("gateway_drain_observed")
            metrics.count("gateway_beacons")
            self._publish_locked()

    def miss(self, rid):
        """One failed beacon poll; `miss_threshold` consecutive misses
        demote the replica to DOWN."""
        with self._lock:
            v = self._view(rid)
            v.misses += 1
            metrics.count("gateway_beacon_misses")
            if v.misses >= self.miss_threshold and v.state != DOWN:
                v.state = DOWN
                metrics.count("gateway_demoted")
            self._publish_locked()

    def note_failure(self, rid):
        """The router's DATA PATH hit a transport failure on `rid`:
        demote immediately — waiting out miss_threshold beacon intervals
        would keep routing sessions into a dead socket."""
        with self._lock:
            v = self._view(rid)
            v.misses = max(v.misses, self.miss_threshold)
            if v.state != DOWN:
                v.state = DOWN
                metrics.count("gateway_demoted")
            self._publish_locked()

    def note_draining(self, rid):
        """The router's DATA PATH received a retryable closed-replica
        refusal from `rid`: it is mid-graceful-shutdown. Softer than
        note_failure — the replica still answers beacon polls (which will
        confirm or supersede this), but NEW sessions must stop landing on
        it NOW, not an interval from now."""
        with self._lock:
            v = self._view(rid)
            if v.state not in (DOWN, DRAINING):
                v.state = DRAINING
                metrics.count("gateway_drain_observed")
            self._publish_locked()

    def state(self, rid):
        with self._lock:
            return self._view(rid).state

    def beacon(self, rid):
        with self._lock:
            return self._views[rid].beacon if rid in self._views else None

    def epochs(self, rid):
        """Live (epoch_id, state) pairs `rid` last advertised (wire v2
        beacons; () when no beacon has landed or the replica runs no key
        lifecycle) — the router's view of which mint epochs still verify
        there."""
        with self._lock:
            v = self._views.get(rid)
            if v is None or v.beacon is None:
                return ()
            return tuple(getattr(v.beacon, "epochs", ()) or ())

    def state_marks(self, rid):
        """Per-keyspace state high-water marks `rid` last advertised
        ((keyspace, origin, seq) triples; wire v3 beacons; () when no
        beacon has landed or the replica runs no StateStore) — the
        StateReplicator's gap-detection input (state/replicate.py)."""
        with self._lock:
            v = self._views.get(rid)
            if v is None or v.beacon is None:
                return ()
            return tuple(getattr(v.beacon, "state_marks", ()) or ())

    def queue_depth(self, rid):
        """Last-beacon queue depth (the least-loaded spill key); unknown
        replicas sort last."""
        with self._lock:
            v = self._views.get(rid)
            if v is None or v.beacon is None:
                return float("inf")
            return v.beacon.queue_depth

    def states(self):
        with self._lock:
            return {rid: v.state for rid, v in self._views.items()}

    def routable(self, rid):
        return self.state(rid) == UP

    def usable(self, rid):
        """UP or DEGRADED — the spill pool (DEGRADED beats DOWN: a
        browned-out replica still answers, a dead one does not). WARMING
        and DRAINING are excluded on purpose: placing a new session on a
        still-compiling or mid-shutdown replica trades a short spill for
        a guaranteed slow or refused request."""
        return self.state(rid) in (UP, DEGRADED)


class GossipLoop:
    """Poll every replica's beacon endpoint each interval and feed the
    directory. `pollers` maps replica_id -> zero-arg callable returning a
    wire.Beacon (raising on transport failure = a miss). Fake-clock tests
    call step() directly; start() runs the real thread."""

    def __init__(
        self,
        directory,
        pollers,
        interval_s=0.25,
        clock=time.monotonic,
    ):
        self.directory = directory
        self.pollers = dict(pollers)
        self.interval_s = interval_s
        self.clock = clock
        self._stop = threading.Event()
        self._thread = None

    def step(self, now=None):
        """One poll sweep across every replica."""
        now = self.clock() if now is None else now
        for rid, poll in self.pollers.items():
            try:
                beacon = poll()
            except Exception:
                self.directory.miss(rid)
                continue
            self.directory.observe(beacon, now=now)

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="gateway-gossip", daemon=True
            )
            self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.interval_s):
            self.step()

    def stop(self, timeout=None):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            alive = self._thread.is_alive()
            self._thread = None
            return not alive
        return True
