"""Health gossip: the router's per-replica health directory, fed by
periodic beacons (PR 13).

Each replica self-reports a wire.Beacon (engine health-ladder summary,
queue depth, brownout flag) when polled on its beacon endpoint. The
router's GossipLoop polls every replica each interval; the
HealthDirectory folds the results into a routing view with the SAME
demotion shape PR 9 gives executors:

  UP        beacons arriving, replica reports admissible capacity
  DEGRADED  beacons arriving, but the replica reports itself
            quarantine-level (zero admissible executors) or browned out
            -> demoted for NEW sessions, eligible only as a last-resort
            spill target
  DOWN      `miss_threshold` consecutive poll failures (or an explicit
            transport failure reported by the router's data path)
            -> not routed to at all; an in-flight failure there is
            retried on survivors

A DOWN replica rejoins the moment a fresh admissible beacon lands —
restart-and-readmit needs no operator action, exactly like the
probation ladder re-admits executors.

Counters: "gateway_beacons", "gateway_beacon_misses",
"gateway_demoted", "gateway_readmitted"; gauge "gateway_up_replicas".
Clock and polling are injectable: fake-clock tests call `step()`
directly and never sleep.
"""

import threading
import time

from .. import metrics

UP = "up"
DEGRADED = "degraded"
DOWN = "down"


class _ReplicaView:
    __slots__ = ("state", "beacon", "misses", "t_beacon")

    def __init__(self):
        self.state = UP  # optimistic until beacons say otherwise
        self.beacon = None
        self.misses = 0
        self.t_beacon = None


class HealthDirectory:
    """The router's view of every replica's health. Thread-safe: the
    gossip loop writes while router data-path threads read and report
    transport failures."""

    def __init__(self, replica_ids=(), miss_threshold=3):
        if miss_threshold < 1:
            raise ValueError(
                "miss_threshold must be >= 1 (got %r)" % (miss_threshold,)
            )
        self.miss_threshold = miss_threshold
        self._lock = threading.Lock()
        self._views = {}
        for rid in replica_ids:
            self._views[rid] = _ReplicaView()
        self._publish_locked()

    def _view(self, rid):
        v = self._views.get(rid)
        if v is None:
            v = self._views[rid] = _ReplicaView()
        return v

    def _publish_locked(self):
        metrics.set_gauge(
            "gateway_up_replicas",
            sum(1 for v in self._views.values() if v.state == UP),
        )

    def observe(self, beacon, now=None):
        """Fold one received beacon in; a DOWN/DEGRADED replica whose
        fresh beacon reports admissible capacity is readmitted."""
        with self._lock:
            v = self._view(beacon.replica_id)
            was = v.state
            v.beacon = beacon
            v.misses = 0
            v.t_beacon = now
            degraded = (not beacon.admissible()) or beacon.brownout
            v.state = DEGRADED if degraded else UP
            if was != UP and v.state == UP:
                metrics.count("gateway_readmitted")
            if was == UP and v.state != UP:
                metrics.count("gateway_demoted")
            metrics.count("gateway_beacons")
            self._publish_locked()

    def miss(self, rid):
        """One failed beacon poll; `miss_threshold` consecutive misses
        demote the replica to DOWN."""
        with self._lock:
            v = self._view(rid)
            v.misses += 1
            metrics.count("gateway_beacon_misses")
            if v.misses >= self.miss_threshold and v.state != DOWN:
                v.state = DOWN
                metrics.count("gateway_demoted")
            self._publish_locked()

    def note_failure(self, rid):
        """The router's DATA PATH hit a transport failure on `rid`:
        demote immediately — waiting out miss_threshold beacon intervals
        would keep routing sessions into a dead socket."""
        with self._lock:
            v = self._view(rid)
            v.misses = max(v.misses, self.miss_threshold)
            if v.state != DOWN:
                v.state = DOWN
                metrics.count("gateway_demoted")
            self._publish_locked()

    def state(self, rid):
        with self._lock:
            return self._view(rid).state

    def beacon(self, rid):
        with self._lock:
            return self._views[rid].beacon if rid in self._views else None

    def queue_depth(self, rid):
        """Last-beacon queue depth (the least-loaded spill key); unknown
        replicas sort last."""
        with self._lock:
            v = self._views.get(rid)
            if v is None or v.beacon is None:
                return float("inf")
            return v.beacon.queue_depth

    def states(self):
        with self._lock:
            return {rid: v.state for rid, v in self._views.items()}

    def routable(self, rid):
        return self.state(rid) == UP

    def usable(self, rid):
        """UP or DEGRADED — the spill pool (DEGRADED beats DOWN: a
        browned-out replica still answers, a dead one does not)."""
        return self.state(rid) != DOWN


class GossipLoop:
    """Poll every replica's beacon endpoint each interval and feed the
    directory. `pollers` maps replica_id -> zero-arg callable returning a
    wire.Beacon (raising on transport failure = a miss). Fake-clock tests
    call step() directly; start() runs the real thread."""

    def __init__(
        self,
        directory,
        pollers,
        interval_s=0.25,
        clock=time.monotonic,
    ):
        self.directory = directory
        self.pollers = dict(pollers)
        self.interval_s = interval_s
        self.clock = clock
        self._stop = threading.Event()
        self._thread = None

    def step(self, now=None):
        """One poll sweep across every replica."""
        now = self.clock() if now is None else now
        for rid, poll in self.pollers.items():
            try:
                beacon = poll()
            except Exception:
                self.directory.miss(rid)
                continue
            self.directory.observe(beacon, now=now)

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="gateway-gossip", daemon=True
            )
            self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.interval_s):
            self.step()

    def stop(self, timeout=None):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            alive = self._thread.is_alive()
            self._thread = None
            return not alive
        return True
