"""Versioned wire format for the fleet gateway (PR 13).

Length-prefixed binary frames over a byte stream (loopback sockets in CI,
TCP in deployment). One frame:

    offset  size  field
    0       2     magic    0xC0C7 (big-endian) — stream resync guard
    2       1     version  WIRE_VERSION (decode REJECTS unknown versions)
    3       1     msg_type (request / response / error / beacon, below)
    4       4     seq      u32 request-correlation id (echoed by the
                           response/error frame; beacon sequence number)
    8       4     length   u32 payload byte count (bounded by
                           MAX_FRAME_BYTES — a corrupt length can never
                           make a reader allocate gigabytes)
    12      len   payload

Message types — one request/response pair per engine program, plus the
typed error envelope and the health beacon:

    request   response  program
    0x01      0x41      verify
    0x02      0x42      prepare
    0x03      0x43      mint
    0x04      0x44      show_prove
    0x05      0x45      show_verify
    0x20      0x60      (beacon poll -> health beacon)
    -         0x7F      error envelope (code / program / retry_after_s /
                        retryable / message — errors.WIRE_ERROR_CODES is
                        the 1:1 code <-> class map)

Payload encodings reuse the library's canonical CTS-v1 serializers
(Signature / SignatureRequest / PoKOfSignatureProof .to_bytes, Fr as
32-byte big-endian) via a `WireCodec` bound to the deployment's Params —
byte-for-byte deterministic, so tests/test_gateway.py pins golden
vectors. Every decode is STRICT: truncated frames, trailing bytes, bad
magic, unknown versions and non-canonical field encodings all raise
DeserializationError (mapped to a non-retryable "bad_request" envelope
by the server) rather than producing a half-parsed request.
"""

import json
import struct

from ..errors import DeserializationError, error_from_wire
from ..keylife.epoch import EPOCH_STATE_CODES, EPOCH_STATE_OF_CODE
from ..ops import serialize as ser
from ..serve.queue import LANES

#: bump when the frame layout or any payload encoding changes; decoders
#: reject every version they were not built for (explicit skew failure
#: beats silent misparsing).
#: v2 (PR 15): mint epochs on the wire — verify/show_prove/show_verify
#: requests and the mint response carry a u32 epoch (0 = unpinned, the
#: pre-lifecycle boot verkey), and beacons advertise the replica's live
#: epoch window.
#: v3 (PR 17): the durable state plane — beacons additionally piggyback
#: the replica's per-keyspace state high-water marks (the anti-entropy
#: trigger), and MSG_STATE_PULL/MSG_STATE_CHUNK page replicated state
#: records between replicas.
#: v4 (PR 19): scenario nullifier scoping — the show_verify request
#: carries an application domain string ("" = unscoped) and an optional
#: 32-byte deterministic spend tag (petition campaigns, e-cash; see
#: state/nullifier.py).
WIRE_VERSION = 4

MAGIC = 0xC0C7

#: payload size cap — a corrupted/hostile length field fails loudly here
MAX_FRAME_BYTES = 1 << 24

HEADER = struct.Struct(">HBBII")
HEADER_BYTES = HEADER.size  # 12

_F64 = struct.Struct(">d")

# -- message types -----------------------------------------------------------

REQUEST_TYPES = {
    "verify": 0x01,
    "prepare": 0x02,
    "mint": 0x03,
    "show_prove": 0x04,
    "show_verify": 0x05,
}
RESPONSE_TYPES = {name: t | 0x40 for name, t in REQUEST_TYPES.items()}
PROGRAM_OF_REQUEST = {t: name for name, t in REQUEST_TYPES.items()}
PROGRAM_OF_RESPONSE = {t: name for name, t in RESPONSE_TYPES.items()}

MSG_BEACON_POLL = 0x20
MSG_BEACON = 0x60
#: anti-entropy state pull (PR 17): request one page of replicated
#: state records from a peer's per-origin log
MSG_STATE_PULL = 0x21
MSG_STATE_CHUNK = 0x61
MSG_ERROR = 0x7F

#: request-header lane codes (serve.queue.LANES order)
_LANE_CODE = {lane: i for i, lane in enumerate(LANES)}
_LANE_OF_CODE = {i: lane for lane, i in _LANE_CODE.items()}


# -- framing -----------------------------------------------------------------


def encode_frame(msg_type, payload, seq=0, version=WIRE_VERSION):
    """One wire frame: 12-byte header + payload."""
    if len(payload) > MAX_FRAME_BYTES:
        raise ValueError(
            "frame payload %d bytes exceeds MAX_FRAME_BYTES" % len(payload)
        )
    return HEADER.pack(MAGIC, version, msg_type, seq, len(payload)) + payload


def parse_header(header):
    """(msg_type, seq, payload_length) from the 12 header bytes. Raises
    DeserializationError on truncation, bad magic, an unknown version, or
    an over-cap length — the stream-reader's validation seam."""
    if len(header) < HEADER_BYTES:
        raise DeserializationError(
            "truncated frame header: %d of %d bytes"
            % (len(header), HEADER_BYTES)
        )
    magic, version, msg_type, seq, length = HEADER.unpack(
        header[:HEADER_BYTES]
    )
    if magic != MAGIC:
        raise DeserializationError(
            "bad frame magic 0x%04X (want 0x%04X)" % (magic, MAGIC)
        )
    if version != WIRE_VERSION:
        raise DeserializationError(
            "unsupported wire version %d (this build speaks %d)"
            % (version, WIRE_VERSION)
        )
    if length > MAX_FRAME_BYTES:
        raise DeserializationError(
            "frame payload length %d exceeds cap %d"
            % (length, MAX_FRAME_BYTES)
        )
    return msg_type, seq, length


def decode_frame(buf):
    """(msg_type, seq, payload) from ONE complete frame; rejects trailing
    bytes (stream readers use parse_header + exact reads instead)."""
    msg_type, seq, length = parse_header(buf)
    if len(buf) != HEADER_BYTES + length:
        raise DeserializationError(
            "frame length mismatch: header says %d payload bytes, got %d"
            % (length, len(buf) - HEADER_BYTES)
        )
    return msg_type, seq, bytes(buf[HEADER_BYTES:])


# -- primitive fields --------------------------------------------------------


def _pack_str(s):
    b = s.encode("utf-8")
    if len(b) > 0xFFFF:
        raise ValueError("string field too long (%d bytes)" % len(b))
    return len(b).to_bytes(2, "big") + b


def _read_str(b, o):
    if len(b) < o + 2:
        raise DeserializationError("truncated string field")
    n = int.from_bytes(b[o : o + 2], "big")
    o += 2
    if len(b) < o + n:
        raise DeserializationError("truncated string field")
    try:
        return b[o : o + n].decode("utf-8"), o + n
    except UnicodeDecodeError:
        raise DeserializationError("non-UTF8 string field")


def _pack_blob(x):
    if len(x) > MAX_FRAME_BYTES:
        raise ValueError("blob field too long (%d bytes)" % len(x))
    return len(x).to_bytes(4, "big") + x


def _read_blob(b, o):
    if len(b) < o + 4:
        raise DeserializationError("truncated blob field")
    n = int.from_bytes(b[o : o + 4], "big")
    o += 4
    if n > MAX_FRAME_BYTES or len(b) < o + n:
        raise DeserializationError("truncated blob field")
    return bytes(b[o : o + n]), o + n


def _read_exact(b, o, n, what):
    if len(b) < o + n:
        raise DeserializationError("truncated %s" % what)
    return bytes(b[o : o + n]), o + n


def _pack_frs(msgs):
    if len(msgs) > 0xFFFF:
        raise ValueError("message vector too long (%d)" % len(msgs))
    return len(msgs).to_bytes(2, "big") + b"".join(
        ser.fr_to_bytes(m) for m in msgs
    )


def _read_frs(b, o):
    if len(b) < o + 2:
        raise DeserializationError("truncated Fr vector")
    n = int.from_bytes(b[o : o + 2], "big")
    o += 2
    out = []
    for _ in range(n):
        raw, o = _read_exact(b, o, 32, "Fr vector")
        out.append(ser.fr_from_bytes(raw))
    return out, o


def _pack_revealed(revealed):
    """Canonical {index: Fr} map: u16 count + sorted (u32 idx, 32B Fr)."""
    if len(revealed) > 0xFFFF:
        raise ValueError("revealed map too long (%d)" % len(revealed))
    out = [len(revealed).to_bytes(2, "big")]
    for idx in sorted(revealed):
        out.append(int(idx).to_bytes(4, "big"))
        out.append(ser.fr_to_bytes(revealed[idx]))
    return b"".join(out)


def _read_revealed(b, o):
    if len(b) < o + 2:
        raise DeserializationError("truncated revealed map")
    n = int.from_bytes(b[o : o + 2], "big")
    o += 2
    out = {}
    for _ in range(n):
        raw_i, o = _read_exact(b, o, 4, "revealed map")
        raw_m, o = _read_exact(b, o, 32, "revealed map")
        idx = int.from_bytes(raw_i, "big")
        if idx in out:
            raise DeserializationError("duplicate revealed index %d" % idx)
        out[idx] = ser.fr_from_bytes(raw_m)
    return out, o


def _pack_epoch(epoch):
    """u32 mint epoch; 0 encodes "unpinned" (None — the boot verkey of a
    deployment that never ran a key lifecycle). Real epochs are >= 1
    (EpochRegistry ids are monotonic from 1)."""
    e = 0 if epoch is None else int(epoch)
    if not 0 <= e <= 0xFFFFFFFF:
        raise ValueError("epoch %r out of u32 range" % (epoch,))
    return e.to_bytes(4, "big")


def _read_epoch(b, o):
    raw, o = _read_exact(b, o, 4, "epoch")
    e = int.from_bytes(raw, "big")
    return (e if e else None), o


def _done(b, o, what):
    if o != len(b):
        raise DeserializationError(
            "trailing bytes in %s (%d extra)" % (what, len(b) - o)
        )


# -- error envelope (program-agnostic, no params needed) ---------------------


def encode_error(exc, program=None):
    """Error-envelope payload for any exception: its stable `code`
    (errors.py; "general" for classes without one), the refusing program,
    the retry-after hint, a retryable flag, and the human message."""
    code = getattr(exc, "code", "general")
    prog = getattr(exc, "program", None) or program
    retry_after = getattr(exc, "retry_after_s", None)
    retryable = retry_after is not None or code == "transient"
    return b"".join(
        (
            _pack_str(code),
            _pack_str(prog or ""),
            _F64.pack(float(retry_after or 0.0)),
            bytes([1 if retryable else 0]),
            _pack_str(str(exc)),
        )
    )


def decode_error(payload):
    """Rebuild the typed exception an error envelope describes (via
    errors.error_from_wire; unknown codes degrade to GeneralError)."""
    code, o = _read_str(payload, 0)
    prog, o = _read_str(payload, o)
    raw, o = _read_exact(payload, o, 8, "error envelope")
    (retry_after,) = _F64.unpack(raw)
    flag, o = _read_exact(payload, o, 1, "error envelope")
    message, o = _read_str(payload, o)
    _done(payload, o, "error envelope")
    err = error_from_wire(
        code, message, program=prog or None, retry_after_s=retry_after
    )
    err.wire_retryable = bool(flag[0])
    return err


# -- health beacon -----------------------------------------------------------


class Beacon:
    """One replica's periodic health self-report: the engine health-ladder
    summary (admissible executors / capacity fraction), queue depth,
    brownout flag the router's gossip directory routes by, and — since
    wire v2 — the live key-epoch window (sorted (epoch_id, state) pairs
    from keylife.EpochRegistry.live_epochs()) so routers know which mint
    epochs each replica can still serve, and — since wire v3 — the
    durable state plane's per-keyspace high-water marks
    ((keyspace, origin, seq) triples from StateStore.marks()) that
    trigger anti-entropy pulls for any replica lagging them."""

    __slots__ = (
        "replica_id",
        "state",
        "capacity_fraction",
        "queue_depth",
        "brownout",
        "healthy_executors",
        "executors",
        "t",
        "epochs",
        "state_marks",
    )

    def __init__(
        self,
        replica_id,
        state,
        capacity_fraction,
        queue_depth,
        brownout,
        healthy_executors,
        executors,
        t,
        epochs=(),
        state_marks=(),
    ):
        self.replica_id = replica_id
        self.state = state
        self.capacity_fraction = capacity_fraction
        self.queue_depth = queue_depth
        self.brownout = brownout
        self.healthy_executors = healthy_executors
        self.executors = executors
        self.t = t
        self.epochs = tuple(epochs)
        self.state_marks = tuple(state_marks)

    def admissible(self):
        """May the router route NEW sessions here? Mirrors the engine's
        executor-admission rule one level up: a replica reporting zero
        admissible executors is demoted exactly like a quarantined
        executor. Lifecycle states (PR 14) are equally inadmissible: a
        "warming" replica is still replaying its shape manifest and a
        "draining" one is mid-graceful-shutdown — both refuse or stall
        new work."""
        return self.state not in ("quarantined", "down", "warming", "draining")

    def as_dict(self):
        return {k: getattr(self, k) for k in self.__slots__}


def _pack_epoch_window(epochs):
    """u16 count + per-entry (u32 epoch, u8 state code); canonical order
    is ascending epoch id (live_epochs() already sorts)."""
    entries = list(epochs)
    if len(entries) > 0xFFFF:
        raise ValueError("epoch window too long (%d)" % len(entries))
    out = [len(entries).to_bytes(2, "big")]
    for epoch, state in entries:
        code = EPOCH_STATE_CODES.get(state)
        if code is None:
            raise ValueError("unknown epoch state %r" % (state,))
        out.append(int(epoch).to_bytes(4, "big"))
        out.append(bytes([code]))
    return b"".join(out)


def _read_epoch_window(b, o):
    if len(b) < o + 2:
        raise DeserializationError("truncated epoch window")
    n = int.from_bytes(b[o : o + 2], "big")
    o += 2
    out = []
    for _ in range(n):
        raw_e, o = _read_exact(b, o, 4, "epoch window")
        raw_s, o = _read_exact(b, o, 1, "epoch window")
        state = EPOCH_STATE_OF_CODE.get(raw_s[0])
        if state is None:
            raise DeserializationError(
                "unknown epoch state code %d" % raw_s[0]
            )
        out.append((int.from_bytes(raw_e, "big"), state))
    return tuple(out), o


def _pack_state_marks(marks):
    """u16 count + per-entry (str keyspace, str origin, u32 seq);
    canonical order is the store's (sorted by keyspace then origin)."""
    entries = list(marks)
    if len(entries) > 0xFFFF:
        raise ValueError("state-mark set too long (%d)" % len(entries))
    out = [len(entries).to_bytes(2, "big")]
    for ks, origin, seq in entries:
        out.append(_pack_str(ks))
        out.append(_pack_str(origin))
        out.append(int(seq).to_bytes(4, "big"))
    return b"".join(out)


def _read_state_marks(b, o):
    if len(b) < o + 2:
        raise DeserializationError("truncated state marks")
    n = int.from_bytes(b[o : o + 2], "big")
    o += 2
    out = []
    for _ in range(n):
        ks, o = _read_str(b, o)
        origin, o = _read_str(b, o)
        raw, o = _read_exact(b, o, 4, "state marks")
        out.append((ks, origin, int.from_bytes(raw, "big")))
    return tuple(out), o


def encode_beacon(beacon):
    return b"".join(
        (
            _pack_str(beacon.replica_id),
            _pack_str(beacon.state),
            _F64.pack(float(beacon.capacity_fraction)),
            int(beacon.queue_depth).to_bytes(4, "big"),
            bytes([1 if beacon.brownout else 0]),
            int(beacon.healthy_executors).to_bytes(4, "big"),
            int(beacon.executors).to_bytes(4, "big"),
            _F64.pack(float(beacon.t)),
            _pack_epoch_window(getattr(beacon, "epochs", ()) or ()),
            _pack_state_marks(
                getattr(beacon, "state_marks", ()) or ()
            ),
        )
    )


def decode_beacon(payload):
    replica_id, o = _read_str(payload, 0)
    state, o = _read_str(payload, o)
    raw, o = _read_exact(payload, o, 8, "beacon")
    (capacity,) = _F64.unpack(raw)
    raw, o = _read_exact(payload, o, 4, "beacon")
    depth = int.from_bytes(raw, "big")
    raw, o = _read_exact(payload, o, 1, "beacon")
    brownout = bool(raw[0])
    raw, o = _read_exact(payload, o, 4, "beacon")
    healthy = int.from_bytes(raw, "big")
    raw, o = _read_exact(payload, o, 4, "beacon")
    executors = int.from_bytes(raw, "big")
    raw, o = _read_exact(payload, o, 8, "beacon")
    (t,) = _F64.unpack(raw)
    epochs, o = _read_epoch_window(payload, o)
    state_marks, o = _read_state_marks(payload, o)
    _done(payload, o, "beacon")
    return Beacon(
        replica_id, state, capacity, depth, brownout, healthy, executors, t,
        epochs=epochs, state_marks=state_marks,
    )


# -- anti-entropy state transfer (PR 17) -------------------------------------
#
# MSG_STATE_PULL asks a peer for one page of its per-origin state log
# (state/store.py records_after); MSG_STATE_CHUNK answers with the raw
# record dicts. Values travel as JSON blobs: the state plane treats
# them as opaque (LWW metadata — keyspace/origin/seq/epoch — is what
# the wire frames natively), so new keyspaces need no wire bump.


def encode_state_pull(keyspace, origin, after_seq, limit):
    return b"".join(
        (
            _pack_str(keyspace),
            _pack_str(origin),
            int(after_seq).to_bytes(4, "big"),
            int(limit).to_bytes(2, "big"),
        )
    )


def decode_state_pull(payload):
    keyspace, o = _read_str(payload, 0)
    origin, o = _read_str(payload, o)
    raw, o = _read_exact(payload, o, 4, "state pull")
    after_seq = int.from_bytes(raw, "big")
    raw, o = _read_exact(payload, o, 2, "state pull")
    limit = int.from_bytes(raw, "big")
    _done(payload, o, "state pull")
    return keyspace, origin, after_seq, limit


def encode_state_chunk(records):
    """u16 count + per-record (str ks, str key, blob json-value,
    str origin, u32 seq, u32 epoch (0 = None), u8 tombstone)."""
    records = list(records)
    if len(records) > 0xFFFF:
        raise ValueError("state chunk too long (%d)" % len(records))
    out = [len(records).to_bytes(2, "big")]
    for rec in records:
        out.append(_pack_str(rec["ks"]))
        out.append(_pack_str(rec["k"]))
        out.append(
            _pack_blob(json.dumps(rec["v"], sort_keys=True).encode())
        )
        out.append(_pack_str(rec["o"]))
        out.append(int(rec["s"]).to_bytes(4, "big"))
        out.append(_pack_epoch(rec["e"]))
        out.append(bytes([1 if rec["t"] else 0]))
    return b"".join(out)


def decode_state_chunk(payload):
    if len(payload) < 2:
        raise DeserializationError("truncated state chunk")
    n = int.from_bytes(payload[:2], "big")
    o = 2
    out = []
    for _ in range(n):
        ks, o = _read_str(payload, o)
        key, o = _read_str(payload, o)
        blob, o = _read_blob(payload, o)
        origin, o = _read_str(payload, o)
        raw, o = _read_exact(payload, o, 4, "state chunk")
        seq = int.from_bytes(raw, "big")
        epoch, o = _read_epoch(payload, o)
        raw, o = _read_exact(payload, o, 1, "state chunk")
        try:
            value = json.loads(blob.decode("utf-8"))
        except (UnicodeDecodeError, ValueError):
            raise DeserializationError("malformed state-record value")
        out.append(
            {
                "ks": ks,
                "k": key,
                "v": value,
                "o": origin,
                "s": seq,
                "e": epoch,
                "t": int(raw[0] != 0),
            }
        )
    _done(payload, o, "state chunk")
    return out


# -- program request/response codec ------------------------------------------


class WireCodec:
    """Encode/decode the five program request+response payloads against
    ONE deployment's Params (the group context fixes every point size, so
    each encoding is canonical and byte-exact)."""

    def __init__(self, params):
        self.params = params
        self.ctx = params.ctx

    # request payload: u8 lane | str api_key | str session | program body
    def encode_request(
        self, program, args, lane="interactive", api_key="", session=""
    ):
        if lane not in _LANE_CODE:
            raise ValueError("unknown lane %r" % (lane,))
        body = getattr(self, "_enc_req_%s" % program)(*args)
        return b"".join(
            (
                bytes([_LANE_CODE[lane]]),
                _pack_str(api_key),
                _pack_str(session),
                body,
            )
        )

    def decode_request(self, msg_type, payload):
        """(program, lane, api_key, session, args) — `args` is the exact
        positional tuple the engine's submit_<program> takes."""
        program = PROGRAM_OF_REQUEST.get(msg_type)
        if program is None:
            raise DeserializationError(
                "unknown request type 0x%02X" % msg_type
            )
        raw, o = _read_exact(payload, 0, 1, "request header")
        lane = _LANE_OF_CODE.get(raw[0])
        if lane is None:
            raise DeserializationError("unknown lane code %d" % raw[0])
        api_key, o = _read_str(payload, o)
        session, o = _read_str(payload, o)
        args, o = getattr(self, "_dec_req_%s" % program)(payload, o)
        _done(payload, o, "%s request" % program)
        return program, lane, api_key, session, args

    def encode_response(self, program, result):
        return getattr(self, "_enc_resp_%s" % program)(result)

    def decode_response(self, program, payload):
        result, o = getattr(self, "_dec_resp_%s" % program)(payload, 0)
        _done(payload, o, "%s response" % program)
        return result

    # -- verify: (sig, messages) -> bool ------------------------------------

    def _enc_req_verify(self, sig, messages):
        # trailing u32: the credential's mint epoch (0 = unpinned) — the
        # replica resolves its verkey from the keychain by this id
        return (
            sig.to_bytes(self.ctx)
            + _pack_frs(messages)
            + _pack_epoch(getattr(sig, "epoch", None))
        )

    def _dec_req_verify(self, b, o):
        from ..signature import Signature

        raw, o = _read_exact(b, o, 2 * self.ctx.sig_nbytes, "Signature")
        sig = Signature.from_bytes(raw, self.ctx)
        msgs, o = _read_frs(b, o)
        epoch, o = _read_epoch(b, o)
        if epoch is not None:
            sig.epoch = epoch
        return (sig, msgs), o

    def _enc_resp_verify(self, verdict):
        return bytes([1 if verdict else 0])

    def _dec_resp_verify(self, b, o):
        raw, o = _read_exact(b, o, 1, "verify response")
        return bool(raw[0]), o

    # -- prepare: (messages, elgamal_pk) -> (SignatureRequest, randomness) --

    def _enc_req_prepare(self, messages, elgamal_pk):
        return _pack_frs(messages) + self.ctx.sig_to_bytes(elgamal_pk)

    def _dec_req_prepare(self, b, o):
        msgs, o = _read_frs(b, o)
        raw, o = _read_exact(b, o, self.ctx.sig_nbytes, "ElGamal pk")
        return (msgs, self.ctx.sig_from_bytes(raw)), o

    def _enc_resp_prepare(self, result):
        sig_req, randomness = result
        return _pack_blob(sig_req.to_bytes(self.ctx)) + _pack_frs(randomness)

    def _dec_resp_prepare(self, b, o):
        from ..signature import SignatureRequest

        raw, o = _read_blob(b, o)
        sig_req = SignatureRequest.from_bytes(raw, self.ctx)
        randomness, o = _read_frs(b, o)
        return (sig_req, randomness), o

    # -- mint: (sig_request, messages, elgamal_sk) -> Signature -------------

    def _enc_req_mint(self, sig_request, messages, elgamal_sk):
        return (
            _pack_blob(sig_request.to_bytes(self.ctx))
            + _pack_frs(messages)
            + ser.fr_to_bytes(elgamal_sk)
        )

    def _dec_req_mint(self, b, o):
        from ..signature import SignatureRequest

        raw, o = _read_blob(b, o)
        sig_req = SignatureRequest.from_bytes(raw, self.ctx)
        msgs, o = _read_frs(b, o)
        raw, o = _read_exact(b, o, 32, "ElGamal sk")
        return (sig_req, msgs, ser.fr_from_bytes(raw)), o

    def _enc_resp_mint(self, sig):
        # trailing u32: the epoch this credential was minted under (the
        # keychain-pinned fan-out stamped it in issue._release); clients
        # carry it into every later verify/show of the credential
        return sig.to_bytes(self.ctx) + _pack_epoch(
            getattr(sig, "epoch", None)
        )

    def _dec_resp_mint(self, b, o):
        from ..signature import Signature

        raw, o = _read_exact(b, o, 2 * self.ctx.sig_nbytes, "Signature")
        sig = Signature.from_bytes(raw, self.ctx)
        epoch, o = _read_epoch(b, o)
        if epoch is not None:
            sig.epoch = epoch
        return sig, o

    # -- show_prove: (sig, messages) -> (proof, challenge, revealed) --------

    _enc_req_show_prove = _enc_req_verify
    _dec_req_show_prove = _dec_req_verify

    def _enc_resp_show_prove(self, result):
        proof, challenge, revealed = result
        return (
            _pack_blob(proof.to_bytes(self.ctx))
            + ser.fr_to_bytes(challenge)
            + _pack_revealed(revealed)
        )

    def _dec_resp_show_prove(self, b, o):
        from ..ps import PoKOfSignatureProof

        raw, o = _read_blob(b, o)
        proof = PoKOfSignatureProof.from_bytes(raw, self.ctx)
        raw, o = _read_exact(b, o, 32, "challenge")
        challenge = ser.fr_from_bytes(raw)
        revealed, o = _read_revealed(b, o)
        return (proof, challenge, revealed), o

    # -- show_verify: (proof, revealed, challenge, epoch, domain, tag)
    #    -> bool ------------------------------------------------------------

    def _enc_req_show_verify(
        self, proof, revealed_msgs, challenge=None, epoch=None,
        domain=None, tag=None,
    ):
        has = challenge is not None
        has_tag = tag is not None
        return b"".join(
            (
                _pack_blob(proof.to_bytes(self.ctx)),
                _pack_revealed(revealed_msgs),
                bytes([1 if has else 0]),
                ser.fr_to_bytes(challenge) if has else b"",
                # the shown credential's mint epoch (0 = unpinned): a
                # proof is only sound against the verkey it was built for
                _pack_epoch(epoch),
                # v4: scenario nullifier scope — domain ("" = unscoped)
                # and optional 32-byte deterministic spend tag
                _pack_str(domain or ""),
                bytes([1 if has_tag else 0]),
                bytes(tag) if has_tag else b"",
            )
        )

    def _dec_req_show_verify(self, b, o):
        from ..ps import PoKOfSignatureProof

        raw, o = _read_blob(b, o)
        proof = PoKOfSignatureProof.from_bytes(raw, self.ctx)
        revealed, o = _read_revealed(b, o)
        raw, o = _read_exact(b, o, 1, "show_verify request")
        challenge = None
        if raw[0]:
            raw, o = _read_exact(b, o, 32, "challenge")
            challenge = ser.fr_from_bytes(raw)
        epoch, o = _read_epoch(b, o)
        domain, o = _read_str(b, o)
        raw, o = _read_exact(b, o, 1, "show_verify request")
        tag = None
        if raw[0]:
            raw, o = _read_exact(b, o, 32, "spend tag")
            tag = bytes(raw)
        return (proof, revealed, challenge, epoch, domain or None, tag), o

    _enc_resp_show_verify = _enc_resp_verify
    _dec_resp_show_verify = _dec_resp_verify
