"""Fleet gateway: wire-format RPC ingress, per-tenant admission, and a
health-gossiping replica router (PR 13).

The unified execution engine (coconut_tpu/engine/) serves one process;
this package turns N such processes into one fleet behind a front door:

  wire.py    CTS-RPC/1 — versioned length-prefixed frames, canonical
             payload encodings for all five program request/response
             pairs, the typed error envelope, and the health beacon
  rpc.py     Replica (an engine behind a serve loop), Socket/Loopback
             transports, and the typed GatewayClient mirroring
             ProtocolEngine's submit_* surface over the wire
  tenant.py  per-tenant API-key auth, token-bucket rate limits, and
             quota counters — enforced BEFORE engine admission
  gossip.py  HealthDirectory (UP/DEGRADED/DOWN per replica, fed by
             periodic beacons) + the GossipLoop poller
  router.py  ReplicaRouter — consistent-hash session affinity,
             least-loaded spill, beacon-driven demotion, and bounded
             failover retry on transport failure

See README.md "Fleet deployment" for the wire format table, tenant
knobs, routing policy, and the gateway_*/tenant_* metric glossary.
"""

from .gossip import (
    DEGRADED,
    DOWN,
    DRAINING,
    UP,
    WARMING,
    GossipLoop,
    HealthDirectory,
)
from .rpc import (
    GatewayClient,
    LoopbackTransport,
    Replica,
    SocketTransport,
)
from .router import ReplicaRouter
from .tenant import Tenant, TenantTable, TokenBucket
from .wire import (
    MAX_FRAME_BYTES,
    MSG_STATE_CHUNK,
    MSG_STATE_PULL,
    WIRE_VERSION,
    Beacon,
    WireCodec,
    decode_frame,
    encode_frame,
)

__all__ = [
    "WIRE_VERSION",
    "MAX_FRAME_BYTES",
    "MSG_STATE_PULL",
    "MSG_STATE_CHUNK",
    "WireCodec",
    "Beacon",
    "encode_frame",
    "decode_frame",
    "Replica",
    "GatewayClient",
    "SocketTransport",
    "LoopbackTransport",
    "Tenant",
    "TenantTable",
    "TokenBucket",
    "HealthDirectory",
    "GossipLoop",
    "WARMING",
    "UP",
    "DEGRADED",
    "DRAINING",
    "DOWN",
    "ReplicaRouter",
]
