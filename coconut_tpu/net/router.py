"""Front-door replica router: consistent-hash session affinity with
least-loaded spill and beacon-driven demotion (PR 13).

A credential session is a stateful FLOW on the client side
(prepare -> mint -> show_prove -> show_verify: the randomness from
prepare is the PoK witness at mint) but stateless on the replica side —
so the router's job is purely placement quality, not correctness:

  AFFINITY   sessions hash onto a consistent ring (sha256, `vnodes`
             virtual nodes per replica) and stick to their ring-primary
             replica while it is UP — warm batches, stable per-replica
             load, minimal reshuffling when the fleet changes size.
  SPILL      a demoted primary (DEGRADED/DOWN in the gossip directory)
             sends the session to the least-loaded routable replica
             (last-beacon queue depth), falling back to DEGRADED
             replicas only when nothing is UP — mirrors PR 9's graded
             executor demotion one level up.
  FAILOVER   a TransientBackendError from the data path (torn
             connection, dead loopback) marks the replica DOWN in the
             directory immediately (`note_failure`) and resubmits the
             request on the next candidate under the retry.py ladder —
             bounded attempts, deterministic jittered backoff. A
             ServiceClosedError (PR 14: retryable over the wire) is the
             GRACEFUL twin: the replica is draining, so the directory
             learns DRAINING (`note_draining` — beacons keep flowing)
             and the request hands off to a ring successor the same
             way. Other typed engine refusals (brownout/overload/
             tenant) are NOT failover triggers: they propagate to the
             caller, whose backoff the retry_after_s hint already
             guides. DoubleSpendError (PR 17) is likewise TERMINAL:
             the nullifier is a deterministic digest of the replayed
             transcript, so every replica with the replicated fact
             returns the same rejection — failing over a double spend
             would only probe for a replica the anti-entropy pull has
             not reached yet, which is exactly the race the drill in
             probes/probe_nullifier.py proves closed.

Counters: "gateway_routed" / "gateway_affinity_hits" / "gateway_spills"
/ "gateway_failovers" / "gateway_drain_handoffs" / per-placement-state
"gateway_placed_<state>" (the rolling-restart drill's proof that no new
session lands on a WARMING or DRAINING replica), plus the directory's
own gateway_* set.
"""

import bisect
import hashlib
import time

from .. import metrics
from ..errors import ServiceClosedError, TransientBackendError
from ..retry import RetryPolicy, call_with_retry
from . import gossip

#: virtual nodes per replica on the hash ring — enough that removing one
#: replica spreads its sessions near-uniformly over the survivors
DEFAULT_VNODES = 64


def _hash64(key):
    return int.from_bytes(
        hashlib.sha256(key.encode("utf-8")).digest()[:8], "big"
    )


class _RoutedFuture:
    """A submitted request plus its failover plan: result() settles the
    current attempt and, on a transport failure, demotes the replica and
    resubmits on the next candidate under the router's retry policy."""

    def __init__(self, router, program, args, lane, session, rid, fut):
        self._router = router
        self._program = program
        self._args = args
        self._lane = lane
        self._session = session
        self._rid = rid
        self._fut = fut
        self._tried = {rid}

    @property
    def replica_id(self):
        """Replica the CURRENT attempt lives on (tests assert affinity)."""
        return self._rid

    def done(self):
        return self._fut.done()

    def add_done_callback(self, fn):
        """Callback-mode settle (PR 19): fires `fn` with the CURRENT
        attempt's future — typed decode/raise semantics, but NO
        synchronous failover or retry sleeps (those would run on the
        transport reader thread). Async consumers like the scenario
        workflow runtime classify the typed error themselves and
        resubmit through the router, which re-routes around the
        unhealthy replica via the gossip directory."""
        self._fut.add_done_callback(lambda _f: fn(self._fut))

    def result(self, timeout=None):
        first = [True]
        last_exc = [None]

        def attempt():
            if not first[0]:
                metrics.count("gateway_failovers")
                if isinstance(last_exc[0], ServiceClosedError):
                    # graceful drain, not a crash: the replica still
                    # answers beacons — mark DRAINING, not DOWN
                    metrics.count("gateway_drain_handoffs")
                    self._router.directory.note_draining(self._rid)
                else:
                    self._router.directory.note_failure(self._rid)
                self._rid, self._fut = self._router._place(
                    self._program,
                    self._args,
                    self._lane,
                    self._session,
                    exclude=self._tried,
                )
                self._tried.add(self._rid)
            first[0] = False
            try:
                return self._fut.result(timeout)
            except Exception as e:
                last_exc[0] = e
                raise

        return call_with_retry(
            attempt, self._router.retry_policy, key=self._session
        )

    def exception(self, timeout=None):
        try:
            self.result(timeout)
            return None
        except TimeoutError:
            raise
        except Exception as e:
            return e


class _SessionClient:
    """A router bound to one session id: exposes the plain engine
    submit_* surface (no session kwarg), so session-flow code written
    against ProtocolEngine — serve/loadgen.py's full-session driver —
    runs over the fleet unchanged."""

    def __init__(self, router, session):
        self._router = router
        self.session = session

    def submit_verify(self, sig, messages, lane="interactive",
                      max_wait_ms=None):
        return self._router.submit_verify(
            sig, messages, lane=lane, session=self.session
        )

    def submit(self, sig, messages, lane="interactive", max_wait_ms=None):
        return self.submit_verify(sig, messages, lane=lane)

    def submit_prepare(self, messages, elgamal_pk, lane="bulk",
                       max_wait_ms=None):
        return self._router.submit_prepare(
            messages, elgamal_pk, lane=lane, session=self.session
        )

    def submit_mint(self, sig_request, messages, elgamal_sk,
                    lane="interactive", max_wait_ms=None):
        return self._router.submit_mint(
            sig_request, messages, elgamal_sk, lane=lane,
            session=self.session,
        )

    def submit_show_prove(self, sig, messages, lane="interactive",
                          max_wait_ms=None):
        return self._router.submit_show_prove(
            sig, messages, lane=lane, session=self.session
        )

    def submit_show_verify(self, proof, revealed_msgs, challenge=None,
                           epoch=None, domain=None, tag=None,
                           lane="interactive", max_wait_ms=None):
        return self._router.submit_show_verify(
            proof, revealed_msgs, challenge=challenge, epoch=epoch,
            domain=domain, tag=tag, lane=lane, session=self.session,
        )


class ReplicaRouter:
    """Spread sessions over `clients` ({replica_id: GatewayClient}) by
    consistent hash, guided by the gossip `directory`'s health view."""

    def __init__(
        self,
        clients,
        directory=None,
        vnodes=DEFAULT_VNODES,
        retry_policy=None,
        clock=time.monotonic,
    ):
        if not clients:
            raise ValueError("router needs at least one replica client")
        self.clients = dict(clients)
        self.directory = (
            gossip.HealthDirectory(self.clients)
            if directory is None
            else directory
        )
        self.clock = clock
        # one data-path attempt per replica plus one: a full ring sweep
        # can land back on the (possibly recovered) affinity target
        # ServiceClosedError rides along (PR 14): a draining replica's
        # refusal is a handoff trigger, exactly like a torn transport
        self.retry_policy = retry_policy or RetryPolicy(
            max_attempts=len(self.clients) + 1,
            base_delay=0.01,
            max_delay=0.5,
            retryable=(TransientBackendError, ServiceClosedError),
        )
        self.vnodes = vnodes
        self._ring = []
        self._order = sorted(self.clients)  # deterministic tie-break
        for rid in self._order:
            for v in range(vnodes):
                self._ring.append((_hash64("%s#%d" % (rid, v)), rid))
        self._ring.sort()
        self._keys = [h for h, _rid in self._ring]

    # -- placement -----------------------------------------------------------

    def candidates(self, session):
        """Every replica id in ring order from the session's hash point —
        [0] is the affinity primary, the rest the failover sequence."""
        start = bisect.bisect_right(self._keys, _hash64("s:%s" % session))
        out, seen = [], set()
        n = len(self._ring)
        for k in range(n):
            rid = self._ring[(start + k) % n][1]
            if rid not in seen:
                seen.add(rid)
                out.append(rid)
                if len(out) == len(self.clients):
                    break
        return out

    def route(self, session, exclude=()):
        """Choose the replica for one request of `session`. Affinity to
        the ring primary while it is UP; least-loaded spill otherwise;
        a fully-DOWN fleet still returns the primary (better to probe a
        possibly-recovering socket than refuse outright — the retry
        ladder bounds the cost)."""
        ring = self.candidates(session)
        live = [r for r in ring if r not in exclude]
        if not live:
            raise TransientBackendError(
                "no replicas left for session %r "
                "(all %d tried)" % (session, len(ring))
            )
        primary = live[0]
        if self.directory.routable(primary):
            metrics.count("gateway_affinity_hits")
            chosen = primary
        else:
            pool = [r for r in live if self.directory.routable(r)]
            if not pool:
                pool = [r for r in live if self.directory.usable(r)]
            if pool:
                d = self.directory
                chosen = min(
                    pool, key=lambda r: (d.queue_depth(r), ring.index(r))
                )
            else:
                chosen = primary  # last resort: everything is DOWN
            metrics.count("gateway_spills")
        metrics.count("gateway_routed")
        # the drill's audit trail: placements bucketed by the chosen
        # replica's directory state — "gateway_placed_warming" and
        # "gateway_placed_draining" staying at ZERO through a rolling
        # restart is the router-never-misplaces proof
        metrics.count("gateway_placed_%s" % self.directory.state(chosen))
        return chosen

    def _place(self, program, args, lane, session, exclude=()):
        rid = self.route(session, exclude=exclude)
        client = self.clients[rid]
        fut = getattr(client, "submit_" + program)(
            *args, lane=lane, session=session
        )
        return rid, fut

    def _submit(self, program, args, lane, session):
        rid, fut = self._place(program, args, lane, session)
        return _RoutedFuture(self, program, args, lane, session, rid, fut)

    # -- engine-shaped surface ------------------------------------------------

    def submit_verify(self, sig, messages, lane="interactive",
                      max_wait_ms=None, session=""):
        return self._submit("verify", (sig, messages), lane, session)

    def submit(self, sig, messages, lane="interactive", max_wait_ms=None,
               session=""):
        return self.submit_verify(
            sig, messages, lane=lane, session=session
        )

    def submit_prepare(self, messages, elgamal_pk, lane="bulk",
                       max_wait_ms=None, session=""):
        return self._submit(
            "prepare", (messages, elgamal_pk), lane, session
        )

    def submit_mint(self, sig_request, messages, elgamal_sk,
                    lane="interactive", max_wait_ms=None, session=""):
        return self._submit(
            "mint", (sig_request, messages, elgamal_sk), lane, session
        )

    def submit_show_prove(self, sig, messages, lane="interactive",
                          max_wait_ms=None, session=""):
        return self._submit("show_prove", (sig, messages), lane, session)

    def submit_show_verify(self, proof, revealed_msgs, challenge=None,
                           epoch=None, domain=None, tag=None,
                           lane="interactive", max_wait_ms=None,
                           session=""):
        return self._submit(
            "show_verify",
            (proof, revealed_msgs, challenge, epoch, domain, tag),
            lane, session,
        )

    def bound(self, session):
        """A client pinned to `session` with the plain engine surface —
        what the full-session loadgen drives one session flow through."""
        return _SessionClient(self, session)

    # -- gossip wiring --------------------------------------------------------

    def gossip_loop(self, interval_s=0.25, poll_timeout_s=2.0, clock=None):
        """A GossipLoop polling every replica's beacon endpoint through
        its own client connection. start() it for real fleets; call
        step() directly in fake-clock tests."""
        pollers = {
            rid: (lambda c=client: c.poll_beacon(timeout=poll_timeout_s))
            for rid, client in self.clients.items()
        }
        return gossip.GossipLoop(
            self.directory,
            pollers,
            interval_s=interval_s,
            clock=self.clock if clock is None else clock,
        )

    def close(self):
        for client in self.clients.values():
            try:
                client.close()
            except Exception:
                pass
