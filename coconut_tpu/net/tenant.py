"""Per-tenant admission: API-key auth, token-bucket rate limits, and
quota counters — enforced at the gateway BEFORE engine admission (PR 13).

The engine's own admission control (bounded queues, brownout shedding)
protects the POOL; this layer protects tenants from EACH OTHER: one
tenant saturating its bucket is throttled with a typed, retriable
TenantRateLimitError (carrying the bucket's refill horizon as
retry_after_s) while every other tenant's traffic is untouched — the
over-quota-tenant-only property the gateway probe asserts.

Three gates, in order, all O(1) under one lock:

  AUTH    unknown API key -> TenantAuthError (non-retryable; counted
          under "gateway_auth_failures")
  QUOTA   absolute per-tenant request budget -> TenantQuotaError
          (non-retryable within the epoch; "_quota_rejected")
  BUCKET  token bucket (rate_per_s, burst) -> TenantRateLimitError
          with retry_after_s = time until one token refills
          ("_throttled")

Metrics per tenant: "gateway_tenant_<id>_admitted" / "_throttled" /
"_quota_rejected", plus the gauge "gateway_tenant_<id>_tokens". Time
comes from an injectable clock so the fake-clock tests drive refill
deterministically with zero real sleeps.
"""

import threading
import time

from .. import metrics
from ..errors import TenantAuthError, TenantQuotaError, TenantRateLimitError


class TokenBucket:
    """Classic token bucket: capacity `burst`, refilled continuously at
    `rate_per_s`. `take()` either consumes one token or returns the
    seconds until one is available (never consuming). rate_per_s=None
    disables rate limiting (the bucket always grants)."""

    def __init__(self, rate_per_s, burst, clock=time.monotonic):
        if rate_per_s is not None and rate_per_s <= 0:
            raise ValueError(
                "rate_per_s must be > 0 or None (got %r)" % (rate_per_s,)
            )
        if burst < 1:
            raise ValueError("burst must be >= 1 (got %r)" % (burst,))
        self.rate_per_s = rate_per_s
        self.burst = float(burst)
        self.clock = clock
        self.tokens = float(burst)
        self._t_last = clock()

    def _refill(self, now):
        if self.rate_per_s is None:
            return
        dt = now - self._t_last
        if dt > 0:
            self.tokens = min(self.burst, self.tokens + dt * self.rate_per_s)
        self._t_last = now

    def take(self, now=None):
        """0.0 and one token consumed when available; otherwise the
        refill horizon in seconds (> 0) with nothing consumed."""
        if self.rate_per_s is None:
            return 0.0
        now = self.clock() if now is None else now
        self._refill(now)
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return 0.0
        return (1.0 - self.tokens) / self.rate_per_s


class Tenant:
    """One provisioned tenant: identity, API key, and its admission
    budget. quota=None means unmetered; rate_per_s=None means unthrottled
    (burst is then only the bucket's initial size, irrelevant)."""

    def __init__(
        self,
        tenant_id,
        api_key,
        rate_per_s=None,
        burst=16,
        quota=None,
        clock=time.monotonic,
    ):
        self.tenant_id = tenant_id
        self.api_key = api_key
        self.quota = quota
        self.used = 0
        self.bucket = TokenBucket(rate_per_s, burst, clock=clock)


class TenantTable:
    """The gateway's tenant registry + admission gate. Thread-safe: the
    replica server admits from per-connection reader threads."""

    def __init__(self, tenants=(), clock=time.monotonic, store=None):
        self.clock = clock
        self._lock = threading.Lock()
        self._by_key = {}
        #: state.StateStore (PR 17): absolute-quota `used` counters
        #: persist into the "tenant_quota" keyspace on a LAZY
        #: durability contract (fsync=False — losing the last few
        #: increments on a crash under-counts briefly, which is the
        #: safe direction for admission), so a restarted replica does
        #: not reset every tenant's quota to zero. Rate buckets are
        #: deliberately NOT persisted: they refill in seconds.
        self._store = store
        for t in tenants:
            self.add(t)

    def add(self, tenant):
        with self._lock:
            if tenant.api_key in self._by_key:
                raise ValueError(
                    "duplicate API key for tenant %r" % (tenant.tenant_id,)
                )
            if self._store is not None:
                tenant.used = max(
                    tenant.used,
                    int(
                        self._store.get(
                            "tenant_quota", tenant.tenant_id, 0
                        )
                    ),
                )
            self._by_key[tenant.api_key] = tenant
        return tenant

    def provision(self, tenant_id, api_key, **kw):
        kw.setdefault("clock", self.clock)
        return self.add(Tenant(tenant_id, api_key, **kw))

    def admit(self, api_key, program=None, now=None):
        """Admit one request for `api_key` or raise the typed refusal
        (TenantAuthError / TenantQuotaError / TenantRateLimitError).
        Returns the Tenant on admission."""
        with self._lock:
            tenant = self._by_key.get(api_key)
            if tenant is None:
                metrics.count("gateway_auth_failures")
                raise TenantAuthError(
                    "unknown API key: no provisioned tenant"
                )
            tid = tenant.tenant_id
            if tenant.quota is not None and tenant.used >= tenant.quota:
                metrics.count("gateway_tenant_%s_quota_rejected" % tid)
                raise TenantQuotaError(tid, tenant.used, tenant.quota)
            retry_after = tenant.bucket.take(
                self.clock() if now is None else now
            )
            metrics.set_gauge(
                "gateway_tenant_%s_tokens" % tid,
                round(tenant.bucket.tokens, 3),
            )
            if retry_after > 0.0:
                metrics.count("gateway_tenant_%s_throttled" % tid)
                raise TenantRateLimitError(
                    tid, retry_after, program=program
                )
            tenant.used += 1
            if self._store is not None and tenant.quota is not None:
                try:
                    self._store.put(
                        "tenant_quota", tid, tenant.used, fsync=False
                    )
                except Exception:
                    # lazy contract: a failing store write must not
                    # turn an admitted request into a refusal
                    metrics.count("gateway_tenant_store_errors")
            metrics.count("gateway_tenant_%s_admitted" % tid)
            return tenant
