"""RPC ingress: the Replica server wrapper, client transports, and the
typed GatewayClient (PR 13).

One Replica wraps one engine (a ProtocolEngine, or anything exposing
`submit_<program>` futures — a bare CredentialService's `submit` serves
the "verify" program) behind the CTS-RPC/1 wire format (net/wire.py):

  request frame in -> decode -> tenant admission (net/tenant.py, BEFORE
  the engine sees the request) -> engine submit -> response frame out
  the moment the engine future settles (ServeFuture.add_done_callback —
  no parked thread per in-flight request). EVERY failure on that path
  becomes a typed MSG_ERROR envelope carrying the request's own seq, so
  a client future always settles: wire garbage, auth/quota/rate-limit
  refusals, brownout/overload shedding, and engine-side exceptions all
  travel the same way.

Two transports share one client:

  SocketTransport    real length-prefixed frames over a TCP connection;
                     a reader thread correlates responses to in-flight
                     futures by seq and fails ALL pending futures with
                     TransientBackendError when the peer dies (the
                     router's failover trigger).
  LoopbackTransport  in-memory, synchronous, zero sockets — the
                     deterministic fake-clock path chaos tests and CI
                     run on.

GatewayClient mirrors ProtocolEngine's submit_* surface 1:1 and
re-raises decoded error envelopes as the ORIGINAL typed exceptions
(errors.error_from_wire), so retry/backoff code written against the
engine — including serve/loadgen.py — runs unchanged over RPC.

Counters: "gateway_requests" / "gateway_responses" / "gateway_errors"
(engine-side failures) / "gateway_refusals" (admission refusals) /
"gateway_wire_errors" (undecodable frames).
"""

import socket
import threading
import time

from .. import metrics
from ..errors import (
    DeserializationError,
    GeneralError,
    ServiceClosedError,
    TransientBackendError,
)
from ..serve.queue import ServeFuture
from . import wire
from .wire import (
    HEADER_BYTES,
    MSG_BEACON,
    MSG_BEACON_POLL,
    MSG_ERROR,
    PROGRAM_OF_REQUEST,
    REQUEST_TYPES,
    RESPONSE_TYPES,
    decode_frame,
    encode_frame,
    parse_header,
)

#: default cap a synchronous handle_frame waits for the engine future
DEFAULT_RESULT_TIMEOUT_S = 60.0


def _recv_exact(conn, n):
    """Read exactly n bytes or raise ConnectionError on EOF."""
    buf = b""
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf += chunk
    return buf


class Replica:
    """One engine behind the wire protocol: a serve loop (real sockets)
    plus a synchronous `handle_frame` seam (loopback transports, golden
    tests). Stateless per request — all session state lives client-side
    in the credential flow itself, which is what makes router failover
    a plain resubmit."""

    def __init__(
        self,
        engine,
        codec,
        tenants=None,
        replica_id="r0",
        clock=time.monotonic,
        result_timeout_s=DEFAULT_RESULT_TIMEOUT_S,
        lifecycle=None,
    ):
        self.engine = engine
        self.codec = codec
        self.tenants = tenants
        self.replica_id = replica_id
        self.clock = clock
        self.result_timeout_s = result_timeout_s
        #: optional engine.lifecycle.LifecycleController: when present,
        #: the beacon reports "warming" until boot() finished its
        #: manifest replay and begin_drain() routes through it
        self.lifecycle = lifecycle
        self.address = None
        self._srv = None
        self._accept_thread = None
        self._closed = False
        self._draining = False
        self._conns_lock = threading.Lock()
        self._conns = set()

    # -- health beacon -------------------------------------------------------

    def beacon(self, now=None):
        """Self-report engine health as a wire.Beacon. Computed from the
        ENGINE OBJECT, not the process-global metrics module — several
        replicas sharing one test process must not read each other's
        gauges. Works for any engine; non-ExecutionEngine services
        (e.g. a bare CredentialService in the bench) report healthy
        with their queue depth."""
        eng = self.engine
        now = self.clock() if now is None else now
        depth = eng.depth() if hasattr(eng, "depth") else 0
        capacity = (
            eng._capacity_fraction()
            if hasattr(eng, "_capacity_fraction")
            else 1.0
        )
        executors = []
        if hasattr(eng, "_all_executors"):
            executors = eng._all_executors()
        healthy = len(executors)
        if executors and hasattr(eng, "_health_of"):
            healthy = sum(
                1
                for ex in executors
                if eng._health_of(ex.label).admissible()
            )
        brownout = False
        if hasattr(eng, "_brownout") and hasattr(eng, "_order"):
            primary = eng._order[0]
            # BrownoutPolicy.check is pure — probing it here sheds nothing
            brownout, _ = eng._brownout.check(
                "bulk", depth, primary.queue.max_depth, capacity
            )
        # wire v2: advertise the live key-epoch window when the engine
        # runs a key lifecycle (routers learn which mint epochs verify
        # here); a keychain-less engine advertises the empty window
        keychain = getattr(eng, "keychain", None)
        epochs = (
            tuple(keychain.live_epochs()) if keychain is not None else ()
        )
        # wire v3: piggyback the durable state plane's per-keyspace
        # high-water marks — peers compare them with their own store
        # and anti-entropy-pull any gap (state/replicate.py); an engine
        # without a StateStore advertises the empty mark set
        store = getattr(eng, "state_store", None)
        state_marks = store.marks() if store is not None else ()
        crashed = getattr(eng, "_crashed", None) is not None
        lc_state = (
            self.lifecycle.state if self.lifecycle is not None else None
        )
        if self._closed or crashed:
            state = "down"
        elif self._draining or lc_state in ("draining", "closed"):
            # still answering polls — gossip must see DRAINING (settle
            # in-flight, route new sessions elsewhere), not a miss that
            # reads as a crash
            state = "draining"
        elif lc_state == "warming":
            state = "warming"
        elif capacity <= 0.0 or (executors and healthy == 0):
            state = "quarantined"
        elif brownout:
            state = "brownout"
        else:
            state = "healthy"
        return wire.Beacon(
            replica_id=self.replica_id,
            state=state,
            capacity_fraction=capacity,
            queue_depth=depth,
            brownout=bool(brownout),
            healthy_executors=healthy,
            executors=len(executors),
            t=now,
            epochs=epochs,
            state_marks=state_marks,
        )

    # -- request handling ----------------------------------------------------

    def _error_frame(self, exc, seq, program=None):
        return encode_frame(
            MSG_ERROR, wire.encode_error(exc, program=program), seq=seq
        )

    def _submit(self, program, args, lane):
        m = getattr(self.engine, "submit_" + program, None)
        if m is None and program == "verify":
            # a bare verify service (CredentialService) exposes submit()
            m = getattr(self.engine, "submit", None)
        if m is None:
            raise GeneralError(
                "replica %r does not serve program %r"
                % (self.replica_id, program)
            )
        return m(*args, lane=lane)

    def handle_message(self, msg_type, seq, payload, send):
        """Process one decoded frame; `send(frame_bytes)` is called
        exactly once — immediately for beacons and refusals, or from the
        engine thread that settles the request's future. `send` must be
        safe to call from another thread (the socket path serializes
        writes under a per-connection lock)."""
        metrics.count("gateway_requests")
        if msg_type == MSG_BEACON_POLL:
            if self._closed:
                metrics.count("gateway_refusals")
                send(
                    self._error_frame(
                        ServiceClosedError("replica closed"), seq
                    )
                )
                return
            send(
                encode_frame(
                    MSG_BEACON, wire.encode_beacon(self.beacon()), seq=seq
                )
            )
            return
        if msg_type == wire.MSG_STATE_PULL:
            # anti-entropy page (PR 17): serve replicated state records
            # from the engine's StateStore per-origin log. Served even
            # while draining — state transfer is how facts escape a
            # replica on its way down — but not once closed.
            if self._closed:
                metrics.count("gateway_refusals")
                send(
                    self._error_frame(
                        ServiceClosedError("replica closed"), seq
                    )
                )
                return
            store = getattr(self.engine, "state_store", None)
            try:
                ks, origin, after_seq, limit = wire.decode_state_pull(
                    payload
                )
                records = (
                    store.records_after(ks, origin, after_seq, limit)
                    if store is not None
                    else ()
                )
                metrics.count("gateway_state_pulls")
                send(
                    encode_frame(
                        wire.MSG_STATE_CHUNK,
                        wire.encode_state_chunk(records),
                        seq=seq,
                    )
                )
            except Exception as e:
                metrics.count("gateway_wire_errors")
                send(self._error_frame(e, seq))
            return
        program = PROGRAM_OF_REQUEST.get(msg_type)
        if program is None:
            metrics.count("gateway_wire_errors")
            send(
                self._error_frame(
                    DeserializationError(
                        "unknown request type 0x%02x" % msg_type
                    ),
                    seq,
                )
            )
            return
        try:
            program, lane, api_key, _session, args = (
                self.codec.decode_request(msg_type, payload)
            )
        except DeserializationError as e:
            metrics.count("gateway_wire_errors")
            send(self._error_frame(e, seq, program))
            return
        try:
            if self._closed or self._draining:
                # retryable over the wire (PR 14): the router fails this
                # over to a ring successor instead of surfacing it
                raise ServiceClosedError(
                    "replica %r is %s: resubmit elsewhere"
                    % (
                        self.replica_id,
                        "draining" if self._draining else "closed",
                    )
                )
            if self.tenants is not None:
                self.tenants.admit(api_key, program=program)
            fut = self._submit(program, args, lane)
        except Exception as e:
            metrics.count("gateway_refusals")
            send(self._error_frame(e, seq, program))
            return

        def _respond(f):
            exc = f.exception()
            if exc is not None:
                metrics.count("gateway_errors")
                send(self._error_frame(exc, seq, program))
                return
            try:
                frame = encode_frame(
                    RESPONSE_TYPES[program],
                    self.codec.encode_response(program, f.result()),
                    seq=seq,
                )
            except Exception as e:
                metrics.count("gateway_errors")
                send(self._error_frame(e, seq, program))
                return
            metrics.count("gateway_responses")
            send(frame)

        fut.add_done_callback(_respond)

    def handle_frame(self, data, timeout=None):
        """Synchronous request/response: one encoded frame in, one
        encoded response frame out (the loopback-transport data path).
        Blocks until the engine settles, bounded by `timeout`."""
        if self._closed:
            raise ConnectionError(
                "replica %r is closed" % (self.replica_id,)
            )
        try:
            msg_type, seq, payload = decode_frame(data)
        except DeserializationError as e:
            metrics.count("gateway_wire_errors")
            return self._error_frame(e, seq=0)
        box = []
        done = threading.Event()

        def send(frame):
            box.append(frame)
            done.set()

        self.handle_message(msg_type, seq, payload, send)
        if not done.wait(
            self.result_timeout_s if timeout is None else timeout
        ):
            raise TimeoutError(
                "replica %r: no response within timeout"
                % (self.replica_id,)
            )
        return box[0]

    # -- socket serve loop ---------------------------------------------------

    def serve(self, host="127.0.0.1", port=0):
        """Bind, listen, and serve on a daemon accept thread; returns
        the bound (host, port). port=0 picks a free port."""
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host, port))
        srv.listen(64)
        self._srv = srv
        self._closed = False
        self.address = srv.getsockname()
        self._accept_thread = threading.Thread(
            target=self._accept_loop,
            name="replica-%s-accept" % self.replica_id,
            daemon=True,
        )
        self._accept_thread.start()
        return self.address

    def _accept_loop(self):
        while True:
            try:
                conn, _peer = self._srv.accept()
            except OSError:
                return  # listener closed
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(
                target=self._conn_loop,
                args=(conn,),
                name="replica-%s-conn" % self.replica_id,
                daemon=True,
            ).start()

    def _conn_loop(self, conn):
        wlock = threading.Lock()

        def send(frame):
            try:
                with wlock:
                    conn.sendall(frame)
            except OSError:
                pass  # peer gone; its client-side futures fail there

        try:
            while True:
                header = _recv_exact(conn, HEADER_BYTES)
                try:
                    msg_type, seq, length = parse_header(header)
                except DeserializationError as e:
                    # framing is lost — answer once and drop the
                    # connection rather than stream garbage
                    metrics.count("gateway_wire_errors")
                    send(self._error_frame(e, seq=0))
                    return
                payload = _recv_exact(conn, length)
                self.handle_message(msg_type, seq, payload, send)
        except (ConnectionError, OSError):
            pass
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def begin_drain(self, timeout=None):
        """Graceful drain-and-handoff (PR 14): flip the beacon to
        DRAINING and stop admitting program requests (each refusal is a
        RETRYABLE ServiceClosedError the router resubmits on a ring
        successor), keep ANSWERING beacon polls so gossip sees an
        orderly shutdown rather than a crash, settle every in-flight
        future via the engine drain (response frames go out as futures
        settle), then close the listener. `timeout` is ONE deadline
        shared across the whole drain. Returns True iff the engine
        drained in time."""
        self._draining = True
        if self.lifecycle is not None:
            ok = self.lifecycle.begin_drain(timeout=timeout)
        else:
            drain = getattr(self.engine, "drain", None)
            ok = bool(drain(timeout=timeout)) if callable(drain) else True
        self.close()
        return ok

    def close(self):
        """Stop serving: refuse new frames, close the listener and every
        live connection. The wrapped engine is NOT drained — the probe's
        kill/rejoin cycle closes and re-serves the same engine."""
        self._closed = True
        if self._srv is not None:
            try:
                self._srv.close()
            except OSError:
                pass
            self._srv = None
        with self._conns_lock:
            conns, self._conns = list(self._conns), set()
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=2.0)
            self._accept_thread = None


class LoopbackTransport:
    """In-memory transport calling a Replica's handle_frame directly on
    the submitting thread — fully deterministic (no sockets, no reader
    threads), which is what lets the chaos tests run on a fake clock.
    `kill()` simulates a dead peer: every subsequent request raises
    TransientBackendError, exactly like a torn TCP connection."""

    def __init__(self, replica, timeout_s=DEFAULT_RESULT_TIMEOUT_S):
        self.replica = replica
        self.timeout_s = timeout_s
        self._dead = None

    def request(self, msg_type, payload, timeout=None):
        if self._dead is not None:
            raise TransientBackendError(
                "loopback to %r is down: %s"
                % (self.replica.replica_id, self._dead)
            )
        try:
            resp = self.replica.handle_frame(
                encode_frame(msg_type, payload, seq=1),
                timeout=self.timeout_s if timeout is None else timeout,
            )
        except (ConnectionError, OSError) as e:
            raise TransientBackendError(
                "loopback to %r failed: %s"
                % (self.replica.replica_id, e)
            )
        resp_type, _seq, resp_payload = decode_frame(resp)
        return resp_type, resp_payload

    def request_async(self, msg_type, payload):
        """Future-shaped request (the client's submit path). Loopback
        resolves it inline — synchronous under the hood, so tests see
        every effect the moment submit returns."""
        fut = ServeFuture()
        try:
            fut.set_result(self.request(msg_type, payload))
        except Exception as e:
            fut.set_exception(e)
        return fut

    @property
    def dead(self):
        return self._dead is not None

    def kill(self):
        self._dead = "killed"

    def revive(self):
        self._dead = None

    def close(self):
        self._dead = "closed"


class SocketTransport:
    """One TCP connection multiplexing concurrent requests by seq. The
    reader thread settles each response onto its pending future; a torn
    connection fails EVERY pending future with TransientBackendError so
    no client ever dangles on a dead socket."""

    def __init__(self, address, connect_timeout_s=5.0):
        host, port = address
        self._sock = socket.create_connection(
            (host, port), timeout=connect_timeout_s
        )
        self._sock.settimeout(None)
        self._wlock = threading.Lock()
        self._lock = threading.Lock()
        self._pending = {}
        self._next_seq = 1
        self._dead = None
        self._reader = threading.Thread(
            target=self._reader_loop,
            name="gateway-reader-%s:%s" % (host, port),
            daemon=True,
        )
        self._reader.start()

    @property
    def dead(self):
        return self._dead is not None

    def request_async(self, msg_type, payload):
        fut = ServeFuture()
        with self._lock:
            if self._dead is not None:
                fut.set_exception(
                    TransientBackendError(
                        "gateway connection down: %s" % (self._dead,)
                    )
                )
                return fut
            seq = self._next_seq
            self._next_seq += 1
            self._pending[seq] = fut
        frame = encode_frame(msg_type, payload, seq=seq)
        try:
            with self._wlock:
                self._sock.sendall(frame)
        except OSError as e:
            self._fail(e)  # fails every pending future, ours included
        return fut

    def request(self, msg_type, payload, timeout=None):
        return self.request_async(msg_type, payload).result(timeout)

    def _reader_loop(self):
        try:
            while True:
                header = _recv_exact(self._sock, HEADER_BYTES)
                msg_type, seq, length = parse_header(header)
                payload = _recv_exact(self._sock, length)
                with self._lock:
                    fut = self._pending.pop(seq, None)
                if fut is not None:
                    fut.set_result((msg_type, payload))
        except (ConnectionError, OSError, DeserializationError) as e:
            self._fail(e)

    def _fail(self, e):
        with self._lock:
            if self._dead is None:
                self._dead = e
            pending, self._pending = self._pending, {}
        if pending:
            metrics.count("gateway_conn_failures")
        err = TransientBackendError(
            "gateway connection lost: %s" % (e,)
        )
        for fut in pending.values():
            fut.set_exception(err)
        try:
            self._sock.close()
        except OSError:
            pass

    def close(self):
        self._fail(ConnectionError("closed by client"))


class RpcFuture:
    """Client-side future mapping a transport response onto the engine
    API's result shape — or re-raising the decoded typed exception, so
    `except ServiceBrownoutError` works identically over the wire."""

    def __init__(self, inner, program, codec):
        self._inner = inner
        self._program = program
        self._codec = codec
        #: parity with ServeFuture's tracing field (no trace over RPC)
        self.trace_id = None

    def done(self):
        return self._inner.done()

    def add_done_callback(self, fn):
        self._inner.add_done_callback(lambda _f: fn(self))

    def result(self, timeout=None):
        msg_type, payload = self._inner.result(timeout)
        if msg_type == MSG_ERROR:
            raise wire.decode_error(payload)
        want = RESPONSE_TYPES[self._program]
        if msg_type != want:
            raise DeserializationError(
                "response type 0x%02x for %r (want 0x%02x)"
                % (msg_type, self._program, want)
            )
        return self._codec.decode_response(self._program, payload)

    def exception(self, timeout=None):
        try:
            self.result(timeout)
            return None
        except TimeoutError:
            raise
        except Exception as e:
            return e


class GatewayClient:
    """ProtocolEngine's submit_* surface over one transport. Stamps the
    caller's API key and session id onto every request frame; the
    session id is ONLY routing affinity (net/router.py hashes it) —
    replicas themselves stay stateless."""

    def __init__(self, transport, codec, api_key="", session=""):
        self.transport = transport
        self.codec = codec
        self.api_key = api_key
        self.session = session

    def _submit(self, program, args, lane, session):
        payload = self.codec.encode_request(
            program,
            args,
            lane=lane,
            api_key=self.api_key,
            session=self.session if session is None else session,
        )
        inner = self.transport.request_async(
            REQUEST_TYPES[program], payload
        )
        return RpcFuture(inner, program, self.codec)

    # max_wait_ms rides for API compat with the engine surface; the
    # replica applies each program's own coalescing default server-side

    def submit_verify(self, sig, messages, lane="interactive",
                      max_wait_ms=None, session=None):
        return self._submit("verify", (sig, messages), lane, session)

    #: CredentialService-shaped alias (bench + verify loadgen)
    def submit(self, sig, messages, lane="interactive", max_wait_ms=None):
        return self.submit_verify(
            sig, messages, lane=lane, max_wait_ms=max_wait_ms
        )

    def submit_prepare(self, messages, elgamal_pk, lane="bulk",
                       max_wait_ms=None, session=None):
        return self._submit(
            "prepare", (messages, elgamal_pk), lane, session
        )

    def submit_mint(self, sig_request, messages, elgamal_sk,
                    lane="interactive", max_wait_ms=None, session=None):
        return self._submit(
            "mint", (sig_request, messages, elgamal_sk), lane, session
        )

    def submit_show_prove(self, sig, messages, lane="interactive",
                          max_wait_ms=None, session=None):
        return self._submit("show_prove", (sig, messages), lane, session)

    def submit_show_verify(self, proof, revealed_msgs, challenge=None,
                           epoch=None, domain=None, tag=None,
                           lane="interactive", max_wait_ms=None,
                           session=None):
        return self._submit(
            "show_verify",
            (proof, revealed_msgs, challenge, epoch, domain, tag),
            lane, session,
        )

    def poll_beacon(self, timeout=5.0):
        """Synchronous beacon poll — the GossipLoop poller. Raises the
        decoded error (or TransientBackendError) on a refusing or dead
        replica, which the loop records as a miss."""
        msg_type, payload = self.transport.request(
            MSG_BEACON_POLL, b"", timeout=timeout
        )
        if msg_type == MSG_ERROR:
            raise wire.decode_error(payload)
        if msg_type != MSG_BEACON:
            raise DeserializationError(
                "beacon poll answered with 0x%02x" % msg_type
            )
        return wire.decode_beacon(payload)

    def pull_state(self, keyspace, origin, after_seq, limit=512,
                   timeout=5.0):
        """Synchronous anti-entropy pull (PR 17): one page of the
        peer's replicated state records for (keyspace, origin) with
        seq > after_seq. The StateReplicator's transfer path."""
        msg_type, payload = self.transport.request(
            wire.MSG_STATE_PULL,
            wire.encode_state_pull(keyspace, origin, after_seq, limit),
            timeout=timeout,
        )
        if msg_type == MSG_ERROR:
            raise wire.decode_error(payload)
        if msg_type != wire.MSG_STATE_CHUNK:
            raise DeserializationError(
                "state pull answered with 0x%02x" % msg_type
            )
        return wire.decode_state_chunk(payload)

    def close(self):
        self.transport.close()
