"""First-t-of-n quorum tracking and credential minting.

The core threshold-issuance fact this module exploits: an aggregated
Coconut credential needs partial signatures from ANY t of the n
authorities, and every valid t-subset interpolates to the SAME signature
(Lagrange at 0 is subset-independent for a degree-(t-1) sharing). So the
service fans a coalesced batch to all live authorities and resolves the
moment the FIRST t partials land — the slowest n-t authorities are off
the latency path entirely, which is what makes hedging (hedge.py) a
latency optimization instead of a correctness requirement.

Three pieces:

  Fanout — one coalesced batch's fan-out record: the queue requests, the
    per-request SignatureRequests/messages/ElGamal secrets, which
    authorities were targeted, and the partial-signature rows that have
    landed so far, each attributed to ITS authority (per-partial
    PROVENANCE — when a minted credential fails verification, the minter
    re-checks each contributing partial under its authority's OWN verkey
    and the quorum drops exactly the culprit's row, never a bystander's).

  QuorumTracker — the arrival bookkeeping: `record()` files one
    authority's partial row and returns the first-t subset exactly once,
    when the t-th distinct row lands; late rows (straggler or hedge
    loser) and rows from abandoned workers are DISCARDED by the stale
    guard ("issue_partials_discarded") — same shape as serve/service.py's
    `_settle` stale check, keyed here on fan-out resolution instead of
    future.done().

  CryptoMinter — the crypto on the resolution path: batch-unblind the
    winning rows (per-request ElGamal secrets), Lagrange-aggregate via
    `signature.batch_aggregate` (ONE [B, t] distinct MSM), and verify
    every minted credential under the subset's aggregated verkey BEFORE
    release — a corrupt partial can waste a mint round, but a credential
    that doesn't verify is never handed to a client. StubMinter in
    tests/test_issue.py swaps this out so quorum/hedge logic tests run
    fake-clock, crypto-free.
"""

import threading
import time

from .. import metrics
from ..signature import (
    Verkey,
    batch_aggregate,
    batch_unblind,
)
from ..ps import batch_verify


class Fanout:
    """One coalesced batch's quorum state. Quorum-arrival fields
    (partials/order/dropped/pending/resolved/minting) mutate under the
    owning QuorumTracker's lock; dispatch bookkeeping (targets/failed)
    under the service's fan-out lock (issue/service.py `_flock`)."""

    __slots__ = (
        "fid",
        "requests",
        "sig_reqs",
        "messages_list",
        "sks",
        "bspan",
        "t_dispatch",
        "partials",
        "order",
        "dropped",
        "pending",
        "targets",
        "failed",
        "resolved",
        "minting",
        "quorum_at",
        "keyset",
        "threshold",
    )

    def __init__(self, fid, requests, sig_reqs, messages_list, sks, bspan, now,
                 keyset=None, threshold=None):
        self.fid = fid
        self.requests = requests
        self.sig_reqs = sig_reqs
        self.messages_list = messages_list
        self.sks = sks  # per-request ElGamal secrets, aligned with requests
        self.bspan = bspan
        self.t_dispatch = now
        #: signer_id -> [BlindSignature] * B, one row per contributing
        #: authority — the provenance record attribution reads from
        self.partials = {}
        self.order = []  # signer ids in row-arrival order (first-t basis)
        self.dropped = set()  # signer ids whose rows were attributed corrupt
        self.pending = set(range(len(requests)))  # unresolved request indices
        self.targets = {}  # label -> SigningAuthority currently signing this
        self.failed = set()  # labels that crashed/hung/failed on this fan-out
        self.resolved = False  # every request settled; late rows are stale
        self.minting = False  # a thread is inside the mint path right now
        self.quorum_at = None
        #: key-lifecycle pin (keylife.KeySet) this fan-out mints under —
        #: fixed at open so a mid-flight refresh/reshare never mixes
        #: partials from different sharings; None on the boot-keys path
        self.keyset = keyset
        #: quorum size for THIS fan-out (a reshare may change t for
        #: later fan-outs; in-flight ones keep the t they opened with)
        self.threshold = threshold

    def available_ids(self):
        """Contributing signer ids still usable for aggregation, in
        arrival order (dropped == attributed-corrupt rows excluded)."""
        return [i for i in self.order if i not in self.dropped]


class QuorumTracker:
    """Arrival bookkeeping for open fan-outs: exactly-once quorum
    resolution, stale/duplicate discard, and corrupt-row drops."""

    def __init__(self, threshold, clock=time.monotonic):
        if threshold < 1:
            raise ValueError("threshold must be >= 1 (got %r)" % (threshold,))
        self.threshold = threshold
        self.clock = clock
        self._lock = threading.Lock()
        self._open = {}  # fid -> Fanout

    def open(self, fanout):
        with self._lock:
            self._open[fanout.fid] = fanout

    def record(self, fanout, signer_id, partials, now=None):
        """File one authority's partial row. Returns the first-t subset
        (signer ids, arrival order) exactly once — on the call that makes
        the quorum — else None. Stale rows (fan-out already resolved) and
        duplicate rows (a hedge racing the original of the SAME authority,
        or a redispatch overlap) are discarded, not filed: counted under
        "issue_partials_discarded"."""
        now = self.clock() if now is None else now
        with self._lock:
            if fanout.resolved or signer_id in fanout.partials:
                metrics.count("issue_partials_discarded", len(partials))
                return None
            fanout.partials[signer_id] = partials
            fanout.order.append(signer_id)
            t = fanout.threshold or self.threshold
            usable = len(fanout.available_ids())
            if usable < t or fanout.minting:
                return None
            fanout.minting = True
            if fanout.quorum_at is None:
                fanout.quorum_at = now
                metrics.observe("issue_quorum_wait_s", now - fanout.t_dispatch)
            return fanout.available_ids()[:t]

    def drop_partials(self, fanout, signer_ids):
        """Attribution verdict: these authorities' rows are corrupt —
        remove them from every future subset ("issue_corrupt_partials"
        is counted by the service, which also quarantines)."""
        with self._lock:
            fanout.dropped.update(signer_ids)

    def next_subset(self, fanout):
        """After a failed mint round (corrupt rows dropped): the next
        usable first-t subset, or None if the remaining rows can't make
        quorum yet. Caller must still hold the minting claim."""
        with self._lock:
            if fanout.resolved:
                return None
            t = fanout.threshold or self.threshold
            ids = fanout.available_ids()
            if len(ids) >= t:
                return ids[:t]
            fanout.minting = False  # wait for more rows to land
            return None

    def release_minting(self, fanout):
        """Give up the minting claim without resolving (mint-path crash
        containment) so a later row can retry the mint."""
        with self._lock:
            fanout.minting = False

    def settle(self, fanout, indices):
        """Mark request indices resolved; returns True when the fan-out
        is fully settled (caller then closes it everywhere: authority
        inboxes, hedge timers, watchdog labels)."""
        with self._lock:
            fanout.pending.difference_update(indices)
            done = not fanout.pending
            if done:
                fanout.resolved = True
                fanout.minting = False
            return done

    def close_fanout(self, fanout):
        """Drop a fully-settled (or force-failed) fan-out. Idempotent.
        Marks it resolved so any in-flight sign's row hits the stale
        guard instead of resurrecting the record."""
        with self._lock:
            fanout.resolved = True
            fanout.minting = False
            self._open.pop(fanout.fid, None)

    def outstanding(self):
        """Snapshot of still-open fan-outs (drain's final sweep)."""
        with self._lock:
            return list(self._open.values())


class CryptoMinter:
    """The resolution-path crypto: unblind -> Lagrange-aggregate ->
    verify-before-release, plus per-partial attribution when a mint
    fails. Pluggable (tests swap in a StubMinter) so quorum mechanics
    are testable without pairings."""

    def __init__(self, threshold, verkeys_by_id, params, backend=None):
        from ..backend import get_backend

        if backend is None or isinstance(backend, str):
            backend = get_backend(backend or "python")
        self.threshold = threshold
        self.verkeys = dict(verkeys_by_id)  # signer_id -> per-signer Verkey
        self.params = params
        self.backend = backend
        self._agg_cache = {}  # sorted id tuple -> aggregated Verkey

    def _agg_verkey(self, subset):
        key = tuple(sorted(subset))
        vk = self._agg_cache.get(key)
        if vk is None:
            vk = Verkey.aggregate(
                self.threshold,
                [(i, self.verkeys[i]) for i in subset],
                ctx=self.params.ctx,
            )
            self._agg_cache[key] = vk
        return vk

    def unblind(self, blind_rows, sks):
        """blind_rows: per-request list of the subset's BlindSignatures;
        sks: per-request ElGamal secrets. One flattened batch_unblind
        call; returns per-request rows of partial Signatures."""
        flat, flat_sks, widths = [], [], []
        for row, sk in zip(blind_rows, sks):
            widths.append(len(row))
            flat.extend(row)
            flat_sks.extend([sk] * len(row))
        out = batch_unblind(
            flat, flat_sks, self.params.ctx, backend=self.backend
        )
        rows, at = [], 0
        for w in widths:
            rows.append(out[at : at + w])
            at += w
        return rows

    def aggregate(self, subset, sig_rows):
        """Lagrange-aggregate each request's subset row — one [B, t]
        distinct MSM via signature.batch_aggregate."""
        partials_list = [
            list(zip(subset, row)) for row in sig_rows
        ]
        return batch_aggregate(
            self.threshold,
            partials_list,
            ctx=self.params.ctx,
            backend=self.backend,
        )

    def verify(self, creds, messages_list, subset):
        """Per-credential verdicts under the subset's aggregated verkey —
        the release gate: only True lanes leave the service."""
        vk = self._agg_verkey(subset)
        return batch_verify(
            creds, messages_list, vk, self.params, backend=self.backend
        )

    def verify_partial(self, signer_id, sig, messages):
        """Attribution check: a partial Signature is itself a valid PS
        signature under ITS authority's own verkey — so when a mint
        fails, re-checking each contributing partial names the culprit
        authority exactly."""
        return sig.verify(messages, self.verkeys[signer_id], self.params)
