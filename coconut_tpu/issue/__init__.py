"""Threshold-issuance service: quorum fan-out over a signing-authority
pool, first-t-of-n Lagrange aggregation, and straggler-hedged minting.

The issuance-side sibling of coconut_tpu/serve (which VERIFIES minted
credentials online): clients submit blind-sign requests, the service
fans each coalesced batch to every live authority, resolves on the first
t partial signatures, and releases only credentials that verify under
the subset's aggregated verkey. See issue/service.py for the design.

    from coconut_tpu.issue import IssuanceService

    svc = IssuanceService(signers, params, threshold=3).start()
    fut = svc.submit(sig_request, messages, elgamal_sk)
    credential = fut.result(timeout=5.0)   # a verified Signature
    svc.drain()
"""

from .authority import SigningAuthority
from .hedge import HedgePolicy, HedgeScheduler
from .quorum import CryptoMinter, Fanout, QuorumTracker
from .service import IssuanceOrder, IssuanceService

__all__ = [
    "CryptoMinter",
    "Fanout",
    "HedgePolicy",
    "HedgeScheduler",
    "IssuanceOrder",
    "IssuanceService",
    "QuorumTracker",
    "SigningAuthority",
]
