"""Straggler-hedging policy for quorum fan-out (Dean & Barroso, "The
Tail at Scale"): when one authority's sign dispatch outlives k x its own
latency EMA, send the batch to a SPARE authority instead of waiting —
first-t-wins means the hedge and the straggler race, the quorum takes
whichever t distinct partials land first, and the loser's late partials
are discarded by the stale guard (quorum.py).

Two decide-only objects, mirroring serve/health.py's discipline (they
DECIDE, the service ACTS; everything fake-clock testable):

  HedgePolicy — per-authority EMA of sign latency and the hedge budget
    ``clamp(k * ema, min_delay_s, max_delay_s)`` derived from it
    (`initial_delay_s` covers an authority with no EMA yet — the first
    sign may pay a jit compile; don't hedge around it). The hedge k is
    deliberately SMALLER than the watchdog's: hedging is a latency
    optimization that costs one duplicate dispatch, while a watchdog
    expiry condemns the authority — so the service hedges early and
    quarantines late.

  HedgeScheduler — the outstanding (fan-out, authority) sign dispatches
    and their hedge deadlines. `begin()` at dispatch, `end()` when the
    partial lands (or the target fails — a failed target is re-covered
    immediately, not hedged on a timer), `due(now)` pops every entry past
    its deadline exactly once — a straggler is hedged at most once per
    fan-out per authority. `cancel(fid)` drops a resolved fan-out's
    remaining entries: once the quorum is minted, nobody races for it.
"""

import threading
import time


class HedgePolicy:
    """Per-authority sign-latency EMA -> hedge-fire budget."""

    def __init__(
        self,
        k=3.0,
        alpha=0.25,
        initial_delay_s=30.0,
        min_delay_s=0.01,
        max_delay_s=60.0,
    ):
        if k <= 0 or alpha <= 0 or alpha > 1:
            raise ValueError("need k > 0 and 0 < alpha <= 1")
        self.k = k
        self.alpha = alpha
        self.initial_delay_s = initial_delay_s
        self.min_delay_s = min_delay_s
        self.max_delay_s = max_delay_s
        self._lock = threading.Lock()
        self._ema = {}  # label -> EMA of successful sign durations

    def observe(self, label, dur):
        """Fold one successful sign duration into `label`'s EMA."""
        with self._lock:
            prev = self._ema.get(label)
            self._ema[label] = (
                dur if prev is None else self.alpha * dur + (1 - self.alpha) * prev
            )

    def ema(self, label):
        with self._lock:
            return self._ema.get(label)

    def budget(self, label):
        """Seconds to wait on `label`'s next sign before hedging."""
        with self._lock:
            ema = self._ema.get(label)
        if ema is None:
            return self.initial_delay_s
        return min(self.max_delay_s, max(self.min_delay_s, self.k * ema))


class HedgeScheduler:
    """Deadline tracker for outstanding (fan-out, authority) dispatches.

    All state behind one lock: authority threads begin/end while the
    health tick pops due entries. Entries are keyed (fid, label); `due()`
    POPS, so each straggler fires its hedge exactly once."""

    def __init__(self, clock=time.monotonic):
        self.clock = clock
        self._lock = threading.Lock()
        self._deadlines = {}  # (fid, label) -> (deadline, fanout)

    def begin(self, fanout, label, budget_s, now=None):
        now = self.clock() if now is None else now
        with self._lock:
            self._deadlines[(fanout.fid, label)] = (now + budget_s, fanout)

    def end(self, fid, label):
        """The partial landed (or the target failed): stop the timer."""
        with self._lock:
            self._deadlines.pop((fid, label), None)

    def cancel(self, fid):
        """Fan-out resolved: drop every remaining timer it owns."""
        with self._lock:
            gone = [key for key in self._deadlines if key[0] == fid]
            for key in gone:
                del self._deadlines[key]
            return len(gone)

    def due(self, now=None):
        """Pop and return every straggler past its hedge deadline as
        ``(fanout, label, overdue_s)``."""
        now = self.clock() if now is None else now
        out = []
        with self._lock:
            late = [k for k, v in self._deadlines.items() if now >= v[0]]
            for key in late:
                deadline, fanout = self._deadlines.pop(key)
                out.append((fanout, key[1], now - deadline))
        return out

    def outstanding(self):
        with self._lock:
            return len(self._deadlines)
