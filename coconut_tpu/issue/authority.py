"""SigningAuthority: one threshold key share's signing executor.

Each authority owns exactly one Shamir share (a keygen.Signer: 1-based
id, Sigkey share, per-signer Verkey) and runs `batch_blind_sign` over
coalesced request batches on ITS backend/device — the issuance analog of
serve/service._DeviceExecutor, with the same worker discipline:

  - an inbox of fan-outs (quorum.Fanout) the service dispatched here,
    bounded by `can_accept()` (2 queued fan-outs: one signing + one
    waiting) so backlog stays in the bounded request queue;
  - DEVICE PINNING through the same `jax.default_device` seam the verify
    pool uses (stream._pin_to_device semantics): operands created inside
    the sign dispatch commit to this authority's chip, so each share's
    MSMs stay on its own device and the jit cache stays per-device-hot;
  - GENERATIONS + `abandon()` for hang containment: the watchdog bumps
    the generation of a wedged worker, whose eventual return is discarded
    by the quorum tracker's stale guard; `start()` respawns a fresh
    worker for the probation probe;
  - loop-level crash containment: a BaseException escaping the per-batch
    handling (faults.InjectedCrash models it) lands in
    `service._authority_failed`, which quarantines ONLY this authority
    and re-covers its in-flight fan-outs from spares.

The sign dispatch goes THROUGH the backend object when it exposes
`batch_blind_sign` (faults.FaultyBackend always does — that is the chaos
seam; stub backends in tests too), else through the library entry point
`signature.batch_blind_sign` with this backend's MSM primitives.
"""

import threading
from collections import deque

from .. import metrics
from ..errors import GeneralError
from ..signature import batch_blind_sign as _batch_blind_sign


class SigningAuthority:
    """One key share's signing loop. `service` is the owning
    IssuanceService; `signer` a keygen.Signer; `backend` an instance or
    registry name (each authority may carry its own — chaos tests wrap
    one authority's backend without touching the others); `device` an
    optional jax device to pin sign dispatches to."""

    def __init__(self, service, signer, backend=None, device=None, label=None):
        from ..backend import get_backend

        if backend is None or isinstance(backend, str):
            backend = get_backend(backend or "python")
        self.service = service
        self.signer = signer
        self.id = signer.id
        self.sigkey = signer.sigkey
        self.verkey = signer.verkey
        self.backend = backend
        self.device = device
        self.label = str(signer.id) if label is None else label
        self.busy_timer = "issue_auth%s_busy_s" % self.label
        self._cond = threading.Condition()
        self._inbox = deque()
        self._closed = False
        self._gen = 0
        self._thread = None
        #: keylife share store: (epoch, gen) -> (Sigkey, Verkey). The
        #: boot `signer` share stays the keyset-less default, so the
        #: historical surface is untouched when no lifecycle runs.
        self._keys = {}

    # -- key lifecycle -------------------------------------------------------

    def install_keys(self, key, sigkey, verkey):
        """Install this authority's share for one KeySet — (epoch, gen)
        keyed, so a refresh's new shares and a reshare's new epoch both
        land without disturbing fan-outs pinned to older sets."""
        self._keys[key] = (sigkey, verkey)

    def _share_for(self, keyset):
        if keyset is None:
            return self.sigkey
        entry = self._keys.get(keyset.key)
        if entry is None:
            # surfaces as a sign FAULT: the service marks this target
            # failed and re-covers the fan-out from spares
            raise GeneralError(
                "authority %s has no key material for epoch %d gen %d"
                % (self.label, keyset.epoch, keyset.gen)
            )
        return entry[0]

    # -- sign dispatch -------------------------------------------------------

    def sign(self, sig_requests, params, keyset=None):
        """Blind-sign one coalesced batch under this share (the boot
        share, or `keyset`'s installed share), pinned to this authority's
        device when it has one."""
        sigkey = self._share_for(keyset)
        if self.device is not None:
            import jax

            with jax.default_device(self.device):
                return self._sign_inner(sig_requests, params, sigkey)
        return self._sign_inner(sig_requests, params, sigkey)

    def _sign_inner(self, sig_requests, params, sigkey):
        fn = getattr(self.backend, "batch_blind_sign", None)
        if fn is not None:
            return fn(sig_requests, sigkey, params)
        return _batch_blind_sign(
            sig_requests, sigkey, params, backend=self.backend
        )

    # -- dispatcher side -----------------------------------------------------

    def queued(self):
        with self._cond:
            return len(self._inbox)

    def can_accept(self):
        with self._cond:
            return len(self._inbox) < 2

    def submit(self, fanout):
        with self._cond:
            self._inbox.append(fanout)
            self._cond.notify_all()
        metrics.count("issue_auth%s_dispatches" % self.label)

    def cancel(self, fid):
        """First-t-wins: drop a resolved fan-out from the inbox (a sign
        not yet started never runs; one mid-dispatch finishes and its
        partials hit the stale guard instead). Returns how many queued
        entries were dropped."""
        with self._cond:
            kept = [f for f in self._inbox if f.fid != fid]
            dropped = len(self._inbox) - len(kept)
            if dropped:
                self._inbox.clear()
                self._inbox.extend(kept)
        return dropped

    def sweep_inbox(self):
        """Soft quarantine: pull every QUEUED (not yet signing) fan-out
        back out — the worker stays alive to finish what it's mid-sign
        on, but its backlog's quorum coverage moves to spares."""
        with self._cond:
            swept = list(self._inbox)
            self._inbox.clear()
            self._cond.notify_all()
        return swept

    # -- lifecycle -----------------------------------------------------------

    def start(self):
        """Spawn the worker — no-op while one runs or after close(). Also
        the probation revival path after abandon()."""
        with self._cond:
            if self._closed or self._thread is not None:
                return
            gen = self._gen
            self._thread = threading.Thread(
                target=self._run,
                args=(gen,),
                name="coconut-issue-auth%s.g%d" % (self.label, gen),
                daemon=True,
            )
            thread = self._thread
        thread.start()

    def close(self):
        """Stop accepting; the loop still signs its inbox, then exits."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def join(self, timeout=None):
        if self._thread is None:
            return True
        self._thread.join(timeout)
        return not self._thread.is_alive()

    def has_worker(self):
        with self._cond:
            return self._thread is not None and self._thread.is_alive()

    def is_current(self, gen):
        with self._cond:
            return gen == self._gen

    def abandon(self):
        """Hang/crash containment: bump the generation (the stuck worker
        becomes stale — its eventual partials are discarded by the quorum
        stale guard) and sweep the inbox. Returns the swept fan-outs; the
        caller owns re-covering them. start() can respawn."""
        with self._cond:
            self._gen += 1
            self._thread = None
            swept = list(self._inbox)
            self._inbox.clear()
            self._cond.notify_all()
        return swept

    # -- worker loop ---------------------------------------------------------

    def _next(self, gen):
        with self._cond:
            while True:
                if self._gen != gen:
                    return None  # abandoned: this worker is stale — exit
                if self._inbox:
                    return self._inbox.popleft()
                if self._closed:
                    return None
                self._cond.wait()

    def _run(self, gen):
        svc = self.service
        current = None
        try:
            while True:
                current = self._next(gen)
                if current is None:
                    return
                svc._sign_fanout(self, current, gen)
                current = None
        except BaseException as e:  # loop-level crash (a code bug in the
            # sign path — faults.InjectedCrash models it): hand the
            # in-flight fan-out plus the swept inbox to the service for
            # quarantine + re-coverage from spare authorities
            svc._authority_failed(self, e, current, gen)
