"""The threshold-issuance service: quorum fan-out over a pool of signing
authorities, first-t-of-n aggregation, and straggler-hedged minting —
packaged as a *program* on the unified execution engine (PR 12).

Where serve/service.py answers "is this credential valid?" against ONE
verkey, this service MINTS credentials against a t-of-n authority pool:
each request's SignatureRequest is blind-signed by every live authority
(quorum fan-out), the first t partial signatures to land are unblinded,
Lagrange-aggregated, and verified under the subset's aggregated verkey,
and only a credential that VERIFIES is released to its future.

The generic serving machinery — bounded admission, coalescing, the
placer thread, the watchdog loop, brownout, lifecycle — is the engine's
(coconut_tpu/engine). What lives HERE is the mint phase itself:

  MintProgram      an own-worker engine program (uses_pool=False): it
                   brings the SigningAuthority pool instead of riding
                   the shared device pool, replaces least-loaded
                   placement with quorum fan-out, keeps its own
                   authority health registry in the "issue_auth*"
                   namespace, claims ITS watchdog expiries (hung signs)
                   via `owns_expiry`, and runs hedge timers + authority
                   probation in the engine's health tick.
  IssuanceService  an ExecutionEngine subclass registering ONE
                   MintProgram, with the historical public API and
                   every historical metric/span name.

What is NEW versus the verify pool (issue/ package) is unchanged from
PR 10 — see quorum.py (QuorumTracker: first-t-wins, per-partial
provenance, drop-and-retry attribution), hedge.py (straggler hedging:
hedge early, quarantine late), authority.py (per-share signing
executors). Failure ladder, per fan-out: a sign FAULT marks the target
failed and re-covers from spares; a sign HANG is expired by the
watchdog (worker abandoned, authority quarantined, coverage restored);
an authority-loop CRASH quarantines only that authority. When live +
landed contributors can no longer reach t, the fan-out's remaining
futures fail with the typed, retriable QuorumUnreachableError — loud,
attributable, and never a dangling future. Drain settles everything in
flight under one shared deadline and sweeps whatever could not reach
quorum.
"""

import threading
import time

from .. import metrics
from ..engine.core import ExecutionEngine, _remaining
from ..engine.program import Program
from ..errors import (
    GeneralError,
    QuorumUnreachableError,
)
from ..obs import trace as otrace
from ..serve import health as _health
from ..serve.batcher import fail_all
from .authority import SigningAuthority
from .hedge import HedgePolicy, HedgeScheduler
from .quorum import CryptoMinter, Fanout, QuorumTracker


class IssuanceOrder:
    """One request's issuance payload, carried in the queue Request's
    `sig` slot (the queue is payload-agnostic): the blind-sign request
    plus the user's ElGamal secret the service unblinds with."""

    __slots__ = ("sig_request", "elgamal_sk")

    def __init__(self, sig_request, elgamal_sk):
        self.sig_request = sig_request
        self.elgamal_sk = elgamal_sk


class MintProgram(Program):
    """The blind-sign/mint phase as an own-worker engine program: quorum
    fan-out over the authority pool, first-t-of-n aggregation, hedging.

    `label_prefix` namespaces authority labels (and their watchdog/
    health keys) when the program shares an engine with pool executors
    whose labels are bare indices — the standalone IssuanceService keeps
    the historical bare str(signer.id) labels."""

    name = "mint"
    metric_ns = "issue"
    slo_class = "standard"
    pad_convention = "none"
    uses_pool = False

    def __init__(
        self,
        signers,
        params,
        threshold,
        backend=None,
        backends=None,
        devices=None,
        minter=None,
        hedge=None,
        max_batch=32,
        max_wait_ms=20.0,
        max_depth=1024,
        label_prefix="",
        keychain=None,
    ):
        signers = list(signers)
        if not signers:
            raise ValueError("need at least one signer")
        if threshold < 1 or threshold > len(signers):
            raise ValueError(
                "threshold %r out of range for %d signers"
                % (threshold, len(signers))
            )
        if backends is not None and len(backends) != len(signers):
            raise ValueError(
                "backends list length %d != %d signers"
                % (len(backends), len(signers))
            )
        if devices is not None and len(devices) != len(signers):
            raise ValueError(
                "devices list length %d != %d signers"
                % (len(devices), len(signers))
            )
        self.signers = signers
        self.params = params
        self.threshold = threshold
        self.max_batch = max_batch
        self.max_wait_ms = max_wait_ms
        self.max_depth = max_depth
        self._backend = backend
        self._backends = backends
        self._devices = devices
        self._minter = minter
        self._hedge = hedge
        self._label_prefix = label_prefix
        #: keylife.EpochRegistry (PR 15): when set, every fan-out pins
        #: the ACTIVE KeySet at open and mints under it start to finish,
        #: minted credentials carry their epoch, and a mid-flight
        #: refresh/reshare never disturbs in-flight work. None = the
        #: historical frozen-at-boot path, byte for byte.
        self.keychain = keychain

    def bind(self, engine):
        super().bind(engine)
        self._authorities = [
            SigningAuthority(
                self,
                s,
                backend=(
                    self._backends[i]
                    if self._backends is not None
                    else self._backend
                ),
                device=(
                    self._devices[i] if self._devices is not None else None
                ),
                label=(
                    self._label_prefix + str(s.id)
                    if self._label_prefix
                    else None
                ),
            )
            for i, s in enumerate(self.signers)
        ]
        self.minter = (
            self._minter
            if self._minter is not None
            else CryptoMinter(
                self.threshold,
                {s.id: s.verkey for s in self.signers},
                self.params,
                backend=self._backend,
            )
        )
        self._tracker = QuorumTracker(self.threshold, clock=engine.clock)
        self._minters = {}  # (epoch, gen) -> CryptoMinter for that KeySet
        self.hedge_policy = (
            self._hedge if self._hedge is not None else HedgePolicy()
        )
        self._hedges = HedgeScheduler(clock=engine.clock)
        #: dispatch bookkeeping lock: Fanout.targets / Fanout.failed and
        #: spare-selection decisions (quorum-arrival state is under the
        #: tracker's own lock; never take _flock while holding it)
        self._flock = threading.Lock()
        self._healths = {}
        for auth in self._authorities:
            self._health_of(auth.label)
        for auth in self._authorities:
            metrics.set_gauge(
                "issue_auth%s_health" % auth.label, _health.HEALTHY
            )
        self.refresh_health_gauges()

    # -- engine hooks --------------------------------------------------------

    @property
    def _queue(self):
        return self.engine._runtimes[self.name].queue

    def capacity_fraction(self):
        ok = sum(
            1
            for a in self._authorities
            if self._health_of(a.label).admissible()
        )
        return ok / len(self._authorities)

    def capacity_ready(self):
        return self._has_quorum_capacity()

    def place(self, batch):
        self._fan_out(batch)

    def refresh_health_gauges(self):
        metrics.set_gauge(
            "issue_healthy_authorities",
            sum(
                1
                for a in self._authorities
                if self._health_of(a.label).admissible()
            ),
        )

    # -- key lifecycle (PR 15) -----------------------------------------------

    def install_keyset(self, keyset):
        """Install one keylife.KeySet: each authority gets ITS share from
        the set's signers, and a per-set CryptoMinter (per-signer verkeys
        for attribution, aggregated-verkey cache for the release gate)
        is readied. Called by KeyLifecycleManager BEFORE the epoch
        activates, so the instant fan-outs start pinning it every
        authority can already sign under it. A reshare's new quorum size
        takes effect for fan-outs opened from then on; in-flight ones
        carry the threshold they pinned."""
        for auth in self._authorities:
            s = keyset.signer(auth.id)
            if s is not None:
                auth.install_keys(keyset.key, s.sigkey, s.verkey)
        self._minters[keyset.key] = CryptoMinter(
            keyset.threshold,
            keyset.verkeys_by_id(),
            self.params,
            backend=self._backend,
        )
        self.threshold = keyset.threshold

    def _minter_for(self, keyset):
        if keyset is None:
            return self.minter
        m = self._minters.get(keyset.key)
        if m is None:
            raise GeneralError(
                "no minter installed for epoch %d gen %d"
                % (keyset.epoch, keyset.gen)
            )
        return m

    def start_workers(self):
        for auth in self._authorities:
            auth.start()

    def close_workers(self):
        for auth in self._authorities:
            auth.close()

    def join_workers(self, deadline):
        ok = True
        for auth in self._authorities:
            ok = auth.join(_remaining(deadline)) and ok
        return ok

    def on_drain(self):
        self._sweep_unreachable()

    def on_crash(self, e):
        """Engine crash sweep: fail every open fan-out's unresolved
        futures with the crash exception, close the authority pool."""
        for f in self._tracker.outstanding():
            pending = [
                i for i in f.pending if not f.requests[i].future.done()
            ]
            if pending:
                self._fail_requests(f, pending, e)
            self._close_fanout(f, result="crashed")
        for auth in self._authorities:
            auth.close()

    def owns_expiry(self, entry):
        # watchdog entries this program began carry a Fanout payload;
        # pool dispatches carry a request list
        return isinstance(entry[2], Fanout)

    def handle_expired(self, entry, now):
        """One hung sign: abandon the stuck worker, quarantine its
        authority, restore the fan-out's quorum coverage."""
        label, fid, fanout, span, overdue_s = entry
        metrics.count("issue_watchdog_timeouts")
        if span is not None:
            span.event(
                "watchdog_timeout",
                authority=label,
                overdue_s=round(overdue_s, 6),
            )
        auth = self._auth_by_label(label)
        if auth is None:
            return
        self._health_of(label).on_crash("hung sign: watchdog timeout")
        swept = auth.abandon()
        self.engine._watchdog.forget_label(label)
        self.refresh_health_gauges()
        self._hedges.end(fid, label)
        for f in [fanout] + swept:
            self._mark_failed(f, label)
            self._ensure_coverage(f)
        self.engine._kick_all()

    def tick(self, now):
        """Per-health-tick: fire due hedges (dispatch a spare for each
        straggling sign) and promote cooled-down authorities into
        half-open probation."""
        for fanout, label, overdue_s in self._hedges.due(now):
            if fanout.resolved:
                continue
            spare = self._pick_spare(fanout)
            if spare is None:
                metrics.count("issue_hedge_no_spare")
                continue
            metrics.count("issue_hedges")
            fanout.bspan.event(
                "hedge",
                straggler=label,
                spare=spare.label,
                overdue_s=round(overdue_s, 6),
            )
            self._dispatch_to(fanout, spare, now=now)
        for auth in self._authorities:
            if self._health_of(auth.label).try_probation(now):
                auth.start()  # respawn an abandoned worker; no-op otherwise
                self.refresh_health_gauges()
                self.engine._kick_all()

    # -- health --------------------------------------------------------------

    def _health_of(self, label):
        h = self._healths.get(label)
        if h is None:
            h = self._healths[label] = _health.ExecutorHealth(
                label,
                self.engine.health_policy,
                clock=self.engine.clock,
                metric_ns="issue",
                gauge_prefix="issue_auth",
            )
        return h

    def _admits(self, auth):
        """May NEW fan-out work target `auth`? Same half-open discipline
        as the verify pool: PROBATION gets one probe dispatch at a time."""
        h = self._health_of(auth.label)
        if not h.admissible():
            return False
        if h.state == _health.PROBATION and auth.queued() > 0:
            return False
        return True

    def _note_success(self, auth):
        change = self._health_of(auth.label).on_success()
        if change:
            self.refresh_health_gauges()
            self.engine._kick_all()

    def _note_failure(self, auth, reason):
        """A sign dispatch (or a partial-signature attribution) failed ON
        this authority: feed its breaker; on quarantine, move its queued
        fan-outs' coverage to spares (soft — the worker stays alive)."""
        change = self._health_of(auth.label).on_failure(reason)
        if change:
            self.refresh_health_gauges()
            self.engine._kick_all()
            if change[1] == _health.QUARANTINED:
                for f in auth.sweep_inbox():
                    self._mark_failed(f, auth.label)
                    self._ensure_coverage(f)

    def _authority_failed(self, auth, exc, inflight, gen):
        """Authority-loop crash containment (runs ON the dying worker's
        thread): quarantine ONLY this authority, re-cover its fan-outs
        from spares. Stale generations (already abandoned by the
        watchdog) do nothing."""
        if not auth.is_current(gen):
            return
        metrics.count("issue_authority_crashes")
        self._health_of(auth.label).on_crash(
            "authority loop crash: %s" % type(exc).__name__
        )
        swept = auth.abandon()
        self.engine._watchdog.forget_label(auth.label)
        self.refresh_health_gauges()
        affected = ([inflight] if inflight is not None else []) + swept
        for f in affected:
            self._mark_failed(f, auth.label)
            self._ensure_coverage(f)
        self.engine._kick_all()

    def _auth_by_label(self, label):
        for a in self._authorities:
            if a.label == label:
                return a
        return None

    def _sweep_unreachable(self):
        """Drain's last act: any fan-out still open could not assemble a
        quorum in time — fail its unresolved futures loudly (typed,
        retriable) so no caller ever hangs on a dropped future."""
        for f in self._tracker.outstanding():
            with self._flock:
                have = len(f.available_ids())
            pending = [
                i for i in f.pending if not f.requests[i].future.done()
            ]
            if pending:
                metrics.count("issue_quorum_unreachable")
                self._fail_requests(
                    f,
                    pending,
                    QuorumUnreachableError(
                        f.threshold or self.threshold,
                        have,
                        live=0,
                        program=self.name,
                    ),
                )
            self._close_fanout(f, result="swept")

    # -- fan-out -------------------------------------------------------------

    def _has_quorum_capacity(self):
        """ready() gate for the batcher: pop a batch only when at least
        `threshold` admissible authorities can accept it — otherwise the
        backlog stays in the bounded queue where admission control and
        the brownout policy see it."""
        return (
            sum(
                1
                for a in self._authorities
                if self._admits(a) and a.can_accept()
            )
            >= self.threshold
        )

    def _fan_out(self, requests):
        """Open one fan-out for a coalesced batch and dispatch it to
        every live authority at once (first-t-wins makes over-dispatch
        the latency strategy)."""
        fid = self.engine._next_seq()
        now = self.engine.clock()
        targets = [
            a for a in self._authorities if self._admits(a) and a.can_accept()
        ]
        if len(targets) < self.threshold:
            # the ready gate normally prevents this; a drain-time flush
            # (closed queue bypasses the gate) widens to anything alive
            targets = [
                a
                for a in self._authorities
                if self._health_of(a.label).admissible() or a.has_worker()
            ]
        if len(targets) < self.threshold:
            metrics.count("issue_quorum_unreachable")
            fail_all(
                requests,
                QuorumUnreachableError(
                    self.threshold, 0, live=len(targets), program=self.name
                ),
                counter="issue_failed_requests",
            )
            return
        keyset = None
        if self.keychain is not None:
            # pin AFTER the early-fail paths so every pin has a matching
            # unpin in _close_fanout; the pin holds this KeySet's epoch
            # out of retirement until the fan-out closes
            try:
                keyset = self.keychain.pin_active()
            except GeneralError as e:
                fail_all(requests, e, counter="issue_failed_requests")
                return
        bspan = otrace.start_span(
            "issue_batch",
            root=True,
            seq=fid,
            n=len(requests),
            quorum=self.threshold,
            fanout_width=len(targets),
            members=[r.future.trace_id for r in requests]
            if otrace.enabled()
            else None,
        )
        for r in requests:
            r.span.set(batch_trace=bspan.trace_id, batch_seq=fid)
        f = Fanout(
            fid,
            requests,
            [r.sig.sig_request for r in requests],
            [r.messages for r in requests],
            [r.sig.elgamal_sk for r in requests],
            bspan,
            now,
            keyset=keyset,
            threshold=keyset.threshold if keyset is not None else None,
        )
        self._tracker.open(f)
        metrics.observe(
            "issue_batch_wait_s", now - min(r.t_submit for r in requests)
        )
        metrics.set_gauge("issue_queue_depth", self._queue.depth())
        for auth in targets:
            self._dispatch_to(f, auth, now=now)

    def _dispatch_to(self, fanout, auth, now=None):
        """Dispatch one fan-out to one authority: deadline-track the sign
        (watchdog from BEFORE the dispatch — a hung sign never returns),
        arm its hedge timer, enqueue."""
        now = self.engine.clock() if now is None else now
        with self._flock:
            if fanout.resolved or auth.label in fanout.targets:
                return False
            fanout.targets[auth.label] = auth
        if self._health_of(auth.label).state == _health.PROBATION:
            metrics.count("issue_probes")
        self.engine._watchdog.begin(
            auth.label, fanout.fid, fanout, span=fanout.bspan, now=now
        )
        self._hedges.begin(
            fanout, auth.label, self.hedge_policy.budget(auth.label), now=now
        )
        auth.submit(fanout)
        return True

    def _mark_failed(self, fanout, label):
        with self._flock:
            fanout.failed.add(label)
        self._hedges.end(fanout.fid, label)

    def _pick_spare(self, fanout):
        """An admissible authority this fan-out has not targeted yet (and
        whose rows were not attributed corrupt), least-queued first."""
        with self._flock:
            targeted = set(fanout.targets)
        spares = [
            a
            for a in self._authorities
            if a.label not in targeted
            and a.id not in fanout.dropped
            and self._admits(a)
            and a.has_worker()
        ]
        if not spares:
            return None
        return min(spares, key=lambda a: (a.queued(), a.id))

    def _ensure_coverage(self, fanout):
        """Re-check that landed + still-signing contributors can reach t;
        dispatch spares to close any gap ("issue_redispatched"), and when
        no spare can close it, fail the fan-out's unresolved requests
        with the typed, retriable QuorumUnreachableError."""
        t = fanout.threshold or self.threshold
        while True:
            if fanout.resolved:
                return
            with self._flock:
                have = len(fanout.available_ids())
                inflight = sum(
                    1
                    for label, a in fanout.targets.items()
                    if label not in fanout.failed
                    and a.id not in fanout.partials
                    and a.id not in fanout.dropped
                )
            if have + inflight >= t:
                return
            spare = self._pick_spare(fanout)
            if spare is None:
                break
            if self._dispatch_to(fanout, spare):
                metrics.count("issue_redispatched")
        pending = [
            i for i in fanout.pending if not fanout.requests[i].future.done()
        ]
        if not pending:
            return
        with self._flock:
            have = len(fanout.available_ids())
        metrics.count("issue_quorum_unreachable")
        self._fail_requests(
            fanout,
            pending,
            QuorumUnreachableError(t, have, live=have, program=self.name),
        )
        if self._tracker.settle(fanout, pending):
            self._close_fanout(fanout, result="unreachable")

    # -- sign + mint (run on authority threads) ------------------------------

    def _sign_fanout(self, auth, fanout, gen):
        """One authority's turn on one fan-out: sign the coalesced batch
        under its share, file the row, and — on the call that completes
        the quorum — mint."""
        if fanout.resolved:
            # first-t-wins already resolved this fan-out (cancel raced
            # the pop): skip the sign, settle the trackers
            metrics.count("issue_sign_skips")
            self.engine._watchdog.end(
                auth.label, fanout.fid, now=self.engine.clock()
            )
            self._hedges.end(fanout.fid, auth.label)
            return
        t0 = self.engine.clock()
        try:
            with metrics.timer(auth.busy_timer):
                partials = auth.sign(
                    fanout.sig_reqs, self.params, keyset=fanout.keyset
                )
        except Exception as e:
            # sign FAULT (not a crash — the worker survives): mark this
            # target failed, breaker the authority, restore coverage
            self.engine._watchdog.end(
                auth.label, fanout.fid, ok=False, now=self.engine.clock()
            )
            self._mark_failed(fanout, auth.label)
            self._note_failure(
                auth, "sign dispatch failed: %s" % type(e).__name__
            )
            self._ensure_coverage(fanout)
            return
        now = self.engine.clock()
        if not auth.is_current(gen):
            # stale worker: the watchdog expired this sign and the
            # fan-out was re-covered — the late row is nobody's news
            metrics.count("issue_partials_discarded", len(partials))
            return
        self.engine._watchdog.end(auth.label, fanout.fid, now=now)
        self._hedges.end(fanout.fid, auth.label)
        self.hedge_policy.observe(auth.label, now - t0)
        self._note_success(auth)
        subset = self._tracker.record(fanout, auth.id, partials, now=now)
        while subset is not None:
            subset = self._mint(fanout, subset)

    def _mint(self, fanout, subset):
        """One mint round over `subset` (the caller holds the tracker's
        minting claim): unblind -> batch-aggregate -> verify under the
        aggregated verkey. Passing lanes release; failing lanes trigger
        per-partial attribution, the culprit's rows drop, and the round
        retries from the next subset (returned; None = done or waiting
        for more rows)."""
        indices = sorted(fanout.pending)
        if not indices:
            self._tracker.settle(fanout, [])
            self._close_fanout(fanout, result="minted")
            return None
        blind_rows = [
            [fanout.partials[i][idx] for i in subset] for idx in indices
        ]
        sks = [fanout.sks[idx] for idx in indices]
        messages_list = [fanout.messages_list[idx] for idx in indices]
        minter = self._minter_for(fanout.keyset)
        try:
            with otrace.use(fanout.bspan):
                with otrace.span("unblind", n=len(indices), t=len(subset)):
                    sig_rows = minter.unblind(blind_rows, sks)
                with otrace.span("aggregate", subset=list(subset)):
                    creds = minter.aggregate(subset, sig_rows)
                with otrace.span("verify", n=len(indices)):
                    verdicts = minter.verify(
                        creds, messages_list, subset
                    )
        except Exception as e:
            # the mint crypto itself failed (malformed subset row, code
            # bug): fail THIS fan-out's unresolved lanes loudly — the
            # authorities are fine, the partials were not
            metrics.count("issue_mint_failures")
            self._fail_requests(fanout, indices, e)
            if self._tracker.settle(fanout, indices):
                self._close_fanout(fanout, result="mint_failed")
            return None
        ok_idx = [i for i, v in zip(indices, verdicts) if v]
        bad_pos = [p for p, v in enumerate(verdicts) if not v]
        if ok_idx:
            self._release(
                fanout,
                ok_idx,
                {
                    idx: cred
                    for idx, cred, v in zip(indices, creds, verdicts)
                    if v
                },
            )
        if not bad_pos:
            if self._tracker.settle(fanout, ok_idx):
                self._close_fanout(fanout, result="minted")
                return None
            return self._tracker.next_subset(fanout)
        if ok_idx:
            self._tracker.settle(fanout, ok_idx)
        # ATTRIBUTION: an aggregated credential failed verification, so
        # at least one contributing partial is corrupt — re-verify each
        # failing lane's partials under their authorities' OWN verkeys
        # to name the culprits exactly (per-partial provenance)
        culprits = set()
        for p in bad_pos:
            row = sig_rows[p]
            msgs = messages_list[p]
            for j, signer_id in enumerate(subset):
                if signer_id in culprits:
                    continue
                if not minter.verify_partial(signer_id, row[j], msgs):
                    culprits.add(signer_id)
        if not culprits:
            # every partial checks out yet the aggregate does not: the
            # REQUEST itself is unservable (e.g. inconsistent messages
            # vs its own commitment) — fail just those lanes, typed
            bad_idx = [indices[p] for p in bad_pos]
            metrics.count("issue_mint_failures")
            self._fail_requests(
                fanout,
                bad_idx,
                GeneralError(
                    "minted credential failed verification with no "
                    "attributable corrupt partial — request unservable"
                ),
            )
            if self._tracker.settle(fanout, bad_idx):
                self._close_fanout(fanout, result="mint_failed")
                return None
            return self._tracker.next_subset(fanout)
        metrics.count("issue_corrupt_partials", len(culprits))
        fanout.bspan.event("corrupt_partials", authorities=sorted(culprits))
        self._tracker.drop_partials(fanout, culprits)
        for signer_id in culprits:
            auth = next(
                (a for a in self._authorities if a.id == signer_id), None
            )
            if auth is not None:
                self._note_failure(auth, "corrupt partial signature")
        subset = self._tracker.next_subset(fanout)
        if subset is None:
            # not enough clean rows yet: the minting claim was released;
            # make sure enough contributors are still coming
            self._ensure_coverage(fanout)
        return subset

    def _release(self, fanout, indices, creds_by_idx):
        """Hand verified credentials to their futures — the ONLY path a
        credential leaves the service on, and it is behind the verify
        gate by construction."""
        now = self.engine.clock()
        epoch = fanout.keyset.epoch if fanout.keyset is not None else None
        for idx in indices:
            r = fanout.requests[idx]
            cred = creds_by_idx[idx]
            if epoch is not None:
                # the credential's mint epoch rides with it (and over the
                # wire): verify resolves the aggregated verkey by epoch
                cred.epoch = epoch
            metrics.observe("issue_latency_s", now - r.t_submit)
            r.span.end(verdict=True)
            r.future.set_result(cred)
        metrics.count("issue_minted", len(indices))

    def _fail_requests(self, fanout, indices, exc):
        for idx in indices:
            r = fanout.requests[idx]
            r.queue_span.end()
            r.span.end(error=type(exc).__name__)
            r.future.set_exception(exc)
        if indices:
            metrics.count("issue_failed_requests", len(indices))

    def _close_fanout(self, fanout, result):
        """Fully settled (or force-failed): close the record everywhere —
        tracker (marks resolved: late rows discard), hedge timers, every
        authority's queued copy (a canceled queued sign ends its watchdog
        deadline too; one mid-sign finishes and ends its own)."""
        self._tracker.close_fanout(fanout)
        self._hedges.cancel(fanout.fid)
        with self._flock:
            # swap-then-unpin so a double close (sweep racing a late
            # settle) never unpins twice
            keyset, fanout.keyset = fanout.keyset, None
        if keyset is not None and self.keychain is not None:
            self.keychain.unpin(keyset)
        now = self.engine.clock()
        for auth in self._authorities:
            if auth.cancel(fanout.fid):
                self.engine._watchdog.end(auth.label, fanout.fid, now=now)
                metrics.count("issue_cancelled_signs")
        fanout.bspan.end(result=result)


class IssuanceService(ExecutionEngine):
    """Dynamic-batching threshold-issuance service over a signer pool.

    signers: keygen.Signer list (id, sigkey share, per-signer verkey) —
    the authority pool; threshold: t, the quorum size. backend: default
    backend (instance or name) for every authority AND the minter;
    backends: optional per-authority override list aligned with signers
    (chaos tests wrap ONE authority's backend in faults.FaultyBackend
    without touching the others); devices: optional per-authority jax
    device list (device-pinned sign dispatch). minter: the resolution
    crypto (default quorum.CryptoMinter; tests inject a stub to exercise
    quorum mechanics fake-clock, crypto-free).

    Self-healing knobs mirror serve/service.py: health_policy per-
    authority breaker, watchdog for hung signs, watchdog_interval_s the
    health-tick period (None = tests drive health_tick() by hand),
    brownout for graded shedding, hedge a hedge.HedgePolicy (None
    disables hedging)."""

    def __init__(
        self,
        signers,
        params,
        threshold,
        backend=None,
        backends=None,
        devices=None,
        minter=None,
        max_batch=32,
        max_wait_ms=20.0,
        max_depth=1024,
        clock=time.monotonic,
        health_policy=None,
        watchdog=None,
        watchdog_interval_s=0.25,
        hedge=None,
        brownout=None,
        keychain=None,
    ):
        super().__init__(
            name="coconut-issue",
            metric_ns="issue",
            clock=clock,
            health_policy=health_policy,
            watchdog=watchdog,
            watchdog_interval_s=watchdog_interval_s,
            brownout=brownout,
        )
        self._crash_msg = "issuance service crashed: %r"
        self._program = MintProgram(
            signers,
            params,
            threshold,
            backend=backend,
            backends=backends,
            devices=devices,
            minter=minter,
            hedge=hedge,
            max_batch=max_batch,
            max_wait_ms=max_wait_ms,
            max_depth=max_depth,
            keychain=keychain,
        )
        self.register(self._program)
        self.params = params
        self.threshold = threshold
        self.max_batch = max_batch
        self.max_wait_ms = max_wait_ms

    # -- client side ---------------------------------------------------------

    def submit(
        self, sig_request, messages, elgamal_sk, lane="interactive",
        max_wait_ms=None,
    ):
        """Admit one issuance request; returns a ServeFuture resolving to
        the minted (verified, aggregated) Signature. `messages` is the
        FULL message vector (hidden + known — the verification gate needs
        it; the authorities only ever see `sig_request`). Raises
        ServiceBrownoutError / ServiceOverloadedError / ServiceClosedError
        exactly like the verify service."""
        return self.submit_request(
            "mint",
            IssuanceOrder(sig_request, elgamal_sk),
            messages,
            lane=lane,
            max_wait_ms=max_wait_ms,
        )

    # -- key lifecycle (PR 15) -----------------------------------------------

    @property
    def keychain(self):
        return self._program.keychain

    def install_keyset(self, keyset):
        self._program.install_keyset(keyset)
        self.threshold = self._program.threshold

    # -- historical surface (delegating to the mint program) -----------------

    @property
    def minter(self):
        return self._program.minter

    @property
    def hedge_policy(self):
        return self._program.hedge_policy

    @property
    def _authorities(self):
        return self._program._authorities

    @property
    def _tracker(self):
        return self._program._tracker

    @property
    def _hedges(self):
        return self._program._hedges

    def _health_of(self, label):
        return self._program._health_of(label)

    def _capacity_fraction(self):
        return self._program.capacity_fraction()
