"""Schnorr proof of knowledge of committed values (generic over the group).

Replaces the `impl_PoK_VC!` macro family the reference imports from ps_sig
(instantiated at signature.rs:73-79; protocol usage signature.rs:227-314,
338-374). Commit/challenge/response structure:

  commit phase   : prover picks blindings b_i (or accepts supplied ones, which
                   is how the issuance PoK links the same hidden message
                   across sub-proofs, signature.rs:233-239,256) and publishes
                   t = prod base_i ^ b_i.
  response phase : response_i = b_i - c * secret_i  (mod r)
  verification   : t == prod base_i ^ response_i * commitment ^ c

The split into pre-challenge (`ProverCommitting.finish`) and post-challenge
(`gen_proof`) mirrors the reference so the proof composes with other
predicates under one Fiat-Shamir challenge (signature.rs:210-215)."""

from .errors import UnequalNoOfBasesExponents
from .ops.fields import R
from .sss import rand_fr


class ProverCommitting:
    """Accumulates (base, blinding) pairs; reference: ProverCommitting{G}."""

    def __init__(self, ops, to_bytes):
        self._ops = ops
        self._to_bytes = to_bytes
        self._bases = []
        self._blindings = []

    def commit(self, base, blinding=None):
        if blinding is None:
            blinding = rand_fr()
        self._bases.append(base)
        self._blindings.append(blinding)
        return len(self._bases) - 1

    def finish(self):
        t = self._ops.msm(self._bases, self._blindings)
        return ProverCommitted(
            self._ops, self._to_bytes, self._bases, self._blindings, t
        )


class ProverCommitted:
    """Commitment-phase output; reference: ProverCommitted{G}."""

    def __init__(self, ops, to_bytes, bases, blindings, t):
        self._ops = ops
        self._to_bytes = to_bytes
        self.bases = bases
        self.blindings = blindings
        self.t = t

    def to_bytes(self):
        """Transcript bytes for Fiat-Shamir: bases then commitment point."""
        out = [self._to_bytes(b) for b in self.bases]
        out.append(self._to_bytes(self.t))
        return b"".join(out)

    def gen_proof(self, challenge, secrets):
        if len(secrets) != len(self.bases):
            raise UnequalNoOfBasesExponents(len(self.bases), len(secrets))
        responses = [
            (b - challenge * s) % R for b, s in zip(self.blindings, secrets)
        ]
        return Proof(self.t, responses)


class Proof:
    """Response-phase output; reference: Proof{G} with fields
    (commitment=t, responses) — response equality across sub-proofs is
    checked by the issuance verifier (signature.rs:363-367)."""

    def __init__(self, t, responses):
        self.t = t
        self.responses = list(responses)

    def verify(self, ops, bases, commitment, challenge):
        if len(bases) != len(self.responses):
            raise UnequalNoOfBasesExponents(len(bases), len(self.responses))
        lhs = ops.add(
            ops.msm(bases, self.responses), ops.mul(commitment, challenge)
        )
        return lhs == self.t

    def to_bytes_with_bases(self, to_bytes, bases):
        """Reconstruct the commit-phase transcript bytes (bases || t) so a
        Fiat-Shamir verifier can recompute the challenge — an addition over
        the reference, whose tests pass the challenge out-of-band."""
        out = [to_bytes(b) for b in bases]
        out.append(to_bytes(self.t))
        return b"".join(out)

    def to_bytes(self, elem_to_bytes):
        """Canonical wire encoding: t || count(4B) || responses (32B each)."""
        out = [elem_to_bytes(self.t), len(self.responses).to_bytes(4, "big")]
        out.extend(r.to_bytes(32, "big") for r in self.responses)
        return b"".join(out)

    @classmethod
    def read_from(cls, b, offset, elem_from_bytes, elem_size):
        """Parse one Proof at `offset`; returns (proof, next_offset)."""
        from .errors import DeserializationError
        from .ops.serialize import fr_from_bytes

        if len(b) < offset + elem_size + 4:
            raise DeserializationError("truncated PoK proof encoding")
        t = elem_from_bytes(b[offset : offset + elem_size])
        offset += elem_size
        n = int.from_bytes(b[offset : offset + 4], "big")
        offset += 4
        if len(b) < offset + 32 * n:
            raise DeserializationError("truncated PoK proof responses")
        responses = [
            fr_from_bytes(b[offset + 32 * i : offset + 32 * (i + 1)])
            for i in range(n)
        ]
        return cls(t, responses), offset + 32 * n
