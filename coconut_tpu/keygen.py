"""Threshold key generation: Shamir (trusted dealer), Pedersen VSS (trusted
dealer, verifiable), and Pedersen DVSS (dealerless). Rebuilds keygen.rs.

The t-of-n structure is the protocol's fault-tolerance mechanism: any n-t
signers can fail and aggregation still succeeds; PVSS lets signers detect a
malicious dealer; DVSS removes the dealer entirely (SURVEY.md §5)."""

from collections import namedtuple

from .errors import GeneralError
from .ops.fields import R
from .signature import Sigkey, Verkey
from .sss import PedersenVSS, get_shared_secret, share_secret_dvss


class Signer:
    """id (1-based), signing key, verification key (keygen.rs:10-14)."""

    def __init__(self, signer_id, sigkey, verkey):
        self.id = signer_id
        self.sigkey = sigkey
        self.verkey = verkey


def keygen_from_shares(num_signers, x_shares, y_shares, params):
    """Lift secret shares to per-signer keys: alpha_i = g_tilde^{x_i},
    beta_i[j] = g_tilde^{y_i[j]} (keygen.rs:17-45)."""
    x_shares = dict(x_shares)
    y_shares = [dict(m) for m in y_shares]
    ops = params.ctx.other
    signers = []
    for i in range(num_signers):
        sid = i + 1
        try:
            x_i = x_shares.pop(sid)
            y_i = [m.pop(sid) for m in y_shares]
        except KeyError:
            raise GeneralError("missing share for signer id %d" % sid)
        alpha_i = ops.mul(params.g_tilde, x_i)
        beta_i = [ops.mul(params.g_tilde, y) for y in y_i]
        signers.append(
            Signer(sid, Sigkey(x_i, y_i), Verkey(alpha_i, beta_i))
        )
    return signers


def trusted_party_SSS_keygen(threshold, total, params):
    """"TTPKeyGen" via plain Shamir (keygen.rs:53-71). Returns
    (secret_x, secret_y list, signers); the first two are the master secrets
    and should be destroyed by a real dealer."""
    secret_x, x_shares = get_shared_secret(threshold, total)
    secret_y, y_shares = [], []
    for _ in range(params.msg_count()):
        s, shares = get_shared_secret(threshold, total)
        secret_y.append(s)
        y_shares.append(shares)
    return secret_x, secret_y, keygen_from_shares(total, x_shares, y_shares, params)


PVSSKeygenOutput = namedtuple(
    "PVSSKeygenOutput",
    [
        "secret_x",
        "secret_y",
        "signers",
        "secret_x_t",
        "comm_coeff_x",
        "x_shares",
        "x_t_shares",
        "secret_y_t",
        "comm_coeff_y",
        "y_shares",
        "y_t_shares",
    ],
)


def trusted_party_PVSS_keygen(threshold, total, params, g, h):
    """Keygen via Pedersen VSS (keygen.rs:74-122): same field order as the
    reference's 11-tuple, as a named tuple, so each signer can
    `PedersenVSS.verify_share` its share against the coefficient commitments
    (README.md:52-68)."""
    secret_x, secret_x_t, comm_coeff_x, x_shares, x_t_shares = PedersenVSS.deal(
        threshold, total, g, h
    )
    secret_y, secret_y_t, comm_coeff_y, y, y_t = [], [], [], [], []
    for _ in range(params.msg_count()):
        s, s_t, cc, shares, t_shares = PedersenVSS.deal(threshold, total, g, h)
        secret_y.append(s)
        secret_y_t.append(s_t)
        comm_coeff_y.append(cc)
        y.append(shares)
        y_t.append(t_shares)
    signers = keygen_from_shares(total, x_shares, y, params)
    return PVSSKeygenOutput(
        secret_x,
        secret_y,
        signers,
        secret_x_t,
        comm_coeff_x,
        x_shares,
        x_t_shares,
        secret_y_t,
        comm_coeff_y,
        y,
        y_t,
    )


def dvss_keygen(threshold, total, params, g, h):
    """Dealerless keygen via Pedersen DVSS (reference: test-only driver
    `setup_signers_for_test`, keygen.rs:167-205 — promoted to library code
    here). Each of x, y_1..y_q is produced by a full decentralized sharing
    round; the returned master secrets exist only because this simulates all
    participants in-process (for tests/benches — a real deployment never
    materializes them)."""
    secret_x = 0
    x_shares = {}
    participants_x = share_secret_dvss(threshold, total, g, h)
    for p in participants_x:
        x_shares[p.id] = p.secret_share
        secret_x = (secret_x + p.secret) % R
    secret_y = []
    y_shares = []
    for _ in range(params.msg_count()):
        participants_y = share_secret_dvss(threshold, total, g, h)
        shares = {}
        sec = 0
        for p in participants_y:
            shares[p.id] = p.secret_share
            sec = (sec + p.secret) % R
        y_shares.append(shares)
        secret_y.append(sec)
    signers = keygen_from_shares(total, x_shares, y_shares, params)
    return secret_x, secret_y, signers


# Reference-name alias (keygen.rs:169): the reference exposes the DVSS setup
# only under this test-scoped name.
setup_signers_for_test = dvss_keygen
