"""Anonymous petition scenario (PR 19) — the Coconut paper's flagship
application.

One credential per user; one ANONYMOUS signature per campaign. Each
campaign is a nullifier DOMAIN ("petition/c<k>"), and the signature's
spend tag is derived from (credential, domain) — so:

  - signing campaign A then campaign B with the same credential is
    ALLOWED (different domains -> different keyspaces, different tags);
  - signing campaign A twice is CAUGHT, even though the second show is
    freshly re-randomized (same credential + same domain -> same tag
    -> same nullifier -> typed DoubleSpendError);
  - two signatures on the same campaign from DIFFERENT users never
    collide (different credentials -> different tags).

A configurable fraction of workflows DELIBERATELY re-sign a campaign
the user already signed (`resign_p`) — those must finish `rejected`
with the double_spend label; an HONEST sign that draws a
DoubleSpendError finishes `failed`, which the drills assert never
happens."""

from ..errors import DoubleSpendError
from .base import ScenarioBase, ScenarioWorkflow, issue_credential, \
    show_credential
from .workflow import REJECTED


def campaign_domain(campaign):
    return "petition/c%03d" % campaign


class PetitionScenario(ScenarioBase):
    name = "petition"

    def __init__(self, client, params, campaigns=4, resign_p=0.1,
                 deadline_s=30.0):
        super().__init__(client, params, deadline_s=deadline_s)
        self.campaigns = int(campaigns)
        self.resign_p = float(resign_p)

    def workflow(self, user, rng):
        return PetitionWorkflow(self, user, rng)


class PetitionWorkflow(ScenarioWorkflow):
    name = "petition"

    def script(self):
        sc, user, rng = self.scenario, self.user, self.rng
        if user.credential is None:
            user.credential = yield from issue_credential(sc, user)
        cred = user.credential
        unsigned = [
            c for c in range(sc.campaigns) if c not in user.signed
        ]
        resign = bool(user.signed) and (
            not unsigned or rng.random() < sc.resign_p
        )
        if resign:
            # deliberately double-sign a campaign this user already
            # signed: the fresh re-randomized show MUST be rejected by
            # the campaign-scoped spend tag, not by transcript replay
            campaign = sorted(user.signed)[
                rng.randrange(len(user.signed))
            ]
            self.expect_rejection = True
        else:
            campaign = unsigned[rng.randrange(len(unsigned))]
        domain = campaign_domain(campaign)
        verdict, _show = yield from show_credential(
            sc, user, cred,
            domain=domain, tag=sc.tag_for(cred, domain),
            step_name="sign",
        )
        self.check(verdict, "petition signature rejected as invalid")
        self.check(
            not self.expect_rejection,
            "deliberate re-sign of %s was ACCEPTED" % domain,
        )
        user.signed.add(campaign)
        user.shows_done += 1

    def classify(self, step, exc):
        if self.expect_rejection and isinstance(exc, DoubleSpendError):
            return "double_spend"
        return None

    def on_terminal(self, run):
        if run.outcome == REJECTED:
            self.user.shows_done += 1
