"""Attribute-based service access scenario (PR 19).

The paper's third application: a user mints ONE credential over their
attributes, then presents it again and again across a long session —
each presentation a FRESH re-randomized show, so the service verifies
the attributes every time but can link none of the visits to each
other (or to the mint). No nullifier domain and no spend tag: each
honest show derives a fresh transcript nullifier, so repeated access
is never mistaken for a double spend — the unlinkability/double-spend
split the nullifier design exists to preserve.

Workflow: ensure credential, then `session_len` (rng-drawn in
`session_range`) sequential show_prove -> show_verify round trips.
Every verdict must be True; any DoubleSpendError here is a detector
false positive and finishes the run `failed`."""

from .base import ScenarioBase, ScenarioWorkflow, issue_credential, \
    show_credential


class AccessScenario(ScenarioBase):
    name = "access"

    def __init__(self, client, params, session_range=(3, 8),
                 deadline_s=60.0):
        super().__init__(client, params, deadline_s=deadline_s)
        self.session_range = session_range

    def workflow(self, user, rng):
        return AccessWorkflow(self, user, rng)


class AccessWorkflow(ScenarioWorkflow):
    name = "access"

    def script(self):
        sc, user, rng = self.scenario, self.user, self.rng
        if user.credential is None:
            user.credential = yield from issue_credential(sc, user)
        cred = user.credential
        lo, hi = sc.session_range
        session_len = rng.randrange(lo, hi + 1)
        for i in range(session_len):
            verdict, _show = yield from show_credential(
                sc, user, cred, step_name="access%d" % i
            )
            self.check(
                verdict, "re-randomized show %d rejected" % i
            )
            user.shows_done += 1
