"""Application scenarios + population-scale traffic model (PR 19).

The Coconut paper's entire point is applications — anonymous petitions,
e-cash with double-spend detection, attribute-based service access
(Sonnino et al.) — and this package scripts them as first-class
multi-phase WORKFLOWS over the ProtocolEngine / GatewayClient future
surface, then drives them at population scale.

Two halves:

  workflow.py    — the state-machine runtime: a scenario is a typed
                   generator of Steps over submit_* futures, with
                   per-step retry classification (the
                   ServiceRetryableError taxonomy), a per-workflow
                   deadline, and an explicit terminal outcome.
  petition.py    — one credential per user, one anonymous signature per
                   campaign (campaign-scoped nullifier domain: a
                   double-sign is caught, signing two campaigns is not).
  ecash.py       — issue then ATOMIC spend: show-verify + nullifier
                   commit IS the spend; a replayed spend surfaces as a
                   typed DoubleSpendError end-to-end.
  access.py      — attribute-based service access: mint once, then a
                   long session of repeated re-randomized shows.

  arrivals.py    — deterministic open-loop arrival processes: diurnal
                   rate curve, injectable flash crowds, Zipf skew.
  population.py  — millions of users as lightweight lazily-materialized
                   state records (NOT threads) fed through a bounded
                   in-flight window.
  report.py      — the availability-timeline success artifact
                   (per-second goodput, retryable-vs-terminal errors,
                   SLO attainment, elastic pool size, brownout events),
                   built on serve/loadgen's availability machinery.

See README "Application scenarios" for the taxonomy table and knobs;
bench.py --scenarios produces the acceptance artifact."""

from .access import AccessScenario
from .arrivals import (
    DiurnalCurve,
    FlashCrowd,
    RateSchedule,
    arrival_times,
    zipf_cdf,
    zipf_pick,
)
from .ecash import EcashScenario
from .petition import PetitionScenario
from .population import Population, PopulationDriver, User
from .report import ScenarioReport
from .workflow import (
    CANCELLED,
    COMPLETED,
    DEADLINE,
    FAILED,
    REJECTED,
    RETRY_EXHAUSTED,
    TERMINAL_OUTCOMES,
    Step,
    Workflow,
    WorkflowCheckError,
    WorkflowRun,
    run_workflow,
)

__all__ = [
    "AccessScenario",
    "CANCELLED",
    "COMPLETED",
    "DEADLINE",
    "DiurnalCurve",
    "EcashScenario",
    "FAILED",
    "FlashCrowd",
    "PetitionScenario",
    "Population",
    "PopulationDriver",
    "REJECTED",
    "RETRY_EXHAUSTED",
    "RateSchedule",
    "ScenarioReport",
    "Step",
    "TERMINAL_OUTCOMES",
    "User",
    "Workflow",
    "WorkflowCheckError",
    "WorkflowRun",
    "arrival_times",
    "run_workflow",
    "zipf_cdf",
    "zipf_pick",
]
