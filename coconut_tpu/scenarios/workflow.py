"""The scenario state-machine runtime (PR 19).

A WORKFLOW is a typed multi-phase script over the engine/gateway future
surface: a generator that yields `Step`s (each step submits ONE
request and receives its result back through the yield), plus two
classification hooks. The runtime — `WorkflowRun` — advances the
script entirely through `ServeFuture.add_done_callback`, the same
no-parked-thread seam net/rpc.py resolves response frames with, so a
million concurrent workflows cost a few hundred bytes of generator
state each, never a thread.

Outcome taxonomy (every started workflow reaches EXACTLY one):

  completed        the script ran to StopIteration
  rejected         a TYPED terminal error the scenario EXPECTED — the
                   protection fired (petition re-sign caught, e-cash
                   double-spend caught). Success of the system, not an
                   error of the run.
  retry_exhausted  retryable refusals (ServiceRetryableError /
                   TransientBackendError) beyond the step's budget
  deadline         the per-workflow deadline expired
  failed           an UNATTRIBUTED error — a typed terminal the
                   scenario did not expect, or a script bug. The
                   acceptance drills assert this count is zero.
  cancelled        the driver drained before the workflow finished

Retry classification reuses the serve taxonomy verbatim: an exception
is retryable iff `isinstance(e, (ServiceRetryableError,
TransientBackendError))`; the retry delay honors the refusal's own
`retry_after_s` hint, floored by exponential backoff with
deterministic per-run jitter (seeded — the fake-clock unit tests are
bit-stable). Everything else consults `Workflow.classify(step, exc)`:
a non-None label means the scenario expected that terminal (→
rejected); None means failed.

Thread-safety: `ServeFuture` callbacks fire on engine executor
threads (or transport reader threads over RPC), so every transition
runs under the run's own lock, and a late callback against an
already-terminal run is a no-op — that is the "no dangling futures on
drain" invariant the unit suite pins.
"""

import random
import threading
import time

from .. import metrics
from ..errors import ServiceRetryableError, TransientBackendError

COMPLETED = "completed"
REJECTED = "rejected"
RETRY_EXHAUSTED = "retry_exhausted"
DEADLINE = "deadline"
FAILED = "failed"
CANCELLED = "cancelled"

TERMINAL_OUTCOMES = (
    COMPLETED, REJECTED, RETRY_EXHAUSTED, DEADLINE, FAILED, CANCELLED,
)

#: floor between retries; doubles per attempt (jittered)
DEFAULT_BACKOFF_S = 0.05
DEFAULT_MAX_RETRIES = 4


class WorkflowCheckError(Exception):
    """A script-level invariant failed (e.g. a show verdict came back
    False for an honest credential). Terminal and UNEXPECTED — the run
    finishes `failed`, which the drills assert never happens."""


class Step:
    """One protocol-phase submission inside a script: `submit()` must
    return a future with `.result()`/`.add_done_callback()` (every
    engine/gateway submit_* does)."""

    __slots__ = ("name", "submit", "max_retries")

    def __init__(self, name, submit, max_retries=DEFAULT_MAX_RETRIES):
        self.name = name
        self.submit = submit
        self.max_retries = max_retries


class Workflow:
    """Base scenario script. Subclasses set `name`, implement
    `script()` (a generator yielding Steps; each yield evaluates to
    that step's result), optionally `classify(step, exc)` (return a
    short label for an EXPECTED typed terminal — the run finishes
    `rejected` with that label — or None), and optionally
    `on_terminal(run)` (update scenario/user state; called exactly
    once, after the outcome is sealed, still under the run's lock)."""

    name = "workflow"
    deadline_s = 30.0

    def script(self):
        raise NotImplementedError

    def classify(self, step, exc):
        return None

    def on_terminal(self, run):
        pass


class WorkflowRun:
    """Drives one Workflow instance to a terminal outcome.

    `on_terminal(run)` fires exactly once (report/driver hook);
    `on_park(run, ready_at)` hands a retry wake-up time to the owner
    (the PopulationDriver's heap, or run_workflow's local loop) —
    without an owner the run sleeps inline via `sleep`."""

    __slots__ = (
        "wf", "clock", "sleep", "rng", "on_terminal", "on_park",
        "backoff_s", "deadline_at", "outcome", "outcome_label",
        "error_code", "retries", "steps_done", "t_start", "t_end",
        "_lock", "_gen", "_step", "_retries_left", "_done_evt",
    )

    def __init__(self, wf, clock=time.monotonic, sleep=time.sleep,
                 seed=0, on_terminal=None, on_park=None,
                 backoff_s=DEFAULT_BACKOFF_S):
        self.wf = wf
        self.clock = clock
        self.sleep = sleep
        self.rng = random.Random(seed)
        self.on_terminal = on_terminal
        self.on_park = on_park
        self.backoff_s = backoff_s
        self.deadline_at = None
        self.outcome = None
        self.outcome_label = None
        self.error_code = None
        self.retries = 0
        self.steps_done = 0
        self.t_start = None
        self.t_end = None
        # re-entrant: ServeFuture.add_done_callback fires the hook
        # INLINE on the registering thread when the future is already
        # resolved, which re-enters the transition path under this lock
        self._lock = threading.RLock()
        self._gen = None
        self._step = None
        self._retries_left = 0
        self._done_evt = threading.Event()

    # -- lifecycle ----------------------------------------------------------

    def start(self):
        now = self.clock()
        self.t_start = now
        if self.wf.deadline_s is not None:
            self.deadline_at = now + self.wf.deadline_s
        metrics.count("scenario_started")
        with self._lock:
            self._gen = self.wf.script()
            self._advance_locked(None, first=True)
        return self

    def done(self):
        return self.outcome is not None

    def wait(self, timeout=None):
        """Block until terminal (run_workflow / tests)."""
        self._done_evt.wait(timeout)
        return self.outcome

    def cancel(self, outcome=CANCELLED):
        """Force-finish a non-terminal run (driver drain). A late
        future callback after this is a no-op."""
        with self._lock:
            if self.outcome is None:
                self._finish_locked(outcome)

    def expire_if_past_deadline(self, now):
        """Driver pump hook: seals `deadline` on a run whose clock ran
        out while parked or waiting on a future."""
        with self._lock:
            if self.outcome is None and self.deadline_at is not None \
                    and now >= self.deadline_at:
                self._finish_locked(DEADLINE)

    # -- transitions (all under self._lock) ---------------------------------

    def _advance_locked(self, value, first=False):
        try:
            step = self._gen.send(None if first else value)
        except StopIteration:
            self._finish_locked(COMPLETED)
            return
        except Exception as e:
            self.error_code = _code_of(e)
            self._finish_locked(FAILED)
            return
        self._step = step
        self._retries_left = step.max_retries
        self._submit_locked()

    def _submit_locked(self):
        now = self.clock()
        if self.deadline_at is not None and now >= self.deadline_at:
            self._finish_locked(DEADLINE)
            return
        try:
            fut = self._step.submit()
        except Exception as e:
            self._on_error_locked(e)
            return
        # an already-resolved future fires the hook inline on this
        # thread (RLock re-entry); a pending one fires it later on the
        # settling engine/transport thread
        fut.add_done_callback(self._on_future)

    def _on_future(self, fut):
        with self._lock:
            if self.outcome is not None:
                return  # late settle against a cancelled/expired run
            try:
                value = fut.result(0)
            except Exception as e:
                self._on_error_locked(e)
                return
            self.steps_done += 1
            self._advance_locked(value)

    def _on_error_locked(self, exc):
        step = self._step
        label = None
        try:
            label = self.wf.classify(step, exc)
        except Exception:
            label = None
        if label is not None:
            self.error_code = _code_of(exc)
            self.outcome_label = label
            self._finish_locked(REJECTED)
            return
        if isinstance(exc, (ServiceRetryableError, TransientBackendError)):
            now = self.clock()
            if self._retries_left <= 0:
                self.error_code = _code_of(exc)
                self._finish_locked(RETRY_EXHAUSTED)
                return
            attempt = step.max_retries - self._retries_left
            self._retries_left -= 1
            self.retries += 1
            metrics.count("scenario_retries")
            hint = getattr(exc, "retry_after_s", None) or 0.0
            backoff = self.backoff_s * (2 ** attempt)
            delay = max(float(hint), backoff * (0.5 + self.rng.random()))
            ready_at = now + delay
            if self.deadline_at is not None and ready_at >= self.deadline_at:
                self.error_code = _code_of(exc)
                self._finish_locked(DEADLINE)
                return
            if self.on_park is not None:
                self.on_park(self, ready_at)
                return
            # ownerless (synchronous) mode: sleep inline and resubmit
            self.sleep(max(0.0, ready_at - self.clock()))
            self._submit_locked()
            return
        self.error_code = _code_of(exc)
        self._finish_locked(FAILED)

    def resubmit(self):
        """Driver wake-up after a park: resubmit the current step."""
        with self._lock:
            if self.outcome is None:
                self._submit_locked()

    def _finish_locked(self, outcome):
        self.outcome = outcome
        self.t_end = self.clock()
        self._gen = None  # drop generator frame (and its closures) now
        self._step = None
        metrics.count("scenario_%s" % outcome)
        try:
            self.wf.on_terminal(self)
        except Exception:
            metrics.count("scenario_hook_errors")
        if self.on_terminal is not None:
            try:
                self.on_terminal(self)
            except Exception:
                metrics.count("scenario_hook_errors")
        self._done_evt.set()


def _code_of(exc):
    """Stable short attribution for an exception: the wire error code
    when it has one, else the class name."""
    return getattr(exc, "code", None) or type(exc).__name__


def run_workflow(wf, clock=time.monotonic, sleep=time.sleep, seed=0,
                 timeout=120.0):
    """Synchronously drive one workflow to its terminal outcome and
    return the finished WorkflowRun — the unit-test / probe harness
    (the population driver runs thousands concurrently instead)."""
    run = WorkflowRun(wf, clock=clock, sleep=sleep, seed=seed)
    run.start()
    if run.wait(timeout) is None:
        run.cancel()
    return run
