"""Population-scale open-loop traffic driver (PR 19).

Simulates millions of users as lightweight STATE RECORDS, not threads:
a `User` is a ~100-byte slotted object materialized lazily on first
arrival (an untouched uid costs nothing, so `n_users=5_000_000` is a
config value, not an allocation), carrying exactly the state the
scenarios need — tenant, per-user rng seed, minted credential, signed
campaigns, unspent coin, think-time horizon.

The driver is OPEN-LOOP (the coordinated-omission-safe discipline
serve/loadgen.py documents): arrivals come from a seeded
inhomogeneous Poisson stream (arrivals.py) regardless of how slow the
system responds. Each arrival picks a user (per-user Zipf-skewed
tenant already assigned), a scenario by mix weight, and starts a
WorkflowRun — whose every step advances via future callbacks on
engine/transport threads, so the driver thread only does three
things: pace arrivals, wake parked retries, expire deadlines, and
sample the per-second gauges for the report.

Back-pressure: `max_in_flight` bounds concurrent workflows — an
arrival beyond the window is counted `scenario_deferred` and DROPPED
(open-loop semantics: a user who finds the site down walks away; the
driver can never OOM on queued futures). Users already mid-workflow
or still in think-time skip the arrival (`scenario_thinking`).
"""

import heapq
import random
import threading
import time

from .. import metrics
from .arrivals import arrival_times, zipf_cdf, zipf_pick
from .workflow import CANCELLED, WorkflowRun

#: think-time bounds (uniform draw) between one user's workflows
DEFAULT_THINK_S = (0.5, 4.0)


class User:
    """One simulated user: all scenario-visible state, a few hundred
    bytes, no thread."""

    __slots__ = (
        "uid", "tenant", "seed", "msgs", "esk", "epk", "credential",
        "signed", "coin", "spent_show", "think_until", "busy",
        "shows_done",
    )

    def __init__(self, uid, tenant, seed):
        self.uid = uid
        self.tenant = tenant
        self.seed = seed
        self.msgs = None          # attribute Frs (lazily drawn)
        self.esk = None           # per-user ElGamal keypair
        self.epk = None
        self.credential = None    # minted Coconut credential
        self.signed = set()       # petition campaigns signed
        self.coin = None          # unspent e-cash credential
        self.spent_show = None    # last spent transcript (replay bait)
        self.think_until = 0.0
        self.busy = False
        self.shows_done = 0


class Population:
    """Lazily-materialized user universe with Zipf-skewed tenant
    assignment: `user(uid)` derives tenant and seed deterministically
    from (seed, uid), so the same uid is the same user in every run —
    and only touched uids ever exist in memory."""

    def __init__(self, n_users, n_tenants=8, zipf_s=1.2, seed=0):
        if n_users <= 0:
            raise ValueError("need at least one user")
        self.n_users = int(n_users)
        self.n_tenants = int(n_tenants)
        self.zipf_s = float(zipf_s)
        self.seed = int(seed)
        self._cdf = zipf_cdf(self.n_tenants, self.zipf_s)
        self._users = {}

    def tenant_of(self, uid):
        rng = random.Random((self.seed << 34) ^ (uid * 2654435761))
        return zipf_pick(rng, self._cdf)

    def user(self, uid):
        u = self._users.get(uid)
        if u is None:
            u = User(uid, self.tenant_of(uid), (self.seed << 20) ^ uid)
            self._users[uid] = u
        return u

    def materialized(self):
        return len(self._users)


class PopulationDriver:
    """Feeds scenario workflows into an engine/gateway client from a
    seeded arrival schedule, through a bounded in-flight window.

    `scenarios` is a list of (weight, scenario) pairs; each scenario
    object implements `workflow(user, rng)` -> Workflow (petition.py /
    ecash.py / access.py). `report` is a ScenarioReport (report.py);
    the driver records every terminal run and samples the per-second
    gauge timeline into it."""

    def __init__(self, population, scenarios, schedule, duration_s,
                 max_in_flight=256, seed=0, clock=time.monotonic,
                 sleep=time.sleep, report=None, engine=None,
                 elastic=None, drain_timeout_s=30.0):
        self.population = population
        self.scenarios = [(float(w), s) for w, s in scenarios]
        if not self.scenarios:
            raise ValueError("need at least one scenario")
        self.schedule = schedule
        self.duration_s = float(duration_s)
        self.max_in_flight = int(max_in_flight)
        self.rng = random.Random(seed)
        self.clock = clock
        self.sleep = sleep
        self.report = report
        #: optional: sampled for the elastic timeline + driven ticks
        self.engine = engine
        self.elastic = elastic
        self.drain_timeout_s = float(drain_timeout_s)
        self._lock = threading.Lock()
        self._in_flight = 0
        self._runs = set()
        self._parked = []  # heap of (ready_at, tiebreak, run)
        self._park_seq = 0
        self.arrivals = 0
        self.deferred = 0
        self.thinking = 0

    # -- workflow bookkeeping (runs on engine/transport threads too) --------

    def _on_park(self, run, ready_at):
        with self._lock:
            self._park_seq += 1
            heapq.heappush(self._parked, (ready_at, self._park_seq, run))

    def _on_terminal(self, run):
        with self._lock:
            self._in_flight -= 1
            self._runs.discard(run)
        if self.report is not None:
            self.report.record(run)

    def _pick_scenario(self):
        total = sum(w for w, _ in self.scenarios)
        r = self.rng.random() * total
        for w, s in self.scenarios:
            r -= w
            if r <= 0:
                return s
        return self.scenarios[-1][1]

    def _start_one(self, now):
        uid = self.rng.randrange(self.population.n_users)
        user = self.population.user(uid)
        if user.busy or user.think_until > now:
            self.thinking += 1
            metrics.count("scenario_thinking")
            return
        with self._lock:
            if self._in_flight >= self.max_in_flight:
                self.deferred += 1
                metrics.count("scenario_deferred")
                return
            self._in_flight += 1
        scenario = self._pick_scenario()
        user.busy = True
        wf_rng = random.Random(user.seed ^ (user.shows_done << 8)
                               ^ self.arrivals)
        wf = scenario.workflow(user, wf_rng)
        lo, hi = getattr(scenario, "think_s", DEFAULT_THINK_S)
        user.think_until = now + lo + wf_rng.random() * (hi - lo)

        def _done(run, _user=user):
            _user.busy = False
            self._on_terminal(run)

        run = WorkflowRun(
            wf, clock=self.clock, seed=user.seed ^ 0x5EED,
            on_terminal=_done, on_park=self._on_park,
        )
        with self._lock:
            self._runs.add(run)
        run.start()

    # -- the pump ------------------------------------------------------------

    def _wake_parked(self, now):
        ready = []
        with self._lock:
            while self._parked and self._parked[0][0] <= now:
                ready.append(heapq.heappop(self._parked)[2])
        for run in ready:
            run.resubmit()

    def _expire_deadlines(self, now):
        with self._lock:
            runs = list(self._runs)
        for run in runs:
            run.expire_if_past_deadline(now)

    def _sample(self, t0, now):
        # elastic decisions ride the 1 Hz sample cadence — the policy's
        # consecutive-sample hysteresis expects evenly-spaced readings,
        # not one per 20 ms pump iteration
        if self.elastic is not None:
            try:
                self.elastic.tick(now)
            except Exception:
                metrics.count("scenario_elastic_tick_errors")
        if self.report is None:
            return
        with self._lock:
            in_flight = self._in_flight
        active = None
        if self.engine is not None:
            try:
                active = self.engine.active_pool_size()
            except Exception:
                active = None
        self.report.sample(now - t0, in_flight, active_executors=active)

    def run(self):
        """Drive the full schedule, then drain. Returns the report's
        built dict (or a minimal summary without a report)."""
        t0 = self.clock()
        if self.report is not None:
            self.report.t0 = t0
        next_sample = 0.0
        for off in arrival_times(self.schedule, self.duration_s, self.rng):
            target = t0 + off
            while True:
                now = self.clock()
                self._wake_parked(now)
                self._expire_deadlines(now)
                if now - t0 >= next_sample:
                    self._sample(t0, now)
                    next_sample = (now - t0) // 1.0 + 1.0
                if now >= target:
                    break
                self.sleep(min(0.02, target - now))
            self.arrivals += 1
            self._start_one(self.clock())
        # drain: stop admitting, pump until every run is terminal
        drain_until = self.clock() + self.drain_timeout_s
        while True:
            now = self.clock()
            self._wake_parked(now)
            self._expire_deadlines(now)
            if now - t0 >= next_sample:
                self._sample(t0, now)
                next_sample = (now - t0) // 1.0 + 1.0
            with self._lock:
                live = len(self._runs)
            if live == 0 or now >= drain_until:
                break
            self.sleep(0.02)
        with self._lock:
            leftovers = list(self._runs)
        for run in leftovers:
            run.cancel(CANCELLED)
        elapsed = self.clock() - t0
        summary = {
            "arrivals": self.arrivals,
            "deferred": self.deferred,
            "thinking": self.thinking,
            "users_materialized": self.population.materialized(),
            "elapsed_s": round(elapsed, 3),
        }
        if self.report is not None:
            return self.report.build(t0, elapsed, driver=summary)
        return summary
