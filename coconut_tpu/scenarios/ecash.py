"""E-cash scenario (PR 19): issue, then ATOMIC spend.

A coin is a credential; THE SPEND IS the show-verify — the engine
WAL-commits the coin's nullifier under the store lock BEFORE the
client's future resolves (engine/phases.py demux), so "verified" and
"spent" are one atomic fact. The nullifier domain is "ecash" with a
spend tag derived from the coin's minted bytes, so ANY second spend
of the same coin — an exact transcript replay OR a fresh
re-randomized show — derives the same nullifier and surfaces as a
typed DoubleSpendError end-to-end (engine, wire envelope, client).

Each workflow: mint a coin if the wallet is empty, spend it, and with
probability `double_spend_p` ALSO attempt to re-spend the coin that
was just consumed (alternating between exact replay of the recorded
spend transcript and a fresh show of the spent coin — both must be
caught). Honest spends that draw a DoubleSpendError finish `failed`:
that would be the detector misfiring, and the drills assert zero."""

from ..errors import DoubleSpendError
from .base import ScenarioBase, ScenarioWorkflow, issue_credential, \
    show_credential
from .workflow import Step

DOMAIN = "ecash"


class EcashScenario(ScenarioBase):
    name = "ecash"

    def __init__(self, client, params, double_spend_p=0.1,
                 deadline_s=30.0):
        super().__init__(client, params, deadline_s=deadline_s)
        self.double_spend_p = float(double_spend_p)

    def workflow(self, user, rng):
        return EcashWorkflow(self, user, rng)


class EcashWorkflow(ScenarioWorkflow):
    name = "ecash"

    def script(self):
        sc, user, rng = self.scenario, self.user, self.rng
        if user.coin is None:
            user.coin = yield from issue_credential(sc, user)
        coin = user.coin
        tag = sc.tag_for(coin, DOMAIN)
        verdict, show = yield from show_credential(
            sc, user, coin, domain=DOMAIN, tag=tag, step_name="spend"
        )
        self.check(verdict, "honest spend rejected as invalid")
        # the spend is durable the moment the future resolved: consume
        # the coin and keep the transcript as replay bait
        user.coin = None
        user.spent_show = (show, tag)
        user.shows_done += 1
        if rng.random() < sc.double_spend_p:
            # attacker move: re-spend the consumed coin. Even rounds
            # replay the exact recorded transcript; odd rounds run a
            # FRESH re-randomized show of the spent coin — the spend
            # tag catches both.
            self.expect_rejection = True
            if user.shows_done % 2 == 0:
                (proof, challenge, revealed, epoch), tag = user.spent_show
                client = sc.client
                yield Step(
                    "respend_replay",
                    lambda: client.submit_show_verify(
                        proof, revealed, challenge, epoch=epoch,
                        domain=DOMAIN, tag=tag,
                    ),
                )
            else:
                yield from show_credential(
                    sc, user, coin, domain=DOMAIN, tag=tag,
                    step_name="respend",
                )
            self.check(False, "double spend of %s was ACCEPTED" % DOMAIN)

    def classify(self, step, exc):
        if self.expect_rejection and isinstance(exc, DoubleSpendError):
            return "double_spend"
        return None
