"""The scenario run's success artifact (PR 19): an availability
timeline plus outcome attribution.

Built on the SAME machinery the serve drills use —
`serve.loadgen.availability_timeline` for the per-second
goodput/error buckets and `serve.loadgen.latency_percentiles` for the
latency summary — so "goodput" means the same thing in a scenario
bench as in a rolling-restart drill.

What it adds over the serve report:

  outcomes       every workflow's terminal outcome, by scenario: the
                 acceptance bar is `failed == 0` (zero UNATTRIBUTED
                 errors) and `cancelled == 0` after a clean drain
                 (zero dangling futures).
  rejections     EXPECTED typed rejections (petition re-sign, e-cash
                 double-spend) counted per scenario and label —
                 protections firing, deliberately excluded from both
                 goodput and the error timeline.
  slo            workflow-latency SLO attainment + p99, overall AND
                 split inside/outside a flash-crowd window, the "p99
                 stays in SLO through the flash crowd" number.
  timeline       per-second driver samples: in-flight window, elastic
                 active-executor pool, brownout flags — the elastic
                 sizing trace that must track the diurnal curve.
"""

import threading

from .. import metrics
from ..serve.loadgen import availability_timeline, latency_percentiles
from .workflow import (
    CANCELLED,
    COMPLETED,
    DEADLINE,
    FAILED,
    REJECTED,
    RETRY_EXHAUSTED,
)

#: program metric namespaces whose brownout gauges/shed counters the
#: per-second sample sweeps (engine/phases.py + serve/batcher.py)
_PROGRAM_NS = ("serve", "prep", "issue", "prove", "showv")


def _brownout_now():
    """1 when any program lane is currently shedding (its
    "<ns>_brownout" gauge is set), else 0."""
    for ns in _PROGRAM_NS:
        if metrics.get_gauge("%s_brownout" % ns):
            return 1
    return 0


class ScenarioReport:
    """Thread-safe collector: workflow terminals arrive from engine /
    transport threads, samples from the driver thread."""

    def __init__(self, slo_s=2.0, flash_window=None):
        self.slo_s = float(slo_s)
        #: (start_s, end_s) relative to run start — usually
        #: FlashCrowd.window(); enables the in-crowd SLO split
        self.flash_window = flash_window
        self.t0 = None
        self._lock = threading.Lock()
        self._events = []      # (t_abs, latency|None, ok) — loadgen shape
        self._latencies = []
        self._flash_latencies = []
        self._calm_latencies = []
        self._outcomes = {}    # scenario -> {outcome: n}
        self._rejections = {}  # scenario -> {label: n}
        self._error_codes = {} # code -> n (failed/exhausted attribution)
        self._retries = 0
        self._samples = []     # dicts, one per driver second

    # -- ingest --------------------------------------------------------------

    def record(self, run):
        """Fold one terminal WorkflowRun in (exactly once per run)."""
        name = run.wf.name
        dur = None
        if run.t_end is not None and run.t_start is not None:
            dur = run.t_end - run.t_start
        in_flash = False
        if (self.flash_window is not None and self.t0 is not None
                and run.t_end is not None):
            lo, hi = self.flash_window
            in_flash = lo <= (run.t_end - self.t0) <= hi
        with self._lock:
            per = self._outcomes.setdefault(name, {})
            per[run.outcome] = per.get(run.outcome, 0) + 1
            self._retries += run.retries
            if run.outcome == COMPLETED:
                self._events.append((run.t_end, dur, True))
                self._latencies.append(dur)
                (self._flash_latencies if in_flash
                 else self._calm_latencies).append(dur)
            elif run.outcome == REJECTED:
                # the protection FIRED — tracked apart from goodput
                # and errors both
                rej = self._rejections.setdefault(name, {})
                label = run.outcome_label or "rejected"
                rej[label] = rej.get(label, 0) + 1
            else:
                self._events.append((run.t_end, None, False))
                if run.error_code:
                    self._error_codes[run.error_code] = (
                        self._error_codes.get(run.error_code, 0) + 1
                    )

    def sample(self, t_rel, in_flight, active_executors=None):
        """One per-second driver sample of the live gauges."""
        s = {
            "t": round(t_rel, 3),
            "in_flight": in_flight,
            "active_executors": (
                active_executors
                if active_executors is not None
                else metrics.get_gauge("elastic_active_executors")
            ),
            "brownout": _brownout_now(),
        }
        with self._lock:
            self._samples.append(s)

    # -- build ---------------------------------------------------------------

    def _outcome_total(self, outcome):
        return sum(
            per.get(outcome, 0) for per in self._outcomes.values()
        )

    def build(self, t0, elapsed, driver=None):
        with self._lock:
            # key on the timestamp alone: a goodput event carries a
            # float latency where an error carries None, and tuple
            # comparison on a timestamp tie would TypeError on those
            events = sorted(self._events, key=lambda e: e[0])
            completed = self._outcome_total(COMPLETED)
            failed = self._outcome_total(FAILED)
            rejected = sum(
                sum(r.values()) for r in self._rejections.values()
            )
            sat = sum(1 for d in self._latencies if d <= self.slo_s)
            flash = list(self._flash_latencies)
            calm = list(self._calm_latencies)
            out = {
                "outcomes": {
                    name: dict(per)
                    for name, per in sorted(self._outcomes.items())
                },
                "totals": {
                    "completed": completed,
                    "rejected_expected": rejected,
                    "retry_exhausted": self._outcome_total(
                        RETRY_EXHAUSTED
                    ),
                    "deadline": self._outcome_total(DEADLINE),
                    "failed": failed,
                    "cancelled": self._outcome_total(CANCELLED),
                    "retries": self._retries,
                },
                "rejections": {
                    name: dict(r)
                    for name, r in sorted(self._rejections.items())
                },
                "error_codes": dict(sorted(self._error_codes.items())),
                "availability": availability_timeline(
                    events, t0, elapsed
                ),
                "latency_s": latency_percentiles(self._latencies),
                "slo": {
                    "slo_s": self.slo_s,
                    "attainment": (
                        round(sat / completed, 4) if completed else None
                    ),
                    "p99_s": metrics.percentile(self._latencies, 99),
                    "flash_window": self.flash_window,
                    "flash_p99_s": metrics.percentile(flash, 99),
                    "flash_completed": len(flash),
                    "calm_p99_s": metrics.percentile(calm, 99),
                },
                "timeline": list(self._samples),
                "goodput_per_s": (
                    round(completed / elapsed, 2) if elapsed > 0 else None
                ),
            }
        pool_sizes = [
            s["active_executors"]
            for s in out["timeline"]
            if s["active_executors"] is not None
        ]
        out["elastic"] = {
            "min_active": min(pool_sizes) if pool_sizes else None,
            "max_active": max(pool_sizes) if pool_sizes else None,
            "grown": metrics.get_count("elastic_grown"),
            "shrunk": metrics.get_count("elastic_shrunk"),
        }
        out["brownout_seconds"] = sum(
            1 for s in out["timeline"] if s["brownout"]
        )
        if driver is not None:
            out["driver"] = driver
        return out
