"""Deterministic open-loop arrival processes for the population model
(PR 19).

Real credential traffic is not a constant-rate Poisson stream: it has
a DIURNAL swing (the elastic controller's reason to exist), flash
crowds (a petition goes viral — the brownout ladder's reason to
exist), and heavy tenant skew (a few campaigns dominate). This module
models all three as pure, seeded functions so every stream is
BIT-STABLE under a fixed seed — the unit suite pins exact values, and
a bench run is reproducible by quoting its seed.

  DiurnalCurve   rate(t): raised-cosine day shape between base_rate
                 (trough) and peak_rate, period_s long (benches
                 compress a "day" into seconds).
  FlashCrowd     factor(t): multiplicative spike with linear ramps.
  RateSchedule   curve x crowds composed into one inhomogeneous rate.
  arrival_times  Lewis-Shedler thinning over the schedule: an
                 inhomogeneous Poisson stream as a generator of
                 offsets — O(1) memory however long the run.
  zipf_cdf/pick  Zipf(s) tenant skew as an explicit CDF draw.
"""

import math


class DiurnalCurve:
    """Raised-cosine daily rate: trough `base_rate` at t=phase_s,
    peak `peak_rate` half a period later."""

    def __init__(self, base_rate, peak_rate, period_s, phase_s=0.0):
        if base_rate < 0 or peak_rate < base_rate:
            raise ValueError("need 0 <= base_rate <= peak_rate")
        if period_s <= 0:
            raise ValueError("period_s must be positive")
        self.base_rate = float(base_rate)
        self.peak_rate = float(peak_rate)
        self.period_s = float(period_s)
        self.phase_s = float(phase_s)

    def rate(self, t):
        swing = self.peak_rate - self.base_rate
        x = 2.0 * math.pi * (t - self.phase_s) / self.period_s
        return self.base_rate + swing * 0.5 * (1.0 - math.cos(x))

    def max_rate(self):
        return self.peak_rate


class FlashCrowd:
    """Multiplicative rate spike: factor ramps 1 -> multiplier over
    `ramp_s`, holds for `duration_s`, ramps back down."""

    def __init__(self, at_s, duration_s, multiplier, ramp_s=0.0):
        if multiplier < 1.0:
            raise ValueError("flash-crowd multiplier must be >= 1")
        self.at_s = float(at_s)
        self.duration_s = float(duration_s)
        self.multiplier = float(multiplier)
        self.ramp_s = float(ramp_s)

    def factor(self, t):
        lo = self.at_s
        hi = self.at_s + self.duration_s
        if t < lo - self.ramp_s or t > hi + self.ramp_s:
            return 1.0
        boost = self.multiplier - 1.0
        if t < lo:  # ramp up
            frac = (t - (lo - self.ramp_s)) / self.ramp_s
            return 1.0 + boost * frac
        if t > hi:  # ramp down
            frac = ((hi + self.ramp_s) - t) / self.ramp_s
            return 1.0 + boost * frac
        return self.multiplier

    def window(self):
        """(start, end) of the full-boost plateau — report.py splits
        SLO attainment inside vs outside this window."""
        return (self.at_s, self.at_s + self.duration_s)


class RateSchedule:
    """A diurnal curve with zero or more flash crowds composed in."""

    def __init__(self, curve, crowds=()):
        self.curve = curve
        self.crowds = tuple(crowds)

    def rate(self, t):
        r = self.curve.rate(t)
        for c in self.crowds:
            r *= c.factor(t)
        return r

    def max_rate(self):
        m = self.curve.max_rate()
        for c in self.crowds:
            m *= c.multiplier
        return m


def arrival_times(schedule, duration_s, rng):
    """Inhomogeneous Poisson arrivals over [0, duration_s) by
    Lewis-Shedler thinning: draw a homogeneous stream at the
    schedule's max rate, keep each point with probability
    rate(t)/max_rate. Yields ascending offsets; deterministic for a
    seeded `rng` (bit-stable — tests pin exact streams)."""
    lam = schedule.max_rate()
    if lam <= 0:
        return
    t = 0.0
    while True:
        t += rng.expovariate(lam)
        if t >= duration_s:
            return
        if rng.random() * lam <= schedule.rate(t):
            yield t


def zipf_cdf(n, s):
    """CDF over n ranks with Zipf exponent s: weight(i) ~ 1/(i+1)^s."""
    if n <= 0:
        raise ValueError("need at least one rank")
    weights = [1.0 / ((i + 1) ** s) for i in range(n)]
    total = sum(weights)
    cdf = []
    acc = 0.0
    for w in weights:
        acc += w / total
        cdf.append(acc)
    cdf[-1] = 1.0  # clamp float drift
    return cdf


def zipf_pick(rng, cdf):
    """One rank drawn from a zipf_cdf (deterministic for a seeded rng)."""
    r = rng.random()
    lo, hi = 0, len(cdf) - 1
    while lo < hi:
        mid = (lo + hi) // 2
        if cdf[mid] < r:
            lo = mid + 1
        else:
            hi = mid
    return lo
