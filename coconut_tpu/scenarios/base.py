"""Shared scenario plumbing (PR 19): per-user credential material and
the issue sub-script every scenario opens with.

A scenario object is CONFIG + a workflow factory: it holds the
engine/gateway client (anything with the submit_* surface — a
ProtocolEngine, a GatewayClient, or a router-bound _SessionClient),
the Params, and the scenario knobs; `workflow(user, rng)` stamps out
one Workflow instance per arrival. All user state lives on the User
record (population.py), so a workflow is just a generator frame over
it — millions of users, zero threads.

User crypto material is drawn DETERMINISTICALLY from the user's seed
(attributes and the ElGamal keypair), so a given uid is the same
principal across runs and replicas — which is what makes the petition
re-sign and e-cash double-spend drills reproducible end-to-end."""

import random

from ..ops.fields import R
from ..state.nullifier import spend_tag_of
from .workflow import Step, Workflow, WorkflowCheckError


def ensure_material(user, params):
    """Lazily equip a user with attributes + ElGamal keypair (seeded
    by uid — bit-stable across runs)."""
    if user.msgs is not None:
        return
    rng = random.Random(user.seed ^ 0xC0C0)
    user.msgs = [rng.randrange(1, R) for _ in range(params.msg_count())]
    user.esk = rng.randrange(1, R)
    user.epk = params.ctx.sig.mul(params.g, user.esk)


def cred_bytes(cred, params):
    """Canonical bytes of a minted credential — the spend-tag input.
    Stable across shows: show_prove re-randomizes a COPY, never the
    minted signature itself."""
    return cred.to_bytes(params.ctx)


def issue_credential(scenario, user):
    """Sub-script (use `yield from`): prepare -> mint, returning the
    minted credential. The prepare rides the bulk lane — issuance is
    backfill, shows are interactive; this is exactly the split the
    brownout ladder sheds by."""
    ensure_material(user, scenario.params)
    client = scenario.client
    msgs, epk, esk = user.msgs, user.epk, user.esk
    sig_req, _rand = yield Step(
        "prepare", lambda: client.submit_prepare(msgs, epk, lane="bulk")
    )
    cred = yield Step(
        "mint", lambda: client.submit_mint(sig_req, msgs, esk)
    )
    return cred


def show_credential(scenario, user, cred, domain=None, tag=None,
                    step_name="show"):
    """Sub-script: show_prove -> show_verify (optionally nullifier-
    scoped to `domain`/`tag`); returns the verdict bool. The verify
    epoch is the credential's mint epoch, as stamped by the engine."""
    client = scenario.client
    msgs = user.msgs
    proof, challenge, revealed = yield Step(
        "%s_prove" % step_name,
        lambda: client.submit_show_prove(cred, msgs),
    )
    epoch = getattr(cred, "epoch", None)
    verdict = yield Step(
        "%s_verify" % step_name,
        lambda: client.submit_show_verify(
            proof, revealed, challenge, epoch=epoch,
            domain=domain, tag=tag,
        ),
    )
    return verdict, (proof, challenge, revealed, epoch)


class ScenarioBase:
    """Config + workflow factory. Subclasses set `name` and implement
    `workflow(user, rng)`."""

    name = "scenario"
    #: per-user think-time bounds between workflows (driver reads this)
    think_s = (0.5, 4.0)

    def __init__(self, client, params, deadline_s=30.0):
        self.client = client
        self.params = params
        self.deadline_s = deadline_s

    def workflow(self, user, rng):
        raise NotImplementedError

    def tag_for(self, cred, domain):
        return spend_tag_of(cred_bytes(cred, self.params), domain)


class ScenarioWorkflow(Workflow):
    """A Workflow bound to (scenario, user, rng); the deadline comes
    from the scenario config."""

    def __init__(self, scenario, user, rng):
        self.scenario = scenario
        self.user = user
        self.rng = rng
        self.deadline_s = scenario.deadline_s
        #: scripts set this before a DELIBERATE double-spend/re-sign
        #: attempt; classify() only blesses the typed rejection then
        self.expect_rejection = False

    def check(self, cond, what):
        if not cond:
            raise WorkflowCheckError(what)
