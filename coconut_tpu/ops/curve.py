"""BLS12-381 group arithmetic (G1 over Fp, G2 over Fp2) — Python reference.

Replaces the reference's `amcl_wrapper` G1/G2 layer (SURVEY.md §2.2): point
add/double/neg, scalar multiplication, multi-scalar multiplication
(`multi_scalar_mul_const_time` / `_var_time` call sites: reference
signature.rs:157,424,427,465,513,521), subgroup membership, cofactor clearing.

Points are affine tuples `(x, y)` with `None` as the point at infinity.
G1 coordinates are Fp ints; G2 coordinates are Fp2 pairs. Internally scalar
multiplication uses Jacobian coordinates (X, Y, Z), Z == 0 for infinity.

Note on const-time: the reference distinguishes const-time MSM (secret
scalars, signature.rs:157,424-428) from var-time MSM (public data,
signature.rs:513). This Python layer is the *correctness spec* only and makes
no timing claims; the C++ core provides the constant-time ladder for the
secret-scalar paths.
"""

from .fields import (
    P,
    R,
    fp_add,
    fp_inv,
    fp_mul,
    fp_neg,
    fp_sq,
    fp_sub,
    fp2_add,
    fp2_inv,
    fp2_mul,
    fp2_mul_fp,
    fp2_neg,
    fp2_sq,
    fp2_sub,
    FP2_ONE,
    FP2_ZERO,
)

# --- Curve constants -------------------------------------------------------

B_G1 = 4  # E:  y^2 = x^3 + 4
B_G2 = (4, 4)  # E': y^2 = x^3 + 4(u+1)

# Standard generators (same as the BLS12-381 spec / zkcrypto / blst).
G1_GEN = (
    0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB,
    0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1,
)
G2_GEN = (
    (
        0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
        0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
    ),
    (
        0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
        0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
    ),
)

G1_COFACTOR = 0x396C8C005555E1568C00AAAB0000AAAB
G2_COFACTOR = 0x5D543A95414E7F1091D50792876A202CD91DE4547085ABAA68A205B2E5A7DDFA628F1CB4D9E82EF21537E293A6691AE1616EC6E786F0C70CF1C38E31C7238E5


class CurveOps:
    """Short-Weierstrass Jacobian arithmetic generic over the coordinate field."""

    def __init__(self, f_add, f_sub, f_mul, f_sq, f_neg, f_inv, zero, one, b):
        self.f_add = f_add
        self.f_sub = f_sub
        self.f_mul = f_mul
        self.f_sq = f_sq
        self.f_neg = f_neg
        self.f_inv = f_inv
        self.zero = zero
        self.one = one
        self.b = b

    # -- affine <-> jacobian

    def to_jacobian(self, p):
        if p is None:
            return (self.one, self.one, self.zero)
        return (p[0], p[1], self.one)

    def to_affine(self, j):
        X, Y, Z = j
        if Z == self.zero:
            return None
        zinv = self.f_inv(Z)
        zinv2 = self.f_sq(zinv)
        return (self.f_mul(X, zinv2), self.f_mul(Y, self.f_mul(zinv2, zinv)))

    # -- jacobian ops

    def jdouble(self, j):
        X, Y, Z = j
        if Z == self.zero or Y == self.zero:
            return (self.one, self.one, self.zero)
        A = self.f_sq(X)
        B = self.f_sq(Y)
        C = self.f_sq(B)
        # D = 2*((X+B)^2 - A - C)
        D = self.f_sub(self.f_sub(self.f_sq(self.f_add(X, B)), A), C)
        D = self.f_add(D, D)
        E = self.f_add(self.f_add(A, A), A)
        F = self.f_sq(E)
        X3 = self.f_sub(F, self.f_add(D, D))
        C8 = self.f_add(C, C)
        C8 = self.f_add(C8, C8)
        C8 = self.f_add(C8, C8)
        Y3 = self.f_sub(self.f_mul(E, self.f_sub(D, X3)), C8)
        Z3 = self.f_mul(self.f_add(Y, Y), Z)
        return (X3, Y3, Z3)

    def jadd(self, j1, j2):
        X1, Y1, Z1 = j1
        X2, Y2, Z2 = j2
        if Z1 == self.zero:
            return j2
        if Z2 == self.zero:
            return j1
        Z1Z1 = self.f_sq(Z1)
        Z2Z2 = self.f_sq(Z2)
        U1 = self.f_mul(X1, Z2Z2)
        U2 = self.f_mul(X2, Z1Z1)
        S1 = self.f_mul(Y1, self.f_mul(Z2, Z2Z2))
        S2 = self.f_mul(Y2, self.f_mul(Z1, Z1Z1))
        if U1 == U2:
            if S1 == S2:
                return self.jdouble(j1)
            return (self.one, self.one, self.zero)
        H = self.f_sub(U2, U1)
        I = self.f_sq(self.f_add(H, H))
        J = self.f_mul(H, I)
        rr = self.f_sub(S2, S1)
        rr = self.f_add(rr, rr)
        V = self.f_mul(U1, I)
        X3 = self.f_sub(self.f_sub(self.f_sq(rr), J), self.f_add(V, V))
        S1J = self.f_mul(S1, J)
        Y3 = self.f_sub(self.f_mul(rr, self.f_sub(V, X3)), self.f_add(S1J, S1J))
        Z3 = self.f_mul(self.f_mul(Z1, Z2), H)
        Z3 = self.f_add(Z3, Z3)  # account for I = (2H)^2 convention
        return (X3, Y3, Z3)

    # -- affine API

    def add(self, p, q):
        return self.to_affine(self.jadd(self.to_jacobian(p), self.to_jacobian(q)))

    def double(self, p):
        return self.to_affine(self.jdouble(self.to_jacobian(p)))

    def neg(self, p):
        if p is None:
            return None
        return (p[0], self.f_neg(p[1]))

    def sub(self, p, q):
        return self.add(p, self.neg(q))

    def mul(self, p, k):
        """Scalar multiplication k*p (k any int; reduced mod group order by caller
        if needed — the math works for any integer)."""
        if p is None or k == 0:
            return None
        if k < 0:
            return self.mul(self.neg(p), -k)
        acc = (self.one, self.one, self.zero)
        base = self.to_jacobian(p)
        for bit in bin(k)[2:]:
            acc = self.jdouble(acc)
            if bit == "1":
                acc = self.jadd(acc, base)
        return self.to_affine(acc)

    def msm(self, points, scalars):
        """Multi-scalar multiplication: sum_i scalars[i] * points[i].

        Reference analogue: `multi_scalar_mul_const_time` / `_var_time`
        (signature.rs:157,424,427,465,513,521). Windowed Straus; the batched
        high-throughput versions live in the C++ core and the TPU backend.
        """
        if len(points) != len(scalars):
            raise ValueError(
                "bases/exponents length mismatch: %d vs %d"
                % (len(points), len(scalars))
            )
        acc = (self.one, self.one, self.zero)
        # 4-bit windowed Straus over all points simultaneously.
        js = [self.to_jacobian(pt) for pt in points]
        # Precompute tables [0..15]*p
        tables = []
        for j in js:
            tbl = [(self.one, self.one, self.zero)]
            for _ in range(15):
                tbl.append(self.jadd(tbl[-1], j))
            tables.append(tbl)
        ks = [k % R for k in scalars]
        nbits = max((k.bit_length() for k in ks), default=0)
        nwin = (nbits + 3) // 4
        for w in range(nwin - 1, -1, -1):
            for _ in range(4):
                acc = self.jdouble(acc)
            for tbl, k in zip(tables, ks):
                d = (k >> (4 * w)) & 0xF
                if d:
                    acc = self.jadd(acc, tbl[d])
        return self.to_affine(acc)

    def is_on_curve(self, p):
        if p is None:
            return True
        x, y = p
        return self.f_sq(y) == self.f_add(self.f_mul(self.f_sq(x), x), self.b)

    def in_subgroup(self, p):
        return self.is_on_curve(p) and self.mul(p, R) is None

    def eq(self, p, q):
        return p == q


g1 = CurveOps(
    f_add=fp_add,
    f_sub=fp_sub,
    f_mul=fp_mul,
    f_sq=fp_sq,
    f_neg=fp_neg,
    f_inv=fp_inv,
    zero=0,
    one=1,
    b=B_G1,
)

g2 = CurveOps(
    f_add=fp2_add,
    f_sub=fp2_sub,
    f_mul=fp2_mul,
    f_sq=fp2_sq,
    f_neg=fp2_neg,
    f_inv=fp2_inv,
    zero=FP2_ZERO,
    one=FP2_ONE,
    b=B_G2,
)
