"""Canonical byte encodings for all wire/persistent types.

The reference relies on amcl's `to_bytes` for Fiat-Shamir transcripts
(signature.rs:201,271-280) and serde for persistence (signature.rs:12,39-122).
We define one canonical spec ("CTS-v1") shared by every backend — it feeds
both the Fiat-Shamir hashing and the checkpoint/credential store:

  - Fr: 32 bytes big-endian.
  - Fp: 48 bytes big-endian.
  - Fp2 (c0 + c1*u): c0 || c1 (96 bytes).
  - G1 point: 96 bytes uncompressed x || y; identity = 96 zero bytes.
  - G2 point: 192 bytes uncompressed x || y; identity = 192 zero bytes.
  - Compressed points (wire/storage): 48 / 96 bytes, ZCash-style flag bits in
    the top three bits of the first byte (compr | infinity | y-sign).

Deserializers validate: field elements canonical (< modulus), points on
curve and in the r-torsion subgroup.
"""

from .curve import g1, g2
from .fields import (
    P,
    R,
    fp2_add,
    fp2_mul,
    fp2_sgn0,
    fp2_sq,
    fp2_sqrt,
    fp_sgn0,
    fp_sqrt,
)
from ..errors import DeserializationError


def fr_to_bytes(a):
    return int(a % R).to_bytes(32, "big")


def fr_from_bytes(b):
    if len(b) != 32:
        raise DeserializationError("Fr must be 32 bytes, got %d" % len(b))
    v = int.from_bytes(b, "big")
    if v >= R:
        raise DeserializationError("non-canonical Fr encoding")
    return v


def fp_to_bytes(a):
    return int(a % P).to_bytes(48, "big")


def fp_from_bytes(b):
    if len(b) != 48:
        raise DeserializationError("Fp must be 48 bytes, got %d" % len(b))
    v = int.from_bytes(b, "big")
    if v >= P:
        raise DeserializationError("non-canonical Fp encoding")
    return v


def fp2_to_bytes(c):
    return fp_to_bytes(c[0]) + fp_to_bytes(c[1])


def fp2_from_bytes(b):
    if len(b) != 96:
        raise DeserializationError("Fp2 must be 96 bytes, got %d" % len(b))
    return (fp_from_bytes(b[:48]), fp_from_bytes(b[48:]))


# --- G1 ---------------------------------------------------------------------


def g1_to_bytes(p):
    """Uncompressed encoding; used for Fiat-Shamir transcripts."""
    if p is None:
        return b"\x00" * 96
    return fp_to_bytes(p[0]) + fp_to_bytes(p[1])


def g1_from_bytes(b):
    if len(b) != 96:
        raise DeserializationError("G1 must be 96 bytes, got %d" % len(b))
    if b == b"\x00" * 96:
        return None
    p = (fp_from_bytes(b[:48]), fp_from_bytes(b[48:]))
    if not g1.in_subgroup(p):
        raise DeserializationError("G1 point not in the r-torsion subgroup")
    return p


def g1_to_compressed(p):
    if p is None:
        return bytes([0xC0]) + b"\x00" * 47
    flags = 0x80 | (0x20 if fp_sgn0(p[1]) else 0)
    raw = bytearray(fp_to_bytes(p[0]))
    raw[0] |= flags
    return bytes(raw)


def g1_from_compressed(b):
    if len(b) != 48:
        raise DeserializationError("compressed G1 must be 48 bytes")
    flags = b[0] & 0xE0
    if not flags & 0x80:
        raise DeserializationError("compression flag not set")
    if flags & 0x40:
        if b != bytes([0xC0]) + b"\x00" * 47:
            raise DeserializationError("malformed G1 identity encoding")
        return None
    raw = bytearray(b)
    raw[0] &= 0x1F
    x = fp_from_bytes(bytes(raw))
    y = fp_sqrt((x * x % P * x + 4) % P)
    if y is None:
        raise DeserializationError("x not on curve")
    if fp_sgn0(y) != (1 if flags & 0x20 else 0):
        y = P - y
    p = (x, y)
    if not g1.in_subgroup(p):
        raise DeserializationError("G1 point not in the r-torsion subgroup")
    return p


# --- G2 ---------------------------------------------------------------------


def g2_to_bytes(p):
    if p is None:
        return b"\x00" * 192
    return fp2_to_bytes(p[0]) + fp2_to_bytes(p[1])


def g2_from_bytes(b):
    if len(b) != 192:
        raise DeserializationError("G2 must be 192 bytes, got %d" % len(b))
    if b == b"\x00" * 192:
        return None
    p = (fp2_from_bytes(b[:96]), fp2_from_bytes(b[96:]))
    if not g2.in_subgroup(p):
        raise DeserializationError("G2 point not in the r-torsion subgroup")
    return p


def g2_to_compressed(p):
    if p is None:
        return bytes([0xC0]) + b"\x00" * 95
    flags = 0x80 | (0x20 if fp2_sgn0(p[1]) else 0)
    raw = bytearray(fp2_to_bytes(p[0]))
    raw[0] |= flags
    return bytes(raw)


def g2_from_compressed(b):
    if len(b) != 96:
        raise DeserializationError("compressed G2 must be 96 bytes")
    flags = b[0] & 0xE0
    if not flags & 0x80:
        raise DeserializationError("compression flag not set")
    if flags & 0x40:
        if b != bytes([0xC0]) + b"\x00" * 95:
            raise DeserializationError("malformed G2 identity encoding")
        return None
    raw = bytearray(b)
    raw[0] &= 0x1F
    x = fp2_from_bytes(bytes(raw))
    y = fp2_sqrt(fp2_add(fp2_mul(fp2_sq(x), x), (4, 4)))
    if y is None:
        raise DeserializationError("x not on curve")
    if fp2_sgn0(y) != (1 if flags & 0x20 else 0):
        y = ((P - y[0]) % P, (P - y[1]) % P)
    p = (x, y)
    if not g2.in_subgroup(p):
        raise DeserializationError("G2 point not in the r-torsion subgroup")
    return p
