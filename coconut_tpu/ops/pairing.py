"""Optimal-ate pairing on BLS12-381 — pure-Python reference implementation.

Replaces the pairing layer the reference reaches through `ps_sig`
(`ate_2_pairing` re-export, reference lib.rs:13; used inside
`PSSignature::verify` and `PoKOfSignature`, reached via signature.rs:477 and
pok_sig.rs:85-105).

Spec decisions (all backends must match these *final* GT values; intermediate
Miller values may differ by subfield factors, which the final exponentiation
kills):
  - e(P, Q) := final_exp(miller_loop(P, Q))
  - final_exp(f) := f ** (3 * (p^12 - 1) / r)   — note the 3x multiple, which
    makes the hard part expressible as an exact polynomial in the BLS
    parameter x (Hayashida-Hayasaka-Teruya): (x-1)^2 (x+p) (x^2+p^2-1) + 3.
    Cubing is a bijection on the order-r target group, so the pairing check
    `== 1` and bilinearity are unaffected.
  - pairing products share one final exponentiation:
    multi_pairing([(P_i, Q_i)]) = final_exp(prod_i miller_loop(P_i, Q_i)),
    which is also exactly the TPU batch-verify structure.

Two Miller-loop formulations are provided and cross-checked in tests:
  - `miller_loop` — affine over Fp12 via the untwist
    (x', y') -> (x'/w^2, y'/w^3), w^6 = xi; one Fp12 inversion per step.
    Simple, auditable: the cross-check oracle.
  - `miller_loop_projective` — the PRIMARY path and the exact blueprint the
    C++/TPU backends mirror: homogeneous coordinates on the twist, denominators
    cleared into line/point scalings that lie in Fp2·{1, w^3} ⊂ Fp4 (a proper
    subfield of Fp12), which the final exponentiation kills. No inversions.
Both yield identical post-final-exp GT values (tested).
"""

from .fields import (
    BLS_X,
    FP2_ONE,
    FP2_ZERO,
    FP6_ZERO,
    FP6_ONE,
    FP12_ONE,
    P,
    R,
    fp2_add,
    fp2_mul,
    fp2_mul_fp,
    fp2_mul_xi,
    fp2_neg,
    fp2_sq,
    fp2_sub,
    fp12_conj,
    fp12_frobenius,
    fp12_frobenius2,
    fp12_inv,
    fp12_mul,
    fp12_pow,
    fp12_sq,
    fp12_sub,
)

# --- Fp12 embedding helpers ------------------------------------------------


def _embed_fp(a):
    """Fp -> Fp12."""
    return (((a % P, 0), FP2_ZERO, FP2_ZERO), FP6_ZERO)


def _embed_fp2(c):
    """Fp2 -> Fp12."""
    return ((c, FP2_ZERO, FP2_ZERO), FP6_ZERO)


# w, w^2 = v, w^3 = v*w as Fp12 elements; inverses precomputed once.
_W2 = ((FP2_ZERO, (1, 0), FP2_ZERO), FP6_ZERO)  # v
_W3 = (FP6_ZERO, (FP2_ZERO, (1, 0), FP2_ZERO))  # v*w
_W2_INV = fp12_inv(_W2)
_W3_INV = fp12_inv(_W3)


def untwist(q):
    """Map a G2 point on the twist E'(Fp2) to E(Fp12): (x,y) -> (x/w^2, y/w^3)."""
    x, y = q
    return (fp12_mul(_embed_fp2(x), _W2_INV), fp12_mul(_embed_fp2(y), _W3_INV))


# --- Miller loop -----------------------------------------------------------

_X_ABS_BITS = bin(-BLS_X)[2:]


def miller_loop(p1, q2):
    """Miller loop f_{|x|,Q}(P) with the end conjugation for x < 0.

    p1: G1 affine (Fp pair) or None; q2: G2 affine (Fp2 pair) or None.
    Returns an Fp12 element (1 if either input is the identity).
    """
    if p1 is None or q2 is None:
        return FP12_ONE
    px = _embed_fp(p1[0])
    py = _embed_fp(p1[1])
    qx, qy = untwist(q2)
    tx, ty = qx, qy
    f = FP12_ONE
    for bit in _X_ABS_BITS[1:]:
        # tangent line at T evaluated at P
        lam = fp12_mul(
            fp12_mul(fp12_sq(tx), _embed_fp(3)),
            fp12_inv(fp12_mul(ty, _embed_fp(2))),
        )
        line = fp12_sub(fp12_sub(py, ty), fp12_mul(lam, fp12_sub(px, tx)))
        f = fp12_mul(fp12_sq(f), line)
        # T <- 2T
        x3 = fp12_sub(fp12_sq(lam), fp12_mul(tx, _embed_fp(2)))
        ty = fp12_sub(fp12_mul(lam, fp12_sub(tx, x3)), ty)
        tx = x3
        if bit == "1":
            # chord line through T and Q evaluated at P
            if tx == qx:
                raise ValueError("degenerate Miller addition step (T == +-Q)")
            lam = fp12_mul(fp12_sub(ty, qy), fp12_inv(fp12_sub(tx, qx)))
            line = fp12_sub(fp12_sub(py, qy), fp12_mul(lam, fp12_sub(px, qx)))
            f = fp12_mul(f, line)
            x3 = fp12_sub(fp12_sub(fp12_sq(lam), tx), qx)
            ty = fp12_sub(fp12_mul(lam, fp12_sub(tx, x3)), ty)
            tx = x3
    # x < 0: conjugate (inverse up to factors killed by the final exponentiation)
    return fp12_conj(f)


# --- Projective Miller loop (primary path; backend blueprint) ---------------
#
# T = (X, Y, Z) homogeneous on the twist E'(Fp2): affine (X/Z, Y/Z); untwisted
# coordinates x_t = X/(Z w^2), y_t = Y/(Z w^3). Lines are the affine chord/
# tangent lines scaled by a factor in Fp2·{1, w^3} ⊂ Fp4, returned as sparse
# coefficients (lA, lB, lC) meaning  lA + lB·x_p·w^2 + lC·y_p·w^3  once
# evaluated at P = (x_p, y_p) ∈ G1. Derivations verified against the affine
# oracle in tests/test_ops.py.


def proj_double_step(T):
    """(2T, tangent-line coefficients at T).

    Line: (X^3 - 8·xi·Z^3) - 3·X^2·Z·x_p·w^2 + 2·Y·Z^2·y_p·w^3, which is the
    affine tangent line scaled by 2·Y·Z^2·w^3 (killed by final exp)."""
    X, Y, Z = T
    A = fp2_sq(X)
    B = fp2_sq(Y)
    C = fp2_sq(Z)
    D = fp2_mul(fp2_mul(X, B), Z)
    F = fp2_sub(fp2_mul_fp(fp2_sq(A), 9), fp2_mul_fp(D, 8))
    YZ = fp2_mul(Y, Z)
    X3 = fp2_mul(fp2_mul_fp(YZ, 2), F)
    Y3 = fp2_sub(
        fp2_mul(fp2_mul_fp(A, 3), fp2_sub(fp2_mul_fp(D, 4), F)),
        fp2_mul_fp(fp2_mul(fp2_sq(B), C), 8),
    )
    t = fp2_mul_fp(YZ, 2)
    Z3 = fp2_mul(fp2_sq(t), t)
    lA = fp2_sub(fp2_mul(X, A), fp2_mul_fp(fp2_mul_xi(fp2_mul(Z, C)), 8))
    lB = fp2_neg(fp2_mul_fp(fp2_mul(A, Z), 3))
    lC = fp2_mul_fp(fp2_mul(Y, C), 2)
    return (X3, Y3, Z3), (lA, lB, lC)


def proj_add_step(T, q):
    """(T + Q, chord-line coefficients), Q = (x2, y2) affine on the twist.

    Line: (theta·x2 - lambda·y2) - theta·x_p·w^2 + lambda·y_p·w^3 with
    theta = Y - y2·Z, lambda = X - x2·Z — the affine chord line scaled by
    lambda·w^3. Degenerate for T == ±Q (unreachable for order-r Q within
    the |BLS_X|-bit loop)."""
    X, Y, Z = T
    x2, y2 = q
    theta = fp2_sub(Y, fp2_mul(y2, Z))
    lam = fp2_sub(X, fp2_mul(x2, Z))
    lam2 = fp2_sq(lam)
    lam3 = fp2_mul(lam2, lam)
    H = fp2_sub(
        fp2_mul(fp2_sq(theta), Z), fp2_mul(lam2, fp2_add(X, fp2_mul(x2, Z)))
    )
    X3 = fp2_mul(lam, H)
    Y3 = fp2_sub(fp2_mul(theta, fp2_sub(fp2_mul(lam2, X), H)), fp2_mul(lam3, Y))
    Z3 = fp2_mul(lam3, Z)
    lA = fp2_sub(fp2_mul(theta, x2), fp2_mul(lam, y2))
    lB = fp2_neg(theta)
    lC = lam
    return (X3, Y3, Z3), (lA, lB, lC)


def line_to_fp12(line, p1):
    """Evaluate sparse line coefficients at P and embed into Fp12:
    positions (w^0, w^2, w^3) -> Fp6 slots ((0,0), (0,1), (1,1))."""
    lA, lB, lC = line
    xp, yp = p1
    return (
        (lA, fp2_mul_fp(lB, xp), FP2_ZERO),
        (FP2_ZERO, fp2_mul_fp(lC, yp), FP2_ZERO),
    )


def miller_loop_projective(p1, q2):
    """Inversion-free Miller loop; same post-final-exp value as
    `miller_loop` (line scalings lie in the Fp4 subfield)."""
    if p1 is None or q2 is None:
        return FP12_ONE
    T = (q2[0], q2[1], FP2_ONE)
    f = FP12_ONE
    for bit in _X_ABS_BITS[1:]:
        T, line = proj_double_step(T)
        f = fp12_mul(fp12_sq(f), line_to_fp12(line, p1))
        if bit == "1":
            T, line = proj_add_step(T, q2)
            f = fp12_mul(f, line_to_fp12(line, p1))
    return fp12_conj(f)


# --- Final exponentiation --------------------------------------------------

# Hard-part lambda decomposition (verified exact at import):
#   3*(p^4 - p^2 + 1)/r = lam0 + lam1*p + lam2*p^2 + lam3*p^3
_LAM3 = (BLS_X - 1) ** 2
_LAM2 = _LAM3 * BLS_X
_LAM1 = _LAM3 * (BLS_X * BLS_X - 1)
_LAM0 = _LAM2 * (BLS_X * BLS_X - 1) + 3
assert _LAM0 + _LAM1 * P + _LAM2 * P**2 + _LAM3 * P**3 == 3 * (
    (P**4 - P**2 + 1) // R
)


def _cyc_pow(a, e):
    """a^e for `a` in the cyclotomic subgroup (so a^-1 == conj(a))."""
    if e < 0:
        return fp12_conj(_cyc_pow(a, -e))
    result = FP12_ONE
    base = a
    while e > 0:
        if e & 1:
            result = fp12_mul(result, base)
        base = fp12_sq(base)
        e >>= 1
    return result


def final_exp(f):
    """f ** (3 * (p^12 - 1) / r)."""
    # easy part: f^((p^6 - 1)(p^2 + 1))
    m = fp12_mul(fp12_conj(f), fp12_inv(f))
    m = fp12_mul(fp12_frobenius2(m), m)
    # hard part via Frobenius multi-exp; m is now cyclotomic
    r0 = _cyc_pow(m, _LAM0)
    r1 = fp12_frobenius(_cyc_pow(m, _LAM1))
    r2 = fp12_frobenius2(_cyc_pow(m, _LAM2))
    r3 = fp12_frobenius(fp12_frobenius2(_cyc_pow(m, _LAM3)))
    return fp12_mul(fp12_mul(r0, r1), fp12_mul(r2, r3))


def final_exp_slow(f):
    """Direct exponentiation — cross-check oracle for final_exp (tests only)."""
    return fp12_pow(f, 3 * ((P**12 - 1) // R))


# The hard part also factors as an x-power chain (the form the TPU backend
# uses — five exponentiations by the 64-bit |BLS_X| instead of four
# multi-hundred-bit exponents):  3·(p^4 - p^2 + 1)/r =
# (x-1)^2·(x+p)·(x^2 + p^2 - 1) + 3.  Verified exact here:
assert (BLS_X - 1) ** 2 * (BLS_X + P) * (BLS_X**2 + P**2 - 1) + 3 == 3 * (
    (P**4 - P**2 + 1) // R
)


def final_exp_chain(f):
    """final_exp via the x-power chain — structural blueprint for the TPU
    backend's final exponentiation; identical output to `final_exp`."""
    m = fp12_mul(fp12_conj(f), fp12_inv(f))
    m = fp12_mul(fp12_frobenius2(m), m)  # cyclotomic now
    # t0 = m^(x-1); t1 = t0^(x-1) = m^((x-1)^2)
    t0 = fp12_mul(_cyc_pow(m, BLS_X), fp12_conj(m))
    t1 = fp12_mul(_cyc_pow(t0, BLS_X), fp12_conj(t0))
    # t2 = t1^(x+p) = t1^x · pi(t1)
    t2 = fp12_mul(_cyc_pow(t1, BLS_X), fp12_frobenius(t1))
    # t3 = t2^(x^2 + p^2 - 1) = (t2^x)^x · pi^2(t2) · conj(t2)
    t3 = fp12_mul(
        fp12_mul(_cyc_pow(_cyc_pow(t2, BLS_X), BLS_X), fp12_frobenius2(t2)),
        fp12_conj(t2),
    )
    # · m^3
    return fp12_mul(t3, fp12_mul(fp12_sq(m), m))


# --- Pairing API -----------------------------------------------------------


def pairing(p1, q2):
    """e(P, Q) for P in G1, Q in G2."""
    return final_exp(miller_loop_projective(p1, q2))


def multi_pairing(pairs):
    """prod_i e(P_i, Q_i) with a single shared final exponentiation.

    This is the reference's `ate_2_pairing` generalized to any number of
    pairs (lib.rs:13) and the exact shape of the TPU batched verify.
    """
    f = FP12_ONE
    for p1, q2 in pairs:
        f = fp12_mul(f, miller_loop_projective(p1, q2))
    return final_exp(f)


def pairing_check(pairs):
    """True iff prod_i e(P_i, Q_i) == 1."""
    return multi_pairing(pairs) == FP12_ONE
