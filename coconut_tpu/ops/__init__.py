"""Low-level cryptographic operations: fields, curves, pairing, hashing,
serialization. The pure-Python modules here are the bit-exact specification
implemented natively by `core/` (C++) and in batch by `coconut_tpu/tpu/`."""

from . import curve, fields, hashing, pairing, serialize  # noqa: F401
